"""Config-driven benchmark runner — BenchmarkRunner.java:20-202 parity.

``python -m scotty_tpu.bench [config.json ...]`` iterates every
windowConfiguration × configuration(engine) × aggFunction cell of each JSON
config, runs it, prints a table, and writes ``result_<name>.json`` next to
``--out-dir`` (default ./bench_results), the analogue of the reference's
``result_<name>.txt`` files (BenchmarkRunner.java:62-69).

Engines:

* ``TpuEngine`` (reference config name ``Slicing`` accepted): the fused
  slicing pipeline — AlignedStreamPipeline when the spec allows, otherwise
  the batch-at-a-time TpuWindowOperator path (out-of-order streams, count
  measure, bands).
* ``Buckets`` (reference name ``Flink`` accepted): the no-sharing
  window-bucket baseline (buckets.py) anchoring the ≥10× claim. Offered load
  comes from ``bucketsThroughput`` (the reference likewise ran its Flink
  baseline at a fraction of Scotty's rate —
  random_tumbling_benchmark_flink.json's 1,600 vs 2,000,000 tuples/s).
* ``Simulator``: the host reference-semantics operator (tiny loads only).
"""

from __future__ import annotations

import contextlib
import json
import os
import time

from typing import List, Optional

import numpy as np

from .. import obs as _obs
from ..obs import latency as _lat
from ..utils import stdout_echo as _stdout
from .harness import (
    BenchmarkConfig,
    BenchResult,
    finalize_observability,
    first_emit_stats,
    latency_stats,
    make_aggregation,
    parse_window_spec,
    run_benchmark,
)


def _round_throughput(throughput: int, grid: int) -> int:
    """Largest rate ≤ throughput that is an integer per-slice count."""
    per = max(1, throughput * grid // 1000)
    return per * 1000 // grid


#: drained emit-latency sampling discipline, shared by every cell type:
#: up to LATENCY_SAMPLES_MAX samples within LATENCY_BUDGET_S seconds,
#: never fewer than LATENCY_SAMPLES_MIN
LATENCY_SAMPLES_MAX = 100
LATENCY_BUDGET_S = 45.0
LATENCY_SAMPLES_MIN = 5

#: every optional result attribute a cell may pin onto its row —
#: run_config copies the ones present, and ``obs diff`` imports this
#: list as part of its known-threshold-key universe (a threshold file
#: gating a row field must not be rejected as a typo)
CELL_EXTRA_FIELDS = (
    "link_mbps_raw", "link_mbps_achieved",
    "link_saturation", "n_lat_samples",
    "first_emit_p50_ms", "first_emit_p99_ms",
    "first_emit_samples",
    "latency_stages_ms",
    "latency_conservation_ok",
    "latency_worst_chain_gap_ms",
    "latency_chains", "latency_owner_stage",
    "latency_overhead_pct_median",
    "first_emit_microbatch_p50_ms",
    "first_emit_microbatch_p99_ms",
    "first_emit_microbatch_samples",
    "microbatch_arms",
    "microbatch_conservation_ok",
    "microbatch_worst_chain_gap_ms",
    "microbatch_tps",
    "microbatch_oracle_match",
    "microbatch_oracle_windows",
    "microbatch_flushes",
    "flags_off_ab_pct_median",
    "p50_emit_ms", "emit_ms_device",
    "p99_emit_ms_trimmed", "n_stall_samples",
    "n_trimmed_samples", "stall_flagged",
    "tail_unattributed", "shaper_back_ms",
    "shaper_late_routed", "shaper_reordered",
    "serving_retraces_after_warmup",
    "serving_registered", "serving_cancelled",
    "serving_rejected", "serving_cache_hits",
    "churn_ops", "throughput_static",
    "throughput_delta_pct", "oracle_match",
    "scan_match", "oracle_windows",
    "tuples_per_sec_inorder",
    "inprogram_tps", "generator_share",
    "legacy_anchor_tps",
    "generator_share_legacy",
    "legacy_anchor_note",
    "ring_fed_vs_inprogram",
    "context_mode", "ctx_speculative_tuples",
    "ctx_fallback_tuples", "ctx_fallback_runs",
    "ctx_fallback_rate",
    "churn_schedule", "churn_seed",
    "ring_occupancy_p50", "ring_occupancy_p90",
    "ring_occupancy_p99",
    "host_staged_p50", "host_staged_p90",
    "host_staged_p99",
    "prefetch_overlap_ratio",
    "ring_full_events", "ring_shed",
    "ring_blocks", "baseline_per_record_tps",
    "speedup_vs_per_record", "platform",
    "tpu_floor_note", "soak_passed",
    "soak_seen", "soak_audits_n",
    "soak_findings", "soak_last_terms",
    "soak_healthz_unhealthy", "soak_report",
    "delivery_mode", "delivery_snapshot",
    "delivery_overhead_pct_median",
    "n_keys", "n_shards", "host_cores",
    "tuples_per_sec_1shard", "scaling_ratio",
    "per_shard_occupancy", "rebalance_match",
    "reshard_retraces", "reshard_timeline",
    "reshard_wall_s", "delivery_tags_unique",
    "workload_phases", "drift_events",
    "drift_fired", "drift_transitions",
    "drift_detect_lags", "drift_all_detected",
    "drift_false_positives",
    "workload_overhead_pct_median",
    "served_health_ok", "served_drift_events",
    "autotune_phases", "autotune_decisions",
    "autotune_retunes", "autotune_retraces",
    "autotune_schedule",
    "adaptive_admitted", "static_admitted",
    "autotune_beats_all_statics",
    "stable_retunes", "stable_decisions",
    "autotune_overhead_pct_median",
    "degrade_transitions",
    "degrade_shed_tuples",
    "slo_tenants", "slo_hot_tenant",
    "slo_violation_detected",
    "slo_violating_tenant",
    "slo_violating_objective",
    "slo_owning_stage",
    "slo_false_positives",
    "slo_burn_events_total",
    "slo_conservation_ok",
    "attribution_overhead_pct_median",
    "sla_ms", "sla_met",
)


def measure_rtt_floor(n: int = 12) -> float:
    """Drained device→host round-trip floor (ms): device_get of a tiny
    freshly-computed scalar on an idle queue. Every emit-latency sample in
    this harness pays at least this — on tunneled devices it is ~125 ms
    and DOMINATES p99 for fast cells, so artifacts report it alongside
    (docs/DESIGN.md)."""
    import jax
    import jax.numpy as jnp

    f = jax.jit(lambda x: x + 1)
    h = f(jnp.int32(0))
    jax.device_get(h)
    best = float("inf")
    for _ in range(n):
        # a FRESH array each time — re-fetching the same jax.Array hits
        # its cached host copy and measures nothing (r3 review)
        h = f(h)
        t0 = time.perf_counter()
        jax.device_get(h)
        best = min(best, time.perf_counter() - t0)
    return best * 1e3


def _run_pipeline_cell(pipeline, cfg: BenchmarkConfig, window_spec: str,
                       agg_name: str, mode: str,
                       latency_samples: int = LATENCY_SAMPLES_MAX,
                       latency_budget_s: float = LATENCY_BUDGET_S,
                       obs: Optional[_obs.Observability] = None) -> BenchResult:
    """bench.py's measurement discipline for any fused pipeline object:
    pre-roll past the widest window span, time a steady-state region, then
    sample emit latency with a drained queue (up to ``latency_samples``
    samples within ``latency_budget_s``, at least 5).

    With ``obs`` attached, the pipeline's driver hooks record per-interval
    step latency + ingest counters, the harness phases record spans, and
    the structured export lands in the result's ``metrics`` section."""
    import jax

    from ..core.windows import SessionWindow

    _span = obs.span if obs is not None else (
        lambda name: contextlib.nullcontext())

    max_span = max(int(w.gap) if isinstance(w, SessionWindow)
                   else w.clear_delay() for w in pipeline.windows)
    warmup = -(-max_span // pipeline.wm_period_ms) + 2
    timed = max(1, cfg.runtime_s,
                getattr(pipeline, "min_timed_intervals", 0))
    if mode == "buckets":
        # the no-sharing baseline is deliberately O(#triggers × ring) per
        # interval — a few deterministic intervals measure it fine
        timed = min(timed, 3)
        latency_samples = min(latency_samples, 3)
    # the sparsest window must trigger at least once inside the timed
    # region (a 60 s-slide window fires every 60 intervals — a 10-interval
    # run would report windows_emitted=0)
    def _trigger_horizon(w):
        from ..core.windows import FixedBandWindow, SlidingWindow

        if isinstance(w, SessionWindow):
            return 0                    # emission cadence is gap-driven;
                                        # min_timed_intervals covers it
        if isinstance(w, FixedBandWindow):
            return int(w.start + w.size)      # its single trigger point
        if isinstance(w, SlidingWindow):
            # the warmup phase (prefill or a full run) always advances past
            # the widest window span before the timed region, so the first
            # sliding trigger has already fired: one slide per further
            # trigger is the exact post-warmup horizon (r3 review —
            # max(size, slide) here only inflated cell wall time)
            return int(w.slide)
        return int(w.size)

    max_period = max(_trigger_horizon(w) for w in pipeline.windows)
    timed = max(timed, -(-max_period // pipeline.wm_period_ms) + 1)

    with _span("warmup"):
        pipeline.reset()
        if hasattr(pipeline, "prefill"):
            pipeline.prefill(warmup)   # ring fill without the query cost
        else:
            pipeline.run(warmup, collect=False)
        pipeline.sync()

    if obs is not None:
        # attach AFTER warmup: warmup tuples must not pollute the counters,
        # and the rate denominator restarts so *_per_s reflects the
        # measured region, not compile/warmup wall time
        if obs.latency is None:
            # emission-latency lineage (ISSUE 14): every metrics-bearing
            # cell traces sampled chains through the driver seams in the
            # timed region, and the drained phase below force-samples
            # its first-emit probes on the same tracer
            obs.attach_latency()
        pipeline.set_observability(obs)
        obs.registry.reset_clock()
    timed_from = getattr(pipeline, "_interval", warmup)
    t0 = time.perf_counter()
    with _span("timed"):
        outs = pipeline.run(timed, collect=True)
        pipeline.sync()
    wall = time.perf_counter() - t0

    cnts = jax.device_get([o[2] for o in outs])
    emitted = int(sum(int((c > 0).sum()) for c in cnts))

    # Emit-latency samples measure DELIVERY of final window values: wide
    # sketch partials lower to one float per window ON DEVICE
    # (DeviceAggregateSpec.lower_device) so the fetched payload is [T]-
    # sized — on bandwidth-limited links, fetching raw [T, width] sketch
    # registers would measure the link, not the engine (docs/DESIGN.md).
    specs = [a.device_spec() for a in pipeline.aggregations]
    if any(s.lower_device is not None for s in specs):
        emit_payload = jax.jit(lambda cnt, results: (cnt, tuple(
            (s.lower_device(r, cnt) if s.lower_device is not None else r)
            for s, r in zip(specs, results))))
        # warm the lowering jit on the last timed output so the first
        # sample doesn't time its compile (r3 review)
        jax.device_get(emit_payload(outs[-1][2], outs[-1][3]))
    else:
        # dense aggs: [T, w<=2] payloads are already small — a jitted
        # identity would only add a dispatch per sample
        emit_payload = lambda cnt, results: (cnt, results)  # noqa: E731
    if obs is not None:
        # the timed region is over: freeze the rate denominator and detach
        # the per-interval hooks so the drained latency phase (up to 45 s
        # of syncs) neither dilutes *_per_s nor inflates the counters
        obs.registry.stop_clock()
        pipeline.set_observability(None)
    lats = []
    fe_lats = []
    tracer = obs.latency if obs is not None else None
    t_lat = time.perf_counter()
    with _span("latency"):
        for _ in range(latency_samples):
            pipeline.sync()
            t1 = time.perf_counter()
            # first-emit probe (ISSUE 14): a force-sampled chain around
            # exactly this drained sample — dispatch at run(1),
            # eligibility the moment the watermark-advancing dispatch
            # returns, emit when the window payload is host-delivered;
            # first_emit = eligibility -> emit, the Karimov-style
            # number the whole-sample wall time (lats) only bounds
            lid = tracer.open(force=True) if tracer is not None else None
            out = pipeline.run(1)[0]
            if lid is not None:
                tracer.stamp(lid, _lat.STAGE_ELIGIBILITY)
            jax.device_get(emit_payload(out[2], out[3]))
            lats.append((time.perf_counter() - t1) * 1e3)
            if lid is not None:
                tracer.stamp(lid, _lat.STAGE_EMIT)
                fin = tracer.finalize(lid)
                if fin is not None and fin["first_emit_ms"] is not None:
                    fe_lats.append(fin["first_emit_ms"])
            if (len(lats) >= LATENCY_SAMPLES_MIN
                    and time.perf_counter() - t_lat > latency_budget_s):
                break
    pipeline.check_overflow()

    if hasattr(pipeline, "tuples_in_range"):
        # silence-aware accounting (session pipelines: silent intervals
        # carry no tuples)
        n_tuples = pipeline.tuples_in_range(timed_from, timed_from + timed)
    else:
        n_tuples = timed * pipeline.tuples_per_interval
    res = BenchResult(
        name=cfg.name, windows=window_spec, aggregation=agg_name,
        tuples_per_sec=n_tuples / wall,
        p99_emit_ms=0.0,                    # filled by latency_stats below
        n_windows_emitted=emitted, n_tuples=n_tuples, wall_s=wall)
    res.n_lat_samples = len(lats)
    # stall-robust stats (VERDICT r4 weak #5): raw p99 stays the primary
    # field, but trimmed p99 + stall count ride alongside so a tunnel
    # stall can never masquerade as an engine latency
    for k, v in latency_stats(lats).items():
        setattr(res, k, v)
    first_emit_stats(res, fe_lats)
    finalize_observability(res, obs, lats, emitted)
    # tunnel-independent emit latency (VERDICT r3 item 9): the fused step
    # computes an interval's window results within the same device program
    # that ingests it, so the steady-state per-interval device time IS the
    # interval-attributable emit latency — no host/tunnel RTT in it (the
    # sampled p50/p99 above measure dispatch→fetched delivery instead,
    # which the tunnel floor dominates)
    res.emit_ms_device = wall / timed * 1e3
    return res


def run_cell(cfg: BenchmarkConfig, window_spec: str, agg_name: str,
             engine: str,
             collect_metrics: bool = True,
             make_obs: Optional[callable] = None) -> BenchResult:
    """One (windowConfiguration × engine × aggFunction) cell. Unless
    ``collect_metrics=False``, a fresh per-cell
    :class:`scotty_tpu.obs.Observability` rides the run and its export is
    embedded in the result (``metrics`` section). ``make_obs`` overrides
    how that per-cell Observability is built (the runner's
    ``--flight-capacity``/``--serve-port`` wiring passes a factory that
    attaches a FlightRecorder and publishes the live instance to the
    shared endpoint)."""
    windows = parse_window_spec(window_spec, seed=cfg.seed)
    engine = {"Slicing": "TpuEngine", "Flink": "Buckets"}.get(engine, engine)
    if not collect_metrics:
        obs = None
    else:
        obs = make_obs() if make_obs is not None else _obs.Observability()
    if cfg.legacy_generator and (engine != "TpuEngine"
                                 or cfg.session_config):
        # the anchor cell must never silently substitute a different
        # execution mode — the whole point is a workload-identical
        # cross-round comparison on the aligned pipeline
        raise NotImplementedError(
            "legacyGenerator anchor cells run only on the TpuEngine "
            "aligned pipeline (no sessionConfig, no alternate engines)")

    if engine == "TpuEngine":
        if not cfg.session_config:
            from ..engine import EngineConfig
            from ..engine.pipeline import AlignedStreamPipeline, StreamPipeline

            econf = EngineConfig(capacity=cfg.capacity, annex_capacity=8,
                                 min_trigger_pad=32,
                                 overflow_policy=cfg.overflow_policy)
            try:
                tp = _round_throughput(
                    cfg.throughput,
                    AlignedStreamPipeline.slice_grid(
                        windows, cfg.watermark_period_ms))
                p = AlignedStreamPipeline(
                    windows, [make_aggregation(agg_name)], config=econf,
                    throughput=tp, wm_period_ms=cfg.watermark_period_ms,
                    max_lateness=cfg.max_lateness, seed=cfg.seed,
                    gc_every=32, out_of_order_pct=cfg.out_of_order_pct,
                    legacy_generator=cfg.legacy_generator,
                    collect_device_metrics=collect_metrics)
                return _run_pipeline_cell(p, cfg, window_spec, agg_name,
                                          "aligned", obs=obs)
            except NotImplementedError:
                if cfg.legacy_generator:
                    # no silent fallback for the anchor cell (see the
                    # guard above; this covers aligned-spec rejections
                    # like sketch aggs or an unaligned window mix)
                    raise
            try:
                # count-measure workloads (count tumbling, optionally mixed
                # with time grids, in- or out-of-order): the fused record-
                # ring pipeline — closed-form count bound, no per-watermark
                # probe (VERDICT r4 item 1)
                from ..engine.count_pipeline import CountStreamPipeline

                p = CountStreamPipeline(
                    windows, [make_aggregation(agg_name)], config=econf,
                    throughput=cfg.throughput,
                    wm_period_ms=cfg.watermark_period_ms,
                    max_lateness=cfg.max_lateness, seed=cfg.seed,
                    out_of_order_pct=cfg.out_of_order_pct,
                    collect_device_metrics=collect_metrics)
                return _run_pipeline_cell(p, cfg, window_spec, agg_name,
                                          "count-fused", obs=obs)
            except NotImplementedError:
                pass
            try:
                # fused fallback for specs the aligned pipeline rejects
                # (fixed-band windows, sketch lifts on bands…): still one
                # XLA dispatch per watermark interval, via the general
                # scatter ingest (+ per-sub-batch late lanes when OOO)
                p = StreamPipeline(
                    windows, [make_aggregation(agg_name)], config=econf,
                    throughput=cfg.throughput,
                    wm_period_ms=cfg.watermark_period_ms,
                    max_lateness=cfg.max_lateness, seed=cfg.seed,
                    out_of_order_pct=cfg.out_of_order_pct,
                    collect_device_metrics=collect_metrics)
                return _run_pipeline_cell(p, cfg, window_spec, agg_name,
                                          "fused", obs=obs)
            except NotImplementedError:
                pass
        # count-measure / session specs: batch-at-a-time device operator
        # via the classic harness (device-generated streams with split
        # late sub-batches). Anything the fused pipelines reject pays
        # per-batch dispatch overhead (~5-15 ms each on tunneled devices —
        # docs/DESIGN.md), so the pipelines above are always preferred.
        return run_benchmark(cfg, window_spec, agg_name, engine="TpuEngine",
                             obs=obs, collect_metrics=collect_metrics)

    if engine == "Buckets":
        from .buckets import BucketWindowPipeline
        from ..engine.pipeline import AlignedStreamPipeline

        tp = getattr(cfg, "buckets_throughput", None) or max(
            1000, cfg.throughput // 200)
        tp = _round_throughput(
            tp, AlignedStreamPipeline.slice_grid(windows,
                                                 cfg.watermark_period_ms))
        p = BucketWindowPipeline(
            windows, [make_aggregation(agg_name)], throughput=tp,
            wm_period_ms=cfg.watermark_period_ms, seed=cfg.seed,
            max_lateness=cfg.max_lateness)
        return _run_pipeline_cell(p, cfg, window_spec, agg_name, "buckets",
                                  obs=obs)

    if engine == "Hybrid":
        # resolve the backend the way HybridWindowOperator would, then use
        # the matching measurement loop: device-realizable workloads take
        # a fused pipeline (one dispatch per watermark interval) or the
        # async TpuEngine path; everything else runs on the host
        from ..hybrid import HybridWindowOperator

        probe = HybridWindowOperator()
        for w in windows:
            probe.add_window_assigner(w)
        probe.add_aggregation(make_aggregation(agg_name))
        if probe._device_realizable():
            if cfg.out_of_order_pct == 0 and cfg.session_config:
                from ..engine import EngineConfig
                from ..engine.session_pipeline import SessionStreamPipeline

                try:
                    p = SessionStreamPipeline(
                        windows, [make_aggregation(agg_name)],
                        config=EngineConfig(
                            capacity=cfg.capacity, annex_capacity=8,
                            min_trigger_pad=32,
                            overflow_policy=cfg.overflow_policy),
                        throughput=cfg.throughput,
                        wm_period_ms=cfg.watermark_period_ms,
                        max_lateness=cfg.max_lateness, seed=cfg.seed,
                        session_config=cfg.session_config,
                        collect_device_metrics=collect_metrics)
                    return _run_pipeline_cell(p, cfg, window_spec,
                                              agg_name, "session", obs=obs)
                except NotImplementedError:
                    pass
            return run_benchmark(cfg, window_spec, agg_name,
                                 engine="TpuEngine", obs=obs,
                                 collect_metrics=collect_metrics)
        return run_benchmark(cfg, window_spec, agg_name, engine="Hybrid",
                             obs=obs, collect_metrics=collect_metrics)

    if engine == "Simulator":
        return run_benchmark(cfg, window_spec, agg_name, engine="Simulator",
                             obs=obs, collect_metrics=collect_metrics)

    if engine == "Keyed":
        return run_keyed_cell(cfg, window_spec, agg_name, obs=obs)

    if engine == "MeshKeyed":
        return run_mesh_keyed_cell(cfg, window_spec, agg_name, obs=obs)

    if engine == "HostFed":
        return run_host_fed_cell(cfg, window_spec, agg_name, obs=obs)

    if engine == "KeyedHostFed":
        return run_keyed_host_fed_cell(cfg, window_spec, agg_name, obs=obs)

    if engine == "ShapedOOO":
        return run_shaped_ooo_cell(cfg, window_spec, agg_name, obs=obs)

    if engine == "ContextChaos":
        return run_context_chaos_cell(cfg, window_spec, agg_name, obs=obs)

    if engine == "CountFused":
        return run_count_fused_cell(cfg, window_spec, agg_name, obs=obs)

    if engine == "RingFed":
        return run_ring_fed_cell(cfg, window_spec, agg_name, obs=obs)

    if engine == "LatencyHeadline":
        return run_latency_headline_cell(cfg, window_spec, agg_name,
                                         obs=obs)

    if engine == "RingFedMesh":
        return run_ring_fed_mesh_cell(cfg, window_spec, agg_name, obs=obs)

    if engine == "IngestExternal":
        return run_ingest_external_cell(cfg, window_spec, agg_name,
                                        obs=obs)

    if engine == "Soak":
        return run_soak_cell(cfg, window_spec, agg_name, obs=obs)

    if engine == "QueryChurn":
        return run_query_churn_cell(cfg, window_spec, agg_name, obs=obs)

    if engine == "QueryChurnMesh":
        return run_query_churn_mesh_cell(cfg, window_spec, agg_name,
                                         obs=obs)

    if engine == "WorkloadDrift":
        return run_workload_drift_cell(cfg, window_spec, agg_name,
                                       obs=obs)

    if engine == "AutotuneShift":
        return run_autotune_shift_cell(cfg, window_spec, agg_name,
                                       obs=obs)

    if engine == "SloChurn":
        return run_slo_churn_cell(cfg, window_spec, agg_name, obs=obs)

    raise ValueError(f"unknown engine {engine!r}")


def _churn_schedule(cfg: BenchmarkConfig, pool, n_intervals: int,
                    n_initial: int):
    """The seeded register/cancel schedule: ``schedule[i]`` is interval
    i's command list (the :func:`scotty_tpu.serving.replay_schedule`
    format), deterministically generated from ``cfg.seed`` — the serving
    run AND the oracle replay both consume THIS structure, so the two
    runs cannot drift. Registers ramp toward ``churn_max_active`` then
    alternate with cancels; >= ``cfg.churn_ops`` operations total."""
    rng = np.random.default_rng(cfg.seed + 0x5e41)
    ops_per_interval = -(-cfg.churn_ops // n_intervals)
    schedule = [[] for _ in range(n_intervals)]
    live: list = []
    next_id = 0
    n_ops = 0
    for i in range(n_intervals):
        for _ in range(ops_per_interval):
            headroom = n_initial + len(live) < cfg.churn_max_active
            if live and (not headroom or rng.random() < 0.45):
                rid = live.pop(int(rng.integers(len(live))))
                schedule[i].append(("cancel", rid))
            else:
                w = pool[int(rng.integers(len(pool)))]
                tenant = f"tenant{next_id % max(1, cfg.churn_tenants)}"
                schedule[i].append(("register", next_id, w, tenant))
                live.append(next_id)
                next_id += 1
            n_ops += 1
    return schedule, n_ops, next_id


def _churn_pool(windows, g: int, P: int, max_size: int):
    """Churnable window geometries: slides/sizes multiples of the slice
    grid, slides >= P/8 so the per-slot trigger-lane bucket stays fixed
    for the whole run (steady-state churn must not rebucket)."""
    from ..core.windows import SlidingWindow, TumblingWindow, WindowMeasure

    T = WindowMeasure.Time
    slides = [s for s in (P, P // 2, P // 4, P // 8)
              if s >= g and s % g == 0] or [max(g, P)]
    pool = []
    for sl in slides:
        for m in (1, 2, 4):
            if sl * m <= max_size:
                pool.append(SlidingWindow(T, sl * m, sl))
        if sl <= max_size:
            pool.append(TumblingWindow(T, sl))
    return pool


def _churn_rows(by_slot: dict, slot: int):
    """One slot's emissions as exact-comparable tuples (f32 value bits)."""
    return [(s, e, c, tuple(np.float32(v).tobytes() for v in vals))
            for (s, e, c, vals) in by_slot.get(slot, ())]


def run_query_churn_cell(cfg: BenchmarkConfig, window_spec: str,
                         agg_name: str,
                         obs: Optional[_obs.Observability] = None
                         ) -> BenchResult:
    """Query-churn cell (ISSUE 6): a seeded schedule registers/cancels
    >= ``churnOps`` windows MID-STREAM against a
    :class:`scotty_tpu.serving.QueryService`, recording the jit-trace
    count after warmup (the zero-steady-state-retrace acceptance), the
    throughput delta vs the static-set equivalent pipeline, and — unless
    ``churnOracle`` is off — a bit-exact comparison of every active
    query's emissions against an always-active superset oracle replaying
    the same schedule (per-trigger-row results are independent and the
    engine state is query-set independent, so equality must be exact)."""
    import jax

    from ..engine import EngineConfig
    from ..engine.pipeline import AlignedStreamPipeline
    from ..serving import QueryAdmission, QueryService, replay_schedule
    from ..serving.cache import pad_pow2

    windows = parse_window_spec(window_spec, seed=cfg.seed)
    P = cfg.watermark_period_ms
    g = AlignedStreamPipeline.slice_grid(windows, P)
    tp = _round_throughput(cfg.throughput, g)
    max_size = max([4 * P] + [int(w.size) for w in windows])
    pool = _churn_pool(windows, g, P, max_size)
    lanes = max(P // int(getattr(w, "slide", w.size)) + 2
                for w in pool + windows)
    econf = EngineConfig(capacity=cfg.capacity, annex_capacity=8,
                         min_trigger_pad=32,
                         overflow_policy=cfg.overflow_policy)

    n_timed = max(4, cfg.runtime_s)
    schedule, n_ops, n_regs = _churn_schedule(cfg, pool, n_timed,
                                              len(windows))
    warmup = max_size // P + 2

    def build_service(max_queries: int, min_slots: int) -> QueryService:
        return QueryService(
            [make_aggregation(agg_name)], slice_grid=g,
            max_window_size=max_size, throughput=tp, wm_period_ms=P,
            max_lateness=cfg.max_lateness, seed=cfg.seed, config=econf,
            admission=QueryAdmission(max_queries=max_queries),
            windows=windows, min_slots=min_slots,
            min_trigger_lanes=pad_pow2(lanes, 8))

    svc = build_service(cfg.churn_max_active,
                        pad_pow2(cfg.churn_max_active, 8))
    svc.run(warmup, collect=False)
    svc.sync()
    svc.mark_warm()
    if obs is not None:
        svc.set_observability(obs)
        obs.registry.reset_clock()

    handles: dict = {}
    slot_maps = []                  # per timed interval: live reg -> slot
    outs = []
    t0 = time.perf_counter()
    for cmds in schedule:
        replay_schedule(svc, cmds, handles)
        slot_maps.append({rid: h.slot for rid, h in handles.items()})
        outs.extend(svc.run(1, collect=True))
    svc.sync()
    wall = time.perf_counter() - t0
    svc.check_overflow()
    retraces = svc.retraces_since_warm
    n_tuples = n_timed * svc.pipeline.tuples_per_interval
    if obs is not None:
        obs.registry.stop_clock()
        svc.set_observability(None)

    # drained emit-latency samples on the live churned query set
    lats = []
    t_lat = time.perf_counter()
    for _ in range(LATENCY_SAMPLES_MAX):
        svc.sync()
        t1 = time.perf_counter()
        out = svc.run(1)[0]
        jax.device_get((out[2], out[3]))
        lats.append((time.perf_counter() - t1) * 1e3)
        if (len(lats) >= LATENCY_SAMPLES_MIN
                and time.perf_counter() - t_lat > LATENCY_BUDGET_S):
            break
    svc.check_overflow()
    emitted = 0
    by_slot_per_interval = [svc.results_by_slot(o) for o in outs]
    for bs in by_slot_per_interval:
        emitted += sum(len(rows) for rows in bs.values())

    # static-set equivalent: the same engine geometry with the seed
    # window set baked in at build time — the <= 5% penalty comparator
    ps = AlignedStreamPipeline(
        windows, [make_aggregation(agg_name)], config=econf, throughput=tp,
        wm_period_ms=P, max_lateness=cfg.max_lateness, seed=cfg.seed)
    ps.run(warmup, collect=False)
    ps.sync()
    t0 = time.perf_counter()
    ps.run(n_timed, collect=False)
    ps.sync()
    static_wall = time.perf_counter() - t0
    ps.check_overflow()
    static_tps = n_timed * ps.tuples_per_interval / static_wall

    oracle_match = None
    if cfg.churn_oracle:
        # superset oracle: every scheduled registration active from the
        # start; the serving run's results for a query active at interval
        # i must BIT-MATCH the oracle's rows for that query at interval i
        oracle = build_service(n_regs + len(windows) + 1,
                               pad_pow2(n_regs + len(windows), 8))
        ohandles: dict = {}
        for cmds in schedule:
            for cmd in cmds:
                if cmd[0] == "register":
                    _, rid, w, tenant = cmd
                    ohandles[rid] = oracle.register(w, tenant=tenant)
        oracle.run(warmup, collect=False)
        oracle.sync()
        oouts = oracle.run(n_timed, collect=True)
        oracle.sync()
        oracle.check_overflow()
        oracle_match = True
        for i, (bs, omap) in enumerate(zip(by_slot_per_interval,
                                           slot_maps)):
            obs_rows = oracle.results_by_slot(oouts[i])
            for rid, slot in omap.items():
                if _churn_rows(bs, slot) != _churn_rows(
                        obs_rows, ohandles[rid].slot):
                    oracle_match = False
                    break
            if not oracle_match:
                break

    res = BenchResult(
        name=cfg.name, windows=window_spec, aggregation=agg_name,
        tuples_per_sec=n_tuples / wall,
        p99_emit_ms=float(np.percentile(lats, 99)) if lats else 0.0,
        n_windows_emitted=emitted, n_tuples=n_tuples, wall_s=wall)
    res.n_lat_samples = len(lats)
    res.p50_emit_ms = float(np.percentile(lats, 50)) if lats else 0.0
    res.emit_ms_device = wall / n_timed * 1e3
    stats = svc.stats()
    res.serving_retraces_after_warmup = int(retraces)
    res.serving_registered = int(stats.get("serving_registered", 0))
    res.serving_cancelled = int(stats.get("serving_cancelled", 0))
    res.serving_rejected = int(stats.get("serving_rejected", 0))
    res.serving_cache_hits = int(stats.get("serving_cache_hits", 0))
    res.churn_ops = int(n_ops)
    res.throughput_static = static_tps
    res.throughput_delta_pct = (1.0 - res.tuples_per_sec
                                / max(static_tps, 1e-9)) * 100.0
    if oracle_match is not None:
        res.oracle_match = bool(oracle_match)
    # the full schedule, compactly: [interval, "r", reg_id, str(window),
    # tenant] / [interval, "c", reg_id] — with the seed this is the
    # complete reproduction recipe
    res.churn_schedule = [
        ([i, "r", cmd[1], str(cmd[2]), cmd[3]] if cmd[0] == "register"
         else [i, "c", cmd[1]])
        for i, cmds in enumerate(schedule) for cmd in cmds]
    res.churn_seed = int(cfg.seed)
    finalize_observability(res, obs, lats, emitted, n_tuples=n_tuples)
    return res


def run_query_churn_mesh_cell(cfg: BenchmarkConfig, window_spec: str,
                              agg_name: str,
                              obs: Optional[_obs.Observability] = None
                              ) -> BenchResult:
    """Mesh-serving churn cell (ISSUE 13): the seeded churn schedule
    registers/cancels >= ``churnOps`` windows MID-STREAM against a
    :class:`scotty_tpu.mesh_serving.MeshQueryService` — ``nKeys``
    logical keys over ``nShards`` device shards — while
    ``meshReshardSchedule`` drives live checkpoint-boundary reshards
    under a Supervisor with an exactly-once TransactionalSink tagging
    every per-query global emission ``(epoch, seq)``.

    Recorded contract:

    * ``serving_retraces_after_warmup`` — trace-counter-reconciled
      steady-state retraces (the zero-retrace acceptance), with the
      compiles a reshard's genuinely-new mesh forces itemized apart as
      ``reshard_retraces``;
    * ``reshard_timeline`` — each live reshard's from/to/interval/wall;
    * ``oracle_match`` — unless ``churnOracle`` is off, every live
      query's emissions (psum-folded global AND sampled per-key rows)
      bit-compared against an always-active superset service replaying
      the SAME reshard schedule (equal shard-count phases make the psum
      reduction trees identical, so equality is exact);
    * ``delivery_tags_unique`` — no ``(epoch, seq)`` tag delivered
      twice across the whole churned, resharded run;
    * aggregate throughput over the churn loop, reshard wall time
      excluded and reported separately (``platform``/``host_cores``
      recorded — the >=6x mesh scaling number stays a TPU-box cert per
      the PR 5/7/10 discipline).
    """
    import os as _os
    import tempfile

    import jax

    from ..delivery import EXACTLY_ONCE, TransactionalSink
    from ..engine import EngineConfig
    from ..engine.pipeline import AlignedStreamPipeline
    from ..mesh_serving import MeshQueryService
    from ..resilience import ManualClock, Supervisor
    from ..serving import QueryAdmission, replay_schedule
    from ..serving.cache import pad_pow2

    windows = parse_window_spec(window_spec, seed=cfg.seed)
    P = cfg.watermark_period_ms
    g = AlignedStreamPipeline.slice_grid(windows, P)
    max_size = max([4 * P] + [int(w.size) for w in windows])
    pool = _churn_pool(windows, g, P, max_size)
    lanes = max(P // int(getattr(w, "slide", w.size)) + 2
                for w in pool + windows)
    n_shards = cfg.n_shards or len(jax.devices())
    K = int(cfg.n_keys)
    econf = EngineConfig(capacity=cfg.capacity, annex_capacity=8,
                         min_trigger_pad=32)
    n_timed = max(4, cfg.runtime_s)
    schedule, n_ops, n_regs = _churn_schedule(cfg, pool, n_timed,
                                              len(windows))
    warmup = max_size // P + 2
    reshard_at = {int(i): int(m) for i, m in cfg.mesh_reshard_schedule}
    for m in reshard_at.values():
        if K % m:
            raise ValueError(
                f"meshReshardSchedule: nKeys {K} is not a multiple of "
                f"shard count {m}")

    def build(max_queries: int, min_slots: int) -> MeshQueryService:
        return MeshQueryService(
            [make_aggregation(agg_name)], slice_grid=g,
            max_window_size=max_size, n_keys=K, n_shards=n_shards,
            throughput=cfg.throughput, wm_period_ms=P,
            max_lateness=cfg.max_lateness, seed=cfg.seed, config=econf,
            admission=QueryAdmission(max_queries=max_queries),
            windows=windows, min_slots=min_slots,
            min_trigger_lanes=pad_pow2(lanes, 4))

    sample_keys = sorted({0, K // 3, K - 1})

    svc = build(cfg.churn_max_active,
                pad_pow2(cfg.churn_max_active, 8))
    svc.run(warmup, collect=False)
    svc.sync()
    svc.mark_warm()
    if obs is not None:
        svc.set_observability(obs)
        obs.registry.reset_clock()
        # served-cell sensor plane (ISSUE 18 satellite): the workload_*
        # fingerprint gauges and the drift counter that the /healthz
        # workload_drift check reads ride the served mesh cell exactly
        # like the single-device connector loops do — audit cadence is
        # wall-time-paced, so keep it short against ms-scale intervals
        from ..obs.drift import DriftDetector
        from ..obs.workload import WorkloadMonitor
        monitor = WorkloadMonitor(audit_interval_s=0.05)
        monitor.attach_detector(DriftDetector())
        obs.attach_workload(monitor)
    # TemporaryDirectory, not mkdtemp: at 64 K keys each committed
    # bundle is 100s of MB, and the live + oracle reshards commit
    # several — cleanup() runs on the success path below and the
    # finalizer reclaims the error path, so repeated bench runs cannot
    # fill /tmp with checkpoint bundles
    tmpdir = tempfile.TemporaryDirectory(prefix="mesh_churn_ck_")
    tmp = tmpdir.name
    sup = Supervisor(_os.path.join(tmp, "ck"), clock=ManualClock(),
                     seed=cfg.seed, obs=obs)
    tags: list = []
    sink = TransactionalSink(mode=EXACTLY_ONCE, obs=obs,
                             deliver=lambda it, e, s: tags.append((e, s)))
    sup.sink = sink

    handles: dict = {}
    per_interval = []          # (slot_map, global rows, sampled key rows)
    reshard_wall_s = 0.0
    t0 = time.perf_counter()
    for i, cmds in enumerate(schedule):
        if i in reshard_at and svc.n_shards != reshard_at[i]:
            row = svc.reshard(reshard_at[i], sup, pos=svc.interval)
            reshard_wall_s += row["wall_ms"] / 1e3
        replay_schedule(svc, cmds, handles)
        out = svc.run(1)[0]
        g_rows = svc.global_rows_by_slot(out)
        k_rows = {k: svc.key_rows_by_slot(out, k) for k in sample_keys}
        slot_map = {rid: h.slot for rid, h in handles.items()}
        per_interval.append((slot_map, g_rows, k_rows))
        for rid in sorted(slot_map):
            sink.emit((i, rid,
                       tuple(map(tuple, g_rows.get(slot_map[rid], ())))))
        if obs is not None:
            # the served loop's drain point: monitor sampled first,
            # then the flight ring — same contract as run_supervised_mesh
            obs.flight_sync(watermark=float((i + 1) * P))
    svc.sync()
    wall = time.perf_counter() - t0 - reshard_wall_s
    svc.check_overflow()
    retraces = svc.retraces_since_warm
    n_tuples = n_timed * svc.pipeline.tuples_per_interval
    health_verdict = None
    if obs is not None:
        # probe the served health verdict while the registry is still
        # live — the same verdict /healthz would have served
        from ..obs.server import HealthPolicy
        health_verdict = HealthPolicy().verdict(obs)
        obs.registry.stop_clock()
        svc.set_observability(None)

    # drained emit-latency samples on the live churned query set
    lats = []
    t_lat = time.perf_counter()
    for _ in range(LATENCY_SAMPLES_MAX):
        svc.sync()
        t1 = time.perf_counter()
        out = svc.run(1)[0]
        svc.pipeline.lowered_global(out)
        lats.append((time.perf_counter() - t1) * 1e3)
        if (len(lats) >= LATENCY_SAMPLES_MIN
                and time.perf_counter() - t_lat > LATENCY_BUDGET_S):
            break
    svc.check_overflow()
    emitted = sum(sum(len(rows) for rows in gr.values())
                  for (_sm, gr, _kr) in per_interval)

    oracle_match = None
    if cfg.churn_oracle:
        # superset oracle: every scheduled registration active from the
        # start, replaying the SAME reshard schedule (equal shard-count
        # phases => identical psum trees => exact equality demanded)
        oracle = build(n_regs + len(windows) + 1,
                       pad_pow2(n_regs + len(windows), 8))
        ohandles: dict = {}
        for cmds in schedule:
            for cmd in cmds:
                if cmd[0] == "register":
                    _, rid, w, tenant = cmd
                    ohandles[rid] = oracle.register(w, tenant=tenant)
        oracle.run(warmup, collect=False)
        oracle.sync()
        osup = Supervisor(_os.path.join(tmp, "ock"), clock=ManualClock(),
                          seed=cfg.seed)
        oracle_match = True
        for i in range(n_timed):
            if i in reshard_at and oracle.n_shards != reshard_at[i]:
                oracle.reshard(reshard_at[i], osup, pos=oracle.interval)
            out = oracle.run(1)[0]
            og = oracle.global_rows_by_slot(out)
            okr = {k: oracle.key_rows_by_slot(out, k)
                   for k in sample_keys}
            slot_map, g_rows, k_rows = per_interval[i]
            for rid, slot in slot_map.items():
                oslot = ohandles[rid].slot
                if g_rows.get(slot) != og.get(oslot):
                    oracle_match = False
                    break
                for k in sample_keys:
                    if k_rows[k].get(slot) != okr[k].get(oslot):
                        oracle_match = False
                        break
                if not oracle_match:
                    break
            if not oracle_match:
                break
        oracle.check_overflow()

    res = BenchResult(
        name=cfg.name, windows=window_spec, aggregation=agg_name,
        tuples_per_sec=n_tuples / wall,
        p99_emit_ms=float(np.percentile(lats, 99)) if lats else 0.0,
        n_windows_emitted=emitted, n_tuples=n_tuples, wall_s=wall)
    res.n_lat_samples = len(lats)
    res.p50_emit_ms = float(np.percentile(lats, 50)) if lats else 0.0
    res.emit_ms_device = wall / n_timed * 1e3
    stats = svc.stats()
    res.serving_retraces_after_warmup = int(retraces)
    res.reshard_retraces = int(stats["reshard_retraces"])
    res.reshard_timeline = list(svc.reshard_timeline)
    res.reshard_wall_s = round(reshard_wall_s, 3)
    res.serving_registered = int(stats.get("serving_registered", 0))
    res.serving_cancelled = int(stats.get("serving_cancelled", 0))
    res.serving_rejected = int(stats.get("serving_rejected", 0))
    res.serving_cache_hits = int(stats.get("serving_cache_hits", 0))
    res.churn_ops = int(n_ops)
    res.n_keys = K
    res.n_shards = int(n_shards)
    res.platform = jax.devices()[0].platform
    res.host_cores = _os.cpu_count()
    res.delivery_mode = EXACTLY_ONCE
    res.delivery_tags_unique = bool(len(tags) == len(set(tags)))
    res.delivery_snapshot = sink.snapshot()
    if oracle_match is not None:
        res.oracle_match = bool(oracle_match)
    res.churn_schedule = [
        ([i, "r", cmd[1], str(cmd[2]), cmd[3]] if cmd[0] == "register"
         else [i, "c", cmd[1]])
        for i, cmds in enumerate(schedule) for cmd in cmds]
    res.churn_seed = int(cfg.seed)
    if health_verdict is not None:
        res.served_health_ok = bool(health_verdict.get("healthy", False))
        res.served_drift_events = int(
            health_verdict.get("checks", {})
            .get("workload_drift", {}).get("drift_events", 0))
    finalize_observability(res, obs, lats, emitted, n_tuples=n_tuples)
    tmpdir.cleanup()
    return res


def run_shaped_ooo_cell(cfg: BenchmarkConfig, window_spec: str,
                        agg_name: str,
                        obs: Optional[_obs.Observability] = None
                        ) -> BenchResult:
    """Shaped out-of-order cell (ISSUE 5): an ADVERSARIALLY DISORDERED
    device-resident stream — every batch fully shuffled, with a bounded
    back-reach into the previous batch's event range — taken through
    ``StreamShaper.shape_device_batch`` end to end: jitted sort-and-split,
    the in-order majority through the scatter-free dense/in-order ingest,
    the late residue through the small ``ingest_device_late`` dispatch,
    plus the normal watermark cadence. This is the general-traffic
    counterpart of the shaped ``TpuEngine`` cells: the stream is NOT
    pipeline-generated, NOT sorted, and NOT aligned — the number to hold
    against ``micro.json: ingest_scatter`` (the same stream unshaped)."""
    import jax
    import jax.numpy as jnp

    from ..autotune import EngineGeometry
    from ..engine import EngineConfig, TpuWindowOperator
    from ..shaper import StreamShaper

    windows = parse_window_spec(window_spec, seed=cfg.seed)
    B = cfg.batch_size
    n_batches = int(max(4, cfg.throughput * cfg.runtime_s // B))
    span = max(1.0, cfg.runtime_s * 1000 / n_batches)
    back = cfg.shaper_back_ms or max(1, min(cfg.max_lateness,
                                            int(span) // 8))

    # pregenerate a cycled pool of shuffled base batches ON DEVICE (the
    # stream's origin is device memory — generation cost is the load
    # generator's, excluded like every other cell); per-batch offsets are
    # added lazily on device, which is part of the source's cost model
    rng = np.random.default_rng(cfg.seed)
    P = min(n_batches, 16)
    pool = []
    for _ in range(P):
        ts = rng.integers(0, int(span) + back, size=B).astype(np.int64)
        vals = (rng.random(B) * 10_000).astype(np.float32)
        pool.append((jax.device_put(vals), jax.device_put(ts)))

    # default residue lanes at B/4: the adversarial stream's expected
    # late fraction is back/(span+back) ≈ 11%, so the static late block
    # runs near half-full — exercised every batch, never overflowing
    late_cap = cfg.shaper_late_capacity or max(64, B // 4)
    # refuse mis-sized geometries UP FRONT: at tiny spans (high
    # throughput / small batches) the integer span collapses and the
    # late fraction back/(int(span)+back) can exceed the residue lanes —
    # the run would only die in ShaperOverflow at the final drain
    exp_late = B * back / (int(span) + back)
    if exp_late * 1.5 > late_cap:
        raise ValueError(
            f"ShapedOOO geometry: expected late fraction "
            f"{back}/({int(span)}+{back}) of batch_size {B} ≈ "
            f"{exp_late:.0f} tuples ≥ late_capacity {late_cap} — lower "
            "throughput (longer span per batch), shrink shaperBackMs, or "
            "raise shaperLateCapacity")
    # one geometry derives both module configs (geometry-discipline):
    # the coupled engine/shaper knobs move as a single value
    geom = EngineGeometry(capacity=cfg.capacity, batch_size=B,
                          late_capacity=late_cap)
    op = TpuWindowOperator(config=geom.engine_config(
        EngineConfig(overflow_policy=cfg.overflow_policy)))
    for w in windows:
        op.add_window_assigner(w)
    op.add_aggregation(make_aggregation(agg_name))
    op.set_max_lateness(max(cfg.max_lateness, back + int(span)))
    shaper = StreamShaper(op, geom.shaper_config())

    def feed(i: int) -> int:
        # batch i covers [i*span - back, i*span + span): shuffled within,
        # reaching `back` ms into batch i-1's range
        off = int((i + 1) * span)
        v_dev, t_dev = pool[i % P]
        lo = off - back
        shaper.shape_device_batch(v_dev, t_dev + jnp.int64(lo), lo,
                                  off + int(span))
        return off + int(span)

    # warmup: compiles sort-split + ingest + watermark kernels
    hi = feed(0)
    hi = feed(1)
    warm_wm = hi + 1
    op.process_watermark_async(warm_wm)
    jax.device_get(op._state.n_slices)
    if obs is not None:
        op.set_observability(obs)
        obs.registry.reset_clock()

    next_wm = (warm_wm // cfg.watermark_period_ms + 1) \
        * cfg.watermark_period_ms
    pending = []
    t0 = time.perf_counter()
    for i in range(2, n_batches):
        hi = feed(i)
        while hi - back - int(span) >= next_wm:
            # watermark only once the back-reach can no longer repair it
            out = op.process_watermark_async(next_wm)
            if out[3] is not None:
                pending.append((out[0].shape[0], out[3]))
            next_wm += cfg.watermark_period_ms
    out = op.process_watermark_async(next_wm)
    if out[3] is not None:
        pending.append((out[0].shape[0], out[3]))
    emitted = 0
    fetched = jax.device_get([c for _, c in pending])
    for (T, _), cnt in zip(pending, fetched):
        emitted += int((cnt[:T] > 0).sum())
    op.check_overflow()                 # includes shaper.check()
    wall = time.perf_counter() - t0
    n_tuples = (n_batches - 2) * B
    if obs is not None:
        obs.registry.stop_clock()
        op.set_observability(None)

    # drained emit-latency samples: one shaped batch + watermark each,
    # time-shifted past the stream end (the shaped delivery path)
    lats = []
    cursor = int(next_wm + 2 * (int(span) + back))
    v0, t0_dev = pool[0]
    t_lat = time.perf_counter()
    for _ in range(LATENCY_SAMPLES_MAX):
        jax.device_get(op._state.n_slices)
        t1 = time.perf_counter()
        shaper.shape_device_batch(v0, t0_dev + jnp.int64(cursor), cursor,
                                  cursor + int(span) + back)
        out = op.process_watermark_async(cursor + int(span) + back + 1)
        if out[3] is not None:
            jax.device_get((out[3], out[4]))
        else:
            jax.device_get(op._state.n_slices)
        lats.append((time.perf_counter() - t1) * 1e3)
        cursor += 2 * (int(span) + back) + cfg.watermark_period_ms
        if (len(lats) >= LATENCY_SAMPLES_MIN
                and time.perf_counter() - t_lat > LATENCY_BUDGET_S):
            break
    op.check_overflow()

    res = BenchResult(
        name=cfg.name, windows=window_spec, aggregation=agg_name,
        tuples_per_sec=n_tuples / wall,
        p99_emit_ms=float(np.percentile(lats, 99)) if lats else 0.0,
        n_windows_emitted=emitted, n_tuples=n_tuples, wall_s=wall)
    res.n_lat_samples = len(lats)
    res.p50_emit_ms = float(np.percentile(lats, 50)) if lats else 0.0
    res.shaper_back_ms = back
    stats = shaper.device_stats()
    res.shaper_late_routed = stats.get("late_routed", 0)
    res.shaper_reordered = stats.get("reordered", 0)
    finalize_observability(res, obs, lats, emitted, n_tuples=n_tuples)
    return res


def _aligned_inprogram_arm(cfg: BenchmarkConfig, windows, agg_name: str,
                           legacy: bool):
    """In-program comparator for the ring-fed headline (ISSUE 11 /
    ADVICE r5 finding 1): the fused AlignedStreamPipeline at the cell's
    geometry — ``(tps, gen_share)`` where ``gen_share`` is the fraction
    of the steady-state interval the STREAM GENERATOR alone accounts
    for, measured by timing the step's own generator closure
    (``_gen_active`` — the legacy arm times the pinned r4 draws) as a
    separate jit over the same rows/chunks."""
    import jax
    import jax.numpy as jnp

    from ..engine import EngineConfig
    from ..engine.pipeline import AlignedStreamPipeline

    tp = _round_throughput(
        cfg.throughput,
        AlignedStreamPipeline.slice_grid(windows, cfg.watermark_period_ms))
    p = AlignedStreamPipeline(
        windows, [make_aggregation(agg_name)],
        config=EngineConfig(capacity=cfg.capacity, annex_capacity=8,
                            min_trigger_pad=32),
        throughput=tp, wm_period_ms=cfg.watermark_period_ms,
        max_lateness=cfg.max_lateness, seed=cfg.seed, gc_every=32,
        legacy_generator=legacy)
    p.reset()
    p.run(3, collect=False)
    p.sync()
    timed = 5
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        p.run(timed, collect=False)
        p.sync()
        best = min(best, (time.perf_counter() - t0) / timed)
    p.check_overflow()

    S, d, R = p.S, p.rows_per_chunk, p.R
    gen = p._gen_active

    @jax.jit
    def probe(key):
        def body(acc, c):
            out = gen(key, c * d + jnp.arange(d, dtype=jnp.int64))
            vals = out[0] if isinstance(out, tuple) else out
            a = acc + jnp.sum(vals)
            if isinstance(out, tuple):      # legacy: offsets are live too
                a = a + jnp.sum(out[1]).astype(jnp.float32)
            return a, None
        acc, _ = jax.lax.scan(body, jnp.float32(0),
                              jnp.arange(S // d, dtype=jnp.int64))
        return acc

    key = p._interval_key(0)
    jax.device_get(probe(key))              # compile
    best_gen = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        for r in range(timed):
            h = probe(jax.random.fold_in(key, r))
        jax.device_get(h)
        best_gen = min(best_gen, (time.perf_counter() - t0) / timed)
    return (p.tuples_per_interval / best,
            min(1.0, best_gen / best))


def run_ring_fed_cell(cfg: BenchmarkConfig, window_spec: str,
                      agg_name: str,
                      obs: Optional[_obs.Observability] = None
                      ) -> BenchResult:
    """Ring-fed headline cell (ISSUE 11, closes ADVICE r5 finding 1):
    the headline window class fed from the PR 7 ingest ring — a
    HOST-resident pregenerated in-order stream through
    ``BatchAccumulator.offer_block`` → ``IngestRing`` →
    ``DeviceRingFeeder`` prefetch → the batch operator — instead of the
    in-program generator, so the recorded number contains ZERO
    generator work. Comparators ride the row: the in-program fused
    pipeline at the same geometry (``inprogram_tps``), the pinned
    legacy-anchor generator arm (``legacy_anchor_tps``, ADVICE r5's
    workload-identical cross-round anchor), and the measured
    ``generator_share`` of each in-program arm's steady-state interval
    — quantifying exactly how much of the headline the generator is."""
    import jax

    from ..autotune import EngineGeometry
    from ..engine import EngineConfig, TpuWindowOperator
    from ..ingest import LineRateFeed

    windows = parse_window_spec(window_spec, seed=cfg.seed)
    B = cfg.batch_size
    n_chunks = int(max(6, cfg.throughput * cfg.runtime_s // B))
    span = max(1.0, cfg.runtime_s * 1000 / n_chunks)
    # event time starts past the widest window span so triggers fire
    # from the first watermarks (the in-program pipelines' prefill
    # equivalent); pooled chunks cycle so pregeneration memory stays
    # bounded at any runtime
    off0 = max(w.clear_delay() for w in windows)
    rng = np.random.default_rng(cfg.seed)
    n_pools = min(n_chunks, 12)
    pools = []
    for _ in range(n_pools):
        ts = np.sort(rng.integers(0, max(1, int(span)),
                                  size=B)).astype(np.int64)
        vals = (rng.random(B) * 10_000).astype(np.float32)
        pools.append((vals, ts))

    def chunk(i):
        vals, ts = pools[i % n_pools]
        lo = off0 + int(i * span)
        return vals, ts + np.int64(lo), off0 + int((i + 1) * span)

    # one geometry derives the engine + ring configs (geometry-
    # discipline): the coupled retunable knobs move as a single value
    geom = EngineGeometry(capacity=cfg.capacity, batch_size=B,
                          ring_depth=cfg.ring_depth or 8,
                          ring_block=cfg.ring_block_size or B)
    op = TpuWindowOperator(config=geom.engine_config(
        EngineConfig(overflow_policy=cfg.overflow_policy)))
    for w in windows:
        op.add_window_assigner(w)
    op.add_aggregation(make_aggregation(agg_name))
    op.set_max_lateness(cfg.max_lateness)
    feed = LineRateFeed(op, ring=geom.ring_config())

    warm_hi = 0
    for i in (0, 1):
        v, t, warm_hi = chunk(i)
        feed.offer_block(v, t)
    op.process_watermark_async(warm_hi + 1)
    jax.device_get(op._state.n_slices)
    if obs is not None:
        op.set_observability(obs)
        obs.registry.reset_clock()
    next_wm = (warm_hi // cfg.watermark_period_ms + 2) \
        * cfg.watermark_period_ms
    pending = []
    t0 = time.perf_counter()
    for i in range(2, n_chunks):
        v, t, hi = chunk(i)
        feed.offer_block(v, t)
        while hi >= next_wm:
            out = op.process_watermark_async(next_wm)
            if out[3] is not None:
                pending.append((out[0].shape[0], out[3]))
            next_wm += cfg.watermark_period_ms
    feed.drain()
    out = op.process_watermark_async(next_wm)
    if out[3] is not None:
        pending.append((out[0].shape[0], out[3]))
    emitted = 0
    fetched = jax.device_get([c for _, c in pending])
    for (T, _), cnt in zip(pending, fetched):
        emitted += int((cnt[:T] > 0).sum())
    op.check_overflow()
    wall = time.perf_counter() - t0
    n_tuples = (n_chunks - 2) * B
    if obs is not None:
        obs.registry.stop_clock()
        op.set_observability(None)

    res = BenchResult(
        name=cfg.name, windows=window_spec, aggregation=agg_name,
        tuples_per_sec=n_tuples / wall,
        p99_emit_ms=0.0,
        n_windows_emitted=emitted, n_tuples=n_tuples, wall_s=wall)
    res.emit_ms_device = wall / max(1, len(pending)) * 1e3
    snap = feed.snapshot()
    res.prefetch_overlap_ratio = feed.feeder.overlap_ratio()
    res.ring_full_events = int(snap["full_events"])
    res.ring_shed = int(snap["shed"])
    res.ring_blocks = int(snap["blocks"])

    # -- in-program + pinned legacy-anchor comparator arms ----------------
    res.inprogram_tps, res.generator_share = _aligned_inprogram_arm(
        cfg, windows, agg_name, legacy=False)
    try:
        (res.legacy_anchor_tps,
         res.generator_share_legacy) = _aligned_inprogram_arm(
            cfg, windows, agg_name, legacy=True)
    except NotImplementedError as e:
        res.legacy_anchor_note = f"legacy arm unavailable: {e}"
    res.ring_fed_vs_inprogram = res.tuples_per_sec / max(
        res.inprogram_tps, 1e-9)
    res.platform = jax.devices()[0].platform
    finalize_observability(res, obs, [], emitted, n_tuples=n_tuples)
    return res


def run_ring_fed_mesh_cell(cfg: BenchmarkConfig, window_spec: str,
                           agg_name: str,
                           obs: Optional[_obs.Observability] = None
                           ) -> BenchResult:
    """Ring-fed MESH cell (ISSUE 11): a HOST-resident keyed external
    stream staged through the keyed PR 7 ingest ring
    (``IngestRing(keyed=True)`` → ``RingIngestor`` →
    ``BlockSinkFeeder``) into the mesh-sharded keyed engine by LOGICAL
    key — no in-program generator anywhere in the recorded number.
    Comparators: the in-program ``MeshKeyedPipeline`` at the same
    keys/shards geometry (``inprogram_tps``) and the pinned
    legacy-anchor arm (``legacy_anchor_tps``) for cross-round context;
    ``platform``/``host_cores`` recorded — mesh scaling floors stay
    TPU-box certifications."""
    import os as _os

    import jax

    from ..engine import EngineConfig
    from ..ingest.feeder import BlockSinkFeeder, RingIngestor
    from ..ingest.ring import IngestRing, RingConfig
    from ..mesh import MeshKeyedEngine, MeshKeyedPipeline

    windows = parse_window_spec(window_spec, seed=cfg.seed)
    K = max(4, cfg.n_keys)
    n_shards = cfg.n_shards or len(jax.devices())
    B = cfg.ring_block_size or (1 << 16)
    Bk = max(64, 1 << int(np.ceil(np.log2(max(2, 4 * B // K)))))
    eng = MeshKeyedEngine(
        n_keys=K, n_shards=n_shards,
        config=EngineConfig(capacity=max(128, min(cfg.capacity, 512)),
                            batch_size=Bk, annex_capacity=8,
                            min_trigger_pad=32))
    for w in windows:
        eng.add_window_assigner(w)
    eng.add_aggregation(make_aggregation(agg_name))
    eng.set_max_lateness(cfg.max_lateness)

    ring = IngestRing(cfg.ring_depth or 8, B, keyed=True,
                      value_dtype=np.float32)
    sink = BlockSinkFeeder(
        ring, lambda keys, vals, ts: eng.process_keyed_elements(
            keys.astype(np.int64), vals, ts))
    ingestor = RingIngestor(ring, sink, obs=obs)

    n_chunks = int(max(6, cfg.throughput * cfg.runtime_s // B))
    span = max(1.0, cfg.runtime_s * 1000 / n_chunks)
    off0 = max(w.clear_delay() for w in windows)
    rng = np.random.default_rng(cfg.seed)
    n_pools = min(n_chunks, 12)
    pools = []
    for _ in range(n_pools):
        ts = np.sort(rng.integers(0, max(1, int(span)),
                                  size=B)).astype(np.int64)
        keys = rng.integers(0, K, size=B)
        vals = (rng.random(B) * 10_000).astype(np.float32)
        pools.append((keys, vals, ts))

    def offer(i):
        keys, vals, ts = pools[i % n_pools]
        lo = off0 + int(i * span)
        ingestor.offer_block(vals, ts + np.int64(lo), keys)
        ingestor.poll()
        return off0 + int((i + 1) * span)

    hi = offer(0)
    hi = offer(1)
    eng.process_watermark_async(hi + 1)
    jax.device_get(jax.tree.leaves(eng._state)[0])
    if obs is not None:
        obs.registry.reset_clock()
    next_wm = (hi // cfg.watermark_period_ms + 2) * cfg.watermark_period_ms
    pending = []
    t0 = time.perf_counter()
    for i in range(2, n_chunks):
        hi = offer(i)
        while hi >= next_wm:
            pending.append(eng.process_watermark_async(next_wm))
            next_wm += cfg.watermark_period_ms
    ingestor.drain()
    pending.append(eng.process_watermark_async(next_wm))
    emitted = 0
    for out in pending:
        ws, we, cnt, lowered = eng.lower_results(*out)
        emitted += int((cnt > 0).sum())
    eng.check_overflow()
    wall = time.perf_counter() - t0
    n_tuples = (n_chunks - 2) * B
    if obs is not None:
        obs.registry.stop_clock()

    res = BenchResult(
        name=cfg.name, windows=window_spec, aggregation=agg_name,
        tuples_per_sec=n_tuples / wall,
        p99_emit_ms=0.0,
        n_windows_emitted=emitted, n_tuples=n_tuples, wall_s=wall)
    res.n_keys = int(K)
    res.n_shards = int(n_shards)
    snap = ingestor.snapshot()
    res.ring_full_events = int(snap["full_events"])
    res.ring_shed = int(snap["shed"])
    res.ring_blocks = int(snap["blocks"])

    # in-program mesh comparator at the same geometry
    p = MeshKeyedPipeline(
        windows, [make_aggregation(agg_name)], n_keys=K,
        n_shards=n_shards,
        config=EngineConfig(capacity=max(128, min(cfg.capacity, 512)),
                            annex_capacity=8, min_trigger_pad=32),
        throughput=cfg.throughput, wm_period_ms=cfg.watermark_period_ms,
        max_lateness=cfg.max_lateness, seed=cfg.seed)
    p.reset()
    p.run(2, collect=False)
    p.sync()
    best = float("inf")
    for _ in range(3):
        t1 = time.perf_counter()
        p.run(3, collect=False)
        p.sync()
        best = min(best, (time.perf_counter() - t1) / 3)
    p.check_overflow()
    res.inprogram_tps = p.tuples_per_interval / best
    res.ring_fed_vs_inprogram = res.tuples_per_sec / max(
        res.inprogram_tps, 1e-9)
    try:
        res.legacy_anchor_tps, res.generator_share_legacy = \
            _aligned_inprogram_arm(cfg, windows, agg_name, legacy=True)
    except NotImplementedError as e:
        res.legacy_anchor_note = f"legacy arm unavailable: {e}"
    res.platform = jax.devices()[0].platform
    res.host_cores = _os.cpu_count()
    finalize_observability(res, obs, [], emitted, n_tuples=n_tuples)
    return res


def run_count_fused_cell(cfg: BenchmarkConfig, window_spec: str,
                         agg_name: str,
                         obs: Optional[_obs.Observability] = None
                         ) -> BenchResult:
    """Count-measure fused cell with an embedded oracle arm (ISSUE 11):
    the throughput number is the standard fused-pipeline discipline at
    the configured ``outOfOrderPct`` (``tuples_per_sec_inorder`` rides
    alongside from an in-order twin), and a SMALL replica of the same
    window/lateness geometry is differentially replayed — in-order vs
    the reference simulator, out-of-order vs the engine's record-merge
    rank semantics — recording ``oracle_match``/``oracle_windows``.
    The >= 50 M t/s ROADMAP floor stays a TPU-box certification; the
    cell records ``platform`` alongside."""
    import jax

    from ..engine import EngineConfig, TpuWindowOperator
    from ..engine.count_pipeline import CountStreamPipeline
    from .. import SlicingWindowOperator

    windows = parse_window_spec(window_spec, seed=cfg.seed)
    econf = EngineConfig(capacity=cfg.capacity, annex_capacity=8,
                         min_trigger_pad=32,
                         overflow_policy=cfg.overflow_policy)

    def mk(throughput, ooo, lateness):
        return CountStreamPipeline(
            windows, [make_aggregation(agg_name)], config=econf,
            throughput=throughput, wm_period_ms=cfg.watermark_period_ms,
            max_lateness=lateness, seed=cfg.seed, out_of_order_pct=ooo,
            collect_device_metrics=obs is not None)

    p = mk(cfg.throughput, cfg.out_of_order_pct, cfg.max_lateness)
    res = _run_pipeline_cell(p, cfg, window_spec, agg_name,
                             "count-fused", obs=obs)

    # in-order comparator twin (best of 3 short segments)
    p0 = mk(cfg.throughput, 0.0, cfg.max_lateness)
    p0.reset()
    p0.run(2, collect=False)
    p0.sync()
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        p0.run(3, collect=False)
        p0.sync()
        best = min(best, (time.perf_counter() - t0) / 3)
    p0.check_overflow()
    res.tuples_per_sec_inorder = p0.tuples_per_interval / best

    # -- oracle arm: small replica, replayed through the semantics
    # oracle for its arrival class (simulator in-order, engine OOO)
    def lowered_rows(agg, fetched, n_iv):
        sp = agg.device_spec()
        out = []
        for i in range(n_iv):
            ws, we, cnt, resi = fetched[i]
            rows = [(int(ws[j]), int(we[j]), float(np.asarray(
                sp.lower(np.asarray(resi[0][j])[None, :],
                         np.asarray([int(cnt[j])]))[0])))
                    for j in range(len(ws)) if cnt[j] > 0]
            out.append(sorted(rows))
        return out

    def oracle_rows(po, op, n_iv):
        out = []
        for i in range(n_iv):
            vs, ts = po.materialize_interval(i)
            for v, t in zip(vs, ts):
                op.process_element(float(v), int(t))
            out.append(sorted(
                (w.start, w.end, float(w.agg_values[0]))
                for w in op.process_watermark(
                    (i + 1) * po.wm_period_ms)))
        return out

    agg = make_aggregation(agg_name)
    oracle_match = True
    o_windows = 0
    n_iv = 5
    for ooo in (0.0, cfg.out_of_order_pct or 0.25):
        po = mk(2000, ooo, min(cfg.max_lateness,
                               cfg.watermark_period_ms))
        fetched = jax.device_get(po.run(n_iv))
        po.check_overflow()
        got = lowered_rows(agg, fetched, n_iv)
        if ooo == 0.0:
            op = SlicingWindowOperator()
        else:
            # record retention spans lateness + the largest count
            # window's clear delay (ms-mixed, reference parity) at the
            # oracle's tuple rate — size the record ring above it
            op = TpuWindowOperator(config=EngineConfig(
                capacity=1 << 13, batch_size=64, annex_capacity=256,
                min_trigger_pad=32, record_capacity=1 << 15))
        for w in windows:
            op.add_window_assigner(w)
        op.add_aggregation(make_aggregation(agg_name))
        op.set_max_lateness(po.max_lateness)
        ref = oracle_rows(po, op, n_iv)
        for g_rows, r_rows in zip(got, ref):
            o_windows += len(r_rows)
            if [g[:2] for g in g_rows] != [r[:2] for r in r_rows]:
                oracle_match = False
                continue
            for g, r in zip(g_rows, r_rows):
                if abs(g[2] - r[2]) > 3e-4 * max(1.0, abs(r[2])):
                    oracle_match = False
    res.oracle_match = bool(oracle_match)
    res.oracle_windows = int(o_windows)
    res.platform = jax.devices()[0].platform
    res.tpu_floor_note = ("the >= 50 M t/s sliding-count ROADMAP floor "
                          "is a TPU-box certification; this cell "
                          f"records platform={res.platform}")
    return res


class _ExactContextOracle:
    """Arrival-order scalar replay of the session / capped-session
    calculus — the reference-semantics third leg of the chaos cells'
    three-way oracle (the capped branch mirrors
    tests/test_context_windows.py::_ExactCapped; ``cap=None`` is the
    plain-session specialization, which the tuned engine and the
    generic SessionDecider both realize)."""

    def __init__(self, gap: int, cap=None):
        self.gap = int(gap)
        self.cap = int(cap) if cap is not None else None
        self.s: list = []          # [first, last, sum] sorted by first
        self.orphans: list = []    # (pos, value)

    def _fits(self, f, l, t):
        if self.cap is None:
            return True
        return (l - t if f > t else t - f) <= self.cap

    def add(self, v: float, t: int) -> None:
        g, s = self.gap, self.s
        exact = declined = False
        fit_i = -1
        for i, (f, l, _) in enumerate(s):
            if f <= t <= l:
                s[i][2] += v
                return                      # inside
            if f - g <= t <= l + g:
                if t == f - g:
                    exact = True
                elif fit_i < 0 and self._fits(f, l, t):
                    fit_i = i
                else:
                    declined = True
        if fit_i >= 0:
            f, l, acc = s[fit_i]
            if t < f:                       # start-extension
                s[fit_i][0] = t
                s[fit_i][2] = acc + v
                if fit_i > 0 and s[fit_i - 1][1] + g >= t \
                        and (self.cap is None
                             or l - s[fit_i - 1][0] <= self.cap):
                    pf, _, pacc = s.pop(fit_i - 1)
                    s[fit_i - 1][0] = pf
                    s[fit_i - 1][2] += pacc
                return
            s[fit_i][1] = t                 # end-extension
            s[fit_i][2] = acc + v
            if fit_i + 1 < len(s) and t + g >= s[fit_i + 1][0] \
                    and (self.cap is None
                         or s[fit_i + 1][1] - f <= self.cap):
                _, nl, nacc = s.pop(fit_i + 1)
                s[fit_i][1] = nl
                s[fit_i][2] += nacc
            return
        if declined or not exact:
            k = 0
            while k < len(s) and s[k][0] <= t:
                k += 1
            s.insert(k, [t, t, v])
            return
        self.orphans.append((t, v))        # exact-gap fall-through

    def sweep(self, wm: int):
        out, keep = [], []
        for f, l, acc in self.s:
            if l + self.gap < wm:
                ws, we = f, l + self.gap
                acc += sum(v for (p, v) in self.orphans if ws <= p < we)
                self.orphans = [(p, v) for (p, v) in self.orphans
                                if not (ws <= p < we)]
                out.append((ws, we, acc))
            else:
                keep.append([f, l, acc])
        self.s = keep
        return out


def _context_chaos_stream(cfg: BenchmarkConfig, gap: int, R: int,
                          n_pools: int = 16):
    """Seeded per-interval chaos pools for the context/session cells:
    ``K`` bursts per watermark interval separated by ``1.5 * gap``
    silences (so sessions actually CLOSE), an ``outOfOrderPct`` late
    fraction displaced back by up to the lateness bound (so chunks
    arrive OOO), and occasional mid-silence BRIDGE tuples delivered
    late (so live sessions actually MERGE). Returns ``(pools, K)``
    where ``pools[j] = (vals f32[R'], ts_off i64[R'])`` are
    interval-relative and cycle by interval index."""
    P = cfg.watermark_period_ms
    cycle = min(P, max(4, int(2.5 * gap)))
    K = max(1, P // cycle)
    burst = max(1, cycle - int(1.5 * gap))
    # displacement stays under half the gap so silences survive (late
    # DEPTH comes from the bridges, delivered up to a full interval
    # late); merges are driven by the mid-silence bridges, which sit
    # within gap of BOTH neighboring bursts
    back = min(cfg.max_lateness, max(1, gap // 2))
    rng = np.random.default_rng(cfg.seed)
    per_burst = max(8, R // K)
    pools = []
    for _ in range(n_pools):
        parts_t = []
        for k in range(K):
            lo = k * cycle
            ts = np.sort(rng.integers(lo, lo + burst,
                                      size=per_burst)).astype(np.int64)
            parts_t.append(ts)
        ts = np.concatenate(parts_t)
        late = rng.random(ts.size) < cfg.out_of_order_pct
        ts = np.where(late,
                      np.maximum(ts - rng.integers(0, back, size=ts.size),
                                 0), ts)
        # bridges: mid-silence tuples, delivered at the end of the
        # interval's arrival order — they MERGE the two adjacent live
        # sessions (silence = 1.5 * gap, so the midpoint is within gap
        # of both burst edges)
        bridges = [np.int64(k * cycle - int(0.75 * gap))
                   for k in range(1, K) if rng.random() < 0.35]
        if bridges:
            ts = np.concatenate([ts, np.asarray(bridges, np.int64)])
        vals = (rng.random(ts.size) * 100.0).astype(np.float32)
        pools.append((vals, ts))
    return pools, K


def run_context_chaos_cell(cfg: BenchmarkConfig, window_spec: str,
                           agg_name: str,
                           obs: Optional[_obs.Observability] = None
                           ) -> BenchResult:
    """Context/session chaos cell (ISSUE 11): a seeded host-fed stream
    that actually GAPS (silent spans close sessions), MERGES (late
    mid-silence bridges join live sessions) and arrives OUT OF ORDER
    (bounded back-displacement), through the batch operator's context
    machinery — the speculative chunked path for specs certifying
    ``speculation_params`` (GenericSession), the tuned session engine
    for ``Session``, the per-tuple scan fallback for order-dependent
    specs (CappedSession).

    Two arms: a throughput arm at the configured offered load
    (scan-bound window classes scale it down honestly — the recorded
    row carries the actual tuple count), and a three-way ORACLE arm on
    a smaller replica of the same stream class: engine vs the
    per-tuple-scan twin (bit-comparable bounds/pathway equivalence) vs
    the host reference simulator vs an independent arrival-order
    scalar replay — ``oracle_match``/``scan_match``/``oracle_windows``
    land in the result row. Speculative telemetry
    (``ctx_speculative_*``) rides the metrics section and the
    ``fallback_rate`` field."""
    import jax

    from ..core.windows import (CappedSessionWindow, GenericSessionWindow,
                                SessionWindow)
    from ..engine import EngineConfig, TpuWindowOperator
    from .. import SlicingWindowOperator

    if agg_name != "sum":
        raise NotImplementedError(
            "ContextChaos cells replay a sum oracle; aggFunctions must "
            "be ['sum']")
    windows = parse_window_spec(window_spec, seed=cfg.seed)
    if len(windows) != 1 or not isinstance(
            windows[0], (SessionWindow, GenericSessionWindow,
                         CappedSessionWindow)):
        raise NotImplementedError(
            "ContextChaos cells take exactly one Session / "
            "GenericSession / CappedSession window")
    w = windows[0]
    gap = int(w.gap)
    cap = int(w.max_span) if isinstance(w, CappedSessionWindow) else None
    spec = w.device_context_spec()
    sp = spec.speculation_params() if spec is not None else None
    if sp is not None and sp.order_free \
            and not isinstance(w, SessionWindow):
        scale = 1.0                 # speculative chunked batching
        mode = "speculative"
    elif isinstance(w, SessionWindow):
        scale = 1 / 40              # tuned chain + sequential late scan
        mode = "session"
    else:
        scale = 1 / 150             # per-tuple scan carries the OOO load
        mode = "scan"
    P = cfg.watermark_period_ms
    lateness = cfg.max_lateness
    R = max(256, int(cfg.throughput * scale))
    intervals = max(8, cfg.runtime_s)

    def mk_op(batch_size):
        op = TpuWindowOperator(config=EngineConfig(
            capacity=max(256, min(cfg.capacity, 1024)), batch_size=batch_size,
            annex_capacity=64, min_trigger_pad=32))
        op.add_window_assigner(w)
        op.add_aggregation(make_aggregation(agg_name))
        op.set_max_lateness(lateness)
        return op

    pools, K = _context_chaos_stream(cfg, gap, R)
    B = 1 << max(10, int(np.ceil(np.log2(max(2, pools[0][1].size)))))
    op = mk_op(B)

    def feed(i):
        vals, ts_off = pools[i % len(pools)]
        op.process_elements(vals, ts_off + np.int64(i) * P)
        op._flush()

    def wm_of(i):
        return (i + 1) * P - lateness

    # warmup: compile apply/chunk/sweep kernels. The sync anchor must be
    # re-read per drain: the context/session kernels DONATE their state
    # buffers, so a handle bound once would be deleted on TPU and would
    # return a stale cached host copy (no queue drain) on CPU.
    def drain():
        st = (op._ctx_states[0] if op._ctx_states
              else op._session_states[0])
        jax.device_get(st.n)

    feed(0)
    op.process_watermark_async(max(1, wm_of(0)))
    drain()
    if obs is not None:
        op.set_observability(obs)
        obs.registry.reset_clock()
    warm_stats = dict(getattr(op, "_ctx_spec_stats", {}) or {})

    pending = []
    lats = []
    SAMPLE_EVERY = 8
    n_tuples = 0
    t0 = time.perf_counter()
    for i in range(1, intervals + 1):
        feed(i)
        n_tuples += pools[i % len(pools)][1].size
        sample = i % SAMPLE_EVERY == 0
        if sample:
            drain()
            t1 = time.perf_counter()
        out = op.process_watermark_async(wm_of(i))
        ms = tuple(g[0] for g in out[1])
        pending.append(ms)
        if sample:
            jax.device_get(ms)
            lats.append((time.perf_counter() - t1) * 1e3)
    drain()
    wall = time.perf_counter() - t0
    op.check_overflow()
    emitted = int(sum(int(m) for grp in jax.device_get(pending)
                      for m in grp))
    if obs is not None:
        obs.registry.stop_clock()
        op.set_observability(None)
    stats = dict(getattr(op, "_ctx_spec_stats", {}) or {})
    for k in stats:
        stats[k] -= warm_stats.get(k, 0)

    # -- three-way oracle arm on a small replica of the stream class ------
    ocfg = BenchmarkConfig(
        name=cfg.name, throughput=max(256, 48 * K), runtime_s=cfg.runtime_s,
        watermark_period_ms=P, max_lateness=lateness, seed=cfg.seed + 1,
        out_of_order_pct=cfg.out_of_order_pct)
    o_pools, _ = _context_chaos_stream(ocfg, gap, ocfg.throughput,
                                       n_pools=8)
    o_intervals = max(intervals, 60)
    eng = mk_op(1024)
    scan = mk_op(1024)
    sim = SlicingWindowOperator()
    sim.add_window_assigner(w)
    sim.add_aggregation(make_aggregation(agg_name))
    sim.set_max_lateness(lateness)
    oracle = _ExactContextOracle(gap, cap)
    oracle_match = scan_match = True
    o_windows = 0
    for i in range(o_intervals):
        vals, ts_off = o_pools[i % len(o_pools)]
        ts = ts_off + np.int64(i) * P
        eng.process_elements(vals, ts)
        eng._flush()
        if not scan._built:
            scan._build()
        scan._ctx_planners = tuple(None for _ in scan._ctx_planners)
        scan.process_elements(vals, ts)
        scan._flush()
        for v, t in zip(vals, ts):
            sim.process_element(float(v), int(t))
            oracle.add(float(v), int(t))
        wm = max(1, wm_of(i))
        r_e = [x for x in eng.process_watermark(wm)]
        r_s = [x for x in scan.process_watermark(wm)]
        r_m = [x for x in sim.process_watermark(wm)]
        exp = oracle.sweep(wm)
        o_windows += len(exp)
        be = [(x.start, x.end) for x in r_e]
        if be != [(x.start, x.end) for x in r_s]:
            scan_match = False
        if be != [(ws, we) for (ws, we, _) in exp] \
                or be != [(x.get_start(), x.get_end()) for x in r_m]:
            oracle_match = False
            continue
        for x, y, (_, _, acc) in zip(r_e, r_s, exp):
            xv = float(x.agg_values[0]) if x.has_value() else None
            yv = float(y.agg_values[0]) if y.has_value() else None
            if (xv is None) != (yv is None) or (
                    xv is not None
                    and abs(xv - yv) > 1e-4 * max(1.0, abs(yv))):
                scan_match = False
            if xv is not None \
                    and abs(xv - acc) > 1e-3 * max(1.0, abs(acc)):
                oracle_match = False
    eng.check_overflow()
    scan.check_overflow()

    res = BenchResult(
        name=cfg.name, windows=window_spec, aggregation=agg_name,
        tuples_per_sec=n_tuples / wall,
        p99_emit_ms=0.0,
        n_windows_emitted=emitted, n_tuples=n_tuples, wall_s=wall)
    res.n_lat_samples = len(lats)
    for k, v in latency_stats(lats).items():
        setattr(res, k, v)
    res.emit_ms_device = wall / intervals * 1e3
    res.context_mode = mode
    res.oracle_match = bool(oracle_match)
    res.scan_match = bool(scan_match)
    res.oracle_windows = int(o_windows)
    total = stats.get("speculative_tuples", 0) \
        + stats.get("fallback_tuples", 0)
    res.ctx_speculative_tuples = int(stats.get("speculative_tuples", 0))
    res.ctx_fallback_tuples = int(stats.get("fallback_tuples", 0))
    res.ctx_fallback_runs = int(stats.get("fallback_runs", 0))
    res.ctx_fallback_rate = (stats.get("fallback_tuples", 0) / total
                             if total else 0.0)
    res.platform = jax.devices()[0].platform
    finalize_observability(res, obs, lats, emitted, n_tuples=n_tuples)
    return res


def run_ingest_external_cell(cfg: BenchmarkConfig, window_spec: str,
                             agg_name: str,
                             obs: Optional[_obs.Observability] = None
                             ) -> BenchResult:
    """Line-rate external-ingest cell (ISSUE 7): an adversarially
    disordered HOST-resident stream — every chunk fully shuffled with a
    bounded back-reach into the previous chunk's event range, nothing
    pipeline-generated — taken through the full ingest edge:
    ``BatchAccumulator.offer_block`` → ``IngestRing`` →
    ``DeviceRingFeeder`` prefetch (H2D of block N+1 overlapping the
    ingest dispatch of block N) → device sort-and-split. The recorded
    comparator is the r5 host edge for exactly this stream class: the
    per-record ``process_element`` → ``BatchAccumulator.offer`` trickle
    (measured on a prefix of the same stream, rate-extrapolated) —
    ``speedup_vs_per_record`` is the ISSUE 7 ≥ 5× acceptance number.
    The device-origin comparator remains the r5 ``ingest_shaped_ooo``
    (ShapedOOO) cell; the ≥ 50 M t/s ROADMAP floor stays a TPU-box
    certification (this cell records the platform alongside)."""
    import jax

    from ..autotune import EngineGeometry
    from ..engine import EngineConfig, TpuWindowOperator
    from ..ingest import LineRateFeed

    windows = parse_window_spec(window_spec, seed=cfg.seed)
    B = cfg.batch_size
    n_chunks = int(max(6, cfg.throughput * cfg.runtime_s // B))
    span = max(1.0, cfg.runtime_s * 1000 / n_chunks)
    back = cfg.shaper_back_ms or max(1, min(cfg.max_lateness,
                                            int(span) // 8))
    late_cap = cfg.shaper_late_capacity or max(64, B // 4)
    exp_late = B * back / (int(span) + back)
    if exp_late * 1.5 > late_cap:
        raise ValueError(
            f"IngestExternal geometry: expected late fraction "
            f"{back}/({int(span)}+{back}) of batch_size {B} ≈ "
            f"{exp_late:.0f} tuples ≥ late_capacity {late_cap} — lower "
            "throughput, shrink shaperBackMs, or raise "
            "shaperLateCapacity")
    # one geometry derives the engine/ring/shaper configs for BOTH arms
    # (geometry-discipline): coupled knobs move as a single value
    geom = EngineGeometry(capacity=cfg.capacity, batch_size=B,
                          ring_depth=cfg.ring_depth or 8,
                          ring_block=cfg.ring_block_size or B,
                          late_capacity=late_cap)

    # pregenerate the HOST-resident chunks (stream origin is host RAM;
    # generation is the load generator's cost, excluded as everywhere)
    rng = np.random.default_rng(cfg.seed)
    chunks = []
    for i in range(n_chunks):
        lo = int((i + 1) * span) - back
        ts = lo + rng.integers(0, int(span) + back, size=B).astype(np.int64)
        vals = (rng.random(B) * 10_000).astype(np.float32)
        chunks.append((vals, ts, lo, int((i + 1) * span) + int(span)))

    def mk_op():
        op = TpuWindowOperator(config=geom.engine_config(
            EngineConfig(overflow_policy=cfg.overflow_policy)))
        for w in windows:
            op.add_window_assigner(w)
        op.add_aggregation(make_aggregation(agg_name))
        op.set_max_lateness(max(cfg.max_lateness, back + 2 * int(span)))
        return op

    op = mk_op()
    feed = LineRateFeed(
        op, ring=geom.ring_config(), shaper=geom.shaper_config())

    # warmup: compiles sort-split + ingest + watermark kernels
    for i in (0, 1):
        v, t, lo, hi = chunks[i]
        feed.offer_block(v, t)
    warm_wm = chunks[1][3] + 1
    op.process_watermark_async(warm_wm)
    jax.device_get(op._state.n_slices)
    if obs is not None:
        op.set_observability(obs)
        obs.registry.reset_clock()

    next_wm = (warm_wm // cfg.watermark_period_ms + 1) \
        * cfg.watermark_period_ms
    pending = []
    occ_samples = []
    t0 = time.perf_counter()
    for i in range(2, n_chunks):
        v, t, lo, hi = chunks[i]
        feed.offer_block(v, t)
        occ_samples.append((feed.ring.occupancy,
                            feed.ring.occupancy + feed.accumulator.held))
        while hi - back - 2 * int(span) >= next_wm:
            out = op.process_watermark_async(next_wm)
            if out[3] is not None:
                pending.append((out[0].shape[0], out[3]))
            next_wm += cfg.watermark_period_ms
    feed.drain()
    out = op.process_watermark_async(next_wm)
    if out[3] is not None:
        pending.append((out[0].shape[0], out[3]))
    emitted = 0
    fetched = jax.device_get([c for _, c in pending])
    for (T, _), cnt in zip(pending, fetched):
        emitted += int((cnt[:T] > 0).sum())
    op.check_overflow()                 # shaper + ring drain-point checks
    wall = time.perf_counter() - t0
    n_tuples = (n_chunks - 2) * B
    if obs is not None:
        obs.registry.stop_clock()
        op.set_observability(None)

    # the r5 comparator: per-record offer trickle on the same stream
    # class (a prefix, rate-extrapolated — the loop is O(records) Python)
    op2 = mk_op()
    from ..shaper import StreamShaper

    StreamShaper(op2, geom.shaper_config())
    base_n = int(min(n_tuples, 200_000))
    t0 = time.perf_counter()
    fed = 0
    wm2 = next_wm
    for i in range(2, n_chunks):
        v, t, lo, hi = chunks[i]
        take = min(B, base_n - fed)
        for j in range(take):
            op2.process_element(float(v[j]), int(t[j]))
        fed += take
        if fed >= base_n:
            break
    op2.process_watermark_async(wm2 + 10 * int(span))
    jax.device_get(op2._state.n_slices)
    base_wall = time.perf_counter() - t0
    op2.check_overflow()
    baseline_tps = fed / base_wall if base_wall > 0 else 0.0

    res = BenchResult(
        name=cfg.name, windows=window_spec, aggregation=agg_name,
        tuples_per_sec=n_tuples / wall,
        p99_emit_ms=0.0,
        n_windows_emitted=emitted, n_tuples=n_tuples, wall_s=wall)
    res.emit_ms_device = wall / max(1, len(pending)) * 1e3
    # ring occupancy is the RING alone (cross-checkable against the
    # ring_bounded invariant / ingest_ring_occupancy gauge);
    # host_staged adds the accumulator's held band — the full
    # host-side staging footprint between the source and the device
    occ = np.asarray(occ_samples if occ_samples else [(0, 0)])
    res.ring_occupancy_p50 = float(np.percentile(occ[:, 0], 50))
    res.ring_occupancy_p90 = float(np.percentile(occ[:, 0], 90))
    res.ring_occupancy_p99 = float(np.percentile(occ[:, 0], 99))
    res.host_staged_p50 = float(np.percentile(occ[:, 1], 50))
    res.host_staged_p90 = float(np.percentile(occ[:, 1], 90))
    res.host_staged_p99 = float(np.percentile(occ[:, 1], 99))
    res.prefetch_overlap_ratio = feed.feeder.overlap_ratio()
    snap = feed.snapshot()
    res.ring_full_events = int(snap["full_events"])
    res.ring_shed = int(snap["shed"])
    res.ring_blocks = int(snap["blocks"])
    res.baseline_per_record_tps = baseline_tps
    res.speedup_vs_per_record = (res.tuples_per_sec
                                 / max(baseline_tps, 1e-9))
    res.shaper_back_ms = back
    res.platform = jax.devices()[0].platform
    res.tpu_floor_note = ("the >= 50 M t/s ROADMAP floor is a TPU-box "
                          "certification; this cell records "
                          f"platform={res.platform}")
    finalize_observability(res, obs, [], emitted, n_tuples=n_tuples)
    return res


def measure_delivery_overhead(seed: int = 0, n_records: int = 3000,
                              pairs: int = 9) -> float:
    """Interleaved A/B of the exactly-once ledger on the iterable keyed
    loop (ISSUE 8 acceptance: <= 2% median on CPU): per-pair bare-loop
    vs TransactionalSink(exactly_once) wall time, returns the median
    overhead in PERCENT (negative = within noise)."""
    from ..connectors.base import (AscendingWatermarks,
                                   KeyedScottyWindowOperator)
    from ..connectors.iterable import run_keyed
    from ..core.aggregates import SumAggregation
    from ..core.windows import TumblingWindow, WindowMeasure
    from ..delivery import EXACTLY_ONCE, TransactionalSink

    rng = np.random.default_rng(seed)
    keys = rng.integers(0, 8, size=n_records)
    vals = rng.standard_normal(n_records)
    recs = [(f"k{keys[i]}", float(vals[i]), i * 10)
            for i in range(n_records)]

    def once(with_sink: bool) -> float:
        op = KeyedScottyWindowOperator(
            windows=[TumblingWindow(WindowMeasure.Time, 100)],
            aggregations=[SumAggregation()],
            watermark_policy=AscendingWatermarks())
        sink = TransactionalSink(mode=EXACTLY_ONCE) if with_sink else None
        t0 = time.perf_counter()
        for _ in run_keyed(iter(recs), op, sink=sink):
            pass
        return time.perf_counter() - t0

    once(False), once(True)                 # warm both paths
    a_times, b_times = [], []
    for _ in range(pairs):
        a_times.append(once(False))
        b_times.append(once(True))
    a_times.sort()
    b_times.sort()
    return 100.0 * (b_times[len(b_times) // 2]
                    / a_times[len(a_times) // 2] - 1.0)


def measure_latency_overhead(seed: int = 0, throughput: int = 4_000_000,
                             intervals: int = 6, pairs: int = 16) -> float:
    """Interleaved A/B of the SAMPLING-OFF latency tracer on the
    aligned pipeline (ISSUE 14 acceptance: ≤ 2% median): per-pair
    obs-without-tracer vs obs-with-``sample_every=0`` tracer wall time
    over the same timed intervals — isolating exactly what every
    steady-state interval pays for the seams (one attribute check per
    hook, one declined ``open()`` per interval). Returns the median
    overhead in PERCENT (negative = within noise)."""
    from ..core.aggregates import SumAggregation
    from ..core.windows import SlidingWindow, WindowMeasure
    from ..engine import EngineConfig
    from ..engine.pipeline import AlignedStreamPipeline

    windows = [SlidingWindow(WindowMeasure.Time, 8000, 1000)]

    def build(with_tracer: bool):
        p = AlignedStreamPipeline(
            windows, [SumAggregation()],
            config=EngineConfig(capacity=2048, annex_capacity=8,
                                min_trigger_pad=32),
            throughput=_round_throughput(
                throughput, AlignedStreamPipeline.slice_grid(windows,
                                                             1000)),
            wm_period_ms=1000, max_lateness=0, seed=seed, gc_every=32)
        obs = _obs.Observability()
        if with_tracer:
            obs.attach_latency(sample_every=0)
        p.reset()
        p.run(2, collect=False)
        p.sync()
        p.set_observability(obs)
        return p

    pa, pb = build(False), build(True)

    def once(p) -> float:
        t0 = time.perf_counter()
        p.run(intervals, collect=False)
        p.sync()
        return time.perf_counter() - t0

    once(pa), once(pb)                       # warm both step paths
    a_times, b_times = [], []
    for i in range(pairs):
        # alternate within-pair order so slow drift (thermal, other
        # tenants on a shared core) cancels instead of biasing one arm
        if i % 2 == 0:
            a_times.append(once(pa))
            b_times.append(once(pb))
        else:
            b_times.append(once(pb))
            a_times.append(once(pa))
    pa.check_overflow()
    pb.check_overflow()
    a_times.sort()
    b_times.sort()
    return 100.0 * (b_times[len(b_times) // 2]
                    / a_times[len(a_times) // 2] - 1.0)


def measure_workload_overhead(seed: int = 0, throughput: int = 4_000_000,
                              intervals: int = 6, pairs: int = 16) -> float:
    """Interleaved A/B of the ISSUE 16 sensor plane on the aligned
    pipeline (acceptance: ≤ 2% median): per-pair bare-obs vs
    obs-with-WorkloadMonitor+DriftDetector wall time over the same timed
    intervals. The monitor samples at the pipeline's existing
    ``flight_sync`` drain point (one per ``sync``) with an audit interval
    short enough that EVERY sample closes an audit window — so the B arm
    pays the full fold (counter snapshot, feature derivation, gauge
    writes, drift judging) each sync, the worst case a production
    ``audit_interval_s`` would amortize. Returns the median overhead in
    PERCENT (negative = within noise)."""
    from ..core.aggregates import SumAggregation
    from ..core.windows import SlidingWindow, WindowMeasure
    from ..engine import EngineConfig
    from ..engine.pipeline import AlignedStreamPipeline
    from ..obs.drift import DriftDetector

    windows = [SlidingWindow(WindowMeasure.Time, 8000, 1000)]

    def build(with_monitor: bool):
        p = AlignedStreamPipeline(
            windows, [SumAggregation()],
            config=EngineConfig(capacity=2048, annex_capacity=8,
                                min_trigger_pad=32),
            throughput=_round_throughput(
                throughput, AlignedStreamPipeline.slice_grid(windows,
                                                             1000)),
            wm_period_ms=1000, max_lateness=0, seed=seed, gc_every=32)
        obs = _obs.Observability()
        if with_monitor:
            mon = obs.attach_workload(audit_interval_s=1e-9)
            mon.attach_detector(DriftDetector())
        p.reset()
        p.run(2, collect=False)
        p.sync()
        p.set_observability(obs)
        return p

    pa, pb = build(False), build(True)

    def once(p) -> float:
        t0 = time.perf_counter()
        p.run(intervals, collect=False)
        p.sync()
        return time.perf_counter() - t0

    once(pa), once(pb)                       # warm both step paths
    a_times, b_times = [], []
    for i in range(pairs):
        # alternate within-pair order so slow drift (thermal, other
        # tenants on a shared core) cancels instead of biasing one arm
        if i % 2 == 0:
            a_times.append(once(pa))
            b_times.append(once(pb))
        else:
            b_times.append(once(pb))
            a_times.append(once(pa))
    pa.check_overflow()
    pb.check_overflow()
    a_times.sort()
    b_times.sort()
    return 100.0 * (b_times[len(b_times) // 2]
                    / a_times[len(a_times) // 2] - 1.0)


def run_workload_drift_cell(cfg: BenchmarkConfig, window_spec: str,
                            agg_name: str,
                            obs: Optional[_obs.Observability] = None
                            ) -> BenchResult:
    """Workload-drift cell (ISSUE 16 acceptance): a seeded 3-phase
    shifting stream — rate ×8, then a lateness storm, then a key-skew
    flip — through the host keyed connector operator with the
    WorkloadMonitor on a ManualClock (one audit window per simulated
    second, sampled only at the per-watermark ``flight_sync`` drain
    point). The attached self-baselining :class:`DriftDetector` must
    fire on EVERY phase transition within a bounded number of audit
    windows, and a second arm replaying the stable phase for the full
    duration must fire ZERO events (the false-positive bound). A third
    arm records the interleaved sensor-plane A/B overhead on the
    aligned pipeline (:func:`measure_workload_overhead`, ≤ 2% median).

    Recorded per cell: the phase schedule with per-transition detect
    lags (``drift_detect_lags``, in audit windows), ``drift_events`` /
    ``drift_fired`` (which features fired when),
    ``drift_false_positives`` (stable arm), and
    ``workload_overhead_pct_median`` — plus the closing fingerprint in
    the ``metrics`` section like every other cell."""
    from ..connectors.base import (AscendingWatermarks,
                                   KeyedScottyWindowOperator)
    from ..obs.drift import DriftDetector
    from ..obs.workload import WorkloadMonitor
    from ..resilience.clock import ManualClock

    windows = parse_window_spec(window_spec, seed=cfg.seed)
    P = cfg.watermark_period_ms            # 1 simulated second per audit
    r0 = max(256, int(cfg.throughput))     # stable tuples per sim second
    n_keys = max(8, cfg.n_keys or 64)
    # phase schedule in simulated seconds == audit windows (audit k folds
    # second k; second 0 arms the monitor's first window)
    phases = [("stable", 12, None),        # baseline + stable arm
              ("rate_x8", 8, "arrival_rate_per_s"),
              ("late_storm", 8, "late_share"),
              ("key_skew", 8, "key_top_share")]
    total_s = sum(n for _, n, _ in phases)
    rng = np.random.default_rng(cfg.seed)

    def second_stream(phase: str, s: int, wm: int):
        """(keys, values, ts) for simulated second ``s`` under ``phase``
        — ts ascending within the second except the lateness storm's
        injected stragglers (below the current watermark but inside
        cfg.max_lateness, so the operator repairs rather than drops)."""
        n = r0 * 8 if phase == "rate_x8" else r0
        if phase == "key_skew":
            # 80% of the load lands on one hot key, rest uniform
            hot = rng.random(n) < 0.80
            keys = rng.integers(0, n_keys, size=n)
            keys[hot] = 0
        else:
            keys = rng.integers(0, n_keys, size=n)
        ts = np.sort(rng.integers(0, P, size=n)) + np.int64(s * P)
        if phase == "late_storm" and wm > 0:
            # ~30% arrive below the watermark by up to half max_lateness
            late = rng.random(n) < 0.30
            age = rng.integers(1, max(2, cfg.max_lateness // 2),
                               size=n)
            ts = np.where(late, np.maximum(0, wm - age), ts)
        vals = (rng.random(n) * 100).astype(np.float64)
        return keys, vals, ts

    def run_arm(schedule):
        """One full stream under ``schedule`` ([(phase, seconds)]);
        returns (detector, monitor, obs, emitted, n_tuples)."""
        arm_obs = _obs.Observability()
        clock = ManualClock()
        mon = arm_obs.attach_workload(
            WorkloadMonitor(clock=clock, audit_interval_s=1.0,
                            top_k=max(1, n_keys // 8)))
        det = DriftDetector()              # self-baseline, confirm=2
        mon.attach_detector(det)
        op = KeyedScottyWindowOperator(
            windows=list(windows),
            aggregations=[make_aggregation(agg_name)],
            allowed_lateness=cfg.max_lateness,
            watermark_policy=AscendingWatermarks(),
            obs=arm_obs)
        emitted = 0
        n_tuples = 0
        s = 0
        wm = 0
        for phase, n_seconds in schedule:
            for _ in range(n_seconds):
                keys, vals, ts = second_stream(phase, s, wm)
                for j in range(len(keys)):
                    for _key, w in op.process_element(
                            int(keys[j]), float(vals[j]), int(ts[j])):
                        emitted += 1
                n_tuples += len(keys)
                wm = (s + 1) * P
                for _key, w in op.process_watermark(wm):
                    emitted += 1
                # the keyed/mesh skew feed (the mesh engine's hot-key
                # drain read does the same fold; host cells feed their
                # own per-second histogram)
                mon.observe_key_loads(np.bincount(keys,
                                                  minlength=n_keys))
                clock.advance(1.0)
                arm_obs.flight_sync(watermark=float(wm))
                s += 1
        return det, mon, arm_obs, emitted, n_tuples

    # -- drift arm: the 3-phase shifting stream --------------------------
    t0 = time.perf_counter()
    schedule = [(ph, n) for ph, n, _ in phases]
    det, mon, arm_obs, emitted, n_tuples = run_arm(schedule)
    wall = time.perf_counter() - t0
    fired_by_feature = {f["feature"]: f["audit"] for f in det.fired}
    transitions = []
    lags = {}
    all_detected = True
    boundary = 0
    for phase, n_seconds, expect in phases:
        start_audit = boundary + (0 if boundary else 1)
        boundary += n_seconds
        if expect is None:
            continue
        fired_at = fired_by_feature.get(expect)
        lag = (fired_at - start_audit + 1) if fired_at is not None \
            else None
        detected = lag is not None and 0 < lag <= 4
        all_detected = all_detected and detected
        lags[phase] = lag
        transitions.append({"phase": phase, "expect": expect,
                            "transition_audit": start_audit,
                            "fired_audit": fired_at, "lag": lag,
                            "detected": detected})

    # -- stable arm: same duration, phase A only — zero events -----------
    det_stable, _, _, _, _ = run_arm([("stable", total_s)])

    # -- sensor-plane overhead arm (aligned pipeline A/B) ----------------
    overhead = round(measure_workload_overhead(seed=cfg.seed), 2)

    res = BenchResult(
        name=cfg.name, windows=window_spec, aggregation=agg_name,
        tuples_per_sec=n_tuples / wall if wall > 0 else 0.0,
        p99_emit_ms=0.0, n_windows_emitted=emitted,
        n_tuples=n_tuples, wall_s=round(wall, 3))
    res.workload_phases = [{"phase": ph, "seconds": n,
                            "expect": expect}
                           for ph, n, expect in phases]
    res.drift_events = det.events
    res.drift_fired = [{"feature": f["feature"], "audit": f["audit"],
                        "reference": round(f["reference"], 6),
                        "live": round(f["live"], 6)}
                       for f in det.fired]
    res.drift_transitions = transitions
    res.drift_detect_lags = lags
    res.drift_all_detected = bool(all_detected and transitions)
    res.drift_false_positives = det_stable.events
    res.workload_overhead_pct_median = overhead
    finalize_observability(res, arm_obs, [], 0)
    return res


def measure_attribution_overhead(seed: int = 0,
                                 throughput: int = 4_000_000,
                                 intervals: int = 4, pairs: int = 25,
                                 n_tenants: int = 4) -> float:
    """Interleaved A/B of the ISSUE 19 accounting plane in STEADY STATE
    (acceptance: ≤ 2% median): both arms drive the same served query
    grid and fetch every interval's trigger rows at the drain point —
    the work a serving loop does regardless; the B arm additionally
    folds the rows into the :class:`TenantAttribution` ledger and
    evaluates the :class:`SloPolicy` at ``flight_sync``. Returns the
    median overhead in PERCENT (negative = within noise)."""
    from ..core.aggregates import SumAggregation
    from ..core.windows import TumblingWindow, WindowMeasure
    from ..engine import EngineConfig
    from ..engine.pipeline import AlignedStreamPipeline
    from ..resilience.clock import ManualClock
    from ..serving import QueryAdmission, QueryService
    from ..serving.cache import pad_pow2

    T = WindowMeasure.Time
    P = 1000
    qwin = TumblingWindow(T, P)
    g = AlignedStreamPipeline.slice_grid([qwin], P)
    tp = _round_throughput(throughput, g)
    econf = EngineConfig(capacity=2048, annex_capacity=8,
                         min_trigger_pad=32)

    def build(with_attr: bool):
        svc = QueryService(
            [SumAggregation()], slice_grid=g, max_window_size=4 * P,
            throughput=tp, wm_period_ms=P, max_lateness=0, seed=seed,
            config=econf,
            admission=QueryAdmission(max_queries=pad_pow2(n_tenants, 8)),
            min_slots=pad_pow2(n_tenants, 8),
            min_trigger_lanes=pad_pow2(4, 8))
        for t in range(n_tenants):
            svc.register(qwin, tenant=f"t{t}")
        svc.run(6, collect=False)
        svc.sync()
        svc.mark_warm()
        o = _obs.Observability()
        clock = ManualClock()
        if with_attr:
            o.attach_attribution(clock=clock)
            o.attach_slo(delivered_share=0.9, clock=clock)
        svc.set_observability(o)
        return svc, o, clock

    a, b = build(False), build(True)

    def once(arm) -> float:
        svc, o, clock = arm
        t0 = time.perf_counter()
        out = svc.run(1, collect=True)[0]
        rows = svc.results_by_slot(out)
        if getattr(o, "attribution", None) is not None:
            svc.account_emissions(rows)
        clock.advance(1.0)
        o.flight_sync(watermark=float(svc.pipeline._interval * P))
        svc.sync()
        return time.perf_counter() - t0

    for _ in range(3):                    # warm both drain paths
        once(a), once(b)

    def sampled_median() -> float:
        a_times, b_times = [], []
        # ONE interval per timing sample, arms interleaved
        # back-to-back with alternating order: ambient drift (another
        # tenant on the core, a GC burst) lands on both arms'
        # distributions instead of biasing one, and the medians shrug
        # off the stall outliers that sink a blocked design
        for i in range(intervals * pairs):
            if i % 2 == 0:
                a_times.append(once(a))
                b_times.append(once(b))
            else:
                b_times.append(once(b))
                a_times.append(once(a))
        a_times.sort()
        b_times.sort()
        return 100.0 * (b_times[len(b_times) // 2]
                        / a_times[len(a_times) // 2] - 1.0)

    # median-of-3 rounds: one round's median still wobbles with
    # ambient load on a shared host; the middle of three rounds is
    # what the acceptance gate records
    rounds = sorted(sampled_median() for _ in range(3))
    a[0].check_overflow()
    b[0].check_overflow()
    return rounds[1]


def run_slo_churn_cell(cfg: BenchmarkConfig, window_spec: str,
                       agg_name: str,
                       obs: Optional[_obs.Observability] = None
                       ) -> BenchResult:
    """SLO-churn cell (ISSUE 19 acceptance; config
    ``bench/configurations/slo_churn.json``): ``sloTenants`` tenants
    share one served grid, each holding one tumbling query under a
    ``per_tenant_quota=1`` admission policy. The seeded HOT tenant
    misbehaves two ways every interval: it hammers ``sloHotFactor − 1``
    extra registrations past its quota (each rejection is
    tenant-attributed exactly), and its offered tuple stream —
    ``sloHotFactor ×`` a fair share — drives the PR 18
    :class:`DegradationLadder` past its audit budget so the sampled
    rung sheds tuples, apportioned to tenants by their OVERAGE above
    the fair share (only the hot tenant has any, with
    ``sloHotFactor ≥ 3``).

    Acceptance recorded on the row: the attached :class:`SloPolicy`
    (``delivered_share`` objective on a ManualClock, one tick per
    interval at the ``flight_sync`` drain point) must latch a burn for
    EXACTLY the hot tenant — ``slo_violation_detected`` with the
    violating tenant/objective/owning stage named,
    ``slo_false_positives == 0`` for every well-behaved tenant — and
    ``slo_conservation_ok`` asserts the ledger equals the engine
    counters (rejected == serving_rejected, shed == the ladder's exact
    count, windows == independently tallied tenant rows). The
    interleaved accounting-plane A/B
    (:func:`measure_attribution_overhead`, ≤ 2% median) rides along as
    ``attribution_overhead_pct_median``."""
    from ..autotune import DegradationLadder
    from ..core.windows import TumblingWindow, WindowMeasure
    from ..engine import EngineConfig
    from ..engine.pipeline import AlignedStreamPipeline
    from ..resilience.clock import ManualClock
    from ..serving import QueryAdmission, QueryService
    from ..serving.cache import pad_pow2

    windows = parse_window_spec(window_spec, seed=cfg.seed)
    P = cfg.watermark_period_ms
    g = AlignedStreamPipeline.slice_grid(windows, P)
    tp = _round_throughput(cfg.throughput, g)
    max_size = max([4 * P] + [int(w.size) for w in windows])
    econf = EngineConfig(capacity=cfg.capacity, annex_capacity=8,
                         min_trigger_pad=32,
                         overflow_policy=cfg.overflow_policy)
    N = max(2, int(cfg.slo_tenants))
    hot = "t0"
    tenants = [f"t{i}" for i in range(N)]
    qwin = TumblingWindow(WindowMeasure.Time, P)

    svc = QueryService(
        [make_aggregation(agg_name)], slice_grid=g,
        max_window_size=max_size, throughput=tp, wm_period_ms=P,
        max_lateness=cfg.max_lateness, seed=cfg.seed, config=econf,
        admission=QueryAdmission(max_queries=pad_pow2(N + 2, 8),
                                 per_tenant_quota=1, on_reject="shed"),
        min_slots=pad_pow2(N + 2, 8),
        min_trigger_lanes=pad_pow2(4, 8))
    handles = {t: svc.register(qwin, tenant=t) for t in tenants}
    tenant_slots = {h.slot for h in handles.values()}
    warmup = max_size // P + 2
    svc.run(warmup, collect=False)
    svc.sync()
    svc.mark_warm()

    cell_obs = obs if obs is not None else _obs.Observability()
    clock = ManualClock()
    attribution = cell_obs.attach_attribution(clock=clock)
    slo = cell_obs.attach_slo(
        delivered_share=cfg.slo_delivered_share,
        burn_threshold=cfg.slo_burn_threshold, clock=clock)
    svc.set_observability(cell_obs)
    cell_obs.registry.reset_clock()
    ladder = DegradationLadder(sample_mod=4, relax_after=2, obs=cell_obs)

    # the offered sideband the ladder degrades: the hot tenant offers
    # sloHotFactor x a fair per-tenant share, so the per-audit budget
    # (total fair load + one share of headroom) is exceeded exactly
    # because of the hot tenant's overage
    base = 64
    offered = {t: base * cfg.slo_hot_factor if t == hot else base
               for t in tenants}
    total_offered = sum(offered.values())
    budget = float(base * (N + 1))
    fair = total_offered / float(N)
    overage = {t: max(0.0, n - fair) for t, n in offered.items()}

    n_timed = max(12, cfg.runtime_s)
    lats = []
    tenant_rows = 0
    t0 = time.perf_counter()
    for _ in range(n_timed):
        t1 = time.perf_counter()
        # hot tenant hammers past its quota: exact rejected attribution
        for _ in range(max(0, cfg.slo_hot_factor - 1)):
            svc.register(qwin, tenant=hot)
        out = svc.run(1, collect=True)[0]
        rows = svc.results_by_slot(out)
        tenant_rows += sum(len(r) for s, r in rows.items()
                           if s in tenant_slots)
        svc.account_emissions(rows)
        wm = float(svc.pipeline._interval * P)
        # offered sideband under the ladder; sheds carry no tenant
        # identity, so the ledger apportions them by overage weight
        shed_before = ladder.shed
        ladder.admit(np.full(total_offered, int(wm), np.int64), int(wm))
        ladder.audit(budget)
        if ladder.shed > shed_before:
            attribution.apportion_count(
                "shed", ladder.shed - shed_before, overage)
        clock.advance(1.0)
        cell_obs.flight_sync(watermark=wm)
        lats.append((time.perf_counter() - t1) * 1e3)
    wall = time.perf_counter() - t0
    svc.sync()
    svc.check_overflow()
    cell_obs.registry.stop_clock()
    n_tuples = n_timed * svc.pipeline.tuples_per_interval

    violations = slo.violations()
    hits = [v for v in violations if v["tenant"] == hot]
    false_pos = [v for v in violations if v["tenant"] != hot]
    totals = attribution.totals()
    stats = svc.stats()
    conserved = (
        attribution.conservation_ok()
        and totals["rejected"] == int(stats.get("serving_rejected", 0))
        and totals["shed"] == int(ladder.shed)
        and totals["windows"] == int(tenant_rows))

    overhead = round(measure_attribution_overhead(seed=cfg.seed), 2)

    res = BenchResult(
        name=cfg.name, windows=window_spec, aggregation=agg_name,
        tuples_per_sec=n_tuples / wall if wall > 0 else 0.0,
        p99_emit_ms=float(np.percentile(lats, 99)) if lats else 0.0,
        n_windows_emitted=tenant_rows, n_tuples=n_tuples,
        wall_s=round(wall, 3))
    res.n_lat_samples = len(lats)
    res.p50_emit_ms = float(np.percentile(lats, 50)) if lats else 0.0
    res.slo_tenants = N
    res.slo_hot_tenant = hot
    res.slo_violation_detected = bool(hits)
    if hits:
        res.slo_violating_tenant = hits[0]["tenant"]
        res.slo_violating_objective = hits[0]["objective"]
        res.slo_owning_stage = hits[0]["owning_stage"]
    res.slo_false_positives = len(false_pos)
    res.slo_burn_events_total = int(
        cell_obs.counter(_obs.SLO_BURN_EVENTS).value)
    res.slo_conservation_ok = bool(conserved)
    res.serving_retraces_after_warmup = int(svc.retraces_since_warm)
    res.serving_rejected = int(stats.get("serving_rejected", 0))
    res.degrade_shed_tuples = int(ladder.shed)
    res.attribution_overhead_pct_median = overhead
    finalize_observability(res, cell_obs, lats, tenant_rows,
                           n_tuples=n_tuples)
    return res


def measure_autotune_overhead(seed: int = 0, throughput: int = 4_000_000,
                              intervals: int = 6, pairs: int = 16) -> float:
    """Interleaved A/B of the ISSUE 18 actuation plane in STEADY STATE
    (acceptance: ≤ 2% median): both arms run the full PR 16 sensor
    plane (monitor + detector, audit every sync); the B arm additionally
    folds the :class:`GeometryController` and :class:`DegradationLadder`
    once per interval — the controller with every candidate admissible
    and no drift, so every ``observe`` takes the steady-state
    short-circuit and decides NOTHING (asserted), which is exactly the
    cost a production loop pays on the vast majority of audits. Returns
    the median overhead in PERCENT (negative = within noise)."""
    from ..autotune import (ControllerPolicy, DegradationLadder,
                            EngineGeometry, GeometryController)
    from ..core.aggregates import SumAggregation
    from ..core.windows import SlidingWindow, WindowMeasure
    from ..engine import EngineConfig
    from ..engine.pipeline import AlignedStreamPipeline
    from ..obs.drift import DriftDetector

    windows = [SlidingWindow(WindowMeasure.Time, 8000, 1000)]

    def build(with_actuation: bool):
        p = AlignedStreamPipeline(
            windows, [SumAggregation()],
            config=EngineConfig(capacity=2048, annex_capacity=8,
                                min_trigger_pad=32),
            throughput=_round_throughput(
                throughput, AlignedStreamPipeline.slice_grid(windows,
                                                             1000)),
            wm_period_ms=1000, max_lateness=0, seed=seed, gc_every=32)
        obs = _obs.Observability()
        mon = obs.attach_workload(audit_interval_s=1e-9)
        mon.attach_detector(DriftDetector())
        p.reset()
        p.run(2, collect=False)
        p.sync()
        p.set_observability(obs)
        ctrl = ladder = None
        if with_actuation:
            base = EngineGeometry.from_pipeline(p)
            ctrl = GeometryController(
                {"base": base,
                 "alt": base.replace(batch_size=base.batch_size * 2)},
                lambda g, f: 1e9, current="base",
                policy=ControllerPolicy(confirm=2, cooldown=2,
                                        drift_window=3))
            ladder = DegradationLadder(sample_mod=4, relax_after=2,
                                       obs=obs)
        return p, mon, ctrl, ladder, obs

    pa, mon_a, _, _, _ = build(False)
    pb, mon_b, ctrl_b, ladder_b, obs_b = build(True)

    def once(p, mon, ctrl, ladder, obs) -> float:
        t0 = time.perf_counter()
        for _ in range(intervals):
            p.run(1, collect=False)
            if ctrl is not None:
                ladder.audit(budget=float("inf"))
                ctrl.observe(mon.features(), drifted=False, obs=obs)
        p.sync()
        return time.perf_counter() - t0

    def once_a() -> float:
        return once(pa, mon_a, None, None, None)

    def once_b() -> float:
        return once(pb, mon_b, ctrl_b, ladder_b, obs_b)

    once_a(), once_b()                       # warm both step paths
    a_times, b_times = [], []
    for i in range(pairs):
        # alternate within-pair order so slow drift (thermal, other
        # tenants on a shared core) cancels instead of biasing one arm
        if i % 2 == 0:
            a_times.append(once_a())
            b_times.append(once_b())
        else:
            b_times.append(once_b())
            a_times.append(once_a())
    pa.check_overflow()
    pb.check_overflow()
    assert ctrl_b.decisions == 0, \
        "steady-state overhead arm must decide nothing"
    a_times.sort()
    b_times.sort()
    return 100.0 * (b_times[len(b_times) // 2]
                    / a_times[len(a_times) // 2] - 1.0)


def run_autotune_shift_cell(cfg: BenchmarkConfig, window_spec: str,
                            agg_name: str,
                            obs: Optional[_obs.Observability] = None
                            ) -> BenchResult:
    """Autotune-shift cell (ISSUE 18 acceptance): the CLOSED loop —
    sensor plane (PR 16 WorkloadMonitor + DriftDetector on a
    ManualClock) → :class:`GeometryController` → real
    :func:`apply_geometry` retunes on a live supervised aligned
    pipeline — driven by a seeded 3-phase offered-load stream (stable →
    rate ×8 → lateness storm) and scored as THROUGHPUT UNDER A LATENCY
    SLO: each simulated second a geometry admits at most
    ``min(batch_size·4, late_capacity·8 / late_share)`` tuples inside
    the watermark interval (the PR 16 cost-law shape: the batch span
    bounds the on-time lane, the late lane bounds repair drains), and
    the :class:`DegradationLadder` guards every arm with that same
    budget, so overload degrades in counted rungs instead of falling
    over.

    Arms, all over the IDENTICAL seeded offered stream:

    * **adaptive** — controller on (bounded candidate set small / big /
      late), each decision actuated by a REAL ``apply_geometry`` retune
      (atomic manifest-sealed commit through a Supervisor) on the live
      pipeline vehicle; decisions land in the flight recorder.
    * **small / big / late** — every static candidate, controller off:
      each is mis-sized for at least one phase (small saturates at
      rate ×8, big's late lane collapses in the storm, late gives up
      on-time headroom), which is WHY the cell exists — no static
      geometry wins every phase, the adaptive arm must beat them ALL
      on total SLO-admitted tuples (``autotune_beats_all_statics``).
    * **stable** — the full-duration stable stream with the controller
      ON: zero decisions, zero retunes (the no-thrash contract).
    * **overhead** — :func:`measure_autotune_overhead`, the interleaved
      steady-state controller-on vs controller-off A/B (≤ 2% median).

    The actuation vehicle is a small aligned pipeline (its batch span
    retunes both directions bit-exactly — the twin-guarantee tests own
    that proof); the offered stream and SLO account are host-modeled so
    the cell stays deterministic and CPU-runnable, with ``platform``
    recorded alongside like every other certification cell."""
    import tempfile

    import jax

    from ..autotune import (ControllerPolicy, DegradationLadder,
                            EngineGeometry, GeometryController,
                            apply_geometry)
    from ..core.aggregates import SumAggregation
    from ..core.windows import TumblingWindow, WindowMeasure
    from ..engine import EngineConfig
    from ..engine.pipeline import AlignedStreamPipeline
    from ..obs.drift import DriftDetector
    from ..obs.workload import WorkloadMonitor
    from ..resilience.clock import ManualClock
    from ..resilience.supervisor import Supervisor
    from ..serving.cache import GeometryCache

    P = cfg.watermark_period_ms            # 1 simulated second per audit
    r0 = max(256, int(cfg.throughput))     # stable tuples per sim second
    # phase schedule in simulated seconds == audit windows; each shifted
    # phase is sized so its mis-matched statics pay for longer than the
    # adaptive arm's detect+confirm+relax transient
    phases = [("stable", 12, r0, 0.0),
              ("rate_x8", 8, r0 * 8, 0.0),
              ("late_storm", 12, r0 * 4, 0.5)]
    total_s = sum(n for _, n, _, _ in phases)

    # -- the actuation vehicle: a live supervised aligned pipeline -------
    pipe_windows = [TumblingWindow(WindowMeasure.Time, 50)]

    def factory(config=None):
        return AlignedStreamPipeline(
            pipe_windows, [SumAggregation()],
            config=config if config is not None else EngineConfig(
                capacity=1 << 12, batch_size=1024, annex_capacity=256,
                min_trigger_pad=32),
            throughput=20_000, wm_period_ms=100, max_lateness=100,
            seed=cfg.seed, gc_every=10 ** 9, value_scale=1024.0,
            collect_device_metrics=False)

    p0 = factory()
    p0.reset()
    base = EngineGeometry.from_pipeline(p0)
    # the bounded candidate set: one geometry per workload regime
    candidates = {
        "small": base.replace(late_capacity=256),         # batch 1024
        "big": base.replace(batch_size=8192, late_capacity=32),
        "late": base.replace(batch_size=2048, late_capacity=1024),
    }

    SLA_BATCHES = 4      # batches the step clears inside one interval
    LATE_DRAINS = 8      # late-lane repair drains per interval
    LATE_FLOOR = 1.0 / 64

    def sla_capacity(g: EngineGeometry, feats: dict) -> float:
        late_share = max(float(feats.get("late_share", 0.0)), LATE_FLOOR)
        return min(float(g.batch_size * SLA_BATCHES),
                   g.late_capacity * LATE_DRAINS / late_share)

    def admission(g: EngineGeometry, feats: dict) -> float:
        return sla_capacity(g, feats) \
            - float(feats.get("arrival_rate_per_s", 0.0))

    def second_stream(rng, phase: str, rate: int, late_frac: float,
                      s: int, wm: int):
        """(timestamps, n_late) for simulated second ``s`` — the storm's
        stragglers land below the current watermark but inside
        cfg.max_lateness (repairable, never silently droppable)."""
        ts = np.sort(rng.integers(0, P, size=rate)) + np.int64(s * P)
        n_late = 0
        if late_frac and wm > 0:
            late = rng.random(rate) < late_frac
            age = rng.integers(1, max(2, cfg.max_lateness // 2),
                               size=rate)
            ts = np.where(late, np.maximum(0, np.int64(wm) - age), ts)
            n_late = int(late.sum())
        return ts, n_late

    def run_arm(static_name, schedule, pipeline=None, supervisor=None):
        """One arm over ``schedule``; controller on iff ``static_name``
        is None, real retunes iff a pipeline vehicle is passed."""
        rng = np.random.default_rng(cfg.seed)   # identical offered
        arm_obs = _obs.Observability()          # stream in every arm
        clock = ManualClock()
        mon = arm_obs.attach_workload(
            WorkloadMonitor(clock=clock, audit_interval_s=1.0))
        det = DriftDetector()
        mon.attach_detector(det)
        ladder = DegradationLadder(sample_mod=4, relax_after=2,
                                   obs=arm_obs)
        ctrl = None
        if static_name is None:
            ctrl = GeometryController(
                candidates, admission, current="small",
                policy=ControllerPolicy(confirm=2, cooldown=2,
                                        drift_window=3))
        p = pipeline
        cache = GeometryCache() if p is not None else None
        sla = offered_total = within = transitions = last_rung = 0
        decisions_log = []
        s = 0
        for phase, n_seconds, rate, late_frac in schedule:
            for _ in range(n_seconds):
                wm = s * P
                ts, n_late = second_stream(rng, phase, rate, late_frac,
                                           s, wm)
                n = int(ts.shape[0])
                offered_total += n
                geom = ctrl.geometry if ctrl is not None \
                    else candidates[static_name]
                # the SLO account uses the second's EXACT stream stats
                # (identical across arms); only the controller runs on
                # the monitor's sensed features
                exact = {"arrival_rate_per_s": float(n),
                         "late_share": n_late / float(n)}
                cap = sla_capacity(geom, exact)
                keep = ladder.admit(ts, wm)
                kept = int(np.count_nonzero(keep))
                sla += min(kept, int(cap))
                if kept <= cap:
                    within += 1
                arm_obs.counter("ingest_tuples").inc(n)
                if n_late:
                    arm_obs.counter("late_tuples").inc(n_late)
                if p is not None:
                    p.run(1, collect=False)
                ev0 = det.events
                clock.advance(1.0)
                arm_obs.flight_sync(watermark=float((s + 1) * P))
                rung = ladder.audit(budget=cap)
                if rung != last_rung:
                    transitions += 1
                    last_rung = rung
                if ctrl is not None and mon.features():
                    g = ctrl.observe(mon.features(),
                                     drifted=det.events > ev0,
                                     obs=arm_obs)
                    if g is not None:
                        decisions_log.append({"second": s,
                                              "to": ctrl.current})
                        if p is not None:
                            p = apply_geometry(
                                p, g, factory=factory,
                                supervisor=supervisor,
                                pos=int(p._interval), cache=cache,
                                obs=arm_obs)
                            # detach: the arm's sensor counters model
                            # the OFFERED stream, not the vehicle's
                            p.set_observability(None)
                s += 1
        if p is not None:
            p.sync()
            p.check_overflow()
        assert ladder.conserved, "ladder accounting must be exact"
        return {"obs": arm_obs, "ctrl": ctrl, "ladder": ladder,
                "sla": sla, "offered": offered_total, "within": within,
                "transitions": transitions, "decisions": decisions_log}

    # -- adaptive arm: controller + real retunes on the live vehicle -----
    t0 = time.perf_counter()
    with tempfile.TemporaryDirectory() as ckpt_dir:
        sup = Supervisor(ckpt_dir, checkpoint_every=10 ** 9)
        adaptive = run_arm(None, phases, pipeline=p0, supervisor=sup)
    wall = time.perf_counter() - t0
    a_obs = adaptive["obs"]
    retunes = int(a_obs.counter(_obs.AUTOTUNE_RETUNES).value)
    retraces = int(a_obs.counter(_obs.AUTOTUNE_RETRACES).value)

    # -- every static candidate, controller off --------------------------
    statics = {name: run_arm(name, phases) for name in candidates}

    # -- stable arm: controller on, zero decisions is the contract -------
    stable = run_arm(None, [("stable", total_s, r0, 0.0)])

    # -- steady-state actuation-plane overhead ---------------------------
    overhead = round(measure_autotune_overhead(seed=cfg.seed), 2)

    res = BenchResult(
        name=cfg.name, windows=window_spec, aggregation=agg_name,
        tuples_per_sec=adaptive["offered"] / wall if wall > 0 else 0.0,
        p99_emit_ms=0.0, n_windows_emitted=adaptive["sla"],
        n_tuples=adaptive["offered"], wall_s=round(wall, 3))
    res.autotune_phases = [{"phase": ph, "seconds": n, "rate": rate,
                            "late_frac": lf}
                           for ph, n, rate, lf in phases]
    res.autotune_decisions = adaptive["ctrl"].decisions
    res.autotune_retunes = retunes
    res.autotune_retraces = retraces
    res.autotune_schedule = adaptive["decisions"]
    res.adaptive_admitted = adaptive["sla"]
    res.static_admitted = {name: arm["sla"]
                           for name, arm in statics.items()}
    res.autotune_beats_all_statics = bool(
        adaptive["sla"] > max(arm["sla"] for arm in statics.values()))
    res.stable_decisions = stable["ctrl"].decisions
    res.stable_retunes = int(
        stable["obs"].counter(_obs.AUTOTUNE_RETUNES).value)
    res.degrade_transitions = adaptive["transitions"]
    res.degrade_shed_tuples = adaptive["ladder"].shed
    res.sla_ms = float(P)
    res.sla_met = round(adaptive["within"] / float(total_s), 4)
    res.autotune_overhead_pct_median = overhead
    res.platform = jax.devices()[0].platform
    finalize_observability(res, a_obs, [], 0)
    return res


def _flags_off_ab_overhead(cfg: BenchmarkConfig, windows, agg_name: str,
                           reps: int = 3) -> float:
    """Interleaved flags-off A/B (ISSUE 15 acceptance). Be precise about
    what this can and cannot measure: the flags are TRACE-time, so the
    two arms (default-constructed vs every ISSUE 15 flag pinned at its
    default) build byte-identical executables — the pins already prove
    the device side, and the flag plumbing's host branches run in BOTH
    arms. The recorded median is therefore the interleaved NOISE FLOOR
    of this box at the cell shape: the bound within which any residual
    flags-off host overhead is indistinguishable from zero. A median
    outside the ±2% acceptance band indicates environment instability
    (rerun), not flag overhead — a real regression in the default-off
    path shows up in the pins or the headline throughput gates, which
    is where the zero-impact claim actually rests."""
    import jax  # noqa: F401

    from ..engine import EngineConfig
    from ..engine.pipeline import AlignedStreamPipeline

    g = AlignedStreamPipeline.slice_grid(windows, cfg.watermark_period_ms)
    tp = _round_throughput(cfg.throughput, g)

    def mk(flagged_defaults: bool):
        kw = dict(pallas_sort_split=False, pallas_slice_merge=False,
                  pallas_packed=False, micro_batch=0) \
            if flagged_defaults else {}
        p = AlignedStreamPipeline(
            windows, [make_aggregation(agg_name)],
            config=EngineConfig(capacity=cfg.capacity, annex_capacity=8,
                                min_trigger_pad=32, **kw),
            throughput=tp, wm_period_ms=cfg.watermark_period_ms,
            max_lateness=cfg.max_lateness, seed=cfg.seed, gc_every=32)
        p.reset()
        p.run(1, collect=False)
        p.sync()                                   # compile + warm
        return p

    a, b = mk(False), mk(True)
    diffs = []
    for _ in range(reps):
        t0 = time.perf_counter()
        a.run(1, collect=False)
        a.sync()
        ta = time.perf_counter() - t0
        t0 = time.perf_counter()
        b.run(1, collect=False)
        b.sync()
        tb = time.perf_counter() - t0
        diffs.append((tb - ta) / max(ta, 1e-9) * 100.0)
    a.check_overflow()
    b.check_overflow()
    return float(np.median(diffs))


def run_latency_headline_cell(cfg: BenchmarkConfig, window_spec: str,
                              agg_name: str,
                              obs: Optional[_obs.Observability] = None
                              ) -> BenchResult:
    """Latency-headline cell (ISSUE 14): the full ingest→emission edge
    at the headline window shape with the emission-latency tracer in
    EXACT mode — host records through ``BatchAccumulator.offer_block``
    → ``IngestRing`` → ``DeviceRingFeeder`` prefetch → the batch
    operator, watermarks through the synchronous emit face, every
    delivered window through a ``TransactionalSink`` — so each sampled
    chain carries the complete stage decomposition (arrival →
    ring_enqueue → ring_dequeue → dispatch → eligibility → drain →
    emit → sink). Recorded per cell: ``first_emit_p50/p99_ms``,
    ``latency_stages_ms`` (the stage decomposition),
    ``latency_conservation_ok`` (per-chain stage sums vs end-to-end),
    ``latency_overhead_pct_median`` (the sampling-off interleaved A/B
    arm), and an ``oracle_match`` arm bit-comparing the operator's
    emitted windows against the host simulator on the same stream."""
    import jax

    from ..autotune import EngineGeometry
    from ..delivery import TransactionalSink
    from ..engine import EngineConfig, TpuWindowOperator
    from ..ingest import LineRateFeed
    from ..obs.latency import CONSERVATION_TOL_MS, LatencyTracer

    windows = parse_window_spec(window_spec, seed=cfg.seed)
    B = cfg.batch_size
    n_chunks = int(max(8, cfg.throughput * cfg.runtime_s // B))
    span = max(1.0, cfg.runtime_s * 1000 / n_chunks)
    off0 = max(w.clear_delay() for w in windows)
    rng = np.random.default_rng(cfg.seed)
    n_pools = min(n_chunks, 12)
    pools = []
    for _ in range(n_pools):
        ts = np.sort(rng.integers(0, max(1, int(span)),
                                  size=B)).astype(np.int64)
        vals = (rng.random(B) * 10_000).astype(np.float32)
        pools.append((vals, ts))

    def chunk(i):
        vals, ts = pools[i % n_pools]
        lo = off0 + int(i * span)
        return vals, ts + np.int64(lo), off0 + int((i + 1) * span)

    if obs is None:
        obs = _obs.Observability()
    tracer = obs.attach_latency(
        LatencyTracer(sample_every=1, exact_limit=1 << 30))
    # the measured arm's engine + ring configs derive from one geometry
    # (geometry-discipline); the comparator arms below intentionally run
    # at their OWN single-config shapes
    geom = EngineGeometry(capacity=cfg.capacity, batch_size=B,
                          ring_depth=cfg.ring_depth or 8,
                          ring_block=cfg.ring_block_size or B,
                          pallas_sort_split=cfg.pallas_sort_split,
                          pallas_slice_merge=cfg.pallas_slice_merge)
    op = TpuWindowOperator(config=geom.engine_config(
        EngineConfig(overflow_policy=cfg.overflow_policy)))
    for w in windows:
        op.add_window_assigner(w)
    op.add_aggregation(make_aggregation(agg_name))
    op.set_max_lateness(cfg.max_lateness)
    # obs passed explicitly: the ring/feed stamps must be live from the
    # first offered block (the operator's obs attaches post-warmup)
    feed = LineRateFeed(op, ring=geom.ring_config(), obs=obs)

    delivered = []
    sink = TransactionalSink(deliver=lambda w, e, s: delivered.append(w),
                             obs=obs)

    warm_hi = 0
    for i in (0, 1):
        v, t, warm_hi = chunk(i)
        feed.offer_block(v, t)
    for w_out in op.process_watermark(warm_hi + 1):
        pass                               # warm compile, discard output
    op.set_observability(obs)
    obs.registry.reset_clock()
    # warmup offers pre-stamped through the live feed while the compile
    # ran — the first measured chain must not inherit those
    tracer.reset_pending()

    next_wm = (warm_hi // cfg.watermark_period_ms + 2) \
        * cfg.watermark_period_ms
    chains = []
    _finalize = tracer._finalize

    def spy(chain):
        out = _finalize(chain)
        chains.append(out)
        return out

    tracer._finalize = spy
    emitted = 0
    t0 = time.perf_counter()
    for i in range(2, n_chunks):
        v, t, hi = chunk(i)
        feed.offer_block(v, t)
        while hi >= next_wm:
            outs = op.process_watermark(next_wm)
            for w_out in outs:
                if w_out.has_value() and sink.emit(w_out):
                    emitted += 1
            next_wm += cfg.watermark_period_ms
    feed.drain()
    for w_out in op.process_watermark(next_wm):
        if w_out.has_value() and sink.emit(w_out):
            emitted += 1
    op.check_overflow()                     # folds the parked chain too
    wall = time.perf_counter() - t0
    obs.registry.stop_clock()
    op.set_observability(None)
    tracer._finalize = _finalize
    n_tuples = (n_chunks - 2) * B

    # -- per-chain conservation + first-emit over the EXACT chain set ----
    fe_lats = []
    conserve_ok = True
    worst_gap = 0.0
    for c in chains:
        gap = abs(sum(c["stages"].values()) - c["end_to_end_ms"])
        worst_gap = max(worst_gap, gap)
        if gap > CONSERVATION_TOL_MS:
            conserve_ok = False
        if c["first_emit_ms"] is not None:
            fe_lats.append(c["first_emit_ms"])

    # -- host-simulator oracle arm: a small replica of the stream class --
    # (per-record Python feeding at the headline batch size would cost
    # minutes; the differential claim needs the WINDOW CLASS and the
    # emit path, not the record count)
    from ..simulator import SlicingWindowOperator

    P = cfg.watermark_period_ms
    B_o = 1024
    sim = SlicingWindowOperator()
    for w in windows:
        sim.add_window_assigner(w)
    sim.add_aggregation(make_aggregation(agg_name))
    sim.set_max_lateness(cfg.max_lateness)
    op2 = TpuWindowOperator(config=EngineConfig(
        capacity=cfg.capacity, batch_size=B_o,
        overflow_policy=cfg.overflow_policy))
    for w in windows:
        op2.add_window_assigner(w)
    op2.add_aggregation(make_aggregation(agg_name))
    op2.set_max_lateness(cfg.max_lateness)
    rng_o = np.random.default_rng(cfg.seed + 1)
    span_o = max(1, P // 2)
    n_o = 24                       # 12 watermark intervals of event time
    wm2 = None
    eng_rows, sim_rows = [], []
    for i in range(n_o):
        lo = off0 + i * span_o
        t = np.sort(rng_o.integers(0, span_o, size=B_o)) + np.int64(lo)
        # float32-exact integer values (the chaos-suite discipline):
        # window sums stay far below 2^24, so the engine's f32
        # accumulation and the simulator's float64 agree BIT-exactly
        # in any summation order
        v = rng_o.integers(0, 10, size=B_o).astype(np.float32)
        for j in range(B_o):
            sim.process_element(float(v[j]), int(t[j]))
        op2.process_elements(v, t.astype(np.int64))
        hi = lo + span_o
        if wm2 is None:
            wm2 = (off0 // P + 2) * P
        while i >= 2 and hi >= wm2:
            eng_rows += [(w.start, w.end, tuple(map(float, w.agg_values)))
                         for w in op2.process_watermark(wm2)
                         if w.has_value()]
            sim_rows += [(w.start, w.end, tuple(map(float, w.agg_values)))
                         for w in sim.process_watermark(wm2)
                         if w.has_value()]
            wm2 += P
    op2.check_overflow()
    oracle_match = sorted(eng_rows) == sorted(sim_rows) \
        and len(eng_rows) > 0

    # -- micro-batched streamed-emission arm (ISSUE 15 / ROADMAP 4) ------
    # The fused aligned pipeline at the cell's headline window shape,
    # split into cfg.microBatch (default 8) arrival-paced micro-batches
    # per interval with streamed per-interval fetches
    # (run_streamed(depth=0)): first-emit = flush dispatch -> result
    # fetch, decoupled from the interval's bulk ingest — the number the
    # whole-interval path pinned at ~70.8 ms p99 on this container
    # (BASELINE.md ISSUE 14 note). Recorded alongside: the pinned
    # legacy_anchor comparator arm, and a small host-simulator oracle
    # twin in the float-exact regime (bit-matching).
    from ..engine.pipeline import AlignedStreamPipeline

    M = cfg.micro_batch or 8
    g_mb = AlignedStreamPipeline.slice_grid(windows,
                                            cfg.watermark_period_ms)
    mb_obs = _obs.Observability()
    mb_tracer = mb_obs.attach_latency(
        LatencyTracer(sample_every=1, exact_limit=1 << 30))
    p_mb = AlignedStreamPipeline(
        windows, [make_aggregation(agg_name)],
        config=EngineConfig(capacity=cfg.capacity, annex_capacity=8,
                            min_trigger_pad=32, micro_batch=M,
                            pallas_sort_split=cfg.pallas_sort_split,
                            pallas_slice_merge=cfg.pallas_slice_merge),
        throughput=_round_throughput(cfg.throughput, g_mb),
        wm_period_ms=cfg.watermark_period_ms,
        max_lateness=cfg.max_lateness, seed=cfg.seed, gc_every=32)
    p_mb.micro_pace = True
    p_mb.run_streamed(2, depth=0)            # compile + warm
    p_mb.sync()
    p_mb.set_observability(mb_obs)
    mb_tracer.reset_pending()
    mb_chains = []
    _mb_fin = mb_tracer._finalize

    def _mb_spy(chain):
        out = _mb_fin(chain)
        mb_chains.append(out)
        return out

    mb_tracer._finalize = _mb_spy
    n_mb = 12
    t_mb = time.perf_counter()
    p_mb.run_streamed(n_mb, depth=0)
    mb_wall = time.perf_counter() - t_mb
    p_mb.sync()
    p_mb.check_overflow()
    mb_tracer._finalize = _mb_fin
    mb_fe = [c["first_emit_ms"] for c in mb_chains
             if c["first_emit_ms"] is not None]
    mb_gap = max((abs(sum(c["stages"].values()) - c["end_to_end_ms"])
                  for c in mb_chains), default=0.0)

    # oracle twin: micro-batched streamed pipeline vs the host simulator
    # in the float-exact regime (32 lanes/row, power-of-two value scale
    # — every window sum is exactly representable, so equality is
    # exact). The window is the cell's sliding CLASS scaled to the
    # twin's horizon (the headline 60 s window first triggers at
    # interval 60; a 62-interval float-exact twin would dominate cell
    # wall time for no extra differential power — the headline shape
    # itself is covered by the operator-path oracle arm above).
    from ..core.windows import SlidingWindow as _SW
    from ..core.windows import WindowMeasure as _WM

    mo_match = True
    mo_windows = 0
    P_mo = cfg.watermark_period_ms
    windows_mo = [_SW(_WM.Time, 4 * P_mo, P_mo)]
    p_mo = AlignedStreamPipeline(
        windows_mo, [make_aggregation(agg_name)],
        config=EngineConfig(capacity=cfg.capacity, annex_capacity=8,
                            min_trigger_pad=32, micro_batch=4),
        throughput=32 * 1000 // AlignedStreamPipeline.slice_grid(
            windows_mo, P_mo),
        wm_period_ms=P_mo,
        max_lateness=cfg.max_lateness, seed=cfg.seed + 2, gc_every=10 ** 9,
        value_scale=8.0)
    sim_mo = SlicingWindowOperator()
    for w in windows_mo:
        sim_mo.add_window_assigner(w)
    sim_mo.add_aggregation(make_aggregation(agg_name))
    sim_mo.set_max_lateness(cfg.max_lateness)
    mo_outs = p_mo.run_streamed(8, depth=0)
    for i, out_i in enumerate(mo_outs):
        v_mo, t_mo_arr = p_mo.materialize_interval(i)
        order = np.argsort(t_mo_arr, kind="stable")
        for v, t in zip(v_mo[order], t_mo_arr[order]):
            sim_mo.process_element(float(v), int(t))
        r_sim = {}
        for w in sim_mo.process_watermark(
                (i + 1) * cfg.watermark_period_ms):
            if w.has_value():
                r_sim.setdefault(
                    (w.get_start(), w.get_end()),
                    [float(x) for x in w.get_agg_values()])
        pipe = {(s, e): [float(x) for x in v]
                for (s, e, c, v) in p_mo.lowered_results(out_i)}
        mo_windows += len(pipe)
        if pipe != r_sim:
            mo_match = False
    p_mo.check_overflow()

    res = BenchResult(
        name=cfg.name, windows=window_spec, aggregation=agg_name,
        tuples_per_sec=n_tuples / wall,
        p99_emit_ms=0.0,
        n_windows_emitted=emitted, n_tuples=n_tuples, wall_s=wall)
    # p99_emit_ms carries the DELIVERY number (eligibility -> sink),
    # not end-to-end chain time — a chain's end-to-end includes the
    # idle accumulation between watermarks (the 'eligibility' stage),
    # which is cadence, not emission latency
    for k, v in latency_stats(fe_lats).items():
        setattr(res, k, v)
    first_emit_stats(res, fe_lats)
    # micro-batched streamed-emission arm (fields; see arm above)
    res.microbatch_arms = M
    res.first_emit_microbatch_samples = len(mb_fe)
    if mb_fe:
        arr_mb = np.asarray(mb_fe)
        res.first_emit_microbatch_p50_ms = float(np.percentile(arr_mb, 50))
        res.first_emit_microbatch_p99_ms = float(np.percentile(arr_mb, 99))
    res.microbatch_conservation_ok = bool(mb_gap <= CONSERVATION_TOL_MS)
    res.microbatch_worst_chain_gap_ms = mb_gap
    res.microbatch_tps = n_mb * p_mb.tuples_per_interval / mb_wall
    res.microbatch_oracle_match = bool(mo_match and mo_windows > 0)
    res.microbatch_oracle_windows = mo_windows
    mb_snap = mb_obs.snapshot()
    res.microbatch_flushes = int(mb_snap.get("microbatch_flushes", 0))
    # flags-off interleaved A/B (ISSUE 15 acceptance: <= 2% median —
    # the host-side complement of the byte-identical HLO pins)
    res.flags_off_ab_pct_median = round(
        _flags_off_ab_overhead(cfg, windows, agg_name), 2)
    # the pinned legacy-anchor comparator (ADVICE r5 discipline): the
    # r4-era workload-identical arm recorded next to the micro numbers
    try:
        (res.legacy_anchor_tps,
         res.generator_share_legacy) = _aligned_inprogram_arm(
            cfg, windows, agg_name, legacy=True)
    except NotImplementedError as e:
        res.legacy_anchor_note = f"legacy arm unavailable: {e}"
    snap = obs.snapshot()
    from ..obs.latency import attribute

    attr = attribute(snap)
    res.latency_stages_ms = attr["stages"]
    res.latency_conservation_ok = bool(
        conserve_ok and attr["conservation_ok"])
    res.latency_worst_chain_gap_ms = worst_gap
    res.latency_chains = len(chains)
    res.oracle_match = bool(oracle_match)
    res.oracle_windows = len(eng_rows)
    res.latency_owner_stage = attr.get("owner")
    res.latency_overhead_pct_median = round(
        measure_latency_overhead(seed=cfg.seed), 2)
    res.platform = jax.devices()[0].platform
    res.host_cores = os.cpu_count()
    finalize_observability(res, obs, [], emitted, n_tuples=n_tuples)
    return res


def run_soak_cell(cfg: BenchmarkConfig, window_spec: str, agg_name: str,
                  obs: Optional[_obs.Observability] = None) -> BenchResult:
    """Soak cell (ISSUE 7): run the endurance harness at a configured
    offered load for ``soakSeconds`` of REAL wall time (SystemClock —
    the runner's ``--soak-seconds``/``--offered-rate`` flags size it:
    seconds in CI, hours on the box), seeded chaos mix on, and embed the
    full evidence bundle (audit history, conservation terms, healthz
    probes, findings) in the result row. A soak with findings is an
    ERROR cell — the ``obs diff`` gate also sees
    ``soak_invariant_failures`` appearing."""
    from ..ingest import RingConfig
    from ..soak import ChaosMix, SoakConfig, SoakRunner

    duration = cfg.soak_seconds or 5.0
    rate = cfg.offered_rate or 50_000.0
    window_ms = 1000
    for w in parse_window_spec(window_spec, seed=cfg.seed):
        # the soak target runs a simple tumbling workload; derive its
        # size from the cell's slide (a 60 s window would never close
        # inside a seconds-long CI soak)
        window_ms = int(getattr(w, "slide", None)
                        or getattr(w, "size", 1000))
        break
    scfg = SoakConfig(
        duration_s=float(duration), offered_rate=float(rate),
        chunk_records=max(64, min(4096, int(rate // 20) or 64)),
        audit_every_s=max(1.0, float(duration) / 10.0), seed=cfg.seed,
        chaos=ChaosMix(late_storm_every=13, poison_pct=0.01,
                       flaky_every=37),
        ring=RingConfig(depth=cfg.ring_depth or 8,
                        block_size=cfg.ring_block_size or 1024),
        window_ms=window_ms, allowed_lateness=cfg.max_lateness,
        delivery=cfg.delivery)
    if obs is not None and obs.flight is None:
        obs.flight = _obs.FlightRecorder(capacity=4096)
    runner = SoakRunner(scfg, obs=obs)
    t0 = time.perf_counter()
    report = runner.run()
    wall = time.perf_counter() - t0
    if not report["passed"]:
        raise RuntimeError(
            f"soak failed: {len(report['findings'])} invariant "
            f"finding(s) — first: {report['findings'][0]}")
    counters = report["counters"]
    res = BenchResult(
        name=cfg.name, windows=window_spec, aggregation=agg_name,
        tuples_per_sec=report["seen"] / wall,
        p99_emit_ms=0.0,
        n_windows_emitted=int(counters.get("windows_emitted", 0)),
        n_tuples=report["seen"], wall_s=wall)
    res.soak_passed = report["passed"]
    res.soak_seen = report["seen"]
    res.soak_audits_n = len(report["audits"])
    res.soak_findings = report["findings"]
    res.soak_last_terms = report["audits"][-1]["terms"] \
        if report["audits"] else {}
    res.soak_healthz_unhealthy = sum(
        1 for h in report["healthz"] if h.get("status") != 200)
    res.soak_report = report
    # delivery guarantee (ISSUE 8): the mode, the sink's ledger
    # snapshot, and — in exactly_once mode — the measured interleaved
    # A/B cost of the ledger on the iterable run loop
    res.delivery_mode = cfg.delivery
    if report.get("delivery") is not None:
        res.delivery_snapshot = report["delivery"]
        res.delivery_overhead_pct_median = \
            measure_delivery_overhead(seed=cfg.seed)
    finalize_observability(res, obs, [], res.n_windows_emitted,
                           n_tuples=report["seen"])
    return res


def run_host_fed_cell(cfg: BenchmarkConfig, window_spec: str,
                      agg_name: str,
                      obs: Optional[_obs.Observability] = None
                      ) -> BenchResult:
    """Host-fed cell (SURVEY.md §7 stage 7): tuples originate in HOST
    memory as pre-packed (ts-delta u32, value f32) batches; the timed
    region covers host→device transfer + unpack + ingest + watermarks via
    the double-buffered HostFeed. The raw link bandwidth of the same
    packed layout is measured alongside — the honest comparison is the
    SATURATION RATIO (end-to-end vs raw link), since the engine sustains
    multi-G t/s from device-resident sources and any slower link makes a
    host-fed stream transport-bound (docs/DESIGN.md, BASELINE.md)."""
    import jax

    from ..engine import EngineConfig, TpuWindowOperator
    from ..engine.host_ingest import HostFeed, measure_link

    windows = parse_window_spec(window_spec, seed=cfg.seed)
    B = cfg.batch_size
    n_batches = max(4, cfg.throughput * cfg.runtime_s // B)  # first 2 warm

    # pregenerate + pack OUTSIDE the timed region (the stream's origin is
    # host RAM; generation itself is the load generator's cost, which the
    # reference also excludes from its operator measurements)
    rng = np.random.default_rng(cfg.seed)
    span = cfg.runtime_s * 1000 / n_batches
    packed = []
    for i in range(n_batches):
        lo = int(i * span)
        ts = np.sort(rng.integers(lo, max(lo + 1, int((i + 1) * span)),
                                  size=B)).astype(np.int64)
        vals = rng.random(B).astype(np.float32) * 10_000
        packed.append(HostFeed.pack(vals, ts) + (int(ts[0]), int(ts[-1])))

    op = TpuWindowOperator(config=EngineConfig(
        capacity=cfg.capacity, batch_size=B,
        overflow_policy=cfg.overflow_policy))
    for w in windows:
        op.add_window_assigner(w)
    op.add_aggregation(make_aggregation(agg_name))
    op.set_max_lateness(cfg.max_lateness)
    feed = HostFeed(op)

    # warmup ON THE SAME operator/feed (compiles unpack + ingest +
    # watermark kernels and lands the valid-mask device constant): the
    # first two batches are the warm region; the timed region continues
    # the stream from batch 2 — the same discipline as _run_pipeline_cell
    feed.feed_packed(*packed[0])
    feed.feed_packed(*packed[1])
    warm_wm = packed[1][4] + 1
    op.process_watermark_async(warm_wm)
    jax.device_get(op._state.n_slices)
    if obs is not None:
        # attach AFTER warmup: warmup tuples must not pollute the counters,
        # and the rate denominator restarts at the measured region
        op.set_observability(obs)
        obs.registry.reset_clock()

    # timed region: pure pipelined flow (no syncs — emit latency is
    # sampled in a separate drained phase below, like _run_pipeline_cell)
    next_wm = (warm_wm // cfg.watermark_period_ms + 1) \
        * cfg.watermark_period_ms
    pending = []
    t0 = time.perf_counter()
    for (base, deltas, vals, lo, hi) in packed[2:]:
        feed.feed_packed(base, deltas, vals, lo, hi)
        while hi >= next_wm:
            out = op.process_watermark_async(next_wm)
            if out[3] is not None:
                pending.append((out[0].shape[0], out[3]))
            next_wm += cfg.watermark_period_ms
    out = op.process_watermark_async(next_wm)
    if out[3] is not None:
        pending.append((out[0].shape[0], out[3]))
    emitted = 0
    fetched = jax.device_get([c for _, c in pending])
    for (T, _), cnt in zip(pending, fetched):
        emitted += int((cnt[:T] > 0).sum())
    op.check_overflow()
    wall = time.perf_counter() - t0
    n_tuples = (n_batches - 2) * B
    if obs is not None:
        obs.registry.stop_clock()       # rates cover the timed region only
        op.set_observability(None)      # latency replays are not ingest

    # drained emit-latency samples: one packed batch + watermark each,
    # transfer included (that IS the host-fed delivery path). The first
    # batch is replayed time-shifted past the stream end.
    lats = []
    base0, deltas0, vals0, lo0, hi0 = packed[0]
    span0 = hi0 - lo0
    cursor = next_wm
    t_lat = time.perf_counter()
    for _ in range(LATENCY_SAMPLES_MAX):
        jax.device_get(op._state.n_slices)
        t1 = time.perf_counter()
        feed.feed_packed(np.int64(cursor), deltas0, vals0,
                         cursor, cursor + span0)
        out = op.process_watermark_async(cursor + span0 + 1)
        if out[3] is not None:
            jax.device_get((out[3], out[4]))
        else:
            jax.device_get(op._state.n_slices)
        lats.append((time.perf_counter() - t1) * 1e3)
        cursor += span0 + cfg.watermark_period_ms
        if (len(lats) >= LATENCY_SAMPLES_MIN
                and time.perf_counter() - t_lat > LATENCY_BUDGET_S):
            break

    # raw link measured twice (the tunnel varies ±30% run to run) — the
    # MAX is the least-underestimated ceiling, keeping the saturation
    # ratio ≤ ~1 (an achieved rate above "raw" would just mean the raw
    # probe caught a slow phase; r3 review)
    link_mbps = max(measure_link(B, n_batches=16),
                    measure_link(B, n_batches=16))
    res = BenchResult(
        name=cfg.name, windows=window_spec, aggregation=agg_name,
        tuples_per_sec=n_tuples / wall,
        p99_emit_ms=float(np.percentile(lats, 99)) if lats else 0.0,
        n_windows_emitted=emitted, n_tuples=n_tuples, wall_s=wall)
    # transport context for the artifact (runner.run_config keeps extras)
    res.link_mbps_raw = link_mbps
    res.link_mbps_achieved = n_tuples * feed.bytes_per_tuple / wall / 1e6
    res.link_saturation = res.link_mbps_achieved / max(link_mbps, 1e-9)
    res.n_lat_samples = len(lats)
    res.p50_emit_ms = float(np.percentile(lats, 50))
    finalize_observability(res, obs, lats, emitted)
    return res


def run_keyed_host_fed_cell(cfg: BenchmarkConfig, window_spec: str,
                            agg_name: str,
                            obs: Optional[_obs.Observability] = None
                            ) -> BenchResult:
    """Keyed host-fed cell (VERDICT r3 item 7): (key, value, ts) records
    originate in HOST memory, pack into padded ``[K, Bk]`` rounds
    (``KeyedHostFeed`` — one vectorized argsort per round) and cross the
    real link; the timed region covers transfer + unpack + keyed ingest +
    watermarks, double-buffered. This is the reference benchmark's
    keyBy → operator boundary end to end
    (flinkBenchmark/BenchmarkJob.java:84-102). As with the single-stream
    host-fed cell, the honest score is the SATURATION RATIO against the
    raw link measured on the same byte volume — the tunneled link is
    orders of magnitude below the device-resident ingest rate."""
    import jax

    from ..engine import EngineConfig
    from ..engine.host_ingest import KeyedHostFeed, measure_link
    from ..parallel.keyed import KeyedTpuWindowOperator

    windows = parse_window_spec(window_spec, seed=cfg.seed)
    K, Bk = cfg.n_keys, cfg.batch_size
    N = K * Bk * 3 // 4          # 75% round fill: binomial overflow of a
    #                              uniform key draw is negligible at Bk>=1k
    n_rounds = max(4, int(-(-cfg.throughput * cfg.runtime_s // N)))

    rng = np.random.default_rng(cfg.seed)
    span = cfg.runtime_s * 1000 / n_rounds

    op = KeyedTpuWindowOperator(K, config=EngineConfig(
        capacity=cfg.capacity, batch_size=Bk))
    for w in windows:
        op.add_window_assigner(w)
    op.add_aggregation(make_aggregation(agg_name))
    op.set_max_lateness(cfg.max_lateness)
    feed = KeyedHostFeed(op)

    packed = []
    for i in range(n_rounds):
        lo = int(i * span)
        ts = np.sort(rng.integers(lo, max(lo + 1, int((i + 1) * span)),
                                  size=N)).astype(np.int64)
        keys = rng.integers(0, K, size=N).astype(np.int64)
        vals = (rng.random(N) * 10_000).astype(np.float32)
        packed.append(feed.pack(keys, vals, ts)
                      + (int(ts[0]), int(ts[-1])))

    feed.feed_packed(*packed[0])
    feed.feed_packed(*packed[1])
    warm_wm = packed[1][5] + 1
    op.process_watermark_async(warm_wm)
    jax.device_get(op._state.n_slices)
    if obs is not None:
        obs.registry.reset_clock()      # rates start at the timed region

    next_wm = (warm_wm // cfg.watermark_period_ms + 1) \
        * cfg.watermark_period_ms
    pending = []
    t0 = time.perf_counter()
    for (base, deltas, vb, counts, lo, hi) in packed[2:]:
        feed.feed_packed(base, deltas, vb, counts, lo, hi)
        while hi >= next_wm:
            out = op.process_watermark_async(next_wm)
            if out[3] is not None:
                pending.append((out[0].shape[0], out[2]))
            next_wm += cfg.watermark_period_ms
    out = op.process_watermark_async(next_wm)
    if out[3] is not None:
        pending.append((out[0].shape[0], out[2]))
    fetched = jax.device_get([c for _, c in pending])
    emitted = 0
    for (T, _), cnt in zip(pending, fetched):
        emitted += int((np.asarray(cnt)[:, :T] > 0).sum())
    op.check_overflow()
    wall = time.perf_counter() - t0
    n_tuples = (n_rounds - 2) * N
    if obs is not None:
        obs.registry.stop_clock()       # rates cover the timed region only

    # drained emit-latency samples (transfer included — that IS the
    # keyed host-fed delivery path); first round replayed time-shifted
    lats = []
    base0, deltas0, vb0, counts0, lo0, hi0 = packed[0]
    span0 = hi0 - lo0
    cursor = next_wm
    t_lat = time.perf_counter()
    for _ in range(LATENCY_SAMPLES_MAX):
        jax.device_get(op._state.n_slices)
        t1 = time.perf_counter()
        feed.feed_packed(np.int64(cursor), deltas0, vb0, counts0,
                         int(cursor), int(cursor) + span0)
        out = op.process_watermark_async(cursor + span0 + 1)
        if out[3] is not None:
            jax.device_get(out[2])
        else:
            jax.device_get(op._state.n_slices)
        lats.append((time.perf_counter() - t1) * 1e3)
        cursor += span0 + cfg.watermark_period_ms
        if (len(lats) >= LATENCY_SAMPLES_MIN
                and time.perf_counter() - t_lat > LATENCY_BUDGET_S):
            break

    link_mbps = max(measure_link(K * Bk, n_batches=8),
                    measure_link(K * Bk, n_batches=8))
    res = BenchResult(
        name=cfg.name, windows=window_spec, aggregation=agg_name,
        tuples_per_sec=n_tuples / wall,
        p99_emit_ms=float(np.percentile(lats, 99)) if lats else 0.0,
        n_windows_emitted=emitted, n_tuples=n_tuples, wall_s=wall)
    # the transfer moves the PADDED [K, Bk] rounds — that is the achieved
    # byte rate the saturation ratio must use
    res.link_mbps_raw = link_mbps
    res.link_mbps_achieved = (n_rounds - 2) * K * Bk * 8 / wall / 1e6
    res.link_saturation = res.link_mbps_achieved / max(link_mbps, 1e-9)
    res.n_lat_samples = len(lats)
    res.p50_emit_ms = float(np.percentile(lats, 50)) if lats else 0.0
    finalize_observability(res, obs, lats, emitted, n_tuples=n_tuples)
    return res


def run_keyed_cell(cfg: BenchmarkConfig, window_spec: str,
                   agg_name: str,
                   obs: Optional[_obs.Observability] = None) -> BenchResult:
    """Keyed-throughput cell: ``cfg.n_keys`` independent keyed operators as
    one batched device program (the reference's keyBy scaling model,
    KeyedScottyWindowOperator.java:56-66 — there a HashMap of JVM objects,
    here a [K, ...] slice-buffer batch; SURVEY.md §2.8).

    Preferred execution mode: the fused KeyedAlignedPipeline (one dispatch
    per watermark interval — the round-driven loop below pays ~5-15 ms of
    dispatch overhead per [K, B] round on tunneled devices, which capped
    the r2 artifact at 41 M t/s). The stream is generated ON DEVICE and
    pre-partitioned per key — the same work split as the reference, where
    the host engine's keyBy partitions before Scotty sees the tuples;
    host-side partitioning is measured separately by bench.micro's
    host_pack phase."""
    from ..parallel.keyed import KeyedAlignedPipeline

    windows = parse_window_spec(window_spec, seed=cfg.seed)
    try:
        from ..engine import EngineConfig

        p = KeyedAlignedPipeline(
            windows, [make_aggregation(agg_name)], n_keys=cfg.n_keys,
            config=EngineConfig(capacity=cfg.capacity, annex_capacity=8,
                                min_trigger_pad=32),
            throughput=cfg.throughput, wm_period_ms=cfg.watermark_period_ms,
            max_lateness=cfg.max_lateness, seed=cfg.seed)
        return _run_pipeline_cell(p, cfg, window_spec, agg_name, "keyed",
                                  obs=obs)
    except NotImplementedError:
        pass
    return _run_keyed_rounds_cell(cfg, windows, window_spec, agg_name,
                                  obs=obs)


def _run_keyed_rounds_cell(cfg: BenchmarkConfig, windows, window_spec: str,
                           agg_name: str,
                           obs: Optional[_obs.Observability] = None
                           ) -> BenchResult:
    """Round-driven keyed fallback for specs the fused keyed pipeline
    rejects: device-generated [K, B] rounds through
    KeyedTpuWindowOperator.ingest_device_round (pays per-round dispatch
    overhead — the fused pipeline is preferred)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ..engine import EngineConfig
    from ..parallel import KeyedTpuWindowOperator

    K = cfg.n_keys
    B = max(64, cfg.batch_size // max(1, K))
    econf = EngineConfig(capacity=cfg.capacity, batch_size=B,
                         min_trigger_pad=32)

    op = KeyedTpuWindowOperator(n_keys=K, config=econf)
    for w in windows:
        op.add_window_assigner(w)
    op.add_aggregation(make_aggregation(agg_name))
    op.set_max_lateness(cfg.max_lateness)

    tuples_per_round = K * B
    rounds_per_wm = max(1, cfg.throughput * cfg.watermark_period_ms
                        // 1000 // tuples_per_round)
    span = cfg.watermark_period_ms / rounds_per_wm    # event-ms per round

    @jax.jit
    def gen_round(key, lo):
        u = jax.random.uniform(key, (2, K, B), dtype=jnp.float32)
        gaps = u[0] / jnp.sum(u[0], axis=1, keepdims=True) * span
        ts = (lo + jnp.cumsum(gaps.astype(jnp.float64), axis=1)) \
            .astype(jnp.int64)
        return ts, u[1] * 10_000.0

    valid = jax.device_put(np.ones((K, B), bool))
    root = jax.random.PRNGKey(cfg.seed)

    def feed_interval(i):
        base = i * cfg.watermark_period_ms
        for r in range(rounds_per_wm):
            lo = base + r * span
            ts, vals = gen_round(jax.random.fold_in(root, i * 4096 + r),
                                 jnp.float64(lo))
            op.ingest_device_round(ts, vals, valid,
                                   int(lo), int(lo + span))

    # warmup interval: compile generator + ingest + watermark kernels
    feed_interval(0)
    op.process_watermark_arrays(cfg.watermark_period_ms)
    jax.device_get(op._state.n_slices[0])
    if obs is not None:
        obs.registry.reset_clock()      # rates start at the timed region

    lats: list = []
    emitted = 0
    pending = []
    SAMPLE_EVERY = 4
    t0 = time.perf_counter()
    for i in range(1, cfg.runtime_s + 1):
        feed_interval(i)
        sample = i % SAMPLE_EVERY == 0
        if sample:                      # drained dispatch→host round trip
            jax.device_get(op._state.n_slices[0])
            t1 = time.perf_counter()
        out = op.process_watermark_async((i + 1) * cfg.watermark_period_ms)
        if sample:
            jax.device_get((out[2], out[3]))
            lats.append((time.perf_counter() - t1) * 1e3)
        pending.append(out)
    for out in pending:                 # bundled result drain
        ws, we, cnt, lowered = op.lower_results(*out)
        emitted += int((cnt > 0).sum())
    op.check_overflow()
    wall = time.perf_counter() - t0
    n_tuples = cfg.runtime_s * rounds_per_wm * tuples_per_round
    if obs is not None:
        obs.registry.stop_clock()       # rates cover the timed region only
    res = BenchResult(
        name=cfg.name, windows=window_spec, aggregation=agg_name,
        tuples_per_sec=n_tuples / wall,
        p99_emit_ms=float(np.percentile(lats, 99)) if lats else 0.0,
        n_windows_emitted=emitted, n_tuples=n_tuples, wall_s=wall)
    finalize_observability(res, obs, lats, emitted, n_tuples=n_tuples)
    return res


def run_mesh_keyed_cell(cfg: BenchmarkConfig, window_spec: str,
                        agg_name: str,
                        obs: Optional[_obs.Observability] = None
                        ) -> BenchResult:
    """Mesh-sharded keyed cell (ISSUE 10): ``cfg.n_keys`` logical keys
    partitioned over ``cfg.n_shards`` device shards (0 = every local
    device), stepped under shard_map with donated carries and the
    in-executable psum global fold.

    Beyond the standard throughput/latency discipline the cell records
    the mesh contract:

    * ``scaling_ratio`` — aggregate throughput vs the SAME pipeline
      pinned to 1 shard at equal total load (the keys-as-scale-out-axis
      claim; on a multi-chip TPU mesh this is the near-linear number,
      on a virtual CPU mesh it is bounded by host cores —
      ``host_cores`` rides alongside so readers can tell);
    * ``oracle_match`` — sampled keys' lowered results bit-match between
      the sharded and 1-shard runs AND match a host-simulator replay of
      the materialized per-key stream;
    * ``rebalance_match`` — a twin run with a mid-run hot-key rebalance
      at a sync boundary emits bit-identical results;
    * ``per_shard_occupancy`` — the drain-point occupancy read.
    """
    import os as _os

    import jax

    from ..mesh import MeshKeyedPipeline

    windows = parse_window_spec(window_spec, seed=cfg.seed)
    from ..engine import EngineConfig

    n_shards = cfg.n_shards or len(jax.devices())
    econf = EngineConfig(capacity=cfg.capacity, annex_capacity=8,
                         min_trigger_pad=32)

    def make(shards):
        return MeshKeyedPipeline(
            windows, [make_aggregation(agg_name)], n_keys=cfg.n_keys,
            n_shards=shards, config=econf, throughput=cfg.throughput,
            wm_period_ms=cfg.watermark_period_ms,
            max_lateness=cfg.max_lateness, seed=cfg.seed)

    p = make(n_shards)
    res = _run_pipeline_cell(p, cfg, window_spec, agg_name, "mesh-keyed",
                             obs=obs)
    res.n_keys = int(cfg.n_keys)
    res.n_shards = int(n_shards)
    res.per_shard_occupancy = [round(float(v), 4)
                               for v in p.shard_occupancy()]
    res.platform = jax.devices()[0].platform
    res.host_cores = _os.cpu_count()

    # -- 1-shard pin at equal total load (the scaling denominator). The
    # single [K, ...] program's wall time is allocator/page-cache noisy
    # on shared hosts, so the denominator is the BEST of three timed
    # segments — understating the ratio is the conservative direction.
    timed = max(3, min(cfg.runtime_s, 6))
    p1 = make(1)
    p1.reset()
    p1.run(3, collect=False)
    p1.sync()
    best1 = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        p1.run(timed, collect=False)
        p1.sync()
        best1 = min(best1, (time.perf_counter() - t0) / timed)
    p1.check_overflow()
    res.tuples_per_sec_1shard = p1.tuples_per_interval / best1
    res.scaling_ratio = res.tuples_per_sec / max(
        res.tuples_per_sec_1shard, 1e-9)

    # -- differential arms (short runs; bit-equality is the assertion) ----
    if cfg.n_keys < 4:
        raise ValueError(
            "MeshKeyed cells need nKeys >= 4 (the differential arms "
            "sample and swap distinct keys)")
    sample_keys = sorted({0, cfg.n_keys // 3, cfg.n_keys - 1})
    pa, pb = make(n_shards), make(1)
    pa.reset(), pb.reset()
    oracle_match = True
    from .. import SlicingWindowOperator

    sim = SlicingWindowOperator()
    for w in windows:
        sim.add_window_assigner(w)
    sim.add_aggregation(make_aggregation(agg_name))
    sim.set_max_lateness(cfg.max_lateness)
    sim_key = sample_keys[1]
    for i in range(3):
        a = pa.run(1)[0]
        b = pb.run(1)[0]
        for kk in sample_keys:
            if pa.lowered_results_for_key(a, kk) \
                    != pb.lowered_results_for_key(b, kk):
                oracle_match = False
        vals, ts = pa.materialize_interval(i, sim_key)
        order = np.argsort(ts, kind="stable")
        sim.process_elements(vals[order], ts[order])
        want = {}
        for w in sim.process_watermark((i + 1) * cfg.watermark_period_ms):
            if w.has_value():
                want.setdefault((w.get_start(), w.get_end()),
                                w.get_agg_values())
        got = {(s, e): v for (s, e, c, v)
               in pa.lowered_results_for_key(a, sim_key)}
        if set(got) != set(want):
            oracle_match = False
        else:
            for k2 in want:
                for x, y in zip(want[k2], got[k2]):
                    if abs(float(x) - float(y)) \
                            > 2e-4 * max(1.0, abs(float(x))):
                        oracle_match = False
    pa.check_overflow()
    res.oracle_match = bool(oracle_match)

    rebalance_match = True
    if getattr(cfg, "mesh_rebalance", True):
        pr, pn = make(n_shards), make(n_shards)
        pr.reset(), pn.reset()
        pr.run(2, collect=False), pn.run(2, collect=False)
        pr.sync()
        # a deterministic "hot-key" plan: the generated load is uniform,
        # so the cell validates the MECHANISM (mid-run row migration at a
        # sync boundary) — skew-driven detection is the engine API's job
        pr.rebalance([(0, cfg.n_keys // 2),
                      (1, min(cfg.n_keys // 2 + 1, cfg.n_keys - 1))])
        for i in range(2):
            a = pr.run(1)[0]
            b = pn.run(1)[0]
            for kk in (0, 1, cfg.n_keys // 2, cfg.n_keys - 1):
                if pr.lowered_results_for_key(a, kk) \
                        != pn.lowered_results_for_key(b, kk):
                    rebalance_match = False
        pr.check_overflow()
        # deliberately NOT counted as mesh_rebalances: the arm validates
        # the migration mechanism on a balanced stream — the gated counter
        # means a hot-key-DRIVEN rebalance fired, and a seeded bench run
        # must export it as zero so the obs-diff default gate stays armed
    res.rebalance_match = bool(rebalance_match)
    return res


def run_config(cfg: BenchmarkConfig, out_dir: str = "bench_results",
               echo=None, collect_metrics: bool = True,
               obs_dir: Optional[str] = None,
               serve_port: Optional[int] = None,
               flight_capacity: Optional[int] = None,
               health_lag_ms: Optional[float] = None,
               health_first_emit_ms: Optional[float] = None,
               fingerprint_ref: Optional[str] = None) -> List[dict]:
    """All cells of one config; writes result_<name>.json (each cell row
    carries a ``metrics`` section unless ``collect_metrics=False``). With
    ``obs_dir``, additionally exports a per-config JSONL time series (one
    snapshot row per cell — ``python -m scotty_tpu.obs report`` summarizes
    it) and per-cell Chrome-trace span files.

    ``serve_port`` (ISSUE 4) starts ONE live ``/metrics``·``/vars``·
    ``/healthz`` endpoint for the whole config run, always answering for
    the currently-running cell's registry (503 before the first cell,
    between cells, and after the last — the live reference is cleared as
    each cell completes); ``flight_capacity`` attaches a FlightRecorder
    of that many ring slots to every cell's Observability (wraparound
    drops surface as the gated ``flight_dropped_events`` counter);
    ``health_lag_ms`` arms the ``/healthz`` watermark-lag check;
    ``health_first_emit_ms`` arms the windowed first-emit p99 check
    (ISSUE 14 — the unhealthy verdict names the owning stage);
    ``fingerprint_ref`` (ISSUE 16) loads a recorded workload fingerprint
    (any export ``obs drift`` accepts) and attaches a WorkloadMonitor +
    DriftDetector referencing it to every cell's Observability — live
    cells then count the gated ``workload_drift_events`` whenever the
    stream moves off the certified workload point."""
    if echo is None:
        echo = _stdout
    rows = []
    cell_idx = 0
    rtt_floor = round(measure_rtt_floor(), 2)
    echo(f"  (drained device->host round-trip floor: {rtt_floor} ms — "
         "lower-bounds every emit-latency sample)")
    if obs_dir and not collect_metrics:
        echo("  (--obs-dir ignored: observability is disabled)")
        obs_dir = None
    if obs_dir:
        os.makedirs(obs_dir, exist_ok=True)
        # truncate: result_<name>.json is overwritten per run, so the
        # sibling JSONL must not accumulate stale rows across runs
        open(os.path.join(obs_dir, f"metrics_{cfg.name}.jsonl"),
             "w").close()
    live = {"obs": None}                 # the endpoint reads the live cell

    ref_fp = None
    if fingerprint_ref:
        from ..obs.drift import load_fingerprint

        ref_fp = load_fingerprint(fingerprint_ref)
        if ref_fp is None:
            echo(f"  (--fingerprint-ref {fingerprint_ref}: no workload "
                 "fingerprint found — drift baseline not armed)")
        else:
            echo(f"  drift baseline: {fingerprint_ref} "
                 f"({len(ref_fp.features)} feature(s), "
                 f"{ref_fp.audits} audit(s))")

    def make_obs():
        flight = None
        if flight_capacity:
            flight = _obs.FlightRecorder(capacity=flight_capacity)
        o = _obs.Observability(flight=flight)
        if ref_fp is not None:
            from ..obs.drift import DriftDetector

            o.attach_workload().attach_detector(
                DriftDetector(reference=ref_fp))
        live["obs"] = o
        return o

    server = None
    if serve_port is not None and collect_metrics:
        from ..obs.server import HealthPolicy, serve as _serve

        health = HealthPolicy(max_watermark_lag_ms=health_lag_ms,
                              max_first_emit_p99_ms=health_first_emit_ms)
        server = _serve(lambda: live["obs"], port=serve_port,
                        health=health)
        echo(f"  live obs endpoint: http://127.0.0.1:{server.port}"
             "/metrics | /vars | /healthz (per running cell)")
    from .. import pallas as _pallas

    try:
        # ONE interpreter-mode context across all cells (ISSUE 15 small
        # fix): the Pallas interpret choice is a run-wide property of
        # the backend — pin it once here so every cell's kernels share
        # one resolution instead of re-entering (and re-resolving) the
        # context per cell
        with _pallas.interpret_mode(not _pallas.backend_is_tpu()):
            return _run_config_cells(cfg, out_dir, echo, collect_metrics,
                                     obs_dir, make_obs, live, rows,
                                     cell_idx, rtt_floor)
    finally:
        if server is not None:
            server.close()


def _run_config_cells(cfg, out_dir, echo, collect_metrics, obs_dir,
                      make_obs, live, rows, cell_idx,
                      rtt_floor) -> List[dict]:
    for window_spec in (cfg.window_configurations or ["Tumbling(1000)"]):
        for engine in cfg.configurations:
            for agg_name in cfg.agg_functions:
                t0 = time.perf_counter()
                try:
                    res = run_cell(cfg, window_spec, agg_name, engine,
                                   collect_metrics=collect_metrics,
                                   make_obs=make_obs)
                except Exception as e:        # one bad cell must not void
                    rows.append({              # the already-computed ones
                        "name": cfg.name, "windows": window_spec,
                        "aggregation": agg_name, "engine": engine,
                        "error": f"{type(e).__name__}: {e}",
                        "cell_wall_s": round(time.perf_counter() - t0, 2)})
                    echo(f"  {window_spec:28s} {engine:10s} {agg_name:8s} "
                         f"ERROR {type(e).__name__}: {e}")
                    continue
                finally:
                    # 503 between cells: a finished cell's frozen registry
                    # must not masquerade as the live pipeline
                    live["obs"] = None
                cell = dict(res.to_dict(), engine=engine,
                            cell_wall_s=round(time.perf_counter() - t0, 2))
                cell["rtt_floor_ms"] = rtt_floor
                for extra in CELL_EXTRA_FIELDS:
                    if hasattr(res, extra):
                        cell[extra] = getattr(res, extra)
                rows.append(cell)
                cell_obs = getattr(res, "observability", None)
                if obs_dir and cell_obs is not None:
                    label = f"{window_spec}|{engine}|{agg_name}"
                    cell_obs.write_jsonl(
                        os.path.join(obs_dir, f"metrics_{cfg.name}.jsonl"),
                        label=label)
                    cell_obs.write_chrome_trace(os.path.join(
                        obs_dir, f"trace_{cfg.name}_{cell_idx}.json"))
                cell_idx += 1
                echo(f"  {window_spec:28s} {engine:10s} {agg_name:8s} "
                     f"{res.tuples_per_sec:15,.0f} t/s  "
                     f"p99={res.p99_emit_ms:8.1f} ms  "
                     f"windows={res.n_windows_emitted}")
    os.makedirs(out_dir, exist_ok=True)
    path = os.path.join(out_dir, f"result_{cfg.name}.json")
    with open(path, "w") as f:
        json.dump(rows, f, indent=1)
    echo(f"  -> {path}")
    if obs_dir:
        echo(f"  -> {obs_dir}/metrics_{cfg.name}.jsonl (summarize with "
             f"`python -m scotty_tpu.obs report`)")
    return rows


def load_config(path: str) -> BenchmarkConfig:
    cfg = BenchmarkConfig.from_json(path)
    with open(path) as f:
        raw = json.load(f)
    cfg.buckets_throughput = raw.get("bucketsThroughput")
    return cfg




def main(argv: Optional[List[str]] = None) -> int:
    import argparse
    import shutil
    import tempfile

    ap = argparse.ArgumentParser(
        prog="python -m scotty_tpu.bench",
        description="Config-driven window-aggregation benchmark runner")
    ap.add_argument("configs", nargs="*",
                    help="JSON config paths (default: bundled configs)")
    ap.add_argument("--out-dir", default="bench_results")
    ap.add_argument("--obs-dir", default=None, metavar="DIR",
                    help="export per-config JSONL metrics time series + "
                         "per-cell Chrome-trace span files into DIR")
    ap.add_argument("--no-obs", action="store_true",
                    help="disable observability entirely (no metrics "
                         "section in results; the overhead A/B baseline)")
    ap.add_argument("--gate", default=None, metavar="THRESHOLDS",
                    help="regression gate: after each config runs, diff "
                         "its fresh result_<name>.json against the "
                         "baseline copy (--baseline-dir, default the "
                         "pre-run file in --out-dir) under this "
                         "threshold JSON (python -m scotty_tpu.obs diff "
                         "semantics; pass 'default' for the built-in "
                         "thresholds); exit nonzero on any regression")
    ap.add_argument("--baseline-dir", default=None, metavar="DIR",
                    help="where baseline result_<name>.json files live "
                         "(with --gate; default: --out-dir, snapshotted "
                         "before each run overwrites it)")
    ap.add_argument("--overflow-policy", default=None, metavar="POLICY",
                    choices=("fail", "shed", "grow"),
                    help="override every config's EngineConfig."
                         "overflow_policy (scotty_tpu.resilience); "
                         "'fail' is the benchmarked default")
    ap.add_argument("--serve-port", default=None, type=int, metavar="PORT",
                    help="serve a live /metrics | /vars | /healthz "
                         "endpoint for the currently-running cell "
                         "(0 = ephemeral port, printed at startup); "
                         "ignored with --no-obs")
    ap.add_argument("--flight-capacity", default=None, type=int,
                    metavar="N",
                    help="attach an N-slot flight recorder "
                         "(scotty_tpu.obs.FlightRecorder) to every "
                         "cell's Observability; ring-wraparound drops "
                         "surface as the gated flight_dropped_events "
                         "counter")
    ap.add_argument("--health-lag-ms", default=None, type=float,
                    metavar="MS",
                    help="arm the /healthz watermark-lag check "
                         "(scotty_tpu.obs.HealthPolicy): verdicts flip "
                         "unhealthy while watermark_lag_ms exceeds MS")
    ap.add_argument("--health-first-emit-ms", default=None, type=float,
                    metavar="MS",
                    help="arm the /healthz windowed first-emit check "
                         "(scotty_tpu.obs.HealthPolicy."
                         "max_first_emit_p99_ms): verdicts flip "
                         "unhealthy while p99 first-emit latency over "
                         "the recent sample window exceeds MS, naming "
                         "the stage that owns the critical path")
    ap.add_argument("--fingerprint-ref", default=None, metavar="FILE",
                    help="arm live workload-drift detection against the "
                         "fingerprint recorded in FILE (any export "
                         "`python -m scotty_tpu.obs drift` accepts: a "
                         "result_<name>.json, a /vars dump, or bare "
                         "fingerprint JSON); every cell gets a "
                         "WorkloadMonitor + DriftDetector referencing "
                         "it, and sustained excursions count the gated "
                         "workload_drift_events; ignored with --no-obs")
    ap.add_argument("--soak-seconds", default=None, type=float,
                    metavar="S",
                    help="override every config's soakSeconds (the Soak "
                         "cell's REAL wall-clock duration: seconds in "
                         "CI, hours on the box)")
    ap.add_argument("--offered-rate", default=None, type=float,
                    metavar="R",
                    help="override every config's offeredRate (Soak "
                         "cell offered load, records/second)")
    ap.add_argument("--delivery", default=None, metavar="MODE",
                    choices=("at_least_once", "exactly_once"),
                    help="override every config's delivery guarantee "
                         "for connector-backed cells (scotty_tpu."
                         "delivery, ISSUE 8): 'at_least_once' (the "
                         "benchmarked default, no ledger) or "
                         "'exactly_once' (epoch-ledger TransactionalSink "
                         "with its measured A/B overhead recorded in "
                         "the cell row)")
    args = ap.parse_args(argv)

    paths = args.configs
    if not paths:
        here = os.path.join(os.path.dirname(__file__), "configurations")
        paths = sorted(
            os.path.join(here, f) for f in os.listdir(here)
            if f.endswith(".json"))
    gate_failures = 0
    for path in paths:
        cfg = load_config(path)
        if args.overflow_policy:
            cfg.overflow_policy = args.overflow_policy
        if args.soak_seconds is not None:
            cfg.soak_seconds = args.soak_seconds
        if args.offered_rate is not None:
            cfg.offered_rate = args.offered_rate
        if args.delivery is not None:
            cfg.delivery = args.delivery
        _stdout(f"== {cfg.name} ({path})")
        baseline_snap = None
        if args.gate:
            src = os.path.join(args.baseline_dir or args.out_dir,
                               f"result_{cfg.name}.json")
            if os.path.exists(src):
                # snapshot BEFORE run_config overwrites result_<name>.json
                fd, baseline_snap = tempfile.mkstemp(suffix=".json")
                os.close(fd)
                shutil.copyfile(src, baseline_snap)
        run_config(cfg, out_dir=args.out_dir,
                   collect_metrics=not args.no_obs, obs_dir=args.obs_dir,
                   serve_port=args.serve_port,
                   flight_capacity=args.flight_capacity,
                   health_lag_ms=args.health_lag_ms,
                   health_first_emit_ms=args.health_first_emit_ms,
                   fingerprint_ref=args.fingerprint_ref)
        if args.gate:
            if baseline_snap is None:
                _stdout(f"  gate: no baseline for {cfg.name} — skipped "
                        "(first run records the baseline)")
                continue
            from ..obs.diff import diff_main

            th = None if args.gate == "default" else args.gate
            rc = diff_main(baseline_snap,
                           os.path.join(args.out_dir,
                                        f"result_{cfg.name}.json"),
                           thresholds_path=th, echo=_stdout)
            os.unlink(baseline_snap)
            if rc:
                gate_failures += 1
    if gate_failures:
        _stdout(f"GATE FAILED: {gate_failures} config(s) regressed")
        return 1
    return 0
