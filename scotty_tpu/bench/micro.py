"""Per-phase microbenchmarks — the JMH analogue.

The reference pins per-element operator cost with JMH
(benchmark/.../microbenchmark/SlicingWindowOperatorBenchmark.java:37-52,
AggregationStoreBenchmark.java); here the phases worth isolating are device
kernels and the host glue around them, so perf work on the full pipeline
stops being blind (VERDICT r1 item 9):

* ``ingest_scatter``    — general batched ingest kernel (scatter-combine)
* ``ingest_aligned``    — slice-aligned generate+reduce+append step
  (AlignedStreamPipeline's fused interval, amortized per tuple)
* ``query``             — range-query kernel at benchmark trigger counts
* ``annex_merge``       — out-of-order annex fold (device sort path)
* ``gc``                — slice-buffer roll
* ``host_pack``         — keyed host packing (lexsort + [K, B] scatter),
  no device work

Run: ``python -m scotty_tpu.bench.micro [--out bench_results/micro.json]``.
Each phase reports mean/min ms per dispatch and derived tuples/s where
meaningful. Shapes default to the headline-benchmark scale; ``--small``
switches to CPU-test shapes.
"""

from __future__ import annotations

import json
import time
from typing import Callable, Optional

import numpy as np


def _time_phase(fn: Callable[[], None], sync: Callable[[], None],
                iters: int, warmup: int = 2) -> dict:
    """Amortized per-dispatch timing: ``iters`` back-to-back dispatches,
    ONE true sync (``sync`` must be a ``jax.device_get`` of a value the
    work produced — ``block_until_ready`` is not a reliable barrier on
    tunneled devices, docs/DESIGN.md). The final sync's round trip is
    measured on an idle queue and subtracted; the per-dispatch mean
    still includes per-dispatch overhead."""
    for _ in range(warmup):
        fn()
    sync()
    t0 = time.perf_counter()
    sync()                              # idle-queue sync = pure round trip
    sync_ms = (time.perf_counter() - t0) * 1e3
    t0 = time.perf_counter()
    for _ in range(iters):
        fn()
    sync()
    total_ms = (time.perf_counter() - t0) * 1e3
    # floor at ~timer resolution: on a fast host with tiny shapes the
    # subtraction can land at/below 0, and a 0 mean poisons every derived
    # rate downstream (VERDICT r3 weak-1). ``floored`` marks the phase so
    # a derived rate is recognizably a bound, not a measurement.
    raw = (total_ms - sync_ms) / iters
    mean = max(raw, 1e-4)
    return {"mean_ms": float(mean), "sync_ms": float(sync_ms),
            "iters": iters, "floored": bool(raw < 1e-4)}


def _rate(n: float, mean_ms: float) -> float:
    """Items/s from an amortized per-dispatch mean (mean_ms is floored at
    timer resolution by _time_phase, so this can't divide by zero)."""
    return n / (mean_ms / 1e3)


def run_micro(small: bool = False, iters: int = 20, seed: int = 0) -> dict:
    import jax
    import jax.numpy as jnp

    from ..core.aggregates import SumAggregation
    from ..core.windows import SlidingWindow, WindowMeasure
    from ..engine import core as ec
    from ..engine.config import EngineConfig
    from ..engine.pipeline import AlignedStreamPipeline

    if small:                      # CPU-test shapes
        C, A, B, Tq = 1 << 10, 64, 1 << 10, 128
        throughput, wm_period = 200_000, 1000
        window = SlidingWindow(WindowMeasure.Time, 60_000, 1000)
    else:                          # headline-benchmark shapes
        C, A, B, Tq = 1 << 17, 1 << 12, 1 << 18, 1 << 16
        throughput, wm_period = 200_000_000, 1000
        window = SlidingWindow(WindowMeasure.Time, 60_000, 1)

    spec = ec.EngineSpec(periods=(1,) if not small else (1000,), bands=(),
                         count_periods=(),
                         aggs=(SumAggregation().device_spec(),))
    rng = np.random.default_rng(seed)
    results: dict = {"shapes": {"capacity": C, "annex": A, "batch": B,
                                "triggers": Tq, "small": small}}

    # ---- ingest (general scatter path) -----------------------------------
    ingest = jax.jit(ec.build_ingest(spec, C, A), donate_argnums=0)
    grid = spec.periods[0]
    ts0 = np.sort(rng.integers(0, B * 2, size=B)).astype(np.int64)
    vals = rng.random(B).astype(np.float32)
    valid = np.ones((B,), bool)
    holder = {"st": ec.init_state(spec, C, A), "i": 0}

    def do_ingest():
        # fresh ts range each call so the buffer doesn't overflow the cap
        off = holder["i"] * 2 * B
        holder["i"] += 1
        holder["st"] = ingest(holder["st"], ts0 + off, vals, valid)

    def sync():
        jax.device_get(holder["st"].n_slices)

    r = _time_phase(do_ingest, sync, iters)
    r["tuples_per_s"] = _rate(B, r["mean_ms"])
    results["ingest_scatter"] = r

    # ---- gc (amortizes the buffer back down) ------------------------------
    gc = jax.jit(ec.build_gc(spec, C, A), donate_argnums=0)

    def do_gc():
        holder["st"] = gc(holder["st"], np.int64(holder["i"] * 2 * B))

    results["gc"] = _time_phase(do_gc, sync, iters)

    # ---- query ------------------------------------------------------------
    query = jax.jit(ec.build_query(spec, C, A))
    # refill a few batches so the buffer has content
    for _ in range(3):
        do_ingest()
    ws = (np.arange(Tq, dtype=np.int64) % (B // 2)) * grid
    we = ws + grid * 16
    mask = np.ones((Tq,), bool)
    ic = np.zeros((Tq,), bool)
    out_holder = {}

    def do_query():
        out_holder["out"] = query(holder["st"], ws, we, mask, ic)

    def sync_q():
        jax.device_get(out_holder["out"][0][0])

    r = _time_phase(do_query, sync_q, iters)
    r["windows_per_s"] = _rate(Tq, r["mean_ms"])
    results["query"] = r

    # ---- annex merge ------------------------------------------------------
    merge = jax.jit(ec.build_annex_merge(spec, C, A), donate_argnums=0)

    def do_merge():
        holder["st"] = merge(holder["st"])

    results["annex_merge"] = _time_phase(do_merge, sync, iters)

    # ---- aligned fused interval ------------------------------------------
    p = AlignedStreamPipeline(
        [window], [SumAggregation()],
        config=EngineConfig(capacity=C, annex_capacity=8, min_trigger_pad=32),
        throughput=throughput, wm_period_ms=wm_period, gc_every=8, seed=seed)
    p.reset()
    p.run(2, collect=False)        # compile + warm
    p.sync()

    def do_aligned():
        p.run(1, collect=False)

    r = _time_phase(do_aligned, lambda: p.sync(), iters)
    r["tuples_per_s"] = _rate(p.tuples_per_interval, r["mean_ms"])
    results["ingest_aligned"] = r
    p.check_overflow()

    # ---- host packing (no device work) ------------------------------------
    K = 64
    Np = B
    keys = rng.integers(0, K, size=Np).astype(np.int32)
    kts = np.sort(rng.integers(0, 1 << 20, size=Np)).astype(np.int64)
    kvals = rng.random(Np).astype(np.float32)

    def do_pack():
        order = np.lexsort((kts, keys))
        k2, v2, t2 = keys[order], kvals[order], kts[order]
        counts = np.bincount(k2, minlength=K)
        starts = np.zeros(K, np.int64)
        starts[1:] = np.cumsum(counts)[:-1]
        pos = np.arange(t2.size, dtype=np.int64) - starts[k2]
        Bk = 1 << 10
        rnd, lane = pos // Bk, pos % Bk
        m = rnd == 0
        ts_b = np.zeros((K, Bk), np.int64)
        ts_b[k2[m], lane[m]] = t2[m]
        return ts_b

    r = _time_phase(do_pack, lambda: None, iters)
    r["tuples_per_s"] = _rate(Np, r["mean_ms"])
    results["host_pack"] = r

    # ---- raw scatter costs (the numbers behind docs/DESIGN.md's "no
    # int64 scatter on the hot path" decisions) ----------------------------
    Bs = B
    pos = jnp.asarray(rng.integers(0, C, size=Bs).astype(np.int32))
    fv = jnp.asarray(rng.random(Bs).astype(np.float32))
    iv = jnp.asarray(rng.integers(0, 1 << 40, size=Bs).astype(np.int64))
    sc_holder = {
        "f32": jnp.zeros((C,), jnp.float32),
        "i64": jnp.full((C,), np.int64(1) << 60),
    }
    scatter_f32 = jax.jit(lambda a: a.at[pos].add(fv), donate_argnums=0)
    scatter_i64 = jax.jit(lambda a: a.at[pos].min(iv), donate_argnums=0)

    def do_sf():
        sc_holder["f32"] = scatter_f32(sc_holder["f32"])

    r = _time_phase(do_sf, lambda: jax.device_get(sc_holder["f32"][0]),
                    iters)
    r["lanes"] = Bs
    results["scatter_f32_add"] = r

    def do_si():
        sc_holder["i64"] = scatter_i64(sc_holder["i64"])

    r = _time_phase(do_si, lambda: jax.device_get(sc_holder["i64"][0]),
                    iters)
    r["lanes"] = Bs
    results["scatter_i64_min"] = r

    results["platform"] = jax.devices()[0].platform
    return results


def main(argv: Optional[list] = None, echo=None) -> int:
    import argparse
    import os

    from ..utils import stdout_echo

    if echo is None:
        echo = stdout_echo

    ap = argparse.ArgumentParser(prog="python -m scotty_tpu.bench.micro")
    ap.add_argument("--out", default="bench_results/micro.json")
    ap.add_argument("--small", action="store_true",
                    help="CPU-test shapes instead of benchmark shapes")
    ap.add_argument("--iters", type=int, default=20)
    args = ap.parse_args(argv)

    res = run_micro(small=args.small, iters=args.iters)
    for phase, r in res.items():
        if not isinstance(r, dict) or "mean_ms" not in r:
            continue
        extra = ""
        if "tuples_per_s" in r:
            extra = f"  {r['tuples_per_s']:16,.0f} tuples/s"
        elif "windows_per_s" in r:
            extra = f"  {r['windows_per_s']:16,.0f} windows/s"
        echo(f"{phase:16s} mean={r['mean_ms']:9.3f} ms/dispatch"
             f"{extra}")
    os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
    with open(args.out, "w") as f:
        json.dump(res, f, indent=1)
    echo(f"-> {args.out}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
