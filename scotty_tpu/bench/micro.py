"""Per-phase microbenchmarks — the JMH analogue.

The reference pins per-element operator cost with JMH
(benchmark/.../microbenchmark/SlicingWindowOperatorBenchmark.java:37-52,
AggregationStoreBenchmark.java); here the phases worth isolating are device
kernels and the host glue around them, so perf work on the full pipeline
stops being blind (VERDICT r1 item 9):

* ``ingest_scatter``    — general batched ingest kernel (scatter-combine)
* ``ingest_aligned``    — slice-aligned generate+reduce+append step
  (AlignedStreamPipeline's fused interval, amortized per tuple)
* ``query``             — range-query kernel at benchmark trigger counts
* ``annex_merge``       — out-of-order annex fold (device sort path)
* ``gc``                — slice-buffer roll
* ``host_pack``         — keyed host packing (lexsort + [K, B] scatter),
  no device work
* ``shape_sort_split``  — the shaper's jitted sort-and-split alone
  (scotty_tpu.shaper.device, ISSUE 5)
* ``ingest_shaped_ooo`` — a DISORDERED device-resident stream through
  the shaper end-to-end (sort-split + dense in-order ingest + late
  residue) — the number to hold against ``ingest_scatter``, which is
  what the same stream costs unshaped

Run: ``python -m scotty_tpu.bench.micro [--out bench_results/micro.json]``.
Each phase reports mean/min ms per dispatch and derived tuples/s where
meaningful. Shapes default to the headline-benchmark scale; ``--small``
switches to CPU-test shapes.
"""

from __future__ import annotations

import json
import time
from typing import Callable, Optional

import numpy as np


def _time_phase(fn: Callable[[], None], sync: Callable[[], None],
                iters: int, warmup: int = 2,
                drain: Optional[Callable[[], None]] = None) -> dict:
    """Amortized per-dispatch timing: ``iters`` back-to-back dispatches,
    ONE true sync (``sync`` must be a ``jax.device_get`` of a value the
    work produced — ``block_until_ready`` is not a reliable barrier on
    tunneled devices, docs/DESIGN.md). The final sync's round trip is
    measured on an idle queue and subtracted; the per-dispatch mean
    still includes per-dispatch overhead.

    ``drain`` retires the WHOLE async dispatch queue (block_until_ready
    over every live device value of the run) before the timed sections.
    ``sync`` alone only waits for this phase's own output — work queued
    by a PREVIOUS section can still be in flight behind it, and that
    work then lands inside this phase's "idle-queue" sync measurement
    (micro.json showed query.sync_ms 124.8 ms > its own mean_ms 70.7 ms
    — queued prior work misattributed to a later section's sync)."""
    for _ in range(warmup):
        fn()
    sync()
    if drain is not None:
        drain()                         # the queue is now REALLY idle
    t0 = time.perf_counter()
    sync()                              # idle-queue sync = pure round trip
    sync_ms = (time.perf_counter() - t0) * 1e3
    t0 = time.perf_counter()
    for _ in range(iters):
        fn()
    sync()
    total_ms = (time.perf_counter() - t0) * 1e3
    # floor at ~timer resolution: on a fast host with tiny shapes the
    # subtraction can land at/below 0, and a 0 mean poisons every derived
    # rate downstream (VERDICT r3 weak-1). ``floored`` marks the phase so
    # a derived rate is recognizably a bound, not a measurement.
    raw = (total_ms - sync_ms) / iters
    mean = max(raw, 1e-4)
    return {"mean_ms": float(mean), "sync_ms": float(sync_ms),
            "iters": iters, "floored": bool(raw < 1e-4)}


def _rate(n: float, mean_ms: float) -> float:
    """Items/s from an amortized per-dispatch mean (mean_ms is floored at
    timer resolution by _time_phase, so this can't divide by zero)."""
    return n / (mean_ms / 1e3)


def run_micro(small: bool = False, iters: int = 20, seed: int = 0) -> dict:
    import jax
    import jax.numpy as jnp

    from ..core.aggregates import SumAggregation
    from ..core.windows import SlidingWindow, WindowMeasure
    from ..engine import core as ec
    from ..engine.config import EngineConfig
    from ..engine.pipeline import AlignedStreamPipeline

    if small:                      # CPU-test shapes
        C, A, B, Tq = 1 << 10, 64, 1 << 10, 128
        throughput, wm_period = 200_000, 1000
        window = SlidingWindow(WindowMeasure.Time, 60_000, 1000)
    else:                          # headline-benchmark shapes
        C, A, B, Tq = 1 << 17, 1 << 12, 1 << 18, 1 << 16
        throughput, wm_period = 200_000_000, 1000
        window = SlidingWindow(WindowMeasure.Time, 60_000, 1)

    spec = ec.EngineSpec(periods=(1,) if not small else (1000,), bands=(),
                         count_periods=(),
                         aggs=(SumAggregation().device_spec(),))
    rng = np.random.default_rng(seed)
    results: dict = {"shapes": {"capacity": C, "annex": A, "batch": B,
                                "triggers": Tq, "small": small}}

    # every live device value of the run, as thunks: the inter-section
    # dispatch-queue drain blocks on ALL of them, so no section's timing
    # inherits queued work from a previous section (see _time_phase)
    live_thunks: list = []

    def drain():
        vals = [t() for t in live_thunks]
        for leaf in jax.tree_util.tree_leaves(
                [v for v in vals if v is not None]):
            if hasattr(leaf, "block_until_ready"):
                leaf.block_until_ready()

    # ---- ingest (general scatter path) -----------------------------------
    ingest = jax.jit(ec.build_ingest(spec, C, A), donate_argnums=0)
    grid = spec.periods[0]
    ts0 = np.sort(rng.integers(0, B * 2, size=B)).astype(np.int64)
    vals = rng.random(B).astype(np.float32)
    valid = np.ones((B,), bool)
    holder = {"st": ec.init_state(spec, C, A), "i": 0}

    def do_ingest():
        # fresh ts range each call so the buffer doesn't overflow the cap
        off = holder["i"] * 2 * B
        holder["i"] += 1
        holder["st"] = ingest(holder["st"], ts0 + off, vals, valid)

    def sync():
        jax.device_get(holder["st"].n_slices)

    live_thunks.append(lambda: holder["st"])
    r = _time_phase(do_ingest, sync, iters, drain=drain)
    r["tuples_per_s"] = _rate(B, r["mean_ms"])
    results["ingest_scatter"] = r

    # ---- gc (amortizes the buffer back down) ------------------------------
    gc = jax.jit(ec.build_gc(spec, C, A), donate_argnums=0)

    def do_gc():
        holder["st"] = gc(holder["st"], np.int64(holder["i"] * 2 * B))

    results["gc"] = _time_phase(do_gc, sync, iters, drain=drain)

    # ---- query ------------------------------------------------------------
    query = jax.jit(ec.build_query(spec, C, A))
    # refill a few batches so the buffer has content
    for _ in range(3):
        do_ingest()
    ws = (np.arange(Tq, dtype=np.int64) % (B // 2)) * grid
    we = ws + grid * 16
    mask = np.ones((Tq,), bool)
    ic = np.zeros((Tq,), bool)
    out_holder = {}

    def do_query():
        out_holder["out"] = query(holder["st"], ws, we, mask, ic)

    def sync_q():
        jax.device_get(out_holder["out"][0][0])

    live_thunks.append(lambda: out_holder.get("out"))
    r = _time_phase(do_query, sync_q, iters, drain=drain)
    r["windows_per_s"] = _rate(Tq, r["mean_ms"])
    results["query"] = r

    # ---- annex merge ------------------------------------------------------
    merge = jax.jit(ec.build_annex_merge(spec, C, A), donate_argnums=0)

    def do_merge():
        holder["st"] = merge(holder["st"])

    results["annex_merge"] = _time_phase(do_merge, sync, iters, drain=drain)

    # ---- aligned fused interval ------------------------------------------
    p = AlignedStreamPipeline(
        [window], [SumAggregation()],
        config=EngineConfig(capacity=C, annex_capacity=8, min_trigger_pad=32),
        throughput=throughput, wm_period_ms=wm_period, gc_every=8, seed=seed)
    p.reset()
    p.run(2, collect=False)        # compile + warm
    p.sync()

    def do_aligned():
        p.run(1, collect=False)

    def _pipeline_drain():
        p.sync()
        return None

    live_thunks.append(_pipeline_drain)
    r = _time_phase(do_aligned, lambda: p.sync(), iters, drain=drain)
    r["tuples_per_s"] = _rate(p.tuples_per_interval, r["mean_ms"])
    results["ingest_aligned"] = r
    p.check_overflow()

    # ---- host packing (no device work) ------------------------------------
    K = 64
    Np = B
    keys = rng.integers(0, K, size=Np).astype(np.int32)
    kts = np.sort(rng.integers(0, 1 << 20, size=Np)).astype(np.int64)
    kvals = rng.random(Np).astype(np.float32)

    def do_pack():
        order = np.lexsort((kts, keys))
        k2, v2, t2 = keys[order], kvals[order], kts[order]
        counts = np.bincount(k2, minlength=K)
        starts = np.zeros(K, np.int64)
        starts[1:] = np.cumsum(counts)[:-1]
        pos = np.arange(t2.size, dtype=np.int64) - starts[k2]
        Bk = 1 << 10
        rnd, lane = pos // Bk, pos % Bk
        m = rnd == 0
        ts_b = np.zeros((K, Bk), np.int64)
        ts_b[k2[m], lane[m]] = t2[m]
        return ts_b

    r = _time_phase(do_pack, lambda: None, iters, drain=drain)
    r["tuples_per_s"] = _rate(Np, r["mean_ms"])
    results["host_pack"] = r

    # ---- raw scatter costs (the numbers behind docs/DESIGN.md's "no
    # int64 scatter on the hot path" decisions) ----------------------------
    Bs = B
    pos = jnp.asarray(rng.integers(0, C, size=Bs).astype(np.int32))
    fv = jnp.asarray(rng.random(Bs).astype(np.float32))
    iv = jnp.asarray(rng.integers(0, 1 << 40, size=Bs).astype(np.int64))
    sc_holder = {
        "f32": jnp.zeros((C,), jnp.float32),
        "i64": jnp.full((C,), np.int64(1) << 60),
    }
    scatter_f32 = jax.jit(lambda a: a.at[pos].add(fv), donate_argnums=0)
    scatter_i64 = jax.jit(lambda a: a.at[pos].min(iv), donate_argnums=0)

    def do_sf():
        sc_holder["f32"] = scatter_f32(sc_holder["f32"])

    live_thunks.append(lambda: (sc_holder["f32"], sc_holder["i64"]))
    r = _time_phase(do_sf, lambda: jax.device_get(sc_holder["f32"][0]),
                    iters, drain=drain)
    r["lanes"] = Bs
    results["scatter_f32_add"] = r

    def do_si():
        sc_holder["i64"] = scatter_i64(sc_holder["i64"])

    r = _time_phase(do_si, lambda: jax.device_get(sc_holder["i64"][0]),
                    iters, drain=drain)
    r["lanes"] = Bs
    results["scatter_i64_min"] = r

    # ---- shaper sort-and-split kernel alone (ISSUE 5) --------------------
    from ..shaper.device import I64_MIN, init_shaper_stats, \
        sort_split_kernel

    late_cap = max(64, B // 8)
    ss_kern = sort_split_kernel(B, late_cap)
    ts_ooo = rng.integers(0, B * 2, size=B).astype(np.int64)  # UNSORTED
    ss_holder = {"stats": init_shaper_stats()}
    cut0 = np.int64(I64_MIN)

    def do_ss():
        out = ss_kern(ss_holder["stats"], ts_ooo, vals, valid, cut0, cut0)
        ss_holder["stats"] = out[0]
        ss_holder["out"] = out[1:]

    def sync_ss():
        jax.device_get(ss_holder["out"][0][0])

    live_thunks.append(lambda: (ss_holder["stats"],
                                ss_holder.get("out")))
    r = _time_phase(do_ss, sync_ss, iters, drain=drain)
    r["tuples_per_s"] = _rate(B, r["mean_ms"])
    results["shape_sort_split"] = r

    # ---- shaped OOO ingest end-to-end (ISSUE 5) --------------------------
    # the SAME disordered device-resident stream class ingest_scatter
    # pays the general kernel for: per-batch uniform draws (unsorted
    # arrival order) with a bounded back-reach into the previous batch's
    # range, taken through StreamShaper.shape_device_batch — sort-split
    # + dense/in-order ingest + the small late-residue dispatch
    from ..autotune import EngineGeometry
    from ..engine import TpuWindowOperator
    from ..shaper import StreamShaper

    from ..core.windows import TumblingWindow

    span = 2 * B                    # event-ms per batch (ingest_scatter's)
    back = max(1, span // 32)       # bounded inter-batch disorder reach
    # the shaped arm's engine + shaper configs derive from one geometry
    # (geometry-discipline): coupled knobs move as a single value
    geom_sh = EngineGeometry(capacity=C, batch_size=B,
                             min_trigger_pad=32, late_capacity=late_cap)
    op_sh = TpuWindowOperator(config=geom_sh.engine_config(
        EngineConfig(annex_capacity=A)))
    # a window whose grid keeps ~iters un-GC'd batches inside `capacity`
    # (the timed loop never watermarks; the grid-1 sliding spec of the
    # scatter cell would blow the slice buffer at full shapes)
    w_grid = max(1000, span // 8)
    op_sh.add_window_assigner(TumblingWindow(WindowMeasure.Time, w_grid))
    op_sh.add_aggregation(SumAggregation())
    op_sh.set_max_lateness(span + back)
    shaper = StreamShaper(op_sh, geom_sh.shaper_config())
    ts_sh = rng.integers(0, span + back, size=B).astype(np.int64)
    sh2 = {"i": 1}                  # start a span in so ts never go < 0

    def do_shaped():
        off = sh2["i"] * span
        sh2["i"] += 1
        # batch i covers [i*span - back, i*span + span): the `back` head
        # reaches into batch i-1's range — the actually-late fraction
        shaper.shape_device_batch(vals, ts_sh + (off - back),
                                  off - back, off + span)

    def sync_sh():
        jax.device_get(op_sh._state.n_slices)

    live_thunks.append(lambda: op_sh._state)
    r = _time_phase(do_shaped, sync_sh, iters, drain=drain)
    r["tuples_per_s"] = _rate(B, r["mean_ms"])
    r["late_capacity"] = late_cap
    if results["ingest_scatter"]["mean_ms"] > 0:
        r["speedup_vs_scatter"] = (results["ingest_scatter"]["mean_ms"]
                                   / r["mean_ms"])
    results["ingest_shaped_ooo"] = r
    shaper.check()
    op_sh.check_overflow()

    # ---- Pallas vs XLA twins (ISSUE 15) ----------------------------------
    # Correctness is the claim these cells certify on CPU: both arms run
    # the identical stream, the Pallas arm under interpreter mode
    # (pl.pallas_call(..., interpret=True) — resolve_interpret picks it
    # on every non-TPU backend), honestly tagged. The relative timing of
    # an interpreted kernel against native XLA says nothing about TPU
    # speed — those floors stay TPU-box certifications (PR 5/7/10
    # discipline) — so the recorded comparator is bit-equality plus the
    # per-dispatch means, both platform-tagged.
    from .. import pallas as _spl

    Bp = min(B, 1 << 14)                 # bitonic network depth ~ log^2 B
    late_p = max(64, Bp // 8)
    ts_p = rng.integers(0, Bp * 2, size=Bp).astype(np.int64)
    vals_p = rng.random(Bp).astype(np.float32)
    valid_p = np.ones((Bp,), bool)
    cut_p = np.int64(Bp)                 # half the span is "late"

    from ..shaper.device import build_sort_split, init_shaper_stats

    ss_xla = jax.jit(build_sort_split(Bp, late_p), donate_argnums=0)
    ss_pls = jax.jit(_spl.build_pallas_sort_split(Bp, late_p),
                     donate_argnums=0)
    hold = {"sx": init_shaper_stats(), "sp": init_shaper_stats()}

    def do_ss_xla():
        out = ss_xla(hold["sx"], ts_p, vals_p, valid_p, cut_p, cut_p)
        hold["sx"], hold["ox"] = out[0], out[1:]

    def do_ss_pls():
        out = ss_pls(hold["sp"], ts_p, vals_p, valid_p, cut_p, cut_p,
                     np.int64(0))
        hold["sp"], hold["op"] = out[0], out[1:]

    live_thunks.append(lambda: (hold.get("ox"), hold.get("op")))
    r = _time_phase(do_ss_xla, lambda: jax.device_get(hold["ox"][0][0]),
                    iters, drain=drain)
    r["tuples_per_s"] = _rate(Bp, r["mean_ms"])
    r["lanes"] = Bp
    results["sort_split_xla_twin"] = r
    r = _time_phase(do_ss_pls, lambda: jax.device_get(hold["op"][0][0]),
                    iters, drain=drain)
    r["tuples_per_s"] = _rate(Bp, r["mean_ms"])
    r["lanes"] = Bp
    r["pallas_interpret"] = _spl.resolve_interpret(None)
    r["bit_match_vs_xla"] = all(
        np.array_equal(np.asarray(a), np.asarray(b))
        for a, b in zip(jax.device_get(hold["ox"]),
                        jax.device_get(hold["op"])))
    results["sort_split_pallas"] = r

    # segmented fold: per-row reduce of an [rows, lanes] value block —
    # the aligned/keyed/mesh lift shape (equal segments by construction)
    rows_f, lanes_f = 256, 1024
    flat_f = jnp.asarray(rng.integers(0, 1 << 10, size=(
        rows_f * lanes_f, 1)).astype(np.float32))
    fold_xla = jax.jit(lambda v: jnp.sum(
        v.reshape(rows_f, lanes_f, 1), axis=1))
    fold_pls = jax.jit(lambda v: _spl.row_fold(
        v, rows_f, lanes_f, "sum", 0.0))
    fhold: dict = {}

    def do_f_xla():
        fhold["x"] = fold_xla(flat_f)

    def do_f_pls():
        fhold["p"] = fold_pls(flat_f)

    live_thunks.append(lambda: (fhold.get("x"), fhold.get("p")))
    r = _time_phase(do_f_xla, lambda: jax.device_get(fhold["x"][0][0]),
                    iters, drain=drain)
    r["tuples_per_s"] = _rate(rows_f * lanes_f, r["mean_ms"])
    results["segment_fold_xla_twin"] = r
    r = _time_phase(do_f_pls, lambda: jax.device_get(fhold["p"][0][0]),
                    iters, drain=drain)
    r["tuples_per_s"] = _rate(rows_f * lanes_f, r["mean_ms"])
    r["rows"], r["lanes"] = rows_f, lanes_f
    r["pallas_interpret"] = _spl.resolve_interpret(None)
    r["bit_match_vs_xla"] = bool(np.array_equal(
        np.asarray(jax.device_get(fhold["x"])),
        np.asarray(jax.device_get(fhold["p"]))))
    results["segment_fold_pallas"] = r

    results["platform"] = jax.devices()[0].platform
    return results


def main(argv: Optional[list] = None, echo=None) -> int:
    import argparse
    import os

    from ..utils import stdout_echo

    if echo is None:
        echo = stdout_echo

    ap = argparse.ArgumentParser(prog="python -m scotty_tpu.bench.micro")
    ap.add_argument("--out", default="bench_results/micro.json")
    ap.add_argument("--small", action="store_true",
                    help="CPU-test shapes instead of benchmark shapes")
    ap.add_argument("--iters", type=int, default=20)
    args = ap.parse_args(argv)

    res = run_micro(small=args.small, iters=args.iters)
    for phase, r in res.items():
        if not isinstance(r, dict) or "mean_ms" not in r:
            continue
        extra = ""
        if "tuples_per_s" in r:
            extra = f"  {r['tuples_per_s']:16,.0f} tuples/s"
        elif "windows_per_s" in r:
            extra = f"  {r['windows_per_s']:16,.0f} windows/s"
        echo(f"{phase:16s} mean={r['mean_ms']:9.3f} ms/dispatch"
             f"{extra}")
    os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
    with open(args.out, "w") as f:
        json.dump(res, f, indent=1)
    echo(f"-> {args.out}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
