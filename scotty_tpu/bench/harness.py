"""Config-driven throughput harness.

Mirrors the reference benchmark module (SURVEY.md §2.5): BenchmarkRunner's
JSON configs with the window-spec string DSL (benchmark/.../BenchmarkRunner.java:96-171),
LoadGeneratorSource (:10-87), ThroughputLogger/ThroughputStatistics (:24-49,
:3-44) — re-designed for batched device execution: the generator produces
event-time batches, the logger samples tuples/s per batch interval, and the
runner reports mean throughput + p99 window-emit latency per configuration.
"""

from __future__ import annotations

import contextlib
import json
import re
import time
from dataclasses import dataclass, field
from typing import List, Optional

import numpy as np

from .. import obs as _obs
from ..obs import latency as _late
from ..core.aggregates import (
    BUILTIN_AGGREGATIONS,
    AggregateFunction,
    CountAggregation,
    DDSketchQuantileAggregation,
    HyperLogLogAggregation,
    MaxAggregation,
    MeanAggregation,
    MinAggregation,
    SumAggregation,
)
from ..core.windows import (
    FixedBandWindow,
    SessionWindow,
    SlidingWindow,
    TumblingWindow,
    Window,
    WindowMeasure,
)


# ---------------------------------------------------------------------------
# Window-spec DSL (BenchmarkRunner.java:96-171)
# ---------------------------------------------------------------------------

_SPEC_RE = re.compile(r"^\s*(\w+)\s*\(([^)]*)\)\s*$")


def parse_window_spec(spec: str, seed: int = 0) -> List[Window]:
    """Parse the reference's window-spec strings:

    ``Tumbling(size)``, ``Sliding(size,slide)``, ``Session(gap)``,
    ``FixedBand(start,size)``, ``CountTumbling(size)``,
    ``randomTumbling(n,min,max)``, ``RandomSession(n,min,max)``,
    ``randomCount(n,min,max)`` — random variants use a fixed seed like the
    reference (BenchmarkRunner.java:96-171). Specs joined with ``+`` build
    a multi-window workload cell (e.g. ``Session(1000)+Sliding(60000,1000)``
    — the BASELINE config-5 mix).
    """
    if "+" in spec:
        out: List[Window] = []
        for part in spec.split("+"):
            out.extend(parse_window_spec(part.strip(), seed=seed))
        return out
    m = _SPEC_RE.match(spec)
    if not m:
        raise ValueError(f"bad window spec: {spec!r}")
    name, args_s = m.group(1), m.group(2)
    args = [int(a) for a in args_s.replace(" ", "").split(",") if a]
    T, C = WindowMeasure.Time, WindowMeasure.Count
    rng = np.random.default_rng(seed)
    name_l = name.lower()
    if name_l == "tumbling":
        return [TumblingWindow(T, args[0])]
    if name_l == "sliding":
        return [SlidingWindow(T, args[0], args[1])]
    if name_l == "session":
        return [SessionWindow(T, args[0])]
    if name_l == "fixedband":
        return [FixedBandWindow(T, args[0], args[1])]
    if name_l == "counttumbling":
        return [TumblingWindow(C, args[0])]
    if name_l == "countsliding":
        return [SlidingWindow(C, args[0], args[1])]
    if name_l == "randomtumbling":
        n, lo, hi = args
        return [TumblingWindow(T, int(rng.integers(lo, hi)))
                for _ in range(n)]
    if name_l == "randomsession":
        n, lo, hi = args
        return [SessionWindow(T, int(rng.integers(lo, hi))) for _ in range(n)]
    if name_l == "randomcount":
        n, lo, hi = args
        return [TumblingWindow(C, int(rng.integers(lo, hi)))
                for _ in range(n)]
    if name_l == "cappedsession":
        from ..core.windows import CappedSessionWindow

        return [CappedSessionWindow(T, args[0], args[1])]
    if name_l == "genericsession":
        from ..core.windows import GenericSessionWindow

        return [GenericSessionWindow(T, args[0])]
    raise ValueError(f"unknown window spec {name!r}")


def make_aggregation(name: str) -> AggregateFunction:
    """Aggregation factory by config name (benchmark aggFunctions)."""
    key = name.lower()
    table = {
        "sum": SumAggregation, "count": CountAggregation,
        "min": MinAggregation, "max": MaxAggregation,
        "mean": MeanAggregation,
    }
    if key in table:
        return table[key]()
    if key in ("quantile", "ddsketch"):
        return DDSketchQuantileAggregation(0.5)
    if key in ("hll", "distinct"):
        return HyperLogLogAggregation(8)
    if key in ("cms", "countmin"):
        from ..core.aggregates import CountMinSketchAggregation

        # target 2500.0: an arbitrary fixed point query in the generators'
        # [0, 10000) value range — the cell measures sketch-ingest cost,
        # not the answer to one heavy hitter
        return CountMinSketchAggregation(2500.0, depth=4, width=256)
    raise ValueError(f"unknown aggregation {name!r} "
                     f"(known: {sorted(BUILTIN_AGGREGATIONS)})")


# ---------------------------------------------------------------------------
# Config (BenchmarkConfig.java:8-29)
# ---------------------------------------------------------------------------


@dataclass
class BenchmarkConfig:
    name: str = "bench"
    throughput: int = 10_000_000           # offered tuples per event-second
    runtime_s: int = 10                    # event-time seconds to simulate
    window_configurations: List[str] = field(default_factory=list)
    configurations: List[str] = field(default_factory=lambda: ["TpuEngine"])
    agg_functions: List[str] = field(default_factory=lambda: ["sum"])
    watermark_period_ms: int = 1000
    batch_size: int = 1 << 15
    capacity: int = 1 << 17
    n_keys: int = 1
    out_of_order_pct: float = 0.0
    max_lateness: int = 1000
    seed: int = 42
    #: record-buffer rows for count-measure cells (0 = EngineConfig's
    #: 4x capacity default); live records span
    #: (lateness + count clear-delays + period) x throughput
    record_capacity: int = 0
    #: {"count": N, "minGapMs": a, "maxGapMs": b} — N silent spans at random
    #: event-time positions (the reference's session gaps,
    #: LoadGeneratorSource.java:60-76, generated BenchmarkRunner.java:174-192).
    #: Without them a constant-rate stream is one session that never closes.
    session_config: Optional[dict] = None
    #: pin the r4-era generator (32-bit value draws + per-tuple offset
    #: stream) so cross-round comparisons keep one workload-identical
    #: anchor cell (ADVICE r5); aligned-pipeline cells only
    legacy_generator: bool = False
    #: EngineConfig.overflow_policy for every engine the cells build:
    #: "fail" (the benchmarked default — BASELINE.md numbers are FAIL),
    #: "shed" or "grow" (scotty_tpu.resilience) for degraded-mode A/Bs
    overflow_policy: str = "fail"
    #: ShaperConfig.late_capacity for the ShapedOOO cell (ISSUE 5);
    #: 0 = the shaper default, max(64, batch_size // 8)
    shaper_late_capacity: int = 0
    #: inter-batch disorder back-reach (event-ms) of the ShapedOOO cell's
    #: adversarial stream; 0 = min(max_lateness, batch span / 8)
    shaper_back_ms: int = 0
    #: QueryChurn cell (ISSUE 6): total register+cancel operations the
    #: seeded churn schedule performs mid-stream (the acceptance floor is
    #: >= 1000)
    churn_ops: int = 1024
    #: peak concurrently-active queries (QueryAdmission.max_queries; the
    #: slot grid is pre-padded to this, so steady-state churn never
    #: rebuckets)
    churn_max_active: int = 256
    #: tenants the churn schedule round-robins registrations over
    churn_tenants: int = 4
    #: replay the same churn schedule through an always-active superset
    #: oracle and bit-compare per-query emissions (doubles cell wall time)
    churn_oracle: bool = True
    #: ingest-ring staging depth for the IngestExternal/Soak cells
    #: (ISSUE 7); 0 = the RingConfig default (8)
    ring_depth: int = 0
    #: ring staging-block rows; 0 = the cell's batch size (IngestExternal)
    #: / 1024 (Soak)
    ring_block_size: int = 0
    #: Soak cell wall-clock duration (SystemClock seconds; the runner's
    #: --soak-seconds flag overrides); 0 = the 5 s CI default
    soak_seconds: float = 0.0
    #: Soak cell offered load (records per second; --offered-rate
    #: overrides); 0 = the 50 000/s default
    offered_rate: float = 0.0
    #: MeshKeyed cell (ISSUE 10): device shards the key axis partitions
    #: over; 0 = every local device
    n_shards: int = 0
    #: run the MeshKeyed cell's mid-run-rebalance differential arm (a
    #: twin run migrates keys at a sync boundary and emissions must
    #: bit-match the unmoved twin)
    mesh_rebalance: bool = True
    #: QueryChurnMesh cell (ISSUE 13): ``[[interval, shards], ...]`` —
    #: live reshard to ``shards`` before the named TIMED interval runs
    #: (a checkpoint-boundary operation under the cell's Supervisor);
    #: the superset oracle replays the same schedule so the global psum
    #: folds stay bit-comparable. Empty = no reshard.
    mesh_reshard_schedule: List[list] = field(default_factory=list)
    #: delivery guarantee for connector-backed cells (ISSUE 8; the
    #: runner's --delivery flag overrides): "at_least_once" (the
    #: benchmarked default — no ledger) or "exactly_once" (a
    #: TransactionalSink sequences every emission and its epoch ledger
    #: commits with each supervisor checkpoint; the cell records the
    #: ledger's overhead alongside)
    delivery: str = "at_least_once"
    #: ISSUE 15 (threaded into EngineConfig like overflowPolicy): Pallas
    #: bucketed sort-split for shaped device batches
    pallas_sort_split: bool = False
    #: Pallas segmented-reduce slice-merge for the dense-ingest fold and
    #: the aligned/keyed/mesh generator lifts
    pallas_slice_merge: bool = False
    #: micro-batches per interval for streamed emission
    #: (FusedPipelineDriver.run_streamed; 0 = whole-interval steps) —
    #: the LatencyHeadline cell's micro-batched first-emit arm reads it
    micro_batch: int = 0
    #: SloChurn cell (ISSUE 19): tenants sharing the served grid; the
    #: seeded HOT one offers ``slo_hot_factor`` times its fair share of
    #: registrations and tuples and must trip exactly its own budget
    slo_tenants: int = 6
    #: offered-load multiplier of the hot tenant vs a fair share
    slo_hot_factor: int = 8
    #: delivered-share SLO objective each tenant is held to
    slo_delivered_share: float = 0.90
    #: fast+slow burn-rate threshold that latches an slo_burn event
    slo_burn_threshold: float = 2.0

    @staticmethod
    def from_json(path: str) -> "BenchmarkConfig":
        with open(path) as f:
            raw = json.load(f)
        return BenchmarkConfig(
            name=raw.get("name", "bench"),
            throughput=raw.get("throughput", 10_000_000),
            runtime_s=raw.get("runtime", raw.get("runtime_s", 10)),
            window_configurations=raw.get("windowConfigurations", []),
            configurations=raw.get("configurations", ["TpuEngine"]),
            agg_functions=raw.get("aggFunctions", ["sum"]),
            watermark_period_ms=raw.get("watermarkPeriodMs", 1000),
            batch_size=raw.get("batchSize", 1 << 15),
            capacity=raw.get("capacity", 1 << 17),
            record_capacity=raw.get("recordCapacity", 0),
            n_keys=raw.get("nKeys", 1),
            out_of_order_pct=raw.get("outOfOrderPct", 0.0),
            max_lateness=raw.get("maxLateness", 1000),
            seed=raw.get("seed", 42),
            session_config=raw.get("sessionConfig"),
            legacy_generator=raw.get("legacyGenerator", False),
            overflow_policy=raw.get("overflowPolicy", "fail"),
            shaper_late_capacity=raw.get("shaperLateCapacity", 0),
            shaper_back_ms=raw.get("shaperBackMs", 0),
            churn_ops=raw.get("churnOps", 1024),
            churn_max_active=raw.get("churnMaxActive", 256),
            churn_tenants=raw.get("churnTenants", 4),
            churn_oracle=raw.get("churnOracle", True),
            ring_depth=raw.get("ringDepth", 0),
            ring_block_size=raw.get("ringBlockSize", 0),
            soak_seconds=raw.get("soakSeconds", 0.0),
            offered_rate=raw.get("offeredRate", 0.0),
            delivery=raw.get("delivery", "at_least_once"),
            n_shards=raw.get("nShards", 0),
            mesh_rebalance=raw.get("meshRebalance", True),
            mesh_reshard_schedule=raw.get("meshReshardSchedule", []),
            pallas_sort_split=raw.get("pallasSortSplit", False),
            pallas_slice_merge=raw.get("pallasSliceMerge", False),
            micro_batch=raw.get("microBatch", 0),
            slo_tenants=raw.get("sloTenants", 6),
            slo_hot_factor=raw.get("sloHotFactor", 8),
            slo_delivered_share=raw.get("sloDeliveredShare", 0.90),
            slo_burn_threshold=raw.get("sloBurnThreshold", 2.0),
        )


# ---------------------------------------------------------------------------
# Load generator (LoadGeneratorSource.java:10-87, device-batch edition)
# ---------------------------------------------------------------------------


def generate_batches(cfg: BenchmarkConfig):
    """Pre-generate the whole stream as numpy batches: values f32, event-time
    ms i64 (ascending, with optional bounded disorder), watermark points every
    ``watermark_period_ms`` of event time. ``cfg.session_config`` inserts
    silent event-time spans (session gaps) by stretching timestamps past
    randomly placed gap positions — the reference generator's pause
    mechanism (LoadGeneratorSource.java:60-76)."""
    rng = np.random.default_rng(cfg.seed)
    n_total = cfg.throughput * cfg.runtime_s
    B = cfg.batch_size
    n_batches = max(1, n_total // B)
    span_ms = cfg.runtime_s * 1000
    gap_starts = gap_cum = None
    if cfg.session_config:
        sc = cfg.session_config
        n_gaps = int(sc.get("count", 8))
        gmin = int(sc.get("minGapMs", 1000))
        gmax = int(sc.get("maxGapMs", 5000))
        gap_starts = np.sort(rng.integers(0, span_ms, size=n_gaps))
        gap_lens = rng.integers(gmin, max(gmin + 1, gmax), size=n_gaps)
        gap_cum = np.cumsum(gap_lens)
    batches = []
    per_batch_span = span_ms / n_batches
    for i in range(n_batches):
        lo = i * per_batch_span
        ts = np.sort(rng.integers(int(lo), int(lo + per_batch_span),
                                  size=B)).astype(np.int64)
        if gap_starts is not None:
            # every tuple past gap k shifts by the total length of gaps
            # 1..k → silent spans appear exactly at the gap positions
            idx = np.searchsorted(gap_starts, ts, side="right")
            ts = ts + np.where(idx > 0, gap_cum[np.maximum(idx - 1, 0)], 0)
        if cfg.out_of_order_pct > 0:
            late = rng.random(B) < cfg.out_of_order_pct
            ts = np.where(
                late, np.maximum(ts - rng.integers(
                    0, cfg.max_lateness, size=B), 0), ts).astype(np.int64)
        vals = rng.integers(1, 10_000, size=B).astype(np.float32)
        batches.append((vals, ts))
    return batches


def make_device_source(cfg: BenchmarkConfig):
    """Device-resident load generator — the TPU-native analogue of the
    reference's in-process LoadGeneratorSource (LoadGeneratorSource.java:10-87):
    tuples are synthesized on-chip (sorted event times via a cumulative-gap
    construction — no device sort needed), so host→device bandwidth never
    bounds the measured operator throughput, exactly as the reference's
    generator never crosses a process boundary.

    With ``cfg.out_of_order_pct > 0`` the generator emits an extra LATE
    sub-batch per base batch (that fraction of tuples, displaced back by up
    to ``cfg.max_lateness`` ms, sorted) — delivered separately so only the
    small sub-batch pays the general kernel's late/annex machinery, while
    the in-order base stream takes the dense fast path.

    Returns ``gen(i) -> (vals, ts, ts_min, ts_max)``; when OOO is enabled,
    ``gen.gen_late(i) -> (vals, ts, valid, n, ts_min, ts_max)``.
    """
    from .. import jax_config  # noqa: F401  (x64 before tracing)
    import jax
    import jax.numpy as jnp

    B = cfg.batch_size
    n_total = cfg.throughput * cfg.runtime_s
    n_batches = max(1, n_total // B)
    span_ms = max(1, cfg.runtime_s * 1000 // n_batches)
    ooo = float(cfg.out_of_order_pct)
    lateness = int(cfg.max_lateness)
    n_late = int(B * ooo)
    late_cap = max(64, 1 << (max(1, n_late) - 1).bit_length())

    @jax.jit
    def _gen(key, lo):
        gaps = jax.random.uniform(key, (B,), dtype=jnp.float32)
        gaps = gaps / jnp.sum(gaps) * span_ms
        ts = lo + jnp.cumsum(gaps).astype(jnp.int64)
        ts = jnp.minimum(ts, lo + span_ms - 1)
        vals = jax.random.uniform(key, (B,), dtype=jnp.float32) * 10_000
        return vals, ts

    @jax.jit
    def _gen_late(key, lo):
        """n_late tuples in [max(0, lo - lateness), lo), sorted — tuples of
        earlier event time arriving now."""
        u = jax.random.uniform(key, (2, late_cap), dtype=jnp.float32)
        lo_f = jnp.maximum(lo.astype(jnp.float64) - lateness, 0.0)
        ts = (lo_f + jnp.sort(u[0]).astype(jnp.float64)
              * (lo.astype(jnp.float64) - lo_f)).astype(jnp.int64)
        return u[1] * 10_000.0, ts

    root = jax.random.PRNGKey(cfg.seed)
    valid_late = None

    def gen(i: int):
        lo = np.int64(i * span_ms)
        vals, ts = _gen(jax.random.fold_in(root, i), lo)
        return vals, ts, int(lo), (i + 1) * span_ms - 1

    def gen_late(i: int):
        nonlocal valid_late
        if valid_late is None:
            v = np.zeros((late_cap,), bool)
            v[:n_late] = True
            valid_late = jax.device_put(v)
        lo = np.int64(i * span_ms)
        vals, ts = _gen_late(jax.random.fold_in(root, 1 << 20 | i), lo)
        # tuple order matches ingest_device_late(ts, vals, valid, n, ...)
        return (ts, vals, valid_late, n_late,
                max(0, int(lo) - lateness), int(lo))

    gen.n_batches = n_batches
    gen.span_ms = span_ms
    gen.gen_late = gen_late if (ooo > 0 and n_late > 0) else None
    gen.n_late = n_late
    return gen


# ---------------------------------------------------------------------------
# Throughput statistics (ThroughputStatistics.java:3-44)
# ---------------------------------------------------------------------------


@dataclass
class ThroughputStatistics:
    tuples: int = 0
    seconds: float = 0.0
    emit_latencies_ms: List[float] = field(default_factory=list)

    @property
    def mean_throughput(self) -> float:
        return self.tuples / self.seconds if self.seconds else 0.0

#: a sample is attributed to a transport STALL only above this absolute
#: floor — the documented tunnel stalls run tens of seconds, while genuine
#: engine tail latency above 10×p50 but below this stays engine-attributed
STALL_ABS_MS = 1000.0


def latency_stats(lats) -> dict:
    """Stall-robust latency summary (VERDICT r4 weak #5, refined per
    ADVICE r5): the raw p99 is the AUTHORITATIVE number; a trimmed
    companion excludes samples > 10×p50. Previously every trimmed sample
    was labeled a stall — silently reclassifying genuine engine tail as
    transport noise. Now ``n_stall_samples`` counts only samples that are
    both > 10×p50 AND > :data:`STALL_ABS_MS` (tunnel stalls run tens of
    seconds); when raw and trimmed diverge with NO identified stall,
    ``tail_unattributed`` flags that the tail is real, engine-attributed
    latency the trimmed figure hides."""
    if not len(lats):
        return {"p99_emit_ms": 0.0, "p50_emit_ms": 0.0,
                "p99_emit_ms_trimmed": 0.0, "n_stall_samples": 0,
                "n_trimmed_samples": 0, "stall_flagged": False,
                "tail_unattributed": False}
    lats = np.asarray(lats, np.float64)
    p50 = float(np.percentile(lats, 50))
    p99 = float(np.percentile(lats, 99))
    core = lats[lats <= 10.0 * p50]
    trimmed = int(lats.size - core.size)
    stalls = int(((lats > 10.0 * p50) & (lats > STALL_ABS_MS)).sum())
    p99_t = float(np.percentile(core, 99)) if core.size else p99
    diverged = bool(p99 > 10.0 * p50)
    return {"p99_emit_ms": p99, "p50_emit_ms": p50,
            "p99_emit_ms_trimmed": p99_t, "n_stall_samples": stalls,
            "n_trimmed_samples": trimmed,
            "stall_flagged": diverged and stalls > 0,
            "tail_unattributed": diverged and stalls == 0}


def first_emit_stats(res: "BenchResult", fe_lats) -> None:
    """Fold drained first-emit samples (watermark-eligibility → first
    delivered window, ISSUE 14 — the ROADMAP item 4 bench dimension)
    onto the result row: ``first_emit_p50_ms`` / ``first_emit_p99_ms``
    / ``first_emit_samples``. Cells that measured nothing embed only
    the zero sample count — a 0.0 percentile must never pose as a
    measured latency (and a baseline of 0.0 would turn the first real
    measurement into a false ``obs diff`` regression)."""
    res.first_emit_samples = len(fe_lats)
    if fe_lats:
        arr = np.asarray(fe_lats, np.float64)
        res.first_emit_p50_ms = float(np.percentile(arr, 50))
        res.first_emit_p99_ms = float(np.percentile(arr, 99))


def finalize_observability(res: "BenchResult", obs, lats, emitted: int,
                           n_tuples: Optional[int] = None) -> None:
    """Shared cell epilogue: fold the sampled emit latencies and emission
    count into the registry, then embed the structured export on the
    result. ``n_tuples`` is passed only by cells whose operator had no
    hook points (the counter would otherwise double-count)."""
    if obs is None:
        return
    for v in lats:
        obs.histogram(_obs.EMIT_LATENCY_MS).observe(v)
    obs.counter(_obs.WINDOWS_EMITTED).inc(emitted)
    if n_tuples is not None:
        obs.counter(_obs.INGEST_TUPLES).inc(n_tuples)
    res.metrics = obs.export()
    res.observability = obs             # for exporters (not in to_dict)


@dataclass
class BenchResult:
    name: str
    windows: str
    aggregation: str
    tuples_per_sec: float
    p99_emit_ms: float
    n_windows_emitted: int
    n_tuples: int
    wall_s: float
    #: structured observability section (Observability.export(): metrics
    #: snapshot + span summary); None when observability was disabled
    metrics: Optional[dict] = None

    def to_dict(self):
        out = {
            "name": self.name, "windows": self.windows,
            "aggregation": self.aggregation,
            "tuples_per_sec": self.tuples_per_sec,
            "p99_emit_ms": self.p99_emit_ms,
            "windows_emitted": self.n_windows_emitted,
            "tuples": self.n_tuples, "wall_s": self.wall_s,
        }
        if self.metrics is not None:
            out["metrics"] = self.metrics
        return out


# ---------------------------------------------------------------------------
# Runner (BenchmarkRunner.java:20-202)
# ---------------------------------------------------------------------------


def run_benchmark(cfg: BenchmarkConfig, window_spec: str, agg_name: str,
                  engine: str = "TpuEngine",
                  warmup_batches: int = 2,
                  obs: Optional[_obs.Observability] = None,
                  collect_metrics: bool = True) -> BenchResult:
    """One (window-config × aggregation × engine) cell: feed the whole
    generated stream, watermark every ``watermark_period_ms`` event-ms,
    report mean tuples/s + p99 window-emit latency.

    Observability: unless ``collect_metrics=False``, a fresh
    :class:`scotty_tpu.obs.Observability` (or the caller's ``obs``) is
    attached to the run — engine hooks record ingest/late/watermark
    telemetry, harness phases record spans, and the structured export is
    embedded as the result's ``metrics`` section
    (``BenchResult.to_dict()["metrics"]``)."""
    import jax

    from ..core.windows import ForwardContextAware, ForwardContextFree

    if obs is None and collect_metrics:
        obs = _obs.Observability()
    _span = obs.span if obs is not None else (
        lambda name: contextlib.nullcontext())

    windows = parse_window_spec(window_spec, seed=cfg.seed)
    # out-of-order streams can use the device source too (on-device
    # displacement + re-sort) — except for count windows, whose OOO
    # handling is host-only. Session windows consume batches in arrival
    # order on the host boundary (ingest_device_batch rejects them);
    # context windows ride the device source in-order when every spec
    # certifies the chain kernel (inorder_chain_params), host-fed
    # otherwise.
    def _ctx_device_ok(w):
        sp = w.device_context_spec()
        return sp is not None and sp.inorder_chain_params() is not None

    _host_only_ooo = any(
        w.measure == WindowMeasure.Count
        or isinstance(w, (ForwardContextAware, ForwardContextFree))
        for w in windows)
    _host_fed = any(
        isinstance(w, SessionWindow)
        or (isinstance(w, (ForwardContextAware, ForwardContextFree))
            and not _ctx_device_ok(w))
        for w in windows)
    device_source = (engine == "TpuEngine" and not cfg.session_config
                     and not _host_fed
                     and (cfg.out_of_order_pct == 0 or not _host_only_ooo))
    with _span("generate"):
        if device_source:
            gen = make_device_source(cfg)
            batches = None
        else:
            batches = generate_batches(cfg)

    if engine == "TpuEngine":
        from ..engine import EngineConfig, TpuWindowOperator

        op = TpuWindowOperator(config=EngineConfig(
            capacity=cfg.capacity, batch_size=cfg.batch_size,
            record_capacity=cfg.record_capacity),
            collect_device_metrics=collect_metrics)
    elif engine == "Simulator":
        from ..simulator import SlicingWindowOperator

        op = SlicingWindowOperator()
    elif engine == "Hybrid":
        # automatic backend routing (session / count / holistic mixes run
        # on the host; device-realizable workloads on the engine) — the
        # BASELINE config-5 path. Measured with the generic sync loop.
        from ..hybrid import HybridWindowOperator

        op = HybridWindowOperator()
    else:
        raise ValueError(f"unknown engine {engine!r}")

    for w in windows:
        op.add_window_assigner(w)
    op.add_aggregation(make_aggregation(agg_name))
    op.set_max_lateness(cfg.max_lateness)
    op_has_obs = hasattr(op, "set_observability")
    if obs is not None and op_has_obs:
        op.set_observability(obs)

    # warmup: compile ingest + query + gc paths on a throwaway twin
    # (deliberately NOT given the observability hooks: warmup tuples must
    # not pollute the run's ingest/watermark counters)
    with _span("warmup"):
        if engine == "TpuEngine" and warmup_batches > 0:
            from ..engine import EngineConfig, TpuWindowOperator

            # the throwaway twin's telemetry is discarded — skip its cost
            twin = TpuWindowOperator(config=EngineConfig(
                capacity=cfg.capacity, batch_size=cfg.batch_size,
                record_capacity=cfg.record_capacity),
                collect_device_metrics=False)
            for w in windows:
                twin.add_window_assigner(w)
            twin.add_aggregation(make_aggregation(agg_name))
            twin.set_max_lateness(cfg.max_lateness)
            if device_source:
                last = 0
                for i in range(warmup_batches):
                    vals, ts, lo, hi = gen(i)
                    twin.ingest_device_batch(vals, ts, lo, hi)
                    if gen.gen_late is not None and i > 0:
                        twin.ingest_device_late(*gen.gen_late(i))
                    last = hi
                twin.process_watermark_async(last + 1)
                twin.process_watermark_async(last + cfg.watermark_period_ms + 1)
                anchor = (twin._state if twin._state is not None
                          else twin._ctx_states[0])
                jax.block_until_ready(jax.tree.leaves(anchor)[0])
            else:
                for vals, ts in batches[:warmup_batches]:
                    twin.process_elements(vals, ts)
                twin.process_watermark(int(batches[warmup_batches - 1][1][-1]) + 1)
                twin.process_watermark(int(batches[warmup_batches - 1][1][-1])
                                       + cfg.watermark_period_ms + 1)
    if obs is not None:
        # rates (*_per_s) measure the stream region, not generation/compile
        obs.registry.reset_clock()
    tracer = None
    fe_lats: List[float] = []
    if obs is not None:
        # first-emit probes (ISSUE 14): sampling-off tracer — the
        # operator seams stay one attribute check, and only the sampled
        # ticks below force a chain around their honest drained measure
        tracer = obs.latency if obs.latency is not None \
            else obs.attach_latency(sample_every=0)

    stats = ThroughputStatistics()
    n_emitted = 0
    next_wm = cfg.watermark_period_ms
    n_tuples = 0
    pending = []                 # (T, cnt_dev) handles, fetched at drain
    pending_sessions = []        # per-watermark emitted-session counts (dev)
    wm_count = 0
    SAMPLE_EVERY = 8             # emit-latency sampling cadence

    def advance_watermark(wm: int) -> None:
        """Watermark advance; on sampled ticks, measure HONEST emit latency:
        drain the device queue first, then time dispatch → results-on-host
        (the reference measures per-watermark result delivery the same way —
        its processWatermark is synchronous). Non-sampled ticks stay fully
        async so throughput is not serialized."""
        nonlocal n_emitted, wm_count
        if engine == "TpuEngine":
            sample = wm_count % SAMPLE_EVERY == 0
            lid = None
            if sample:
                anchor = (op._state if op._state is not None
                          else op._session_states[0]
                          if op._session_states else op._ctx_states[0])
                jax.device_get(                           # drain the queue
                    jax.tree.leaves(anchor)[0].ravel()[0])
                t_wm = time.perf_counter()
                if tracer is not None:
                    lid = tracer.open(force=True)
            out = op.process_watermark_async(wm)
            if lid is not None:
                # the watermark dispatch returned: its windows are
                # eligible; the sampled fetch below is their delivery
                tracer.stamp(lid, _late.STAGE_ELIGIBILITY)
            if isinstance(out[0], str) and out[0] == "session":
                ms = tuple(g[0] for g in out[1])   # per-window emit counts
                pending_sessions.append(ms)
                if sample:
                    jax.device_get(ms)
            elif isinstance(out[0], str):        # mixed grid + sessions
                _, grid, s_outs = out
                ms = tuple(g[0] for g in s_outs)
                pending_sessions.append(ms)
                if grid[3] is not None:
                    pending.append((grid[0].shape[0], grid[3]))
                if sample:
                    jax.device_get(ms)
                    if grid[3] is not None:
                        jax.device_get((grid[3], grid[4]))
            elif out[3] is not None:
                pending.append((out[0].shape[0], out[3]))
                if sample:
                    jax.device_get((out[3], out[4]))
            if sample:
                stats.emit_latencies_ms.append(
                    (time.perf_counter() - t_wm) * 1e3)
                if lid is not None:
                    tracer.stamp(lid, _late.STAGE_EMIT)
                    fin = tracer.finalize(lid)
                    if fin is not None \
                            and fin["first_emit_ms"] is not None:
                        fe_lats.append(fin["first_emit_ms"])
        else:
            t_wm = time.perf_counter()
            lid = tracer.open(force=True) if tracer is not None else None
            if lid is not None:
                tracer.stamp(lid, _late.STAGE_ELIGIBILITY)
            results = op.process_watermark(wm)
            n_emitted += sum(1 for r in results if r.has_value())
            stats.emit_latencies_ms.append(
                (time.perf_counter() - t_wm) * 1e3)
            if lid is not None:
                tracer.stamp(lid, _late.STAGE_EMIT)
                fin = tracer.finalize(lid)
                if fin is not None and fin["first_emit_ms"] is not None:
                    fe_lats.append(fin["first_emit_ms"])
        wm_count += 1

    t0 = time.perf_counter()
    with _span("stream"):
        if device_source:
            for i in range(gen.n_batches):
                vals, ts, lo, hi = gen(i)
                op.ingest_device_batch(vals, ts, lo, hi)
                n_tuples += cfg.batch_size
                if gen.gen_late is not None and i > 0:
                    late_args = gen.gen_late(i)
                    op.ingest_device_late(*late_args)
                    n_tuples += late_args[3]
                while hi >= next_wm:
                    advance_watermark(next_wm)
                    next_wm += cfg.watermark_period_ms
            batches = []
        for vals, ts in batches:
            if engine in ("TpuEngine", "Hybrid"):
                op.process_elements(vals, ts)
            else:
                for v, t in zip(vals, ts):
                    op.process_element(float(v), int(t))
            n_tuples += len(vals)
            last_ts = int(ts[-1])
            while last_ts >= next_wm:
                advance_watermark(next_wm)
                next_wm += cfg.watermark_period_ms
    # drain: one final watermark past the stream end + bundled result fetch
    with _span("drain"):
        advance_watermark(next_wm)
        if engine == "TpuEngine":
            fetched = jax.device_get([c for _, c in pending])
            for (T, _), cnt in zip(pending, fetched):
                n_emitted += int((cnt[:T] > 0).sum())
            if pending_sessions:
                n_emitted += int(sum(
                    int(m) for grp in jax.device_get(pending_sessions)
                    for m in grp))
            op.check_overflow()
    wall = time.perf_counter() - t0
    if obs is not None:
        obs.registry.stop_clock()       # rates cover the stream region only

    stats.tuples = n_tuples
    stats.seconds = wall
    res = BenchResult(
        name=cfg.name, windows=window_spec, aggregation=agg_name,
        tuples_per_sec=stats.mean_throughput,
        p99_emit_ms=0.0,                    # filled by latency_stats below
        n_windows_emitted=n_emitted, n_tuples=n_tuples, wall_s=wall)
    for k, v in latency_stats(stats.emit_latencies_ms).items():
        setattr(res, k, v)
    first_emit_stats(res, fe_lats)
    # engines without hook points (Simulator/Hybrid host paths) still
    # report harness-known ingest totals
    finalize_observability(res, obs, stats.emit_latencies_ms, n_emitted,
                           n_tuples=None if op_has_obs else n_tuples)
    return res
