"""Window-bucket baseline engine — the Flink-buckets analogue.

The reference's ≥10× claim is anchored by a baseline that keeps one
independent bucket per concurrent window and never shares partial aggregates
(FlinkBenchmarkJob.java:16-73: one native ``timeWindow(...).sum(1)`` per
configured window; README.md:47-58 charts). This is that baseline re-done the
straightforward TPU way, deliberately WITHOUT slicing:

* raw tuples are retained in a device ring covering the maximum window span
  (state O(span × rate) — vs the slicing engine's O(#slices));
* every triggered window is answered by a masked reduction over the whole
  ring (work O(#triggers × ring) per watermark — vs the slicing engine's
  O(#slices + #triggers)).

The generator is byte-identical to AlignedStreamPipeline's (same RNG stream,
same slice-row structure — the bucket engine simply doesn't exploit it), so
bucket results are directly comparable to the slicing engine's in
differential tests and the throughput gap is purely algorithmic.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from .. import jax_config  # noqa: F401

from ..core.aggregates import AggregateFunction
from ..core.windows import SlidingWindow, TumblingWindow, WindowMeasure
from ..engine.pipeline import (
    AlignedStreamPipeline,
    FusedPipelineDriver,
    build_trigger_grid,
    draw_uniform16,
    lower_interval,
)


class BucketWindowPipeline(FusedPipelineDriver):
    """Fused per-watermark-interval bucket engine (no aggregate sharing)."""

    def __init__(self, windows: Sequence, aggregations: Sequence[AggregateFunction],
                 throughput: int = 1_000_000, wm_period_ms: int = 1000,
                 seed: int = 0, chunk: int = 1 << 18,
                 value_scale: float = 10_000.0, max_lateness: int = 1000):
        import jax
        import jax.numpy as jnp

        self.windows = list(windows)
        self.aggregations = list(aggregations)
        self.wm_period_ms = wm_period_ms
        self.seed = seed

        max_span = 0
        for w in self.windows:
            if w.measure != WindowMeasure.Time or not isinstance(
                    w, (TumblingWindow, SlidingWindow)):
                raise NotImplementedError(
                    "bucket baseline: Time tumbling/sliding only")
            max_span = max(max_span, w.clear_delay())
        self.aspecs = []
        for a in self.aggregations:
            spec = a.device_spec()
            if spec is None or spec.is_sparse:
                raise NotImplementedError(
                    "bucket baseline: dense aggregations only")
            self.aspecs.append(spec)

        # same grid rule as the slicing pipeline (wm period folded into the
        # gcd, so wm_period_ms % g == 0 by construction — arbitrary window
        # sizes like randomTumbling's are handled, not rejected)
        g = AlignedStreamPipeline.slice_grid(self.windows, wm_period_ms)
        if throughput * g % 1000:
            raise ValueError("throughput not an integer per-slice rate")
        R = throughput * g // 1000
        if R > 1 << 25:
            # the aligned twin switches to sub-row (row, sub)-keyed
            # chunking past its lift budget, so the per-row streams would
            # silently diverge; the bucket baseline is run at far lower
            # offered loads anyway (O(triggers × ring) per watermark)
            raise NotImplementedError(
                "bucket baseline: per-slice rate exceeds the row-granular "
                "generator (the aligned pipeline sub-chunks here and the "
                "streams would differ); lower bucketsThroughput")
        S = wm_period_ms // g
        self.grid, self.R, self.S = g, R, S
        self.tuples_per_interval = S * R
        n_new = S * R

        # ring: enough intervals to cover the widest window + current one
        intervals_needed = -(-(max_span + wm_period_ms) // wm_period_ms) + 1
        N = intervals_needed * n_new
        self.ring_slots = N
        n_ring_chunks = max(1, -(-N // chunk))
        Npad = n_ring_chunks * chunk
        self.hbm_bytes = Npad * 12

        make_triggers, self.T = build_trigger_grid(self.windows, wm_period_ms)
        P = wm_period_ms

        def gen_and_write(ring_ts, ring_vals, key, interval_idx):
            """Generate one interval's tuples (byte-identical RNG stream to
            AlignedStreamPipeline: per-ROW fold_in keys, so it matches the
            aligned pipeline at ANY chunk shape) and write them into the
            ring — the shared body of step() and fill()."""
            base = interval_idx * P

            rows = jnp.arange(S, dtype=jnp.int64)
            keys = jax.vmap(lambda r: jax.random.fold_in(key, r))(rows)
            # byte-identical to AlignedStreamPipeline.gen_rows (r5)
            vals = jax.vmap(lambda k: draw_uniform16(
                k, (R,), value_scale))(keys).reshape(-1)
            row_starts = base + g * rows
            # tuples sit at their row start (the aligned generator emits
            # no offset stream — unobservable on the aligned grid)
            ts = jnp.broadcast_to(row_starts[:, None], (S, R)).reshape(-1)

            slot = (interval_idx % intervals_needed) * n_new
            ring_ts = jax.lax.dynamic_update_slice(
                ring_ts, ts, (slot.astype(jnp.int32),))
            ring_vals = jax.lax.dynamic_update_slice(
                ring_vals, vals, (slot.astype(jnp.int32),))
            return ring_ts, ring_vals

        first_lw = max(0, P - max_lateness)   # first-watermark lateness
                                              # clamp, same rule as the
                                              # engine pipelines

        def step(ring_ts, ring_vals, key, interval_idx):
            base = interval_idx * P
            ring_ts, ring_vals = gen_and_write(ring_ts, ring_vals, key,
                                               interval_idx)
            last_wm = jnp.where(interval_idx > 0, base, jnp.int64(first_lw))
            ws, we, tmask = make_triggers(last_wm, base + P)
            Tn = ws.shape[0]

            def body(carry, c):
                cnt, accs = carry
                t_c = jax.lax.dynamic_slice(ring_ts, (c * chunk,), (chunk,))
                v_c = jax.lax.dynamic_slice(ring_vals, (c * chunk,), (chunk,))
                m = (t_c[None, :] >= ws[:, None]) & (t_c[None, :] < we[:, None])
                cnt = cnt + jnp.sum(m, axis=1, dtype=jnp.int64)
                new_accs = []
                for aspec, acc in zip(self.aspecs, accs):
                    lifted = aspec.lift_dense(v_c)          # [chunk, w]
                    masked = jnp.where(m[:, :, None], lifted[None, :, :],
                                       jnp.asarray(aspec.identity,
                                                   lifted.dtype))
                    if aspec.kind == "sum":
                        new_accs.append(acc + jnp.sum(masked, axis=1))
                    elif aspec.kind == "min":
                        new_accs.append(jnp.minimum(acc,
                                                    jnp.min(masked, axis=1)))
                    else:
                        new_accs.append(jnp.maximum(acc,
                                                    jnp.max(masked, axis=1)))
                return (cnt, tuple(new_accs)), None

            init = (jnp.zeros((Tn,), jnp.int64),
                    tuple(jnp.full((Tn, a.width), a.identity, jnp.float32)
                          for a in self.aspecs))
            (cnt, accs), _ = jax.lax.scan(body, init,
                                          jnp.arange(n_ring_chunks))
            cnt = jnp.where(tmask, cnt, 0)
            accs = tuple(jnp.where(tmask[:, None], a,
                                   jnp.asarray(sp.identity, a.dtype))
                         for sp, a in zip(self.aspecs, accs))
            return ring_ts, ring_vals, (ws, we, cnt, accs)

        self._step = jax.jit(step, donate_argnums=(0, 1))
        # fill: ring write only — pre-roll the window span without paying
        # the O(#triggers × ring) query of a full step
        self._fill = jax.jit(gen_and_write, donate_argnums=(0, 1))
        self._Npad = Npad
        self._root = None
        self._ring = None
        self._interval = 0

    def _init_pipeline_state(self) -> None:
        import jax.numpy as jnp

        self._ring = (jnp.full((self._Npad,), np.int64(1) << 62, jnp.int64),
                      jnp.zeros((self._Npad,), jnp.float32))

    def _step_interval(self, key, i: int):
        rt, rv, res = self._step(*self._ring, key, np.int64(i))
        self._ring = (rt, rv)
        return res

    def _sync_anchor(self):
        return self._ring[0][0]

    def prefill(self, n_intervals: int) -> None:
        if self._needs_reset():
            self.reset()
        for _ in range(n_intervals):
            i = self._interval
            self._ring = self._fill(*self._ring, self._interval_key(i),
                                    np.int64(i))
            self._interval += 1

    def check_overflow(self) -> None:
        pass                       # ring overwrites exactly after the span

    def lowered_results(self, interval_out) -> list:
        return lower_interval(self.aggregations, interval_out)
