"""Seeded offered-load source for the soak harness.

Everything is a pure function of ``(seed, chunk_index)`` — the chaos
discipline of :mod:`scotty_tpu.resilience.chaos`: two soaks with the
same seed offer byte-identical streams, and a restarted run can re-offer
any chunk exactly (the supervised-recovery path rewinds to a checkpoint
offset and replays).

Records are keyed ``(key, value, ts)`` tuples: small-integer float32
values (exact under any aggregation order), event time advancing at the
offered rate. The chaos mix injects the failure classes the resilience
layer claims to survive:

* **late storms** — every Nth chunk's timestamps reach back up to
  ``late_reach_ms`` behind the stream head (annex/shaper pressure);
* **poison** — a seeded fraction of records are malformed (a 2-tuple /
  a non-integral ts) and must take the dead-letter path;
* **flaky** — fetching every Nth chunk raises
  :class:`~scotty_tpu.resilience.chaos.ChaosError` ONCE (the transient
  contract: a retry succeeds);
* **crash** — the consumer-side one-shot crash hook (the supervised
  restart path), fired by the harness after the named chunks land.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Tuple

import numpy as np

from ..resilience.chaos import ChaosError, rng_of


@dataclass(frozen=True)
class ChaosMix:
    """Seeded fault mix for a soak (all off by default — a clean soak)."""

    late_storm_every: int = 0      # every Nth chunk is a late storm
    late_reach_ms: int = 2000      # how far a storm reaches back
    poison_pct: float = 0.0        # fraction of records made malformed
    flaky_every: int = 0           # every Nth chunk fetch fails once
    crash_at_chunks: Tuple[int, ...] = ()   # consumer crashes (one-shot)


@dataclass(frozen=True)
class SourceConfig:
    offered_rate: float = 2000.0   # records per clock-second
    chunk_records: int = 256
    n_keys: int = 8
    seed: int = 0
    value_hi: int = 256
    chaos: ChaosMix = field(default_factory=ChaosMix)


class SoakSource:
    """``chunk(i)`` → the i-th record chunk (pure in ``(seed, i)``, minus
    the one-shot flaky set). ``due_s(i)`` → the clock second chunk i is
    due at the offered rate."""

    def __init__(self, config: SourceConfig):
        self.config = config
        self._flaky_fired: set = set()

    def due_s(self, i: int) -> float:
        c = self.config
        return i * c.chunk_records / c.offered_rate

    def chunk(self, i: int) -> List[Tuple]:
        c = self.config
        mix = c.chaos
        if mix.flaky_every and i > 0 and i % mix.flaky_every == 0 \
                and i not in self._flaky_fired:
            self._flaky_fired.add(i)
            raise ChaosError(f"injected transient source failure at "
                             f"chunk {i}")
        rng = rng_of(c.seed + 0x50AC + i)
        n = c.chunk_records
        base_ms = int(self.due_s(i) * 1000)
        span_ms = max(1, int(n / c.offered_rate * 1000))
        ts = base_ms + np.sort(rng.integers(0, span_ms, size=n))
        if mix.late_storm_every and i > 0 \
                and i % mix.late_storm_every == 0:
            # the whole chunk reaches back behind the stream head
            ts = np.maximum(ts - int(rng.integers(1, mix.late_reach_ms + 1)),
                            0)
        keys = rng.integers(0, c.n_keys, size=n)
        vals = rng.integers(0, c.value_hi, size=n)
        recs: List[Tuple] = [
            (f"k{int(k)}", float(v), int(t))
            for k, v, t in zip(keys, vals, ts)]
        if mix.poison_pct > 0:
            n_bad = max(1, int(n * mix.poison_pct))
            for j in rng.choice(n, size=n_bad, replace=False):
                k, v, t = recs[j]
                # alternate malformations: wrong arity / non-integral ts
                recs[j] = (k, v) if int(j) % 2 == 0 else (k, v, "not-a-ts")
        return recs
