"""Soak/endurance harness (ISSUE 7): prove the system survives sustained
external load — no silent drops, no unbounded queues, no leaks.

* :mod:`.source` — seeded offered-load record source with the chaos mix
  (late storms / poison / flaky fetches / one-shot consumer crashes).
* :mod:`.invariants` — the audit functions: exact tuple conservation,
  watermark monotonicity, ring boundedness, the memory ratchet, the
  sink-duplicate audit and the checkpoint-dir disk ratchet (ISSUE 8).
* :mod:`.harness` — :class:`SoakRunner` / :func:`run_soak`: the paced
  loop on the injectable Clock, under the Supervisor's checkpoint /
  restart discipline, polling ``/healthz``, failing fast on any audit
  finding, and writing the evidence bundle even on success.
"""

from .harness import (
    ConnectorSoakTarget,
    SoakConfig,
    SoakInvariantViolation,
    SoakRunner,
    run_soak,
)
from .invariants import (
    check_conservation,
    check_disk_bounded,
    check_memory_ratchet,
    check_ring_bounded,
    check_sink_duplicates,
    check_watermark_monotone,
    live_objects,
    rss_bytes,
)
from .source import ChaosMix, SoakSource, SourceConfig

__all__ = [
    "SoakConfig", "SoakRunner", "SoakInvariantViolation", "run_soak",
    "ConnectorSoakTarget", "ChaosMix", "SoakSource", "SourceConfig",
    "check_conservation", "check_watermark_monotone",
    "check_ring_bounded", "check_memory_ratchet",
    "check_sink_duplicates", "check_disk_bounded",
    "rss_bytes", "live_objects",
]
