"""Soak invariant audits: what must hold at EVERY audit, exactly.

Each audit function returns a list of finding dicts
(``{"invariant": ..., "detail": ...}``) — empty means the invariant
held. The harness runs them on the injectable clock's cadence and fails
the soak on any finding (with a postmortem naming it), so an invariant
violation can never ride out an hours-long run unnoticed.

* :func:`check_conservation` — the tuple-conservation identity
  ``seen == delivered + shed + held + dead_lettered``, EXACT (every term
  is an integer maintained by construction; one missing tuple fails the
  audit).
* :func:`check_watermark_monotone` — the watermark history never goes
  backward.
* :func:`check_ring_bounded` — ring occupancy (and its high-water) never
  exceeds the configured ``depth × block_size`` bound.
* :func:`check_memory_ratchet` — RSS and live-object count must plateau:
  a window of ``ratchet_audits`` consecutive strictly-increasing
  readings past the grace window whose total growth exceeds the slack is
  a leak signature, reported with the trend values.
* :func:`check_sink_duplicates` (ISSUE 8) — every ``(epoch, seq)`` tag
  the exactly-once sink delivered downstream was observed AT MOST once
  across all restarts; a re-delivered tag is a duplicate the suppression
  horizon failed to catch, named exactly.
* :func:`check_disk_bounded` (ISSUE 8) — the checkpoint directory holds
  no more generations than the Supervisor's retention policy
  (``keep_checkpoints``) allows: an hours-long soak must not grow disk
  the way PR 7's ratchet forbids growing RSS.
"""

from __future__ import annotations

import gc
import os
from typing import List, Mapping, Optional, Tuple


def rss_bytes() -> int:
    """Current resident set size (Linux ``/proc/self/statm``; falls back
    to the ``ru_maxrss`` HIGH-WATER elsewhere — still a valid ratchet
    signal, only less prompt to plateau)."""
    try:
        with open("/proc/self/statm") as f:
            return int(f.read().split()[1]) * os.sysconf("SC_PAGE_SIZE")
    except (OSError, ValueError, IndexError):
        import resource
        import sys

        ru = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        # ru_maxrss is KILOBYTES on Linux but BYTES on macOS — an
        # unconditional *1024 would inflate darwin readings 1024x and
        # trip the ratchet's slack on benign growth
        return ru if sys.platform == "darwin" else ru * 1024


def live_objects() -> int:
    """Collector-visible live objects after a full collection — the
    Python-heap side of the ratchet (a container leak grows it even when
    the allocator hides RSS growth behind freelists)."""
    gc.collect()
    return len(gc.get_objects())


def check_conservation(seen: int, delivered: int, shed: int, held: int,
                       dead_lettered: int) -> List[dict]:
    rhs = delivered + shed + held + dead_lettered
    if seen == rhs:
        return []
    return [{
        "invariant": "tuple_conservation",
        "detail": (f"seen={seen} != delivered={delivered} + shed={shed} "
                   f"+ held={held} + dead_lettered={dead_lettered} "
                   f"(= {rhs}; {seen - rhs:+d} tuples unaccounted)")}]


def check_watermark_monotone(history: List[Optional[int]]) -> List[dict]:
    prev = None
    for i, wm in enumerate(history):
        if wm is None:
            continue
        if prev is not None and wm < prev:
            return [{
                "invariant": "watermark_monotonicity",
                "detail": (f"watermark went backward at audit {i}: "
                           f"{prev} -> {wm}")}]
        prev = wm
    return []


def check_ring_bounded(snapshot: dict) -> List[dict]:
    bound = snapshot["depth"] * snapshot["block_size"]
    findings = []
    for key in ("occupancy", "highwater"):
        if snapshot[key] > bound:
            findings.append({
                "invariant": "ring_bounded",
                "detail": (f"ring {key}={snapshot[key]} exceeds the "
                           f"configured bound depth*block_size={bound}")})
    return findings


def check_sink_duplicates(tag_counts: Mapping[Tuple[int, int], int]
                          ) -> List[dict]:
    """``tag_counts`` maps each ``(epoch, seq)`` tag the sink handed
    downstream to how many times it was observed. Any tag observed more
    than once is a duplicate that reached the consumer — the exact
    failure the exactly-once ledger exists to prevent; the finding names
    the worst offenders so the postmortem can be lined up against the
    flight ring's ``emit``/``duplicate_suppressed`` events."""
    dupes = {t: c for t, c in tag_counts.items() if c > 1}
    if not dupes:
        return []
    worst = sorted(dupes.items(), key=lambda kv: (-kv[1], kv[0]))[:5]
    return [{
        "invariant": "sink_duplicates",
        "detail": (f"{len(dupes)} (epoch, seq) tag(s) delivered more "
                   f"than once — worst: "
                   + ", ".join(f"{t} x{c}" for t, c in worst))}]


def check_disk_bounded(ckpt_dir: str, keep_checkpoints: int) -> List[dict]:
    """The checkpoint-dir disk ratchet: committed generations must stay
    within the Supervisor's retention policy (GC bounds them after every
    commit; more on disk than ``keep_checkpoints`` means GC stopped
    working and an hours-long soak grows disk without bound). Stale
    ``*.tmp`` staging dirs are NOT findings here — one may legitimately
    exist between a crashed save and the next commit's sweep; fsck
    flags the long-lived ones."""
    from ..utils.checkpoint import list_generations

    # oldest-first (the Supervisor's scan, reversed) for the evidence
    gens = list(reversed(list_generations(ckpt_dir)))
    if len(gens) <= keep_checkpoints:
        return []
    return [{
        "invariant": "disk_bounded",
        "detail": (f"{len(gens)} checkpoint generations on disk exceed "
                   f"the retention policy keep_checkpoints="
                   f"{keep_checkpoints}: {gens}")}]


def check_memory_ratchet(history: List[dict], grace_audits: int,
                         ratchet_audits: int, rss_slack_bytes: float,
                         objects_slack: int) -> List[dict]:
    """``history`` rows are ``{"rss": bytes, "objects": n}`` per audit.
    A leak signature = the last ``ratchet_audits`` readings (all past
    the grace window) strictly increasing with total growth beyond the
    slack. The returned finding names the trend so the postmortem is
    directly actionable."""
    if len(history) < grace_audits + ratchet_audits:
        return []
    window = history[-ratchet_audits:]
    findings = []
    for key, slack, unit in (("rss", rss_slack_bytes, "bytes"),
                             ("objects", objects_slack, "objects")):
        vals = [row[key] for row in window]
        monotone = all(b > a for a, b in zip(vals, vals[1:]))
        growth = vals[-1] - vals[0]
        if monotone and growth > slack:
            findings.append({
                "invariant": "memory_ratchet",
                "detail": (f"{key} ratcheted monotonically over the last "
                           f"{ratchet_audits} audits: {vals} "
                           f"(+{growth} {unit} > slack {slack})")})
    return findings
