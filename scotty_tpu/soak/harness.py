"""The soak harness: sustained offered load + invariant audits + evidence.

``SoakRunner`` drives a windowing target at a configured offered rate
for a configured duration on the **injectable Clock** — seconds of
virtual time in CI (``ManualClock``: the smoke soak is deterministic and
fast), hours of wall time on a real box (``SystemClock``) — under the
PR 3 :class:`~scotty_tpu.resilience.supervisor.Supervisor`'s checkpoint
/ restart / give-up discipline, with the seeded chaos mix of
:mod:`.source` turned on or off per run.

Every ``audit_every_s`` the runner proves, not assumes:

* **tuple conservation** (exact): ``seen == delivered + shed + held +
  dead_lettered (+ abandoned)`` — ``abandoned`` counts records a
  crashed target generation had staged but not delivered; the
  checkpoint rewind re-offers them, so it stays 0 in crash-free soaks
  and the identity is the ISSUE 7 contract verbatim;
* **watermark monotonicity**;
* **ring boundedness** (occupancy and high-water vs depth × block_size);
* **memory ratchet**: RSS + live-object readings must plateau — a
  monotone ratchet past the grace window fails the soak with the trend
  in the finding;
* **sink duplicates** (ISSUE 8, with ``delivery="exactly_once"``): every
  ``(epoch, seq)`` tag the transactional sink handed downstream was
  observed at most once across all restarts;
* **disk boundedness** (ISSUE 8): the checkpoint dir's committed
  generations stay within the Supervisor's ``keep_checkpoints``
  retention — the disk analogue of the RSS ratchet.

``/healthz`` is polled on every audit when serving is enabled. Any
invariant failure stops the soak (configurable), counts
``soak_invariant_failures`` (gated by the default ``obs diff``), and
dumps a flight-recorder postmortem. The artifact bundle —
``soak_report.json`` with the audit history, counters, healthz history
and findings, plus the flight snapshot — is written **even on
success**: a clean soak's evidence is as load-bearing as a failed one's.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field
from typing import Callable, List, Optional

from .. import obs as _obs
from ..obs import flight as _flight
from ..ingest import RingConfig, RingIngestor
from ..resilience.chaos import ChaosError
from ..resilience.clock import Clock, SystemClock, wall_time
from .invariants import (
    check_conservation,
    check_disk_bounded,
    check_memory_ratchet,
    check_ring_bounded,
    check_sink_duplicates,
    check_watermark_monotone,
    live_objects,
    rss_bytes,
)
from .source import ChaosMix, SoakSource, SourceConfig


class SoakInvariantViolation(RuntimeError):
    """An audit found a violated invariant; carries the findings."""

    def __init__(self, findings: List[dict]):
        super().__init__("; ".join(
            f"{f['invariant']}: {f['detail']}" for f in findings))
        self.findings = findings


@dataclass(frozen=True)
class SoakConfig:
    """One soak's shape. Durations/rates are CLOCK units — a ManualClock
    makes ``duration_s=3600`` a fast deterministic run; a SystemClock
    makes it a real hour."""

    duration_s: float = 60.0
    offered_rate: float = 2000.0
    chunk_records: int = 256
    audit_every_s: float = 5.0
    seed: int = 0
    n_keys: int = 8
    chaos: ChaosMix = field(default_factory=ChaosMix)
    ring: RingConfig = field(default_factory=RingConfig)
    window_ms: int = 1000
    allowed_lateness: int = 5000
    max_delay_ms: Optional[float] = 200.0     # accumulator flush deadline
    slack_ms: int = 0
    serve_healthz: bool = True
    checkpoint_every_audits: int = 4          # 0 = no supervisor ckpts
    max_restarts: int = 3
    stop_on_failure: bool = True
    # delivery guarantee (ISSUE 8): "exactly_once" arms a
    # TransactionalSink around the target's emissions — its epoch
    # ledger commits inside every supervisor checkpoint, replayed
    # duplicates after a restart are suppressed, and the sink-duplicate
    # audit proves no (epoch, seq) tag ever reached the consumer twice
    delivery: str = "at_least_once"
    keep_checkpoints: int = 3                 # supervisor lineage depth
    # memory-ratchet knobs (slacks sized so a healthy CI run never
    # false-positives; the leak-detection path is tested with tight
    # slacks + an injected leak)
    mem_grace_audits: int = 3
    mem_ratchet_audits: int = 5
    rss_slack_mb: float = 64.0
    objects_slack: int = 100_000


class ConnectorSoakTarget:
    """Default target: a keyed connector operator behind the ingest ring
    (the exact production edge ISSUE 7 hardens). Custom pipelines plug
    in via ``SoakRunner(make_target=...)`` with the same face."""

    def __init__(self, cfg: SoakConfig, obs, clock: Clock):
        from ..connectors.base import (AscendingWatermarks,
                                       KeyedScottyWindowOperator)
        from ..core.aggregates import SumAggregation
        from ..core.windows import TumblingWindow, WindowMeasure
        from ..resilience.connectors import PoisonHandler
        from ..shaper import ShaperConfig

        self.obs = obs
        self.clock = clock
        self.op = KeyedScottyWindowOperator(
            windows=[TumblingWindow(WindowMeasure.Time, cfg.window_ms)],
            aggregations=[SumAggregation()],
            allowed_lateness=cfg.allowed_lateness,
            watermark_policy=AscendingWatermarks(), obs=obs)
        if cfg.max_delay_ms is not None or cfg.slack_ms:
            B = cfg.ring.block_size or 1024
            self.op.attach_shaper(
                ShaperConfig(slack_ms=cfg.slack_ms,
                             max_delay_ms=cfg.max_delay_ms,
                             batch_size=B), clock=clock)
        # count, never retain: an hours-long soak must not grow memory
        # proportional to its own output — the harness exists to prove
        # the opposite (window emission totals live in the obs counters;
        # exact shed counts in the ring's ``shed``)
        self.windows_emitted = 0
        #: optional TransactionalSink (ISSUE 8) every emission passes
        self.sink = None
        self.poison = PoisonHandler(obs=obs)
        self.ring = RingIngestor.for_sink(
            cfg.ring,
            lambda keys, vals, tss: self._emit(
                self.op.process_block(keys, vals, tss)),
            keyed=True, obs=obs, clock=clock)

    def attach_sink(self, sink) -> None:
        """Arm the exactly-once output boundary: every emission passes
        ``sink.emit`` before it counts as delivered downstream."""
        self.sink = sink

    def _emit(self, items) -> None:
        if self.sink is None:
            self.windows_emitted += len(items)
            return

        def deliver(_item):
            self.windows_emitted += 1

        # per-item handoff (sink.drain_into): each delivered item counts
        # before the next emission's flight event — a crash site — fires
        self.sink.drain_into(items, deliver)

    def offer_chunk(self, recs) -> None:
        for rec in recs:
            try:
                key, value, ts = rec
                ts = int(ts)
            except (TypeError, ValueError) as e:
                self.poison.handle(rec, e)
                continue
            self.ring.offer_one(value, ts, key)

    def poll(self) -> None:
        self.ring.poll()
        self._emit(self.op.poll_shaper())

    def drain(self) -> None:
        self.ring.drain()
        self._emit(self.op.drain_shaper())

    @property
    def held(self) -> int:
        # staged between the source and the operator: the RING only.
        # Records in the operator's shaper accumulator already count as
        # delivered input (the ring handed them over); their own
        # exactness is the shaper differential suite's contract, their
        # drain-to-zero at stream end is asserted via shaper_held, and
        # counting them here too would double an audit's right-hand side
        # the moment an idle tick moves a partial block along.
        return self.ring.ring.occupancy

    def audit_terms(self) -> dict:
        return {"delivered": self.ring.ring.delivered,
                "shed": self.ring.shed,
                "held": self.held,
                "dead_lettered": self.poison.count}

    def watermark(self) -> Optional[int]:
        return self.op.policy.current_watermark()

    def check(self) -> None:
        self.ring.check()

    def save(self, path: str) -> None:
        self.drain()               # staged records count as consumed
        self.op.save(path)

    def restore(self, path: str) -> None:
        self.op.restore(path)


class SoakRunner:
    """Run one soak (module docstring). ``report_dir`` receives the
    artifact bundle; ``make_target(cfg, obs, clock)`` overrides the
    default connector target."""

    def __init__(self, config: SoakConfig, clock: Optional[Clock] = None,
                 obs=None, report_dir: Optional[str] = None,
                 make_target: Optional[Callable] = None,
                 audit_hook: Optional[Callable] = None):
        self.config = config
        self.clock = clock or SystemClock()
        if obs is None:
            obs = _obs.Observability(
                flight=_obs.FlightRecorder(capacity=4096, clock=self.clock),
                postmortem_dir=report_dir)
        self.obs = obs
        self.report_dir = report_dir
        self.make_target = make_target or ConnectorSoakTarget
        #: test seam: called after each audit with (runner, audit_row) —
        #: the leak-injection tests grow state here
        self.audit_hook = audit_hook
        self.source = SoakSource(SourceConfig(
            offered_rate=config.offered_rate,
            chunk_records=config.chunk_records, n_keys=config.n_keys,
            seed=config.seed, chaos=config.chaos))
        self.supervisor = None
        if report_dir is not None and config.checkpoint_every_audits:
            from ..resilience.supervisor import Supervisor

            self.supervisor = Supervisor(
                os.path.join(report_dir, "checkpoints"), clock=self.clock,
                obs=self.obs, max_restarts=config.max_restarts,
                seed=config.seed,
                keep_checkpoints=config.keep_checkpoints)
        # exactly-once delivery (ISSUE 8): the sink outlives target
        # generations (it belongs to the runner), its ledger commits
        # inside every supervisor checkpoint, and every tag it hands
        # downstream is recorded for the sink-duplicate audit
        self.sink = None
        self.sink_tags: dict = {}
        if config.delivery not in ("at_least_once", "exactly_once"):
            raise ValueError(
                f"SoakConfig.delivery must be 'at_least_once' or "
                f"'exactly_once', got {config.delivery!r}")
        if config.delivery == "exactly_once":
            from ..delivery import EXACTLY_ONCE, TransactionalSink

            def _observe(item, epoch, seq):
                tag = (epoch, seq)
                self.sink_tags[tag] = self.sink_tags.get(tag, 0) + 1

            self.sink = TransactionalSink(deliver=_observe,
                                          mode=EXACTLY_ONCE, obs=self.obs)
            if self.supervisor is not None:
                self.supervisor.sink = self.sink
        # lifetime accounting across target generations (restarts)
        self.seen = 0
        self.abandoned = 0
        self._base_terms = {"delivered": 0, "shed": 0, "dead_lettered": 0}
        self._crashes_fired: set = set()
        # audit state
        self.audits: List[dict] = []
        self.findings: List[dict] = []
        self.wm_history: List[Optional[int]] = []
        self.mem_history: List[dict] = []
        self.healthz_history: List[dict] = []
        self._server = None

    # -- accounting --------------------------------------------------------
    def _terms(self, target) -> dict:
        cur = target.audit_terms()
        return {
            "seen": self.seen,
            "delivered": self._base_terms["delivered"] + cur["delivered"],
            "shed": self._base_terms["shed"] + cur["shed"],
            "held": cur["held"],
            "dead_lettered": (self._base_terms["dead_lettered"]
                              + cur["dead_lettered"]),
            "abandoned": self.abandoned,
        }

    def _retire_target(self, target) -> None:
        """A generation crashed: bank its delivered/shed/dead totals and
        count what it had staged but never delivered as ABANDONED (the
        rewind re-offers those records, so end-to-end nothing is lost —
        and the audit identity stays exact through the restart)."""
        cur = target.audit_terms()
        for k in self._base_terms:
            self._base_terms[k] += cur[k]
        self.abandoned += cur["held"]

    # -- audits ------------------------------------------------------------
    def _audit(self, target, idx: int) -> List[dict]:
        cfg = self.config
        target.poll()
        target.check()
        terms = self._terms(target)
        self.wm_history.append(target.watermark())
        self.mem_history.append({"rss": rss_bytes(),
                                 "objects": live_objects()})
        findings: List[dict] = []
        findings += check_conservation(
            terms["seen"],
            terms["delivered"], terms["shed"], terms["held"],
            terms["dead_lettered"] + terms["abandoned"])
        findings += check_watermark_monotone(self.wm_history)
        findings += check_ring_bounded(target.ring.ring.snapshot())
        findings += check_memory_ratchet(
            self.mem_history, cfg.mem_grace_audits,
            cfg.mem_ratchet_audits, cfg.rss_slack_mb * 1e6,
            cfg.objects_slack)
        if self.sink is not None:
            findings += check_sink_duplicates(self.sink_tags)
        if self.supervisor is not None:
            findings += check_disk_bounded(self.supervisor.dir,
                                           cfg.keep_checkpoints)
        health = self._probe_healthz()
        row = {"audit": idx, "clock_s": self.clock.now(), "terms": terms,
               "watermark": self.wm_history[-1],
               "ring": target.ring.ring.snapshot(),
               "memory": self.mem_history[-1], "healthz": health,
               "findings": findings}
        if self.sink is not None:
            row["delivery"] = self.sink.snapshot()
        self.audits.append(row)
        self.obs.counter(_obs.SOAK_AUDITS).inc()
        self.obs.flight_event(_flight.SOAK_AUDIT, "audit", float(idx))
        if findings:
            self.obs.counter(_obs.SOAK_INVARIANT_FAILURES).inc(
                len(findings))
            for f in findings:
                self.obs.flight_event(_flight.SOAK_INVARIANT,
                                      f["invariant"])
            self.findings.extend(findings)
        if self.audit_hook is not None:
            self.audit_hook(self, row)
        return findings

    def _probe_healthz(self) -> Optional[dict]:
        if self._server is None:
            return None
        import urllib.error
        import urllib.request

        url = f"http://127.0.0.1:{self._server.port}/healthz"
        try:
            with urllib.request.urlopen(url, timeout=5) as resp:
                body = json.loads(resp.read().decode())
                code = resp.status
        except urllib.error.HTTPError as e:     # 503 = unhealthy verdict
            code = e.code
            try:
                body = json.loads(e.read().decode())
            # scotty: allow(silent-drop) — a non-JSON /healthz error
            # page is itself evidence; the row still lands in
            # healthz_history below, never a soak killer
            except Exception:   # noqa: BLE001
                body = {}
        # scotty: allow(silent-drop) — the probe error is captured into
        # the healthz_history row (status None); probing must not kill
        # the soak whose health it reports
        except Exception as e:      # noqa: BLE001
            body, code = {"error": str(e)}, None
        row = {"clock_s": self.clock.now(), "status": code,
               "healthy": body.get("healthy")}
        self.healthz_history.append(row)
        return row

    # -- the loop ----------------------------------------------------------
    def run(self) -> dict:
        cfg = self.config
        target = self.make_target(cfg, self.obs, self.clock)
        if self.sink is not None and hasattr(target, "attach_sink"):
            target.attach_sink(self.sink)
        if cfg.serve_healthz:
            self._server = self.obs.serve(port=0)
        t0 = self.clock.now()
        next_audit = cfg.audit_every_s
        audit_idx = 0
        last_ckpt_audit = 0
        i = 0                       # chunk cursor (the source offset)
        error: Optional[BaseException] = None
        try:
            while self.source.due_s(i) < cfg.duration_s:
                due = self.source.due_s(i)
                now = self.clock.now() - t0
                if now < due:
                    self.clock.sleep(due - now)
                try:
                    recs = self.source.chunk(i)
                except ChaosError:
                    self.obs.counter(
                        _obs.RESILIENCE_SOURCE_RETRIES).inc()
                    self.obs.flight_event(_flight.RETRY, "soak_source",
                                          float(i))
                    continue        # transient: retry the same chunk
                try:
                    self.seen += len(recs)
                    self.obs.counter(_obs.SOAK_RECORDS_SEEN).inc(
                        len(recs))
                    target.offer_chunk(recs)
                    target.poll()
                    if i in cfg.chaos.crash_at_chunks \
                            and i not in self._crashes_fired:
                        self._crashes_fired.add(i)
                        raise ChaosError(
                            f"injected consumer crash after chunk {i}")
                except ChaosError as e:
                    target, i = self._recover(target, e, i)
                    continue
                i += 1
                while self.clock.now() - t0 >= next_audit:
                    audit_idx += 1
                    findings = self._audit(target, audit_idx)
                    next_audit += cfg.audit_every_s
                    if findings and cfg.stop_on_failure:
                        raise SoakInvariantViolation(findings)
                    if self.supervisor is not None \
                            and cfg.checkpoint_every_audits \
                            and audit_idx - last_ckpt_audit \
                            >= cfg.checkpoint_every_audits:
                        last_ckpt_audit = audit_idx
                        self.supervisor.commit_checkpoint(
                            audit_idx,
                            lambda d: target.save(d),  # noqa: B023
                            offset=i)
            target.drain()
            audit_idx += 1
            findings = self._audit(target, audit_idx)
            if findings and cfg.stop_on_failure:
                raise SoakInvariantViolation(findings)
        except BaseException as e:          # noqa: BLE001 — evidence path
            error = e
            self.obs.record_failure(
                e, kind=_flight.SOAK_INVARIANT
                if isinstance(e, SoakInvariantViolation)
                else _flight.CRASH)
            if not isinstance(e, SoakInvariantViolation):
                raise
        finally:
            if self._server is not None:
                self._server.close()
                self._server = None
            # ONE report document: the on-disk evidence bundle must be
            # byte-identical to what the caller receives/embeds
            final = self.report(error)
            self._write_artifacts(final)
        return final

    def _recover(self, target, exc, i: int):
        """Supervised restart: bank the crashed generation's accounting,
        back off (restart counters + postmortem + give-up), rebuild,
        restore the last checkpoint and rewind the source cursor to its
        offset."""
        self._retire_target(target)
        if self.supervisor is None:
            raise exc
        self.supervisor.handle_failure(exc)     # SupervisorGaveUp raises
        # restoring a checkpoint legitimately REWINDS the watermark to
        # the committed offset — monotonicity is a per-generation
        # invariant, so the audit baseline restarts here (the audit rows
        # already written keep the pre-crash watermarks as evidence)
        self.wm_history.clear()
        fresh = self.make_target(self.config, self.obs, self.clock)
        if self.sink is not None and hasattr(fresh, "attach_sink"):
            fresh.attach_sink(self.sink)
        ckpt = self.supervisor.latest_checkpoint()
        offset = 0
        if ckpt is not None:
            d, offset = ckpt
            fresh.restore(d)
            if self.sink is not None:
                # rewind (epoch, seq) numbering to the restored ledger;
                # the delivered high-water stays — it is the suppression
                # horizon that keeps the replay exactly-once
                self.sink.restore(d)
            self.obs.flight_event(_flight.RESTORE, os.path.basename(d),
                                  float(offset))
        elif self.sink is not None:
            self.sink.restore(None)
        return fresh, offset

    # -- artifacts ---------------------------------------------------------
    def report(self, error: Optional[BaseException] = None) -> dict:
        return {
            "schema": "scotty_tpu.soak_report/1",
            "created_t": wall_time(),
            "passed": error is None and not self.findings,
            "error": None if error is None
            else {"type": type(error).__name__, "message": str(error)},
            "config": {
                "duration_s": self.config.duration_s,
                "offered_rate": self.config.offered_rate,
                "chunk_records": self.config.chunk_records,
                "audit_every_s": self.config.audit_every_s,
                "seed": self.config.seed,
                "delivery": self.config.delivery,
                "keep_checkpoints": self.config.keep_checkpoints,
                "ring": {"depth": self.config.ring.depth,
                         "block_size": self.config.ring.block_size,
                         "policy": self.config.ring.policy},
                "chaos": {
                    "late_storm_every": self.config.chaos.late_storm_every,
                    "poison_pct": self.config.chaos.poison_pct,
                    "flaky_every": self.config.chaos.flaky_every,
                    "crash_at_chunks":
                        list(self.config.chaos.crash_at_chunks)},
            },
            "seen": self.seen,
            "audits": self.audits,
            "findings": self.findings,
            "healthz": self.healthz_history,
            "counters": self.obs.snapshot(),
            "delivery": None if self.sink is None else {
                **self.sink.snapshot(),
                "tags_observed": len(self.sink_tags),
                "tags_duplicated": sum(
                    1 for c in self.sink_tags.values() if c > 1)},
        }

    def _write_artifacts(self, report: dict) -> None:
        """The evidence bundle, written EVEN ON SUCCESS (atomic tmp +
        replace, the PR 3/4 discipline)."""
        if self.report_dir is None:
            return
        os.makedirs(self.report_dir, exist_ok=True)
        artifacts = {"soak_report.json": report}
        if self.obs.flight is not None:
            artifacts["flight.json"] = self.obs.flight.snapshot()
        for name, doc in artifacts.items():
            path = os.path.join(self.report_dir, name)
            tmp = f"{path}.tmp.{os.getpid()}"
            # scotty: allow(fsio-discipline) — evidence writer, same
            # exemption as obs.flight.write_postmortem: the bundle is
            # written in the failure path's finally, and an armed fsio
            # fault hook interposing here would fault/mask the very
            # evidence of the outcome it is recording (nothing ever
            # restores from these files)
            with open(tmp, "w") as f:
                # scotty: allow(fsio-discipline) — same evidence
                # exemption
                json.dump(doc, f, indent=1, default=float)
            # scotty: allow(fsio-discipline) — same evidence exemption
            os.replace(tmp, path)


def run_soak(config: SoakConfig, clock: Optional[Clock] = None,
             obs=None, report_dir: Optional[str] = None,
             make_target: Optional[Callable] = None) -> dict:
    """One-call face: build a :class:`SoakRunner`, run it, return the
    report dict (artifacts land in ``report_dir`` either way)."""
    return SoakRunner(config, clock=clock, obs=obs,
                      report_dir=report_dir,
                      make_target=make_target).run()
