"""Process-wide JAX configuration for the engine.

Import this module before tracing any engine-adjacent jitted function:
* ``jax_enable_x64`` — event timestamps are int64 (epoch-ms exceeds int32);
  partial aggregates remain explicit float32.
* persistent compilation cache — kernels are static per window/agg mix, so
  repeat runs (tests, benchmarks) skip XLA compilation entirely.
"""

import os

import jax

jax.config.update("jax_enable_x64", True)

_cache_dir = os.environ.get("SCOTTY_TPU_COMPILE_CACHE",
                            os.path.expanduser("~/.cache/scotty_tpu_xla"))
try:
    jax.config.update("jax_compilation_cache_dir", _cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
except Exception:                      # pragma: no cover - older jax
    pass
