"""Generic device path for forward-context-aware windows.

The reference accepts ANY user window implementing the per-tuple
``WindowContext`` calculus (core/.../ForwardContextAware.java:6-9,
windowContext/WindowContext.java:9-107): ``updateContext`` edits a sorted
list of active ``[start, end]`` windows (shift edges, insert, merge,
delete), the recorded Shift/Add/Delete modifications drive slice repair
(SliceManager.java:89-166), and ``triggerWindows`` emits completed windows
at each watermark.

The TPU-first redesign keeps the session engine's shape (engine/sessions.py:
bounded active-window arrays owning their own partial aggregates — no
data-dependent slice topology to repair) and factors the WINDOW-SPECIFIC
part behind :class:`DeviceContextSpec`: per tuple, the spec's ``decide``
inspects the active-window arrays with pure jax ops and returns a
:class:`ContextDecision` — fold into a row (with optional edge shifts),
merge two adjacent rows, insert a fresh window, or drop (orphan) — which
the generic apply kernel executes as masked array updates inside one
``lax.scan``. This is the same dual-face pattern as
``DeviceAggregateSpec``: the host face (``Window.create_context()``) runs
on the reference-semantics simulator, the device face here, and coherence
between the two is the implementor's contract, pinned by differential
tests (tests/test_context_windows.py).

Sequential per-tuple application is deliberate: the reference calculus is
arrival-order-dependent (same argument as the session late scan,
engine/sessions.py module docstring), and a user-defined decision function
has no general batched form. Windows that admit one (sessions: the
in-order chain) keep their vectorized fast paths; the generic path is the
capability floor, fused into one device program per chunk with no host
round-trips.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from ..core.aggregates import DeviceAggregateSpec
from .core import I64_MAX, I64_MIN
from .sessions import SessionState, init_session_state  # noqa: F401 (re-export)


class ContextDecision(NamedTuple):
    """One tuple's effect on the active-window arrays — the device
    analogue of one ``updateContext`` call. All fields are 0-d arrays.

    Exactly one of ``touch``/``insert``/``drop`` may hold (or none: the
    tuple vanishes from this window family, like the reference's
    fall-through-returning-null); ``merge`` may accompany ``touch``.
    """

    touch: jnp.ndarray      # bool — fold the tuple into row ``row``
    row: jnp.ndarray        # i32 — target row of the fold
    set_first: jnp.ndarray  # i64 — new first for ``row`` (I64_MAX: keep)
    set_last: jnp.ndarray   # i64 — new last for ``row`` (I64_MIN: keep)
    merge: jnp.ndarray      # i32 — merge rows (merge, merge+1); -1: none
    insert: jnp.ndarray     # bool — open a fresh window
    ins_first: jnp.ndarray  # i64
    ins_last: jnp.ndarray   # i64
    drop: jnp.ndarray       # bool — park the tuple in the orphan buffer


class DeviceContextSpec:
    """Device face of a ForwardContextAware/ForwardContextFree window.

    Implementations must be pure jax-traceable functions of their array
    arguments (they run inside jit/scan). ``token`` keys the kernel cache,
    so two windows with equal tokens MUST have identical behavior.
    """

    def token(self):
        raise NotImplementedError

    def decide(self, first: jnp.ndarray, last: jnp.ndarray,
               n: jnp.ndarray, pos: jnp.ndarray) -> ContextDecision:
        """Per-tuple decision over the live rows ``[0, n)`` of the sorted
        (by ``first``) active-window arrays."""
        raise NotImplementedError

    def trigger_done(self, first: jnp.ndarray, last: jnp.ndarray,
                     n: jnp.ndarray, wm: jnp.ndarray) -> jnp.ndarray:
        """bool[K] mask of live rows complete at watermark ``wm``
        (need not be a prefix)."""
        raise NotImplementedError

    def emit_bounds(self, first: jnp.ndarray, last: jnp.ndarray):
        """

        Emitted window bounds ``(ws, we)`` of completed rows (vectorized
        over rows; e.g. sessions emit ``[first, last + gap)``)."""
        raise NotImplementedError

    def orphan_reach(self) -> int:
        """How far below the GC bound an orphaned tuple may still be
        claimed by a future window (sessions: the gap)."""
        raise NotImplementedError

    def clear_delay(self) -> int:
        """GC-bound participation, mirroring ``Window.clear_delay``:
        retention beyond ``orphan_reach()`` is applied by the operator as
        extra slack on the sweep's gc_bound, so orphans survive down to
        ``wm - max_lateness - clear_delay()``."""
        raise NotImplementedError


class SessionDecider(DeviceContextSpec):
    """SessionWindow's calculus through the generic contract — the
    coherence proof that the generic path reproduces the tuned session
    path (pinned by tests), and the template for user windows.
    Decision logic mirrors engine/sessions.py::build_session_late
    (itself replaying SessionWindow.java:40-98)."""

    def __init__(self, gap: int):
        self.gap = int(gap)

    def token(self):
        return ("session", self.gap)

    def decide(self, first, last, n, pos):
        S = first.shape[0]
        gap = jnp.int64(self.gap)
        idx = jnp.arange(S)
        live = idx < n
        reach = live & (first - gap <= pos) & (pos <= last + gap)
        has = reach.any()
        j = jnp.argmax(reach).astype(jnp.int32)
        fj, lj = first[j], last[j]
        inside = has & (fj <= pos) & (pos <= lj)
        ext_s = has & (fj > pos) & (fj - gap < pos)
        ext_e = has & (lj < pos) & (pos <= lj + gap)
        touch = inside | ext_s | ext_e
        jm1 = jnp.maximum(j - 1, 0)
        jp1 = jnp.minimum(j + 1, S - 1)
        merge_pre = ext_s & (j > 0) & (last[jm1] + gap >= pos)
        merge_nxt = ext_e & (j + 1 < n) & (pos + gap >= first[jp1])
        merge = jnp.where(merge_pre, jm1,
                          jnp.where(merge_nxt, j, -1)).astype(jnp.int32)
        return ContextDecision(
            touch=touch, row=j,
            set_first=jnp.where(ext_s, pos, I64_MAX),
            set_last=jnp.where(ext_e, pos, I64_MIN),
            merge=merge,
            insert=~has, ins_first=pos, ins_last=pos,
            drop=has & ~touch)

    def trigger_done(self, first, last, n, wm):
        live = jnp.arange(first.shape[0]) < n
        return live & (last + jnp.int64(self.gap) < wm)

    def emit_bounds(self, first, last):
        return first, last + jnp.int64(self.gap)

    def orphan_reach(self) -> int:
        return self.gap

    def clear_delay(self) -> int:
        return self.gap


class CappedSessionDecider(DeviceContextSpec):
    """Device face of :class:`scotty_tpu.core.windows.CappedSessionWindow`
    (sessions that refuse to grow beyond ``max_span``) — the shipped
    example of a USER-DEFINED context-aware window with both faces."""

    def __init__(self, gap: int, max_span: int):
        self.gap = int(gap)
        self.max_span = int(max_span)

    def token(self):
        return ("capped-session", self.gap, self.max_span)

    def decide(self, first, last, n, pos):
        S = first.shape[0]
        gap = jnp.int64(self.gap)
        cap = jnp.int64(self.max_span)
        idx = jnp.arange(S)
        live = idx < n
        reach = live & (first - gap <= pos) & (pos <= last + gap)
        has = reach.any()
        j = jnp.argmax(reach).astype(jnp.int32)
        fj, lj = first[j], last[j]
        inside = has & (fj <= pos) & (pos <= lj)
        want_s = has & (fj > pos) & (fj - gap < pos)
        want_e = has & (lj < pos) & (pos <= lj + gap)
        fit_s = want_s & (lj - pos <= cap)       # span after start-extension
        fit_e = want_e & (pos - fj <= cap)       # span after end-extension
        touch = inside | fit_s | fit_e
        jm1 = jnp.maximum(j - 1, 0)
        jp1 = jnp.minimum(j + 1, S - 1)
        merge_pre = fit_s & (j > 0) & (last[jm1] + gap >= pos) \
            & (lj - first[jm1] <= cap)           # merged span within cap
        merge_nxt = fit_e & (j + 1 < n) & (pos + gap >= first[jp1]) \
            & (last[jp1] - fj <= cap)
        merge = jnp.where(merge_pre, jm1,
                          jnp.where(merge_nxt, j, -1)).astype(jnp.int32)
        # a declined extension opens a fresh [pos, pos] window instead —
        # capped windows may therefore sit closer than gap to a neighbor
        insert = ~has | (want_s & ~fit_s) | (want_e & ~fit_e)
        return ContextDecision(
            touch=touch, row=j,
            set_first=jnp.where(fit_s, pos, I64_MAX),
            set_last=jnp.where(fit_e, pos, I64_MIN),
            merge=merge,
            insert=insert, ins_first=pos, ins_last=pos,
            drop=has & ~touch & ~insert)

    def trigger_done(self, first, last, n, wm):
        live = jnp.arange(first.shape[0]) < n
        return live & (last + jnp.int64(self.gap) < wm)

    def emit_bounds(self, first, last):
        return first, last + jnp.int64(self.gap)

    def orphan_reach(self) -> int:
        return self.gap

    def clear_delay(self) -> int:
        return self.gap + self.max_span


def build_context_apply(aggs: tuple[DeviceAggregateSpec, ...],
                        spec: DeviceContextSpec, capacity: int):
    """Arrival-order application of a tuple chunk to one context window's
    active arrays: one ``lax.scan``, each step = ``spec.decide`` + the
    generic masked-array application (fold / edge shifts / merge / insert
    / orphan) transplanted from the session late kernel
    (engine/sessions.py::build_session_late)."""
    S = capacity
    idx = jnp.arange(S)

    def _bcast(mask, arr):
        return mask if arr.ndim == 1 else mask[:, None]

    def shift_left(arr, b, flag, fill):
        nxt = jnp.concatenate([arr[1:], jnp.full_like(arr[:1], fill)])
        return jnp.where(_bcast(flag & (idx >= b), arr), nxt, arr)

    def shift_right(arr, p, flag, fill):
        prv = jnp.concatenate([jnp.full_like(arr[:1], fill), arr[:-1]])
        return jnp.where(_bcast(flag & (idx > p), arr), prv, arr)

    def step(st: SessionState, x):
        pos, valid, lifts = x
        d = spec.decide(st.first, st.last, st.n, pos)
        touch = valid & d.touch
        new = valid & d.insert
        dropped = valid & d.drop
        j = jnp.clip(d.row, 0, S - 1)
        onej = idx == j
        first = jnp.where(onej & touch & (d.set_first < I64_MAX),
                          d.set_first, st.first)
        last = jnp.where(onej & touch & (d.set_last > I64_MIN),
                         d.set_last, st.last)
        counts = st.counts + jnp.where(onej & touch, 1, 0)
        partials = []
        for agg, part, lift in zip(aggs, st.partials, lifts):
            if agg.is_sparse:
                col, v = lift
                m2 = (onej & touch)[:, None] \
                    & (jnp.arange(part.shape[1]) == col)[None, :]
            else:
                v = lift
                m2 = (onej & touch)[:, None]
            if agg.kind == "sum":
                part = jnp.where(m2, part + v, part)
            elif agg.kind == "min":
                part = jnp.where(m2, jnp.minimum(part, v), part)
            else:
                part = jnp.where(m2, jnp.maximum(part, v), part)
            partials.append(part)

        # -- merge (at most one per tuple, like the reference) -------------
        do_merge = valid & (d.merge >= 0)
        a = jnp.clip(jnp.where(do_merge, d.merge, 0), 0, S - 1)
        b = a + 1
        onea = idx == a
        last = jnp.where(onea & do_merge, last[jnp.minimum(b, S - 1)], last)
        counts = jnp.where(onea & do_merge,
                           counts[a] + counts[jnp.minimum(b, S - 1)], counts)
        merged = []
        for agg, part in zip(aggs, partials):
            pa = part[a]
            pb = part[jnp.minimum(b, S - 1)]
            comb = (pa + pb if agg.kind == "sum"
                    else jnp.minimum(pa, pb) if agg.kind == "min"
                    else jnp.maximum(pa, pb))
            merged.append(jnp.where((onea & do_merge)[:, None], comb, part))
        first = shift_left(first, b, do_merge, I64_MAX)
        last = shift_left(last, b, do_merge, I64_MIN)
        counts = shift_left(counts, b, do_merge, 0)
        merged = [shift_left(p, b, do_merge, ag.identity)
                  for ag, p in zip(aggs, merged)]

        # -- insert at the sorted position (AFTER equal starts — matching
        # the host face's _add_sorted walk; duplicate-start inserts happen
        # for cap-declined extensions at repeated timestamps) -------------
        p = jnp.searchsorted(first, d.ins_first,
                             side="right").astype(idx.dtype)
        first = shift_right(first, p, new, I64_MAX)
        last = shift_right(last, p, new, I64_MIN)
        counts = shift_right(counts, p, new, 0)
        inserted = []
        for agg, part, lift in zip(aggs, merged, lifts):
            part = shift_right(part, p, new, agg.identity)
            if agg.is_sparse:
                col, v = lift
                m2 = (idx == p)[:, None] \
                    & (jnp.arange(part.shape[1]) == col)[None, :] & new
                base = jnp.where((idx == p)[:, None] & new,
                                 jnp.asarray(agg.identity, part.dtype), part)
                part = jnp.where(m2, v, base)
            else:
                part = jnp.where((idx == p)[:, None] & new, lift, part)
            inserted.append(part)
        onep = idx == p
        first = jnp.where(onep & new, d.ins_first, first)
        last = jnp.where(onep & new, d.ins_last, last)
        counts = jnp.where(onep & new, 1, counts)

        # -- orphan append --------------------------------------------------
        O = st.o_pos.shape[0]
        oidx = jnp.arange(O)
        oneo = (oidx == st.o_n) & dropped
        o_pos = jnp.where(oneo, pos, st.o_pos)
        o_partials = []
        for agg, part, lift in zip(aggs, st.o_partials, lifts):
            if agg.is_sparse:
                col, v = lift
                m2 = oneo[:, None] \
                    & (jnp.arange(part.shape[1]) == col)[None, :]
                base = jnp.where(oneo[:, None],
                                 jnp.asarray(agg.identity, part.dtype), part)
                part = jnp.where(m2, v, base)
            else:
                part = jnp.where(oneo[:, None], lift, part)
            o_partials.append(part)

        n2 = st.n + jnp.where(new, 1, 0) - jnp.where(do_merge, 1, 0)
        o_n2 = st.o_n + jnp.where(dropped, 1, 0)
        overflow = st.overflow | (new & (st.n >= S)) \
            | (dropped & (st.o_n >= O))
        return SessionState(first=first, last=last, counts=counts,
                            partials=tuple(inserted),
                            n=n2.astype(jnp.int32),
                            o_pos=o_pos, o_partials=tuple(o_partials),
                            o_n=o_n2.astype(jnp.int32),
                            overflow=overflow), None

    def apply(st: SessionState, ts: jnp.ndarray, vals: jnp.ndarray,
              valid: jnp.ndarray) -> SessionState:
        lifts = []
        for agg in aggs:
            if agg.is_sparse:
                col, v = agg.lift_sparse(vals)
                lifts.append((col.astype(jnp.int32),
                              jnp.where(valid, v, agg.identity)))
            else:
                lifted = agg.lift_dense(vals)
                lifts.append(jnp.where(valid[:, None], lifted, agg.identity))
        out, _ = jax.lax.scan(step, st, (ts, valid, tuple(lifts)))
        return out

    return apply


def build_context_sweep(aggs: tuple[DeviceAggregateSpec, ...],
                        spec: DeviceContextSpec, capacity: int,
                        emit_cap: int):
    """Watermark trigger for one context window: emit rows the spec marks
    complete (NOT necessarily a prefix — capped windows can interleave),
    recover covered orphans, compact survivors. Same output contract as
    the session sweep: ``(new_state, m, starts[E], ends[E], counts[E],
    partials…[E])``."""
    S, E = capacity, emit_cap

    def sweep(st: SessionState, wm: jnp.ndarray, gc_bound: jnp.ndarray):
        done = spec.trigger_done(st.first, st.last, st.n, wm)
        m = jnp.sum(done.astype(jnp.int32))
        order = jnp.argsort(~done, stable=True)        # done rows first,
        idx = jnp.arange(E)                            # in row (start) order
        sel = order[jnp.clip(idx, 0, S - 1)]
        b_ws, b_we = spec.emit_bounds(st.first[sel], st.last[sel])
        e_starts = jnp.where(idx < m, b_ws, I64_MAX)
        e_ends = jnp.where(idx < m, b_we, I64_MAX)
        e_counts = jnp.where(idx < m, st.counts[sel], 0)
        e_partials = [p[sel] for p in st.partials]
        em_overflow = m > E

        # -- orphan recovery (first covering window claims the orphan) -----
        O = st.o_pos.shape[0]
        o_live = jnp.arange(O) < st.o_n
        cov = (o_live[None, :] & (e_starts[:, None] <= st.o_pos[None, :])
               & (st.o_pos[None, :] < e_ends[:, None]))        # [E, O]
        first_cov = (jnp.cumsum(cov, axis=0) == 1) & cov
        e_counts = e_counts + jnp.sum(first_cov, axis=1)
        for i, (agg, op_) in enumerate(zip(aggs, st.o_partials)):
            if agg.kind == "sum":
                e_partials[i] = e_partials[i] \
                    + first_cov.astype(op_.dtype) @ op_        # [E, w] MXU
            else:
                ident = jnp.asarray(agg.identity, op_.dtype)
                masked = jnp.where(first_cov[:, :, None], op_[None, :, :],
                                   ident)
                red = (jnp.min if agg.kind == "min" else jnp.max)(masked,
                                                                 axis=1)
                e_partials[i] = (jnp.minimum if agg.kind == "min"
                                 else jnp.maximum)(e_partials[i], red)
        consumed = jnp.any(first_cov, axis=0)
        live_mask = (jnp.arange(S) < st.n) & ~done
        cov_live = jnp.any(
            live_mask[:, None] & (st.first[:, None] <= st.o_pos[None, :])
            & (st.o_pos[None, :] < st.last[:, None]
               + jnp.int64(spec.orphan_reach())), axis=0)
        keep_o = o_live & ~consumed \
            & (cov_live | (st.o_pos >= gc_bound - spec.orphan_reach()))
        oorder = jnp.argsort(~keep_o, stable=True)
        o_n2 = jnp.sum(keep_o.astype(jnp.int32)).astype(jnp.int32)
        o_pos2 = jnp.where(jnp.arange(O) < o_n2, st.o_pos[oorder], I64_MAX)
        o_partials2 = tuple(
            jnp.where((jnp.arange(O) < o_n2)[:, None], p[oorder],
                      jnp.asarray(a.identity, p.dtype))
            for a, p in zip(aggs, st.o_partials))

        # -- compact survivors (order-preserving) --------------------------
        keep = (jnp.arange(S) < st.n) & ~done
        korder = jnp.argsort(~keep, stable=True)
        n2 = (st.n - m).astype(jnp.int32)
        krows = jnp.arange(S) < n2

        def compact(a, fill):
            g = a[korder]
            if a.ndim == 1:
                return jnp.where(krows, g, fill)
            return jnp.where(krows[:, None], g, fill)

        new_state = SessionState(
            first=compact(st.first, I64_MAX),
            last=compact(st.last, I64_MIN),
            counts=compact(st.counts, 0),
            partials=tuple(compact(p, a.identity)
                           for a, p in zip(aggs, st.partials)),
            n=n2,
            o_pos=o_pos2, o_partials=o_partials2, o_n=o_n2,
            overflow=st.overflow | em_overflow,
        )
        return new_state, m, e_starts, e_ends, e_counts, tuple(e_partials)

    return sweep
