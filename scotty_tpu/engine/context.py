"""Generic device path for forward-context-aware windows.

The reference accepts ANY user window implementing the per-tuple
``WindowContext`` calculus (core/.../ForwardContextAware.java:6-9,
windowContext/WindowContext.java:9-107): ``updateContext`` edits a sorted
list of active ``[start, end]`` windows (shift edges, insert, merge,
delete), the recorded Shift/Add/Delete modifications drive slice repair
(SliceManager.java:89-166), and ``triggerWindows`` emits completed windows
at each watermark.

The TPU-first redesign keeps the session engine's shape (engine/sessions.py:
bounded active-window arrays owning their own partial aggregates — no
data-dependent slice topology to repair) and factors the WINDOW-SPECIFIC
part behind :class:`DeviceContextSpec`: per tuple, the spec's ``decide``
inspects the active-window arrays with pure jax ops and returns a
:class:`ContextDecision` — fold into a row (with optional edge shifts),
merge two adjacent rows, insert a fresh window, or drop (orphan) — which
the generic apply kernel executes as masked array updates inside one
``lax.scan``. This is the same dual-face pattern as
``DeviceAggregateSpec``: the host face (``Window.create_context()``) runs
on the reference-semantics simulator, the device face here, and coherence
between the two is the implementor's contract, pinned by differential
tests (tests/test_context_windows.py).

Sequential per-tuple application is deliberate: the reference calculus is
arrival-order-dependent (same argument as the session late scan,
engine/sessions.py module docstring), and a user-defined decision function
has no general batched form. Windows that admit one (sessions: the
in-order chain) keep their vectorized fast paths; the generic path is the
capability floor, fused into one device program per chunk with no host
round-trips.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import numpy as np

import jax
import jax.numpy as jnp

from ..core.aggregates import DeviceAggregateSpec
from .core import I64_MAX, I64_MIN
from .sessions import SessionState, init_session_state  # noqa: F401 (re-export)


class SpeculationCert(NamedTuple):
    """Certification enabling the speculative chunked fast path
    (:class:`SpeculativePlanner`) for a chain-certified context spec
    (``inorder_chain_params() is not None``). The implementor certifies:

    * ``reach`` — the interaction bound: two tuples (or a tuple and a
      live row edge) farther than ``reach`` apart in event time can
      never influence each other's ``decide`` outcomes, directly or
      through any window either of them touches (sessions: the gap —
      windows only ever span observed tuple extents, so reach composes).
    * ``order_free`` — when True, an ISOLATED set of tuples (no two
      members, and no member and live-row edge, farther apart than
      ``reach``-connected hulls allow) produces the same final active
      arrays under every arrival order, EXCEPT at exact-``reach``
      start-side collisions (the orphan fall-through) — which the
      planner detects per tuple against the actual arrival order and
      routes to the scan. Plain sessions qualify; the capped calculus
      does not (a cap-decline's split point depends on arrival order),
      so capped specs set False and only arrival-sorted components take
      the fast path.
    * ``trigger_done`` is exactly ``last + reach < wm`` per live row
      (both shipped deciders) — the planner's host mirror prunes rows
      on that rule, in lockstep with the device sweep.
    """

    reach: int
    order_free: bool


class ContextDecision(NamedTuple):
    """One tuple's effect on the active-window arrays — the device
    analogue of one ``updateContext`` call. All fields are 0-d arrays.

    Exactly one of ``touch``/``insert``/``drop`` may hold (or none: the
    tuple vanishes from this window family, like the reference's
    fall-through-returning-null); ``merge`` may accompany ``touch``.
    """

    touch: jnp.ndarray      # bool — fold the tuple into row ``row``
    row: jnp.ndarray        # i32 — target row of the fold
    set_first: jnp.ndarray  # i64 — new first for ``row`` (I64_MAX: keep)
    set_last: jnp.ndarray   # i64 — new last for ``row`` (I64_MIN: keep)
    merge: jnp.ndarray      # i32 — merge rows (merge, merge+1); -1: none
    insert: jnp.ndarray     # bool — open a fresh window
    ins_first: jnp.ndarray  # i64
    ins_last: jnp.ndarray   # i64
    drop: jnp.ndarray       # bool — park the tuple in the orphan buffer


class DeviceContextSpec:
    """Device face of a ForwardContextAware/ForwardContextFree window.

    Implementations must be pure jax-traceable functions of their array
    arguments (they run inside jit/scan). ``token`` keys the kernel cache,
    so two windows with equal tokens MUST have identical behavior.
    """

    def token(self):
        raise NotImplementedError

    def decide(self, first: jnp.ndarray, last: jnp.ndarray,
               n: jnp.ndarray, pos: jnp.ndarray) -> ContextDecision:
        """Per-tuple decision over the live rows ``[0, n)`` of the sorted
        (by ``first``) active-window arrays."""
        raise NotImplementedError

    def trigger_done(self, first: jnp.ndarray, last: jnp.ndarray,
                     n: jnp.ndarray, wm: jnp.ndarray) -> jnp.ndarray:
        """bool[K] mask of live rows complete at watermark ``wm``
        (need not be a prefix)."""
        raise NotImplementedError

    def emit_bounds(self, first: jnp.ndarray, last: jnp.ndarray):
        """

        Emitted window bounds ``(ws, we)`` of completed rows (vectorized
        over rows; e.g. sessions emit ``[first, last + gap)``)."""
        raise NotImplementedError

    def orphan_reach(self) -> int:
        """How far below the GC bound an orphaned tuple may still be
        claimed by a future window (sessions: the gap)."""
        raise NotImplementedError

    def clear_delay(self) -> int:
        """GC-bound participation, mirroring ``Window.clear_delay``:
        retention beyond ``orphan_reach()`` is applied by the operator as
        extra slack on the sweep's gc_bound, so orphans survive down to
        ``wm - max_lateness - clear_delay()``."""
        raise NotImplementedError

    def inorder_chain_params(self):
        """Optional batched fast path — the certification that on a
        SORTED (in-order) stream this window's calculus reduces to the
        greedy gap/span chain: a tuple folds into the newest window
        unless its gap to the previous tuple exceeds ``gap`` or the
        window's span would exceed ``span_cap``, in which case a fresh
        window opens at the tuple. Return ``(gap, span_cap)`` (span_cap
        may be None for uncapped) to enable the vectorized chunk kernel
        (:func:`build_context_chunk` — one device program per chunk, no
        per-tuple scan), or None (default) to stay on the sequential
        scan. Correctness of the certification is the implementor's
        contract, pinned by the differential tests."""
        return None

    def speculation_params(self) -> Optional[SpeculationCert]:
        """Optional speculative chunked batching over OUT-OF-ORDER
        chunks (ISSUE 11): return a :class:`SpeculationCert` to let the
        operator sort each chunk, segment it where ``decide`` provably
        cannot interact across the cut (consecutive sorted timestamps
        more than ``reach`` apart), execute safe segment runs as one
        vectorized chunk-kernel dispatch, and fall back to the
        per-tuple scan only for segments the safety guards reject.
        Requires ``inorder_chain_params()``; None (default) keeps OOO
        chunks on the sequential scan."""
        return None


class SessionDecider(DeviceContextSpec):
    """SessionWindow's calculus through the generic contract — the
    coherence proof that the generic path reproduces the tuned session
    path (pinned by tests), and the template for user windows.
    Decision logic mirrors engine/sessions.py::build_session_late
    (itself replaying SessionWindow.java:40-98)."""

    def __init__(self, gap: int):
        self.gap = int(gap)

    def token(self):
        return ("session", self.gap)

    def decide(self, first, last, n, pos):
        S = first.shape[0]
        gap = jnp.int64(self.gap)
        idx = jnp.arange(S)
        live = idx < n
        reach = live & (first - gap <= pos) & (pos <= last + gap)
        has = reach.any()
        j = jnp.argmax(reach).astype(jnp.int32)
        fj, lj = first[j], last[j]
        inside = has & (fj <= pos) & (pos <= lj)
        ext_s = has & (fj > pos) & (fj - gap < pos)
        ext_e = has & (lj < pos) & (pos <= lj + gap)
        touch = inside | ext_s | ext_e
        jm1 = jnp.maximum(j - 1, 0)
        jp1 = jnp.minimum(j + 1, S - 1)
        merge_pre = ext_s & (j > 0) & (last[jm1] + gap >= pos)
        merge_nxt = ext_e & (j + 1 < n) & (pos + gap >= first[jp1])
        merge = jnp.where(merge_pre, jm1,
                          jnp.where(merge_nxt, j, -1)).astype(jnp.int32)
        return ContextDecision(
            touch=touch, row=j,
            set_first=jnp.where(ext_s, pos, I64_MAX),
            set_last=jnp.where(ext_e, pos, I64_MIN),
            merge=merge,
            insert=~has, ins_first=pos, ins_last=pos,
            drop=has & ~touch)

    def trigger_done(self, first, last, n, wm):
        live = jnp.arange(first.shape[0]) < n
        return live & (last + jnp.int64(self.gap) < wm)

    def emit_bounds(self, first, last):
        return first, last + jnp.int64(self.gap)

    def orphan_reach(self) -> int:
        return self.gap

    def clear_delay(self) -> int:
        return self.gap

    def inorder_chain_params(self):
        # sorted streams only ever extend the newest session or open a
        # new one after a gap — the uncapped chain
        return (self.gap, None)

    def speculation_params(self):
        # the session calculus is arrival-order free within an isolated
        # gap-connected component (merging is confluent, aggregates
        # commute) except at exact-gap start-side collisions — which the
        # planner detects per tuple and routes to the scan
        return SpeculationCert(reach=self.gap, order_free=True)


class CappedSessionDecider(DeviceContextSpec):
    """Device face of :class:`scotty_tpu.core.windows.CappedSessionWindow`
    (sessions that refuse to grow beyond ``max_span``) — the shipped
    example of a USER-DEFINED context-aware window with both faces."""

    def __init__(self, gap: int, max_span: int):
        self.gap = int(gap)
        self.max_span = int(max_span)

    def token(self):
        return ("capped-session", self.gap, self.max_span)

    def decide(self, first, last, n, pos):
        # Priority calculus, mirroring the host face
        # (CappedSessionWindow.CappedContext.update_context): capped
        # windows may sit CLOSER than gap to a neighbor, so "act on the
        # first window in reach" (the plain-session rule) degenerates —
        # a capped-out session keeps winning the reach walk and every
        # later tuple re-inserts a point window. Priority instead:
        # (1) fold into a CONTAINING row; (2) first FITTING extension;
        # (3) cap-declined reach inserts a fresh point window; exact-gap
        # reach (pos == first - gap) orphans, as in plain sessions.
        S = first.shape[0]
        gap = jnp.int64(self.gap)
        cap = jnp.int64(self.max_span)
        idx = jnp.arange(S)
        live = idx < n
        inside_k = live & (first <= pos) & (pos <= last)
        start_side = live & (first > pos) & (first - gap <= pos)
        exact_k = start_side & (first - gap == pos)
        fit_s_k = start_side & ~exact_k & (last - pos <= cap)
        end_side = live & (last < pos) & (pos <= last + gap)
        fit_e_k = end_side & (pos - first <= cap)
        fit_k = fit_s_k | fit_e_k
        has_inside = inside_k.any()
        has_fit = fit_k.any()
        has_decl = ((start_side & ~exact_k & ~fit_s_k)
                    | (end_side & ~fit_e_k)).any()
        has_exact = exact_k.any()
        j = jnp.where(has_inside, jnp.argmax(inside_k),
                      jnp.argmax(fit_k)).astype(jnp.int32)
        touch = has_inside | has_fit
        fs = fit_s_k[j] & ~has_inside
        fe = fit_e_k[j] & ~has_inside
        fj, lj = first[j], last[j]
        jm1 = jnp.maximum(j - 1, 0)
        jp1 = jnp.minimum(j + 1, S - 1)
        merge_pre = fs & (j > 0) & (last[jm1] + gap >= pos) \
            & (lj - first[jm1] <= cap)           # merged span within cap
        merge_nxt = fe & (j + 1 < n) & (pos + gap >= first[jp1]) \
            & (last[jp1] - fj <= cap)
        merge = jnp.where(merge_pre, jm1,
                          jnp.where(merge_nxt, j, -1)).astype(jnp.int32)
        insert = ~touch & (has_decl | ~has_exact)
        return ContextDecision(
            touch=touch, row=j,
            set_first=jnp.where(fs, pos, I64_MAX),
            set_last=jnp.where(fe, pos, I64_MIN),
            merge=merge,
            insert=insert, ins_first=pos, ins_last=pos,
            drop=~touch & ~insert)

    def trigger_done(self, first, last, n, wm):
        live = jnp.arange(first.shape[0]) < n
        return live & (last + jnp.int64(self.gap) < wm)

    def emit_bounds(self, first, last):
        return first, last + jnp.int64(self.gap)

    def orphan_reach(self) -> int:
        return self.gap

    def clear_delay(self) -> int:
        return self.gap + self.max_span

    def inorder_chain_params(self):
        # on a sorted stream the priority calculus reduces to the greedy
        # chain: the newest session extends while within gap AND span;
        # a cap-decline opens the next session at the declining tuple
        # (older rows can never fit when the newest declines — their
        # spans are larger and their reach smaller)
        return (self.gap, self.max_span)

    def speculation_params(self):
        # NOT order-free: a cap-decline's split point depends on arrival
        # order (the same isolated set partitions differently under
        # different orders), so only arrival-sorted components batch
        return SpeculationCert(reach=self.gap, order_free=False)


class SpeculativePlanner:
    """Host-side segmentation + safety classifier for ONE context
    window's speculative chunked batching (ISSUE 11).

    The planner sorts each arrival-order chunk, cuts it into
    interaction components (consecutive sorted timestamps more than
    ``reach`` apart never interact — :class:`SpeculationCert`), proves
    per component that executing it SORTED through the vectorized
    chain kernel (:func:`build_context_chunk`) is equivalent to the
    per-tuple arrival-order scan, and returns a run plan: maximal
    stretches of safe components as single chunk-kernel dispatches,
    unsafe components through the scan in exact arrival order.

    Safety rests on a host mirror of the live-row BOUNDS (first/last
    only — values stay on device) that the planner maintains from the
    same inputs the device kernels consume:

    * chunk runs update the mirror through the exact host replay of the
      chain-kernel walk (:meth:`note_chunk`);
    * per-tuple scan runs make the affected region UNKNOWN: rows with
      ``first <= V`` (``V`` = scanned max + reach — the first-edge
      blast radius) move to a stale set summarized by ``U``, an upper
      bound on every unknown row's ``last`` (scan extensions are
      bounded by the scanned max, so ``U`` stays sound);
    * sweeps prune mirrored rows by the certified trigger rule
      (``last + reach < wm``) and clear the stale region once the
      watermark passes ``U + reach`` (every unknown row has completed
      by then).

    A component is CHUNK-safe iff, against the pre-batch mirror:

    * it cannot touch the stale region (``lo > U + reach``);
    * it cannot touch any known non-top row (``lo > l_second +
      reach``; rows are disjoint and ordered, so the second-newest
      ``last`` bounds them all);
    * if it touches the known top row (``lo <= l_top + reach``) it is
      the FIRST such component, starts inside it (``lo >= f_top`` —
      the chunk kernel never extends a row's start), and no OTHER
      component also touches the top (two components interacting
      through a wide row interact with each other);
    * ``order_free`` specs: no tuple is exposed to the exact-``reach``
      start-side orphan collision under the ACTUAL arrival order (a
      tuple whose exact partner arrived first, with no other in-reach
      tuple or the top row arriving before it);
    * non-``order_free`` specs (capped): the component's arrival order
      is already sorted, so the chunk kernel is the certified in-order
      chain on that stretch.
    """

    #: mirror of build_context_chunk's default segment budget
    MAX_SEGMENTS = 64

    def __init__(self, spec: DeviceContextSpec):
        cert = spec.speculation_params()
        chain = spec.inorder_chain_params()
        if cert is None or chain is None:
            raise ValueError(
                "SpeculativePlanner needs speculation_params() AND "
                "inorder_chain_params() certifications")
        self.reach = int(cert.reach)
        self.order_free = bool(cert.order_free)
        self.gap = int(chain[0])
        self.cap = None if chain[1] is None else int(chain[1])
        if self.reach != self.gap:
            # the component cut doubles as the chain's gap break (a
            # component has no internal break), which needs reach==gap
            raise ValueError(
                "speculation reach must equal the chain gap "
                f"(reach={self.reach}, gap={self.gap})")
        self.first = np.empty(0, np.int64)     # known live-row bounds
        self.last = np.empty(0, np.int64)      # (sorted by first)
        self.stale_u: Optional[int] = None     # unknown-row last bound

    # -- classification ----------------------------------------------------
    def plan(self, tss: np.ndarray):
        """Runs for one arrival-order chunk: ``[("chunk"|"scan",
        idx_array)]`` where chunk indices are ts-sorted and scan indices
        are in arrival order. Components never interact, so processing
        runs in sorted-component order preserves arrival semantics."""
        n = int(tss.size)
        if n == 0:
            return []
        r = self.reach
        order = np.argsort(tss, kind="stable")
        ts_s = tss[order]
        cuts = np.flatnonzero(np.diff(ts_s) > r) + 1
        bounds = np.concatenate(([0], cuts, [n]))
        kf, kl = self.first, self.last
        f_top = int(kf[-1]) if kf.size else None
        l_top = int(kl[-1]) if kl.size else None
        l_second = int(kl[-2]) if kf.size > 1 else None
        U = self.stale_u

        comps = list(zip(bounds[:-1], bounds[1:]))
        # components touching the known top row form a PREFIX (sorted);
        # two of them interact THROUGH the top row, so only a lone
        # top-toucher may batch
        n_top = 0
        if l_top is not None:
            while n_top < len(comps) \
                    and int(ts_s[comps[n_top][0]]) <= l_top + r:
                n_top += 1
        safe_flags = []
        for ci, (a, b) in enumerate(comps):
            lo = int(ts_s[a])
            safe = True
            if U is not None and lo <= U + r:
                safe = False
            elif l_second is not None and lo <= l_second + r:
                safe = False
            elif ci < n_top and (n_top > 1 or lo < f_top):
                # (components beyond the top-zone prefix always have
                # lo > l_top + reach >= f_top, so start containment
                # only binds here)
                safe = False
            if safe and not self.order_free:
                oa = order[a:b]
                if oa.size > 1 and not bool((oa[:-1] < oa[1:]).all()):
                    safe = False
            if safe and self.order_free \
                    and self._orphan_hazard(ts_s, order, int(a), int(b),
                                            l_top):
                safe = False
            safe_flags.append(safe)

        runs = []
        i = 0
        while i < len(comps):
            if safe_flags[i]:
                j = i
                while j + 1 < len(comps) and safe_flags[j + 1]:
                    j += 1
                runs.append(("chunk",
                             order[comps[i][0]:comps[j][1]]))
                i = j + 1
            else:
                # interacting unsafe components (the multi-top prefix)
                # must replay INTERLEAVED in arrival order; isolated
                # unsafe components may too — coalescing adjacent scan
                # components is always arrival-faithful
                j = i
                while j + 1 < len(comps) and not safe_flags[j + 1]:
                    j += 1
                idx = np.sort(
                    np.concatenate([order[a:b]
                                    for (a, b) in comps[i:j + 1]]))
                runs.append(("scan", idx))
                i = j + 1
        return runs

    @staticmethod
    def _range_min(vals: np.ndarray, lo: np.ndarray,
                   hi: np.ndarray) -> np.ndarray:
        """min(vals[lo[i]:hi[i]]) per element (sentinel I64_MAX for
        empty ranges) — a log sparse table, so the hazard check stays
        O(n log n) on dense chunks instead of a per-candidate probe."""
        n = int(vals.size)
        out = np.full(lo.shape, np.iinfo(np.int64).max, np.int64)
        width = hi - lo
        m = width > 0
        if n == 0 or not bool(m.any()):
            return out
        levels = [vals.astype(np.int64)]
        while (1 << len(levels)) <= int(width.max()):
            half = 1 << (len(levels) - 1)
            prev = levels[-1]
            nxt = prev.copy()
            if n > half:
                nxt[:n - half] = np.minimum(prev[:n - half], prev[half:])
            levels.append(nxt)
        j = np.zeros(lo.shape, np.int64)
        j[m] = np.floor(np.log2(width[m])).astype(np.int64)
        for lev in np.unique(j[m]):
            sel = m & (j == lev)
            t = levels[int(lev)]
            a = lo[sel]
            b = hi[sel] - (1 << int(lev))
            out[sel] = np.minimum(t[a], t[np.maximum(b, a)])
        return out

    def _orphan_hazard(self, ts_s, order, a: int, b: int,
                       l_top) -> bool:
        """Exact-``reach`` start-side collision under the ACTUAL arrival
        order: tuple p orphans iff a row starting exactly at
        ``p + reach`` exists at p's arrival with nothing else in reach —
        i.e. p's exact partner arrived first AND no tuple in
        ``[p - reach, p + reach)`` (whose row would touch p) nor the
        live top row (``p <= l_top + reach`` — p >= f_top, so reach is
        touch) precedes p.

        Cost model: dense ms streams have an exact partner for nearly
        EVERY tuple, so the check must not walk candidates one by one.
        An O(n) prefilter settles almost all of them — a sorted
        NEIGHBOR inside the reach window that arrived earlier makes p
        safe, and on mostly-in-order traffic (the late fraction sits
        among earlier-arrived in-order tuples) that covers everything.
        Survivors go through an exact per-candidate probe; a
        pathological candidate count (fully shuffled arrival) switches
        to the O(n log n) sparse-table evaluation instead."""
        r = self.reach
        seg = ts_s[a:b]
        n = seg.size
        oa = order[a:b].astype(np.int64)
        safe = np.zeros(n, bool)
        if n > 1:
            prev_in = np.concatenate(([False], np.diff(seg) <= r))
            prev_early = np.concatenate(([False], oa[:-1] < oa[1:]))
            nxt_in = np.concatenate((np.diff(seg) < r, [False]))
            nxt_early = np.concatenate((oa[1:] < oa[:-1], [False]))
            safe = (prev_in & prev_early) | (nxt_in & nxt_early)
        if l_top is not None:
            safe |= seg <= l_top + r       # the live top row touches p
        ci = np.flatnonzero(~safe)
        if ci.size == 0:
            return False
        pv = seg[ci] + r
        p_lo = np.searchsorted(seg, pv, side="left")
        has = (p_lo < n) & (seg[np.minimum(p_lo, n - 1)] == pv)
        ci, p_lo = ci[has], p_lo[has]
        if ci.size == 0:
            return False
        if ci.size > 4096:
            # adversarially shuffled arrival: evaluate exactly, shared
            # sparse table over the arrival ranks
            p_hi = np.searchsorted(seg, seg + r, side="right")
            pl_f = np.searchsorted(seg, seg + r, side="left")
            w_lo = np.searchsorted(seg, seg - r, side="left")
            partner_min = self._range_min(oa, pl_f, p_hi)
            window_min = self._range_min(oa, w_lo, pl_f)
            hazard = np.zeros(n, bool)
            hazard[ci] = True
            hazard &= (partner_min < oa) & (window_min >= oa)
            return bool(hazard.any())
        for k, i in enumerate(ci):
            t = int(seg[i])
            lo_p = int(p_lo[k])
            hi_p = int(np.searchsorted(seg, t + r, side="right"))
            if int(oa[lo_p:hi_p].min()) > int(oa[i]):
                continue                   # partner row not yet open
            w = int(np.searchsorted(seg, t - r, side="left"))
            if w < lo_p and int(oa[w:lo_p].min()) < int(oa[i]):
                continue                   # an in-reach row precedes p
            return True
        return False

    # -- mirror maintenance ------------------------------------------------
    def note_chunk(self, ts_sorted: np.ndarray) -> None:
        """Exact host replay of the chain-kernel walk
        (:func:`build_context_chunk`) over one sorted chunk run."""
        ts = np.asarray(ts_sorted, np.int64)
        n = int(ts.size)
        if n == 0:
            return
        g, cap, M = self.gap, self.cap, self.MAX_SEGMENTS
        kf, kl = self.first, self.last
        cont = bool(kf.size) and int(ts[0]) <= int(kl[-1]) + g
        if cont and cap is not None:
            cont = int(ts[0]) - int(kf[-1]) <= cap
        brk = np.flatnonzero(np.diff(ts) > g) + 1
        anchor = int(kf[-1]) if cont else int(ts[0])
        segs = []
        cur = 0
        bi = 0
        while cur < n and len(segs) < M:
            while bi < brk.size and int(brk[bi]) <= cur:
                bi += 1
            nb = int(brk[bi]) if bi < brk.size else n
            capi = n if cap is None else int(
                np.searchsorted(ts, anchor + cap, side="right"))
            nxt = max(min(nb, capi, n), cur + 1)
            segs.append((cur, nxt))
            anchor = int(ts[min(nxt, n - 1)])
            cur = nxt
        seg_first = [int(ts[s]) for s, _ in segs]
        seg_last = [int(ts[e - 1]) for _, e in segs]
        start = 0
        if cont and segs:
            kl[-1] = max(int(kl[-1]), seg_last[0])
            start = 1
        if len(segs) > start:
            self.first = np.concatenate([kf, seg_first[start:]])
            self.last = np.concatenate([kl, seg_last[start:]])

    def note_scan(self, tss: np.ndarray) -> None:
        """A per-tuple scan ran: rows with ``first <= scanned max +
        reach`` become unknown (their firsts may drop, new rows may
        appear below); ``U`` bounds every unknown row's last."""
        if tss.size == 0:
            return
        mx = int(np.max(tss))
        v = mx + self.reach
        moved = self.first <= v
        u = mx if self.stale_u is None else max(self.stale_u, mx)
        if bool(moved.any()):
            u = max(u, int(self.last[moved].max()))
            keep = ~moved
            self.first = self.first[keep]
            self.last = self.last[keep]
        self.stale_u = u

    def invalidate(self, met) -> None:
        """Host-opaque state change (device-resident ingest, checkpoint
        restore): every row whose edges could sit at/below ``met``
        becomes unknown."""
        if met is None and self.first.size == 0 \
                and self.stale_u is None:
            return
        u = int(met) if met is not None else 0
        if self.first.size:
            u = max(u, int(self.last.max()))
        if self.stale_u is not None:
            u = max(u, self.stale_u)
        self.first = np.empty(0, np.int64)
        self.last = np.empty(0, np.int64)
        self.stale_u = u

    def sweep(self, wm: int) -> None:
        """Mirror the device sweep: certified trigger rule per known
        row; the stale region clears once every unknown row has
        provably completed."""
        if self.first.size:
            keep = self.last + self.reach >= wm
            self.first = self.first[keep]
            self.last = self.last[keep]
        if self.stale_u is not None and self.stale_u + self.reach < wm:
            self.stale_u = None


def build_context_apply(aggs: tuple[DeviceAggregateSpec, ...],
                        spec: DeviceContextSpec, capacity: int):
    """Arrival-order application of a tuple chunk to one context window's
    active arrays: one ``lax.scan``, each step = ``spec.decide`` + the
    generic masked-array application (fold / edge shifts / merge / insert
    / orphan) transplanted from the session late kernel
    (engine/sessions.py::build_session_late)."""
    S = capacity
    idx = jnp.arange(S)

    def _bcast(mask, arr):
        return mask if arr.ndim == 1 else mask[:, None]

    def shift_left(arr, b, flag, fill):
        nxt = jnp.concatenate([arr[1:], jnp.full_like(arr[:1], fill)])
        return jnp.where(_bcast(flag & (idx >= b), arr), nxt, arr)

    def shift_right(arr, p, flag, fill):
        prv = jnp.concatenate([jnp.full_like(arr[:1], fill), arr[:-1]])
        return jnp.where(_bcast(flag & (idx > p), arr), prv, arr)

    def step(st: SessionState, x):
        pos, valid, lifts = x
        d = spec.decide(st.first, st.last, st.n, pos)
        touch = valid & d.touch
        new = valid & d.insert
        dropped = valid & d.drop
        j = jnp.clip(d.row, 0, S - 1)
        onej = idx == j
        first = jnp.where(onej & touch & (d.set_first < I64_MAX),
                          d.set_first, st.first)
        last = jnp.where(onej & touch & (d.set_last > I64_MIN),
                         d.set_last, st.last)
        counts = st.counts + jnp.where(onej & touch, 1, 0)
        partials = []
        for agg, part, lift in zip(aggs, st.partials, lifts):
            if agg.is_sparse:
                col, v = lift
                m2 = (onej & touch)[:, None] \
                    & (jnp.arange(part.shape[1]) == col)[None, :]
            else:
                v = lift
                m2 = (onej & touch)[:, None]
            if agg.kind == "sum":
                part = jnp.where(m2, part + v, part)
            elif agg.kind == "min":
                part = jnp.where(m2, jnp.minimum(part, v), part)
            else:
                part = jnp.where(m2, jnp.maximum(part, v), part)
            partials.append(part)

        # -- merge (at most one per tuple, like the reference) -------------
        do_merge = valid & (d.merge >= 0)
        a = jnp.clip(jnp.where(do_merge, d.merge, 0), 0, S - 1)
        b = a + 1
        onea = idx == a
        last = jnp.where(onea & do_merge, last[jnp.minimum(b, S - 1)], last)
        counts = jnp.where(onea & do_merge,
                           counts[a] + counts[jnp.minimum(b, S - 1)], counts)
        merged = []
        for agg, part in zip(aggs, partials):
            pa = part[a]
            pb = part[jnp.minimum(b, S - 1)]
            comb = (pa + pb if agg.kind == "sum"
                    else jnp.minimum(pa, pb) if agg.kind == "min"
                    else jnp.maximum(pa, pb))
            merged.append(jnp.where((onea & do_merge)[:, None], comb, part))
        first = shift_left(first, b, do_merge, I64_MAX)
        last = shift_left(last, b, do_merge, I64_MIN)
        counts = shift_left(counts, b, do_merge, 0)
        merged = [shift_left(p, b, do_merge, ag.identity)
                  for ag, p in zip(aggs, merged)]

        # -- insert at the sorted position (AFTER equal starts — matching
        # the host face's _add_sorted walk; duplicate-start inserts happen
        # for cap-declined extensions at repeated timestamps) -------------
        p = jnp.searchsorted(first, d.ins_first,
                             side="right").astype(idx.dtype)
        first = shift_right(first, p, new, I64_MAX)
        last = shift_right(last, p, new, I64_MIN)
        counts = shift_right(counts, p, new, 0)
        inserted = []
        for agg, part, lift in zip(aggs, merged, lifts):
            part = shift_right(part, p, new, agg.identity)
            if agg.is_sparse:
                col, v = lift
                m2 = (idx == p)[:, None] \
                    & (jnp.arange(part.shape[1]) == col)[None, :] & new
                base = jnp.where((idx == p)[:, None] & new,
                                 jnp.asarray(agg.identity, part.dtype), part)
                part = jnp.where(m2, v, base)
            else:
                part = jnp.where((idx == p)[:, None] & new, lift, part)
            inserted.append(part)
        onep = idx == p
        first = jnp.where(onep & new, d.ins_first, first)
        last = jnp.where(onep & new, d.ins_last, last)
        counts = jnp.where(onep & new, 1, counts)

        # -- orphan append --------------------------------------------------
        O = st.o_pos.shape[0]
        oidx = jnp.arange(O)
        oneo = (oidx == st.o_n) & dropped
        o_pos = jnp.where(oneo, pos, st.o_pos)
        o_partials = []
        for agg, part, lift in zip(aggs, st.o_partials, lifts):
            if agg.is_sparse:
                col, v = lift
                m2 = oneo[:, None] \
                    & (jnp.arange(part.shape[1]) == col)[None, :]
                base = jnp.where(oneo[:, None],
                                 jnp.asarray(agg.identity, part.dtype), part)
                part = jnp.where(m2, v, base)
            else:
                part = jnp.where(oneo[:, None], lift, part)
            o_partials.append(part)

        n2 = st.n + jnp.where(new, 1, 0) - jnp.where(do_merge, 1, 0)
        o_n2 = st.o_n + jnp.where(dropped, 1, 0)
        overflow = st.overflow | (new & (st.n >= S)) \
            | (dropped & (st.o_n >= O))
        return SessionState(first=first, last=last, counts=counts,
                            partials=tuple(inserted),
                            n=n2.astype(jnp.int32),
                            o_pos=o_pos, o_partials=tuple(o_partials),
                            o_n=o_n2.astype(jnp.int32),
                            overflow=overflow), None

    def apply(st: SessionState, ts: jnp.ndarray, vals: jnp.ndarray,
              valid: jnp.ndarray) -> SessionState:
        lifts = []
        for agg in aggs:
            if agg.is_sparse:
                col, v = agg.lift_sparse(vals)
                lifts.append((col.astype(jnp.int32),
                              jnp.where(valid, v, agg.identity)))
            else:
                lifted = agg.lift_dense(vals)
                lifts.append(jnp.where(valid[:, None], lifted, agg.identity))
        out, _ = jax.lax.scan(step, st, (ts, valid, tuple(lifts)))
        return out

    return apply


def build_context_sweep(aggs: tuple[DeviceAggregateSpec, ...],
                        spec: DeviceContextSpec, capacity: int,
                        emit_cap: int):
    """Watermark trigger for one context window: emit rows the spec marks
    complete (NOT necessarily a prefix — capped windows can interleave),
    recover covered orphans, compact survivors. Same output contract as
    the session sweep: ``(new_state, m, starts[E], ends[E], counts[E],
    partials…[E])``."""
    S, E = capacity, emit_cap

    def sweep(st: SessionState, wm: jnp.ndarray, gc_bound: jnp.ndarray):
        done = spec.trigger_done(st.first, st.last, st.n, wm)
        m = jnp.sum(done.astype(jnp.int32))
        order = jnp.argsort(~done, stable=True)        # done rows first,
        idx = jnp.arange(E)                            # in row (start) order
        sel = order[jnp.clip(idx, 0, S - 1)]
        b_ws, b_we = spec.emit_bounds(st.first[sel], st.last[sel])
        e_starts = jnp.where(idx < m, b_ws, I64_MAX)
        e_ends = jnp.where(idx < m, b_we, I64_MAX)
        e_counts = jnp.where(idx < m, st.counts[sel], 0)
        e_partials = [p[sel] for p in st.partials]
        em_overflow = m > E

        # -- orphan recovery (first covering window claims the orphan) -----
        O = st.o_pos.shape[0]
        o_live = jnp.arange(O) < st.o_n
        cov = (o_live[None, :] & (e_starts[:, None] <= st.o_pos[None, :])
               & (st.o_pos[None, :] < e_ends[:, None]))        # [E, O]
        first_cov = (jnp.cumsum(cov, axis=0) == 1) & cov
        e_counts = e_counts + jnp.sum(first_cov, axis=1)
        for i, (agg, op_) in enumerate(zip(aggs, st.o_partials)):
            if agg.kind == "sum":
                e_partials[i] = e_partials[i] \
                    + first_cov.astype(op_.dtype) @ op_        # [E, w] MXU
            else:
                ident = jnp.asarray(agg.identity, op_.dtype)
                masked = jnp.where(first_cov[:, :, None], op_[None, :, :],
                                   ident)
                red = (jnp.min if agg.kind == "min" else jnp.max)(masked,
                                                                 axis=1)
                e_partials[i] = (jnp.minimum if agg.kind == "min"
                                 else jnp.maximum)(e_partials[i], red)
        consumed = jnp.any(first_cov, axis=0)
        live_mask = (jnp.arange(S) < st.n) & ~done
        cov_live = jnp.any(
            live_mask[:, None] & (st.first[:, None] <= st.o_pos[None, :])
            & (st.o_pos[None, :] < st.last[:, None]
               + jnp.int64(spec.orphan_reach())), axis=0)
        keep_o = o_live & ~consumed \
            & (cov_live | (st.o_pos >= gc_bound - spec.orphan_reach()))
        oorder = jnp.argsort(~keep_o, stable=True)
        o_n2 = jnp.sum(keep_o.astype(jnp.int32)).astype(jnp.int32)
        o_pos2 = jnp.where(jnp.arange(O) < o_n2, st.o_pos[oorder], I64_MAX)
        o_partials2 = tuple(
            jnp.where((jnp.arange(O) < o_n2)[:, None], p[oorder],
                      jnp.asarray(a.identity, p.dtype))
            for a, p in zip(aggs, st.o_partials))

        # -- compact survivors (order-preserving) --------------------------
        keep = (jnp.arange(S) < st.n) & ~done
        korder = jnp.argsort(~keep, stable=True)
        n2 = (st.n - m).astype(jnp.int32)
        krows = jnp.arange(S) < n2

        def compact(a, fill):
            g = a[korder]
            if a.ndim == 1:
                return jnp.where(krows, g, fill)
            return jnp.where(krows[:, None], g, fill)

        new_state = SessionState(
            first=compact(st.first, I64_MAX),
            last=compact(st.last, I64_MIN),
            counts=compact(st.counts, 0),
            partials=tuple(compact(p, a.identity)
                           for a, p in zip(aggs, st.partials)),
            n=n2,
            o_pos=o_pos2, o_partials=o_partials2, o_n=o_n2,
            overflow=st.overflow | em_overflow,
        )
        return new_state, m, e_starts, e_ends, e_counts, tuple(e_partials)

    return sweep


def build_context_chunk(aggs: tuple, spec: DeviceContextSpec,
                        capacity: int, chunk_len: int, max_segments: int = 64):
    """Vectorized in-order chunk application for specs certifying the
    greedy gap/span chain (``DeviceContextSpec.inorder_chain_params``):
    the whole sorted chunk is segmented into its chain windows in ONE
    device program — gap breaks via a reverse running-min of break
    indices, span-cap splits via a bounded split loop (``max_segments``
    iterations, each one searchsorted — which XLA lowers to an O(B)
    broadcast compare on TPU, so ``max_segments`` is a real cost knob:
    64 iterations over a 2 M chunk measure ~19 ms, 256 measure ~1.6 s) —
    then each segment folds with one prefix-sum / log-sweep range
    reduction, and the new windows append as one block write. Replaces
    ``max_segments``-bounded stretches of the per-tuple scan with ~O(B)
    total work: the difference between ~10 K t/s and >100 M t/s on the
    capped-session bench cell. More than ``max_segments`` chain windows
    in one chunk sets the overflow flag (feed smaller batches).

    Precondition (checked by the caller): the chunk is sorted and starts
    at/after every prior tuple, and the orphan set is empty of future
    claims only the scan could service (in-order chains never orphan).
    """
    from .core import _range_combine

    gap_i, cap_i = spec.inorder_chain_params()
    S, B, M = capacity, chunk_len, max_segments
    gap = jnp.int64(gap_i)
    cap = None if cap_i is None else jnp.int64(cap_i)
    levels = max(1, B.bit_length())
    red = {"min": jnp.minimum, "max": jnp.maximum}

    def apply_chunk(st: SessionState, ts: jnp.ndarray, vals: jnp.ndarray,
                    valid: jnp.ndarray) -> SessionState:
        nv = jnp.sum(valid.astype(jnp.int32))
        idx32 = jnp.arange(B, dtype=jnp.int32)

        # next gap-break at/after each lane (reverse running min of
        # breaking lane indices; lane 0's break is the continuation test)
        brk_at = jnp.where(
            jnp.concatenate([jnp.asarray([False]),
                             ts[1:] - ts[:-1] > gap]),
            idx32, jnp.int32(B))
        nxt_brk = jax.lax.cummin(brk_at, reverse=True)

        # continuation of the newest live window?
        top = jnp.maximum(st.n - 1, 0)
        f_top, l_top = st.first[top], st.last[top]
        t0 = ts[0]
        cont = (st.n > 0) & (t0 <= l_top + gap) & (nv > 0)
        if cap is not None:
            cont = cont & (t0 - f_top <= cap)
        anchor0 = jnp.where(cont, f_top, t0)

        def body(k, carry):
            cur, anchor, count, starts, ends = carry
            active = cur < nv
            nb = nxt_brk[jnp.clip(cur + 1, 0, B - 1)]
            nb = jnp.where(cur + 1 < B, nb, B)
            if cap is not None:
                capi = jnp.searchsorted(
                    ts, anchor + cap, side="right").astype(jnp.int32)
            else:
                capi = jnp.int32(B)
            nxt = jnp.minimum(jnp.minimum(nb, capi), nv.astype(jnp.int32))
            nxt = jnp.maximum(nxt, cur + 1)        # always progress
            starts = starts.at[k].set(jnp.where(active, cur, B))
            ends = ends.at[k].set(jnp.where(active, nxt, B))
            count = count + active.astype(jnp.int32)
            anchor = jnp.where(active, ts[jnp.clip(nxt, 0, B - 1)], anchor)
            return (jnp.where(active, nxt, cur), anchor, count,
                    starts, ends)

        cur, _, n_seg, seg_s, seg_e = jax.lax.fori_loop(
            0, M, body,
            (jnp.int32(0), anchor0, jnp.int32(0),
             jnp.full((M,), B, jnp.int32), jnp.full((M,), B, jnp.int32)))
        unfinished = cur < nv                      # > M chain windows

        seg_cnt = (seg_e - seg_s).astype(jnp.int64)
        sc = jnp.clip(seg_s, 0, B - 1)
        se = jnp.clip(seg_e - 1, 0, B - 1)
        seg_first = ts[sc]
        seg_last = ts[se]

        seg_parts = []
        for agg in aggs:
            if agg.is_sparse:
                col, v = agg.lift_sparse(vals)
                lifted = jnp.full((B, agg.width), agg.identity,
                                  jnp.float32)
                # one column per lane: segment-combine via the same
                # range machinery over a dense [B, width] table
                lifted = jnp.where(
                    (jnp.arange(agg.width)[None, :] == col[:, None])
                    & valid[:, None], v[:, None], lifted)
            else:
                lifted = agg.lift_dense(vals)
                lifted = jnp.where(valid[:, None], lifted,
                                   jnp.asarray(agg.identity, lifted.dtype))
            if agg.kind == "sum":
                Pr = jnp.concatenate(
                    [jnp.zeros((1, lifted.shape[1]), lifted.dtype),
                     jnp.cumsum(lifted, axis=0)])
                seg_parts.append(Pr[jnp.clip(seg_e, 0, B)]
                                 - Pr[jnp.clip(seg_s, 0, B)])
            else:
                seg_parts.append(_range_combine(
                    lifted, seg_s, jnp.maximum(seg_e - seg_s, 0),
                    red[agg.kind], agg.identity, levels))

        # -- fold segment 0 into the continued top row ---------------------
        has0 = n_seg > 0
        fold_top = cont & has0
        onetop = (jnp.arange(S) == top) & fold_top
        last = jnp.where(onetop, jnp.maximum(st.last, seg_last[0]),
                         st.last)
        counts = st.counts + jnp.where(onetop, seg_cnt[0], 0)
        partials = []
        for agg, part, sp in zip(aggs, st.partials, seg_parts):
            upd = sp[0][None, :]
            if agg.kind == "sum":
                comb = part + upd
            else:
                comb = red[agg.kind](part, upd.astype(part.dtype))
            partials.append(jnp.where(onetop[:, None], comb, part))

        # -- append the remaining segments as new rows ---------------------
        # The write block is anchored at min(n, S - Mb) so rows near the
        # capacity edge stay writable (the block never hangs past S); the
        # block-row → segment mapping shifts by the anchor displacement d,
        # so usable capacity is NOT reduced by the block length — overflow
        # means exactly n + k_new > S, same as the scan kernel.
        Mb = min(M, S)
        shift = jnp.where(cont, 1, 0)              # segment→block offset
        bidx = jnp.arange(Mb)
        k_new = jnp.maximum(n_seg - shift, 0)
        start = jnp.clip(st.n, 0, S - Mb)
        d = st.n - start                           # 0 unless n > S - Mb
        src = jnp.clip(bidx - d + shift, 0, M - 1)
        newrow = (bidx >= d) & (bidx - d < k_new)

        def write_block(arr, rows, fill_mask):
            curb = jax.lax.dynamic_slice(
                arr, (start,) + (jnp.int32(0),) * (arr.ndim - 1),
                (Mb,) + arr.shape[1:])
            m = fill_mask if arr.ndim == 1 else fill_mask[:, None]
            return jax.lax.dynamic_update_slice(
                arr, jnp.where(m, rows.astype(arr.dtype), curb),
                (start,) + (jnp.int32(0),) * (arr.ndim - 1))

        first = write_block(st.first, seg_first[src], newrow)
        last = write_block(last, seg_last[src], newrow)
        counts = write_block(counts, seg_cnt[src], newrow)
        partials = [write_block(p, sp[src], newrow)
                    for p, sp in zip(partials, seg_parts)]

        overflow = st.overflow | unfinished | (st.n + k_new > S)
        return st._replace(
            first=first, last=last, counts=counts,
            partials=tuple(partials),
            n=(st.n + k_new).astype(jnp.int32),
            overflow=overflow)

    return apply_chunk
