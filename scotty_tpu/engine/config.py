"""Static configuration for the TPU slicing engine.

Everything here is trace-time static: slice-buffer capacity, ingest batch
size, trigger padding buckets. The reference sizes its slice store dynamically
(an ArrayList pre-sized 1000, slicing/.../LazyAggregateStore.java:148-157);
under XLA every shape must be static, so capacities are explicit and the
operator raises on overflow instead of growing.
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True)
class EngineConfig:
    #: Max number of live slices per key shard. Slices live for roughly
    #: ``(max_window_size + max_lateness + watermark_period) / min_edge_period``
    #: — e.g. the 60 s / 1 ms sliding benchmark needs ~61k ⇒ default 1 << 17.
    capacity: int = 1 << 17

    #: Device ingest batch size (tuples per kernel launch). The host driver
    #: packs tuples into batches of this size; the last batch before a
    #: watermark is padded and masked.
    batch_size: int = 1 << 15

    #: Triggered-window arrays are padded to the next power-of-two bucket at
    #: least this large to bound recompilation.
    min_trigger_pad: int = 256

    #: Hard cap on triggered windows per watermark (query-kernel padding).
    max_triggers: int = 1 << 17

    #: Capacity of the out-of-order annex (late tuples that open slices whose
    #: grid range was never materialized). Bounded by the number of distinct
    #: empty grid ranges that receive late tuples between two watermarks.
    annex_capacity: int = 1 << 12

    #: Partial-aggregate dtype on device.
    partial_dtype: str = "float32"

    #: Record-buffer capacity for count-measure workloads (0 = 4×capacity).
    #: Count windows aggregate ts-sorted rank ranges, so the engine retains
    #: raw (ts, value) records while count windows are registered — the
    #: device analogue of the reference's lazy slices (record retention is
    #: forced by count measure in its decision tree, SliceFactory.java:17-22).
    record_capacity: int = 0

    @property
    def records(self) -> int:
        return self.record_capacity or 4 * self.capacity

    #: Run bound for the dense in-order ingest kernel (ingest_dense): an
    #: in-order batch touching < this many NEW slices takes the
    #: scatter-free path (int64 scatters are the dominant ingest cost on
    #: TPU; the dense kernel replaces [batch]-lane scatters with run
    #: reductions + a [runs]-lane update). Batches spanning more slices
    #: fall back to the general kernel — the host checks the bound from
    #: the batch's time span and the minimum grid period. 0 disables.
    dense_ingest_runs: int = 16

    #: Overflow policy at the engine's admission/drain points
    #: (scotty_tpu.resilience.policy): ``"fail"`` (the default — today's
    #: hard RuntimeError, the benchmarked mode), ``"shed"`` (drop the
    #: lowest-watermark-impact tuples at the host ingest boundary,
    #: exactly counted in DeviceMetrics + ``resilience_shed_tuples``) or
    #: ``"grow"`` (checkpoint-snapshot the carried state, rebuild the
    #: jitted kernels at 2× capacity, restore, resume — bounded by
    #: ``max_capacity``). Policies are PREVENTIVE: a raised device
    #: overflow flag means data was already clamped and stays fatal.
    overflow_policy: str = "fail"

    #: Hard bound for the GROW policy (0 = 8 × ``capacity``, i.e. three
    #: doublings) so an unbounded overload cannot OOM-spiral.
    max_capacity: int = 0

    #: Live-slice occupancy fraction at which a GROW fused pipeline
    #: doubles capacity at its sync/drain points (growth must fire before
    #: the overflow flag can — see resilience.policy).
    grow_occupancy: float = 0.85

    #: Pallas bucketed sort-split for the shaper's device batches
    #: (scotty_tpu.pallas.sort_split, ROADMAP item 4): int32 bitonic
    #: network in VMEM instead of the emulated-int64 full-block
    #: ``lax.sort``. Default OFF — every existing step HLO pin stays
    #: byte-identical; batches whose host-known timestamp span exceeds
    #: the 31-bit bucket budget fall back to the XLA twin (counted as
    #: ``pallas_fallbacks``, never silent). Correctness gates on CPU
    #: via Pallas interpreter mode in tier-1; speed is a TPU-box cert.
    pallas_sort_split: bool = False

    #: Pallas segmented-reduce slice-merge (scotty_tpu.pallas.seg_fold)
    #: for the dense-ingest run fold and the aligned/keyed/mesh
    #: generator lifts (including the PR 10 multi-cell sparse lift):
    #: lane blocks stream HBM→VMEM double-buffered and reduce into row
    #: accumulators — no scatter-combine on the fold. Default OFF (HLO
    #: pins byte-identical); interpreter-mode gated on CPU like
    #: ``pallas_sort_split``.
    pallas_slice_merge: bool = False

    #: Pack the Pallas slice-merge value stream as bf16 (half the HBM
    #: traffic; f32 accumulators). Only meaningful with
    #: ``pallas_slice_merge``; results carry the derived bf16 rounding
    #: bound instead of bit-matching the XLA twin.
    pallas_packed: bool = False

    #: Micro-batches per watermark interval for streamed emission
    #: (``FusedPipelineDriver.run_streamed``): the per-interval fused
    #: step splits into this many async micro-dispatches plus one
    #: trigger/query flush, and the driver fetches interval N's
    #: eligible windows while N+1's micro-batches dispatch — first-emit
    #: latency decouples from interval size. 0/1 = off (the default;
    #: ``run()`` and every HLO pin are untouched). Results bit-match
    #: the whole-interval step on the same generation keying.
    micro_batch: int = 0

    def __post_init__(self):
        # literal check, NOT an import of resilience.policy.OverflowPolicy:
        # the engine config must not pull the whole resilience package in
        # (layering: resilience depends on engine, not the reverse)
        if self.overflow_policy not in ("fail", "shed", "grow"):
            raise ValueError(
                f"unknown overflow_policy {self.overflow_policy!r}: "
                "expected one of ('fail', 'shed', 'grow')")

    def trigger_pad(self, n: int) -> int:
        """Next power-of-two bucket ≥ n (≥ min_trigger_pad, ≤ max_triggers).

        ``max_triggers`` is a HARD cap: a window set needing more trigger
        rows than the cap raises here instead of silently returning a pad
        above it (which would compile a query kernel bigger than the
        documented bound and let ``n`` keep growing unnoticed).
        """
        if n > self.max_triggers:
            raise ValueError(
                f"{n} triggered windows exceeds EngineConfig.max_triggers="
                f"{self.max_triggers}: raise max_triggers (pads the query "
                "kernel larger), register fewer/coarser windows, or advance "
                "watermarks more often so fewer triggers fire per interval")
        p = self.min_trigger_pad
        while p < n:
            p <<= 1
        return min(p, self.max_triggers)
