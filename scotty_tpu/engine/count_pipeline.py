"""Fused per-interval pipeline for count-measure workloads.

The r4 count cells drove the batch-at-a-time operator: a NumPy cut
calculus per batch, an O(RC) record rank merge per late batch, and a
device→host count probe per watermark — 0.2–1.2 M t/s against a 21 G
headline (VERDICT r4 weak #1). This module is the count analogue of
:class:`.pipeline.AlignedStreamPipeline`: the whole watermark interval
(generate → rank bookkeeping → trigger → range query) is ONE XLA
program, built from three observations:

1. **The count bound is static.** The reference converts a watermark ts
   to a count bound by probing the slice covering the watermark
   (WindowManager.java:110-115); with a paced generator every tuple of
   interval ``i`` has ``ts < wm_i``, so the probe always answers "the
   whole stream" — a closed form of the interval index, and count-window
   trigger enumeration (TumblingWindow.java:34-39 over counts,
   ``trigger_windows(last_count, cend+1)``) compiles to a static grid
   with a validity mask, exactly like ``build_trigger_grid``'s time grid.
   The per-watermark device→host count probe disappears entirely.

2. **Millisecond rows ARE the rank order.** Out-of-order count windows
   aggregate ts-sorted rank ranges (the closed form of the reference's
   ripple, SliceManager.java:64-86), with equal-ts ties in arrival order
   (build_record_merge's stable sides). Event time is integral ms — so
   bucketing records into one row per ms, appending within a row in
   arrival order, IS the global rank order (rows ascending, columns in
   append order): ties only ever happen inside a row. No sort, no
   scatter, no searchsorted over tuples — the formulations that need
   them measure 100–150 ms per 800 K lanes on TPU (scatters with runtime
   indices serialize; XLA sort is ~43 ns/elem), while this layout is
   pure block writes.

3. **Stratified late lanes make appends static.** Late tuples are
   generated pre-grouped per ms row — ``E`` per row over the lateness
   span, the same stratified rendering of the uniform late load the
   aligned pipeline uses (`late_fold_segment`). A row of age ``a``
   intervals receives its append at column ``u + E·(a-1)`` — a fixed
   column per age — so the whole late fold is ``q`` masked block writes
   of ``[P, E]``, and every row's capacity is exactly ``u + E·q``
   (overflow is impossible by construction).

Window values are range queries over ranks: rank → (row, offset) via a
``[W]``-row count prefix (W is a few thousand — negligible), whole rows
from per-row maintained aggregates (prefix sums for sum-like, a log-sweep
sparse table for min/max), boundary rows from a ``[T, cap]`` gather +
masked fold — T triggers and cap columns are both small.

Reproduced reference cadence quirks (pinned by the oracle differential
tests in tests/test_count_pipeline.py):

* **ends ≤ cend+1** — the off-by-one in WindowManager's count bound
  triggers the top window one tuple early with a PARTIAL value (ranks
  ``[a, N_i)``) and re-emits it complete at the next watermark.
* **last_count jumps to the total** (simulator/operator.py:265) — count
  windows whose trigger was deferred past a watermark are lost.

Time windows in a count+time mix use the reference's ARRIVAL-cut rank
semantics in closed form: a time edge ``e`` is cut by the first in-order
tuple with ``ts >= e``, and the number of arrivals before that cut is a
pure function of ``e`` under the paced generator — so a time window
``[ws, we)`` is the rank range ``[c_cut(ws), c_cut(we))``, matching the
engine's mix_rec slice walk (post-ripple tLast containment,
AggregateWindowState.java:25-31). The duplicated-edge shadowing of the
reference's batch scan (a count cut whose start equals the batch's
min_ts shadows earlier same-start slices out of that window —
LazyAggregateStore.java:83-92 find-from-END, reproduced by
``build_query(mix_rec=True)``) is reproduced in closed form in the
step's ``mstar`` calculus. One artifact at a measure-zero boundary is
deliberately NOT reproduced (it needs an entire post-cut slice's rank
range re-filled by late content): the hi-bound slice extension — the
OOO-mix differential fuzz bounds the observable effect. The simulator's
TreeSet record dedup at equal ts (StreamRecord equals-ignores-element,
a mirrored reference artifact) is likewise not reproduced — the DEVICE
engine is the tie-semantics oracle (tests/test_count_pipeline.py).
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Sequence

import numpy as np

from .. import jax_config  # noqa: F401
from .. import obs as _obs
from ..obs import flight as _flight

from ..core.aggregates import AggregateFunction
from ..core.windows import (
    SlidingWindow,
    TumblingWindow,
    WindowMeasure,
)
from .config import EngineConfig
from .pipeline import FusedPipelineDriver, build_trigger_grid


class CountRowState(NamedTuple):
    rows: object             # f32 [W, cap] — per-ms rows, append order
    row_aggs: tuple          # per agg: [W, width] maintained row combines
    overflow: object         # bool — a query reached below the window


class CountStreamPipeline(FusedPipelineDriver):
    """Fused count-measure benchmark pipeline (count tumbling AND
    sliding windows — rank ranges answer arbitrary ``[a, b)``, so the
    slide cadence is just a denser trigger grid — optionally mixed with
    time tumbling/sliding windows), in-order and out-of-order down to
    sub-period lateness (``max_lateness < wm_period`` rides a partial
    oldest stratum, ISSUE 11). One XLA dispatch per watermark interval;
    no host sync anywhere in the steady state."""

    _uses_device_metrics = True

    def __init__(self, windows: Sequence, aggregations: Sequence[AggregateFunction],
                 config: Optional[EngineConfig] = None,
                 throughput: int = 5_000_000, wm_period_ms: int = 1000,
                 max_lateness: int = 1000, seed: int = 0, gc_every: int = 32,
                 value_scale: float = 10_000.0,
                 out_of_order_pct: float = 0.0,
                 collect_device_metrics: bool = True):
        import jax
        import jax.numpy as jnp

        from . import core as ec
        from ..obs import device as _dev

        self.collect_device_metrics = bool(collect_device_metrics)
        self.config = config or EngineConfig()
        self.windows = list(windows)
        self.aggregations = list(aggregations)
        self.wm_period_ms = int(wm_period_ms)
        self.max_lateness = int(max_lateness)
        self.gc_every = gc_every
        self.seed = seed
        self.value_scale = float(value_scale)
        self.out_of_order_pct = float(out_of_order_pct)
        self.max_fixed = 0                     # no out-of-step GC

        count_windows, time_windows = [], []
        for w in self.windows:
            if w.measure == WindowMeasure.Count:
                if isinstance(w, SlidingWindow):
                    # sliding count windows: the rank-range layout
                    # already answers arbitrary [a, b) partial ranges,
                    # so the slide cadence is just a denser trigger
                    # enumeration (ISSUE 11). The kind tag stays
                    # explicit: SlidingWindow(c, c) keeps the sliding
                    # walk's end <= cend+2 guard, it does NOT collapse
                    # into the tumbling enumeration.
                    count_windows.append((int(w.size), int(w.slide), "s"))
                elif isinstance(w, TumblingWindow):
                    count_windows.append((int(w.size), int(w.size), "t"))
                else:
                    raise NotImplementedError(
                        "count pipeline: count-measure windows must be "
                        "rank-range realizable — CountTumbling "
                        "(TumblingWindow) and CountSliding "
                        "(SlidingWindow, the sliding-count entry point) "
                        f"are supported; {type(w).__name__} is not "
                        "(count-measure sessions/bands ride the host "
                        "SlicingWindowOperator)")
            elif isinstance(w, (TumblingWindow, SlidingWindow)):
                time_windows.append(w)
            else:
                raise NotImplementedError(
                    f"count pipeline: {type(w).__name__} has no rank-range "
                    "realization (supported: CountTumbling/CountSliding "
                    "rank ranges, optionally mixed with time-measure "
                    "Tumbling/Sliding grids)")
        if not count_windows:
            raise NotImplementedError(
                "count pipeline: needs >= 1 count-measure window — "
                "CountTumbling(size) or CountSliding(size,slide) — (use "
                "AlignedStreamPipeline for pure time grids)")
        specs = [a.device_spec() for a in self.aggregations]
        if any(s is None or s.is_sparse for s in specs):
            raise NotImplementedError(
                "count pipeline: dense device aggregations only")

        P = self.wm_period_ms
        SR = throughput * P // 1000
        u = SR // P                            # in-order tuples per ms row
        if u < 1:
            raise NotImplementedError(
                "count pipeline: needs >= 1 tuple per ms (throughput >= "
                "1000); the batch operator covers trickle rates")
        SR = u * P
        lateness = self.max_lateness
        L_req = int(SR * self.out_of_order_pct)
        if L_req and lateness < 1:
            raise NotImplementedError(
                "count pipeline: out-of-order needs max_lateness >= 1 ms "
                "(the stratified late model spreads the late load over "
                "the lateness span)")
        # Late span = the FULL lateness contract in ms rows (ISSUE 11:
        # previously floored to whole watermark periods, which rejected
        # max_lateness < wm_period outright). The span splits into
        # q_full whole-period strata plus one PARTIAL oldest stratum of
        # ``rem`` rows — its append is a masked block write, and every
        # closed form below counts bands per row instead of whole
        # periods. Relaxed retention (rem != 0) is surfaced through the
        # gated ``count_lateness_relaxed_rows`` counter.
        sc = lateness if L_req else 0          # late span in ms rows
        E = -(-L_req // sc) if L_req else 0    # late appends per row
        L = E * sc
        sc = sc if E else 0
        q_full = sc // P                       # whole-period strata
        rem = sc % P                           # partial-stratum rows
        qc = q_full + (1 if rem else 0)        # strata per interval
        self.R_total = SR + L                  # steady-state (i >= qc)
        self.SR, self.L, self.E, self.u = SR, L, E, u
        self.q, self.q_full, self.rem, self.sc = qc, q_full, rem, sc
        q = qc
        self.tuples_per_interval = self.R_total
        self.n_late = L
        cap = u + E * qc                       # exact row capacity

        # Row-window coverage: deepest ms any trigger can reach below the
        # watermark — count windows reach c_max + R_total ranks
        # (≈ that many / u ms), time windows reach t_max ms, late appends
        # reach `lateness` ms. W is a multiple of P so an interval's row
        # block never straddles the ring seam.
        c_max = max(c for (c, _, _) in count_windows)
        t_max = max([int(w.size) for w in time_windows], default=0)
        need = max(t_max, -(-(c_max + self.R_total) // u)) \
            + (lateness if E else 0) + 2 * P
        W = -(-need // P) * P
        self.row_window = W
        self.row_capacity = cap

        # -- trigger layout: count windows first, then the time grid ------
        # tumbling: the end-grid walk (size == slide); sliding: the
        # start-grid walk needs head-room for the reference's negative
        # leading starts (guarded out by starts >= 0)
        count_layout = [
            (c, s, (self.R_total // c + 2) if kind == "t"
             else ((self.R_total + c) // s + 3), kind)
            for (c, s, kind) in count_windows]
        Tc = sum(k for _, _, k, _ in count_layout)
        if time_windows:
            make_time_triggers, Tt = build_trigger_grid(time_windows, P)
        else:
            make_time_triggers, Tt = None, 0
        self.T = Tc + Tt
        first_lw = max(0, P - lateness)

        red = {"min": jnp.minimum, "max": jnp.maximum}
        row_levels = max(1, W.bit_length())
        n_blocks = W // P

        def lift_rows(sp, block):
            """[rows, n] values → [rows, width] combined row partials."""
            rows_n = block.shape[0]
            lifted = sp.lift_dense(block.reshape(-1)).reshape(
                rows_n, block.shape[1], -1)
            if sp.kind == "sum":
                return jnp.sum(lifted, axis=1)
            return (jnp.min if sp.kind == "min" else jnp.max)(lifted,
                                                              axis=1)

        # -- closed-form arrival accounting --------------------------------
        def late_of(k):
            """Late lanes of interval k = E per live band row; interval
            k's band is [kP - sc, kP) clipped at the stream start, so
            its row count is min(sc, kP) (early intervals have fewer
            prior rows to stratify over)."""
            return E * jnp.minimum(jnp.maximum(k, 0) * P, sc) if E else 0

        def arrived_before(k):
            """Total arrivals of intervals [0, k): the in-order pace
            plus E * sum_{j<k} min(sc, jP) — a triangular ramp over the
            first q_full intervals, then sc per interval."""
            k = jnp.maximum(k, 0)
            if not E:
                return k * SR
            n = jnp.maximum(k - 1, 0)
            m = jnp.minimum(n, q_full)
            tri = m * (m + 1) // 2
            extra = sc * jnp.maximum(n - q_full, 0)
            return k * SR + E * (P * tri + extra)

        def c_cut(e, N_i):
            """Arrival-cut rank of time edge ``e`` (see module docstring):
            interval k's late lanes arrive first, then the paced in-order
            lanes ``ts = kP + j//u``. Edge 0 is the bootstrap slice."""
            e = jnp.maximum(e, 0)
            k = e // P
            j = (e - k * P) * u                # first in-order lane >= e
            cut = arrived_before(k) + late_of(k) + j
            return jnp.where(e == 0, 0, jnp.minimum(cut, N_i))

        def gen_inorder(key, i):
            """[P, u] in-order values (ts of row r = i*P + r, u per ms —
            the constant-rate LoadGeneratorSource)."""
            return jax.random.uniform(
                key, (P, u), dtype=jnp.float32) * value_scale

        def gen_late(key, i, a):
            """[P, E] late values appended this interval to the rows of
            age ``a`` (ms [i*P - a*P, i*P - a*P + P))."""
            ka = jax.random.fold_in(key, 0x70000000 + a)
            return jax.random.uniform(
                ka, (P, E), dtype=jnp.float32) * value_scale

        def rowstart_slot(base_next):
            """Ring slot of ms ``base_next - W`` .. : slot of a row with
            ms m is m mod W; the retained window is [wm - W, wm)."""
            return jnp.mod(base_next, W)

        cdm = self.collect_device_metrics

        def step(state, dm, key, i):
            base = i * jnp.int64(P)
            N_prev = arrived_before(i)
            N_i = arrived_before(i + 1)
            rows, row_aggs = state.rows, list(state.row_aggs)

            if cdm:
                # In-jit telemetry. Late lanes arrive at the START of the
                # interval (materialize_interval arrival order: oldest ms
                # rows first), below the running max base-1 left by the
                # previous interval's paced rows — EXCEPT the age-0 ms
                # row (ts == base-1 is not strictly below the max). Ages
                # are closed-form: the a-strata's rows sit at
                # aP-1 … (a-1)P below the stream head, E tuples per ms.
                n_late_rows = jnp.int64(0)
                if E:
                    for a in range(1, q + 1):
                        ok = base - a * P >= 0
                        rows_lo = P - rem if (rem and a == q) else 0
                        ages = (jnp.int64(a) * P - 1
                                - jnp.arange(P, dtype=jnp.int64))
                        m = ok & (ages > 0) \
                            & (jnp.arange(P) >= rows_lo)
                        dm = _dev.record_late_ages(dm, ages, m,
                                                   weight=jnp.int64(E))
                        dm = dm._replace(
                            late=dm.late + E * jnp.sum(m.astype(jnp.int64)))
                        n_late_rows = n_late_rows \
                            + jnp.where(ok, jnp.int64(P - rows_lo), 0)
                dm = dm._replace(
                    ingested=dm.ingested + jnp.int64(SR)
                    + late_of(i),
                    slices_touched=dm.slices_touched + jnp.int64(P)
                    + n_late_rows)

            # 1. claim this interval's P rows (aligned block in the ring)
            slot = jnp.mod(base, W).astype(jnp.int32)
            vals_in = gen_inorder(key, i)                    # [P, u]
            blk = jnp.zeros((P, cap), jnp.float32)
            blk = jax.lax.dynamic_update_slice(blk, vals_in, (0, 0))
            rows = jax.lax.dynamic_update_slice(rows, blk,
                                                (slot, jnp.int32(0)))
            for ai, sp in enumerate(specs):
                row_aggs[ai] = jax.lax.dynamic_update_slice(
                    row_aggs[ai],
                    lift_rows(sp, vals_in).astype(row_aggs[ai].dtype),
                    (slot, jnp.int32(0)))

            # 2. late appends: one fixed-column [P, E] block per age
            # (the PARTIAL oldest stratum — rem != 0, a == q — masks its
            # leading P - rem rows: they sit below the lateness span)
            if E:
                for a in range(1, q + 1):
                    tgt = base - a * P
                    ok = tgt >= 0
                    rows_lo = P - rem if (rem and a == q) else 0
                    rmask = ok & (jnp.arange(P) >= rows_lo)
                    slot_a = jnp.mod(jnp.maximum(tgt, 0),
                                     W).astype(jnp.int32)
                    lv = gen_late(key, i, a)                 # [P, E]
                    col = jnp.int32(u + E * (a - 1))
                    cur = jax.lax.dynamic_slice(rows, (slot_a, col),
                                                (P, E))
                    rows = jax.lax.dynamic_update_slice(
                        rows, jnp.where(rmask[:, None], lv, cur),
                        (slot_a, col))
                    for ai, sp in enumerate(specs):
                        wdt = row_aggs[ai].shape[1]
                        cur_a = jax.lax.dynamic_slice(
                            row_aggs[ai], (slot_a, jnp.int32(0)), (P, wdt))
                        upd = lift_rows(sp, lv).astype(cur_a.dtype)
                        if sp.kind == "sum":
                            comb = cur_a + upd
                        else:
                            comb = red[sp.kind](cur_a, upd)
                        row_aggs[ai] = jax.lax.dynamic_update_slice(
                            row_aggs[ai],
                            jnp.where(rmask[:, None], comb, cur_a),
                            (slot_a, jnp.int32(0)))

            # 3. per-row counts of the retained window, in ms order —
            # closed form: row of ms m holds u + E x (elapsed bands
            # containing m), where row m sits in the late band of
            # intervals (m/P, (m+sc)/P] — whole periods plus the
            # partial oldest stratum (0 for m < 0)
            shift = rowstart_slot(base + P)
            ms = (base + P - W) + jnp.arange(W, dtype=jnp.int64)  # ms order
            kk = ms // P
            if E:
                bands = jnp.clip(
                    jnp.minimum(i, (ms + sc) // P) - kk, 0, q)
                cnt_row = jnp.where(ms >= 0, u + E * bands,
                                    0).astype(jnp.int64)
            else:
                cnt_row = jnp.where(ms >= 0, u, 0).astype(jnp.int64)
            prefix = jnp.concatenate(
                [jnp.zeros((1,), jnp.int64), jnp.cumsum(cnt_row)])
            base_rank = N_i - prefix[-1]       # global rank of ms-order 0

            # ms-order views of rows / row_aggs (one roll of small arrays)
            def ms_order(x):
                return jnp.roll(x, -shift, axis=0)

            aggs_o = [ms_order(a) for a in row_aggs]  # [W, width]: small

            # -- triggers --------------------------------------------------
            ws_parts, we_parts, ok_parts, cw_parts = [], [], [], []
            wr_parts = []                # rank-range end basis per row
            for (c, s, maxk, kind) in count_layout:
                if kind == "t":
                    # tumbling: end-grid walk (TumblingWindow.java:34-39
                    # over counts)
                    last_start = (N_prev // c) * c
                    ends = last_start + c * (1 + jnp.arange(
                        maxk, dtype=jnp.int64))
                    ok = ends <= N_i + 1       # the reference's cend+1
                    ws = ends - c
                    we_rank = ends
                else:
                    # sliding: start-grid walk (SlidingWindow.java:50-57
                    # over counts, via trigger_arrays(last_count,
                    # cend+1)): starts on the slide grid with
                    # end > last_count, guarded start >= 0 and
                    # end <= (cend+1)+1 — the doubled "+1" is the
                    # reference's sliding end <= wm+1 quirk applied to
                    # the count bound. Values are SLICE-GRANULAR when
                    # size % slide != 0: count cuts land only on the
                    # slide grid, so the reference aggregates the whole
                    # slices inside the window — ranks [ws, ws +
                    # (size // slide) * slide) — matching the simulator
                    # AND the engine (pinned by the differential
                    # tests); the reported bounds keep the true end.
                    first_start = ((N_prev - c) // s + 1) * s
                    ws = first_start + s * jnp.arange(maxk,
                                                      dtype=jnp.int64)
                    ends = ws + c
                    ok = (ws >= 0) & (ends <= N_i + 2)
                    we_rank = ws + (c // s) * s
                ws_parts.append(ws)
                we_parts.append(ends)
                wr_parts.append(we_rank)
                ok_parts.append(ok)
                cw_parts.append(jnp.ones((maxk,), bool))
            if make_time_triggers is not None:
                last_wm = jnp.where(i > 0, base, jnp.int64(first_lw))
                t_ws, t_we, t_ok = make_time_triggers(last_wm, base + P)
                ws_parts.append(t_ws)
                we_parts.append(t_we)
                ok_parts.append(t_ok)
                cw_parts.append(jnp.zeros((Tt,), bool))
            ws = jnp.concatenate(ws_parts)
            we = jnp.concatenate(we_parts)
            tmask = jnp.concatenate(ok_parts)
            is_count = jnp.concatenate(cw_parts)

            a_rank = jnp.where(is_count, ws, c_cut(ws, N_i))
            if make_time_triggers is not None:
                # The reference's duplicated-edge shadowing
                # (LazyAggregateStore.java:83-92 find* walk from the END;
                # reproduced by build_query's mix_rec scan bounds): when a
                # count cut fires while the running max still equals the
                # batch's min time edge (count edge m with arrival m-1 in
                # min_ts's ms row), its slice start duplicates min_ts and
                # the batch scan starts at the LAST duplicate — slices in
                # ranks [c_cut(min_ts), m*) are shadowed out of the
                # min_ts window, unless the batch's min_count bound pulls
                # the scan start below them (the simulator seeds it with
                # the running total, operator.py:252).
                t_valid = ~is_count & tmask
                min_ts = jnp.min(jnp.where(t_valid, ws, ec.I64_MAX))
                r0 = c_cut(min_ts, N_i)
                mstar = r0
                for (_, s, _, _) in count_layout:
                    # the count cut cadence is the window's slide (the
                    # engine's count_periods take w.slide for sliding)
                    cand = ((r0 + u) // s) * s
                    mstar = jnp.maximum(mstar,
                                        jnp.where(cand > r0, cand, r0))
                min_count = jnp.minimum(
                    N_i, jnp.min(jnp.where(is_count & tmask, ws,
                                           ec.I64_MAX)))
                shadow = (mstar > r0) & (min_count >= mstar) \
                    & jnp.any(t_valid)
                a_rank = jnp.where(
                    shadow & t_valid & (ws == min_ts),
                    jnp.maximum(a_rank, mstar), a_rank)
            # count rows answer rank ranges with the reference's slice
            # containment: while the stream has NOT advanced past the
            # window end (N_i <= we) the OPEN boundary slice's extent
            # fits inside the window and every retained rank below we
            # counts (b = N_i — also the tumbling cend+1 partial); once
            # N_i > we the boundary slice sticks out and only whole
            # slices aggregate (b = the slide-grid floor; for tumbling
            # the floor IS the end, reproducing min(we, N_i)). Time
            # rows answer the arrival cut of the true end.
            we_rank = jnp.concatenate(
                wr_parts + ([jnp.zeros((Tt,), jnp.int64)]
                            if make_time_triggers is not None else []))
            b_rank = jnp.where(is_count,
                               jnp.where(N_i <= we, N_i, we_rank),
                               c_cut(we, N_i))
            b_rank = jnp.maximum(b_rank, a_rank)
            cnt = jnp.where(tmask, b_rank - a_rank, 0)
            bad = jnp.any(tmask & (cnt > 0) & (a_rank < base_rank))

            # rank → (ms-order row, intra-row offset)
            def locate(r):
                rr = jnp.clip(r - base_rank, 0, prefix[-1])
                row = jnp.clip(
                    jnp.searchsorted(prefix, rr, side="right") - 1,
                    0, W - 1)
                return row, (rr - prefix[row]).astype(jnp.int32)

            row_a, off_a = locate(a_rank)
            row_b, off_b = locate(b_rank)
            # boundary rows gathered straight from the ring (the [W, cap]
            # tuple store is never rolled — only its [T]-sized gathers)
            ga = rows[jnp.mod(shift + row_a, W)]         # [T, cap]
            gb = rows[jnp.mod(shift + row_b, W)]
            col = jnp.arange(cap, dtype=jnp.int32)[None, :]
            n_a = cnt_row[row_a].astype(jnp.int32)

            results = []
            for sp, agg_o in zip(specs, aggs_o):
                wdt = agg_o.shape[1]
                ident = jnp.asarray(sp.identity, agg_o.dtype)

                def boundary(g, keep):        # [T, cap] masked row fold
                    lifted = sp.lift_dense(g.reshape(-1)).reshape(
                        g.shape[0], cap, -1)
                    lifted = jnp.where(keep[:, :, None], lifted, ident)
                    if sp.kind == "sum":
                        return jnp.sum(lifted, axis=1)
                    return (jnp.min if sp.kind == "min" else jnp.max)(
                        lifted, axis=1)

                if sp.kind == "sum":
                    Pr = jnp.concatenate(
                        [jnp.zeros((1, wdt), agg_o.dtype),
                         jnp.cumsum(agg_o, axis=0)])
                    # S(x) = full rows below row(x) + head of row(x)
                    Sa = Pr[row_a] + boundary(ga, col < off_a[:, None])
                    Sb = Pr[row_b] + boundary(gb, col < off_b[:, None])
                    res = Sb - Sa
                else:
                    # tail of row(a) ∪ full rows (row_a, row_b) ∪ head of
                    # row(b); same-row ranges use one masked fold
                    same = row_a == row_b
                    seg = boundary(
                        ga, (col >= off_a[:, None])
                        & jnp.where(same[:, None], col < off_b[:, None],
                                    col < n_a[:, None]))
                    mid = ec._range_combine(
                        agg_o, row_a + 1,
                        jnp.maximum(row_b - row_a - 1, 0),
                        red[sp.kind], sp.identity, row_levels)
                    head = boundary(gb, col < jnp.where(same, 0,
                                                        off_b)[:, None])
                    res = red[sp.kind](seg, red[sp.kind](mid, head))
                results.append(
                    jnp.where((tmask & (cnt > 0))[:, None], res, ident))

            if cdm:
                dm = dm._replace(
                    triggers=dm.triggers + jnp.sum(tmask),
                    windows_nonempty=dm.windows_nonempty
                    + jnp.sum(tmask & (cnt > 0)))
                # occupancy of the ms-row ring: retained rows with data
                dm = _dev.record_occupancy(
                    dm, jnp.sum((cnt_row > 0).astype(jnp.int64)), W)

            new_state = CountRowState(
                rows=rows, row_aggs=tuple(row_aggs),
                overflow=state.overflow | bad)
            return new_state, dm, (ws, we, cnt, tuple(results))

        self._step = jax.jit(step, donate_argnums=(0, 1))
        self._init = lambda: CountRowState(
            rows=jnp.zeros((W, cap), jnp.float32),
            row_aggs=tuple(
                jnp.full((W, sp.width), sp.identity,
                         jnp.dtype(self.config.partial_dtype))
                for sp in specs),
            overflow=jnp.asarray(False))
        self._root = None
        self.state = None
        self._interval = 0

    # -- driver hooks ------------------------------------------------------
    #: the anchor is the overflow flag, not a slice count — the driver's
    #: occupancy gauges don't apply to the fixed [W, cap] row ring
    _anchor_is_slices = False

    def _init_pipeline_state(self) -> None:
        self.state = self._init()

    def _sync_anchor(self):
        return self.state.overflow

    def _interval_tuples(self, i: int) -> int:
        """Telemetry: intervals before the late reach warms up carry
        only the in-order stream plus the partial late strata (interval
        i's band spans min(sc, i*P) rows)."""
        late_i = self.E * min(i * self.wm_period_ms, self.sc)
        if self.obs is not None and self.L:
            self.obs.counter(_obs.LATE_TUPLES).inc(late_i)
            if self.rem and i >= self.q:
                # sub-period lateness relaxation active (ISSUE 11):
                # the partial oldest stratum carried `rem` rows this
                # interval — gated so a silent flip into/out of the
                # relaxed retention model fails `obs diff`
                self.obs.counter(
                    _obs.COUNT_LATENESS_RELAXED_ROWS).inc(self.rem)
        return self.SR + late_i

    def check_overflow(self) -> None:
        import jax

        if bool(jax.device_get(self.state.overflow)):
            e = RuntimeError(
                "count row-window underrun: a trigger reached below the "
                "retained per-ms rows — widen the retention model "
                "(windows larger than expected?). Overflow policies do "
                "not apply here: the ring is sized by the window spec, "
                "not by load, so shedding/growing cannot repair a "
                "mis-sized retention model")
            if self.obs is not None:
                self.obs.counter(_obs.OVERFLOWS).inc()
                self.obs.record_failure(e, kind=_flight.OVERFLOW,
                                        config=self.config)
            raise e

    def lowered_results(self, interval_out) -> list:
        """Fetch + lower one interval's window results on host — the
        same face every other fused pipeline exposes, so the Supervisor
        (and the ISSUE 8 crash-point sweep) can drive count pipelines
        through ``run_pipeline`` like any other class."""
        from .pipeline import lower_interval

        return lower_interval(self.aggregations, interval_out)

    # -- test/replay face --------------------------------------------------
    def materialize_interval(self, i: int):
        """Regenerate interval ``i``'s tuples on host, in ARRIVAL order
        (late lanes first — they arrive at the start of the interval, in
        ms order, ``E`` per row over the lateness span — then the paced
        in-order lanes): ``(vals f32, ts i64)``. Bit-identical to what
        the fused step folds in (same fold_in keying and draws)."""
        import jax
        import jax.numpy as jnp

        if self._root is None:
            self._root = jax.random.PRNGKey(self.seed)
        P, u, E, q = self.wm_period_ms, self.u, self.E, self.q
        rem = self.rem
        key = self._interval_key(i)
        base = np.int64(i) * P
        vin = np.asarray(jax.random.uniform(
            key, (P, u), dtype=jnp.float32)) * self.value_scale
        ts_in = base + np.repeat(np.arange(P, dtype=np.int64), u)
        parts_v, parts_t = [], []
        if E:
            for a in range(min(i, q), 0, -1):  # oldest rows first (ms asc)
                ka = jax.random.fold_in(key, 0x70000000 + a)
                lv = np.asarray(jax.random.uniform(
                    ka, (P, E), dtype=jnp.float32)) * self.value_scale
                lo = int(base) - a * P
                # the partial oldest stratum keeps only the tail rows
                # inside the lateness span (the fused step masks the
                # same rows)
                rows_lo = P - rem if (rem and a == q) else 0
                parts_v.append(lv[rows_lo:].reshape(-1))
                parts_t.append(lo + np.repeat(
                    np.arange(rows_lo, P, dtype=np.int64), E))
        parts_v.append(vin.reshape(-1))
        parts_t.append(ts_in)
        return (np.concatenate(parts_v).astype(np.float32),
                np.concatenate(parts_t))
