"""Fused stream pipeline: source → slicing → trigger/query → GC in ONE
jitted program per watermark interval.

This is the benchmark-shaped execution mode (the reference's BenchmarkJob
pipeline — LoadGeneratorSource → operator → sink inside one Flink task,
benchmark/.../BenchmarkJob.java:26-103) re-designed for the XLA dispatch
model: per-computation dispatch overhead dominates when the host drives the
device batch-by-batch (hundreds of ms per execution on tunneled devices,
~10 µs locally — either way it bounds small-batch rates), so the whole
watermark interval — G generator+ingest sub-batches via ``lax.scan``,
device-side trigger enumeration, the range-query final merge, and GC —
compiles into one program whose single dispatch amortizes over millions of
tuples.

Device-side trigger enumeration: for each registered window the number of
possible triggers per interval is static (``period // grid + 2``), so
trigger (start, end) arrays are a fixed-shape grid with a validity mask —
the device-side equivalent of WindowManager's per-watermark enumeration
(WindowManager.java:104-118, TumblingWindow.java:34-39,
SlidingWindow.java:50-57).
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from .. import jax_config  # noqa: F401

from ..core.aggregates import AggregateFunction
from ..core.windows import (
    FixedBandWindow,
    SlidingWindow,
    TumblingWindow,
    WindowMeasure,
)
from .config import EngineConfig


class StreamPipeline:
    """One fused XLA step per watermark interval.

    ``windows``: context-free Time-measure windows (static).
    ``throughput``: offered tuples per event-second (generator rate —
    LoadGeneratorSource.java:45-57's role).
    ``wm_period_ms``: event-time between watermarks (ThroughputLogger-style
    cadence; the reference triggers per watermark, not per tuple).
    """

    def __init__(self, windows: Sequence, aggregations: Sequence[AggregateFunction],
                 config: Optional[EngineConfig] = None,
                 throughput: int = 50_000_000, wm_period_ms: int = 1000,
                 max_lateness: int = 1000, seed: int = 0,
                 sub_batch: int = 1 << 18):
        import jax
        import jax.numpy as jnp

        from . import core as ec

        self.config = config or EngineConfig()
        self.windows = list(windows)
        self.aggregations = list(aggregations)
        self.max_lateness = max_lateness
        self.wm_period_ms = wm_period_ms
        self.seed = seed

        B = sub_batch
        tuples_per_interval = throughput * wm_period_ms // 1000
        G = max(1, tuples_per_interval // B)
        self.G, self.B = G, B
        self.tuples_per_interval = G * B
        span = wm_period_ms / G            # event-ms per sub-batch

        periods, bands = [], []
        max_fixed = 0
        for w in self.windows:
            if w.measure != WindowMeasure.Time:
                raise NotImplementedError("pipeline: time-measure only")
            max_fixed = max(max_fixed, w.clear_delay())
            if isinstance(w, TumblingWindow):
                periods.append(int(w.size))
            elif isinstance(w, SlidingWindow):
                periods.append(int(w.slide))
            elif isinstance(w, FixedBandWindow):
                bands.append((int(w.start), int(w.size)))
            else:
                raise NotImplementedError(f"pipeline: {type(w).__name__}")
        spec = ec.EngineSpec(
            periods=tuple(sorted(set(periods))),
            bands=tuple(sorted(set(bands))),
            count_periods=(),
            aggs=tuple(a.device_spec() for a in self.aggregations),
        )
        self.spec = spec
        C, A = self.config.capacity, self.config.annex_capacity
        ingest = ec.build_ingest(spec, C, A, assume_inorder=True)
        query = ec.build_query(spec, C, A)
        gc = ec.build_gc(spec, C, A)
        self._init_state = lambda: ec.init_state(spec, C, A)

        # ---- static trigger grid per window ------------------------------
        # window j with grid g_j (slide/size) triggers at ends = multiples of
        # g_j in (last_wm, wm]; at most period // g_j + 1 per interval.
        trig_layout = []                   # (grid, size, maxk, kind)
        for w in self.windows:
            if isinstance(w, TumblingWindow):
                trig_layout.append((int(w.size), int(w.size),
                                    wm_period_ms // int(w.size) + 1, "t"))
            elif isinstance(w, SlidingWindow):
                trig_layout.append((int(w.slide), int(w.size),
                                    wm_period_ms // int(w.slide) + 1, "s"))
            elif isinstance(w, FixedBandWindow):
                trig_layout.append((int(w.start), int(w.size), 1, "b"))
        self.T = sum(m for _, _, m, _ in trig_layout)
        P = wm_period_ms

        valid_all = np.ones((B,), bool)

        def make_triggers(last_wm, wm):
            ws_parts, we_parts, valid_parts = [], [], []
            for (g, size, maxk, kind) in trig_layout:
                if kind == "b":
                    end = jnp.asarray([g + size], jnp.int64)
                    start = jnp.asarray([g], jnp.int64)
                    ok = (end >= last_wm) & (end <= wm)
                else:
                    first_end = (last_wm // g + 1) * g
                    ends = first_end + g * jnp.arange(maxk, dtype=jnp.int64)
                    starts = ends - size
                    ok = ends <= wm
                    if kind == "s":
                        # SlidingWindow.java:50-57 guards
                        ok = ok & (starts >= 0) & (ends <= wm + 1)
                    start, end = starts, ends
                ws_parts.append(start)
                we_parts.append(end)
                valid_parts.append(ok)
            return (jnp.concatenate(ws_parts), jnp.concatenate(we_parts),
                    jnp.concatenate(valid_parts))

        def step(state, key, interval_idx):
            last_wm = interval_idx * P
            wm = last_wm + P

            def body(st, g):
                kg = jax.random.fold_in(key, g)
                lo = (last_wm + g * span).astype(jnp.float64)
                gaps = jax.random.uniform(kg, (B,), dtype=jnp.float32)
                gaps = gaps / jnp.sum(gaps) * span
                ts = lo.astype(jnp.int64) + jnp.cumsum(gaps).astype(jnp.int64)
                vals = jax.random.uniform(kg, (B,), dtype=jnp.float32) * 10_000
                return ingest(st, ts, vals, valid_all), None

            state, _ = jax.lax.scan(body, state, jnp.arange(G))
            ws, we, tmask = make_triggers(last_wm, wm)
            is_count = jnp.zeros_like(tmask)
            cnt, results = query(state, ws, we, tmask, is_count)
            bound = wm - max_lateness - max_fixed
            state = gc(state, jnp.int64(bound))
            return state, (ws, we, cnt, results)

        self._step = jax.jit(step, donate_argnums=0)
        self._key = None
        self.state = None

    def reset(self) -> None:
        self.state = self._init_state()

    def run(self, n_intervals: int, collect: bool = True):
        """Run n watermark intervals; returns list of per-interval
        (ws, we, cnt, results) device handles (fetch with jax.device_get)."""
        import jax

        if self.state is None:
            self.reset()
        root = jax.random.PRNGKey(self.seed)
        out = []
        for i in range(n_intervals):
            self.state, res = self._step(self.state,
                                         jax.random.fold_in(root, i),
                                         np.int64(i))
            if collect:
                out.append(res)
        return out

    def lowered_results(self, interval_out) -> list:
        """Fetch + lower one interval's window results on host."""
        import jax

        ws, we, cnt, results = jax.device_get(interval_out)
        rows = []
        lowered = []
        for agg, res in zip(self.aggregations, results):
            spec = agg.device_spec()
            lowered.append(np.asarray(spec.lower(res, cnt)))
        for i in range(ws.shape[0]):
            if cnt[i] > 0:
                rows.append((int(ws[i]), int(we[i]), int(cnt[i]),
                             [lw[i] for lw in lowered]))
        return rows
