"""Fused stream pipeline: source → slicing → trigger/query → GC in ONE
jitted program per watermark interval.

This is the benchmark-shaped execution mode (the reference's BenchmarkJob
pipeline — LoadGeneratorSource → operator → sink inside one Flink task,
benchmark/.../BenchmarkJob.java:26-103) re-designed for the XLA dispatch
model: per-computation dispatch overhead dominates when the host drives the
device batch-by-batch (hundreds of ms per execution on tunneled devices,
~10 µs locally — either way it bounds small-batch rates), so the whole
watermark interval — G generator+ingest sub-batches via ``lax.scan``,
device-side trigger enumeration, the range-query final merge, and GC —
compiles into one program whose single dispatch amortizes over millions of
tuples.

Device-side trigger enumeration: for each registered window the number of
possible triggers per interval is static (``period // grid + 2``), so
trigger (start, end) arrays are a fixed-shape grid with a validity mask —
the device-side equivalent of WindowManager's per-watermark enumeration
(WindowManager.java:104-118, TumblingWindow.java:34-39,
SlidingWindow.java:50-57).
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import List, NamedTuple, Optional, Sequence

import numpy as np

from .. import jax_config  # noqa: F401
from .. import obs as _obs
from ..obs import flight as _flight
from ..obs import latency as _lat

from ..core.aggregates import AggregateFunction
from ..core.windows import (
    FixedBandWindow,
    SlidingWindow,
    TumblingWindow,
    WindowMeasure,
)
from .config import EngineConfig


def half_draw_parts(bits, value_scale: float):
    """The two 16-bit-granular value halves of 32-bit draws, as separate
    arrays — for consumers that must avoid the concatenation (another
    fusion breaker: lifting the halves separately kept the sub-row
    chunked interval fused, 178 → 44 ms per 800 M tuples)."""
    import jax.numpy as jnp

    sc = jnp.float32(value_scale / 65536.0)
    lo = (bits & jnp.uint32(0xffff)).astype(jnp.float32) * sc
    hi = (bits >> 16).astype(jnp.float32) * sc
    return lo, hi


def half_draw(bits, value_scale: float):
    """Expand 32-bit draws into TWO 16-bit-granular uniform values over
    ``[0, value_scale)``, laid out as blocks (lo half then hi half) along
    the LAST axis. The layout is load-bearing: a stride-2 interleave
    breaks XLA's producer fusion into dot operands (measured
    2.75 G → 0.77 G on the factored-histogram quantile cell), and the
    bucket/keyed generators must agree bit-exactly with the aligned one.
    Callers pass ``jax.random.bits(..., dtype=jnp.uint32)`` — under x64
    the default widens to uint64 and silently rescales the values."""
    import jax.numpy as jnp

    lo, hi = half_draw_parts(bits, value_scale)
    return jnp.concatenate([lo, hi], axis=-1)


def draw_uniform16(key, shape, value_scale: float):
    """The benchmark generators' value draw: ``shape`` values uniform
    over 65536 levels in ``[0, value_scale)`` via the half-draw block
    layout when ``shape[-1]`` is even (two values per 32-bit threefry
    draw), plain f32 uniforms otherwise. Every generator (aligned,
    bucket, keyed, session — device AND host-replay faces) goes through
    THIS function so the streams cannot drift."""
    import jax
    import jax.numpy as jnp

    if shape[-1] % 2 == 0:
        bits = jax.random.bits(key, shape[:-1] + (shape[-1] // 2,),
                               dtype=jnp.uint32)
        return half_draw(bits, value_scale)
    return jax.random.uniform(key, shape, dtype=jnp.float32) * value_scale


def build_trigger_grid(windows, wm_period_ms: int):
    """Device-side trigger enumeration with a static layout.

    For each window the number of possible triggers per watermark interval is
    static (``period // grid + 2``), so the per-interval (start, end) arrays
    are a fixed-shape grid with a validity mask — the device-side equivalent
    of WindowManager's per-watermark enumeration (WindowManager.java:104-118,
    TumblingWindow.java:34-39, SlidingWindow.java:50-57; ascending per window
    rather than the reference's backward walk).

    Returns ``(make_triggers(last_wm, wm) -> (ws, we, valid), T)``.
    """
    import jax.numpy as jnp

    trig_layout = []                   # (grid, size, maxk, kind)
    for w in windows:
        if isinstance(w, TumblingWindow):
            trig_layout.append((int(w.size), int(w.size),
                                wm_period_ms // int(w.size) + 1, "t"))
        elif isinstance(w, SlidingWindow):
            # +2: the reference guard is end <= wm+1 (SlidingWindow.java:54),
            # so an interval can include both boundary ends last_wm+1 and
            # wm+1 — including re-emitting a window already emitted at the
            # previous watermark (ends in (last_wm, wm+1] overlap across
            # consecutive intervals at exactly end == wm+1; reference quirk,
            # reproduced for parity).
            trig_layout.append((int(w.slide), int(w.size),
                                wm_period_ms // int(w.slide) + 2, "s"))
        elif isinstance(w, FixedBandWindow):
            trig_layout.append((int(w.start), int(w.size), 1, "b"))
        else:
            raise NotImplementedError(f"pipeline: {type(w).__name__}")

    if len(trig_layout) <= 32:
        # few windows: per-window parts, exact trigger counts
        def make_triggers(last_wm, wm):
            ws_parts, we_parts, valid_parts = [], [], []
            for (g, size, maxk, kind) in trig_layout:
                if kind == "b":
                    end = jnp.asarray([g + size], jnp.int64)
                    start = jnp.asarray([g], jnp.int64)
                    ok = (end >= last_wm) & (end <= wm)
                elif kind == "s":
                    # starts lie on the slide grid; ends = start + size are
                    # NOT multiples of the slide when size % slide != 0, so
                    # enumerate starts: smallest grid start with
                    # end > last_wm.
                    first_start = ((last_wm - size) // g + 1) * g
                    starts = first_start + g * jnp.arange(maxk,
                                                          dtype=jnp.int64)
                    ends = starts + size
                    # SlidingWindow.java:50-57 guards (note <= wm + 1)
                    ok = (starts >= 0) & (ends <= wm + 1)
                    start, end = starts, ends
                else:
                    first_end = (last_wm // g + 1) * g
                    ends = first_end + g * jnp.arange(maxk, dtype=jnp.int64)
                    starts = ends - size
                    ok = ends <= wm
                    start, end = starts, ends
                ws_parts.append(start)
                we_parts.append(end)
                valid_parts.append(ok)
            return (jnp.concatenate(ws_parts), jnp.concatenate(we_parts),
                    jnp.concatenate(valid_parts))

        return make_triggers, sum(m for _, _, m, _ in trig_layout)

    # many windows (e.g. 1000 random tumbling): a per-window op chain makes
    # the traced graph O(5·n_windows) and OOM-kills the XLA compiler. Build
    # ONE [N, K] grid per window kind instead (K = that kind's max trigger
    # count; rows padded with an invalid mask), then restore exact
    # registration order with a single static gather.
    groups = {"t": [], "s": [], "b": []}
    for idx, (g, size, maxk, kind) in enumerate(trig_layout):
        groups[kind].append((idx, g, size, maxk))
    # static row layout: (window idx, k) for each emitted slot, kind-grouped
    slot_owner = []
    for kind in ("t", "s", "b"):
        rows = groups[kind]
        if not rows:
            continue
        K = max(m for _, _, _, m in rows)
        for (idx, _, _, _) in rows:
            for k in range(K):
                slot_owner.append((idx, k))
    # permutation restoring registration order, dropping over-padded slots
    # beyond each window's own maxk
    slot_of = {ik: pos for pos, ik in enumerate(slot_owner)}
    order = []
    for idx, (g, size, maxk, kind) in enumerate(trig_layout):
        for k in range(maxk):
            order.append(slot_of[(idx, k)])
    perm = np.asarray(order, dtype=np.int64)
    T_total = perm.shape[0]

    def make_triggers_grouped(last_wm, wm):
        ws_parts, we_parts, ok_parts = [], [], []
        for kind in ("t", "s", "b"):
            rows = groups[kind]
            if not rows:
                continue
            K = max(m for _, _, _, m in rows)
            gs = jnp.asarray([g for _, g, _, _ in rows], jnp.int64)[:, None]
            szs = jnp.asarray([s for _, _, s, _ in rows],
                              jnp.int64)[:, None]
            mks = jnp.asarray([m for _, _, _, m in rows],
                              jnp.int64)[:, None]
            k = jnp.arange(K, dtype=jnp.int64)[None, :]
            if kind == "b":
                ends = gs + szs + 0 * k
                starts = gs + 0 * k
                ok = (ends >= last_wm) & (ends <= wm)
            elif kind == "s":
                first_start = ((last_wm - szs) // gs + 1) * gs
                starts = first_start + gs * k
                ends = starts + szs
                ok = (starts >= 0) & (ends <= wm + 1)
            else:
                first_end = (last_wm // gs + 1) * gs
                ends = first_end + gs * k
                starts = ends - szs
                ok = ends <= wm
            ok = ok & (k < mks)
            ws_parts.append(starts.reshape(-1))
            we_parts.append(ends.reshape(-1))
            ok_parts.append(ok.reshape(-1))
        return (jnp.concatenate(ws_parts)[perm],
                jnp.concatenate(we_parts)[perm],
                jnp.concatenate(ok_parts)[perm])

    return make_triggers_grouped, T_total


QUERY_KIND_TUMBLING = 0
QUERY_KIND_SLIDING = 1


@dataclass(frozen=True)
class SlotGeometry:
    """Static geometry of a dynamic-query slot grid (scotty_tpu.serving).

    The serving layer pads runtime window sets to power-of-two slot grids
    so register/cancel stays inside one compiled executable: ``n_slots``
    query rows, each answering up to ``triggers_per_slot`` triggers per
    watermark interval, over the fixed aligned ``slice_grid``. Everything
    here is trace-time static — changing any field is a new compile-cache
    bucket (scotty_tpu.serving.cache), never an in-place mutation.
    """

    #: padded query-slot rows ([Q] mask/param arrays; power of two)
    n_slots: int
    #: static per-slot trigger lanes K: every admitted window must satisfy
    #: ``wm_period // grid + 2 <= K`` (grid = slide for sliding windows,
    #: size for tumbling)
    triggers_per_slot: int
    #: the aligned slice grid g (ms). Admission requires every window
    #: size/slide to be a multiple — the aligned pipeline's exactness
    #: condition (window edges land on slice edges)
    slice_grid: int
    #: retention bound fed to GC in place of the static set's max
    #: ``clear_delay()`` — the largest window size admission will accept,
    #: so slices live long enough for any query registered later
    max_size: int

    def __post_init__(self):
        for f in ("n_slots", "triggers_per_slot", "slice_grid", "max_size"):
            if int(getattr(self, f)) < 1:
                raise ValueError(f"SlotGeometry.{f} must be >= 1")


class QuerySlots(NamedTuple):
    """Device-resident query table: the ``[Q]`` window-parameter rows and
    the active mask carried in the serving step's donated state. A
    register/cancel is ONE row write (``dynamic_update_slice`` via
    ``.at[i].set``) — never a retrace."""

    kinds: "jnp.ndarray"     # [Q] int32: QUERY_KIND_TUMBLING | _SLIDING
    grids: "jnp.ndarray"     # [Q] int64: slide (sliding) / size (tumbling)
    sizes: "jnp.ndarray"     # [Q] int64 window size
    active: "jnp.ndarray"    # [Q] bool


def init_query_slots(geometry: SlotGeometry,
                     rows: Optional[dict] = None) -> QuerySlots:
    """Fresh device table — all slots inactive (grid/size 1 so the masked
    per-slot trigger arithmetic never divides by zero), or uploaded from a
    host mirror dict of numpy rows (``kinds/grids/sizes/active``)."""
    import jax
    import jax.numpy as jnp

    Q = geometry.n_slots
    if rows is None:
        kinds = np.zeros((Q,), np.int32)
        grids = np.ones((Q,), np.int64)
        sizes = np.ones((Q,), np.int64)
        active = np.zeros((Q,), bool)
    else:
        kinds = np.asarray(rows["kinds"], np.int32)
        grids = np.asarray(rows["grids"], np.int64)
        sizes = np.asarray(rows["sizes"], np.int64)
        active = np.asarray(rows["active"], bool)
        if kinds.shape != (Q,):
            raise ValueError(
                f"query-table rows have {kinds.shape[0]} slots, geometry "
                f"expects {Q}")
    dev = jax.device_put((kinds, grids, sizes, active))
    return QuerySlots(jnp.asarray(dev[0]), jnp.asarray(dev[1]),
                      jnp.asarray(dev[2]), jnp.asarray(dev[3]))


def build_slot_trigger_grid(geometry: SlotGeometry, wm_period_ms: int):
    """Mask-aware trigger enumeration over a dynamic query-slot table.

    The static :func:`build_trigger_grid` bakes each window's (grid, size,
    kind) into the traced program; here they are DATA — ``[Q]`` device rows
    read from the carried :class:`QuerySlots` — so registering or
    cancelling a query never retraces. Per slot the same per-kind trigger
    formulas run over a static ``[Q, K]`` lane grid (K =
    ``geometry.triggers_per_slot``); lanes beyond a slot's own trigger
    count, and whole slots with ``active=False``, fold into the validity
    mask the query kernel already consumes.

    Trigger semantics are identical to the static builder (tumbling: ends
    on the size grid, ``end <= wm``; sliding: starts on the slide grid,
    ``start >= 0 & end <= wm + 1`` — the reference guard
    SlidingWindow.java:50-57 quirk included), so a slot's rows bit-match
    the rows a static pipeline computes for the same window.

    Returns ``(make_triggers(slots, last_wm, wm) -> (ws, we, valid), T)``
    with ``T = Q * K``; row ``q*K + k`` belongs to slot ``q``.
    """
    import jax.numpy as jnp

    Q, K = geometry.n_slots, geometry.triggers_per_slot
    P = wm_period_ms

    def make_triggers(slots: QuerySlots, last_wm, wm):
        g = slots.grids[:, None]                       # [Q, 1]
        sz = slots.sizes[:, None]
        k = jnp.arange(K, dtype=jnp.int64)[None, :]    # [1, K]
        # tumbling: ends on the size grid (grid == size)
        t_ends = (last_wm // g + 1) * g + g * k
        t_starts = t_ends - sz
        t_ok = t_ends <= wm
        # sliding: starts on the slide grid; ends = start + size are NOT
        # grid multiples when size % slide != 0, so enumerate starts
        s_starts = ((last_wm - sz) // g + 1) * g + g * k
        s_ends = s_starts + sz
        s_ok = (s_starts >= 0) & (s_ends <= wm + 1)
        sliding = (slots.kinds == QUERY_KIND_SLIDING)[:, None]
        ws = jnp.where(sliding, s_starts, t_starts)
        we = jnp.where(sliding, s_ends, t_ends)
        # exact per-slot trigger count (build_trigger_grid's maxk): the
        # static lane count K only bounds it — admission enforces K is
        # large enough for every admitted window
        maxk = P // slots.grids + jnp.where(
            slots.kinds == QUERY_KIND_SLIDING, 2, 1)
        ok = (jnp.where(sliding, s_ok, t_ok)
              & (k < maxk[:, None]) & slots.active[:, None])
        return ws.reshape(-1), we.reshape(-1), ok.reshape(-1)

    return make_triggers, Q * K


def lower_interval_columns(aggregations: Sequence[AggregateFunction],
                           interval_out):
    """Fetch one interval's trigger columns and host-lower each
    aggregation: ``(ws, we, cnt, [per-agg lowered [T] arrays])`` — the
    one place the lowering contract lives (row-shaped consumers:
    :func:`lower_interval`; slot-attributed consumers:
    ``serving.QueryService.results_by_slot``)."""
    import jax

    ws, we, cnt, results = jax.device_get(interval_out)
    lowered = []
    for agg, res in zip(aggregations, results):
        spec = agg.device_spec()
        lowered.append(np.asarray(spec.lower(res, cnt)))
    return ws, we, cnt, lowered


def lower_interval(aggregations: Sequence[AggregateFunction], interval_out):
    """Fetch + lower one interval's window results on host: list of
    (start, end, count, [per-agg final value]) for non-empty windows."""
    ws, we, cnt, lowered = lower_interval_columns(aggregations, interval_out)
    rows = []
    for i in range(ws.shape[0]):
        if cnt[i] > 0:
            rows.append((int(ws[i]), int(we[i]), int(cnt[i]),
                         [lw[i] for lw in lowered]))
    return rows


class FusedPipelineDriver:
    """Shared host driver for the fused per-interval pipelines
    (:class:`AlignedStreamPipeline`, :class:`StreamPipeline`,
    :class:`.session_pipeline.SessionStreamPipeline`,
    :class:`..parallel.keyed.KeyedAlignedPipeline`,
    :class:`..bench.buckets.BucketWindowPipeline`): stateful interval
    numbering, per-interval PRNG keying, GC cadence, and the
    device_get-based sync (``block_until_ready`` is not a reliable
    barrier on tunneled devices — docs/DESIGN.md). Subclasses set
    ``wm_period_ms``, ``max_lateness``, ``max_fixed``, ``gc_every``,
    ``seed``, implement ``_init_pipeline_state()``,
    ``_step_interval(key, i) -> result`` and ``_sync_anchor()``, and
    optionally ``_gc(bound)`` for out-of-step GC.
    """

    #: attached Observability (scotty_tpu.obs) — None = zero-overhead off.
    #: Host-side hooks fire at interval boundaries; the IN-JIT telemetry
    #: (obs/device.py DeviceMetrics) rides the carried state and is folded
    #: into the registry at sync().
    obs = None
    #: whether _sync_anchor() is the live-slice count (occupancy gauges);
    #: pipelines whose anchor is something else (count pipeline: the
    #: overflow flag) set this False
    _anchor_is_slices = True
    #: pipelines whose jitted step threads a DeviceMetrics pytree set this
    #: True (their _step takes and returns the dm as the second carry);
    #: others (buckets baseline, keyed) keep the two-value contract
    _uses_device_metrics = False
    #: static at construction: False builds the step WITHOUT the in-jit
    #: counter updates (the dm passes through untouched — the overhead
    #: A/B baseline and an escape hatch)
    collect_device_metrics = True
    #: the carried DeviceMetrics (device pytree); None until reset() on a
    #: supporting pipeline
    dm = None
    #: the jitted step contains a Pallas kernel (set by pipelines whose
    #: config enables one) — run loops count ``pallas_kernel_dispatches``
    #: host-side per dispatch when this is True
    _pallas_in_step = False
    #: arrival-paced micro-batching (``run_streamed``): bound the
    #: in-flight micro queue to one via a tiny anchor fetch per
    #: micro-dispatch — the streaming discipline of a source that
    #: delivers micro-batches at the sustainable rate (the latency
    #: bench arm turns this on; throughput runs leave it off)
    micro_pace = False
    #: device-resident dynamic-query table (:class:`QuerySlots`) carried in
    #: the serving step's donated state; None on every static pipeline
    _qstate = None
    #: times the jitted step's Python body ran — i.e. jit TRACES. The
    #: serving layer's zero-steady-state-retrace contract is asserted on
    #: this counter (scotty_tpu.serving; the churn bench records its delta)
    _trace_count = 0

    def set_observability(self, obs) -> None:
        """Attach an :class:`scotty_tpu.obs.Observability`; pass ``None``
        to detach. Telemetry recorded per interval: ``interval_step_ms``
        histogram, ``ingest_tuples`` counter; per :meth:`sync`:
        ``sync_ms`` histogram + ``slice_occupancy``/``slice_headroom``
        gauges (sync is the drain point — the one place occupancy is
        host-known without adding a device round trip) + the in-jit
        DeviceMetrics delta folded as ``device_*`` counters. Attaching
        mid-run baselines the device counters at the last drained
        snapshot, so pre-attach (warmup) tuples don't pollute the fold."""
        self.obs = obs
        if obs is not None and self._uses_device_metrics:
            self._dm_folded = getattr(self, "_dm_host", None)

    def device_metrics(self):
        """Fetch + flatten the in-jit DeviceMetrics as a ``device_*`` name
        → int dict (one device sync). None when this pipeline doesn't
        thread device telemetry or hasn't started."""
        if self.dm is None:
            return None
        import jax

        from ..obs import device as _dev

        return _dev.host_snapshot(jax.device_get(self.dm))

    def _interval_tuples(self, i: int) -> int:
        """Host-known tuple count interval ``i`` ingests (telemetry)."""
        return int(getattr(self, "tuples_per_interval", 0))

    def reset(self) -> None:
        import jax

        self._root = jax.random.PRNGKey(self.seed)
        self._interval = 0
        self._init_pipeline_state()
        if self._uses_device_metrics:
            from ..obs import device as _dev

            self.dm = _dev.init_device_metrics()
            self._dm_host = None
            self._dm_folded = None
        self._pipeline_ready = True

    def _interval_key(self, i: int):
        import jax

        # the fold-in data rides an EXPLICIT device_put: the step loop
        # runs under jax.transfer_guard("disallow") in the differential
        # tests, and the per-interval index is the one sanctioned
        # host->device upload (an implicit-transfer creep anywhere else
        # in the step fails those tests)
        return jax.random.fold_in(self._root,
                                  jax.device_put(np.uint32(i)))

    def _needs_reset(self) -> bool:
        # NOT keyed on _root: the materialize_* helpers lazily seed _root
        # on a fresh pipeline, which must not make run() skip state init
        return not getattr(self, "_pipeline_ready", False)

    def _step_interval(self, key, i: int):
        import jax

        # explicit upload of the interval scalar (same sanctioned-
        # transfer contract as _interval_key; aval unchanged, so the
        # lowered step HLO is identical — pinned by tests/hlo_pins.json)
        iv = jax.device_put(np.int64(i))
        if self._qstate is not None:
            # serving mode: the query table rides the donated carry
            (self.state, self.dm, self._qstate,
             res) = self._step(self.state, self.dm, self._qstate, key,
                               iv)
        elif self._uses_device_metrics:
            self.state, self.dm, res = self._step(self.state, self.dm, key,
                                                  iv)
        else:
            self.state, res = self._step(self.state, key, iv)
        return res

    def _sync_anchor(self):
        return self.state.n_slices

    def run(self, n_intervals: int, collect: bool = True):
        """Advance n watermark intervals (continuing from the last call —
        interval numbering is stateful, so warmup + timed + latency phases
        see one continuous stream); returns the per-interval result
        handles. Dispatch only — no sync."""
        if self._needs_reset():
            self.reset()
        out = []
        for _ in range(n_intervals):
            _i, _lid, res = self._dispatch_interval(streamed=False)
            if collect:
                out.append(res)
        return out

    def _dispatch_interval(self, streamed: bool):
        """ONE interval's dispatch + bookkeeping, shared verbatim by
        :meth:`run` and :meth:`run_streamed` (a counter/stamp/GC change
        must not silently diverge the two loops): perf timing, the
        emission-latency lineage (ISSUE 14, host-side only — the step
        HLO stays pinned byte-identical: the chain opens at dispatch,
        and the step's own watermark advance IS the eligibility moment,
        so eligibility stamps the instant the dispatch returns), the
        interval counters, the Pallas dispatch count, and the GC
        cadence. Returns ``(interval, chain_key, result_handle)``."""
        import jax

        obs = self.obs
        lat = obs.latency if obs is not None else None
        i = self._interval
        t0 = time.perf_counter() if obs is not None else 0.0
        lid = lat.open() if lat is not None else None
        res = self._dispatch_streamed(i) if streamed \
            else self._step_interval(self._interval_key(i), i)
        if lid is not None:
            lat.stamp(lid, _lat.STAGE_ELIGIBILITY)
        self._interval += 1
        if obs is not None:
            obs.histogram(_obs.INTERVAL_STEP_MS).observe(
                (time.perf_counter() - t0) * 1e3)
            obs.counter(_obs.INGEST_TUPLES).inc(self._interval_tuples(i))
            if self._pallas_in_step:
                from .. import pallas as _pl

                _pl.record_dispatch(obs)
        if self._gc is not None and self._interval % self.gc_every == 0:
            self._gc(jax.device_put(
                np.int64(self._interval * self.wm_period_ms
                         - self.max_lateness - self.max_fixed)))
        return i, lid, res

    _gc = None                      # subclasses assign when GC is a
                                    # separate kernel outside the step

    # -- micro-batched streamed emission (ROADMAP item 4, ISSUE 15) -------
    def run_streamed(self, n_intervals: int, emit=None, depth: int = 1):
        """Streamed emission: dispatch interval N+1's work while
        fetching interval N's eligible windows, instead of queueing the
        whole run behind one drain. Per interval the driver dispatches
        the step (for pipelines with ``config.micro_batch > 1`` and
        micro support — the aligned pipeline — as M micro-batch
        dispatches plus one trigger/query flush), stamps ELIGIBILITY
        the moment the watermark-advancing dispatch returns, and
        fetches each interval's results as soon as ``depth`` newer
        intervals are in flight — so first-emit latency tracks one
        interval's residual compute, not the queued run (the PR 13
        drain-stage attribution shrinks accordingly; conservation stays
        exact because every stamp is a chain delta).

        Emitted results BIT-MATCH :meth:`run` on the same construction
        (same generation keying, same fold order); ``emit(i, host)`` is
        called per fetched interval. Returns the fetched host results
        in interval order.
        """
        if self._needs_reset():
            self.reset()
        from collections import deque

        obs = self.obs
        lat = obs.latency if obs is not None else None
        pending: "deque" = deque()
        out = []
        for _ in range(n_intervals):
            pending.append(self._dispatch_interval(streamed=True))
            while len(pending) > max(0, int(depth)):
                out.append(self._fetch_streamed(pending.popleft(), emit,
                                                lat))
        while pending:
            out.append(self._fetch_streamed(pending.popleft(), emit, lat))
        return out

    def _dispatch_streamed(self, i: int):
        """One interval's async dispatch — subclasses with a real
        micro-batched step (aligned) override; the base dispatches the
        whole-interval step (streamed fetch overlap only)."""
        return self._step_interval(self._interval_key(i), i)

    def _fetch_streamed(self, entry, emit, lat):
        """Fetch one queued interval's windows (the streamed drain):
        the chain closes here — drain and emit ride the same fetch."""
        import jax

        i, lid, res = entry
        host = jax.device_get(res)
        if lat is not None:
            lat.stamp(lid, _lat.STAGE_DRAIN)
            lat.stamp(lid, _lat.STAGE_EMIT)
            lat.finalize(lid)
        if emit is not None:
            emit(i, host)
        return host

    def sync(self) -> int:
        """Drain all queued device work; returns the anchor scalar. The
        in-jit DeviceMetrics pytree rides the same fetch (no extra round
        trip) and its delta folds into the registry as ``device_*``
        counters."""
        import jax

        obs = self.obs
        t0 = time.perf_counter() if obs is not None else 0.0
        if self.dm is not None:
            from ..obs import device as _dev

            v, dm_h = jax.device_get((self._sync_anchor(), self.dm))
        else:
            dm_h = None
            v = jax.device_get(self._sync_anchor())
        v = int(v)
        if obs is not None:
            obs.histogram(_obs.SYNC_MS).observe(
                (time.perf_counter() - t0) * 1e3)
            cap = getattr(getattr(self, "config", None), "capacity", 0)
            if self._anchor_is_slices and cap:
                obs.gauge(_obs.SLICE_OCCUPANCY).set(v / cap)
                obs.gauge(_obs.SLICE_HEADROOM).set(cap - v)
        if dm_h is not None:
            snap = _dev.host_snapshot(dm_h)
            self._dm_host = snap
            if obs is not None:
                self._dm_folded = _dev.fold_into(obs.registry, snap,
                                                 self._dm_folded)
        if obs is not None:
            # flight-recorder sample rides the SAME drain (no extra device
            # sync): the watermark this pipeline has advanced to plus the
            # registry deltas since the last drain land in the ring
            obs.flight_sync(watermark=self._interval * self.wm_period_ms)
            lat = obs.latency
            if lat is not None:
                # every queued interval's chain observes this one drain
                # (the sync drains them all); the drain IS the delivery
                # point of the steady-state pipelined flow, so chains
                # close here — the stamp rides the fetch that already
                # happened, zero extra syncs
                lat.stamp_open(_lat.STAGE_DRAIN)
                lat.finalize_open()
        return v

    def enforce_overflow_policy(self, factory=None, obs=None):
        """Apply ``EngineConfig.overflow_policy`` at a drain point and
        return the pipeline to continue with.

        ``fail`` (default) — :meth:`check_overflow` as today. ``grow`` —
        when the live-slice occupancy (read at the sync this method
        performs) reaches ``config.grow_occupancy``, snapshot the carried
        state via the checkpoint pytree machinery, rebuild through
        ``factory(grown_config)`` at 2× capacity and hand back the grown
        replacement (same interval counter / RNG root / DeviceMetrics —
        the continued run is bit-identical to one pre-sized larger);
        growth is preventive and bounded by ``config.max_capacity``.
        ``shed`` has no pipeline meaning (fused pipelines generate their
        own load in-jit — there is nothing external to shed; admission-
        boundary shedding lives in TpuWindowOperator/connectors) and
        behaves like ``fail`` here.

        This method owns the drain: it always performs ONE
        :meth:`sync` (which also folds the DeviceMetrics delta and, under
        GROW, doubles as the occupancy read) before the overflow check —
        callers like the Supervisor need no separate ``sync()`` per
        checkpoint chunk. Without a ``factory`` the method degrades to
        drain + :meth:`check_overflow`.
        """
        from ..resilience.policy import OverflowPolicy, grow_pipeline

        policy = getattr(self.config, "overflow_policy", OverflowPolicy.FAIL)
        n = self.sync()
        p = self
        if (policy == OverflowPolicy.GROW and factory is not None
                and self._anchor_is_slices):
            cap = self.config.capacity
            if n >= int(cap * getattr(self.config, "grow_occupancy", 0.85)):
                p = grow_pipeline(
                    self, factory,
                    obs=obs if obs is not None else self.obs)
        p.check_overflow()
        return p


class StreamPipeline(FusedPipelineDriver):
    """One fused XLA step per watermark interval.

    ``windows``: context-free Time-measure windows (static).
    ``throughput``: offered tuples per event-second (generator rate —
    LoadGeneratorSource.java:45-57's role).
    ``wm_period_ms``: event-time between watermarks (ThroughputLogger-style
    cadence; the reference triggers per watermark, not per tuple).
    """

    _uses_device_metrics = True

    def __init__(self, windows: Sequence, aggregations: Sequence[AggregateFunction],
                 config: Optional[EngineConfig] = None,
                 throughput: int = 50_000_000, wm_period_ms: int = 1000,
                 max_lateness: int = 1000, seed: int = 0,
                 sub_batch: int = 1 << 18, out_of_order_pct: float = 0.0,
                 collect_device_metrics: bool = True):
        import jax
        import jax.numpy as jnp

        from . import core as ec
        from ..obs import device as _dev

        self.collect_device_metrics = bool(collect_device_metrics)
        self.config = config or EngineConfig()
        self.windows = list(windows)
        self.aggregations = list(aggregations)
        self.max_lateness = max_lateness
        self.wm_period_ms = wm_period_ms
        self.seed = seed
        self.out_of_order_pct = float(out_of_order_pct)

        B = sub_batch
        tuples_per_interval = throughput * wm_period_ms // 1000
        G = max(1, tuples_per_interval // B)
        # disorder: each sub-batch is followed by a small sorted LATE batch
        # (tuples displaced back by < max_lateness) — the in-order base
        # takes the cheap kernel, only the late lanes pay the general
        # kernel's late/annex machinery, and the annex folds back once per
        # interval before the query. No sort anywhere: both parts are
        # sorted by construction.
        B_late = 0
        if self.out_of_order_pct > 0:
            n = int(B * self.out_of_order_pct)
            B_late = max(64, 1 << max(0, (n - 1).bit_length()))
        self.G, self.B, self.B_late = G, B, B_late
        self.tuples_per_interval = G * (B + (int(B * self.out_of_order_pct)
                                             if B_late else 0))
        span = wm_period_ms / G            # event-ms per sub-batch

        periods, bands = [], []
        max_fixed = 0
        for w in self.windows:
            if w.measure != WindowMeasure.Time:
                raise NotImplementedError("pipeline: time-measure only")
            if isinstance(w, TumblingWindow):
                periods.append(int(w.size))
            elif isinstance(w, SlidingWindow):
                periods.append(int(w.slide))
            elif isinstance(w, FixedBandWindow):
                bands.append((int(w.start), int(w.size)))
            else:
                raise NotImplementedError(f"pipeline: {type(w).__name__}")
            max_fixed = max(max_fixed, w.clear_delay())
        spec = ec.EngineSpec(
            periods=ec.collapse_periods(periods),
            bands=tuple(sorted(set(bands))),
            count_periods=(),
            aggs=tuple(a.device_spec() for a in self.aggregations),
        )
        self.spec = spec
        C, A = self.config.capacity, self.config.annex_capacity
        ingest = ec.build_ingest(spec, C, A, assume_inorder=True)
        ingest_general = ec.build_ingest(spec, C, A) if B_late else None
        annex_merge = ec.build_annex_merge(spec, C, A) if B_late else None
        query = ec.build_query(spec, C, A)
        gc = ec.build_gc(spec, C, A)
        self._init_state = lambda: ec.init_state(spec, C, A)

        # ---- static trigger grid per window ------------------------------
        make_triggers, self.T = build_trigger_grid(self.windows, wm_period_ms)
        P = wm_period_ms
        ooo = self.out_of_order_pct
        n_late = int(B * ooo)

        valid_all = np.ones((B,), bool)
        valid_late = np.zeros((B_late,), bool)
        valid_late[:n_late] = True

        # the reference's FIRST watermark clamps its trigger range to
        # wm - maxLateness (WindowManager.java:43-45, floored at the
        # bootstrap slice start 0); later watermarks continue from the
        # previous one. Latent until max_lateness < wm_period.
        first_lw = max(0, P - max_lateness)

        cdm = self.collect_device_metrics

        def step(state, dm, key, interval_idx):
            base = interval_idx * P
            last_wm = jnp.where(interval_idx > 0, base,
                                jnp.int64(first_lw))
            wm = base + P
            n_pre = state.n_slices

            def body(carry, g):
                st, dmc = carry
                kg = jax.random.fold_in(key, g)
                lo = (base + g * span).astype(jnp.float64)
                gaps = jax.random.uniform(kg, (B,), dtype=jnp.float32)
                gaps = gaps / jnp.sum(gaps) * span
                ts = lo.astype(jnp.int64) + jnp.cumsum(gaps).astype(jnp.int64)
                vals = jax.random.uniform(kg, (B,), dtype=jnp.float32) * 10_000
                st = ingest(st, ts, vals, valid_all)
                if B_late:
                    kl = jax.random.fold_in(kg, 7)
                    u = jax.random.uniform(kl, (2, B_late),
                                           dtype=jnp.float32)
                    lo_l = jnp.maximum(lo - max_lateness, 0.0)
                    lts = (lo_l + jnp.sort(u[0]).astype(jnp.float64)
                           * (lo - lo_l)).astype(jnp.int64)
                    lvals = u[1] * 10_000.0
                    if cdm:
                        # the arrival-order running max at this point IS
                        # st.max_event_time (the base sub-batch just
                        # folded), so the age calculus matches a host
                        # replay of the same arrival order exactly
                        lmask = jnp.asarray(valid_late)
                        dmc = _dev.record_late_ages(
                            dmc, st.max_event_time - lts, lmask)
                        dmc = dmc._replace(
                            late=dmc.late + jnp.sum(lmask))
                    st = ingest_general(st, lts, lvals,
                                        jnp.asarray(valid_late))
                return (st, dmc), None

            (state, dm), _ = jax.lax.scan(body, (state, dm),
                                          jnp.arange(G))
            if B_late:
                state = annex_merge(state)
            ws, we, tmask = make_triggers(last_wm, wm)
            is_count = jnp.zeros_like(tmask)
            cnt, results = query(state, ws, we, tmask, is_count)
            bound = wm - max_lateness - max_fixed
            if cdm:
                dm = dm._replace(
                    ingested=dm.ingested
                    + jnp.int64(G * (B + (n_late if B_late else 0))),
                    triggers=dm.triggers + jnp.sum(tmask),
                    windows_nonempty=dm.windows_nonempty
                    + jnp.sum(tmask & (cnt > 0)),
                    slices_touched=dm.slices_touched + jnp.maximum(
                        state.n_slices - n_pre, 0))
            state = gc(state, jnp.int64(bound))
            if cdm:
                dm = _dev.record_occupancy(dm, state.n_slices, C)
            return state, dm, (ws, we, cnt, results)

        self._step = jax.jit(step, donate_argnums=(0, 1))
        self._root = None
        self.state = None
        self._interval = 0

    def _init_pipeline_state(self) -> None:
        self.state = self._init_state()

    def check_overflow(self) -> None:
        import jax

        if bool(jax.device_get(self.state.overflow)):
            e = RuntimeError("slice buffer overflow: raise capacity or "
                             "advance watermarks more often")
            if self.obs is not None:
                self.obs.counter(_obs.OVERFLOWS).inc()
                self.obs.record_failure(e, kind=_flight.OVERFLOW,
                                        config=self.config)
            raise e

    def materialize_interval(self, i: int):
        """Regenerate interval i's tuple stream on host (testing), in
        ARRIVAL order: per sub-batch, the B in-order lanes then that
        sub-batch's late lanes. Uses the exact jnp op sequence of the
        fused step's generator, so the replay is bit-identical — the
        oracle face the device-telemetry differential tests replay
        through the host simulator."""
        import jax
        import jax.numpy as jnp

        if self._root is None:
            self._root = jax.random.PRNGKey(self.seed)
        key = self._interval_key(i)
        P, G, B, B_late = self.wm_period_ms, self.G, self.B, self.B_late
        span = P / G
        n_late = int(B * self.out_of_order_pct) if B_late else 0
        base = np.int64(i) * P
        max_lateness = self.max_lateness

        def one(g):
            kg = jax.random.fold_in(key, g)
            lo = (base + g * span).astype(jnp.float64)
            gaps = jax.random.uniform(kg, (B,), dtype=jnp.float32)
            gaps = gaps / jnp.sum(gaps) * span
            ts = lo.astype(jnp.int64) + jnp.cumsum(gaps).astype(jnp.int64)
            vals = jax.random.uniform(kg, (B,), dtype=jnp.float32) * 10_000
            if not B_late:
                return ts, vals
            kl = jax.random.fold_in(kg, 7)
            u = jax.random.uniform(kl, (2, B_late), dtype=jnp.float32)
            lo_l = jnp.maximum(lo - max_lateness, 0.0)
            lts = (lo_l + jnp.sort(u[0]).astype(jnp.float64)
                   * (lo - lo_l)).astype(jnp.int64)
            return ts, vals, lts, u[1] * 10_000.0

        parts_v, parts_t = [], []
        for g in range(G):
            out = jax.device_get(one(jnp.int64(g)))
            parts_v.append(out[1])
            parts_t.append(out[0])
            if B_late and n_late:
                parts_v.append(out[3][:n_late])
                parts_t.append(out[2][:n_late])
        return (np.concatenate(parts_v).astype(np.float32),
                np.concatenate(parts_t).astype(np.int64))

    def lowered_results(self, interval_out) -> list:
        """Fetch + lower one interval's window results on host."""
        return lower_interval(self.aggregations, interval_out)


def _gcd_all(xs):
    import math

    g = 0
    for x in xs:
        g = math.gcd(g, int(x))
    return g


class AlignedStreamPipeline(FusedPipelineDriver):
    """Slice-aligned fused pipeline — the flagship benchmark execution mode.

    TPU-first observation: scatters (especially int64 scatters) are the worst
    op class on TPU — the general ingest kernel's duplicate-index
    scatter-combines cost ~25 ms per 262 K-tuple batch on v5e, two orders of
    magnitude over the HBM bound. But the benchmark source is a *paced*
    generator (LoadGeneratorSource.java:45-57 emits a constant rate), so the
    stream can be generated **grouped by slice**: a [rows, R] block where row
    j holds exactly the R tuples of slice ``base + j*g`` (g = the slice grid
    = gcd of every window's slide AND size — sizes included so window end
    edges always land on the grid, closing the size-not-multiple-of-slide
    containment hole of the coarse union grid). Ingest then is:

    * per-row lift + combine — a dense row reduction (VPU-friendly, fuses
      with the on-device generator, no [B] scatter anywhere), and
    * one contiguous ``dynamic_update_slice`` append of the S new slices.

    This is the same slicing algebra — one partial per slice, windows
    answered by range queries over slice partials (build_query) — with the
    segmentation done by construction instead of by searched scatter. The
    whole watermark interval (generate → slice-combine → append → trigger →
    range-query → results) is ONE XLA program; GC amortizes over
    ``gc_every`` intervals.

    Constraints (fall back to :class:`StreamPipeline` otherwise): Time-measure
    tumbling/sliding windows only; dense-lift aggregations; wm_period_ms a
    multiple of the grid g; throughput*g/1000 ≥ 1 tuple per slice.
    """

    @staticmethod
    def slice_grid(windows, wm_period_ms: int) -> int:
        """The uniform slice grid: gcd of every window's slide and size AND
        the watermark period — every window edge and every watermark lands
        on a slice boundary."""
        members = [wm_period_ms]
        for w in windows:
            if not isinstance(w, (TumblingWindow, SlidingWindow,
                                  FixedBandWindow)):
                raise NotImplementedError(
                    f"no slice grid for {type(w).__name__}")
            members.append(int(w.size))
            if isinstance(w, SlidingWindow):
                members.append(int(w.slide))
        return _gcd_all(members)

    _uses_device_metrics = True

    def __init__(self, windows: Sequence, aggregations: Sequence[AggregateFunction],
                 config: Optional[EngineConfig] = None,
                 throughput: int = 200_000_000, wm_period_ms: int = 1000,
                 max_lateness: int = 1000, seed: int = 0, gc_every: int = 32,
                 max_chunk_elems: int = 1 << 25, value_scale: float = 10_000.0,
                 out_of_order_pct: float = 0.0,
                 collect_device_metrics: bool = True,
                 legacy_generator: bool = False,
                 query_slots: Optional[SlotGeometry] = None):
        import jax
        import jax.numpy as jnp

        from . import core as ec
        from ..obs import device as _dev

        self.collect_device_metrics = bool(collect_device_metrics)
        #: ADVICE r5: the r5 generator cheapened the benchmark workload
        #: itself (16-bit half-draws, offset stream dropped), so r4→r5
        #: cell comparisons mix engine speedup with workload reduction.
        #: ``legacy_generator=True`` pins the r4-era stream cost — one
        #: full 32-bit uniform draw per VALUE plus a generated per-tuple
        #: OFFSET stream (consumed by the row's t_first/t_last extrema,
        #: which stays containment-identical on the aligned grid) — so
        #: cross-round sweeps keep one workload-identical anchor cell.
        self.legacy_generator = bool(legacy_generator)
        self.config = config or EngineConfig()
        self.windows = list(windows)
        self.aggregations = list(aggregations)
        self.max_lateness = max_lateness
        self.wm_period_ms = wm_period_ms
        self.gc_every = gc_every
        self.seed = seed
        self.out_of_order_pct = float(out_of_order_pct)
        self.value_scale = float(value_scale)
        #: Pallas segmented-reduce fold for the generator lifts
        #: (EngineConfig.pallas_slice_merge; default off keeps the step
        #: HLO byte-identical — the pin asserts it)
        self._pallas_fold = bool(getattr(self.config, "pallas_slice_merge",
                                         False))
        self._pallas_packed = self._pallas_fold and bool(
            getattr(self.config, "pallas_packed", False))
        self._pallas_in_step = self._pallas_fold
        #: micro-batched streamed emission (EngineConfig.micro_batch):
        #: M micro-dispatches + one flush per interval via run_streamed
        self._micro_batch = int(getattr(self.config, "micro_batch", 0)
                                or 0)
        if self._micro_batch <= 1:
            self._micro_batch = 0

        max_fixed = 0
        for w in self.windows:
            if w.measure != WindowMeasure.Time or not isinstance(
                    w, (TumblingWindow, SlidingWindow)):
                raise NotImplementedError(
                    "aligned pipeline: Time tumbling/sliding only; use "
                    "StreamPipeline")
            max_fixed = max(max_fixed, w.clear_delay())
        for a in self.aggregations:
            if a.device_spec() is None:
                raise NotImplementedError(
                    "aligned pipeline: device-realizable aggregations only")
        #: dynamic-query serving mode (scotty_tpu.serving): the trigger
        #: grid reads a [Q] window-parameter table + active mask carried in
        #: the step's donated state instead of baking self.windows in. The
        #: slice grid and GC retention come from the SlotGeometry so state
        #: evolution is independent of the registered set — the property
        #: that makes register/cancel a mask write. None (default) leaves
        #: the static step byte-identical.
        self._query_slots = query_slots
        self._qs_host = None
        if query_slots is None:
            g = self.slice_grid(self.windows, wm_period_ms)
        else:
            g = int(query_slots.slice_grid)
            if wm_period_ms % g:
                raise ValueError(
                    f"SlotGeometry.slice_grid {g} must divide "
                    f"wm_period_ms {wm_period_ms}")
            for w in self.windows:
                sl = int(w.slide) if isinstance(w, SlidingWindow) \
                    else int(w.size)
                if int(w.size) % g or sl % g:
                    raise ValueError(
                        f"{w}: size/slide must be multiples of the serving "
                        f"slice grid {g} ms (aligned exactness)")
            max_fixed = max(max_fixed, int(query_slots.max_size))
        if throughput * g % 1000:
            raise ValueError(
                f"throughput {throughput} is not an integer number of tuples "
                f"per {g} ms slice — the generated load would silently fall "
                "short of the requested rate")
        R = throughput * g // 1000
        if R < 1:
            raise ValueError("throughput too low: <1 tuple per slice")
        S = wm_period_ms // g
        self.grid, self.R, self.S = g, R, S
        self.max_fixed = max_fixed
        # Out-of-order mode: per interval, L extra LATE tuples — event times
        # uniform in [max(0, base - max_lateness), base), arriving at the
        # START of the interval (so their displacement never exceeds
        # max_lateness relative to the stream's max event time, the
        # reference contract WindowOperator.java:31-37). On the aligned
        # grid every covering slice row is materialized (the base stream
        # fills every row), so the late fold needs NO annex, NO sort and NO
        # search: covering rows are affine in the grid start, and the
        # combines are bounded [L]-lane scatters. t_last is deliberately
        # NOT updated by late lanes: on the aligned grid every window edge
        # is a slice edge, so t_last containment (AggregateWindowState.java:
        # 25-31) is equivalent to start containment — and skipping it
        # avoids the dominant int64 scatter (~100 ms per 1M lanes on v5e).
        L_req = int(S * R * self.out_of_order_pct)
        # Dense-agg late streams use the SEGMENT fold (r4, VERDICT r3 item
        # 5): late tuples are generated pre-grouped by slice row over the
        # contiguous lateness span, so the fold is dynamic_slice + row
        # reduce + dynamic_update_slice — zero scatters (the [L]-lane
        # scatters were ~0.6 s of the drained OOO interval). Sparse
        # (sketch) aggregations keep the scatter fold.
        self._late_span = 0
        self._late_R = 0
        if L_req and all(not a.device_spec().is_sparse
                         for a in self.aggregations):
            span = max(1, min(max_lateness // g, self.config.capacity - 1))
            self._late_span = span
            self._late_R = -(-L_req // span)       # ceil: offered is a floor
            self.n_late = span * self._late_R
        else:
            self.n_late = L_req
        self.tuples_per_interval = S * R + self.n_late

        # Sparse-lift strategy per aggregation:
        # * sum-kind sketches (DDSketch histograms) take the FACTORED
        #   MXU histogram: width = WA·WB, so the [R, width] one-hot
        #   factors into two small one-hots [R, WA]·[R, WB] and the
        #   per-row histogram is their contraction A^T·B — a batched
        #   matmul that puts the 2048-wide accumulation on the systolic
        #   array instead of a serialized scatter or a VPU-bound
        #   [R, 2048] densify (the r4 cost model, 556 M t/s ceiling).
        #   Lift temporaries shrink from R·width to R·(WA+WB).
        # * min/max sketches (HLL registers) keep the one-hot densify
        #   (budget permitting) or the flat scatter — max doesn't ride
        #   a matmul contraction.
        onehot_ok = {}
        self._factored = {}
        max_width = 1
        for a in self.aggregations:
            sp = a.device_spec()
            # multi-cell sketches (count-min) skip the factored/one-hot
            # strategies — both assume one column per lane — and take the
            # flat scatter, whose advanced-index broadcast fans the [B]
            # row ids across the d cells
            if sp.is_sparse and sp.kind == "sum" \
                    and sp.cells_per_tuple == 1:
                wa = 1 << ((sp.width.bit_length()) // 2)
                if wa * (sp.width // wa) == sp.width:
                    self._factored[sp.token] = (wa, sp.width // wa)
                    max_width = max(max_width, wa + sp.width // wa)
                    continue
            if sp.is_sparse:
                onehot_ok[sp.token] = (sp.cells_per_tuple == 1
                                       and R * sp.width <= max_chunk_elems)
                if onehot_ok[sp.token]:
                    max_width = max(max_width, sp.width)
            else:
                max_width = max(max_width, sp.width)
        # rows per generation chunk: the static heuristic picks the largest
        # divisor of S within the budget (the budget counts lifted elements,
        # so wide sketch partials shrink the chunk rather than exploding the
        # [d*R, width] lift temporary). The measured-throughput sweet spot
        # is shape-dependent beyond this model (VERDICT r3 weak-2) —
        # ``autotune_chunk()`` times candidate shapes and keeps the winner.
        self._max_width = max_width
        self._max_chunk_elems = max_chunk_elems
        d = 1
        for cand in range(1, S + 1):
            if S % cand == 0 and cand * R * max_width <= max_chunk_elems:
                d = cand
        self._heuristic_d = d
        # Sub-row chunking (r5): coarse grids put the whole interval in a
        # handful of rows (S=1, R=800M for Sliding(60s,10s) at 800M/s), so
        # even d=1 materializes a multi-GB row and the generator+reduce
        # can't tile. When one row exceeds the budget, the scan iterates
        # over n_sub sub-chunks per row (smallest divisor count bringing
        # R/n_sub within budget), keyed per ABSOLUTE (row, sub) pair —
        # the sub-chunked stream is a pure function of the pipeline
        # parameters, and materialize_interval replays it bit-exactly.
        n_sub = 1
        if R * max_width > max_chunk_elems:
            n_sub = min(-(-R * max_width // max_chunk_elems), R)
            while R % n_sub and n_sub < R:
                n_sub += 1
            # degenerate budgets (max_width > max_chunk_elems) land on
            # q = 1 lanes per chunk rather than spinning or crashing
        if self._micro_batch:
            # micro-batching dispatches the interval's sub-chunks in M
            # groups, so the generation MUST use the per-(row, sub)
            # keying on both paths — force the sub-row chunking on (and
            # divisible by M) so run() and run_streamed() draw the
            # identical stream and bit-match
            if legacy_generator:
                raise NotImplementedError(
                    "micro_batch: the legacy anchor generator is "
                    "whole-interval only (cross-round workload pin)")
            if query_slots is not None:
                raise NotImplementedError(
                    "micro_batch: serving mode steps whole intervals "
                    "(the query table rides the interval carry)")
            M = self._micro_batch
            n_sub = max(n_sub, 2)
            while n_sub <= R and (R % n_sub or (S * n_sub) % M):
                n_sub += 1
            if n_sub > R:
                raise ValueError(
                    f"micro_batch {M}: no sub-chunk count divides both "
                    f"R={R} lanes/row and M micro-batches — pick M "
                    "dividing the interval's tuple count")
        self._n_sub = n_sub

        spec = ec.EngineSpec(
            periods=(g,), bands=(), count_periods=(),
            aggs=tuple(a.device_spec() for a in self.aggregations))
        self.spec = spec
        C, A = self.config.capacity, self.config.annex_capacity
        query = ec.build_query(spec, C, A)
        self._gc_kernel = jax.jit(ec.build_gc(spec, C, A), donate_argnums=0)
        self._init_state = lambda: ec.init_state(spec, C, A)
        if query_slots is None:
            make_triggers, self.T = build_trigger_grid(self.windows,
                                                       wm_period_ms)
        else:
            make_triggers, self.T = build_slot_trigger_grid(query_slots,
                                                            wm_period_ms)
        self._make_triggers = make_triggers
        self._write_slot_fn = None
        P = wm_period_ms

        red = {"sum": jnp.sum, "min": jnp.min, "max": jnp.max}

        first_lw = max(0, P - max_lateness)   # first-watermark clamp
                                              # (WindowManager.java:43-45)
        L = self.n_late
        cdm = self.collect_device_metrics

        def late_fold(state, dm, key, base):
            """Fold this interval's late tuples into their covering slices.

            Runs BEFORE the base append: at this point the top slice is the
            previous interval's last row (start == base - g), so a late
            tuple with grid start gs sits at row
            ``n_slices - 1 - (base - g - gs) / g`` — affine, no search.
            Rows behind the GC horizon cannot occur (the GC bound
            ``wm - max_lateness - max_fixed`` keeps every row the late span
            can touch). Interval 0 has no earlier span: all lanes masked.
            """
            # fold constant outside the per-row key range [0, S) so the
            # late stream never collides with a slice row's stream
            kl = jax.random.fold_in(key, 0x7fffffff)
            u = jax.random.uniform(kl, (2, L), dtype=jnp.float32)
            lo_l = jnp.maximum(base - max_lateness, 0).astype(jnp.float64)
            span_l = base.astype(jnp.float64) - lo_l
            lts = (lo_l + u[0].astype(jnp.float64) * span_l).astype(jnp.int64)
            lts = jnp.minimum(lts, base - 1)
            lvals = u[1] * value_scale
            ok = base > 0                      # scalar; interval-0 guard
            gs = lts - jnp.mod(lts, g)
            row = (state.n_slices.astype(jnp.int64) - 1
                   - (base - g - gs) // g)
            # out-of-range sentinel + identity-masked values + mode="drop":
            # masked lanes can neither combine nor clamp onto a live row.
            # Negative rows (outside the GC invariant) must hit the sentinel
            # too — JAX normalizes negative indices onto live slices.
            lane_ok = ok & (row >= 0)
            pos = jnp.where(lane_ok, row, C).astype(jnp.int32)
            d32 = jnp.zeros((C,), jnp.int32).at[pos].add(
                jnp.int32(1), mode="drop")
            partials = []
            for aspec, part in zip(spec.aggs, state.partials):
                if aspec.is_sparse:
                    col, v = aspec.lift_sparse(lvals)
                    v = jnp.where(ok, v, aspec.identity)
                    idx = (pos, col)
                else:
                    v = aspec.lift_dense(lvals)
                    v = jnp.where(ok, v, aspec.identity)
                    idx = (pos,)
                if aspec.kind == "sum":
                    part = part.at[idx].add(v, mode="drop")
                elif aspec.kind == "min":
                    part = part.at[idx].min(v, mode="drop")
                else:
                    part = part.at[idx].max(v, mode="drop")
                partials.append(part)
            n_ok = jnp.where(ok, jnp.int64(L), jnp.int64(0))
            bad = ok & jnp.any((row < 0)
                               | (row >= state.n_slices.astype(jnp.int64)))
            if cdm:
                # EXACT arrival-order lateness: the canonical stream (the
                # materialize_* replay faces) has the base tuples at their
                # row starts, so the running max entering this fold is
                # base - g; within the fold it evolves lane by lane
                # (cummax), and a lane is late iff its ts is strictly
                # below the running max at ITS arrival — the same
                # calculus a host replay of the arrival order computes.
                seed = jnp.reshape(base - g, (1,))
                rm = jax.lax.cummax(jnp.concatenate([seed, lts[:-1]]))
                late_m = ok & (lts < rm)
                dm = _dev.record_late_ages(dm, rm - lts, late_m)
                dm = dm._replace(
                    ingested=dm.ingested + n_ok,
                    late=dm.late + jnp.sum(late_m),
                    dropped=dm.dropped + jnp.sum(
                        jnp.where(ok & (row < 0), jnp.int64(1), 0)),
                    slices_touched=dm.slices_touched
                    + jnp.sum((d32 > 0).astype(jnp.int64)))
            return state._replace(
                counts=state.counts + d32.astype(jnp.int64),
                partials=tuple(partials),
                current_count=state.current_count + n_ok,
                overflow=state.overflow | bad), dm

        def gen_rows(key, rows):
            """The paced generator: R tuples per slice row (the reference's
            constant-rate LoadGeneratorSource), values uniform over 65536
            levels in [0, value_scale). Keyed per ABSOLUTE slice row (not
            per chunk), so the stream is a function of (interval, row)
            alone and any chunk regrouping (``set_rows_per_chunk``/
            ``autotune_chunk``) generates bit-identical tuples.

            The RNG is a first-order throughput term (threefry sustains
            ~9 G 32-bit lanes/s on v5e), so — as in the keyed pipeline —
            each 32-bit draw yields TWO 16-bit-granular values, and the
            per-tuple OFFSET stream is not generated at all: on the
            aligned grid every window edge is a slice edge, so intra-slice
            tuple placement is unobservable (t_last containment ≡ start
            containment) and tuples sit at their row start."""
            keys = jax.vmap(lambda r: jax.random.fold_in(key, r))(rows)
            return jax.vmap(
                lambda k: draw_uniform16(k, (R,), value_scale))(keys)

        def gen_lanes(kk, n):
            """[n] values from one key — the sub-row chunk generator
            (same half-draw block layout as gen_rows)."""
            return draw_uniform16(kk, (n,), value_scale)

        span_l8 = self._late_span
        R_l8 = self._late_R

        def late_fold_segment(state, dm, key, base):
            """Scatter-free late fold (dense aggs): this interval's late
            tuples, R_l8 per slice row over the ``span_l8`` rows covering
            [base - max_lateness, base) — a stratified rendering of the
            same uniform late load. The target rows are CONTIGUOUS (the
            aligned base stream materializes every row), so the fold is a
            slice read + per-row reduce + slice write. RNG is keyed per
            absolute row (0x70000000 | row — disjoint from the base
            stream's per-row keys), t_last deliberately untouched (start
            containment ≡ t_last containment on the aligned grid)."""
            n = state.n_slices
            start = jnp.clip(n - span_l8, 0, C - span_l8)
            rows = (start + jnp.arange(span_l8)).astype(jnp.int64)
            row_ts = base + (rows - n.astype(jnp.int64)) * g
            lo_l = jnp.maximum(base - max_lateness, 0)
            # rows with row_ts in [lo_l, base) are always live on the
            # aligned grid (the base stream materializes every row and the
            # GC bound keeps the lateness span — `bad` below flags any
            # violation), so validity is a pure function of ts and the
            # host replay needs no GC-history row count
            valid = (row_ts >= lo_l) & (row_ts < base)
            # RNG keyed by ABSOLUTE grid index (ts/g): GC-independent and
            # disjoint from the base stream's per-interval-row keys
            keys = jax.vmap(lambda t: jax.random.fold_in(
                key, 0x70000000 + t // g))(row_ts)
            u = jax.vmap(lambda k: jax.random.uniform(
                k, (2, R_l8), dtype=jnp.float32))(keys)  # [span, 2, R]
            lvals = u[:, 0] * value_scale
            add_cnt = jnp.where(valid, jnp.int64(R_l8), 0)
            cnt_sl = jax.lax.dynamic_slice(state.counts, (start,),
                                           (span_l8,))
            counts = jax.lax.dynamic_update_slice(
                state.counts, cnt_sl + add_cnt, (start,))
            partials = []
            for aspec, part in zip(spec.aggs, state.partials):
                lifted = aspec.lift_dense(lvals.reshape(-1)).reshape(
                    span_l8, R_l8, -1)
                upd = red[aspec.kind](lifted, axis=1)      # [span, w]
                ident = jnp.asarray(aspec.identity, part.dtype)
                w = part.shape[1]
                ps = jax.lax.dynamic_slice(part, (start, jnp.int32(0)),
                                           (span_l8, w))
                if aspec.kind == "sum":
                    comb = ps + jnp.where(valid[:, None], upd, 0)
                elif aspec.kind == "min":
                    comb = jnp.minimum(ps, jnp.where(valid[:, None], upd,
                                                     ident))
                else:
                    comb = jnp.maximum(ps, jnp.where(valid[:, None], upd,
                                                     ident))
                partials.append(jax.lax.dynamic_update_slice(
                    part, comb, (start, jnp.int32(0))))
            # GC mistuning: the late span needs (base - lo_l)/g rows; fewer
            # live/covered rows means silently lost late tuples — flag it
            needed = (base - lo_l) // g
            have = jnp.minimum(n.astype(jnp.int64), jnp.int64(span_l8))
            bad = (base > 0) & (needed > have)
            if cdm:
                # EXACT arrival-order lateness (see late_fold): the
                # stratified rendering has real per-tuple offsets in the
                # replay face (materialize_interval_late u[:, 1]); replay
                # order is rows ascending, lanes in draw order. Running
                # max enters at base - g (the canonical stream's head)
                # and evolves by cummax over the flattened lane order.
                offs = jnp.clip(jnp.floor(u[:, 1] * jnp.float32(g)), 0,
                                g - 1).astype(jnp.int64)   # [span, R]
                lts_full = row_ts[:, None] + offs
                lane_ok = jnp.broadcast_to(valid[:, None], lts_full.shape)
                flat = jnp.where(lane_ok, lts_full,
                                 jnp.int64(-(1 << 62))).reshape(-1)
                seed = jnp.reshape(base - g, (1,))
                rm = jax.lax.cummax(jnp.concatenate([seed, flat[:-1]]))
                late_m = lane_ok.reshape(-1) & (flat < rm)
                dm = _dev.record_late_ages(dm, rm - flat, late_m)
                dm = dm._replace(
                    ingested=dm.ingested + jnp.sum(add_cnt),
                    late=dm.late + jnp.sum(late_m),
                    slices_touched=dm.slices_touched
                    + jnp.sum(valid.astype(jnp.int64)))
            return state._replace(
                counts=counts, partials=tuple(partials),
                current_count=state.current_count + jnp.sum(add_cnt),
                overflow=state.overflow | bad), dm

        late_fold_active = late_fold_segment if span_l8 else late_fold

        n_sub = self._n_sub
        legacy = self.legacy_generator
        if legacy and n_sub > 1:
            raise NotImplementedError(
                "legacy_generator: pick a shape whose rows fit the chunk "
                "budget (sub-row chunking postdates the r4 generator)")
        if legacy and L:
            raise NotImplementedError(
                "legacy_generator: the cross-round anchor cell is "
                "in-order (out_of_order_pct must be 0)")

        def gen_rows_legacy(key, rows):
            """The r4-era generator, pinned for the cross-round anchor
            cell (ADVICE r5): one full 32-bit uniform draw per VALUE and
            a generated per-tuple OFFSET stream (uniform in [0, g)), both
            keyed per absolute row. The offsets feed the row's
            t_first/t_last extrema — containment-identical on the aligned
            grid, but the draws stay live so the workload cost matches
            r4, not r5's halved-draw stream."""
            keys = jax.vmap(lambda r: jax.random.fold_in(key, r))(rows)
            vals = jax.vmap(lambda k: jax.random.uniform(
                k, (R,), dtype=jnp.float32) * value_scale)(keys)
            offs = jax.vmap(lambda k: jnp.clip(jnp.floor(
                jax.random.uniform(jax.random.fold_in(k, 1), (R,),
                                   dtype=jnp.float32) * g),
                0, g - 1).astype(jnp.int64))(keys)
            return vals, offs

        def lift_chunk(flat, dd, RR):
            """Per-aggregation [dd, width] partials of a flat [dd*RR]
            value chunk — the sparse/factored/dense strategy block shared
            by row-granular and sub-row chunking."""
            parts = []
            for aspec in spec.aggs:
                if self._pallas_fold:
                    # Pallas segmented-reduce fold (ROADMAP item 4):
                    # lane blocks stream HBM→VMEM and reduce per slice
                    # row — replaces the one-hot/factored densifies AND
                    # the multi-cell sparse flat scatter below
                    from .. import pallas as _spl

                    if aspec.is_sparse:
                        col, v = aspec.lift_sparse(flat)
                        parts.append(_spl.sparse_row_fold(
                            col, v, dd, RR, aspec.width, aspec.kind,
                            aspec.identity))
                    else:
                        lifted = aspec.lift_dense(flat)
                        parts.append(_spl.row_fold(
                            lifted, dd, RR, aspec.kind, aspec.identity,
                            packed=self._pallas_packed))
                    continue
                if aspec.is_sparse and aspec.token in self._factored:
                    # factored MXU histogram (see strategy note):
                    # hist[row] = A^T·B with A, B the hi/lo one-hots
                    wa, wb = self._factored[aspec.token]
                    col, v = aspec.lift_sparse(flat)
                    hi = (col // wb).astype(jnp.int32)
                    lo = (col - hi * wb).astype(jnp.int32)
                    A = jnp.where(
                        hi[:, None] == jnp.arange(wa)[None, :],
                        v[:, None], 0.0).reshape(dd, RR, wa)  # carries v
                    Bm = (lo[:, None]
                          == jnp.arange(wb)[None, :]).astype(
                              jnp.bfloat16).reshape(dd, RR, wb)
                    hist = jnp.einsum(
                        "drk,drl->dkl", A, Bm,
                        preferred_element_type=jnp.float32)
                    parts.append(hist.reshape(dd, wa * wb))
                elif aspec.is_sparse and onehot_ok[aspec.token]:
                    # one-hot densify + row reduce (see strategy note
                    # in __init__)
                    col, v = aspec.lift_sparse(flat)
                    lifted = jnp.where(
                        col[:, None] == jnp.arange(aspec.width)[None, :],
                        v[:, None], jnp.asarray(aspec.identity,
                                                v.dtype))
                    lifted = lifted.reshape(dd, RR, -1)
                    parts.append(red[aspec.kind](lifted, axis=1))
                elif aspec.is_sparse:
                    # flat [dd*width] f32 scatter — per-lane cost only
                    col, v = aspec.lift_sparse(flat)
                    row_id = jnp.arange(dd * RR, dtype=jnp.int32) // RR
                    fi = row_id * aspec.width + col.astype(jnp.int32)
                    tgt = jnp.full((dd * aspec.width,), aspec.identity,
                                   jnp.float32)
                    if aspec.kind == "sum":
                        tgt = tgt.at[fi].add(v)
                    elif aspec.kind == "min":
                        tgt = tgt.at[fi].min(v)
                    else:
                        tgt = tgt.at[fi].max(v)
                    parts.append(tgt.reshape(dd, aspec.width))
                else:
                    lifted = aspec.lift_dense(flat).reshape(dd, RR, -1)
                    parts.append(red[aspec.kind](lifted, axis=1))
            return parts

        q_sub = R // n_sub

        def sub_chunk(key, c):
            """One (row, sub) generation+lift sub-chunk — shared verbatim
            by the whole-interval scan and the micro-batched step, so
            the two dispatch shapes draw the identical stream and their
            results bit-match."""
            row = c // n_sub
            s_i = c % n_sub
            kk = jax.random.fold_in(
                jax.random.fold_in(key, row),
                0x5f000000 + s_i)
            if q_sub % 2 == 0:
                lo, hi = half_draw_parts(
                    jax.random.bits(kk, (q_sub // 2,),
                                    dtype=jnp.uint32),
                    value_scale)
                pl = lift_chunk(lo, 1, q_sub // 2)
                ph = lift_chunk(hi, 1, q_sub // 2)
                out = []
                for aspec, a, b in zip(spec.aggs, pl, ph):
                    if aspec.kind == "sum":
                        out.append((a + b)[0])
                    elif aspec.kind == "min":
                        out.append(jnp.minimum(a, b)[0])
                    else:
                        out.append(jnp.maximum(a, b)[0])
                return tuple(out)
            flat = gen_lanes(kk, q_sub)
            return tuple(p[0] for p in lift_chunk(flat, 1, q_sub))

        def finish_interval(state, dm, qs, base, interval_idx, parts,
                            off_first_rows=None, off_last_rows=None):
            """Append the interval's folded rows + trigger/query/GC-side
            bookkeeping — the step tail, shared verbatim by the
            whole-interval step and the micro-batched flush."""
            row_starts = base + g * jnp.arange(S, dtype=jnp.int64)
            # tuples sit at their row start (the offset stream is
            # unobservable on the aligned grid and not generated — see
            # gen_rows); t_last takes the conservative row bound, which
            # gives IDENTICAL query containment for grid-aligned edges.
            # The legacy anchor generates real offsets and uses their
            # extrema instead (same containment on the aligned grid).
            t_first = row_starts if off_first_rows is None \
                else row_starts + off_first_rows
            t_last = row_starts + (g - 1) if off_last_rows is None \
                else row_starts + off_last_rows
            n = state.n_slices

            def app(buf, rows):
                idx = (n,) + (jnp.int32(0),) * (buf.ndim - 1)
                return jax.lax.dynamic_update_slice(
                    buf, rows.astype(buf.dtype), idx)

            state = state._replace(
                starts=app(state.starts, row_starts),
                ends=app(state.ends, row_starts + g),
                t_first=app(state.t_first, t_first),
                t_last=app(state.t_last, t_last),
                c_start=app(state.c_start, state.current_count
                            + R * jnp.arange(S, dtype=jnp.int64)),
                counts=app(state.counts, jnp.full((S,), R, jnp.int64)),
                partials=tuple(
                    app(p, pr)
                    for p, pr in zip(state.partials, parts)),
                n_slices=n + S,
                max_event_time=jnp.maximum(state.max_event_time, t_last[-1]),
                current_count=state.current_count + S * R,
                overflow=state.overflow | (n + S > C),
            )
            last_wm = jnp.where(interval_idx > 0, base,
                                jnp.int64(first_lw))
            if qs is None:
                ws, we, tmask = self._make_triggers(last_wm, base + P)
            else:
                ws, we, tmask = self._make_triggers(qs, last_wm, base + P)
            cnt, results = query(state, ws, we, tmask,
                                 jnp.zeros_like(tmask))
            if cdm:
                dm = dm._replace(
                    ingested=dm.ingested + jnp.int64(S * R),
                    triggers=dm.triggers + jnp.sum(tmask),
                    windows_nonempty=dm.windows_nonempty
                    + jnp.sum(tmask & (cnt > 0)),
                    slices_touched=dm.slices_touched + jnp.int64(S))
                dm = _dev.record_occupancy(dm, state.n_slices, C)
            if qs is None:
                return state, dm, (ws, we, cnt, results)
            return state, dm, qs, (ws, we, cnt, results)

        def step_impl(state, dm, qs, key, interval_idx, d):
            base = interval_idx * P
            if L:
                state, dm = late_fold_active(state, dm, key, base)

            off_first_rows = off_last_rows = None
            if n_sub > 1:
                # sub-row chunking (see __init__): q lanes of one row per
                # scan step, keyed per absolute (row, sub) pair. The two
                # 16-bit halves lift SEPARATELY and combine as partials —
                # concatenating them first is a fusion breaker that
                # materializes every chunk (measured 178 ms vs 56 ms per
                # 800 M-tuple interval); regrouping the fold is sound for
                # the commutative combine kinds (sum/min/max), and the
                # replayed stream is the same multiset at the same ts.
                def body(_, c):
                    return None, sub_chunk(key, c)

                _, stacked = jax.lax.scan(
                    body, None, jnp.arange(S * n_sub, dtype=jnp.int64))
                parts = tuple(
                    red[a.kind](p.reshape(S, n_sub, -1), axis=1)
                    for a, p in zip(spec.aggs, stacked))
            elif legacy:
                def body(_, c):
                    rows = c * d + jnp.arange(d, dtype=jnp.int64)
                    vals, offs = gen_rows_legacy(key, rows)
                    return None, (tuple(lift_chunk(vals.reshape(-1), d, R)),
                                  jnp.min(offs, axis=1),
                                  jnp.max(offs, axis=1))

                _, (stacked, off_mins, off_maxs) = jax.lax.scan(
                    body, None, jnp.arange(S // d))
                parts = tuple(p.reshape(S, -1) for p in stacked)
                off_first_rows = off_mins.reshape(S)
                off_last_rows = off_maxs.reshape(S)
            else:
                def body(_, c):
                    vals = gen_rows(
                        key, c * d + jnp.arange(d, dtype=jnp.int64))
                    return None, tuple(lift_chunk(vals.reshape(-1), d, R))

                _, stacked = jax.lax.scan(
                    body, None, jnp.arange(S // d))
                parts = tuple(p.reshape(S, -1) for p in stacked)

            return finish_interval(state, dm, qs, base, interval_idx,
                                   parts, off_first_rows, off_last_rows)

        self._step_impl = step_impl

        # -- micro-batched step (EngineConfig.micro_batch, ISSUE 15) -------
        # The interval's S*n_sub sub-chunks dispatch in M groups; the
        # per-(row, sub) slabs accumulate in a donated carry and ONE
        # flush program reduces + appends + triggers — byte-for-byte
        # finish_interval, so a streamed run bit-matches run(). Built
        # only when the flag is on: the flags-off trace set (and every
        # HLO pin) is untouched.
        if self._micro_batch:
            Mb = self._micro_batch
            T_sub = S * n_sub
            cpm = T_sub // Mb
            widths = tuple(a.width for a in spec.aggs)

            def micro_step(state, dm, slab, key, interval_idx, m):
                base = interval_idx * P
                if L:
                    state, dm = jax.lax.cond(
                        m == 0,
                        lambda sd: late_fold_active(sd[0], sd[1], key,
                                                    base),
                        lambda sd: sd,
                        (state, dm))

                def body(_, c):
                    return None, sub_chunk(key, c)

                cs = (m.astype(jnp.int64) * cpm
                      + jnp.arange(cpm, dtype=jnp.int64))
                _, stacked = jax.lax.scan(body, None, cs)
                slab = tuple(
                    jax.lax.dynamic_update_slice(
                        sl, st.astype(sl.dtype),
                        (m * cpm, jnp.int32(0)))
                    for sl, st in zip(slab, stacked))
                return state, dm, slab

            def micro_flush(state, dm, slab, key, interval_idx):
                self._trace_count += 1
                base = interval_idx * P
                parts = tuple(
                    red[a.kind](p.reshape(S, n_sub, -1), axis=1)
                    for a, p in zip(spec.aggs, slab))
                return finish_interval(state, dm, None, base,
                                       interval_idx, parts)

            self._micro_step_fn = jax.jit(micro_step,
                                          donate_argnums=(0, 1, 2))
            # the slab is consumed by the reduce, not carried through —
            # donating it would only warn (no output aliases its shape)
            self._micro_flush_fn = jax.jit(micro_flush,
                                           donate_argnums=(0, 1))
            # slab zeros materialize INSIDE a jitted thunk: an eager
            # jnp.zeros implicitly uploads its fill scalar, which the
            # transfer-guard differential arm (rightly) rejects
            self._micro_slab_init = jax.jit(lambda: tuple(
                jnp.zeros((T_sub, w), jnp.float32) for w in widths))
            self._micro_shape = (T_sub, cpm, widths)
        self._gen_rows = gen_rows
        self._gen_lanes = gen_lanes
        #: the generator the ACTIVE step closes over (legacy anchor cells
        #: trace gen_rows_legacy) — the bench's generator-share probe
        #: times exactly this stream cost (ISSUE 11; a separate jit, the
        #: pinned step HLO is untouched)
        self._gen_active = gen_rows_legacy if legacy else gen_rows
        self.set_rows_per_chunk(self._heuristic_d)
        self._root = None
        self.state = None
        self._interval = 0

    def set_rows_per_chunk(self, d: int) -> None:
        """Re-jit the interval step at a new generation-chunk shape (d slice
        rows per chunk; must divide S). State shapes and the generated
        stream are unaffected (per-row RNG keying). A FRESH closure per
        shape — jax's jit cache is keyed on the function object, so
        re-wrapping the same function would silently keep executing the
        originally traced shape (r4 review finding)."""
        import jax

        d = int(d)
        if d < 1 or self.S % d:
            raise ValueError(f"rows_per_chunk {d} must divide S={self.S}")
        self.rows_per_chunk = d
        self._n_chunks = self.S // d
        impl = self._step_impl

        if self._query_slots is None:
            def step_at_d(state, dm, key, interval_idx):
                # host-side trace counter: this body runs once per jit
                # TRACE (the serving layer's zero-retrace contract reads
                # it); no traced ops — the emitted HLO is unchanged
                self._trace_count += 1
                return impl(state, dm, None, key, interval_idx, d)

            self._step = jax.jit(step_at_d, donate_argnums=(0, 1))
        else:
            def step_at_d(state, dm, qs, key, interval_idx):
                self._trace_count += 1
                return impl(state, dm, qs, key, interval_idx, d)

            # the query table is part of the donated carry: XLA aliases it
            # straight through (it is returned untouched), so the steady-
            # state step moves zero extra bytes for it
            self._step = jax.jit(step_at_d, donate_argnums=(0, 1, 2))
        self._pipeline_ready = False

    def chunk_candidates(self, k: int = 3) -> list:
        """Up to ``k`` log-spaced candidate chunk shapes within the lifted-
        element budget, largest (the static heuristic's pick) first."""
        ds = [c for c in range(1, self.S + 1)
              if self.S % c == 0
              and c * self.R * self._max_width <= self._max_chunk_elems]
        if not ds:
            return [1]
        picks = []
        for i in range(k):
            j = round((len(ds) - 1) * (1 - i / max(k - 1, 1)))
            if ds[j] not in picks:
                picks.append(ds[j])
        return picks

    def autotune_chunk(self, reps: int = 2, candidates=None,
                       budget_s: float = None) -> dict:
        """Measure candidate chunk shapes (one compile + ``reps`` timed
        intervals each, idle-subtracted device_get syncs — block_until_ready
        is not a reliable barrier on tunneled devices) and keep the fastest.
        The engine owns the sweet spot instead of a hand-set bench constant
        (VERDICT r3 item 3). Returns {d: seconds_per_interval}; stops early
        when ``budget_s`` wall seconds are spent, keeping the best so far."""
        import time as _time

        cands = list(candidates) if candidates else self.chunk_candidates()
        timings: dict = {}
        t_start = _time.perf_counter()
        for d in cands:
            self.set_rows_per_chunk(d)
            self.reset()
            self.run(1, collect=False)
            self.sync()                     # compile + warm
            t0 = _time.perf_counter()
            self.sync()
            idle = _time.perf_counter() - t0
            t0 = _time.perf_counter()
            self.run(reps, collect=False)
            self.sync()
            timings[d] = max((_time.perf_counter() - t0 - idle) / reps,
                             1e-9)
            if budget_s is not None \
                    and _time.perf_counter() - t_start > budget_s:
                break
        best = min(timings, key=timings.get)
        self.set_rows_per_chunk(best)
        self.reset()
        return timings

    def _init_pipeline_state(self) -> None:
        self.state = self._init_state()
        if self._query_slots is not None:
            self._qstate = init_query_slots(self._query_slots, self._qs_host)

    # -- dynamic-query serving hooks (scotty_tpu.serving) ------------------
    def set_query_rows(self, rows: Optional[dict]) -> None:
        """Bind the HOST mirror of the query table (numpy ``kinds/grids/
        sizes/active`` rows, kept by the serving layer's QueryTable — held
        by reference, so in-place row writes stay visible). ``reset()``
        and checkpoint restores re-upload the table from this mirror, so
        a restore replays the active query set."""
        if self._query_slots is None:
            raise ValueError("not a serving pipeline (query_slots=None)")
        self._qs_host = rows
        if getattr(self, "_pipeline_ready", False):
            self._qstate = init_query_slots(self._query_slots, rows)

    def write_query_slot(self, slot: int, kind: int, grid: int, size: int,
                         active: bool) -> None:
        """One-row device table write — the register/cancel hot path. The
        row index and parameters are traced arguments, so every write (any
        slot, any geometry-compatible window) reuses ONE compiled
        executable; the table buffer is donated and updated in place."""
        import jax

        if self._qstate is None:
            if self._query_slots is None:
                raise ValueError("not a serving pipeline")
            self.reset()
        if self._write_slot_fn is None:
            def w(qs, i, kind, grid, size, act):
                return QuerySlots(
                    kinds=qs.kinds.at[i].set(kind),
                    grids=qs.grids.at[i].set(grid),
                    sizes=qs.sizes.at[i].set(size),
                    active=qs.active.at[i].set(act))

            self._write_slot_fn = jax.jit(w, donate_argnums=0)
        self._qstate = self._write_slot_fn(
            self._qstate, np.int32(slot), np.int32(kind), np.int64(grid),
            np.int64(size), np.bool_(active))

    def set_slot_geometry(self, geometry: SlotGeometry) -> None:
        """Rebuild the step at a new slot-grid bucket (a counted retrace;
        scotty_tpu.serving.cache keeps the old bucket's executable warm).
        The carried slice state is untouched — its shapes are independent
        of the query set — so a rebucket continues the stream exactly."""
        if self._query_slots is None:
            raise ValueError("not a serving pipeline (query_slots=None)")
        if int(geometry.slice_grid) != self.grid:
            raise ValueError(
                f"slot-geometry slice grid {geometry.slice_grid} != the "
                f"pipeline's aligned grid {self.grid}: the slice grid is "
                "state-shaping and cannot change at a rebucket")
        ready = getattr(self, "_pipeline_ready", False)
        self._query_slots = geometry
        self._make_triggers, self.T = build_slot_trigger_grid(
            geometry, self.wm_period_ms)
        self.set_rows_per_chunk(self.rows_per_chunk)
        # rebucketing must NOT wipe mid-stream state (set_rows_per_chunk
        # marks the pipeline for reset — correct for autotuning, wrong
        # here); the caller re-uploads the re-padded table
        self._pipeline_ready = ready

    def compiled_step(self):
        """(step, make_triggers, T, geometry, rows_per_chunk) — what the
        serving compile cache stores per bucket."""
        return (self._step, self._make_triggers, self.T, self._query_slots,
                self.rows_per_chunk)

    def adopt_compiled_step(self, entry) -> None:
        """Re-enter a previously compiled bucket (cache hit): swap the
        jitted step back in WITHOUT building a fresh closure — jax's jit
        cache is keyed on the function object, so this reuses the warm
        executable and traces nothing."""
        step, make_triggers, T, geometry, d = entry
        if self._query_slots is None:
            raise ValueError("not a serving pipeline (query_slots=None)")
        if int(geometry.slice_grid) != self.grid:
            raise ValueError("cached bucket was built for a different "
                             "slice grid")
        self._step = step
        self._make_triggers = make_triggers
        self.T = T
        self._query_slots = geometry
        self.rows_per_chunk = d
        self._n_chunks = self.S // d

    def _gc(self, bound) -> None:
        self.state = self._gc_kernel(self.state, bound)

    # -- micro-batched streamed dispatch (EngineConfig.micro_batch) --------
    def _dispatch_streamed(self, i: int):
        if not self._micro_batch:
            return super()._dispatch_streamed(i)
        self.micro_start(i)
        while self._micro_m < self._micro_batch:
            self.micro_push()
        return self.micro_finish()

    def micro_start(self, i: int) -> None:
        """Open interval ``i``'s micro-batched dispatch: a fresh slab
        carry, the interval key, micro cursor at 0. The stepwise faces
        (:meth:`micro_push` / :meth:`micro_finish`) exist so the carry
        is checkpointable BETWEEN micro-batches — the resume arm of the
        differential suite snapshots mid-interval."""
        import jax

        self._micro_slab = self._micro_slab_init()
        self._micro_i = int(i)
        self._micro_key = self._interval_key(int(i))
        self._micro_iv = jax.device_put(np.int64(int(i)))
        self._micro_m = 0

    def micro_push(self) -> None:
        """Dispatch the next micro-batch (async). With
        :attr:`micro_pace` a tiny anchor fetch bounds the in-flight
        micro queue to one — the arrival-paced streaming discipline."""
        import jax

        m = jax.device_put(np.int32(self._micro_m))
        self.state, self.dm, self._micro_slab = self._micro_step_fn(
            self.state, self.dm, self._micro_slab, self._micro_key,
            self._micro_iv, m)
        self._micro_m += 1
        if self.micro_pace:
            jax.device_get(self.state.n_slices)

    def micro_finish(self):
        """Reduce the slab, append, trigger and query — the flush
        program; returns the interval's result handle (the same tuple
        shape as the whole-interval step, bit-matching it)."""
        self.state, self.dm, res = self._micro_flush_fn(
            self.state, self.dm, self._micro_slab, self._micro_key,
            self._micro_iv)
        self._micro_slab = None
        if self.obs is not None:
            self.obs.counter(_obs.MICROBATCH_FLUSHES).inc()
            fl = getattr(self.obs, "flight", None)
            if fl is not None:
                fl.record(_flight.MICROBATCH_FLUSH, "flush",
                          self._micro_batch)
        return res

    def micro_snapshot(self) -> dict:
        """Host checkpoint of the micro-batched carry, valid between
        micro-batches: device state + metrics + slab + cursors. One
        deliberate drain (this IS a checkpoint boundary)."""
        import jax

        return {
            "state": jax.device_get(self.state),
            "dm": jax.device_get(self.dm),
            "slab": jax.device_get(self._micro_slab),
            "interval": self._micro_i,
            "m": self._micro_m,
            "next_interval": self._interval,
        }

    def micro_restore(self, snap: dict) -> None:
        """Resume a :meth:`micro_snapshot` mid-interval; the continued
        run is bit-identical to the uninterrupted twin (asserted by the
        checkpoint-resume arm)."""
        import jax

        if self._needs_reset():
            self.reset()
        self.state = jax.device_put(snap["state"])
        self.dm = jax.device_put(snap["dm"])
        self._micro_slab = jax.device_put(tuple(snap["slab"]))
        self._micro_i = int(snap["interval"])
        self._micro_m = int(snap["m"])
        self._interval = int(snap["next_interval"])
        self._micro_key = self._interval_key(self._micro_i)
        self._micro_iv = jax.device_put(np.int64(self._micro_i))

    def check_overflow(self) -> None:
        import jax

        if bool(jax.device_get(self.state.overflow)):
            e = RuntimeError("slice buffer overflow: raise capacity or "
                             "gc more often")
            if self.obs is not None:
                self.obs.counter(_obs.OVERFLOWS).inc()
                self.obs.record_failure(e, kind=_flight.OVERFLOW,
                                        config=self.config)
            raise e

    def materialize_interval_late(self, i: int):
        """Regenerate interval i's LATE tuple stream on host (testing):
        returns (vals[n_late] f32, ts[n_late] i64) — the tuples the fused
        step folds in at the START of interval i, before that interval's
        base stream. Empty for interval 0 (no earlier span). Bit-identical
        to the device late_fold generator."""
        import jax
        import jax.numpy as jnp

        if self.n_late == 0 or i == 0:
            return (np.empty(0, np.float32), np.empty(0, np.int64))
        if self._root is None:
            self._root = jax.random.PRNGKey(self.seed)
        base = i * self.wm_period_ms
        lo_l = max(base - self.max_lateness, 0)
        key = self._interval_key(i)
        if self._late_span:
            # segment-fold replay: validity and RNG are pure functions of
            # the absolute grid ts, so no GC-history row count is needed
            R_late, g = self._late_R, self.grid
            first = -(-lo_l // g) * g          # first grid point >= lo_l
            row_ts = np.arange(first, base, g, dtype=np.int64)
            if row_ts.size == 0:
                return (np.empty(0, np.float32), np.empty(0, np.int64))
            keys = jax.vmap(lambda t: jax.random.fold_in(
                key, 0x70000000 + t // g))(jnp.asarray(row_ts))
            u = jax.device_get(jax.vmap(lambda k: jax.random.uniform(
                k, (2, R_late), dtype=jnp.float32))(keys))
            vals = u[:, 0] * np.float32(self.value_scale)
            offs = np.clip(np.floor(np.asarray(u[:, 1], np.float32)
                                    * np.float32(g)), 0, g - 1)
            lts = row_ts[:, None] + offs.astype(np.int64)
            return vals.reshape(-1), lts.reshape(-1)
        key = jax.random.fold_in(key, 0x7fffffff)
        u = jax.device_get(jax.random.uniform(
            key, (2, self.n_late), dtype=jnp.float32))
        lts = (np.float64(lo_l)
               + u[0].astype(np.float64) * (base - lo_l)).astype(np.int64)
        lts = np.minimum(lts, base - 1)
        return u[1] * np.float32(self.value_scale), lts

    def materialize_interval(self, i: int):
        """Regenerate interval i's tuple stream on host (testing): returns
        (vals[S*R] f32, ts[S*R] i64), row-major by slice. Uses the exact
        device RNG stream of the fused step."""
        import jax
        import jax.numpy as jnp

        if self._root is None:
            self._root = jax.random.PRNGKey(self.seed)
        key = self._interval_key(i)
        g, P, S = self.grid, self.wm_period_ms, self.S
        if self.legacy_generator:
            # legacy anchor replay: 32-bit value draws + the offset stream
            # (see gen_rows_legacy) — per-tuple ts = row start + offset
            keys = jax.vmap(lambda r: jax.random.fold_in(key, r))(
                jnp.arange(S, dtype=jnp.int64))
            vals = np.asarray(jax.device_get(jax.vmap(
                lambda k: jax.random.uniform(
                    k, (self.R,), dtype=jnp.float32)
                * self.value_scale)(keys)))
            offs = np.asarray(jax.device_get(jax.vmap(
                lambda k: jnp.clip(jnp.floor(jax.random.uniform(
                    jax.random.fold_in(k, 1), (self.R,),
                    dtype=jnp.float32) * g), 0, g - 1)
                .astype(jnp.int64))(keys)))
            row_starts = i * P + g * np.arange(S, dtype=np.int64)
            ts = row_starts[:, None] + offs
            return vals.reshape(-1), ts.reshape(-1)
        if self._n_sub > 1:
            # sub-row chunking: per-(row, sub) keying (see step_impl) —
            # one vmapped generation over all (row, sub) pairs, not a
            # dispatch per chunk
            q = self.R // self._n_sub
            rr = jnp.repeat(jnp.arange(S, dtype=jnp.int64), self._n_sub)
            ss = jnp.tile(jnp.arange(self._n_sub, dtype=jnp.int64), S)
            vals = np.asarray(jax.device_get(jax.vmap(
                lambda r, s: self._gen_lanes(
                    jax.random.fold_in(jax.random.fold_in(key, r),
                                       0x5f000000 + s), q))(rr, ss))
            ).reshape(S, self.R)
        else:
            # per-row keying makes the stream chunk-shape-independent, so
            # one whole-interval generation replays ANY chunking bit-exact
            vals = np.asarray(jax.device_get(self._gen_rows(
                key, jnp.arange(S, dtype=jnp.int64))))
        row_starts = i * P + g * np.arange(S, dtype=np.int64)
        # tuples sit at their row start (see gen_rows: the offset stream
        # is unobservable on the aligned grid and not generated)
        ts = np.broadcast_to(row_starts[:, None], (S, self.R))
        return vals.reshape(-1), ts.reshape(-1).copy()

    def lowered_results(self, interval_out) -> list:
        """Fetch + lower one interval's window results on host."""
        return lower_interval(self.aggregations, interval_out)
