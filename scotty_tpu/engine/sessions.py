"""Device session state + kernels: fully general session windows on TPU.

TPU-first redesign of the reference's session machinery
(core/.../SessionWindow.java:40-116 session calculus,
slicing/.../SliceManager.java:89-166 flexible-edge slice repair): instead of
sharing one slice store between session and time-grid windows and repairing
slice edges when sessions move (the reference's Shift/Add/Delete calculus),
each registered session window owns a bounded **active-session array** —
SURVEY.md §7 "hard parts" #3 — holding, per live session, its observed tuple
extent ``[first, last]``, tuple count, and one fixed-width partial aggregate
per registered aggregation. Time-grid windows are answered by the grid slice
buffer (:mod:`.core`) untouched; duplicating partial state per window family
is cheap on HBM and removes all data-dependent slice topology.

Invariant (holds under every kernel here, matching the reference calculus):
live sessions are sorted by ``first`` and separated by **strictly more than
``gap``** — so they are also sorted by ``last``, and completed sessions
(``last + gap < watermark``) always form a prefix.

Three kernels:

* **in-order ingest** — a batch of ascending tuples chains into sessions
  wherever the inter-arrival gap exceeds ``gap`` (the in-order
  specialization of SessionContext.updateContext): one segmented
  scatter-combine, no data-dependent control flow.
* **late ingest** — a ``lax.scan`` applying late tuples ONE AT A TIME in
  arrival order. Sequential on purpose: the reference's session calculus is
  arrival-order-dependent at exact-gap boundaries (a tuple landing exactly
  ``gap`` before a session's start extends nothing — SessionWindow.java's
  update falls through every branch — while the same tuple arriving before
  that session existed would have seeded it), so a batched merge cannot
  reproduce it. Late tuples are rare by contract; each step is O(S)
  vectorized work over the session array.
* **sweep** — watermark trigger: emit the completed prefix
  (``[first, last + gap)`` windows, SessionWindow.java:107-116) and compact.

In-order tuples may be processed before interleaved late tuples without
changing any outcome: an in-order tuple interacts only with the newest
session (whose ``last`` equals the running max event time, which no late
tuple can change), and a late tuple's session lookup is unaffected by
sessions created above the pre-batch maximum.
"""

from __future__ import annotations

from typing import NamedTuple

import numpy as np

import jax
import jax.numpy as jnp

from ..core.aggregates import DeviceAggregateSpec
from .core import I64_MAX, I64_MIN, _combine_scatter, _lift


class SessionState(NamedTuple):
    """One session window's live sessions as a pytree of device arrays.

    The orphan buffer holds tuples the session calculus DROPS (the
    exact-gap fall-through in SessionWindow.java's update — see
    :func:`build_session_late`). In the reference those tuples still live in
    shared slices, so a session that later merges/extends over their
    position recovers their values at emission; the orphan buffer
    reproduces that recovery by position (slice-granularity data loss the
    reference sporadically exhibits is NOT reproduced — the engine reports
    the exact aggregate, same policy as PARITY.md deviation 5).
    """

    first: jnp.ndarray     # i64[S] min observed tuple ts; I64_MAX = unused
    last: jnp.ndarray      # i64[S] max observed tuple ts; I64_MIN = unused
    counts: jnp.ndarray    # i64[S] tuples per session
    partials: tuple        # per agg: f32[S, width]
    n: jnp.ndarray         # i32 scalar — live session count
    o_pos: jnp.ndarray     # i64[O] orphan tuple positions; I64_MAX = unused
    o_partials: tuple      # per agg: f32[O, width] — one lifted tuple each
    o_n: jnp.ndarray       # i32 scalar — orphan count
    overflow: jnp.ndarray  # bool scalar — capacity exhausted


def init_session_state(aggs: tuple[DeviceAggregateSpec, ...], capacity: int,
                       orphan_capacity: int = 64,
                       dtype=jnp.float32) -> SessionState:
    S, O = capacity, orphan_capacity
    return SessionState(
        first=jnp.full((S,), I64_MAX, dtype=jnp.int64),
        last=jnp.full((S,), I64_MIN, dtype=jnp.int64),
        counts=jnp.zeros((S,), dtype=jnp.int64),
        partials=tuple(jnp.full((S, a.width), a.identity, dtype=dtype)
                       for a in aggs),
        n=jnp.int32(0),
        o_pos=jnp.full((O,), I64_MAX, dtype=jnp.int64),
        o_partials=tuple(jnp.full((O, a.width), a.identity, dtype=dtype)
                         for a in aggs),
        o_n=jnp.int32(0),
        overflow=jnp.bool_(False),
    )


def build_session_ingest(aggs: tuple[DeviceAggregateSpec, ...], gap: int,
                         capacity: int):
    """Batched in-order ingest: ``ts`` ascending, every ts at or above the
    newest session's ``last``. A new session opens where the inter-arrival
    gap exceeds ``gap`` (inclusive join: ``ts - prev <= gap`` chains, the
    reference's ``end + gap >= position`` forward extension)."""
    S = capacity
    gap_j = jnp.int64(gap)

    def ingest(st: SessionState, ts: jnp.ndarray, vals: jnp.ndarray,
               valid: jnp.ndarray) -> SessionState:
        B = ts.shape[0]
        n = st.n
        # chain against the NEWEST LIVE session's extent, not the stream
        # max event time: after a sweep emptied the array (or late tuples
        # seeded sessions below the max) the two differ, and the reference
        # chains on the live context only (SessionWindow.java:40-45).
        open_last = jnp.where(n > 0, st.last[jnp.maximum(n - 1, 0)],
                              jnp.int64(I64_MIN))
        prev = jnp.concatenate([open_last[None], ts[:-1]])
        first_ever = (jnp.arange(B) == 0) & (n == 0)
        newflag = valid & (first_ever | (ts - prev > gap_j))
        k = jnp.cumsum(newflag.astype(jnp.int32))
        pos = jnp.clip((n - 1) + k, 0, S - 1)
        overflow = st.overflow | (((n - 1) + k[-1]) >= S)

        one = jnp.where(valid, jnp.int64(1), jnp.int64(0))
        first = st.first.at[pos].min(jnp.where(valid, ts, I64_MAX))
        last = st.last.at[pos].max(jnp.where(valid, ts, I64_MIN))
        counts = st.counts.at[pos].add(one)
        partials = []
        for agg, part in zip(aggs, st.partials):
            dense, sparse = _lift(agg, vals, valid)
            if sparse is None:
                part = _combine_scatter(part, pos, dense, agg.kind)
            else:
                col, v = sparse
                part = _combine_scatter(part, (pos, col), v, agg.kind)
            partials.append(part)
        return st._replace(
            first=first, last=last, counts=counts, partials=tuple(partials),
            n=(n + k[-1]).astype(jnp.int32), overflow=overflow)

    return ingest


def build_session_ingest_dense(aggs: tuple[DeviceAggregateSpec, ...],
                               gap: int, capacity: int, runs: int):
    """In-order session ingest without [B]-lane scatters (the benchmark fast
    path, same trick as :func:`.core.build_ingest_dense`): when the batch
    opens fewer than ``runs`` sessions, run boundaries come from two vmapped
    ``searchsorted``, sum partials from a one-hot MXU matmul, min/max from a
    masked reduce, and only ``runs`` buffer rows are scattered. Raises the
    overflow flag when the bound is violated (host falls back)."""
    S, R = capacity, runs
    gap_j = jnp.int64(gap)

    def ingest(st: SessionState, ts: jnp.ndarray, vals: jnp.ndarray,
               valid: jnp.ndarray) -> SessionState:
        B = ts.shape[0]
        n = st.n
        open_last = jnp.where(n > 0, st.last[jnp.maximum(n - 1, 0)],
                              jnp.int64(I64_MIN))
        prev = jnp.concatenate([open_last[None], ts[:-1]])
        first_ever = (jnp.arange(B) == 0) & (n == 0)
        newflag = valid & (first_ever | (ts - prev > gap_j))
        k = jnp.cumsum(newflag.astype(jnp.int32))        # run id per lane
        k_last = k[-1]
        row_n = jnp.sum(valid.astype(jnp.int32))

        r_idx = jnp.arange(R, dtype=jnp.int32)
        lo = jnp.searchsorted(k, r_idx, side="left")
        hi = jnp.minimum(jnp.searchsorted(k, r_idx, side="right") - 1,
                         row_n - 1)
        cnt_r = jnp.maximum(hi - lo + 1, 0).astype(jnp.int64)
        live = cnt_r > 0
        first_r = ts[jnp.clip(lo, 0, B - 1)]
        last_r = ts[jnp.clip(hi, 0, B - 1)]

        rows = jnp.clip((n - 1) + r_idx, 0, S - 1)
        first = st.first.at[rows].min(jnp.where(live, first_r, I64_MAX))
        last = st.last.at[rows].max(jnp.where(live, last_r, I64_MIN))
        counts = st.counts.at[rows].add(jnp.where(live, cnt_r, 0))

        partials = []
        for agg, part in zip(aggs, st.partials):
            dense, sparse = _lift(agg, vals, valid)
            if sparse is None:
                if agg.kind == "sum":
                    oh = (k[:, None] == r_idx[None, :]).astype(part.dtype)
                    upd = oh.T @ dense                       # [R, w] — MXU
                    upd = jnp.where(live[:, None], upd, 0)
                    part = part.at[rows].add(upd)
                else:
                    oh = k[:, None] == r_idx[None, :]
                    ident = jnp.asarray(agg.identity, part.dtype)
                    masked = jnp.where(oh[:, :, None], dense[:, None, :],
                                       ident)                # [B, R, w]
                    op_ = jnp.min if agg.kind == "min" else jnp.max
                    upd = op_(masked, axis=0)
                    upd = jnp.where(live[:, None], upd, ident)
                    part = _combine_scatter(part, rows, upd, agg.kind)
            else:
                # sparse lifts (sketches) scatter into [R, w] — R rows, so
                # the scatter target is tiny even at 1M-lane batches
                col, v = sparse
                part = _combine_scatter(part, (rows[k], col), v, agg.kind)
            partials.append(part)

        return st._replace(
            first=first, last=last, counts=counts, partials=tuple(partials),
            n=(n + k_last).astype(jnp.int32),
            overflow=(st.overflow | (((n - 1) + k_last) >= S)
                      | (k_last > R - 1)))

    return ingest


def build_session_late(aggs: tuple[DeviceAggregateSpec, ...], gap: int,
                       capacity: int, late_len: int):
    """Sequential late-tuple application (lax.scan, arrival order).

    Each step replays SessionContext.updateContext exactly
    (SessionWindow.java:40-98) against the session array:

    * find the EARLIEST session in reach (``first - gap <= pos <= last +
      gap`` — the getSession linear scan, vectorized to a masked argmax);
    * inside ``[first, last]`` → fold the tuple in;
    * ``first - gap < pos < first`` → extend start, then merge with the
      previous session when ``last[j-1] + gap >= pos`` (mergeWithPre);
    * ``last < pos <= last + gap`` → extend end, then merge with the next
      session when ``pos + gap >= first[j+1]``;
    * exactly ``pos == first - gap`` (and out of reach of every earlier
      session) → **no session change**: the reference's update falls through
      every branch and returns null, and the tuple's slice lands outside
      every emitted session window — the tuple vanishes from session
      results. Reproduced bit-for-bit (the count/value still reaches
      time-grid windows through the grid path).
    * no session in reach → insert a fresh ``[pos, pos]`` session at its
      sorted position.
    """
    S, L = capacity, late_len
    gap_j = jnp.int64(gap)
    idx = jnp.arange(S)

    def shift_left(arr, b, flag, fill):
        """Delete row b (rows above slide down) where flag."""
        nxt = jnp.concatenate([arr[1:], jnp.full_like(arr[:1], fill)])
        return jnp.where(_bcast(flag & (idx >= b), arr), nxt, arr)

    def shift_right(arr, p, flag, fill):
        """Open row p (rows at/above slide up) where flag."""
        prv = jnp.concatenate([jnp.full_like(arr[:1], fill), arr[:-1]])
        return jnp.where(_bcast(flag & (idx > p), arr), prv, arr)

    def _bcast(mask, arr):
        return mask if arr.ndim == 1 else mask[:, None]

    def step(carry, x):
        st = carry
        pos, valid, lifts = x
        live = idx < st.n
        reach = live & (st.first - gap_j <= pos) & (pos <= st.last + gap_j)
        has = reach.any()
        j = jnp.argmax(reach)                    # earliest session in reach
        fj, lj = st.first[j], st.last[j]
        inside = valid & has & (fj <= pos) & (pos <= lj)
        ext_s = valid & has & (fj > pos) & (fj - gap_j < pos)
        ext_e = valid & has & (lj < pos) & (pos <= lj + gap_j)
        new = valid & ~has
        touch = inside | ext_s | ext_e
        # the exact-gap fall-through (pos == first - gap, out of reach of
        # every earlier session): no session changes, but the tuple's value
        # must be recoverable by a session that later covers its position —
        # park it in the orphan buffer (consumed or GC'd at sweep time)
        dropped = valid & has & ~touch

        jm1 = jnp.maximum(j - 1, 0)
        jp1 = jnp.minimum(j + 1, S - 1)
        merge_pre = ext_s & (j > 0) & (st.last[jm1] + gap_j >= pos)
        merge_nxt = ext_e & (j + 1 < st.n) & (pos + gap_j >= st.first[jp1])

        onej = idx == j
        first = jnp.where(onej & ext_s, pos, st.first)
        last = jnp.where(onej & ext_e, pos, st.last)
        counts = st.counts + jnp.where(onej & touch, 1, 0)
        partials = []
        for agg, part, lift in zip(aggs, st.partials, lifts):
            if agg.is_sparse:
                col, v = lift
                m2 = (onej & touch)[:, None] \
                    & (jnp.arange(part.shape[1]) == col)[None, :]
            else:
                v = lift
                m2 = (onej & touch)[:, None]
            if agg.kind == "sum":
                part = jnp.where(m2, part + v, part)
            elif agg.kind == "min":
                part = jnp.where(m2, jnp.minimum(part, v), part)
            else:
                part = jnp.where(m2, jnp.maximum(part, v), part)
            partials.append(part)

        # -- merge (at most one per tuple, like the reference) -------------
        do_merge = merge_pre | merge_nxt
        a = jnp.where(merge_pre, jm1, j)         # absorbing row
        b = a + 1                                # deleted row
        onea = idx == a
        last = jnp.where(onea & do_merge, last[jnp.minimum(b, S - 1)], last)
        counts = jnp.where(onea & do_merge,
                           counts[a] + counts[jnp.minimum(b, S - 1)], counts)
        merged = []
        for agg, part in zip(aggs, partials):
            pa = part[a]
            pb = part[jnp.minimum(b, S - 1)]
            comb = (pa + pb if agg.kind == "sum"
                    else jnp.minimum(pa, pb) if agg.kind == "min"
                    else jnp.maximum(pa, pb))
            merged.append(jnp.where((onea & do_merge)[:, None], comb, part))
        first = shift_left(first, b, do_merge, I64_MAX)
        last = shift_left(last, b, do_merge, I64_MIN)
        counts = shift_left(counts, b, do_merge, 0)
        merged = [shift_left(p, b, do_merge, a.identity)
                  for a, p in zip(aggs, merged)]

        # -- insert (exclusive with merge: only when nothing in reach) -----
        p = jnp.searchsorted(first, pos, side="left").astype(idx.dtype)
        first = shift_right(first, p, new, I64_MAX)
        last = shift_right(last, p, new, I64_MIN)
        counts = shift_right(counts, p, new, 0)
        inserted = []
        for agg, part, lift in zip(aggs, merged, lifts):
            part = shift_right(part, p, new, agg.identity)
            if agg.is_sparse:
                col, v = lift
                m2 = (idx == p)[:, None] \
                    & (jnp.arange(part.shape[1]) == col)[None, :] & new
                base = jnp.where((idx == p)[:, None] & new,
                                 jnp.asarray(agg.identity, part.dtype), part)
                part = jnp.where(m2, v, base)
            else:
                part = jnp.where((idx == p)[:, None] & new, lift, part)
            inserted.append(part)
        onep = idx == p
        first = jnp.where(onep & new, pos, first)
        last = jnp.where(onep & new, pos, last)
        counts = jnp.where(onep & new, 1, counts)

        # -- orphan append (exclusive with every other action) -------------
        O = st.o_pos.shape[0]
        oidx = jnp.arange(O)
        oneo = (oidx == st.o_n) & dropped
        o_pos = jnp.where(oneo, pos, st.o_pos)
        o_partials = []
        for agg, part, lift in zip(aggs, st.o_partials, lifts):
            if agg.is_sparse:
                col, v = lift
                m2 = oneo[:, None] \
                    & (jnp.arange(part.shape[1]) == col)[None, :]
                base = jnp.where(oneo[:, None],
                                 jnp.asarray(agg.identity, part.dtype), part)
                part = jnp.where(m2, v, base)
            else:
                part = jnp.where(oneo[:, None], lift, part)
            o_partials.append(part)

        n2 = st.n + jnp.where(new, 1, 0) - jnp.where(do_merge, 1, 0)
        o_n2 = st.o_n + jnp.where(dropped, 1, 0)
        overflow = st.overflow | (new & (st.n >= S)) \
            | (dropped & (st.o_n >= O))
        return SessionState(first=first, last=last, counts=counts,
                            partials=tuple(inserted),
                            n=n2.astype(jnp.int32),
                            o_pos=o_pos, o_partials=tuple(o_partials),
                            o_n=o_n2.astype(jnp.int32),
                            overflow=overflow), None

    # lifts are precomputed vectorized OUTSIDE the scan (one lift per agg
    # over the [L] late lanes), so each step only gathers its row.
    def ingest(st: SessionState, ts: jnp.ndarray, vals: jnp.ndarray,
               valid: jnp.ndarray) -> SessionState:
        lifts = []
        for agg in aggs:
            if agg.is_sparse:
                col, v = agg.lift_sparse(vals)
                lifts.append((col.astype(jnp.int32),
                              jnp.where(valid, v, agg.identity)))
            else:
                lifted = agg.lift_dense(vals)
                lifts.append(jnp.where(valid[:, None], lifted, agg.identity))
        out, _ = jax.lax.scan(step, st, (ts, valid, tuple(lifts)))
        return out

    return ingest


def build_session_sweep(aggs: tuple[DeviceAggregateSpec, ...], gap: int,
                        capacity: int, emit_cap: int):
    """Watermark trigger: emit sessions with ``last + gap < watermark`` as
    ``[first, last + gap)`` windows (SessionWindow.java:107-116) and compact
    the array. Completed sessions are a prefix (see module invariant), so
    emission is a prefix gather and compaction a masked roll.

    Orphaned tuples (exact-gap drops) whose position an emitted window
    covers fold into that window's value — the engine equivalent of the
    reference recovering a context-dropped tuple through slice containment
    when a session later expands over it. Consumed orphans and orphans
    behind ``gc_bound`` (no future tuple may create a session reaching
    them) are compacted away.

    Returns (new_state, m, starts[E], ends[E], counts[E], partials…[E]);
    rows at index >= m are padding.
    """
    S, E = capacity, emit_cap
    gap_j = jnp.int64(gap)

    def sweep(st: SessionState, wm: jnp.ndarray, gc_bound: jnp.ndarray):
        live = jnp.arange(S) < st.n
        done = live & (st.last + gap_j < wm)
        m = jnp.sum(done.astype(jnp.int32))
        idx = jnp.arange(E)
        sel = jnp.clip(idx, 0, S - 1)
        e_starts = jnp.where(idx < m, st.first[sel], I64_MAX)
        e_ends = jnp.where(idx < m, st.last[sel] + gap_j, I64_MAX)
        e_counts = jnp.where(idx < m, st.counts[sel], 0)
        e_partials = [p[sel] for p in st.partials]
        em_overflow = m > E

        # -- orphan recovery (at most one window covers an orphan) ---------
        O = st.o_pos.shape[0]
        o_live = jnp.arange(O) < st.o_n
        cov = (o_live[None, :] & (e_starts[:, None] <= st.o_pos[None, :])
               & (st.o_pos[None, :] < e_ends[:, None]))        # [E, O]
        e_counts = e_counts + jnp.sum(cov, axis=1)
        for i, (agg, op_) in enumerate(zip(aggs, st.o_partials)):
            if agg.kind == "sum":
                e_partials[i] = e_partials[i] \
                    + cov.astype(op_.dtype) @ op_              # [E, w] MXU
            else:
                ident = jnp.asarray(agg.identity, op_.dtype)
                masked = jnp.where(cov[:, :, None], op_[None, :, :], ident)
                red = (jnp.min if agg.kind == "min" else jnp.max)(masked,
                                                                 axis=1)
                e_partials[i] = (jnp.minimum if agg.kind == "min"
                                 else jnp.maximum)(e_partials[i], red)
        consumed = jnp.any(cov, axis=0)
        # an orphan stays alive while (a) a still-live session's eventual
        # window [first, last+gap) could cover it, or (b) an in-contract
        # future tuple (ts >= gc_bound = wm - lateness) could seed a session
        # reaching it; otherwise it is dead and compacted away
        live_rows = jnp.arange(S) >= m
        live_mask = live_rows & (jnp.arange(S) < st.n)
        cov_live = jnp.any(
            live_mask[:, None] & (st.first[:, None] <= st.o_pos[None, :])
            & (st.o_pos[None, :] < st.last[:, None] + gap_j), axis=0)
        keep_o = o_live & ~consumed \
            & (cov_live | (st.o_pos >= gc_bound - gap_j))
        order = jnp.argsort(~keep_o, stable=True)      # kept orphans first
        o_n2 = jnp.sum(keep_o.astype(jnp.int32)).astype(jnp.int32)
        o_pos2 = jnp.where(jnp.arange(O) < o_n2, st.o_pos[order], I64_MAX)
        o_partials2 = tuple(
            jnp.where((jnp.arange(O) < o_n2)[:, None], p[order],
                      jnp.asarray(a.identity, p.dtype))
            for a, p in zip(aggs, st.o_partials))

        def roll(a, fill):
            rolled = jnp.roll(a, -m, axis=0)
            keep = jnp.arange(a.shape[0]) < (a.shape[0] - m)
            if a.ndim == 1:
                return jnp.where(keep, rolled, fill)
            return jnp.where(keep[:, None], rolled, fill)

        new_state = SessionState(
            first=roll(st.first, I64_MAX),
            last=roll(st.last, I64_MIN),
            counts=roll(st.counts, 0),
            partials=tuple(roll(p, a.identity)
                           for a, p in zip(aggs, st.partials)),
            n=(st.n - m).astype(jnp.int32),
            o_pos=o_pos2, o_partials=o_partials2, o_n=o_n2,
            overflow=st.overflow | em_overflow,
        )
        return new_state, m, e_starts, e_ends, e_counts, tuple(e_partials)

    return sweep
