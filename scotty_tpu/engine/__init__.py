"""TPU device engine: slice ring buffers in HBM, batched segment-combine
ingest, prefix-sum / sparse-table window queries (SURVEY.md §7)."""

from .config import EngineConfig
from .operator import TpuWindowOperator, UnsupportedOnDevice

__all__ = ["EngineConfig", "TpuWindowOperator", "UnsupportedOnDevice"]
