"""Host→device ingest pipeline (SURVEY.md §7 stage 7): double-buffered
transfers of packed tuple batches overlapping the previous batch's ingest.

**This is the PRE-SHAPED fast path**: both feeds hard-error on unsorted
input (``pack`` raises on any descending timestamp) because they exist to
saturate the link with zero per-tuple host work. A stream that is not
already sorted-and-batched belongs to the general entry point,
:class:`scotty_tpu.shaper.StreamShaper` (ISSUE 5) — its accumulator
coalesces and sorts irregular host records into exactly the blocks these
feeds want, and its device sort-and-split shapes device-resident batches
without a host round trip.

The reference's LoadGeneratorSource emits tuples in-process
(benchmark/.../LoadGeneratorSource.java:10-87) — there IS no host→device
boundary in the reference. On TPU the boundary is real, and this module is
the framework's story for streams that originate in host memory:

* **Packing**: an in-order batch ships as ``(base i64 scalar, ts-delta
  u32[B], value f32[B])`` — 8 bytes/tuple instead of 12; deltas are exact
  while the batch spans < 2^32 ms (~49 days).
* **Double buffering**: ``feed()`` issues the H2D transfers and the
  unpack+ingest dispatch WITHOUT any device sync, so batch i+1's transfer
  overlaps batch i's ingest kernel under the runtime's async dispatch
  queue. The slice-engine state advances through the same donated-buffer
  kernels as device-resident sources.
* **Transport saturation is the design target**: the ingest kernels
  sustain multi-G tuples/s from device-resident sources (bench.py), so a
  host-fed stream is transport-bound on any link slower than that.
  ``measure_link()`` reports the raw ``device_put`` bandwidth of the same
  packed buffers; an end-to-end rate close to it means the pipeline adds
  ~nothing on top of the link. (On the tunneled devices this repo
  benchmarks on, the measured link is ~1 MB/s — see BASELINE.md — so
  absolute host-fed numbers say nothing about the engine; the saturation
  ratio does.)
"""

from __future__ import annotations

import time
from typing import Optional

import numpy as np

from .. import jax_config  # noqa: F401

from .operator import TpuWindowOperator


class HostFeed:
    """Double-buffered packed feed into a :class:`TpuWindowOperator`.

    Batches must be in-order (ascending ts, each batch at/above the
    previous batch's max) and exactly ``op.config.batch_size`` long —
    the operator's zero-copy device-batch contract.
    """

    def __init__(self, op: TpuWindowOperator):
        import jax
        import jax.numpy as jnp

        self.op = op
        self._unpack = jax.jit(
            lambda base, d: jnp.int64(base) + d.astype(jnp.int64))
        self.bytes_per_tuple = 8          # u32 delta + f32 value

    @staticmethod
    def pack(vals: np.ndarray, ts: np.ndarray):
        """Host-side packing: (base, deltas u32, vals f32).

        Raises ValueError when the in-order / <2^32-ms-span contract is
        violated — a silent u32 wrap would corrupt timestamps (ADVICE r3).
        """
        base = np.int64(ts[0])
        wide = np.asarray(ts, dtype=np.int64) - base
        if int(wide.max()) >= 1 << 32 or (wide.size > 1
                                          and (np.diff(wide) < 0).any()):
            raise ValueError(
                "HostFeed.pack: unsorted ts or span >= 2**32 ms — the "
                "in-order contract is violated and a u32 delta would wrap "
                "or feed a stale ts_max downstream (ADVICE r3)")
        deltas = wide.astype(np.uint32)
        return base, deltas, np.ascontiguousarray(vals, dtype=np.float32)

    def feed_packed(self, base: np.int64, deltas: np.ndarray,
                    vals: np.ndarray, ts_min: int, ts_max: int) -> None:
        """Transfer + dispatch one packed batch; returns without syncing."""
        import jax

        d_dev = jax.device_put(deltas)
        v_dev = jax.device_put(vals)
        ts_dev = self._unpack(base, d_dev)
        self.op.ingest_device_batch(v_dev, ts_dev, ts_min, ts_max)

    def feed(self, vals: np.ndarray, ts: np.ndarray) -> None:
        base, deltas, v = self.pack(vals, ts)
        self.feed_packed(base, deltas, v, int(ts[0]), int(ts[-1]))


class KeyedHostFeed:
    """Double-buffered packed feed into a ``KeyedTpuWindowOperator``
    (VERDICT r3 item 7): host-side (key, value, ts) records pack into one
    ``[K, Bk]`` round per transfer — u32 ts-deltas + f32 values, padded
    rows masked on device from a tiny per-key count vector.

    Packing is fully vectorized (one stable argsort by key + a fancy-index
    write — the stream is globally ts-ascending, so a stable key sort
    leaves each key's run ascending), the reference's keyBy→operator
    boundary (flinkBenchmark/BenchmarkJob.java:84-102) with the transport
    explicit.
    """

    def __init__(self, op):
        import jax
        import jax.numpy as jnp

        self.op = op
        K, Bk = op.n_keys, op.config.batch_size
        self.K, self.Bk = K, Bk
        self._unpack = jax.jit(
            lambda base, d: jnp.int64(base) + d.astype(jnp.int64))
        self._mask = jax.jit(
            lambda row_n: jnp.arange(Bk)[None, :] < row_n[:, None])
        self.bytes_per_tuple = 8          # u32 delta + f32 value (pre-pad)

    def pack(self, keys: np.ndarray, vals: np.ndarray, ts: np.ndarray):
        """(base, deltas u32[K, Bk], vals f32[K, Bk], counts i32[K]).
        Contract: ts globally ascending, < 2**32 ms span, every per-key
        count <= Bk (ValueError otherwise)."""
        K, Bk = self.K, self.Bk
        base = np.int64(ts[0])
        wide = np.asarray(ts, dtype=np.int64) - base
        if int(wide.max()) >= 1 << 32 or (wide.size > 1
                                          and (np.diff(wide) < 0).any()):
            raise ValueError("KeyedHostFeed.pack: unsorted ts or span >= "
                             "2**32 ms violates the in-order contract")
        order = np.argsort(keys, kind="stable")
        k2 = np.asarray(keys, np.int64)[order]
        if k2.size and (k2[-1] >= K or k2[0] < 0):
            # a round can hold BOTH negative and >= K keys — report every
            # offending value class plus the out-of-range count, not just
            # whichever end the old single-value message happened to pick
            bad = (k2 < 0) | (k2 >= K)
            offenders = []
            if k2[0] < 0:
                offenders.append(int(k2[0]))
            if k2[-1] >= K:
                offenders.append(int(k2[-1]))
            raise ValueError(
                f"KeyedHostFeed.pack: {int(bad.sum())} tuple(s) with keys "
                f"out of range [0, {K}); offending value(s): "
                f"{', '.join(str(o) for o in offenders)}")
        counts = np.bincount(k2, minlength=K)
        if counts.max(initial=0) > Bk:
            raise ValueError(
                f"KeyedHostFeed.pack: a key holds {int(counts.max())} "
                f"tuples > round size {Bk}; shrink rounds or raise "
                "batch_size")
        row_starts = np.zeros((K,), np.int64)
        row_starts[1:] = np.cumsum(counts)[:-1]
        pos = np.arange(k2.size, dtype=np.int64) - row_starts[k2]
        deltas = np.zeros((K, Bk), np.uint32)
        deltas[k2, pos] = wide[order].astype(np.uint32)
        vb = np.zeros((K, Bk), np.float32)
        vb[k2, pos] = np.asarray(vals, np.float32)[order]
        return base, deltas, vb, counts.astype(np.int32)

    def feed_packed(self, base, deltas, vb, counts, ts_min: int,
                    ts_max: int) -> None:
        """Transfer + dispatch one packed round; returns without syncing."""
        import jax

        d_dev = jax.device_put(deltas)
        v_dev = jax.device_put(vb)
        rn = jax.device_put(counts)
        self.op.ingest_device_round(self._unpack(base, d_dev), v_dev,
                                    self._mask(rn), ts_min, ts_max)

    def feed(self, keys, vals, ts) -> None:
        base, d, v, c = self.pack(keys, vals, ts)
        self.feed_packed(base, d, v, c, int(ts[0]), int(ts[-1]))


def measure_link(batch_size: int, n_batches: int = 8) -> float:
    """Raw host→device bandwidth of the packed layout (MB/s): device_put
    of (u32, f32) pairs, consumed by a trivial device reduction so the
    measurement can't complete before the bytes actually land."""
    import jax
    import jax.numpy as jnp

    consume = jax.jit(lambda d, v: jnp.sum(d) + jnp.sum(v).astype(jnp.int64))
    deltas = np.arange(batch_size, dtype=np.uint32)
    vals = np.random.default_rng(0).random(batch_size).astype(np.float32)
    int(consume(jax.device_put(deltas), jax.device_put(vals)))  # warm
    t0 = time.perf_counter()
    acc = []
    for _ in range(n_batches):
        acc.append(consume(jax.device_put(deltas), jax.device_put(vals)))
    jax.device_get(acc)
    dt = time.perf_counter() - t0
    return n_batches * batch_size * 8 / dt / 1e6
