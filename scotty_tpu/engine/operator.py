"""TPU window operator: the device-engine implementation of WindowOperator.

Host driver around the device kernels in :mod:`.core`: buffers tuples into
fixed-size batches, launches the ingest kernel, and on each watermark
enumerates triggered windows in closed form (host-side numpy — the exact
trigger order of WindowManager.processWatermark, WindowManager.java:41-80),
answers them all with one device query, and GCs the slice buffer.

Covers context-free tumbling / sliding / fixed-band windows in Time and
Count measure (any mix, in-order or out-of-order within ``max_lateness``)
and Time-measure session windows, with device-realizable aggregations.
Count workloads retain records in a device rank buffer (the closed form of
the reference's OOO ripple); count+time mixes additionally run the
arrival-order cut calculus host-side (``_mixed_cut_calculus``). Remaining
host-only classes — count-measure sessions, arbitrary-object elements,
host-only aggregates — run on the reference-semantics operator
(`scotty_tpu.simulator.SlicingWindowOperator`); `scotty_tpu.HybridWindowOperator`
picks automatically — the same role the eager/lazy decision tree plays in the
reference (SliceFactory.java:17-22).
"""

from __future__ import annotations

import time
from typing import Any, List, Optional, Sequence

import numpy as np

from .. import obs as _obs
from ..obs import flight as _flight
from ..obs import latency as _lat
from ..core.aggregates import AggregateFunction
from ..core.operator import AggregateWindow, WindowOperator
from ..core.windows import (
    LONG_MAX,
    ContextFreeWindow,
    FixedBandWindow,
    ForwardContextAware,
    ForwardContextFree,
    SessionWindow,
    SlidingWindow,
    TumblingWindow,
    Window,
    WindowMeasure,
)
from ..state import StateFactory
from .config import EngineConfig


class UnsupportedOnDevice(NotImplementedError):
    """Raised when a window/aggregation mix has no device realization."""


_KERNEL_CACHE: dict = {}


def _session_kernels(aggs, gap: int, capacity: int, late_len: int,
                     emit_cap: int):
    """Jitted session kernels (in-order ingest + late scan + sweep) for one
    registered session window, cached like _kernels."""
    import jax
    from . import sessions as es

    key = ("session", gap, tuple(a.token for a in aggs), capacity, late_len,
           emit_cap)
    hit = _KERNEL_CACHE.get(key)
    if hit is None:
        hit = (
            jax.jit(es.build_session_ingest(aggs, gap, capacity),
                    donate_argnums=0),
            jax.jit(es.build_session_late(aggs, gap, capacity, late_len),
                    donate_argnums=0),
            jax.jit(es.build_session_sweep(aggs, gap, capacity, emit_cap),
                    donate_argnums=0),
        )
        _KERNEL_CACHE[key] = hit
    return hit


def _session_dense_kernel(aggs, gap: int, capacity: int, runs: int):
    """Jitted run-bounded in-order session ingest, cached."""
    import jax
    from . import sessions as es

    key = ("session-dense", gap, tuple(a.token for a in aggs), capacity,
           runs)
    hit = _KERNEL_CACHE.get(key)
    if hit is None:
        hit = jax.jit(es.build_session_ingest_dense(aggs, gap, capacity,
                                                    runs),
                      donate_argnums=0)
        _KERNEL_CACHE[key] = hit
    return hit


def _kernels(spec, capacity: int, annex_capacity: int,
             record_capacity: int = 0):
    """Jitted kernels shared across operator instances with the same static
    spec — compilation is the dominant cost of small runs/tests."""
    import jax
    from . import core as ec

    key = (spec.periods, spec.bands, spec.count_periods, spec.session_gaps,
           spec.offset_periods, tuple(a.token for a in spec.aggs), capacity,
           annex_capacity, record_capacity)
    hit = _KERNEL_CACHE.get(key)
    if hit is None:
        hit = (
            jax.jit(ec.build_ingest(spec, capacity, annex_capacity),
                    donate_argnums=0),
            # plain query/probe: exact from slice partials while the
            # stream is in-order (cheap); record-aware variants take over
            # permanently once a late count tuple is seen
            jax.jit(ec.build_query(spec, capacity, annex_capacity, 0)),
            jax.jit(ec.build_gc(spec, capacity, annex_capacity)),
            jax.jit(ec.build_count_probe(spec, capacity)),
            jax.jit(ec.build_annex_merge(spec, capacity, annex_capacity),
                    donate_argnums=0),
            # in-order batches skip the late/annex scatter sets entirely
            # (int64 scatters dominate ingest cost — ~100 ms per 1M lanes)
            jax.jit(ec.build_ingest(spec, capacity, annex_capacity,
                                    assume_inorder=True),
                    donate_argnums=0),
            # rec-aware query: for count+time mixes ALL windows answer from
            # record rank ranges once a late tuple was seen (mix_rec)
            jax.jit(ec.build_query(spec, capacity, annex_capacity,
                                   record_capacity,
                                   mix_rec=spec.has_time_grid))
            if record_capacity else None,
            jax.jit(ec.build_count_probe(spec, capacity, record_capacity))
            if record_capacity else None,
            # count ingest with host-supplied arrival-order cut starts
            jax.jit(ec.build_ingest(spec, capacity, annex_capacity,
                                    assume_inorder=True,
                                    with_cut_starts=True),
                    donate_argnums=0)
            if record_capacity else None,
            # arrival-order row-scatter ingest (OOO count+time mixes)
            jax.jit(ec.build_ingest_rows(spec, capacity), donate_argnums=0)
            if record_capacity and spec.has_time_grid else None,
        )
        _KERNEL_CACHE[key] = hit
    return hit


def _record_kernels(record_capacity: int, capacity: int):
    """Jitted record-buffer kernels (count-measure workloads), cached."""
    import jax
    from . import core as ec

    key = ("records", record_capacity, capacity)
    hit = _KERNEL_CACHE.get(key)
    if hit is None:
        hit = (
            jax.jit(ec.build_record_merge(record_capacity),
                    donate_argnums=0),
            jax.jit(ec.build_record_gc(capacity, record_capacity),
                    donate_argnums=1),
            jax.jit(ec.build_record_append(record_capacity),
                    donate_argnums=0),
        )
        _KERNEL_CACHE[key] = hit
    return hit


def _context_kernels(aggs, spec, capacity: int, emit_cap: int):
    """Jitted generic context-window kernels (apply scan + sweep), cached
    by the spec's token — see engine/context.py."""
    import jax
    from . import context as ectx

    key = ("context", spec.token(), tuple(a.token for a in aggs), capacity,
           emit_cap)
    hit = _KERNEL_CACHE.get(key)
    if hit is None:
        hit = (
            jax.jit(ectx.build_context_apply(aggs, spec, capacity),
                    donate_argnums=0),
            jax.jit(ectx.build_context_sweep(aggs, spec, capacity,
                                             emit_cap),
                    donate_argnums=0),
        )
        _KERNEL_CACHE[key] = hit
    return hit


def _context_chunk_kernel(aggs, spec, capacity: int, chunk_len: int):
    """Jitted vectorized in-order chain kernel (one per padded chunk
    length), cached by the spec's token — see
    engine/context.py::build_context_chunk."""
    import jax
    from . import context as ectx

    key = ("context-chunk", spec.token(), tuple(a.token for a in aggs),
           capacity, chunk_len)
    hit = _KERNEL_CACHE.get(key)
    if hit is None:
        hit = jax.jit(
            ectx.build_context_chunk(aggs, spec, capacity, chunk_len),
            donate_argnums=0)
        _KERNEL_CACHE[key] = hit
    return hit


def _dm_ingest_kernel():
    """Jitted DeviceMetrics batch updater for device-resident ingest
    (ingest_device_batch / ingest_device_late): device timestamps are
    opaque to the host, so exact late counts/ages can only be computed
    in-jit. Arrival-order running max (cummax) seeded at the stream's
    host-known max event time — the same calculus a host arrival-order
    replay computes. Cached like the other kernels; zero host syncs."""
    import jax
    import jax.numpy as jnp

    from . import core as ec
    from ..obs import device as _dev

    key = ("dm_ingest",)
    hit = _KERNEL_CACHE.get(key)
    if hit is None:
        def upd(dm, ts, valid, met_pre):
            ts = jnp.asarray(ts)
            valid = jnp.asarray(valid)
            eff = jnp.where(valid, ts, jnp.int64(ec.I64_MIN))
            shifted = jnp.concatenate(
                [jnp.reshape(jnp.int64(met_pre), (1,)), eff[:-1]])
            rm = jax.lax.cummax(shifted)
            late_m = valid & (ts < rm)
            dm = _dev.record_late_ages(dm, rm - ts, late_m)
            return dm._replace(
                ingested=dm.ingested + jnp.sum(valid.astype(jnp.int64)),
                late=dm.late + jnp.sum(late_m))

        hit = jax.jit(upd, donate_argnums=0)
        _KERNEL_CACHE[key] = hit
    return hit


def _dense_kernel(spec, capacity: int, runs: int,
                  pallas_fold: bool = False, pallas_packed: bool = False):
    """Jitted scatter-free in-order ingest (build_ingest_dense), cached.
    The Pallas flags are part of the cache key — a flags-off operator
    can never be handed a Pallas-bearing executable."""
    import jax
    from . import core as ec

    key = ("dense", spec.periods, spec.bands, spec.offset_periods,
           tuple(a.token for a in spec.aggs), capacity, runs,
           bool(pallas_fold), bool(pallas_packed))
    hit = _KERNEL_CACHE.get(key)
    if hit is None:
        hit = jax.jit(ec.build_ingest_dense(
            spec, capacity, runs, pallas_fold=pallas_fold,
            pallas_packed=pallas_packed), donate_argnums=0)
        _KERNEL_CACHE[key] = hit
    return hit


def dense_eligible(spec) -> bool:
    """Static part of the dense-ingest decision: no count/session windows,
    dense-lift aggregations only."""
    return (not spec.count_periods and not spec.session_gaps
            and all(not a.is_sparse for a in spec.aggs))


def min_grid_period(spec) -> int:
    """Smallest distance between consecutive union-grid points — the
    host-side bound for how many slices a time span can touch."""
    g = 0
    import math

    for p in spec.periods:
        g = math.gcd(g, int(p))
    for (p, r) in spec.offset_periods:
        g = math.gcd(g, int(p))
        g = math.gcd(g, int(r))
    for (bs, bsz) in spec.bands:
        g = math.gcd(g, int(bs))
        g = math.gcd(g, int(bsz))
    return max(1, g)


class TpuWindowOperator(WindowOperator):
    """Device-engine WindowOperator (SURVEY.md §7 stage 3-5).

    Same public contract as the reference SlicingWindowOperator
    (slicing/.../SlicingWindowOperator.java:21-69) plus the batched
    ``process_elements`` entry point that actually feeds the accelerator.
    """

    def __init__(self, state_factory: Optional[StateFactory] = None,
                 config: Optional[EngineConfig] = None, obs=None,
                 collect_device_metrics: Optional[bool] = None,
                 shaper=None):
        self.config = config or EngineConfig()
        self.obs = obs                      # scotty_tpu.obs.Observability
        #: stream-shaping front-end (scotty_tpu.shaper, ISSUE 5). Pass a
        #: ShaperConfig (or a prebuilt StreamShaper) to route host-fed
        #: tuples through the coalescing/sorting accumulator; watermarks
        #: drain it first and check_overflow folds its telemetry. None
        #: (default) leaves every pre-shaper path byte-identical.
        self._shaper = None
        self._shaper_feeding = False
        #: line-rate ingest feed (scotty_tpu.ingest.LineRateFeed, ISSUE
        #: 7): attaches itself at construction. Watermark dispatch drains
        #: its staged records first (same contract as the shaper) and
        #: check_overflow folds its ingest_ring_* telemetry.
        self._ingest_feed = None
        #: the in-flight emission-latency chain key (ISSUE 14): one per
        #: watermark, opened at dispatch, completed at the arrays/emit
        #: face and closed by the sink handoff (obs.latency)
        self._lat_open = None
        if shaper is not None:
            from ..shaper import ShaperConfig, StreamShaper

            if isinstance(shaper, ShaperConfig):
                StreamShaper(self, shaper)      # attaches via __init__
            elif isinstance(shaper, StreamShaper):
                shaper.op = self
                self._shaper = shaper
            else:
                raise TypeError(
                    "shaper= expects a scotty_tpu.shaper.ShaperConfig or "
                    f"StreamShaper, got {type(shaper).__name__}")
        #: device_* telemetry mode. None (default) = AUTO: collect only
        #: while an Observability is attached, so a bare operator stays
        #: zero-overhead (no dm_ingest kernel dispatch per device batch,
        #: no numpy running-max mirror per host batch). True forces
        #: collection without obs (device_metrics() consumers); False
        #: disables entirely (the overhead A/B baseline — run_benchmark
        #: propagates its collect_metrics flag here).
        self.collect_device_metrics = collect_device_metrics
        #: SHED policy hook: called as ``shed_callback(vals, ts)`` with the
        #: numpy arrays of every tuple the admission control dropped — the
        #: auditable dead-letter face (the chaos differential suite replays
        #: the surviving complement through the host oracle).
        self.shed_callback = None
        self.windows: List[ContextFreeWindow] = []
        #: per-window active mask (ISSUE 6 serving control path): the
        #: watermark trigger loop skips inactive windows, so
        #: register_window/cancel_window never touch the compiled kernels
        #: — registration order (and with it emission order) is preserved
        self._win_active: List[bool] = []
        self.aggregations: List[AggregateFunction] = []
        self.max_lateness = 1000            # WindowManager.java:24 default
        self.max_fixed_window_size = 0
        self._last_watermark = -1
        self._built = False
        self._state = None
        self._pend_vals: list = []
        self._pend_ts: list = []
        self._n_pending = 0
        # in-jit device telemetry (obs/device.py): the device pytree is
        # allocated lazily on the first device-resident batch; host-fed
        # batches accumulate the same device_* names in numpy (their ts
        # are host-visible — no extra dispatch on the hot path)
        self._dm = None
        self._dm_host_acc: dict = {}
        self._dm_folded = None

    # -- registry ----------------------------------------------------------
    def add_window_assigner(self, window: Window) -> None:
        if self._built:
            self._add_window_dynamic(window)
            return
        if isinstance(window, SessionWindow):
            # sessions run on their own bounded active-session arrays
            # (engine/sessions.py), one per registered window — any mix
            # with time-grid windows, in- or out-of-order streams.
            if window.measure != WindowMeasure.Time:
                raise UnsupportedOnDevice("count-measure sessions: host only")
            self.windows.append(window)
            self._win_active.append(True)
            return
        if isinstance(window, (ForwardContextAware, ForwardContextFree)):
            # user-defined context-aware windows run on the generic
            # active-window-array engine (engine/context.py) when they
            # provide a device face; host-only contexts fall back.
            # The device calculus runs over event TIMESTAMPS, while the
            # host face (and the reference, TupleContext.getTs(measure))
            # runs count-measure contexts over arrival positions — so a
            # non-Time measure must not silently reach the device.
            if window.window_measure != WindowMeasure.Time:
                raise UnsupportedOnDevice(
                    "count-measure context windows: host only (the device "
                    "context calculus runs over event time)")
            if window.device_context_spec() is None:
                raise UnsupportedOnDevice(
                    f"{type(window).__name__} has no device context spec "
                    "(device_context_spec() is None); use "
                    "SlicingWindowOperator or HybridWindowOperator")
            self.windows.append(window)
            self._win_active.append(True)
            return
        if not isinstance(window, (TumblingWindow, SlidingWindow,
                                   FixedBandWindow)):
            raise UnsupportedOnDevice(
                f"{type(window).__name__} has no device path; use "
                "SlicingWindowOperator or HybridWindowOperator")
        if (window.measure == WindowMeasure.Count
                and isinstance(window, FixedBandWindow)):
            raise UnsupportedOnDevice(
                "count-measure fixed-band windows have no device path; use "
                "SlicingWindowOperator")
        self.windows.append(window)
        self._win_active.append(True)
        # the reference mixes count sizes into the (ms) GC delay bound —
        # WindowManager.java:121-127 takes clearDelay() of every
        # context-free window regardless of measure; mirrored for parity.
        self.max_fixed_window_size = max(self.max_fixed_window_size,
                                         window.clear_delay())

    def _add_window_dynamic(self, window: Window) -> None:
        """Register a window mid-stream (TumblingWindowOperatorTest.java:96-145,
        SlidingWindowOperatorTest dynamic cases).

        The slice-buffer arrays are spec-independent, so the existing state
        carries over untouched; only the kernels (which close over the union
        grid) are rebuilt. Pre-addition slices stay on the coarser old grid —
        the query's t_last containment (AggregateWindowState.java:25-31)
        handles windows of the new assigner that straddle them, exactly like
        the reference. Pending host-buffered tuples are flushed through the
        OLD kernels first: the new grid applies from this call on.

        Deliberate deviation: the union grid takes effect IMMEDIATELY at
        this call. The reference caches its next slice edge
        (StreamSlicer.java min_next_edge_ts) and keeps filling the current
        coarse slice until that stale pre-addition edge is crossed — tuples
        arriving in [addition_ts, stale_edge) silently vanish from every
        window of the new assigner that ends before the stale edge. Here
        they are sliced on the new grid at once, so new-assigner windows
        see them; results are identical from the first old-grid edge after
        the addition onward.
        """
        if self._session_windows or getattr(self, "_ctx_windows", None) \
                or isinstance(window, (SessionWindow, ForwardContextAware,
                                       ForwardContextFree)):
            raise UnsupportedOnDevice(
                "dynamic addition with session/context windows needs the "
                "host operator")
        if not isinstance(window, (TumblingWindow, SlidingWindow,
                                   FixedBandWindow)):
            raise UnsupportedOnDevice(
                f"{type(window).__name__} has no device path")
        if window.measure == WindowMeasure.Count:
            raise UnsupportedOnDevice(
                "dynamic count-measure window addition needs the host "
                "operator (count slicing would need a record replay)")
        self._flush()                      # old grid for already-fed tuples
        self.windows.append(window)
        self._win_active.append(True)
        self.max_fixed_window_size = max(self.max_fixed_window_size,
                                         window.clear_delay())
        self._spec = self._grid_spec = self._compute_spec()
        C, A = self.config.capacity, self.config.annex_capacity
        RCap = self.config.records if self._has_count else 0
        (self._ingest, self._query, self._gc, self._count_at,
         self._merge, self._ingest_inorder, self._query_rec,
         self._count_at_rec, self._ingest_cut,
         self._ingest_rows) = _kernels(self._grid_spec, C, A, RCap)
        # the dense fast path closes over the union grid too
        self._dense_runs = self.config.dense_ingest_runs \
            if dense_eligible(self._grid_spec) else 0
        self._min_grid = min_grid_period(self._grid_spec)
        self._ingest_dense = None

    def _serving_compatible(self, window: Window) -> bool:
        """Whether ``window`` can register against the BUILT kernels with
        no rebuild: a Time-measure tumbling/sliding window whose edges all
        land on slice cuts the existing union grid already makes —
        tumbling: size a multiple of some registered period; sliding:
        slide a multiple, and size a multiple of slide (or the residue
        grid already in the spec). Anything else goes through the
        `_add_window_dynamic` rebuild path."""
        if self._session_windows or getattr(self, "_ctx_windows", None):
            return False
        if not isinstance(window, (TumblingWindow, SlidingWindow)) \
                or window.measure != WindowMeasure.Time:
            return False
        periods = self._grid_spec.periods
        if not periods:
            return False
        if isinstance(window, SlidingWindow):
            sl, sz = int(window.slide), int(window.size)
            if not any(sl % p == 0 for p in periods):
                return False
            if sz % sl == 0:
                return True
            return (sl, sz % sl) in self._grid_spec.offset_periods
        return any(int(window.size) % p == 0 for p in periods)

    def register_window(self, window: Window, tenant: str = "default") -> int:
        """Serving control path (ISSUE 6): register a window mid-stream and
        return an opaque handle for :meth:`cancel_window` (handles are
        never reused — stale cancels raise instead of touching a
        recycled slot).

        When the window is :meth:`_serving_compatible` with the built
        union grid, registration is PURE HOST BOOKKEEPING — the compiled
        kernels are untouched and the next watermark simply enumerates
        the new window's triggers (zero retrace; the query kernel's
        trigger-pad bucket keeps it warm), reusing a cancelled
        registration's window slot when one is free. Incompatible windows
        fall back to the `_add_window_dynamic` kernel rebuild, counted as
        a ``serving_retraces``. Like the dynamic-addition path, data GC'd
        before registration is gone: the new window answers from the
        slices still retained.
        """
        if not hasattr(self, "_serving_handles"):
            self._serving_handles: dict = {}
            self._serving_next = 0
            self._win_free: list = []
        retrace = False
        if not self._built:
            self.add_window_assigner(window)
            idx = len(self.windows) - 1
        elif self._serving_compatible(window):
            self._flush()             # pending tuples precede registration
            if self._win_free:
                # recycle a cancelled registration's window slot so
                # sustained churn bounds the list (and the per-watermark
                # trigger scan) at PEAK concurrency, not total history
                idx = self._win_free.pop()
                self.windows[idx] = window
                self._win_active[idx] = True
            else:
                self.windows.append(window)
                self._win_active.append(True)
                idx = len(self.windows) - 1
            self.max_fixed_window_size = max(self.max_fixed_window_size,
                                             window.clear_delay())
        else:
            self._add_window_dynamic(window)      # kernel rebuild
            idx = len(self.windows) - 1
            retrace = True
        h = self._serving_next
        self._serving_next += 1
        self._serving_handles[h] = (idx, tenant)
        if self.obs is not None:
            self.obs.counter(_obs.SERVING_REGISTERED).inc()
            if retrace:
                self.obs.counter(_obs.SERVING_RETRACES).inc()
            self.obs.flight_event(_flight.QUERY_REGISTER,
                                  f"{tenant}:{window}", float(h))
        return h

    def cancel_window(self, handle: int, tenant: str = "default") -> None:
        """Deactivate a registered window: its triggers stop being
        enumerated from the next watermark on (a host mask write — the
        kernels, the slice state and every other window are untouched)
        and its window slot joins the recycle list. Handles are opaque
        and never reused (a stale handle raises; only
        :meth:`register_window` registrations cancel — build-time windows
        are the static contract). Session/context windows have no cancel
        path (their sweeps carry per-window device state)."""
        entry = getattr(self, "_serving_handles", {}).pop(handle, None)
        if entry is None:
            raise ValueError(
                f"unknown or already-cancelled window handle {handle}")
        idx, reg_tenant = entry
        w = self.windows[idx]
        if isinstance(w, (SessionWindow, ForwardContextAware,
                          ForwardContextFree)):
            self._serving_handles[handle] = entry     # nothing changed
            raise UnsupportedOnDevice(
                "session/context windows cannot be cancelled (their sweep "
                "state is per-registration); only grid windows support "
                "the serving control path")
        self._win_active[idx] = False
        self._win_free.append(idx)
        if self.obs is not None:
            self.obs.counter(_obs.SERVING_CANCELLED).inc()
            self.obs.flight_event(_flight.QUERY_CANCEL,
                                  f"{reg_tenant}:{w}", float(handle))

    def add_aggregation(self, window_function: AggregateFunction) -> None:
        if self._built:
            raise RuntimeError("add aggregations before first element")
        if window_function.device_spec() is None:
            raise UnsupportedOnDevice(
                f"{type(window_function).__name__} has no device realization "
                "(device_spec() is None); use SlicingWindowOperator")
        self.aggregations.append(window_function)

    def set_max_lateness(self, max_lateness: int) -> None:
        self.max_lateness = max_lateness

    def set_observability(self, obs) -> None:
        """Attach an :class:`scotty_tpu.obs.Observability` (None detaches).
        All hooks are host-side at batch/watermark boundaries — the jitted
        kernels are untouched: ``ingest_tuples``/``ingest_batch_size`` on
        ingest, ``late_tuples`` when a batch reaches below the stream's
        max event time, ``watermarks``/``watermark_lag_ms``/
        ``watermark_dispatch_ms`` per watermark, ``overflows`` on overflow,
        ``slice_occupancy``/``slice_headroom`` at the
        :meth:`check_overflow` sync point — where the in-jit ``device_*``
        telemetry (obs/device.py) also folds in. Attaching mid-run
        baselines the device counters so pre-attach (warmup) batches
        don't pollute the fold."""
        self.obs = obs
        if obs is not None and (self._dm is not None or self._dm_host_acc):
            self._dm_folded = self.device_metrics()

    # -- build -------------------------------------------------------------
    def _compute_spec(self):
        from . import core as ec

        periods = []
        bands = []
        count_periods = []
        session_gaps = []
        offset_periods = []
        for w in self.windows:
            if isinstance(w, SessionWindow):
                session_gaps.append(int(w.gap))
            elif isinstance(w, (ForwardContextAware, ForwardContextFree)):
                pass        # generic context windows own their arrays
            elif w.measure == WindowMeasure.Count:
                count_periods.append(int(w.slide)
                                     if isinstance(w, SlidingWindow)
                                     else int(w.size))
            elif isinstance(w, TumblingWindow):
                periods.append(int(w.size))
            elif isinstance(w, SlidingWindow):
                periods.append(int(w.slide))
                if w.size % w.slide:
                    # window ends off the slide grid: add their residue grid
                    # so range queries stay exact (EngineSpec.offset_periods)
                    offset_periods.append((int(w.slide),
                                           int(w.size % w.slide)))
            elif isinstance(w, FixedBandWindow):
                bands.append((int(w.start), int(w.size)))
        return ec.EngineSpec(
            periods=ec.collapse_periods(periods),
            bands=tuple(sorted(set(bands))),
            count_periods=tuple(sorted(set(count_periods))),
            aggs=tuple(a.device_spec() for a in self.aggregations),
            session_gaps=tuple(session_gaps),
            offset_periods=tuple(sorted(set(offset_periods))),
        )

    def _build(self) -> None:
        from . import core as ec
        from . import sessions as es

        if not self.windows:
            raise RuntimeError("no windows registered")
        if not self.aggregations:
            raise RuntimeError("no aggregations registered")
        self._spec = self._compute_spec()
        if any(a.cells_per_tuple > 1 for a in self._spec.aggs) and (
                self._spec.session_gaps or self._spec.count_periods
                or any(isinstance(w, (ForwardContextAware,
                                      ForwardContextFree))
                       for w in self.windows)):
            # sessions/context chains/the count record ring densify per-lane
            # one-hots ([B, width]), which assumes one cell per tuple; the
            # scatter-combine time-grid paths broadcast over the extra cells
            raise UnsupportedOnDevice(
                "multi-cell sparse aggregations (count-min) ride the "
                "time-grid paths only; use SlicingWindowOperator for "
                "session/count/context workloads")
        C, A = self.config.capacity, self.config.annex_capacity
        # Session windows run on their own per-registration active-session
        # arrays (engine/sessions.py); the grid slice buffer serves only
        # context-free windows. Stripping the gaps from the grid spec keeps
        # kernel-cache keys and the dense fast path independent of sessions.
        self._session_windows = [w for w in self.windows
                                 if isinstance(w, SessionWindow)]
        self._ctx_windows = [
            w for w in self.windows
            if isinstance(w, (ForwardContextAware, ForwardContextFree))
            and not isinstance(w, SessionWindow)]
        import dataclasses

        self._grid_spec = dataclasses.replace(self._spec, session_gaps=())
        self._has_grid = (self._grid_spec.has_time_grid
                          or bool(self._grid_spec.count_periods))
        self._pure_session = bool(self._session_windows
                                  or self._ctx_windows) \
            and not self._has_grid
        self._has_count = bool(self._grid_spec.count_periods)
        self._rec = None
        if self._has_grid:
            RCap = self.config.records if self._has_count else 0
            self._state = ec.init_state(self._grid_spec, C, A)
            (self._ingest, self._query, self._gc, self._count_at,
             self._merge, self._ingest_inorder, self._query_rec,
             self._count_at_rec, self._ingest_cut,
             self._ingest_rows) = _kernels(self._grid_spec, C, A, RCap)
            if self._has_count:
                # count windows aggregate ts-sorted rank ranges — retain
                # records (the reference's lazy-slice retention)
                self._rec = ec.init_records(RCap)
                (self._rec_merge, self._rec_gc,
                 self._rec_append) = _record_kernels(RCap, C)
        else:
            self._state = None
        if self._session_windows:
            self._emit_cap = self.config.trigger_pad(1024)
            # the late scan is SEQUENTIAL (one device step per late tuple) —
            # cap its static length well below bench batch sizes; rarer
            # larger late sets chunk through it (_feed_sessions)
            self._late_len = min(self.config.batch_size, 256)
            trips = [_session_kernels(self._spec.aggs, int(w.gap), C,
                                      self._late_len, self._emit_cap)
                     for w in self._session_windows]
            self._session_ingests = tuple(t[0] for t in trips)
            self._session_lates = tuple(t[1] for t in trips)
            self._session_sweeps = tuple(t[2] for t in trips)
            # orphan capacity rides annex_capacity: both hold the rare
            # out-of-contract-ish residue between watermarks
            self._session_states = [
                es.init_session_state(
                    self._spec.aggs, C,
                    orphan_capacity=max(64, A))
                for _ in self._session_windows]
            self._session_dense = [None] * len(self._session_windows)
        else:
            self._session_states = []
        if self._ctx_windows:
            from . import context as ectx

            if not self._session_windows:
                self._emit_cap = self.config.trigger_pad(1024)
            specs = [w.device_context_spec() for w in self._ctx_windows]
            pairs = [_context_kernels(self._spec.aggs, sp, C, self._emit_cap)
                     for sp in specs]
            self._ctx_applies = tuple(p[0] for p in pairs)
            self._ctx_sweeps = tuple(p[1] for p in pairs)
            self._ctx_specs = tuple(specs)
            self._ctx_chain = tuple(
                sp.inorder_chain_params() is not None for sp in specs)
            # speculative chunked batching (ISSUE 11): specs certifying
            # SpeculationCert get a host planner that sorts OOO chunks,
            # proves per interaction component that the vectorized chain
            # kernel reproduces the arrival-order scan, and falls back
            # to the scan only for the components it cannot prove
            self._ctx_planners = tuple(
                ectx.SpeculativePlanner(sp)
                if (sp.inorder_chain_params() is not None
                    and sp.speculation_params() is not None) else None
                for sp in specs)
            self._ctx_spec_stats = {"speculative_tuples": 0,
                                    "fallback_tuples": 0,
                                    "fallback_runs": 0}
            # clear_delay participates in the GC bound (mirroring
            # Window.clear_delay / WindowManager.java:121-127): retention
            # beyond what orphan_reach already grants is applied as a
            # per-window slack on the sweep's gc_bound, so a user decider
            # declaring a long clear_delay actually keeps its orphans.
            self._ctx_gc_slack = tuple(
                max(0, int(sp.clear_delay()) - int(sp.orphan_reach()))
                for sp in specs)
            self._ctx_states = [
                es.init_session_state(self._spec.aggs, C,
                                      orphan_capacity=max(64, A))
                for _ in specs]
        else:
            self._ctx_states = []
            self._ctx_planners = ()
            self._ctx_spec_stats = {}
        # per-watermark emission order among context windows follows their
        # REGISTRATION order (the simulator iterates contexts in that
        # order, WindowManager.java:98-118)
        self._ctx_order = []
        si = gi = 0
        for w in self.windows:
            if isinstance(w, SessionWindow):
                self._ctx_order.append(("s", si))
                si += 1
            elif isinstance(w, (ForwardContextAware, ForwardContextFree)):
                self._ctx_order.append(("g", gi))
                gi += 1
        self._dense_runs = self.config.dense_ingest_runs \
            if (self._has_grid and dense_eligible(self._grid_spec)) else 0
        self._min_grid = min_grid_period(self._grid_spec)
        self._ingest_dense = None       # built lazily on first eligible batch
        self._last_count = 0
        self._host_met = None           # host mirror of max event time
        self._host_min_ts = None        # host mirror of min event time
        self._host_first_ts = None      # ts of the FIRST ARRIVAL ever
        self._host_count = 0            # host mirror of current_count
        self._annex_dirty = False       # a late tuple may sit in the annex
        self._count_late_seen = False   # sticky: rec query/probe from then on
        self._valid_dev = None          # cached all-true lane mask
        self._host_open = None          # mirror of the open slice's start
        self._device_fed = False        # device batches bypass the mirror
        # overflow-policy admission mirrors (resilience.policy): host-side
        # UPPER BOUNDS on live slices / pending annex rows, grown per
        # admitted batch and re-synced exactly (one device round trip)
        # only when a batch's projected need approaches capacity. Under
        # the default FAIL policy none of this runs.
        if self.config.overflow_policy != "fail" and (
                not self._has_grid or self._has_count or self._ctx_windows):
            raise UnsupportedOnDevice(
                f"overflow_policy={self.config.overflow_policy!r} covers "
                "time-grid (optionally session-mixed) workloads; count/"
                "context/pure-session workloads run policy 'fail' — the "
                "host admission mirror has no exact occupancy bound for "
                "their buffers")
        self._pol_slices_ub = 0
        self._pol_annex_ub = 0
        self._pol_seen_start = None
        self._built = True

    # -- device telemetry --------------------------------------------------
    @property
    def _dm_active(self) -> bool:
        """Whether the device_* telemetry collects right now (see the
        collect_device_metrics mode doc in __init__)."""
        if self.collect_device_metrics is None:
            return self.obs is not None
        return bool(self.collect_device_metrics)

    def _dm_host_add(self, name: str, delta: int) -> None:
        if delta:
            self._dm_host_acc[name] = self._dm_host_acc.get(name, 0) + delta

    def device_metrics(self) -> dict:
        """Merged in-jit + host-mirrored telemetry as a ``device_*`` name
        → int dict (syncs the device pytree if one exists)."""
        from ..obs import device as _dev

        snap = dict(self._dm_host_acc)
        if self._dm is not None:
            import jax

            for name, v in _dev.host_snapshot(
                    jax.device_get(self._dm)).items():
                snap[name] = snap.get(name, 0) + v
        return snap

    def _dm_device_update(self, ts, valid) -> None:
        """Fold one device-resident batch into the in-jit pytree (its ts
        are host-opaque; the jitted cummax kernel is the only exact
        source of late counts/ages). Zero host syncs; no-op when device
        telemetry is disabled."""
        from . import core as ec
        from ..obs import device as _dev

        if not self._dm_active:
            return
        if self._dm is None:
            self._dm = _dev.init_device_metrics()
        met = np.int64(self._host_met) if self._host_met is not None \
            else np.int64(ec.I64_MIN)
        self._dm = _dm_ingest_kernel()(self._dm, ts, valid, met)

    # -- ingest ------------------------------------------------------------
    def process_element(self, element: Any, ts: int) -> None:
        self.process_elements(np.asarray([element], dtype=np.float32),
                              np.asarray([ts], dtype=np.int64))

    @property
    def shaper(self):
        """The attached :class:`scotty_tpu.shaper.StreamShaper` (None
        when the operator runs bare)."""
        return self._shaper

    def process_elements(self, elements: Sequence, timestamps: Sequence) -> None:
        if not self._built:
            self._build()
        lat = self.obs.latency if self.obs is not None else None
        if lat is not None:
            # emission-latency lineage (ISSUE 14): record-arrival at
            # the operator boundary — unless this call IS the shaper's
            # flush re-entering (then the arrival already stamped when
            # the records first offered, and THIS moment is the
            # shaper_flush stage)
            lat.pre(_lat.STAGE_SHAPER_FLUSH if self._shaper_feeding
                    else _lat.STAGE_ARRIVAL)
        if self._shaper is not None and not self._shaper_feeding:
            # shaped ingest: the accumulator coalesces/sorts and calls
            # back into this method (reentrancy flag set) per full block
            self._shaper.offer_many(
                np.asarray(elements, dtype=np.float32).reshape(-1),
                np.asarray(timestamps, dtype=np.int64).reshape(-1))
            return
        vals = np.asarray(elements, dtype=np.float32).reshape(-1)
        tss = np.asarray(timestamps, dtype=np.int64).reshape(-1)
        if vals.shape != tss.shape:
            raise ValueError("elements/timestamps length mismatch")
        if self.obs is not None:
            self.obs.counter(_obs.INGEST_TUPLES).inc(vals.shape[0])
            self.obs.histogram(_obs.INGEST_BATCH_SIZE).observe(vals.shape[0])
        self._pend_vals.append(vals)
        self._pend_ts.append(tss)
        self._n_pending += vals.shape[0]
        B = self.config.batch_size
        while self._n_pending >= B:
            self._launch_batch(B)

    def _launch_batch(self, take: int) -> None:
        """Pop `take` tuples from the pending queue, pad to batch_size,
        ts-sort (late tuples must be grouped for the annex path), launch."""
        if self.obs is not None and self.obs.latency is not None:
            # device-work-begins pre-stamp for the next watermark's
            # emission chain (first launch since the last claim wins)
            self.obs.latency.pre(_lat.STAGE_DISPATCH)
        B = self.config.batch_size
        if len(self._pend_vals) == 1:
            vals_cat, ts_cat = self._pend_vals[0], self._pend_ts[0]
        else:
            vals_cat = np.concatenate(self._pend_vals)
            ts_cat = np.concatenate(self._pend_ts)
        batch_v, rest_v = vals_cat[:take], vals_cat[take:]
        batch_t, rest_t = ts_cat[:take], ts_cat[take:]
        self._pend_vals = [rest_v] if rest_v.size else []
        self._pend_ts = [rest_t] if rest_t.size else []
        self._n_pending -= take

        met_pre = self._host_met            # max event time BEFORE this batch
        if take and self.config.overflow_policy != "fail":
            # SHED/GROW admission control (resilience.policy) — before any
            # telemetry, so counters reflect what was actually ingested
            batch_v, batch_t, take = self._policy_admit(batch_v, batch_t,
                                                        take, met_pre)
            if take == 0:
                return
        if self.obs is not None and take and met_pre is not None:
            # late = below the stream's max event time at batch start
            # (host-side count; the device late/annex path handles them)
            n_below = int((batch_t[:take] < met_pre).sum())
            if n_below:
                self.obs.counter(_obs.LATE_TUPLES).inc(n_below)
        if take and self._dm_active:
            # device_* telemetry, host mirror (these ts are host-visible
            # pre-sort, so the exact arrival-order running-max calculus
            # costs one numpy accumulate — no extra device dispatch):
            # a tuple is late iff strictly below the running max at ITS
            # arrival; its age is the running max minus its ts
            from ..obs import device as _dev

            arr = batch_t[:take]
            seed = np.int64(met_pre) if met_pre is not None \
                else np.iinfo(np.int64).min
            rm = np.maximum.accumulate(np.concatenate(([seed], arr[:-1])))
            late_m = arr < rm
            n_late_exact = int(late_m.sum())
            self._dm_host_add(_dev.DEVICE_INGEST_TUPLES, take)
            self._dm_host_add(_dev.DEVICE_LATE_TUPLES, n_late_exact)
            if n_late_exact:
                hist = _dev.host_late_age_hist(rm[late_m] - arr[late_m])
                for name, v in zip(_dev.late_bucket_names(),
                                   hist.tolist()):
                    self._dm_host_add(name, int(v))
        if take and self._host_first_ts is None:
            self._host_first_ts = int(batch_t[0])   # arrival order, pre-sort
        intra_ooo = take > 1 and not bool(
            (batch_t[:take - 1] <= batch_t[1:take]).all())
        mixed = self._has_count and self._grid_spec.has_time_grid
        mixed_late = mixed and take and (
            intra_ooo or (met_pre is not None
                          and int(batch_t[:take].min()) < met_pre))
        if mixed_late and self._device_fed:
            # device-resident batches bypassed the host cut mirror, so the
            # arrival-order slice assignment can no longer be reconstructed
            raise UnsupportedOnDevice(
                "out-of-order count+time mixes after device-resident "
                "batches need the host operator (host cut mirror is stale)")
        if self._session_states and take:
            # sessions consume the batch in ARRIVAL order — the reference's
            # session calculus is arrival-order-dependent at exact-gap
            # boundaries (engine/sessions.py module docstring)
            self._feed_sessions(batch_v[:take], batch_t[:take], met_pre)
        if self._ctx_states and take:
            # generic context windows replay the batch in arrival order:
            # sorted in-order batches take the vectorized chunk kernel
            # when the spec certifies the greedy chain
            # (DeviceContextSpec.inorder_chain_params); everything else
            # goes through the per-tuple scan (engine/context.py)
            bt = batch_t[:take]
            inorder = bool((bt[:-1] <= bt[1:]).all()) \
                and (met_pre is None or int(bt[0]) >= met_pre)
            self._feed_contexts(batch_v[:take], bt, inorder=inorder)

        if not self._has_grid:
            # pure-session/context workloads: no slice buffer to feed,
            # so skip the grid path's full ts-sort (it was ~15% of a
            # speculative context batch) and update the host clock
            # mirrors straight from the arrival arrays
            if take:
                mx = int(batch_t[:take].max())
                mn = int(batch_t[:take].min())
                self._host_met = mx if self._host_met is None \
                    else max(self._host_met, mx)
                self._host_min_ts = mn if self._host_min_ts is None \
                    else min(self._host_min_ts, mn)
                self._host_count += take
            return

        if mixed and take:
            # arrival-order cut calculus: maintains the open-slice mirror on
            # EVERY batch; for late-containing batches it also yields the
            # per-lane slice assignment the row-scatter kernel consumes
            row_off, is_cut, cut_val, cut_c = self._mixed_cut_calculus(
                batch_t[:take], met_pre)
        if mixed_late:
            # Out-of-order count+time mix — device path (VERDICT r3 item 1).
            # The ripple (SliceManager.java:64-86) re-aligns slice content
            # to ts-sorted rank ranges; on device that is: merge the batch
            # into the record buffer by ts rank, add +1 to the row open at
            # each tuple's ARRIVAL, materialize the arrival's cuts. All
            # window values then come from record rank ranges (mix_rec
            # query) — sticky from the first late tuple.
            self._count_late_seen = True
            order = np.argsort(batch_t[:take], kind="stable")
            sort_t = np.full((B,), batch_t[:take][order[-1]], np.int64)
            sort_v = np.zeros((B,), np.float32)
            sort_t[:take] = batch_t[:take][order]
            sort_v[:take] = batch_v[:take][order]
            valid = np.zeros((B,), bool)
            valid[:take] = True
            self._rec = self._rec_merge(self._rec, sort_t, sort_v, valid)

            arr_t = np.full((B,), batch_t[take - 1], np.int64)
            arr_t[:take] = batch_t[:take]
            ro_p = np.zeros((B,), np.int32)
            ro_p[:take] = row_off
            cut_p = np.zeros((B,), bool)
            cut_p[:take] = is_cut
            cs_p = np.zeros((B,), np.int64)
            cs_p[:take] = cut_val
            cc_p = np.zeros((B,), np.int64)
            cc_p[:take] = cut_c
            self._state = self._ingest_rows(self._state, arr_t, valid,
                                            ro_p, cut_p, cs_p, cc_p)
            mx = int(batch_t[:take].max())
            mn = int(batch_t[:take].min())
            self._host_met = mx if met_pre is None else max(met_pre, mx)
            self._host_min_ts = mn if self._host_min_ts is None \
                else min(self._host_min_ts, mn)
            self._host_count += take
            return

        cut_starts = None
        if self._has_count and not self._grid_spec.has_time_grid and take:
            # count-cut slice starts = ARRIVAL-order running max event time
            # (the reference appends at maxEventTime) — computed before the
            # ts-sort erases arrival order; lane j of the sorted batch cuts
            # at count offset j, which is arrival j
            seed = np.int64(met_pre) if met_pre is not None \
                else np.iinfo(np.int64).min
            cs = np.maximum.accumulate(
                np.concatenate(([seed], batch_t[:take - 1])))
            cut_starts = np.full((B,), cs[-1], np.int64)
            cut_starts[:take] = cs

        if take and not bool((batch_t[:-1] <= batch_t[1:]).all()):
            order = np.argsort(batch_t, kind="stable")
            batch_v, batch_t = batch_v[order], batch_t[order]
        has_late = (take > 0 and met_pre is not None
                    and int(batch_t[0]) < met_pre)
        if take:
            mx = int(batch_t[take - 1]) if take < B else int(batch_t[-1])
            self._host_met = mx if self._host_met is None \
                else max(self._host_met, mx)
            mn = int(batch_t[0])
            self._host_min_ts = mn if self._host_min_ts is None \
                else min(self._host_min_ts, mn)
            self._host_count += take
        if not self._has_grid:
            return
        if has_late and not self._has_count:
            # late tuples may open annex slices → merge before next query.
            # (Count-only OOO never touches the annex, and the merge's
            # coincident-start combining would corrupt count slices, whose
            # starts legitimately repeat.)
            self._annex_dirty = True
        valid = np.ones((B,), dtype=bool)
        if take < B:
            pad_t = batch_t[-1] if take else 0
            batch_t = np.concatenate(
                [batch_t, np.full((B - take,), pad_t, np.int64)])
            batch_v = np.concatenate(
                [batch_v, np.zeros((B - take,), np.float32)])
            valid[take:] = False
        if self._has_count:
            # in-order batches append (O(B)); late-containing batches pay
            # the rank merge (O(RC) scatters) — see build_record_append
            rec_kern = self._rec_merge if has_late else self._rec_append
            self._rec = rec_kern(self._rec, batch_t, batch_v, valid)
            if cut_starts is not None:
                # count-only workloads (in- or out-of-order): the ts-sorted
                # batch through the in-order kernel IS the ripple's count
                # bookkeeping — every non-cutting lane folds into the open
                # slice (closed slices keep their fixed count ranges) and
                # count edges still cut, at arrival-order start positions.
                # OOO values come from the record buffer at query time.
                if has_late:
                    self._count_late_seen = True
                self._state = self._ingest_cut(self._state, batch_t,
                                               batch_v, valid, cut_starts)
                return
        if has_late:
            # Split the sorted batch at the lateness boundary: the late
            # prefix is usually a small fraction, but the combined general
            # kernel pays its full-lane scatter sets (in-order + late +
            # annex) for EVERY lane. Ingest the in-order tail through the
            # cheap kernels and only the late prefix through the general
            # kernel on a B/8 sub-batch — same semantics (the combined
            # kernel also folds late tuples against the already-updated
            # slice buffer). Falls back to one combined dispatch when the
            # late prefix exceeds the sub-batch.
            n_late = int(np.searchsorted(batch_t[:take], met_pre))
            late_cap = max(64, B // 8)
            if 0 < n_late <= late_cap and n_late < take:
                io_t = np.empty_like(batch_t)
                io_v = np.empty_like(batch_v)
                n_io = take - n_late
                io_t[:n_io] = batch_t[n_late:take]
                io_v[:n_io] = batch_v[n_late:take]
                io_t[n_io:] = io_t[n_io - 1]
                io_v[n_io:] = 0
                io_valid = np.zeros((B,), bool)
                io_valid[:n_io] = True
                kern = self._pick_inorder_kernel(int(io_t[0]),
                                                 int(io_t[n_io - 1]))
                self._state = kern(self._state, io_t, io_v, io_valid)

                lt = np.empty((late_cap,), np.int64)
                lv = np.zeros((late_cap,), np.float32)
                lt[:n_late] = batch_t[:n_late]
                lv[:n_late] = batch_v[:n_late]
                lt[n_late:] = lt[n_late - 1]
                l_valid = np.zeros((late_cap,), bool)
                l_valid[:n_late] = True
                self._state = self._ingest(self._state, lt, lv, l_valid)
                return
            self._state = self._ingest(self._state, batch_t, batch_v, valid)
            return
        kern = self._pick_inorder_kernel(
            int(batch_t[0]) if take else 0,
            int(batch_t[take - 1]) if take else 0)
        self._state = kern(self._state, batch_t, batch_v, valid)

    def _mixed_cut_calculus(self, ts: np.ndarray, met_pre):
        """Arrival-order slice-cut calculus for count+time mixed workloads
        — the host mirror of StreamSlicer.determineSlices over one batch.

        Count edges cut for EVERY tuple at the running max event time
        (StreamSlicer.java:37-44); time edges cut only for in-order tuples
        whose union-grid start exceeds the open slice's start (the engine's
        segment rule — empty grid ranges are not materialized). A lane with
        both cuts materializes one row at the later start (the intermediate
        slice would be empty). Returns per-lane ``(row_off, is_cut, start,
        cut_c)`` where ``row_off`` is the inclusive cut count (the lane's
        row is ``n_slices - 1 + row_off``) and ``cut_c`` the cutting lane's
        pre-insert global count (the new slice's fixed count start,
        SliceManager.appendSlice cStart). Also advances the persistent
        open-slice-start mirror, so it must run on every host batch of a
        mixed workload, in-order ones included.
        """
        from . import core as ec

        spec = self._grid_spec
        ts = np.asarray(ts, dtype=np.int64)
        take = ts.shape[0]
        imin = np.int64(ec.I64_MIN)
        seed = np.int64(met_pre) if met_pre is not None else imin
        # running max event time BEFORE each lane (maxEventTime is updated
        # after the tuple is processed, StreamSlicer.java:85)
        rm = np.maximum.accumulate(np.concatenate(([seed], ts[:-1])))
        inorder = ts >= rm
        c_idx = self._host_count + np.arange(take, dtype=np.int64)
        count_cut = (c_idx > 0) & (ec.host_count_grid(spec, c_idx)
                                   > ec.host_count_grid(spec, c_idx - 1))
        gs = ec.host_grid_start(spec, ts)
        open_pre = np.int64(self._host_open) \
            if self._host_open is not None else imin
        # open-start evolution = running max of fired cut values; including
        # non-firing candidates is harmless (a candidate <= the current
        # open start contributes nothing to the max)
        cand = np.where(count_cut, rm, imin)
        cand = np.maximum(cand, np.where(inorder, gs, imin))
        run = np.maximum(open_pre, np.maximum.accumulate(cand))
        open_before = np.concatenate(([open_pre], run[:-1]))
        time_cut = inorder & (gs > open_before)
        cut = count_cut | time_cut
        start = np.maximum(np.where(count_cut, rm, imin),
                           np.where(time_cut, gs, imin))
        self._host_open = int(run[-1]) if take else int(open_pre)
        row_off = np.cumsum(cut).astype(np.int32)
        return row_off, cut, start, c_idx

    def _feed_sessions(self, vals: np.ndarray, tss: np.ndarray,
                       met_pre) -> None:
        """Update every registered session window's active-session array
        with this batch, in arrival order.

        In-order tuples (at/above the running max event time) go through the
        vectorized chain kernel first; late tuples follow one at a time
        through the sequential scan kernel — processing all in-order tuples
        before the interleaved late ones provably cannot change any outcome
        (sessions.py module docstring), and within each class arrival order
        is preserved.
        """
        B = self.config.batch_size
        seed = np.int64(met_pre) if met_pre is not None \
            else np.iinfo(np.int64).min
        prev_rm = np.maximum.accumulate(
            np.concatenate((np.asarray([seed]), tss[:-1])))
        late_m = tss < prev_rm
        io_t, io_v = tss[~late_m], vals[~late_m]
        n_io = io_t.size
        if n_io:
            for lo in range(0, n_io, B):
                chunk_t, chunk_v = io_t[lo:lo + B], io_v[lo:lo + B]
                k = chunk_t.size
                pt = np.full((B,), chunk_t[-1], np.int64)
                pv = np.zeros((B,), np.float32)
                pt[:k], pv[:k] = chunk_t, chunk_v
                m = np.zeros((B,), bool)
                m[:k] = True
                gaps_t = np.diff(chunk_t) if k > 1 else \
                    np.empty(0, np.int64)
                for i, kern in enumerate(self._session_ingests):
                    # scatter-free run-bounded kernel when the chunk opens
                    # few sessions (the common bench shape: long sessions,
                    # huge batches) — same gate as the grid dense path
                    R = self.config.dense_ingest_runs
                    if R:
                        gap = int(self._session_windows[i].gap)
                        n_new = int((gaps_t > gap).sum()) + 2
                        if n_new <= R:
                            if self._session_dense[i] is None:
                                self._session_dense[i] = \
                                    _session_dense_kernel(
                                        self._spec.aggs, gap,
                                        self.config.capacity, R)
                            kern = self._session_dense[i]
                    self._session_states[i] = kern(
                        self._session_states[i], pt, pv, m)
        n_late = int(late_m.sum())
        if n_late:
            lt_all, lv_all = tss[late_m], vals[late_m]
            L = self._late_len
            for lo in range(0, n_late, L):
                chunk_t, chunk_v = lt_all[lo:lo + L], lv_all[lo:lo + L]
                k = chunk_t.size
                pt = np.full((L,), chunk_t[-1], np.int64)
                pv = np.zeros((L,), np.float32)
                pt[:k], pv[:k] = chunk_t, chunk_v
                m = np.zeros((L,), bool)
                m[:k] = True
                for i, kern in enumerate(self._session_lates):
                    self._session_states[i] = kern(
                        self._session_states[i], pt, pv, m)

    def _ctx_dispatch(self, i: int, cv: np.ndarray, ct: np.ndarray,
                      chunk: bool) -> None:
        """One padded device dispatch for context window ``i``: the
        vectorized chain kernel (``chunk=True``, sorted input) or the
        per-tuple scan (arrival-order input). Pads to a small
        power-of-two bucket, NOT the full batch size — the scan is
        sequential per lane, so a trickle flush at batch_size-length
        would pay thousands of wasted device steps (the kernels retrace
        per padded length; bucketing bounds the variants)."""
        B = self.config.batch_size
        k = ct.size
        if k == 0:
            return
        L = B if k == B else min(B, 1 << max(6, (k - 1).bit_length()))
        pt = np.full((L,), ct[-1], np.int64)
        pv = np.zeros((L,), np.float32)
        pt[:k], pv[:k] = ct, cv
        m = np.zeros((L,), bool)
        m[:k] = True
        if chunk:
            kern = _context_chunk_kernel(
                self._spec.aggs, self._ctx_specs[i],
                self.config.capacity, L)
        else:
            kern = self._ctx_applies[i]
        self._ctx_states[i] = kern(self._ctx_states[i], pt, pv, m)

    def _feed_contexts(self, vals: np.ndarray, tss: np.ndarray,
                       inorder: bool = False) -> None:
        """Apply this batch to every generic context window's active
        arrays, preserving arrival-order semantics.

        Per window: sorted in-order chunks take the vectorized chain
        kernel when the spec certifies it (inorder_chain_params — O(B)
        total work). OUT-OF-ORDER chunks of specs additionally
        certifying ``speculation_params`` go through the speculative
        planner (ISSUE 11): the chunk is sorted, segmented where
        ``decide`` provably cannot interact across the cut, safe
        segment runs execute as single chain-kernel dispatches, and
        only the segments the safety proof rejects replay through the
        per-tuple scan (in exact arrival order) — counted in the gated
        ``ctx_speculative_*`` telemetry. Everything else stays on the
        sequential scan."""
        from ..obs import (CTX_SPECULATIVE_FALLBACK_TUPLES,
                           CTX_SPECULATIVE_FALLBACKS,
                           CTX_SPECULATIVE_TUPLES)

        B = self.config.batch_size
        for i in range(len(self._ctx_states)):
            planner = self._ctx_planners[i]
            for lo in range(0, tss.size, B):
                ct, cv = tss[lo:lo + B], vals[lo:lo + B]
                if inorder and self._ctx_chain[i]:
                    self._ctx_dispatch(i, cv, ct, chunk=True)
                    if planner is not None:
                        planner.note_chunk(ct)
                        self._ctx_spec_stats["speculative_tuples"] += \
                            ct.size
                        if self.obs is not None:
                            self.obs.counter(
                                CTX_SPECULATIVE_TUPLES).inc(ct.size)
                    continue
                if planner is None:
                    self._ctx_dispatch(i, cv, ct, chunk=False)
                    continue
                for kind, idx in planner.plan(ct):
                    if kind == "chunk":
                        self._ctx_dispatch(i, cv[idx], ct[idx],
                                           chunk=True)
                        planner.note_chunk(ct[idx])
                        self._ctx_spec_stats["speculative_tuples"] += \
                            idx.size
                        if self.obs is not None:
                            self.obs.counter(
                                CTX_SPECULATIVE_TUPLES).inc(idx.size)
                    else:
                        self._ctx_dispatch(i, cv[idx], ct[idx],
                                           chunk=False)
                        planner.note_scan(ct[idx])
                        self._ctx_spec_stats["fallback_tuples"] += \
                            idx.size
                        self._ctx_spec_stats["fallback_runs"] += 1
                        if self.obs is not None:
                            self.obs.counter(
                                CTX_SPECULATIVE_FALLBACK_TUPLES).inc(
                                    idx.size)
                            self.obs.counter(
                                CTX_SPECULATIVE_FALLBACKS).inc()

    def _pick_inorder_kernel(self, ts_lo: int, ts_hi: int):
        """Scatter-free dense kernel when the batch's slice-run count is
        provably under the bound; general in-order kernel otherwise."""
        pf = bool(getattr(self.config, "pallas_slice_merge", False))
        if self._dense_runs:
            runs = (ts_hi - ts_lo) // self._min_grid + 3
            if runs <= self._dense_runs:
                if self._ingest_dense is None:
                    self._ingest_dense = _dense_kernel(
                        self._grid_spec, self.config.capacity,
                        self._dense_runs, pallas_fold=pf,
                        pallas_packed=pf and bool(getattr(
                            self.config, "pallas_packed", False)))
                if pf:
                    # picked once per dispatched batch — the host-side
                    # dispatch count of Pallas-bearing programs
                    from .. import pallas as _pl

                    _pl.record_dispatch(self.obs)
                return self._ingest_dense
        if pf:
            # a flagged batch over the runs bound (or dense ingest
            # disabled) degrades to the scatter-heavy general kernel —
            # the same counted-never-silent contract as the shaper's
            # span/shape misses, gated by obs diff
            from .. import pallas as _pl

            _pl.record_fallback(self.obs, "dense_runs_bound")
        return self._ingest_inorder

    # -- overflow policy (resilience.policy) -------------------------------
    #: admission slack: slices the mirror always keeps free so an exact
    #: bound slip (e.g. the annex merge materializing a boundary row) can
    #: never push the device buffers over
    _POL_SLACK = 2

    def _pol_refresh(self) -> None:
        """Re-sync the admission mirrors exactly (one deliberate device
        round trip — only paid when a batch's projected need approaches
        capacity). Pending annex rows count against the slice bound too:
        the watermark merge materializes up to one new slice per row."""
        import jax

        if self._state is None:
            return
        n, na = jax.device_get((self._state.n_slices, self._state.n_annex))
        self._pol_annex_ub = int(na)
        self._pol_slices_ub = int(n) + int(na)

    def _policy_admit(self, vals: np.ndarray, ts: np.ndarray, take: int,
                      met_pre):
        """SHED/GROW admission control at the host ingest boundary.

        The host mirror tracks UPPER BOUNDS on live slices and pending
        annex rows: an in-order batch opens at most one slice per distinct
        union-grid start above the stream head; a late tuple claims at
        most one annex row per distinct grid start (which the watermark
        merge may turn into a slice). When a batch's projected need
        exceeds the remaining headroom the mirror re-syncs exactly, then:

        * ``grow`` — double capacity (checkpoint → rebuild → restore)
          until the batch fits or ``max_capacity`` raises;
        * ``shed`` — drop late tuples first (they can only repair
          already-old windows — the lowest-watermark-impact rows), then
          tuples opening grid slices beyond the remaining headroom,
          admitting starts in ascending order. Drops are exact and
          auditable: ``resilience_shed_tuples`` + ``device_dropped_tuples``
          counters and the ``shed_callback(vals, ts)`` hook — the engine's
          results equal an oracle replay of precisely the survivors.
        """
        from . import core as ec
        from ..obs import device as _dev
        from ..resilience.policy import OverflowPolicy

        cfg = self.config
        vals, ts = vals[:take], ts[:take]
        starts = ec.host_grid_start(self._grid_spec, ts)
        late_m = (ts < met_pre) if met_pre is not None \
            else np.zeros(take, bool)
        seen = self._pol_seen_start
        io_starts = np.unique(starts[~late_m])
        if seen is not None:
            io_starts = io_starts[io_starts > seen]
        late_starts = np.unique(starts[late_m])
        slack = self._POL_SLACK
        cap_s = cfg.capacity - slack
        cap_a = cfg.annex_capacity - slack

        def over():
            return (self._pol_slices_ub + io_starts.size + late_starts.size
                    > cap_s
                    or self._pol_annex_ub + late_starts.size > cap_a)

        if over():
            self._pol_refresh()
        if over() and cfg.overflow_policy == OverflowPolicy.GROW:
            while over():
                self._grow_capacity()       # raises at max_capacity
                cap_s = self.config.capacity - slack
                cap_a = self.config.annex_capacity - slack
        elif over():                        # SHED
            drop = np.zeros(take, bool)
            if late_starts.size:            # late lanes first
                drop |= late_m
                late_starts = late_starts[:0]
            if self._pol_slices_ub + io_starts.size > cap_s:
                allowed = max(0, cap_s - self._pol_slices_ub)
                if allowed < io_starts.size:
                    drop |= (~late_m) & (starts >= io_starts[allowed])
                    io_starts = io_starts[:allowed]
            n_drop = int(drop.sum())
            if n_drop:
                if self.obs is not None:
                    self.obs.counter(_obs.RESILIENCE_SHED_TUPLES).inc(n_drop)
                    self.obs.flight_event(_flight.SHED,
                                          _obs.RESILIENCE_SHED_TUPLES,
                                          n_drop)
                if self._dm_active:
                    self._dm_host_add(_dev.DEVICE_DROPPED_TUPLES, n_drop)
                if self.shed_callback is not None:
                    self.shed_callback(vals[drop].copy(), ts[drop].copy())
                keep = ~drop
                vals, ts, starts = vals[keep], ts[keep], starts[keep]
                take = int(vals.shape[0])
        # mirror the admitted batch
        self._pol_slices_ub += io_starts.size + late_starts.size
        self._pol_annex_ub += late_starts.size
        if take and io_starts.size:
            self._pol_seen_start = int(max(
                seen if seen is not None else np.iinfo(np.int64).min,
                io_starts[-1]))
        return vals, ts, take

    def _grow_capacity(self) -> None:
        """GROW one step: snapshot the full device state via the
        checkpoint pytree machinery, rebuild every jitted kernel at the
        doubled capacity, corner-paste the old state into the fresh
        (larger) buffers and resume — host clock mirrors carry over, so
        the continued run is bit-identical to one pre-sized at the larger
        capacity (tests/test_resilience_policy.py)."""
        import contextlib

        import jax

        from ..resilience.policy import grow_engine_config, pad_tree
        from ..utils import checkpoint as _ck

        new_cfg = grow_engine_config(self.config)   # raises at max_capacity
        span = self.obs.span(_obs.RESILIENCE_GROW_SPAN) \
            if self.obs is not None else contextlib.nullcontext()
        with span:
            old_leaves = jax.device_get(
                jax.tree.flatten(_ck._full_state(self))[0])
            mirrors = {k: getattr(self, k) for k in (
                "_host_met", "_host_min_ts", "_host_first_ts", "_host_count",
                "_last_count", "_annex_dirty", "_count_late_seen",
                "_host_open", "_device_fed", "_last_watermark", "_dm",
                "_dm_host_acc", "_dm_folded", "_pol_seen_start")}
            self.config = new_cfg
            self._built = False
            self._build()                   # fresh kernels + state at 2×
            for k, v in mirrors.items():
                setattr(self, k, v)
            _ck._set_full_state(
                self, pad_tree(old_leaves, _ck._full_state(self)))
        self._pol_refresh()
        if self.obs is not None:
            self.obs.counter(_obs.RESILIENCE_GROW_EVENTS).inc()
            self.obs.flight_event(_flight.GROW, "capacity",
                                  float(self.config.capacity))

    def _flush(self) -> None:
        while self._n_pending > 0:
            self._launch_batch(min(self._n_pending, self.config.batch_size))

    def ingest_device_batch(self, vals, ts, ts_min: int, ts_max: int,
                            n_valid: Optional[int] = None,
                            valid=None) -> None:
        """Zero-copy ingest of device-resident arrays (shape [batch_size],
        ts ascending — late tuples allowed as the sorted prefix, within
        ``max_lateness``). ``ts_min``/``ts_max`` are host-known event-time
        bounds of the batch (they keep the host clock mirrors exact without
        a device sync; conservative bounds are fine). This is the path for
        device-side sources — host→device bandwidth never caps throughput.

        ``valid`` (optional) is a DEVICE-resident boolean lane mask that
        overrides the ``n_valid`` prefix mask — the stream shaper's
        sort-and-split computes its split point on device, so the mask
        cannot be host-materialized without a sync (scotty_tpu.shaper).
        Valid lanes must still be a sorted prefix with pad lanes
        repeating the last valid ts; ``n_valid`` then only feeds the
        host tuple-count mirrors (a conservative total is fine)."""
        if not self._built:
            self._build()
        if self.obs is not None and self.obs.latency is not None:
            # dispatch pre-stamp (ISSUE 14): the host-side moment this
            # device batch's ingest program is dispatched — pure Python,
            # the ingest kernel HLO is untouched
            self.obs.latency.pre(_lat.STAGE_DISPATCH)
        if self.config.overflow_policy != "fail":
            raise UnsupportedOnDevice(
                "overflow policies need host-visible timestamps for the "
                "admission mirror; device-resident ingest runs policy "
                "'fail'")
        import jax

        B = self.config.batch_size
        if self._valid_dev is None:
            self._valid_dev = jax.device_put(np.ones((B,), bool))
        n = B if n_valid is None else n_valid
        if valid is None:
            if n == B:
                valid = self._valid_dev
            else:
                # partially filled batch: lanes >= n_valid MUST be masked
                # or their pad values aggregate into real windows (lanes
                # must be a sorted prefix, pad lanes repeating the last
                # valid ts)
                m = np.zeros((B,), bool)
                m[:n] = True
                valid = jax.device_put(m)
        if self._session_states:
            raise UnsupportedOnDevice(
                "device-resident batches with session windows: use "
                "process_elements (host-fed) for session workloads")
        if self._ctx_states:
            # context windows accept device-resident batches when every
            # spec certifies the in-order chain (the chunk kernel needs
            # no host-side inspection) and the batch is in-order
            if not all(self._ctx_chain):
                raise UnsupportedOnDevice(
                    "device-resident batches with scan-only context "
                    "windows: use process_elements (host-fed)")
            if self._host_met is not None and ts_min < self._host_met:
                raise UnsupportedOnDevice(
                    "out-of-order device batches with context windows "
                    "need the host operator")
            for i in range(len(self._ctx_states)):
                kern = _context_chunk_kernel(
                    self._spec.aggs, self._ctx_specs[i],
                    self.config.capacity, B)
                self._ctx_states[i] = kern(self._ctx_states[i], ts, vals,
                                           valid)
                if self._ctx_planners[i] is not None:
                    # device-resident timestamps are host-opaque: the
                    # speculative bounds mirror cannot replay the chain
                    # walk, so the affected region goes conservatively
                    # unknown (later host OOO chunks re-prove safety
                    # only above it)
                    self._ctx_planners[i].invalidate(ts_max)
            if not self._has_grid:
                if self.obs is not None:        # pure-context ingest done
                    self.obs.counter(_obs.INGEST_TUPLES).inc(n)
                    self.obs.histogram(_obs.INGEST_BATCH_SIZE).observe(n)
                self._dm_device_update(ts, valid)
                self._host_met = ts_max if self._host_met is None \
                    else max(self._host_met, ts_max)
                self._host_min_ts = ts_min if self._host_min_ts is None \
                    else min(self._host_min_ts, ts_min)
                if self._host_first_ts is None:
                    self._host_first_ts = ts_min
                self._host_count += n
                return
        if self._has_count and self._grid_spec.has_time_grid:
            # the host cut mirror can't see device-resident timestamps; a
            # later late host batch must fall back (see _launch_batch)
            self._device_fed = True
        has_late = self._host_met is not None and ts_min < self._host_met
        if has_late:
            if self._has_count:
                raise UnsupportedOnDevice(
                    "out-of-order device batches with count-measure "
                    "windows need the host operator")
            self._annex_dirty = True
        if self.obs is not None:
            # past every reject guard: the batch is definitely ingested.
            # Device-resident ts are opaque host-side, so a back-reaching
            # batch counts whole as late at THIS host boundary — the
            # in-jit device_* counters below carry the exact count.
            self.obs.counter(_obs.INGEST_TUPLES).inc(n)
            self.obs.histogram(_obs.INGEST_BATCH_SIZE).observe(n)
            if has_late:
                self.obs.counter(_obs.LATE_TUPLES).inc(n)
        self._dm_device_update(ts, valid)
        if self._host_first_ts is None:
            self._host_first_ts = ts_min    # conservative (device ts opaque)
        self._host_met = ts_max if self._host_met is None \
            else max(self._host_met, ts_max)
        self._host_min_ts = ts_min if self._host_min_ts is None \
            else min(self._host_min_ts, ts_min)
        self._host_count += n
        if has_late:
            kern = self._ingest         # general kernel: late/annex paths
        else:
            # dense scatter-free variant when the span bound allows
            kern = self._pick_inorder_kernel(ts_min, ts_max)
        self._state = kern(self._state, ts, vals, valid)
        if self._has_count:
            # device batches with count windows are in-order by contract
            self._rec = self._rec_append(self._rec, ts, vals, valid)

    def ingest_device_late(self, ts, vals, valid, n: int, ts_min: int,
                           ts_max: int) -> None:
        """Zero-copy ingest of a device-resident LATE sub-batch (ts sorted,
        all within ``max_lateness``; shape is the caller's static late
        capacity — typically a small fraction of batch_size, so the general
        kernel's full-lane late/annex scatters stay cheap). Companion to
        :meth:`ingest_device_batch` for device sources that separate their
        disorder from the in-order base stream."""
        if not self._built:
            self._build()
        if self.config.overflow_policy != "fail":
            raise UnsupportedOnDevice(
                "overflow policies need host-visible timestamps for the "
                "admission mirror; device-resident ingest runs policy "
                "'fail'")
        if self._has_count or self._session_states or self._ctx_states:
            raise UnsupportedOnDevice(
                "out-of-order device batches with count-measure, session "
                "or context windows need the host operator")
        if self.obs is not None:
            self.obs.counter(_obs.INGEST_TUPLES).inc(n)
            self.obs.counter(_obs.LATE_TUPLES).inc(n)
        self._dm_device_update(ts, valid)
        self._annex_dirty = True
        self._host_met = ts_max if self._host_met is None \
            else max(self._host_met, ts_max)
        self._host_min_ts = ts_min if self._host_min_ts is None \
            else min(self._host_min_ts, ts_min)
        self._host_count += n
        self._state = self._ingest(self._state, ts, vals, valid)

    # -- watermark ---------------------------------------------------------
    def process_watermark(self, watermark_ts: int) -> List[AggregateWindow]:
        ws, we, cnt, lowered = self.process_watermark_arrays(watermark_ts)
        measures = getattr(self, "_trigger_measures", None)
        out: List[AggregateWindow] = []
        for i in range(ws.shape[0]):
            has = bool(cnt[i] > 0)
            values = [lw[i] for lw in lowered] if has else []
            m = (WindowMeasure.Count
                 if measures is not None and measures.shape[0] > i
                 and measures[i] else WindowMeasure.Time)
            out.append(AggregateWindow(m, int(ws[i]), int(we[i]), values, has))
        if self._lat_open is not None and self.obs is not None \
                and self.obs.latency is not None:
            # hand the chain to the sink slot: a TransactionalSink
            # downstream stamps the first delivery and closes it; a
            # sink-less run's chain closes at the next watermark or the
            # check_overflow flush
            self.obs.latency.emitted(self._lat_open)
            self._lat_open = None
        return out

    def process_watermark_async(self, watermark_ts: int):
        """Dispatch the full watermark program with NO device→host sync on
        the time-measure path (the tunnel makes each sync ~100s of ms — the
        dominant cost at benchmark rates). Returns
        ``(ws, we, is_count, cnt_dev, results_dev)`` where the last two are
        device arrays (padded; first ``len(ws)`` rows are live). Call
        :meth:`check_overflow` after draining a stream.

        Host-side clock mirrors replace the reference's store inspection:
        emptiness (WindowManager.java:46-49) is "no tuples ever fed"; the
        oldest-slice clamp (:51-55) only binds on the FIRST watermark —
        after any GC, oldest ≤ gc bound < last watermark — and at that point
        the oldest slice start is exactly grid_start(min ts seen).
        """
        obs = self.obs
        if obs is None:
            return self._process_watermark_dispatch(watermark_ts)
        lat = obs.latency
        if lat is not None and self._lat_open is not None:
            # an async caller never fetched the previous watermark's
            # results through this operator — close its chain as-is
            # (no drain/emit stamps) instead of leaking it to eviction
            lat.finalize(self._lat_open)
            self._lat_open = None
        t_elig = lat.clock.now() if lat is not None else 0.0
        t0 = time.perf_counter()
        out = self._process_watermark_dispatch(watermark_ts)
        if lat is not None:
            # emission-latency lineage (ISSUE 14): the watermark's
            # arrival IS the eligibility moment for every window it
            # closes — the chain opens here, claiming the pending
            # arrival/ring/shaper/dispatch pre-stamps of the records
            # this watermark sweeps (drains inside the dispatch above
            # may add late pre-stamps; finalize time-orders them). One
            # chain per watermark, completed by the arrays/emit face.
            self._lat_open = lat.open()
            lat.stamp(self._lat_open, _lat.STAGE_ELIGIBILITY, at=t_elig)
        # host-side, interval-boundary telemetry: dispatch wall time (no
        # device sync — delivery latency is the harness's emit_latency_ms),
        # watermark count, and event-time lag of the watermark behind the
        # stream head
        obs.histogram(_obs.WATERMARK_DISPATCH_MS).observe(
            (time.perf_counter() - t0) * 1e3)
        obs.counter(_obs.WATERMARKS).inc()
        obs.flight_event(_flight.WATERMARK, "watermark",
                         float(watermark_ts))
        if self._host_met is not None:
            # floored at 0: a drain watermark deliberately runs past the
            # stream end, and a last-value gauge stuck negative would make
            # the headline lag metric meaningless for the whole run
            obs.gauge(_obs.WATERMARK_LAG_MS).set(
                max(0, self._host_met - watermark_ts))
        return out

    def _process_watermark_dispatch(self, watermark_ts: int):
        if not self._built:
            self._build()
        if self._shaper is not None:
            # event time is about to advance past anything still held in
            # the shaper's accumulator — drain it first (the shaper's
            # bounded-delay contract also caps how much can be here)
            self._shaper.flush()
        if self._ingest_feed is not None:
            # same contract for the ingest ring: records still staged
            # (accumulator slack band, partial block, prefetch stage)
            # must land before the watermark sweeps past them
            self._ingest_feed.drain()
        self._flush()
        if self._pure_session:
            outs = self._sweep_sessions(watermark_ts)
            self._last_watermark = watermark_ts
            return ("session", outs)
        st = self._state

        last_wm = self._last_watermark
        first_watermark = last_wm == -1
        if first_watermark:                  # WindowManager.java:43-45
            last_wm = max(0, watermark_ts - self.max_lateness)

        empty = np.empty(0, dtype=np.int64)
        no_result = (empty, empty, np.empty(0, bool), None, None)
        if self._host_met is None:           # store empty: :46-49
            self._last_watermark = watermark_ts
            return self._wrap_mixed(no_result, watermark_ts)

        # The reference's first-watermark clamp to the oldest slice start
        # (WindowManager.java:51-55) reads the FIRST-INSERTED slice. For
        # time-only specs that is the bootstrap/seeded walk from
        # ``te - maxLateness`` (clamped >= 0), so the max(0, wm - lateness)
        # above already matches (clamping to grid_start(min ts) instead
        # would skip the leading empty windows the reference emits — caught
        # by randomized differential fuzzing). With a COUNT measure the
        # first-inserted slice is the count bootstrap cut at the FIRST
        # ARRIVAL's ts (StreamSlicer.java:37-44 fires before any time
        # edge), so streams starting above wm - lateness would otherwise
        # emit leading time windows the reference suppresses (caught by
        # the r4 mixed-OOO review).
        if first_watermark and self._has_count \
                and self._host_first_ts is not None:
            last_wm = max(last_wm, self._host_first_ts)

        if self._annex_dirty:
            self._state = self._merge(self._state)
            st = self._state
            self._annex_dirty = False

        # count-measure trigger bound: watermark ts → count
        # (WindowManager.java:104-118). The one remaining sync, count
        # workloads only.
        cend = None
        if self._has_count:
            cend = int(self._count_at_rec(st, self._rec,
                                          np.int64(watermark_ts))
                       if self._count_late_seen
                       else self._count_at(st, np.int64(watermark_ts)))

        trig_s, trig_e, trig_c = [], [], []
        for w, act in zip(self.windows, self._win_active):
            if not act:
                continue              # cancelled query: mask, not rebuild
            if isinstance(w, (SessionWindow, ForwardContextAware,
                              ForwardContextFree)):
                continue              # context windows emit via their sweeps
            if w.measure == WindowMeasure.Count:
                s_arr, e_arr = w.trigger_arrays(self._last_count, cend + 1)
                trig_c.append(np.ones(s_arr.shape[0], bool))
            else:
                s_arr, e_arr = w.trigger_arrays(last_wm, watermark_ts)
                trig_c.append(np.zeros(s_arr.shape[0], bool))
            trig_s.append(s_arr)
            trig_e.append(e_arr)
        ws = np.concatenate(trig_s) if trig_s else empty
        we = np.concatenate(trig_e) if trig_e else empty
        is_count = (np.concatenate(trig_c) if trig_c
                    else np.empty(0, dtype=bool))
        T = ws.shape[0]
        if T > self.config.max_triggers:
            raise RuntimeError(
                f"{T} triggered windows exceeds max_triggers="
                f"{self.config.max_triggers}")

        cnt_d = results = None
        if T:
            Tp = self.config.trigger_pad(T)
            ws_p = np.zeros((Tp,), np.int64)
            we_p = np.zeros((Tp,), np.int64)
            mask = np.zeros((Tp,), bool)
            ic_p = np.zeros((Tp,), bool)
            ws_p[:T], we_p[:T], mask[:T] = ws, we, True
            ic_p[:T] = is_count
            if self._has_count and self._count_late_seen:
                if self._grid_spec.has_time_grid:
                    # the reference final-merge's batch scan bounds
                    # (WindowManager.java:98-118 → LazyAggregateStore
                    # .aggregate): defaults LONG_MAX/0, count default =
                    # current count; duplicates shadow (see build_query)
                    tm = ~is_count
                    min_ts = int(ws[tm].min()) if tm.any() else LONG_MAX
                    max_ts = int(we[tm].max()) if tm.any() else 0
                    min_count = self._host_count
                    max_count = 0
                    if is_count.any():
                        min_count = min(min_count, int(ws[is_count].min()))
                        max_count = int(we[is_count].max())
                    cnt_d, results = self._query_rec(
                        st, self._rec, ws_p, we_p, mask, ic_p,
                        np.int64(min_ts), np.int64(max_ts),
                        np.int64(min_count), np.int64(max_count))
                else:
                    cnt_d, results = self._query_rec(st, self._rec, ws_p,
                                                     we_p, mask, ic_p)
            else:
                cnt_d, results = self._query(st, ws_p, we_p, mask, ic_p)

        if self._has_count:
            self._last_count = self._host_count   # exact host mirror
        bound = (watermark_ts - self.max_lateness) - self.max_fixed_window_size
        if self._has_count:
            # records GC in rank-lockstep with the slices (reads the PRE-GC
            # slice buffer; dispatched before the slice GC)
            self._rec = self._rec_gc(st, self._rec, np.int64(bound))
        self._state = self._gc(st, np.int64(bound))
        self._last_watermark = watermark_ts
        self._trigger_measures = is_count
        return self._wrap_mixed((ws, we, is_count, cnt_d, results),
                                watermark_ts)

    def _wrap_mixed(self, grid, watermark_ts: int):
        """Append context-window sweeps to a grid watermark result when
        session/context windows are registered (emission order matches
        the simulator: context-free windows first, then context-aware —
        WindowManager.java:98-118)."""
        if not (self._session_states or self._ctx_states):
            return grid
        return ("mixed", grid, self._sweep_sessions(watermark_ts))

    def _sweep_sessions(self, watermark_ts: int):
        """Sweep every context window (tuned session paths and generic
        device-context paths) in registration order."""
        outs = []
        wm = np.int64(watermark_ts)
        gc_bound = np.int64(watermark_ts - self.max_lateness)
        for kind, i in self._ctx_order:
            if kind == "s":
                new_s, m_d, e_s, e_e, e_c, e_p = self._session_sweeps[i](
                    self._session_states[i], wm, gc_bound)
                self._session_states[i] = new_s
            else:
                new_s, m_d, e_s, e_e, e_c, e_p = self._ctx_sweeps[i](
                    self._ctx_states[i], wm,
                    gc_bound - np.int64(self._ctx_gc_slack[i]))
                self._ctx_states[i] = new_s
                if self._ctx_planners[i] is not None:
                    # the planner's bounds mirror prunes on the same
                    # certified trigger rule the device sweep applies
                    self._ctx_planners[i].sweep(watermark_ts)
            outs.append((m_d, e_s, e_e, e_c, e_p))
        return outs

    def _lat_stamp(self, stage: str) -> None:
        """Stamp one stage on the in-flight watermark chain (no-op
        without a tracer or an open chain — one attribute check)."""
        if self._lat_open is not None and self.obs is not None:
            lat = self.obs.latency
            if lat is not None:
                lat.stamp(self._lat_open, stage)

    def process_watermark_arrays(self, watermark_ts: int):
        """Synchronous watermark: returns numpy ``(starts[T], ends[T],
        counts[T], [per-agg lowered [T]])`` — one bundled device fetch."""
        out = self.process_watermark_async(watermark_ts)
        if isinstance(out[0], str) and out[0] == "session":
            ws, we, cnt, lowered = self._fetch_sessions(out[1])
            self._trigger_measures = np.zeros((ws.shape[0],), bool)
            self._lat_stamp(_lat.STAGE_EMIT)
            return ws, we, cnt, lowered
        if isinstance(out[0], str) and out[0] == "mixed":
            _, grid, s_outs = out
            g_ws, g_we, g_cnt, g_low = self._fetch_grid(grid)
            s_ws, s_we, s_cnt, s_low = self._fetch_sessions(s_outs)
            ws = np.concatenate([g_ws, s_ws])
            we = np.concatenate([g_we, s_we])
            cnt = np.concatenate([g_cnt, s_cnt])
            lowered = [np.concatenate([np.asarray(a), np.asarray(b)])
                       for a, b in zip(g_low, s_low)]
            is_count = grid[2]
            self._trigger_measures = np.concatenate(
                [is_count, np.zeros((s_ws.shape[0],), bool)])
            self._lat_stamp(_lat.STAGE_EMIT)
            return ws, we, cnt, lowered
        res = self._fetch_grid(out)
        self._lat_stamp(_lat.STAGE_EMIT)
        return res

    def _fetch_grid(self, grid):
        import jax

        ws, we, is_count, cnt_d, results = grid
        T = ws.shape[0]
        lowered: List[np.ndarray] = [np.empty(0)
                                     for _ in self.aggregations] if T == 0 \
            else []
        cnt_np = np.zeros((T,), dtype=np.int64)
        if T:
            ovf_src = self._state.overflow if self._rec is None \
                else self._state.overflow | self._rec.overflow
            cnt_h, res_h, ovf = jax.device_get((cnt_d, results, ovf_src))
            self._lat_stamp(_lat.STAGE_DRAIN)
            self._raise_if_overflow(ovf)
            cnt_np = cnt_h[:T]
            for agg, res in zip(self.aggregations, res_h):
                spec = agg.device_spec()
                lowered.append(np.asarray(spec.lower(res[:T], cnt_np)))
        return ws, we, cnt_np, lowered

    def _raise_if_overflow(self, ovf) -> None:
        if bool(ovf):
            note = "" if self.config.overflow_policy == "fail" else (
                f" (overflow_policy={self.config.overflow_policy!r} could "
                "not prevent it — the raised device flag means writes were "
                "already clamped, which is unrecoverable under any policy)")
            e = RuntimeError(
                "slice/session buffer overflow: raise EngineConfig.capacity "
                "(slice rows, session rows) / annex_capacity (late annex & "
                "session orphan buffer) / batch sizing, advance watermarks "
                "more often, or set EngineConfig.overflow_policy to "
                "'shed'/'grow' (scotty_tpu.resilience)" + note)
            if self.obs is not None:
                self.obs.counter(_obs.OVERFLOWS).inc()
                self.obs.record_failure(e, kind=_flight.OVERFLOW,
                                        config=self.config)
            raise e

    def check_overflow(self) -> None:
        """One deliberate sync validating the run (async users call this
        after draining a stream)."""
        if self._shaper is not None:
            # shaper drain-point check: raises ShaperOverflow on a lost
            # late residue and folds the shaper_* telemetry
            self._shaper.check()
        if self._ingest_feed is not None:
            # ingest-ring drain-point fold (ingest_ring_* counters +
            # occupancy gauges — scotty_tpu.ingest)
            self._ingest_feed.check()
        if not self._built:
            return
        if self._state is not None:
            self._raise_if_overflow(self._state.overflow)
        if self._rec is not None:
            self._raise_if_overflow(self._rec.overflow)
        for st in getattr(self, "_session_states", ()):
            self._raise_if_overflow(st.overflow)
        for st in getattr(self, "_ctx_states", ()):
            self._raise_if_overflow(st.overflow)
        if self.obs is not None and self._state is not None:
            # this method is already a deliberate sync point, so the
            # occupancy/headroom gauges can read the live slice count
            # without introducing a new device round trip
            import jax

            n = int(jax.device_get(self._state.n_slices))
            cap = self.config.capacity
            self.obs.gauge(_obs.SLICE_OCCUPANCY).set(n / cap)
            self.obs.gauge(_obs.SLICE_HEADROOM).set(cap - n)
        if self.obs is not None:
            # same drain point: fold the device_* telemetry delta
            from ..obs import device as _dev

            self._dm_folded = _dev.fold_into(
                self.obs.registry, self.device_metrics(), self._dm_folded)
            # and sample the flight ring (zero additional device syncs —
            # the watermark advance itself was recorded at dispatch)
            self.obs.flight_sample()
            lat = self.obs.latency
            if lat is not None:
                # latency drain-point tidy: close a chain an async
                # caller left open and a parked sink handoff, fold the
                # lineage/drop totals — same discipline as the folds
                # above, zero extra syncs
                if self._lat_open is not None:
                    lat.finalize(self._lat_open)
                    self._lat_open = None
                lat.flush()

    def _fetch_sessions(self, outs):
        """Fetch per-session-window sweep outputs; emission follows window
        registration order (the simulator's context list order)."""
        import jax

        fetched = jax.device_get(
            (outs, tuple(s.overflow for s in (list(self._session_states)
                                              + list(self._ctx_states)))))
        self._lat_stamp(_lat.STAGE_DRAIN)
        gap_outs, ovfs = fetched
        for ovf in ovfs:
            self._raise_if_overflow(ovf)
        ws_parts, we_parts, cnt_parts = [], [], []
        low_parts = [[] for _ in self.aggregations]
        for (m, ws_h, we_h, cnt_h, res_h) in gap_outs:
            m = int(m)
            if m > self._emit_cap:
                # the second overflow raise path (ISSUE 3 satellite):
                # counted like the buffer-overflow path so dashboards and
                # the obs diff gate see it, with an actionable hint
                e = RuntimeError(
                    f"{m} sessions completed in one watermark exceeds the "
                    f"emission buffer ({self._emit_cap}); raise "
                    "EngineConfig.min_trigger_pad, advance watermarks more "
                    "often (fewer sessions complete per sweep), or run "
                    "under a scotty_tpu.resilience.Supervisor to restart "
                    "from the last checkpoint")
                if self.obs is not None:
                    self.obs.counter(_obs.OVERFLOWS).inc()
                    self.obs.record_failure(e, kind=_flight.OVERFLOW,
                                            config=self.config)
                raise e
            ws_parts.append(ws_h[:m])
            we_parts.append(we_h[:m])
            cnt_parts.append(cnt_h[:m])
            for j, (agg, res) in enumerate(zip(self.aggregations, res_h)):
                spec = agg.device_spec()
                low_parts[j].append(
                    np.asarray(spec.lower(res[:m], cnt_h[:m])))
        ws = np.concatenate(ws_parts) if ws_parts else np.empty(0, np.int64)
        we = np.concatenate(we_parts) if we_parts else np.empty(0, np.int64)
        cnt = np.concatenate(cnt_parts) if cnt_parts \
            else np.empty(0, np.int64)
        lowered = [np.concatenate(p) if p else np.empty(0) for p in low_parts]
        return ws, we, cnt, lowered

    # -- introspection -----------------------------------------------------
    @property
    def n_slices(self) -> int:
        total = 0
        if self._state is not None:
            total += int(self._state.n_slices)
        for st in getattr(self, "_session_states", ()):
            total += int(st.n)              # live sessions
        for st in getattr(self, "_ctx_states", ()):
            total += int(st.n)              # live context windows
        return total
