"""Fused session-workload pipeline: ONE XLA dispatch per watermark interval
for session windows (optionally mixed with time-grid windows).

TPU-first observation driving the design: per-lane scatter work is the only
ingest cost class that scales with the tuple count (f32 scatters ~6-12 ms
per 1M lanes on v5e, int64 ~15-20× worse — measured, docs/DESIGN.md and
bench_results/micro.json), and per-dispatch
overhead on tunneled devices is ~5-15 ms. A session benchmark stream is a
constant-rate generator with occasional SILENT SPANS (the reference's
session-gap mechanism, LoadGeneratorSource.java:60-76): at benchmark rates
the inter-arrival time between consecutive tuples (~µs) never approaches a
session gap (~seconds), so sessions can only break at the injected silent
spans. This pipeline quantizes silent spans to whole watermark intervals,
which makes each live interval's tuples one contiguous chain segment:

* per interval, ONE shared fold per aggregation covers every registered
  session window — a dense reduction for sum-kind lifts, a single [B]-lane
  f32 scatter into the sketch width for sparse lifts (HLL registers,
  DDSketch buckets);
* each session window then updates at most ONE row of its bounded
  active-session array (extend the open session, or close it and open a new
  one when the preceding silence exceeded that window's gap) — the
  in-order specialization of SessionContext.updateContext
  (SessionWindow.java:40-84) at interval granularity;
* completed sessions emit via the shared sweep kernel
  (engine/sessions.py:build_session_sweep — trigger semantics
  SessionWindow.java:107-116);
* time-grid windows in the mix ride the slice-aligned append of
  AlignedStreamPipeline (no scatters at all) over the SAME generated
  tuples; silent intervals append nothing, so grid windows over silence
  emit empty exactly like the reference (empty windows are not emitted).

Generality note: this execution mode covers the benchmark-shaped session
workload (in-order stream, silence-separated sessions). Arbitrary
out-of-order session streams run on TpuWindowOperator's session kernels
(engine/sessions.py late scan) or the host oracle — the decision tree in
hybrid.py.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from .. import jax_config  # noqa: F401
from .. import obs as _obs
from ..obs import flight as _flight

from ..core.aggregates import AggregateFunction
from ..core.windows import (
    SessionWindow,
    SlidingWindow,
    TumblingWindow,
    WindowMeasure,
)
from .config import EngineConfig
from .pipeline import FusedPipelineDriver, build_trigger_grid


class SessionStreamPipeline(FusedPipelineDriver):
    """One fused step per watermark interval for session(-mix) workloads.

    ``session_config``: {"count": N, "minGapMs": a, "maxGapMs": b} — the
    reference benchmark's silent-span parameters (BenchmarkRunner.java:
    174-192). Spans are placed by a seeded schedule over a cyclic horizon
    and quantized to whole intervals (lengths rounded UP, so a span meant
    to exceed a session gap still does).
    """

    _uses_device_metrics = True

    def __init__(self, windows: Sequence, aggregations: Sequence[AggregateFunction],
                 config: Optional[EngineConfig] = None,
                 throughput: int = 32_000_000, wm_period_ms: int = 1000,
                 max_lateness: int = 1000, seed: int = 0,
                 session_config: Optional[dict] = None, gc_every: int = 32,
                 max_chunk_elems: int = 1 << 25,
                 value_scale: float = 10_000.0,
                 collect_device_metrics: bool = True):
        import jax
        import jax.numpy as jnp

        from . import core as ec
        from . import sessions as es
        from ..obs import device as _dev

        self.collect_device_metrics = bool(collect_device_metrics)
        self.config = config or EngineConfig()
        self.windows = list(windows)
        self.aggregations = list(aggregations)
        self.max_lateness = max_lateness
        self.wm_period_ms = wm_period_ms
        self.gc_every = gc_every
        self.seed = seed
        self.value_scale = float(value_scale)

        self.session_windows = [w for w in self.windows
                                if isinstance(w, SessionWindow)]
        grid_windows = [w for w in self.windows
                       if not isinstance(w, SessionWindow)]
        for w in self.session_windows:
            if w.measure != WindowMeasure.Time:
                raise NotImplementedError("count-measure sessions: host only")
        max_fixed = 0
        for w in grid_windows:
            if w.measure != WindowMeasure.Time or not isinstance(
                    w, (TumblingWindow, SlidingWindow)):
                raise NotImplementedError(
                    "session pipeline: time tumbling/sliding mixes only")
            max_fixed = max(max_fixed, w.clear_delay())
        aggs = tuple(a.device_spec() for a in self.aggregations)
        if any(a is None for a in aggs):
            raise NotImplementedError("device-realizable aggregations only")
        if any(a.cells_per_tuple > 1 for a in aggs):
            # the session chain kernel and the one-hot segment reduce both
            # assume one sparse cell per tuple
            raise NotImplementedError(
                "session pipeline: multi-cell sparse aggregations "
                "(count-min) are unsupported; use the time-grid pipelines")

        # ---- generator layout (slice-aligned rows, like the aligned
        # pipeline; for pure-session workloads an artificial row grid keeps
        # intra-interval inter-arrival far below any session gap) ----------
        P = wm_period_ms
        members = [P] + [int(w.size) for w in grid_windows] \
            + [int(w.slide) for w in grid_windows
               if isinstance(w, SlidingWindow)]
        import math

        g = 0
        for m in members:
            g = math.gcd(g, m)
        if self.session_windows:
            min_gap = min(int(w.gap) for w in self.session_windows)
            # row span must stay well under the smallest session gap so
            # rows are never mistaken for silence (inter-arrival <= 2 rows)
            while g > max(1, min_gap // 4):
                for dv in range(2, g + 1):
                    if g % dv == 0:
                        g //= dv
                        break
        R = throughput * g // 1000     # rounded down to whole tuples/row;
                                       # accounting uses the exact S*R
        if R < 1:
            raise NotImplementedError("throughput too low: <1 tuple per row")
        S = P // g
        self.grid, self.R, self.S = g, R, S
        self.tuples_per_interval = S * R

        # ---- silent-span schedule (cyclic, host-precomputed) -------------
        # No session_config → no silent spans (a constant-rate stream; note
        # sessions then never complete — callers route such workloads
        # elsewhere, bench/runner.py Hybrid branch)
        sc = session_config or {"count": 0}
        n_gaps = int(sc.get("count", 8))
        gmin = int(sc.get("minGapMs", 1500))
        gmax = int(sc.get("maxGapMs", 4000))
        rng = np.random.default_rng(seed)
        lens_iv = np.maximum(1, -(-rng.integers(
            gmin, max(gmin + 1, gmax), size=n_gaps) // P))  # ceil → intervals
        # cyclic horizon sized so silence is ~40% of intervals — the
        # reference's pause density in benchmark terms; gap starts random
        horizon = max(16, int(lens_iv.sum() / 0.4) + 1)
        silent = np.zeros(horizon, bool)
        for ln in lens_iv:
            # keep each span's configured length: draw a start that fits
            # before the horizon end instead of truncating there (ADVICE r3);
            # interval 0 stays non-silent so the first interval carries tuples
            hi = max(2, horizon - int(ln) + 1)
            pos = int(rng.integers(1, hi))
            silent[pos:pos + int(ln)] = True
        silent[0] = False
        self._silent = silent
        self._horizon = horizon
        #: timed regions shorter than this may see zero completed sessions
        #: (a session only completes after a silent span)
        self.min_timed_intervals = 16 if self.session_windows else 0
        self.max_fixed = max_fixed

        # ---- kernels ------------------------------------------------------
        # the grid buffer only ever holds rows younger than the GC horizon
        # (widest window + lateness + gc cadence); the query's log-sweep
        # sparse table scales with the BUFFER capacity, so clamping it to
        # the live span (instead of inheriting the generic config default,
        # sized for 60k-window suites) removes almost all query cost on
        # session-mix shapes (r4 — the hll mix cell was sweep-bound)
        need_rows = (max_fixed + max_lateness) // g + S * (gc_every + 2) + 8
        C = min(self.config.capacity,
                1 << max(4, (need_rows - 1).bit_length()))
        A = self.config.annex_capacity
        self.has_grid = bool(grid_windows)
        # pure-session mode anchors the live-SESSION count, whose capacity
        # is the session array's, not config.capacity — the driver's
        # occupancy gauges would misreport headroom, so they stay off there
        self._anchor_is_slices = self.has_grid
        spec = ec.EngineSpec(
            periods=(g,) if self.has_grid else (), bands=(),
            count_periods=(), aggs=aggs)
        self.spec = spec
        if self.has_grid:
            query = ec.build_query(spec, C, A)
            self._gc_kernel = jax.jit(ec.build_gc(spec, C, A),
                                      donate_argnums=0)
            make_triggers, self.T = build_trigger_grid(grid_windows, P)
        self._init_grid = (lambda: ec.init_state(spec, C, A)) \
            if self.has_grid else (lambda: None)
        E = self.config.trigger_pad(1024)
        self._emit_cap = E
        gaps = [int(w.gap) for w in self.session_windows]
        self._gaps = gaps
        # live sessions per window are bounded by open + completed-awaiting-
        # sweep (swept every interval) — a few rows, not the slice-buffer
        # capacity; small arrays keep HBM use and per-sweep gather work tiny
        SC_CAP = min(C, 512)
        sweeps = [es.build_session_sweep(aggs, gp, SC_CAP, E) for gp in gaps]
        self._sc_cap = SC_CAP
        self._init_sessions = lambda: [
            es.init_session_state(aggs, SC_CAP, orphan_capacity=8)
            for _ in gaps]

        # rows per generation chunk (divisor of S within the lift budget).
        # Sparse lifts scatter into flat [d*width] targets — per-lane cost
        # only — so they count as width 1 here; dense lifts materialize
        # [d*R, width].
        max_width = max(1 if a.is_sparse else a.width for a in aggs)
        d = 1
        for cand in range(1, S + 1):
            if S % cand == 0 and cand * R * max_width <= max_chunk_elems:
                d = cand
        n_chunks = S // d
        self._d, self._n_chunks = d, n_chunks
        first_lw = max(0, P - max_lateness)

        # Narrow sparse sketches (HLL's 256 registers) take a sub-batched
        # one-hot segment reduce instead of the flat [B]-lane scatter: the
        # scatter costs ~7 ms per M lanes on v5e regardless of target size
        # (the r3 hll cell's ceiling), while a [q, width] masked reduce is
        # bandwidth/VPU-bound — ~6× cheaper at width<=512 (VERDICT r3
        # item 4). Wide sketches (DDSketch 2048) keep the scatter: their
        # one-hot would blow the traffic up past the scatter cost.
        onehot_q = {}
        for a in aggs:
            if a.is_sparse and a.width <= 512:
                qmax = min(R, max(1, max_chunk_elems // a.width))
                for q in range(qmax, 0, -1):
                    if R % q == 0:
                        break
                if q >= 1024:          # too-small sub-batches can't amortize
                    onehot_q[a.token] = q
        self._onehot_q = onehot_q

        def gen_chunk(key, c):
            """[d, R] values for chunk c. Values take the half-draw block
            layout (two 16-bit values per 32-bit draw — the shared RNG
            cost model, engine/pipeline.half_draw); event times are PACED
            within each slice row (tuple j at offset j·g//R — the
            reference's constant-rate LoadGeneratorSource arrival clock),
            so the per-tuple offset stream costs nothing and the row
            extrema are closed form."""
            from .pipeline import draw_uniform16

            return draw_uniform16(jax.random.fold_in(key, c), (d, R),
                                  value_scale)

        # paced intra-row offsets: first tuple at the row start, last at
        # (R-1)·g//R — deterministic, identical for every row
        off_first = 0
        off_last = ((R - 1) * g) // R

        cdm = self.collect_device_metrics

        def step(grid_state, sess_states, dm, key, interval_idx, live):
            """live: i1 scalar — False = silent interval (no tuples)."""
            base = interval_idx * P
            wm = base + P
            if cdm:
                dm = dm._replace(
                    ingested=dm.ingested
                    + jnp.where(live, jnp.int64(S * R), 0),
                    silent_intervals=dm.silent_intervals
                    + jnp.where(live, 0, jnp.int64(1)),
                    slices_touched=dm.slices_touched + jnp.where(
                        live,
                        jnp.int64((S if self.has_grid else 0) + len(gaps)),
                        0))

            def gen_and_fold(_):
                def body(carry, c):
                    vals = gen_chunk(key, c)
                    flat = vals.reshape(-1)
                    parts = []
                    for aspec in spec.aggs:
                        red = {"sum": jnp.sum, "min": jnp.min,
                               "max": jnp.max}[aspec.kind]
                        if aspec.is_sparse \
                                and aspec.token in onehot_q:
                            # sub-batched one-hot segment reduce (see the
                            # strategy note in __init__): q tuples at a
                            # time, [q, width] masked reduce, one-row
                            # combine into the [d, width] row partials
                            q = onehot_q[aspec.token]
                            per_row = R // q
                            ident = jnp.asarray(aspec.identity,
                                                jnp.float32)

                            def sub(acc, j, _a=aspec, _q=q, _pr=per_row,
                                    _ident=ident, _flat=flat):
                                seg = jax.lax.dynamic_slice(
                                    _flat, (j * _q,), (_q,))
                                col, v = _a.lift_sparse(seg)
                                oh = col[:, None] == jnp.arange(
                                    _a.width, dtype=col.dtype)[None, :]
                                row = j // _pr
                                if _a.kind == "sum":
                                    upd = jnp.sum(
                                        jnp.where(oh, v[:, None], 0),
                                        axis=0)
                                    return acc.at[row].add(upd), None
                                # min/max sketch values are small exact
                                # integers (HLL rho <= 32): the [q, width]
                                # masked reduce runs in bf16 — half the
                                # VPU/HBM traffic of f32, no precision loss
                                vb = v.astype(jnp.bfloat16)
                                ib = _ident.astype(jnp.bfloat16)
                                if _a.kind == "min":
                                    upd = jnp.min(
                                        jnp.where(oh, vb[:, None], ib),
                                        axis=0).astype(jnp.float32)
                                    return acc.at[row].min(upd), None
                                upd = jnp.max(
                                    jnp.where(oh, vb[:, None], ib),
                                    axis=0).astype(jnp.float32)
                                return acc.at[row].max(upd), None

                            init_pr = jnp.full((d, aspec.width),
                                               aspec.identity, jnp.float32)
                            pr, _ = jax.lax.scan(
                                sub, init_pr,
                                jnp.arange((d * R) // q, dtype=jnp.int32))
                        elif aspec.is_sparse:
                            # per-row sketch partials via ONE flat [B]-lane
                            # f32 scatter (never a dense [B, width] lift)
                            col, v = aspec.lift_sparse(flat)
                            row_id = jnp.arange(
                                d * R, dtype=jnp.int32) // R
                            fi = row_id * aspec.width \
                                + col.astype(jnp.int32)
                            tgt = jnp.full((d * aspec.width,),
                                           aspec.identity, jnp.float32)
                            if aspec.kind == "sum":
                                tgt = tgt.at[fi].add(v)
                            elif aspec.kind == "min":
                                tgt = tgt.at[fi].min(v)
                            else:
                                tgt = tgt.at[fi].max(v)
                            pr = tgt.reshape(d, aspec.width)
                        else:
                            lifted = aspec.lift_dense(flat).reshape(d, R, -1)
                            pr = red(lifted, axis=1)              # [d, w]
                        parts.append(pr)
                    return carry, tuple(parts)

                _, parts = jax.lax.scan(
                    body, None, jnp.arange(n_chunks))
                # the interval-wide fold shared by every session window
                # derives from the STACKED row partials ([n_chunks, d, w]
                # — tiny), never from the lifted lanes: a second consumer
                # of the [q, width] one-hot producer makes XLA DUPLICATE
                # it into both fusions, doubling the step's flops
                # (measured 9.1 -> 17.7 GFLOP, 44 -> 74 ms on the hll
                # mix cell — the r4 'mix at half the pure-session rate'
                # mystery, VERDICT r4 weak #3)
                comb = []
                for aspec, pstack in zip(spec.aggs, parts):
                    red = {"sum": jnp.sum, "min": jnp.min,
                           "max": jnp.max}[aspec.kind]
                    comb.append(red(pstack, axis=(0, 1)))
                comb = tuple(comb)
                return comb, parts

            def no_fold(_):
                comb = tuple(jnp.full((a.width,), a.identity, jnp.float32)
                             for a in spec.aggs)
                parts = tuple(jnp.full((S // d, d, a.width), a.identity,
                                       jnp.float32) for a in spec.aggs)
                return comb, parts

            comb, parts = jax.lax.cond(live, gen_and_fold, no_fold, None)
            row_starts = base + g * jnp.arange(S, dtype=jnp.int64)
            t_first_iv = base + off_first          # first tuple ts (paced)
            t_last_iv = base + (S - 1) * g + off_last
            n_tuples = jnp.where(live, jnp.int64(S * R), 0)

            # ---- grid append (aligned, zero-scatter) ---------------------
            if self.has_grid:
                st = grid_state
                n = st.n_slices

                def app(buf, rows):
                    idx = (n,) + (jnp.int32(0),) * (buf.ndim - 1)
                    return jax.lax.dynamic_update_slice(
                        buf, rows.astype(buf.dtype), idx)

                appended = st._replace(
                    starts=app(st.starts, row_starts),
                    ends=app(st.ends, row_starts + g),
                    t_first=app(st.t_first, row_starts + off_first),
                    t_last=app(st.t_last, row_starts + off_last),
                    c_start=app(st.c_start, st.current_count
                                + R * jnp.arange(S, dtype=jnp.int64)),
                    counts=app(st.counts, jnp.full((S,), R, jnp.int64)),
                    partials=tuple(
                        app(p, pr.reshape(S, -1))
                        for p, pr in zip(st.partials, parts)),
                    n_slices=n + S,
                    max_event_time=jnp.maximum(st.max_event_time, t_last_iv),
                    current_count=st.current_count + S * R,
                    overflow=st.overflow | (n + S > C),
                )
                grid_state = jax.tree.map(
                    lambda a, b: jnp.where(live, a, b), appended, st)
                last_wm = jnp.where(interval_idx > 0, base,
                                    jnp.int64(first_lw))
                ws, we, tmask = make_triggers(last_wm, wm)
                cnt, results = query(grid_state, ws, we, tmask,
                                     jnp.zeros_like(tmask))
            else:
                ws = jnp.zeros((0,), jnp.int64)
                we = jnp.zeros((0,), jnp.int64)
                cnt = jnp.zeros((0,), jnp.int64)
                results = tuple(jnp.zeros((0, a.width), jnp.float32)
                                for a in spec.aggs)

            if cdm and self.has_grid:
                dm = dm._replace(
                    triggers=dm.triggers + jnp.sum(tmask),
                    windows_nonempty=dm.windows_nonempty
                    + jnp.sum(tmask & (cnt > 0)))
                dm = _dev.record_occupancy(dm, grid_state.n_slices, C)

            # ---- session updates: at most one row per window -------------
            new_states = []
            ws_parts, we_parts, cnt_parts = [ws], [we], [cnt]
            res_parts = [results]
            for gap, sweep, sst in zip(gaps, sweeps, sess_states):
                n_s = sst.n
                open_last = jnp.where(
                    n_s > 0, sst.last[jnp.maximum(n_s - 1, 0)],
                    jnp.int64(-(1 << 62)))
                chain = live & (n_s > 0) & (t_first_iv - open_last <= gap)
                fresh = live & ~chain
                row = jnp.where(chain, n_s - 1, n_s).astype(jnp.int32)
                upd = jnp.where(live, row, SC_CAP)   # out of range = drop
                first = sst.first.at[upd].min(
                    jnp.where(live, t_first_iv, 1 << 62), mode="drop")
                last = sst.last.at[upd].max(
                    jnp.where(live, t_last_iv, -(1 << 62)), mode="drop")
                counts = sst.counts.at[upd].add(n_tuples, mode="drop")
                partials = []
                for aspec, part, fv in zip(spec.aggs, sst.partials, comb):
                    fv = jnp.where(live, fv, jnp.asarray(
                        aspec.identity, jnp.float32))
                    if aspec.kind == "sum":
                        part = part.at[upd].add(fv, mode="drop")
                    elif aspec.kind == "min":
                        part = part.at[upd].min(fv, mode="drop")
                    else:
                        part = part.at[upd].max(fv, mode="drop")
                    partials.append(part)
                sst = sst._replace(
                    first=first, last=last, counts=counts,
                    partials=tuple(partials),
                    n=(n_s + jnp.where(fresh, 1, 0)).astype(jnp.int32),
                    overflow=sst.overflow | (fresh & (n_s >= SC_CAP)))
                sst, m, e_s, e_e, e_c, e_p = sweep(
                    sst, jnp.int64(wm), jnp.int64(wm - max_lateness))
                new_states.append(sst)
                ws_parts.append(e_s)
                we_parts.append(e_e)
                cnt_parts.append(e_c)
                res_parts.append(e_p)
                if cdm:
                    # every completed session is both a trigger and a
                    # non-empty window (empty sessions don't exist)
                    m64 = jnp.asarray(m, jnp.int64)
                    dm = dm._replace(
                        triggers=dm.triggers + m64,
                        windows_nonempty=dm.windows_nonempty + m64)

            out = (jnp.concatenate(ws_parts), jnp.concatenate(we_parts),
                   jnp.concatenate(cnt_parts),
                   tuple(jnp.concatenate([r[i] for r in res_parts])
                         for i in range(len(spec.aggs))))
            return grid_state, new_states, dm, out

        self._step = jax.jit(step, donate_argnums=(0, 1, 2)) \
            if self.has_grid else jax.jit(step, donate_argnums=(1, 2))
        self._root = None
        self.state = None
        self.sess_states = None
        self._interval = 0

    # -- driver-facing interface (FusedPipelineDriver hooks) ---------------
    def _init_pipeline_state(self) -> None:
        self.state = self._init_grid()
        self.sess_states = self._init_sessions()

    def _step_interval(self, key, i: int):
        import jax

        # explicit device_put of the per-interval scalars — the one
        # sanctioned h2d upload under the differential tests'
        # jax.transfer_guard("disallow") (same avals: HLO unchanged,
        # pinned by tests/hlo_pins.json)
        iv, live = jax.device_put((np.int64(i),
                                   np.bool_(self.live(i))))
        self.state, self.sess_states, self.dm, res = self._step(
            self.state, self.sess_states, self.dm, key, iv, live)
        return res

    def _gc(self, bound) -> None:
        if self.has_grid:
            self.state = self._gc_kernel(self.state, bound)

    def _sync_anchor(self):
        return self.state.n_slices if self.has_grid \
            else self.sess_states[0].n

    def live(self, i: int) -> bool:
        return not bool(self._silent[i % self._horizon])

    def _interval_tuples(self, i: int) -> int:
        """Telemetry: silent intervals carry no tuples — counting them at
        the flat per-interval rate would overstate ``ingest_tuples`` by
        the silence fraction; count them (``silent_intervals``) instead."""
        if not self.live(i):
            if self.obs is not None:
                self.obs.counter(_obs.SILENT_INTERVALS).inc()
            return 0
        return int(self.tuples_per_interval)

    def tuples_in_range(self, i0: int, i1: int) -> int:
        return sum(self.tuples_per_interval
                   for i in range(i0, i1) if self.live(i))

    def check_overflow(self) -> None:
        import jax

        flags = [s.overflow for s in self.sess_states]
        if self.has_grid:
            flags.append(self.state.overflow)
        if any(bool(v) for v in jax.device_get(flags)):
            e = RuntimeError(
                "slice/session buffer overflow: raise capacity. (GROW's "
                "occupancy trigger watches the slice anchor only, so "
                "session-row pressure on this pipeline cannot be "
                "prevented by overflow_policy='grow'; a raised flag is "
                "unrecoverable under any policy)")
            if self.obs is not None:
                self.obs.counter(_obs.OVERFLOWS).inc()
                self.obs.record_failure(e, kind=_flight.OVERFLOW,
                                        config=self.config)
            raise e

    def materialize_interval(self, i: int):
        """Regenerate interval i's tuple stream on host (testing): returns
        (vals f32, ts i64), row-major by slice row — EMPTY for silent
        intervals. Bit-identical to the device generator."""
        import jax
        import jax.numpy as jnp

        if not self.live(i):
            return np.empty(0, np.float32), np.empty(0, np.int64)
        if self._root is None:
            self._root = jax.random.PRNGKey(self.seed)
        key = jax.random.fold_in(self._root, i)
        g, d, R, P = self.grid, self._d, self.R, self.wm_period_ms
        from .pipeline import draw_uniform16

        vals_all, ts_all = [], []
        paced = (np.arange(R, dtype=np.int64) * g) // R
        for c in range(self._n_chunks):
            kg = jax.random.fold_in(key, jnp.int64(c))
            vals = np.asarray(jax.device_get(draw_uniform16(
                kg, (d, R), self.value_scale)))
            row_starts = (i * P + g * (c * d + np.arange(d, dtype=np.int64)))
            # paced intra-row event times (see gen_chunk)
            ts = row_starts[:, None] + paced[None, :]
            vals_all.append(vals.reshape(-1))
            ts_all.append(ts.reshape(-1))
        return np.concatenate(vals_all), np.concatenate(ts_all)

    def lowered_results(self, interval_out) -> list:
        from .pipeline import lower_interval

        return lower_interval(self.aggregations, interval_out)
