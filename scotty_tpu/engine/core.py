"""Device data model + kernels of the TPU slicing engine.

This is the TPU-first re-design of the reference's slicing hot paths
(slicing/.../StreamSlicer.java:36-86, SliceManager.java:47-87,
LazyAggregateStore.java:83-111 — see SURVEY.md §3.1/§3.3):

* The slice store is a **sorted linear buffer in HBM** with static capacity:
  ``starts[C]`` (slice start edges, ascending, LONG_MAX-padded), per-slice
  record counts, observed ts extents, and one fixed-width partial-aggregate
  matrix ``partials[C, width]`` per registered aggregation.

* **Ingest** processes a whole batch of tuples in one fused kernel: each
  tuple's slice start is the latest window-grid point ≤ its timestamp
  (closed-form over all registered context-free windows — the vectorized
  equivalent of the reference's ``assignNextWindowStart`` min-loop,
  StreamSlicer.java:103-116); segment boundaries fall where that grid start
  changes; partial aggregates fold in via duplicate-index scatter-combine
  (the associativity of ``combine`` is the license, AggregateFunction.java:19-34).
  Empty grid ranges are *not* materialized — an absent slice contributes the
  combine identity, which is exactly what the reference's empty slices
  contribute (LazyAggregateStore.java:83-111 merges nothing from them).

* **Window results** replace the reference's O(#slices × #windows) nested
  final-merge loop with range queries over the sorted buffer: a window
  [ws, we) covers exactly the slices with ``ws <= start < we`` (slice edges
  are window-grid points, so slices never straddle a window boundary), hence

  - sum-like aggregations (sum/count/mean/DDSketch histograms) answer all
    triggered windows at once from one prefix-sum: ``P[hi] - P[lo]``;
  - min/max-like aggregations (min/max/HLL registers) use a log-sweep
    sparse-table: L = log2(C) doubling levels, each window answered at its
    level with two gathers.

* **GC** (WindowManager.clearAfterWatermark, WindowManager.java:82-95) is a
  masked roll of the buffer.

Out-of-order tuples within ``max_lateness`` need no edge repair for
context-free windows (Shift/Add/Delete modifications only originate from
context-aware windows — WindowContext.java:19-63): a late tuple folds into
the existing covering slice (scatter-combine), or — when its grid range was
never materialized — into a small unsorted *annex* that is merged into the
main buffer at the next watermark.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Any, Callable, NamedTuple, Sequence

import numpy as np

import jax

from .. import jax_config  # noqa: F401  (x64 + compile cache, import-order safe)

import jax.numpy as jnp

from ..core.aggregates import DeviceAggregateSpec
from ..core.windows import LONG_MAX

I64_MAX = np.int64(LONG_MAX)
I64_MIN = np.int64(-(1 << 62))  # headroom so comparisons can't overflow


# ---------------------------------------------------------------------------
# Static spec
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class EngineSpec:
    """Trace-time-static description of the registered windows/aggregations.

    ``periods``: slide/size of every time-measure tumbling/sliding window —
    their union grid defines the fixed slice edges (StreamSlicer.java:103-116).
    ``bands``: (start, size) of time-measure fixed-band windows (their two
    one-shot edges, FixedBandWindow.java:36-48).
    ``count_periods``: count-measure window grids (StreamSlicer.java:88-101).
    ``aggs``: device realization of each aggregation, in registration order.
    ``session_gaps``: gaps of session windows (pure-session device path).
    """

    periods: tuple[int, ...]
    bands: tuple[tuple[int, int], ...]
    count_periods: tuple[int, ...]
    aggs: tuple[DeviceAggregateSpec, ...]
    session_gaps: tuple[int, ...] = ()
    #: (period, offset) residue grids: window END edges of sliding windows
    #: whose size is not a multiple of their slide land at
    #: k*slide + (size % slide) — off the slide grid. Adding these edges to
    #: the slice grid keeps every window boundary on a slice edge, so range
    #: queries are EXACT. Deliberate deviation from the reference, which
    #: slices on the slide grid only and silently DROPS the straddling
    #: slice's in-window tuples (AggregateWindowState.java:25-31 t_last
    #: containment) — see VERDICT r1 item 6.
    offset_periods: tuple[tuple[int, int], ...] = ()

    @property
    def has_time_grid(self) -> bool:
        return bool(self.periods or self.bands or self.offset_periods)

    @property
    def pure_session(self) -> bool:
        return bool(self.session_gaps) and not self.has_time_grid \
            and not self.count_periods


def collapse_periods(periods) -> tuple:
    """Many-window grids: slicing on the union of N period grids costs N
    int64 mods per tuple (emulated int64 makes this the per-tuple hot cost
    at e.g. 1000 random tumbling windows). The GCD grid is a SUPERSET of
    every period grid — every window edge is a multiple of its period,
    hence of the gcd — so slicing on it alone is exactly as correct (finer
    slices, same range-query answers). Collapse when the period count is
    large; keep the union for few windows (their union grid is sparser
    than the gcd's, fewer slices)."""
    import math

    ps = tuple(sorted(set(int(p) for p in periods)))
    if len(ps) <= 32:
        return ps
    g = 0
    for p in ps:
        g = math.gcd(g, p)
    return (max(1, g),)


def grid_start(spec: EngineSpec, ts: jnp.ndarray) -> jnp.ndarray:
    """Latest union-grid point ≤ ts (vectorized; [B] -> [B]).

    Equivalent to the latest slice edge the reference would have placed at or
    before ts. Clamped to ≥ 0 to mirror the reference's initial slice at 0
    (SliceManager.java empty-store bootstrap) — device streams use ts ≥ 0.
    """
    cands = [jnp.zeros_like(ts)]
    if spec.periods:
        # chunk the period axis so [B, K] temporaries stay bounded when many
        # concurrent windows are registered (e.g. 1000 random tumbling sizes)
        pall = np.asarray(sorted(set(spec.periods)), dtype=np.int64)
        for i in range(0, len(pall), 128):
            p = jnp.asarray(pall[i:i + 128])
            cands.append(jnp.max(ts[:, None] - jnp.mod(ts[:, None], p[None, :]),
                                 axis=1))
    for (p, r) in spec.offset_periods:
        # largest point ≤ ts congruent to r (mod p), clamped to ≥ 0
        cands.append(jnp.maximum(ts - jnp.mod(ts - r, p), 0))
    for (bs, bsz) in spec.bands:
        c = jnp.where(ts >= bs + bsz, jnp.int64(bs + bsz),
                      jnp.where(ts >= bs, jnp.int64(bs), jnp.int64(0)))
        cands.append(c)
    if spec.session_gaps:
        # session slice edges are data-dependent; handled by the session path
        pass
    return functools.reduce(jnp.maximum, cands)


def host_grid_start(spec: EngineSpec, ts: np.ndarray) -> np.ndarray:
    """Numpy mirror of :func:`grid_start` for host-side cut calculus
    (the out-of-order count+time mixed path precomputes per-lane slice
    assignments in arrival order — see operator._mixed_cut_calculus)."""
    ts = np.asarray(ts, dtype=np.int64)
    best = np.zeros_like(ts)
    for p in spec.periods:
        np.maximum(best, ts - ts % np.int64(p), out=best)
    for (p, r) in spec.offset_periods:
        np.maximum(best, np.maximum(ts - (ts - r) % np.int64(p), 0),
                   out=best)
    for (bs, bsz) in spec.bands:
        c = np.where(ts >= bs + bsz, np.int64(bs + bsz),
                     np.where(ts >= bs, np.int64(bs), np.int64(0)))
        np.maximum(best, c, out=best)
    return best


def host_count_grid(spec: EngineSpec, c: np.ndarray) -> np.ndarray:
    """Numpy mirror of the ingest kernel's count-grid function ``cgs``."""
    c2 = np.maximum(np.asarray(c, dtype=np.int64), 0)
    best = np.zeros_like(c2)
    for p in spec.count_periods:
        np.maximum(best, c2 - c2 % np.int64(p), out=best)
    return best


def next_edge(spec: EngineSpec, s: jnp.ndarray) -> jnp.ndarray:
    """Earliest union-grid point strictly > s — the closing edge of a slice
    opened at s (SliceManager.appendSlice end bookkeeping)."""
    cands = [jnp.full_like(s, I64_MAX)]
    if spec.periods:
        pall = np.asarray(sorted(set(spec.periods)), dtype=np.int64)
        for i in range(0, len(pall), 128):
            p = jnp.asarray(pall[i:i + 128])
            cands.append(jnp.min(s[:, None] - jnp.mod(s[:, None], p[None, :])
                                 + p[None, :], axis=1))
    for (p, r) in spec.offset_periods:
        # smallest point > s congruent to r (mod p)
        cands.append(s + p - jnp.mod(s - r, p))
    for (bs, bsz) in spec.bands:
        for pt in (bs, bs + bsz):
            c = jnp.where(s < pt, jnp.int64(pt), I64_MAX)
            cands.append(c)
    return functools.reduce(jnp.minimum, cands)


# ---------------------------------------------------------------------------
# Device state
# ---------------------------------------------------------------------------


class SliceBufferState(NamedTuple):
    """The slice store as a pytree of device arrays (one key shard).

    Sorted main buffer [C] + unsorted out-of-order annex [A]; scalar clocks
    mirror WindowManager/StreamSlicer bookkeeping (WindowManager.java:16-33,
    StreamSlicer.java:27-34).
    """

    starts: jnp.ndarray        # i64[C] slice start edge; LONG_MAX = unused
    ends: jnp.ndarray          # i64[C] closing grid edge (informational)
    t_first: jnp.ndarray       # i64[C] min observed record ts
    t_last: jnp.ndarray        # i64[C] max observed record ts
    c_start: jnp.ndarray       # i64[C] arrival index of first record (count measure)
    counts: jnp.ndarray        # i64[C] records per slice
    partials: tuple            # per agg: f32[C, width]
    ax_starts: jnp.ndarray     # i64[A] annex slice starts (unsorted)
    ax_counts: jnp.ndarray     # i64[A]
    ax_partials: tuple         # per agg: f32[A, width]
    n_slices: jnp.ndarray      # i32 scalar
    n_annex: jnp.ndarray       # i32 scalar
    max_event_time: jnp.ndarray  # i64 scalar
    current_count: jnp.ndarray   # i64 scalar
    overflow: jnp.ndarray        # bool scalar — capacity exhausted


def init_state(spec: EngineSpec, capacity: int, annex_capacity: int,
               dtype=jnp.float32) -> SliceBufferState:
    C, A = capacity, annex_capacity
    return SliceBufferState(
        starts=jnp.full((C,), I64_MAX, dtype=jnp.int64),
        ends=jnp.full((C,), I64_MAX, dtype=jnp.int64),
        t_first=jnp.full((C,), I64_MAX, dtype=jnp.int64),
        t_last=jnp.full((C,), I64_MIN, dtype=jnp.int64),
        c_start=jnp.full((C,), I64_MAX, dtype=jnp.int64),
        counts=jnp.zeros((C,), dtype=jnp.int64),
        partials=tuple(jnp.full((C, a.width), a.identity, dtype=dtype)
                       for a in spec.aggs),
        ax_starts=jnp.full((A,), I64_MAX, dtype=jnp.int64),
        ax_counts=jnp.zeros((A,), dtype=jnp.int64),
        ax_partials=tuple(jnp.full((A, a.width), a.identity, dtype=dtype)
                          for a in spec.aggs),
        n_slices=jnp.int32(0),
        n_annex=jnp.int32(0),
        max_event_time=jnp.int64(I64_MIN),
        current_count=jnp.int64(0),
        overflow=jnp.bool_(False),
    )


def _combine_scatter(arr: jnp.ndarray, pos: jnp.ndarray, vals: jnp.ndarray,
                     kind: str) -> jnp.ndarray:
    """Duplicate-index scatter with the aggregation's combine — this IS the
    in-slice fold of AggregateValueState.addElement (AggregateValueState.java:23-31),
    batched."""
    if kind == "sum":
        return arr.at[pos].add(vals)
    if kind == "min":
        return arr.at[pos].min(vals)
    if kind == "max":
        return arr.at[pos].max(vals)
    raise ValueError(f"unknown combine kind {kind!r}")


def _lift(agg: DeviceAggregateSpec, vals: jnp.ndarray, valid: jnp.ndarray):
    """Apply the aggregation's vectorized lift, masking padded lanes to the
    combine identity. Returns (dense[B, w], None) or (None, (col[B], val[B]))."""
    if agg.is_sparse:
        col, v = agg.lift_sparse(vals)
        v = jnp.where(valid, v, agg.identity)
        return None, (col, v)
    lifted = agg.lift_dense(vals)
    lifted = jnp.where(valid[:, None], lifted, agg.identity)
    return lifted, None


# ---------------------------------------------------------------------------
# Ingest kernel
# ---------------------------------------------------------------------------


def build_ingest(spec: EngineSpec, capacity: int, annex_capacity: int,
                 assume_inorder: bool = False,
                 with_cut_starts: bool = False):
    """Batched in-order + late-tuple ingest.

    Replaces the per-tuple hot loop StreamSlicer.determineSlices →
    SliceManager.processElement (SURVEY.md §3.1) with one fused device
    program over a [B] batch. Requirements: ``ts`` ascending within the batch
    (the host driver sorts when out-of-order is enabled) and every ts within
    ``max_lateness`` of the stream's max event time (reference contract,
    WindowOperator.java:31-37).

    ``assume_inorder=True`` compiles out the late/annex machinery — for
    callers that guarantee a fully ascending stream (e.g. the fused pipeline
    whose device generator is ascending by construction).

    ``with_cut_starts=True`` (count-measure workloads) adds a fifth input:
    per-lane count-cut slice starts precomputed by the host in ARRIVAL
    order (``max(met, arrival_ts[0..j-1])`` for the lane cutting at count
    offset ``j``) — the reference appends count-cut slices at its
    arrival-order ``maxEventTime`` (StreamSlicer.java:37-44), which a
    ts-sorted batch cannot reconstruct on device.
    """
    C, A = capacity, annex_capacity

    def ingest(state: SliceBufferState, ts: jnp.ndarray, vals: jnp.ndarray,
               valid: jnp.ndarray,
               cut_starts: jnp.ndarray = None) -> SliceBufferState:
        B = ts.shape[0]
        s = grid_start(spec, ts)

        n = state.n_slices
        open_start = jnp.where(
            n > 0, state.starts[jnp.maximum(n - 1, 0)], jnp.int64(I64_MIN))

        # ---- split batch: in-order tail vs late tuples -------------------
        # The reference's in-order predicate: te >= maxEventTime
        # (StreamSlicer.java:139-141). The host driver ts-sorts each batch,
        # so late tuples form a prefix relative to the stream's max event
        # time at batch entry. A late tuple with ts >= open_start folds into
        # the OPEN slice (the reference's covering-slice insert,
        # SliceManager.java:64-76) — comparing on ts, not grid_start(ts),
        # matters after a dynamic window addition where the open slice is
        # coarser than the current union grid (grid_start(ts) can exceed
        # open_start while ts sits inside the open slice's span; opening a
        # new slice there would interleave slice spans and break the
        # t_last sort order the query's containment bound relies on).
        if assume_inorder:
            late = jnp.zeros_like(valid)
            pin = jnp.zeros_like(valid)
        else:
            behind = valid & (ts < state.max_event_time)
            late = behind & (ts < open_start)
            pin = behind & ~late

        # ---- count-measure edges (StreamSlicer.java:37-44,88-101) --------
        # Arrival index of each tuple (count before insertion); a count edge
        # is cut when the latest count-grid point changes between consecutive
        # arrivals. The new slice starts at the cutting tuple's event ts —
        # the reference starts count-cut slices at maxEventTime.
        c_idx = (state.current_count
                 + jnp.cumsum(valid.astype(jnp.int64)) - valid)
        if spec.count_periods:
            cp = jnp.asarray(np.asarray(spec.count_periods, dtype=np.int64))

            def cgs(c):
                c2 = jnp.maximum(c, 0)
                return jnp.max(c2[:, None] - jnp.mod(c2[:, None], cp[None, :]),
                               axis=1)

            count_flag = valid & (c_idx > 0) & (cgs(c_idx) > cgs(c_idx - 1))
        else:
            count_flag = jnp.zeros_like(valid)

        # ---- in-order segment path (SURVEY.md §3.1) ----------------------
        # A count-cut slice starts at the PREVIOUS max event time — the
        # reference appends it at maxEventTime before updating it
        # (StreamSlicer.java:37-44,84-85); a same-tuple time edge may push
        # the start further (the intermediate slice would be empty).
        prev_ts = jnp.concatenate(
            [jnp.where(state.max_event_time == I64_MIN, ts[:1],
                       state.max_event_time[None]), ts[:-1]])
        if spec.pure_session:
            # pure-session slicing (eager session case,
            # SliceFactory.java:17-22): a new slice — which IS a session —
            # opens when the inter-arrival gap exceeds the session gap
            # (SessionContext.updateContext, SessionWindow.java:40-84,
            # in-order specialization). Slice start = first tuple's ts.
            gap = jnp.int64(spec.session_gaps[0])
            first_ever = (jnp.arange(B) == 0) & (n == 0)
            newflag = valid & (first_ever | (ts - prev_ts > gap))
            io_s = ts
            k = jnp.cumsum(newflag.astype(jnp.int32))
            pos = jnp.clip((n - 1) + k, 0, C - 1)
            overflow = state.overflow | (((n - 1) + k[-1]) >= C)
            io_valid = valid
            one = jnp.where(io_valid, jnp.int64(1), jnp.int64(0))
            starts = state.starts.at[pos].min(jnp.where(valid, io_s, I64_MAX))
            ends = state.ends
            counts = state.counts.at[pos].add(one)
            t_last = state.t_last.at[pos].max(
                jnp.where(io_valid, ts, I64_MIN))
            t_first = state.t_first.at[pos].min(
                jnp.where(io_valid, ts, I64_MAX))
            c_start = state.c_start.at[pos].min(
                jnp.where(io_valid, c_idx, I64_MAX))
            partials = []
            for agg, part in zip(spec.aggs, state.partials):
                dense, sparse = _lift(agg, vals, io_valid)
                if sparse is None:
                    part = _combine_scatter(part, pos, dense, agg.kind)
                else:
                    col, v = sparse
                    part = _combine_scatter(part, (pos, col), v, agg.kind)
                partials.append(part)
            return state._replace(
                starts=starts, ends=ends, t_first=t_first, t_last=t_last,
                c_start=c_start, counts=counts, partials=tuple(partials),
                n_slices=(n + k[-1]).astype(jnp.int32),
                max_event_time=jnp.maximum(
                    state.max_event_time,
                    jnp.max(jnp.where(valid, ts, I64_MIN))),
                current_count=state.current_count
                + jnp.sum(valid.astype(jnp.int64)),
                overflow=overflow,
            )
        # late AND pinned lanes anchored to the open slice: late lanes so
        # they never trigger a spurious edge (they're io_valid-masked),
        # pinned lanes because they genuinely insert there
        io_s = jnp.where(late | pin, open_start, s)
        # count-cut slices start at the RUNNING MAX event time (the
        # reference appends at maxEventTime, StreamSlicer.java:37-44): a
        # raw prev_ts would place a cut fired by a late lane BELOW earlier
        # starts and break the sorted-starts invariant the probe/GC
        # searchsorted on. For in-order batches cummax(prev_ts) == prev_ts;
        # disordered count batches pass exact arrival-order cut starts.
        run_max = cut_starts if with_cut_starts else jax.lax.cummax(prev_ts)
        io_s = jnp.where(count_flag & ~late, jnp.maximum(io_s, run_max),
                         io_s)
        prev = jnp.concatenate([open_start[None], io_s[:-1]])
        newflag = ((io_s > prev) | (count_flag & ~late)) & valid
        k = jnp.cumsum(newflag.astype(jnp.int32))
        pos = jnp.clip((n - 1) + k, 0, C - 1)
        overflow = state.overflow | (((n - 1) + k[-1]) >= C)

        io_valid = valid & ~late
        one = jnp.where(io_valid, jnp.int64(1), jnp.int64(0))
        if spec.count_periods and not spec.has_time_grid:
            # pure-count slices: only count-cutting lanes (and the stream's
            # first tuple, matching the reference's bootstrap-at-first-ts)
            # define a slice start. Non-cut lanes carry grid_start(ts) == 0,
            # and min-scattering that into the open slice would zero every
            # start — breaking the ts-based GC bound and watermark probe.
            first_lane = (jnp.arange(B) == 0) & (n == 0)
            start_val = jnp.where(count_flag & ~late, io_s,
                                  jnp.where(first_lane, ts, I64_MAX))
        else:
            start_val = io_s
        starts = state.starts.at[pos].min(
            jnp.where(valid, start_val, I64_MAX))
        # pinned lanes don't define a new slice: keep the open slice's
        # closing edge as recorded at creation (post-dynamic-addition it is
        # coarser than next_edge under the current union grid)
        ends = state.ends.at[pos].min(
            jnp.where(valid & ~pin & ~late, next_edge(spec, io_s), I64_MAX))
        counts = state.counts.at[pos].add(one)
        t_last = state.t_last.at[pos].max(jnp.where(io_valid, ts, I64_MIN))
        # int64 scatters cost ~100 ms per 1M lanes on v5e — only maintain
        # the fields something reads. t_first feeds nothing outside the
        # session branch; c_start only the count-measure probe/containment.
        if spec.count_periods:
            t_first = state.t_first.at[pos].min(
                jnp.where(io_valid, ts, I64_MAX))
            c_start = state.c_start.at[pos].min(
                jnp.where(io_valid, c_idx, I64_MAX))
        else:
            t_first = state.t_first
            c_start = state.c_start

        partials = []
        for agg, part in zip(spec.aggs, state.partials):
            dense, sparse = _lift(agg, vals, io_valid)
            if sparse is None:
                part = _combine_scatter(part, pos, dense, agg.kind)
            else:
                col, v = sparse
                part = _combine_scatter(part, (pos, col), v, agg.kind)
            partials.append(part)

        if assume_inorder:
            return SliceBufferState(
                starts=starts, ends=ends, t_first=t_first, t_last=t_last,
                c_start=c_start, counts=counts, partials=tuple(partials),
                ax_starts=state.ax_starts, ax_counts=state.ax_counts,
                ax_partials=state.ax_partials,
                n_slices=(n + k[-1]).astype(jnp.int32),
                n_annex=state.n_annex,
                max_event_time=jnp.maximum(
                    state.max_event_time,
                    jnp.max(jnp.where(valid, ts, I64_MIN))),
                current_count=state.current_count
                + jnp.sum(valid.astype(jnp.int64)),
                overflow=overflow,
            )

        # ---- late path ---------------------------------------------------
        # Covering main-buffer slice: the last slice with start <= ts whose
        # recorded closing edge still reaches past ts (ts < ends[lo]) — the
        # engine equivalent of findSliceIndexByTimestamp
        # (LazyAggregateStore.java:29-37). Under a static spec this equals
        # "a slice with start == grid_start(ts) exists"; after a dynamic
        # window addition it also covers pre-addition coarse slices, which
        # the reference likewise keeps folding late tuples into. If no
        # covering slice exists (the grid range was never materialized),
        # the tuple goes to the annex under the current union grid.
        new_state_partials = partials
        lo_raw = jnp.searchsorted(starts, ts, side="right") - 1
        lo = jnp.clip(lo_raw, 0, C - 1)
        covered = late & (lo_raw >= 0) & (starts[lo] <= ts) & (ts < ends[lo])
        cov_pos = jnp.where(covered, lo, C - 1)          # C-1 lane is masked
        cov_one = jnp.where(covered, jnp.int64(1), jnp.int64(0))
        counts = counts.at[cov_pos].add(cov_one)
        t_last = t_last.at[cov_pos].max(jnp.where(covered, ts, I64_MIN))
        if spec.count_periods:
            t_first = t_first.at[cov_pos].min(
                jnp.where(covered, ts, I64_MAX))
        partials2 = []
        for agg, part in zip(spec.aggs, new_state_partials):
            dense, sparse = _lift(agg, vals, covered)
            if sparse is None:
                part = _combine_scatter(part, cov_pos, dense, agg.kind)
            else:
                col, v = sparse
                part = _combine_scatter(part, (cov_pos, col), v, agg.kind)
            partials2.append(part)

        # Annex: late tuples with no covering slice, segmented by grid start.
        # The batch is ts-sorted, so equal grid starts are adjacent.
        ax = late & ~covered
        ax_prev = jnp.concatenate([jnp.full((1,), I64_MIN), s[:-1]])
        ax_new = ax & ((s != ax_prev)
                       | ~jnp.concatenate([jnp.zeros((1,), bool), ax[:-1]]))
        ax_k = jnp.cumsum(ax_new.astype(jnp.int32))
        ax_pos = jnp.clip(state.n_annex + ax_k - 1, 0, A - 1)
        ax_pos = jnp.where(ax, ax_pos, A - 1)
        overflow = overflow | ((state.n_annex + ax_k[-1]) > A)
        ax_one = jnp.where(ax, jnp.int64(1), jnp.int64(0))
        ax_starts = state.ax_starts.at[ax_pos].min(jnp.where(ax, s, I64_MAX))
        ax_counts = state.ax_counts.at[ax_pos].add(ax_one)
        ax_partials = []
        for agg, part in zip(spec.aggs, state.ax_partials):
            dense, sparse = _lift(agg, vals, ax)
            if sparse is None:
                part = _combine_scatter(part, ax_pos, dense, agg.kind)
            else:
                col, v = sparse
                part = _combine_scatter(part, (ax_pos, col), v, agg.kind)
            ax_partials.append(part)

        return SliceBufferState(
            starts=starts, ends=ends, t_first=t_first, t_last=t_last,
            c_start=c_start, counts=counts, partials=tuple(partials2),
            ax_starts=ax_starts, ax_counts=ax_counts,
            ax_partials=tuple(ax_partials),
            n_slices=(n + k[-1]).astype(jnp.int32),
            n_annex=(state.n_annex + ax_k[-1]).astype(jnp.int32),
            max_event_time=jnp.maximum(
                state.max_event_time,
                jnp.max(jnp.where(valid, ts, I64_MIN))),
            current_count=state.current_count
            + jnp.sum(valid.astype(jnp.int64)),
            overflow=overflow,
        )

    return ingest


def build_ingest_dense(spec: EngineSpec, capacity: int, runs: int,
                       pallas_fold: bool = False,
                       pallas_packed: bool = False):
    """In-order ingest without large scatters — the keyed/batched fast path.

    int64 scatters cost ~100 ms per 1M lanes on v5e (no native int64: XLA
    emulates with i32 pairs), which makes the generic kernel's per-field
    [B]-lane scatters the dominant ingest cost. In-order batches touch only
    a CONTIGUOUS run of slice rows [n-1, n-1+k_last], so when the host can
    bound the number of runs (``k_last < runs`` — it knows the batch's time
    span and the minimum grid period), every slice field reduces to

    * run boundaries: two vmapped ``searchsorted`` over the sorted run ids
      + gathers (t_last = ts at a run's last lane; start/end at its first),
    * sum-like partials: a [B, R] one-hot matmul (MXU),
    * min/max partials: a masked [B, R, w] reduction,
    * one tiny [R]-lane scatter per field into the buffer (R ≈ 8-64 rows vs
      B = 1M lanes — three orders of magnitude fewer scatter lanes).

    Contract (host-checked): ts ascending, all ts >= max_event_time, no
    count-measure or session windows, dense-lift aggregations, and the
    batch spans < ``runs`` new slices (the kernel raises the overflow flag
    if the bound is violated).

    ``pallas_fold=True`` (``EngineConfig.pallas_slice_merge``) replaces
    the per-run one-hot matmul / masked [B, R, w] reduction with the
    Pallas segmented-reduce kernel
    (:func:`scotty_tpu.pallas.build_segment_fold`): lane blocks stream
    HBM→VMEM double-buffered into one [R, w] accumulator — the tiny
    [R]-lane buffer scatter stays. Default OFF keeps this builder's
    lowering byte-identical. ``pallas_packed`` streams the lifted
    values as bf16 (toleranced, see ``pallas.packed_tolerance``).
    """
    C, R = capacity, runs

    def ingest(state: SliceBufferState, ts: jnp.ndarray, vals: jnp.ndarray,
               valid: jnp.ndarray) -> SliceBufferState:
        B = ts.shape[0]
        s = grid_start(spec, ts)
        n = state.n_slices
        open_start = jnp.where(
            n > 0, state.starts[jnp.maximum(n - 1, 0)], jnp.int64(I64_MIN))

        prev = jnp.concatenate([open_start[None], s[:-1]])
        newflag = (s > prev) & valid
        k = jnp.cumsum(newflag.astype(jnp.int32))          # run id per lane
        k_last = k[-1]
        row_n = jnp.sum(valid.astype(jnp.int32))           # valid prefix len

        r_idx = jnp.arange(R, dtype=jnp.int32)
        first = jnp.searchsorted(k, r_idx, side="left")
        last = jnp.minimum(
            jnp.searchsorted(k, r_idx, side="right") - 1, row_n - 1)
        cnt_r = jnp.maximum(last - first + 1, 0).astype(jnp.int64)
        live = cnt_r > 0

        t_last_r = ts[jnp.clip(last, 0, B - 1)]
        start_r = s[jnp.clip(first, 0, B - 1)]
        ends_r = next_edge(spec, start_r)

        rows = jnp.clip((n - 1) + r_idx, 0, C - 1)
        starts = state.starts.at[rows].min(
            jnp.where(live, start_r, I64_MAX))
        ends = state.ends.at[rows].min(jnp.where(live, ends_r, I64_MAX))
        counts = state.counts.at[rows].add(jnp.where(live, cnt_r, 0))
        t_last = state.t_last.at[rows].max(
            jnp.where(live, t_last_r, I64_MIN))

        partials = []
        for agg, part in zip(spec.aggs, state.partials):
            lifted, sparse = _lift(agg, vals, valid)
            assert sparse is None, "dense ingest needs dense-lift aggs"
            if pallas_fold:
                from ..pallas import build_segment_fold

                fold = build_segment_fold(
                    B, R, part.shape[1], agg.kind, agg.identity,
                    packed=pallas_packed)
                # invalid lanes alias run k_last with identity-masked
                # values (the _lift mask above), so their combine is a
                # no-op — same guarantee the live mask gives the XLA
                # branches below
                upd = fold(k, lifted).astype(part.dtype)
                part = _combine_scatter(part, rows, upd, agg.kind)
            elif agg.kind == "sum":
                oh = (k[:, None] == r_idx[None, :]).astype(part.dtype)
                upd = oh.T @ lifted                          # [R, w] — MXU
                upd = jnp.where(live[:, None], upd, 0)
                part = part.at[rows].add(upd)
            else:
                oh = k[:, None] == r_idx[None, :]            # [B, R]
                ident = jnp.asarray(agg.identity, part.dtype)
                masked = jnp.where(oh[:, :, None], lifted[:, None, :],
                                   ident)                    # [B, R, w]
                op_ = jnp.min if agg.kind == "min" else jnp.max
                upd = op_(masked, axis=0)                    # [R, w]
                upd = jnp.where(live[:, None], upd, ident)
                part = _combine_scatter(part, rows, upd, agg.kind)
            partials.append(part)

        return state._replace(
            starts=starts, ends=ends, counts=counts, t_last=t_last,
            partials=tuple(partials),
            n_slices=(n + k_last).astype(jnp.int32),
            max_event_time=jnp.maximum(
                state.max_event_time,
                jnp.max(jnp.where(valid, ts, I64_MIN))),
            current_count=state.current_count
            + jnp.sum(valid.astype(jnp.int64)),
            overflow=(state.overflow | (((n - 1) + k_last) >= C)
                      | (k_last > R - 1)),
        )

    return ingest


def build_ingest_rows(spec: EngineSpec, capacity: int):
    """Arrival-order ingest with host-precomputed slice assignment — the
    out-of-order count+time MIXED path.

    The reference handles a late tuple under a count measure by inserting
    it into its ts-covering slice and rippling the ts-max record of every
    later slice forward (SliceManager.java:64-86). The ripple is an
    insertion-sort step: after it, slice k holds exactly the ts-sorted
    ranks ``[c_start_k, c_start_k + counts_k)`` — for count+time mixes
    too, because ripples move ts-max records forward only, preserving the
    global content ordering, while the grid ``tStart`` edges stay put.
    The net slice-metadata effect of ANY tuple (late or in-order) is
    therefore: +1 record to the slice that is OPEN at its arrival, plus
    whatever new slices its arrival cuts (count edges for every tuple,
    StreamSlicer.java:37-44; time edges for in-order tuples only,
    StreamSlicer.java:47-82). The host computes those cuts in arrival
    order (operator._mixed_cut_calculus — it knows the running max event
    time, the open-slice start, and the running count); this kernel just
    scatters them. Aggregate VALUES are answered from the record buffer's
    rank ranges from then on (``build_query(..., mix_rec=True)``), so the
    partial-aggregate matrices are deliberately left stale.

    Inputs (arrival order, NOT ts-sorted): per-lane assigned row offset
    ``row_off`` (inclusive cut count — lane's row = n_slices-1+row_off),
    ``is_cut``, cut ``start`` values and the cutting lane's pre-insert
    global count ``cut_c``.
    """
    C = capacity

    def ingest(state: SliceBufferState, ts: jnp.ndarray,
               valid: jnp.ndarray, row_off: jnp.ndarray,
               is_cut: jnp.ndarray, cut_start: jnp.ndarray,
               cut_c: jnp.ndarray) -> SliceBufferState:
        # values are NOT taken: they live in the record buffer and every
        # answer on this path is a rank-range query — no point paying the
        # H2D transfer of a [B] float array that would only be discarded
        n = state.n_slices
        row = (n - 1).astype(jnp.int32) + row_off
        pos = jnp.clip(row, 0, C - 1)
        pos = jnp.where(valid, pos, C).astype(jnp.int32)  # sentinel + drop
        cut = valid & is_cut
        one = jnp.where(valid, jnp.int64(1), jnp.int64(0))
        counts = state.counts.at[pos].add(one, mode="drop")
        starts = state.starts.at[pos].min(
            jnp.where(cut, cut_start, I64_MAX), mode="drop")
        ends = state.ends.at[pos].min(
            jnp.where(cut, next_edge(spec, cut_start), I64_MAX),
            mode="drop")
        c_start = state.c_start.at[pos].min(
            jnp.where(cut, cut_c, I64_MAX), mode="drop")
        k_last = jnp.max(jnp.where(valid, row_off, 0))
        return state._replace(
            starts=starts, ends=ends, counts=counts, c_start=c_start,
            n_slices=(n + k_last).astype(jnp.int32),
            max_event_time=jnp.maximum(
                state.max_event_time,
                jnp.max(jnp.where(valid, ts, I64_MIN))),
            current_count=state.current_count
            + jnp.sum(valid.astype(jnp.int64)),
            overflow=state.overflow | (((n - 1) + k_last) >= C),
        )

    return ingest


# ---------------------------------------------------------------------------
# Query kernel (watermark final-merge)
# ---------------------------------------------------------------------------


def _range_combine(tbl: jnp.ndarray, lo: jnp.ndarray, length: jnp.ndarray,
                   op, ident, levels: int):
    """Min/max over row ranges [lo, lo+length) of ``tbl`` via a log-sweep
    sparse table: each query answered at level floor(log2(len)) with two
    gathers; the table doubles per level."""
    N = tbl.shape[0]
    kbits = jnp.where(
        length > 0,
        jnp.floor(jnp.log2(jnp.maximum(length, 1)
                           .astype(jnp.float64))).astype(jnp.int32),
        -1)
    res = jnp.full((lo.shape[0], tbl.shape[1]), ident, tbl.dtype)
    hi = lo + length
    for lvl in range(levels):
        size = 1 << lvl
        sel = (kbits == lvl)
        a = tbl[jnp.clip(lo, 0, N - 1)]
        b = tbl[jnp.clip(hi - size, 0, N - 1)]
        res = jnp.where(sel[:, None], op(a, b), res)
        if size < N:
            shifted = jnp.concatenate(
                [tbl[size:],
                 jnp.full((size, tbl.shape[1]), ident, tbl.dtype)])
            tbl = op(tbl, shifted)
    return res


def build_query(spec: EngineSpec, capacity: int, annex_capacity: int,
                record_capacity: int = 0, mix_rec: bool = False):
    """All triggered windows answered at once.

    Replaces LazyAggregateStore.aggregate's O(#slices × #windows) nested
    combine loop (LazyAggregateStore.java:83-111) with
    - prefix-sum range queries for sum-like partials,
    - a log-sweep sparse table for min/max-like partials,
    over the sorted slice buffer, plus a masked fold over the (small) annex.

    With ``record_capacity`` set (count-measure workloads), count-window
    VALUES come from ts-sorted rank ranges of the record buffer — the
    closed form of the reference's out-of-order ripple (see
    :class:`RecordBuffer`); slice counts still provide containment and
    emptiness.

    With ``mix_rec`` (count+time mixed workloads after a late tuple), TIME
    windows also answer from record rank ranges: the ripple re-aligns slice
    CONTENT to ts-sorted rank ranges (so the partial matrices are stale),
    and each slice's post-ripple ``tLast`` — what the reference's
    containment reads, AggregateWindowState.java:25-31 — is the ts of its
    last rank, ``rts[c_start + counts - 1 - base]``. The mix query also
    takes the trigger batch's scan bounds ``(min_ts, max_ts, min_count,
    max_count)``: the reference's final-merge loop only walks slices in
    ``[findSliceIndexByTimestamp(minTs) ∧ findSliceByCount(minCount),
    findSliceIndexByTimestamp(maxTs) ∨ findSliceByCount(maxCount)]``
    (LazyAggregateStore.java:83-92, WindowManager.java:98-118), and find*
    returns the LAST slice at a duplicated edge — so a non-empty slice
    whose start duplicates ``min_ts`` (count cut + time cut at one point)
    is SHADOWED out of every window of that batch. Reproduced exactly.
    """
    C, A = capacity, annex_capacity
    # levels must include log2(N) itself: a range spanning the WHOLE table
    # (length == N, N a power of two) is answered at that level
    L = max(1, C.bit_length())
    RC = record_capacity
    use_rec = RC > 0 and bool(spec.count_periods)
    Lr = max(1, RC.bit_length()) if use_rec else 0
    assert not (mix_rec and not use_rec), "mix_rec needs the record buffer"

    def answer(state: SliceBufferState, rec, ws: jnp.ndarray,
               we: jnp.ndarray, tmask: jnp.ndarray, is_count: jnp.ndarray,
               scan=None):
        lo_t = jnp.searchsorted(state.starts, ws, side="left")
        # Upper containment bound per the reference: a slice is covered iff
        # window.end > slice.tLast (AggregateWindowState.java:25-31).
        # When every window edge is a slice-grid point this equals
        # ``starts < we`` (records never cross next_edge), but after a
        # DYNAMIC window addition pre-addition slices are coarser than the
        # new union grid and may straddle new window boundaries — t_last
        # containment then excludes them exactly like the reference does.
        # t_last is nondecreasing over live rows (t_last[i] < starts[i+1]
        # <= t_last[i+1]); pad rows are masked to LONG_MAX to keep the
        # array sorted for searchsorted.
        live = jnp.arange(C) < state.n_slices
        if mix_rec:
            # post-ripple tLast, derived from the record buffer (stored
            # t_last is pre-ripple). Live rows always hold >= 1 record
            # (every cut lane lands in its own new row), so the derived
            # array is nondecreasing like rts itself.
            last_rank = jnp.clip(state.c_start + state.counts - 1 - rec.base,
                                 0, RC - 1)
            live_t_last = jnp.where(live, rec.rts[last_rank], I64_MAX)
        else:
            live_t_last = jnp.where(live, state.t_last, I64_MAX)
        hi_t = jnp.searchsorted(live_t_last, we, side="left")
        # Count containment (AggregateWindowState.java:25-31 Count branch):
        # window [ws, we] covers slices with c_start >= ws and
        # c_last = c_start + counts <= we; both arrays are nondecreasing
        # in-order, so the covered set is a contiguous index range.
        cs_end = jnp.where(state.c_start < I64_MAX,
                           state.c_start + state.counts, I64_MAX)
        lo_c = jnp.searchsorted(state.c_start, ws, side="left")
        hi_c = jnp.searchsorted(cs_end, we, side="right")
        lo = jnp.where(is_count, jnp.minimum(lo_c, hi_c), lo_t)
        hi = jnp.where(is_count, hi_c, hi_t)
        if mix_rec:
            # the reference's batch scan bounds (see docstring): find* walk
            # from the END, so duplicated edges resolve to the LAST slice
            # — searchsorted(side='right') - 1
            (min_ts, max_ts, min_count, max_count) = scan
            n1 = jnp.maximum(state.n_slices - 1, 0)
            si = jnp.minimum(
                jnp.maximum(
                    jnp.searchsorted(state.starts, min_ts, side="right") - 1,
                    0),
                jnp.searchsorted(state.c_start, min_count,
                                 side="right") - 1)
            si = jnp.maximum(si, 0)
            ei = jnp.maximum(
                jnp.minimum(
                    n1,
                    jnp.searchsorted(state.starts, max_ts, side="right") - 1),
                jnp.searchsorted(state.c_start, max_count,
                                 side="right") - 1)
            lo = jnp.maximum(lo, si)
            hi = jnp.minimum(hi, ei + 1)
        # a coarse pre-addition slice spanning the whole window gives
        # hi < lo (start < ws and t_last >= we): the window covers nothing
        hi = jnp.maximum(hi, lo)
        length = hi - lo

        cnt_prefix = jnp.concatenate(
            [jnp.zeros((1,), jnp.int64), jnp.cumsum(state.counts)])
        cnt = cnt_prefix[hi] - cnt_prefix[lo]

        # The annex is guaranteed empty here: the host dispatches the
        # annex-merge kernel before any query once a late tuple was ingested
        # (an O(T × A) masked annex scan in this kernel costs seconds at
        # benchmark trigger counts — measured 2.2 s at T=65k, A=4k).
        if use_rec:
            live_r = jnp.arange(RC) < rec.n
            # rank range of the covered slices: c_start of the first covered
            # slice (absolute counts) → buffer row; extent = covered count
            rlo = jnp.clip(state.c_start[jnp.clip(lo, 0, C - 1)] - rec.base,
                           0, RC)
            rec_rows = (jnp.ones_like(is_count) if mix_rec else is_count)
            rlen = jnp.where(rec_rows, jnp.clip(cnt, 0, RC - rlo), 0)

        results = []
        for agg, part in zip(spec.aggs, state.partials):
            op = jnp.minimum if agg.kind == "min" else jnp.maximum
            ident = jnp.asarray(agg.identity, part.dtype)
            if mix_rec:
                res = None          # partials are stale; records only
            elif agg.kind == "sum":
                P = jnp.concatenate(
                    [jnp.zeros((1, part.shape[1]), part.dtype),
                     jnp.cumsum(part, axis=0)])
                res = P[hi] - P[lo]
            else:
                res = _range_combine(part, lo, length, op, agg.identity, L)
            if use_rec:
                # count windows: aggregate the ts-sorted rank range directly
                if agg.is_sparse:
                    col, v = agg.lift_sparse(rec.rvals)
                    lifted = jnp.full((RC, part.shape[1]), agg.identity,
                                      part.dtype)
                    lifted = _combine_scatter(
                        lifted, (jnp.arange(RC), col),
                        jnp.where(live_r, v, agg.identity), agg.kind)
                else:
                    lifted = agg.lift_dense(rec.rvals)
                    lifted = jnp.where(live_r[:, None], lifted, agg.identity)
                if agg.kind == "sum":
                    Pr = jnp.concatenate(
                        [jnp.zeros((1, part.shape[1]), part.dtype),
                         jnp.cumsum(lifted, axis=0)])
                    rres = Pr[rlo + rlen] - Pr[rlo]
                else:
                    rres = _range_combine(lifted, rlo, rlen, op,
                                          agg.identity, Lr)
                res = rres if mix_rec \
                    else jnp.where(is_count[:, None], rres, res)
            results.append(jnp.where(tmask[:, None], res, ident))

        return jnp.where(tmask, cnt, 0), tuple(results)

    if mix_rec:
        def query(state, rec, ws, we, tmask, is_count,
                  min_ts, max_ts, min_count, max_count):
            return answer(state, rec, ws, we, tmask, is_count,
                          (min_ts, max_ts, min_count, max_count))
    elif use_rec:
        def query(state, rec, ws, we, tmask, is_count):
            return answer(state, rec, ws, we, tmask, is_count)
    else:
        def query(state, ws, we, tmask, is_count):
            return answer(state, None, ws, we, tmask, is_count)
    return query


# ---------------------------------------------------------------------------
# GC / annex-merge kernel
# ---------------------------------------------------------------------------


def build_annex_merge(spec: EngineSpec, capacity: int, annex_capacity: int):
    """Fold the out-of-order annex back into the sorted main buffer.

    Re-sorts the concatenated (main ++ annex) buffer by start — annex entries
    either coincide with an existing start (combine) or fill a
    previously-empty grid range (insert). The host dispatches this only on
    watermarks after a late tuple actually entered the annex (the device
    sort is expensive on TPU), so in-order streams never pay for it.
    """
    C, A = capacity, annex_capacity

    def merge(st: SliceBufferState) -> SliceBufferState:
        cat_starts = jnp.concatenate([st.starts, st.ax_starts])
        order = jnp.argsort(cat_starts)          # stable; LONG_MAX sinks
        sorted_starts = cat_starts[order]
        # coincident starts → combine into one slice: segment by value
        prev = jnp.concatenate([jnp.full((1,), I64_MIN), sorted_starts[:-1]])
        newflag = (sorted_starts > prev) & (sorted_starts < I64_MAX)
        seg = jnp.cumsum(newflag.astype(jnp.int32)) - 1      # [C+A]
        seg = jnp.clip(seg, 0, C - 1)
        n_new = jnp.max(jnp.where(newflag, seg + 1, 0)).astype(jnp.int32)

        uniq_starts = jnp.full((C,), I64_MAX, jnp.int64).at[seg].min(
            jnp.where(newflag, sorted_starts, I64_MAX))
        cat_ends = jnp.concatenate([st.ends, next_edge(spec, st.ax_starts)])
        uniq_ends = jnp.full((C,), I64_MAX, jnp.int64).at[seg].min(
            cat_ends[order])
        cat_tf = jnp.concatenate([st.t_first, st.ax_starts])
        uniq_tf = jnp.full((C,), I64_MAX, jnp.int64).at[seg].min(cat_tf[order])
        # pad annex rows hold I64_MAX starts; mask them to I64_MIN or the
        # max-scatter below would poison the last real slice's t_last
        cat_tl = jnp.concatenate(
            [st.t_last, jnp.where(st.ax_starts < I64_MAX, st.ax_starts,
                                  I64_MIN)])
        uniq_tl = jnp.full((C,), I64_MIN, jnp.int64).at[seg].max(cat_tl[order])
        cat_cnt = jnp.concatenate([st.counts, st.ax_counts])
        uniq_cnt = jnp.zeros((C,), jnp.int64).at[seg].add(cat_cnt[order])
        cat_cs = jnp.concatenate(
            [st.c_start, jnp.full((A,), I64_MAX, jnp.int64)])
        uniq_cs = jnp.full((C,), I64_MAX, jnp.int64).at[seg].min(
            cat_cs[order])

        new_partials = []
        for agg, part, ax_part in zip(spec.aggs, st.partials,
                                      st.ax_partials):
            cat = jnp.concatenate([part, ax_part])[order]
            tgt = jnp.full((C, part.shape[1]), agg.identity, part.dtype)
            new_partials.append(_combine_scatter(tgt, seg, cat, agg.kind))

        return st._replace(
            starts=uniq_starts, ends=uniq_ends, t_first=uniq_tf,
            t_last=uniq_tl, counts=uniq_cnt, c_start=uniq_cs,
            partials=tuple(new_partials),
            ax_starts=jnp.full((A,), I64_MAX, jnp.int64),
            ax_counts=jnp.zeros((A,), jnp.int64),
            ax_partials=tuple(
                jnp.full((A, a.width), a.identity, p.dtype)
                for a, p in zip(spec.aggs, st.ax_partials)),
            n_slices=n_new, n_annex=jnp.int32(0),
        )

    return merge


def build_gc(spec: EngineSpec, capacity: int, annex_capacity: int):
    """Drop slices behind the GC bound (WindowManager.clearAfterWatermark,
    WindowManager.java:82-95 -> LazyAggregateStore.removeSlices :138-146):
    a masked roll of the buffer. Assumes the annex was merged first when
    non-empty."""
    C, A = capacity, annex_capacity

    def gc(state: SliceBufferState, bound: jnp.ndarray) -> SliceBufferState:
        # ---- drop slices behind the bound --------------------------------
        # keep the slice covering `bound` (removeSlices deletes [0, index)).
        idx = jnp.searchsorted(state.starts, bound, side="right") - 1
        k = jnp.clip(idx, 0, jnp.maximum(state.n_slices - 1, 0)).astype(jnp.int32)

        def roll(a, fill):
            rolled = jnp.roll(a, -k, axis=0)
            keep = jnp.arange(a.shape[0]) < (a.shape[0] - k)
            if a.ndim == 1:
                return jnp.where(keep, rolled, fill)
            return jnp.where(keep[:, None], rolled, fill)

        return state._replace(
            starts=roll(state.starts, I64_MAX),
            ends=roll(state.ends, I64_MAX),
            t_first=roll(state.t_first, I64_MAX),
            t_last=roll(state.t_last, I64_MIN),
            c_start=roll(state.c_start, I64_MAX),
            counts=roll(state.counts, 0),
            partials=tuple(roll(p, a.identity)
                           for a, p in zip(spec.aggs, state.partials)),
            n_slices=state.n_slices - k,
        )

    return gc

# ---------------------------------------------------------------------------
# Record buffer (count-measure workloads)
# ---------------------------------------------------------------------------


class RecordBuffer(NamedTuple):
    """Raw (ts, value) records in ascending-ts order — retained only while
    count-measure windows are registered, mirroring the reference's lazy
    record retention (SliceFactory.java:17-22: count measure forces lazy
    slices). Count windows aggregate ts-sorted RANK ranges: the reference's
    out-of-order ripple (SliceManager.java:77-85) shifts the ts-max element
    of every later slice forward so each slice keeps its fixed count range —
    i.e. after any repairs, slice k holds exactly the ts-sorted ranks
    ``[c_start_k, c_start_k + counts_k)``. The engine answers count windows
    directly from this buffer instead of materializing the shifts."""

    rts: jnp.ndarray      # i64[RC] record timestamps, ascending; pad I64_MAX
    rvals: jnp.ndarray    # f32[RC] record values
    n: jnp.ndarray        # i32 scalar — live record count
    base: jnp.ndarray     # i64 scalar — absolute count index of row 0
    overflow: jnp.ndarray


def init_records(record_capacity: int) -> RecordBuffer:
    RC = record_capacity
    return RecordBuffer(
        rts=jnp.full((RC,), I64_MAX, dtype=jnp.int64),
        rvals=jnp.zeros((RC,), dtype=jnp.float32),
        n=jnp.int32(0),
        base=jnp.int64(0),
        overflow=jnp.bool_(False),
    )


def build_record_merge(record_capacity: int):
    """Merge a ts-sorted batch into the sorted record buffer (stable:
    existing records precede batch records at equal ts — insertion order,
    like the reference's TreeSet walk)."""
    RC = record_capacity

    def merge(rec: RecordBuffer, ts: jnp.ndarray, vals: jnp.ndarray,
              valid: jnp.ndarray) -> RecordBuffer:
        B = ts.shape[0]
        n = rec.n
        live = jnp.arange(RC) < n
        bts = jnp.where(valid, ts, I64_MAX)
        nb = jnp.sum(valid.astype(jnp.int32))
        # final position of each existing record: own rank + batch records
        # strictly before it (ties: batch goes after → side='left')
        pos_old = jnp.arange(RC) + jnp.searchsorted(bts, rec.rts,
                                                    side="left")
        pos_old = jnp.where(live, pos_old, RC)          # dead rows drop
        # final position of each batch record: own rank + existing records
        # at-or-before it (side='right')
        pos_new = jnp.arange(B) + jnp.searchsorted(
            jnp.where(live, rec.rts, I64_MAX), bts, side="right")
        pos_new = jnp.where(valid, pos_new, RC)
        rts = jnp.full((RC,), I64_MAX, jnp.int64)
        rts = rts.at[pos_old].set(rec.rts, mode="drop")
        rts = rts.at[pos_new].set(bts, mode="drop")
        rvals = jnp.zeros((RC,), rec.rvals.dtype)
        rvals = rvals.at[pos_old].set(rec.rvals, mode="drop")
        rvals = rvals.at[pos_new].set(vals.astype(rec.rvals.dtype),
                                      mode="drop")
        return RecordBuffer(
            rts=rts, rvals=rvals, n=(n + nb).astype(jnp.int32),
            base=rec.base, overflow=rec.overflow | ((n + nb) > RC))

    return merge


def build_record_append(record_capacity: int):
    """In-order record append: a ts-sorted batch at/above the stream's max
    event time lands as one contiguous ``dynamic_update_slice`` — O(B),
    versus the general rank merge's O(RC) int64 scatters (~113 ms per M
    lanes on v5e), which made every in-order count batch pay the whole
    buffer (r4). Pad lanes are written beyond ``n + nb`` and are dead:
    every record reader masks by ``rec.n``. The write block must fit —
    ``overflow`` is raised with one batch of headroom, since a clamped
    ``dynamic_update_slice`` would land misaligned."""
    RC = record_capacity

    def append(rec: RecordBuffer, ts: jnp.ndarray, vals: jnp.ndarray,
               valid: jnp.ndarray) -> RecordBuffer:
        B = ts.shape[0]
        nb = jnp.sum(valid.astype(jnp.int32))
        if B > RC:
            # tiny buffers (tests): the contiguous block can't fit the
            # operand — fall back to a [B]-lane drop-mode scatter
            pos = rec.n + jnp.arange(B, dtype=jnp.int32)
            pos = jnp.where(valid, pos, RC)
            rts = rec.rts.at[pos].set(ts, mode="drop")
            rvals = rec.rvals.at[pos].set(vals.astype(rec.rvals.dtype),
                                          mode="drop")
            ovf = rec.n + nb > RC
        else:
            rts = jax.lax.dynamic_update_slice(rec.rts, ts, (rec.n,))
            rvals = jax.lax.dynamic_update_slice(
                rec.rvals, vals.astype(rec.rvals.dtype), (rec.n,))
            ovf = rec.n + B > RC
        return RecordBuffer(
            rts=rts, rvals=rvals, n=(rec.n + nb).astype(jnp.int32),
            base=rec.base, overflow=rec.overflow | ovf)

    return append


def build_record_gc(capacity: int, record_capacity: int):
    """Drop records behind the slice-GC bound, keeping ranks aligned with
    the surviving slices: the new base is the first surviving slice's
    ``c_start`` (computed from the PRE-GC slice buffer, same bound as
    :func:`build_gc`)."""
    C, RC = capacity, record_capacity

    def rgc(state: SliceBufferState, rec: RecordBuffer,
            bound: jnp.ndarray) -> RecordBuffer:
        idx = jnp.searchsorted(state.starts, bound, side="right") - 1
        k = jnp.clip(idx, 0, jnp.maximum(state.n_slices - 1, 0))
        new_base = state.c_start[k]
        new_base = jnp.where(new_base < I64_MAX, new_base, rec.base)
        d = jnp.clip(new_base - rec.base, 0, RC).astype(jnp.int32)

        def roll(a, fill):
            rolled = jnp.roll(a, -d, axis=0)
            keep = jnp.arange(a.shape[0]) < (a.shape[0] - d)
            return jnp.where(keep, rolled, fill)

        return RecordBuffer(
            rts=roll(rec.rts, I64_MAX), rvals=roll(rec.rvals, 0),
            n=(rec.n - d).astype(jnp.int32), base=new_base,
            overflow=rec.overflow)

    return rgc


# ---------------------------------------------------------------------------
# Watermark → count probe
# ---------------------------------------------------------------------------


def build_count_probe(spec: EngineSpec, capacity: int,
                      record_capacity: int = 0):
    """Convert a watermark timestamp to a count bound for count-measure
    triggering (WindowManager.java:110-115): locate the slice covering the
    watermark; if its last observed record is at/after the watermark, step
    back one slice; the bound is that slice's last count.

    With ``record_capacity`` (the out-of-order count path), the slice's
    "last observed record" comes from the record buffer — after the
    reference's ripple, slice k's last record is the ts-sorted rank
    ``c_start_k + counts_k - 1``, whereas the arrival-order ``t_last``
    field keeps pre-ripple maxima."""
    RC = record_capacity

    def count_at(state: SliceBufferState, wm: jnp.ndarray) -> jnp.ndarray:
        idx = jnp.searchsorted(state.starts, wm, side="right") - 1
        idx = jnp.clip(idx, 0, capacity - 1)
        step = (state.t_last[idx] >= wm) & (idx > 0)
        idx = jnp.where(step, idx - 1, idx)
        return state.c_start[idx] + state.counts[idx]

    if not RC:
        return count_at

    def count_at_rec(state: SliceBufferState, rec: RecordBuffer,
                     wm: jnp.ndarray) -> jnp.ndarray:
        def t_last_of(i):
            r = jnp.clip(state.c_start[i] + state.counts[i] - 1 - rec.base,
                         0, RC - 1)
            return rec.rts[r]

        idx = jnp.searchsorted(state.starts, wm, side="right") - 1
        idx = jnp.clip(idx, 0, capacity - 1)
        step = (t_last_of(idx) >= wm) & (idx > 0)
        idx = jnp.where(step, idx - 1, idx)
        return state.c_start[idx] + state.counts[idx]

    return count_at_rec

# ---------------------------------------------------------------------------
# Session sweep (pure-session watermark path)
# ---------------------------------------------------------------------------


def build_session_sweep(spec: EngineSpec, capacity: int, emit_cap: int):
    """Trigger + emit + GC for the pure-session device path.

    Sessions whose ``t_last + gap < watermark`` are complete
    (SessionContext.triggerWindows, SessionWindow.java:107-116). In-order,
    completed sessions form a prefix of the slice buffer, so emission is a
    prefix gather of length m and GC is a roll by m. Emitted window bounds
    are ``[t_first, t_last + gap)``.

    Returns (new_state, m, starts[E], ends[E], counts[E], partials…[E]) with
    E = ``emit_cap`` static rows (rows ≥ m are padding).
    """
    C, E = capacity, emit_cap
    gap = int(spec.session_gaps[0])

    def sweep(state: SliceBufferState, wm: jnp.ndarray):
        live = jnp.arange(C) < state.n_slices
        done = live & (state.t_last + gap < wm)
        m = jnp.sum(done.astype(jnp.int32))        # prefix length
        idx = jnp.arange(E)
        sel = jnp.clip(idx, 0, C - 1)
        e_starts = jnp.where(idx < m, state.t_first[sel], I64_MAX)
        e_ends = jnp.where(idx < m, state.t_last[sel] + gap, I64_MAX)
        e_counts = jnp.where(idx < m, state.counts[sel], 0)
        e_partials = tuple(p[sel] for p in state.partials)
        em_overflow = m > E

        def roll(a, fill):
            rolled = jnp.roll(a, -m, axis=0)
            keep = jnp.arange(a.shape[0]) < (a.shape[0] - m)
            if a.ndim == 1:
                return jnp.where(keep, rolled, fill)
            return jnp.where(keep[:, None], rolled, fill)

        new_state = state._replace(
            starts=roll(state.starts, I64_MAX),
            ends=roll(state.ends, I64_MAX),
            t_first=roll(state.t_first, I64_MAX),
            t_last=roll(state.t_last, I64_MIN),
            c_start=roll(state.c_start, I64_MAX),
            counts=roll(state.counts, 0),
            partials=tuple(roll(p, a.identity)
                           for a, p in zip(spec.aggs, state.partials)),
            n_slices=state.n_slices - m,
            overflow=state.overflow | em_overflow,
        )
        return new_state, m, e_starts, e_ends, e_counts, e_partials

    return sweep
