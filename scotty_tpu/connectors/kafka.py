"""Kafka connector (import-gated).

Mirrors the reference kafkaStreams-connector: a Processor consuming keyed
records and forwarding window results
(kafkaStreams-connector/.../KeyedScottyWindowOperator.java:17-94, 100 ms
event-time tick). Requires ``kafka-python`` or ``confluent-kafka`` at
runtime; the adapter logic is complete and library-agnostic — it only needs
a consumer that yields records with key/value/timestamp.

Hardening (ISSUE 3): a record whose payload fails to deserialize —
non-UTF-8 bytes, non-JSON non-numeric text, missing fields — used to kill
the whole ``run()`` loop with an uncaught ``ValueError``. Deserialization
errors are now POISON records: counted (``resilience_poison_records``),
handed to an optional ``dead_letter(record, exc)`` callback, and skipped —
up to an optional ``poison_limit`` (an all-garbage stream should not fail
silently). An optional ``stall_timeout_s`` wraps the consumer in the
no-progress watchdog (``resilience_stall_events``).
"""

from __future__ import annotations

import json
from typing import Callable, Iterable, Optional, Tuple

from .base import KeyedScottyWindowOperator, PeriodicWatermarks


def _default_deserialize(record) -> Tuple:
    """(key, value, ts) from a Kafka record: JSON value with 'value' field,
    record timestamp as event time. Raises on payloads that are neither
    JSON nor numeric — ``run()`` routes that through the poison path."""
    key = record.key.decode() if isinstance(record.key, bytes) else record.key
    raw = record.value.decode() if isinstance(record.value, bytes) else record.value
    try:
        val = json.loads(raw)
        if isinstance(val, dict):
            val = val.get("value", val)
    except (json.JSONDecodeError, TypeError):
        val = float(raw)
    return key, val, int(record.timestamp)


def _poll_takes_timeout_ms(consumer) -> bool:
    """Detect the consumer's poll face ONCE, by signature: kafka-python
    takes ``timeout_ms=``, confluent_kafka takes positional SECONDS (its
    C-implemented method has no inspectable signature). Probing with a
    per-call try/except would swallow genuine ``TypeError``s raised
    inside a kafka-python poll and misroute every later call."""
    import inspect

    try:
        params = inspect.signature(consumer.poll).parameters
    except (TypeError, ValueError):      # C impl (confluent-style)
        return False
    return "timeout_ms" in params


def _poll_records(consumer, idle_poll_ms: int, clock=None,
                  stall_timeout_s=None, obs=None, on_stall=None):
    """Drive a Kafka consumer's poll face — ``poll(timeout_ms=...)``
    (kafka-python) or positional-seconds ``poll(timeout)``
    (confluent_kafka; see :func:`_poll_takes_timeout_ms`) — as
    an endless record iterator yielding :data:`~scotty_tpu.connectors.
    iterable.IDLE_TICK` on every empty poll — the idle tick that keeps
    bounded-delay flushes honest on silent topics. Only ``max_records``
    (or an external stop) ends a polling loop.

    Polling mode owns the stall watchdog itself: a post-hoc
    ``watchdog_source`` around this iterator would only ever see
    sub-``idle_poll_ms`` gaps (every empty poll yields a tick), so
    instead the QUIET time on the injectable clock accumulates across
    empty polls and every ``stall_timeout_s`` of it flags a stall (the
    ``queue_source`` discipline: a continuing stall keeps counting)."""
    from ..resilience.clock import SystemClock
    from ..resilience.connectors import flag_stall
    from .iterable import IDLE_TICK

    clock = clock or SystemClock()
    quiet_from = None
    poll_kw = _poll_takes_timeout_ms(consumer)
    while True:
        if poll_kw:
            polled = consumer.poll(timeout_ms=idle_poll_ms)
        else:
            polled = consumer.poll(idle_poll_ms / 1000.0)
        if not polled:
            if stall_timeout_s is not None:
                now = clock.now()
                if quiet_from is None:
                    quiet_from = now
                elif now - quiet_from > stall_timeout_s:
                    flag_stall(obs, "kafka_poll", now - quiet_from,
                               on_stall)
                    quiet_from = now     # a continuing stall re-flags
            yield IDLE_TICK
            continue
        quiet_from = None
        if isinstance(polled, dict):      # kafka-python: {tp: [records]}
            for records in polled.values():
                for r in records:
                    yield r
        else:                             # a bare record (confluent-style)
            yield polled


class KafkaScottyWindowOperator:
    """Consume a Kafka topic, window it, hand results to ``on_result``.

    The watermark default matches the reference kafka connector's 100 ms
    event-time tick (kafkaStreams-connector/.../KeyedScottyWindowOperator.java:25,62-77).
    """

    def __init__(self, operator: Optional[KeyedScottyWindowOperator] = None,
                 deserialize: Callable = _default_deserialize,
                 watermark_period_ms: int = 100,
                 obs=None):
        self.operator = operator or KeyedScottyWindowOperator(
            watermark_policy=PeriodicWatermarks(watermark_period_ms),
            obs=obs)
        if obs is not None and self.operator.obs is None:
            # a caller-supplied operator still gets the requested telemetry
            self.operator.obs = obs
        self.deserialize = deserialize
        #: the live ObsServer while run(serve_port=...) is looping
        self.obs_server = None

    def run(self, consumer: Iterable, on_result: Callable[[Tuple], None],
            max_records: Optional[int] = None,
            dead_letter: Optional[Callable] = None,
            poison_limit: Optional[int] = None,
            stall_timeout_s: Optional[float] = None,
            clock=None,
            serve_port: Optional[int] = None,
            health=None,
            shaper=None,
            control=None,
            idle_poll_ms: Optional[int] = None,
            ingest_ring=None,
            shed_callback: Optional[Callable] = None,
            sink=None) -> int:
        """``consumer``: any iterable of Kafka-like records (KafkaConsumer
        instances are iterables of ConsumerRecord). Returns records
        consumed (poison records count — they were consumed, then
        dead-lettered).

        A record whose ``deserialize`` raises is handled per the module
        docstring instead of killing the loop; ``stall_timeout_s`` flags
        no-progress gaps on the (injectable) ``clock``.

        ``serve_port`` (opt-in, ISSUE 4; needs an attached Observability)
        serves ``/metrics``·``/vars``·``/healthz`` for the duration of
        the loop — ``0`` binds an ephemeral port, read back from
        ``self.obs_server.port`` while running. ``health`` is the
        :class:`scotty_tpu.obs.HealthPolicy` behind ``/healthz`` (pass
        ``HealthPolicy(max_watermark_lag_ms=...)`` to arm the
        watermark-lag check; the default only watches stalls/overflows).

        ``shaper`` (a :class:`scotty_tpu.shaper.ShaperConfig`, ISSUE 5)
        attaches the coalescing/sorting front-end for the duration of
        the loop: records buffer into sorted blocks, the config's
        ``max_delay_ms`` deadline (on the injectable ``clock``) is
        evaluated as each record arrives — while the consumer iterator
        blocks on a silent topic there is no execution to evaluate it
        on — and anything still held drains through ``on_result`` at
        loop end.

        ``control`` (ISSUE 6) is the register/cancel control path shared
        with the iterable run loops: ``(after_records, command)`` rows,
        each ``command`` called with the operator once that many records
        were consumed (``lambda op: op.register_window(...)`` /
        ``op.cancel_window(...)``); any remainder fires at loop end.

        ``idle_poll_ms`` (ISSUE 7 satellite — the max_delay_ms honesty
        fix): when the consumer exposes Kafka's ``poll(timeout_ms=...)``
        face, the loop drives it in polling mode with that timeout; an
        empty poll is an IDLE TICK that evaluates the accumulator
        deadline (``poll_shaper``) and pumps the ingest ring, so a
        silent topic still flushes held records on time. In polling mode
        the loop only ends at ``max_records`` — set it (or stop
        externally). Plain iterables may yield the
        :data:`~scotty_tpu.connectors.iterable.IDLE_TICK` sentinel for
        the same effect.

        ``ingest_ring`` (a :class:`scotty_tpu.ingest.RingConfig`, ISSUE
        7) stages records through the bounded backpressure ring —
        block/shed/fail on full, exact ``ingest_ring_*`` accounting,
        block-at-a-time vectorized replay; ``shed_callback(vals, ts,
        keys)`` sees records a 'shed' policy dropped.

        ``sink`` (a :class:`scotty_tpu.delivery.TransactionalSink`,
        ISSUE 8) gates every ``on_result`` call through the exactly-once
        output boundary: replayed duplicates after a supervised restore
        are suppressed instead of delivered.
        """
        from ..resilience.connectors import PoisonHandler, watchdog_source
        from .iterable import (IDLE_TICK, _apply_control, _control_cursor,
                               _make_ring, _pop, _ring_polls_deadline)

        if sink is not None:
            downstream = on_result

            def on_result(item, _down=downstream, _sink=sink):
                if _sink.emit(item):
                    _down(item)
        if shaper is not None:
            self.operator.attach_shaper(shaper, clock=clock)
        poison = PoisonHandler(dead_letter=dead_letter, limit=poison_limit,
                               obs=self.operator.obs)
        if idle_poll_ms is not None and hasattr(consumer, "poll"):
            # polling mode carries its own stall accounting — wrapping
            # the tick stream in watchdog_source instead would measure
            # only sub-idle_poll_ms gaps and never flag a dead producer
            consumer = _poll_records(consumer, idle_poll_ms, clock=clock,
                                     stall_timeout_s=stall_timeout_s,
                                     obs=self.operator.obs)
        elif stall_timeout_s is not None:
            consumer = watchdog_source(consumer, stall_timeout_s,
                                       clock=clock, obs=self.operator.obs)
        ring = None
        ring_results: list = []
        if ingest_ring is not None:
            ring = _make_ring(ingest_ring, self.operator, True,
                              self.operator.obs, shed_callback,
                              ring_results)
        ring_poll = _ring_polls_deadline(self.operator, ring)
        self.obs_server = None
        if serve_port is not None and self.operator.obs is not None:
            self.obs_server = self.operator.obs.serve(port=serve_port,
                                                      health=health)
        n = 0
        ctl, nxt = _control_cursor(control)
        try:
            for record in consumer:
                if record is IDLE_TICK:       # idle tick (quiet topic)
                    if ring is not None:
                        ring.poll()
                        for item in _pop(ring_results):
                            on_result(item)
                    for item in self.operator.poll_shaper():
                        on_result(item)
                    continue
                if nxt is not None and n >= nxt[0] and ring is not None:
                    # control barrier: staged records land first
                    ring.drain()
                    for item in _pop(ring_results):
                        on_result(item)
                nxt = _apply_control(self.operator, ctl, nxt, n)
                n += 1
                try:
                    key, value, ts = self.deserialize(record)
                except Exception as e:   # noqa: BLE001 — poison boundary
                    poison.handle(record, e)
                else:
                    if ring is not None:
                        ring.offer_one(value, ts, key)
                        if ring_poll:   # per-arrival deadline parity
                            items = (_pop(ring_results)
                                     + self.operator.poll_shaper())
                        else:
                            items = _pop(ring_results)
                    else:
                        items = self.operator.process_element(key, value,
                                                              ts)
                    for item in items:
                        on_result(item)
                if max_records is not None and n >= max_records:
                    break
            if ring is not None:
                ring.drain()
                for item in _pop(ring_results):
                    on_result(item)
            nxt = _apply_control(self.operator, ctl, nxt, float("inf"))
            for item in self.operator.drain_shaper():
                on_result(item)
        finally:
            if self.obs_server is not None:
                self.obs_server.close()
                self.obs_server = None
        return n


def make_consumer(topic: str, bootstrap_servers: str = "localhost:9092",
                  **kwargs):
    """Create a real KafkaConsumer (requires kafka-python)."""
    try:
        from kafka import KafkaConsumer
    except ImportError as e:                      # pragma: no cover
        raise ImportError(
            "kafka-python is not installed; pass any iterable of records "
            "to KafkaScottyWindowOperator.run instead") from e
    return KafkaConsumer(topic, bootstrap_servers=bootstrap_servers, **kwargs)
