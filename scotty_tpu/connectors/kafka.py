"""Kafka connector (import-gated).

Mirrors the reference kafkaStreams-connector: a Processor consuming keyed
records and forwarding window results
(kafkaStreams-connector/.../KeyedScottyWindowOperator.java:17-94, 100 ms
event-time tick). Requires ``kafka-python`` or ``confluent-kafka`` at
runtime; the adapter logic is complete and library-agnostic — it only needs
a consumer that yields records with key/value/timestamp.
"""

from __future__ import annotations

import json
from typing import Callable, Iterable, Optional, Tuple

from .base import KeyedScottyWindowOperator, PeriodicWatermarks


def _default_deserialize(record) -> Tuple:
    """(key, value, ts) from a Kafka record: JSON value with 'value' field,
    record timestamp as event time."""
    key = record.key.decode() if isinstance(record.key, bytes) else record.key
    raw = record.value.decode() if isinstance(record.value, bytes) else record.value
    try:
        val = json.loads(raw)
        if isinstance(val, dict):
            val = val.get("value", val)
    except (json.JSONDecodeError, TypeError):
        val = float(raw)
    return key, val, int(record.timestamp)


class KafkaScottyWindowOperator:
    """Consume a Kafka topic, window it, hand results to ``on_result``.

    The watermark default matches the reference kafka connector's 100 ms
    event-time tick (kafkaStreams-connector/.../KeyedScottyWindowOperator.java:25,62-77).
    """

    def __init__(self, operator: Optional[KeyedScottyWindowOperator] = None,
                 deserialize: Callable = _default_deserialize,
                 watermark_period_ms: int = 100,
                 obs=None):
        self.operator = operator or KeyedScottyWindowOperator(
            watermark_policy=PeriodicWatermarks(watermark_period_ms),
            obs=obs)
        if obs is not None and self.operator.obs is None:
            # a caller-supplied operator still gets the requested telemetry
            self.operator.obs = obs
        self.deserialize = deserialize

    def run(self, consumer: Iterable, on_result: Callable[[Tuple], None],
            max_records: Optional[int] = None) -> int:
        """``consumer``: any iterable of Kafka-like records (KafkaConsumer
        instances are iterables of ConsumerRecord). Returns records consumed."""
        n = 0
        for record in consumer:
            key, value, ts = self.deserialize(record)
            for item in self.operator.process_element(key, value, ts):
                on_result(item)
            n += 1
            if max_records is not None and n >= max_records:
                break
        return n


def make_consumer(topic: str, bootstrap_servers: str = "localhost:9092",
                  **kwargs):
    """Create a real KafkaConsumer (requires kafka-python)."""
    try:
        from kafka import KafkaConsumer
    except ImportError as e:                      # pragma: no cover
        raise ImportError(
            "kafka-python is not installed; pass any iterable of records "
            "to KafkaScottyWindowOperator.run instead") from e
    return KafkaConsumer(topic, bootstrap_servers=bootstrap_servers, **kwargs)
