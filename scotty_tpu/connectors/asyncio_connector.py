"""Asyncio connector: windowed aggregation over async streams.

The streaming-Python analogue of the reference's push-based engine
connectors (Samza StreamTask / Kafka Processor callbacks, SURVEY.md §2.4):
an async task consumes ``(key, value, ts)`` items from an ``asyncio.Queue``
or async iterator and emits window results to a callback as watermarks fire.

Telemetry: pass an :class:`scotty_tpu.obs.Observability` to record
connector-side ingest metrics — ``ingest_tuples``/``windows_emitted`` in
:func:`run_keyed_async`, the source ``queue_depth`` gauge in
:func:`queue_source`. The registry is thread-safe, so a producer thread
filling the queue and the consumer task share one registry safely.

Backpressure (ISSUE 7): an unbounded producer queue is a hidden infinite
buffer that defeats every downstream bound — use :func:`bounded_queue`
(``maxsize`` defaults to :data:`DEFAULT_QUEUE_MAXSIZE`). Producer-side
behavior at the bound is the standard asyncio contract: ``await
queue.put(item)`` BLOCKS until the consumer frees a slot (end-to-end
backpressure to the producer), ``queue.put_nowait(item)`` raises
``asyncio.QueueFull`` (the producer's explicit shed decision).
:func:`queue_source` flags an unbounded queue in the flight ring so a
postmortem shows where the bound was missing.
"""

from __future__ import annotations

import asyncio
from typing import AsyncIterator, Awaitable, Callable, Optional, Tuple

from .. import obs as _obs
from ..obs import flight as _flight
from .base import KeyedScottyWindowOperator

#: default bound for :func:`bounded_queue` — deep enough to ride bursts,
#: small enough that a stalled consumer pushes back on the producer
#: within one block's worth of records rather than one heap's worth
DEFAULT_QUEUE_MAXSIZE = 1024


def bounded_queue(maxsize: int = DEFAULT_QUEUE_MAXSIZE) -> "asyncio.Queue":
    """The sanctioned producer queue for :func:`queue_source` /
    :func:`run_keyed_async` (module docstring: producer-side semantics at
    the bound). ``maxsize`` must be positive — an unbounded queue defeats
    ring backpressure by construction."""
    if maxsize <= 0:
        raise ValueError(
            "bounded_queue needs maxsize > 0 — an unbounded producer "
            "queue is a hidden infinite buffer (pass asyncio.Queue() "
            "explicitly if you really want one)")
    return asyncio.Queue(maxsize=maxsize)


async def run_keyed_async(
        source: AsyncIterator[Tuple],
        operator: KeyedScottyWindowOperator,
        emit: Callable[[Tuple], Optional[Awaitable]],
        obs=None,
        serve_port: Optional[int] = None,
        health=None,
        shaper=None,
        control=None,
        idle_poll_s: Optional[float] = None,
        ingest_ring=None,
        shed_callback: Optional[Callable] = None,
        sink=None,
) -> None:
    """Consume (key, value, ts) from an async iterator; call ``emit`` for
    every (key, AggregateWindow) result. ``emit`` may be sync or async.
    ``obs`` defaults to the operator's attached Observability (metrics are
    then recorded by the operator itself — no double counting).

    ``serve_port`` (opt-in, ISSUE 4) serves ``/metrics``·``/vars``·
    ``/healthz`` over the effective Observability for the duration of the
    loop; ``0`` binds an ephemeral port, read back from
    ``operator.obs_server.port`` while running. ``health`` is the
    :class:`scotty_tpu.obs.HealthPolicy` behind ``/healthz``
    (``HealthPolicy(max_watermark_lag_ms=...)`` arms the lag check).

    ``shaper`` (a :class:`scotty_tpu.shaper.ShaperConfig`, ISSUE 5)
    attaches the coalescing/sorting front-end to the operator for this
    run; held records drain through ``emit`` when the source ends.

    ``control`` (ISSUE 6) is the register/cancel control path shared
    with the iterable run loops: ``(after_records, command)`` rows, each
    ``command`` called with the operator once that many records were
    consumed.

    ``idle_poll_s`` (ISSUE 7 satellite — the max_delay_ms honesty fix):
    wait at most this long for the next record; a timeout is an IDLE
    TICK that evaluates the accumulator deadline (``poll_shaper``) and
    pumps the ingest ring, so held records flush on time while the
    source is silent. The pending ``__anext__`` is NOT cancelled on a
    tick (an async generator would die), it just keeps waiting.

    ``ingest_ring`` (a :class:`scotty_tpu.ingest.RingConfig`, ISSUE 7)
    stages records through the bounded backpressure ring — block/shed/
    fail on full, exact ``ingest_ring_*`` accounting, block-at-a-time
    vectorized replay; ``shed_callback(vals, ts, keys)`` sees records a
    'shed' policy dropped. Pair it with :func:`bounded_queue` so the
    producer side is bounded too.

    ``sink`` (a :class:`scotty_tpu.delivery.TransactionalSink`, ISSUE 8)
    gates every ``emit`` call through the exactly-once output boundary:
    replayed duplicates after a supervised restore are suppressed
    instead of delivered."""
    from .iterable import (_apply_control, _control_cursor, _counted,
                           _make_ring, _pop, _pop_counted,
                           _ring_polls_deadline)

    if shaper is not None:
        operator.attach_shaper(shaper)
    own_obs = obs if obs is not None and obs is not operator.obs else None
    eff_obs = obs if obs is not None else operator.obs
    ring = None
    ring_results: list = []
    if ingest_ring is not None:
        ring = _make_ring(ingest_ring, operator, True, eff_obs,
                          shed_callback, ring_results)
    ring_poll = _ring_polls_deadline(operator, ring)
    server = None
    if serve_port is not None and eff_obs is not None:
        server = eff_obs.serve(port=serve_port, health=health)
        operator.obs_server = server
    ctl, nxt = _control_cursor(control)
    n_seen = 0

    async def _emit(item) -> None:
        if sink is not None and not sink.emit(item):
            return                           # suppressed replay duplicate
        r = emit(item)
        if asyncio.iscoroutine(r) or isinstance(r, Awaitable):
            await r

    ait = source.__aiter__()
    pending = None
    try:
        while True:
            if idle_poll_s is None:
                try:
                    rec = await ait.__anext__()
                except StopAsyncIteration:
                    break
            else:
                if pending is None:
                    pending = asyncio.ensure_future(ait.__anext__())
                done, _ = await asyncio.wait({pending},
                                             timeout=idle_poll_s)
                if not done:                  # idle tick; keep waiting
                    if ring is not None:
                        ring.poll()
                        for item in _pop_counted(ring_results, own_obs):
                            await _emit(item)
                    for item in _counted(operator.poll_shaper(),
                                         own_obs):
                        await _emit(item)
                    continue
                try:
                    rec = pending.result()
                except StopAsyncIteration:
                    pending = None
                    break
                pending = None
            key, value, ts = rec
            if nxt is not None and n_seen >= nxt[0] and ring is not None:
                ring.drain()                  # control barrier
                for item in _pop_counted(ring_results, own_obs):
                    await _emit(item)
            nxt = _apply_control(operator, ctl, nxt, n_seen)
            n_seen += 1
            if ring is not None:
                ring.offer_one(value, int(ts), key)
                if ring_poll:           # per-arrival deadline parity
                    items = _pop(ring_results) + operator.poll_shaper()
                else:
                    items = _pop(ring_results)
            else:
                items = operator.process_element(key, value, int(ts))
            if own_obs is not None:
                own_obs.counter(_obs.INGEST_TUPLES).inc()
                if items:
                    own_obs.counter(_obs.WINDOWS_EMITTED).inc(len(items))
            for item in items:
                await _emit(item)
        if ring is not None:
            ring.drain()
            for item in _pop_counted(ring_results, own_obs):
                await _emit(item)
        nxt = _apply_control(operator, ctl, nxt, float("inf"))
        for item in operator.drain_shaper():
            await _emit(item)
    finally:
        if pending is not None:
            pending.cancel()
        if server is not None:
            server.close()
            operator.obs_server = None


async def queue_source(queue: "asyncio.Queue", sentinel=None, obs=None,
                       depth_sample_every: int = 16,
                       stall_timeout_s: Optional[float] = None,
                       on_stall=None, max_stalls: Optional[int] = None):
    """Adapt an asyncio.Queue into an async iterator (terminates on
    ``sentinel``). With ``obs``, the queue depth gauge is sampled AFTER
    each blocking ``get`` (sampling before it reported the depth seen
    before a possibly-long wait — a perpetually stale value on an idle
    consumer) and throttled to every ``depth_sample_every``-th item.

    Use :func:`bounded_queue` to build the queue: an unbounded one is
    flight-marked (``queue_source_unbounded``) because it silently
    defeats every downstream bound (module docstring).

    ``stall_timeout_s`` arms the preemptive no-progress watchdog: every
    ``get`` that exceeds the timeout counts a ``resilience_stall_events``
    and calls ``on_stall(seconds_waited)``; after ``max_stalls``
    consecutive timeouts (None = keep waiting forever) the source raises
    ``SourceStalled`` so a supervisor can restart the producer."""
    from ..resilience.connectors import SourceStalled, flag_stall

    if obs is not None and queue.maxsize <= 0:
        obs.flight_event(_flight.MARK, "queue_source_unbounded")
    n = 0
    while True:
        if stall_timeout_s is None:
            item = await queue.get()
        else:
            stalls = 0
            while True:
                try:
                    item = await asyncio.wait_for(queue.get(),
                                                  stall_timeout_s)
                    break
                except asyncio.TimeoutError:
                    stalls += 1
                    flag_stall(obs, "queue_source",
                               stalls * stall_timeout_s, on_stall)
                    if max_stalls is not None and stalls >= max_stalls:
                        raise SourceStalled(
                            f"queue source made no progress for "
                            f"{stalls * stall_timeout_s:.3f}s") from None
        if obs is not None and n % max(1, depth_sample_every) == 0:
            obs.gauge(_obs.QUEUE_DEPTH).set(queue.qsize())
        n += 1
        if item is sentinel:
            return
        yield item
