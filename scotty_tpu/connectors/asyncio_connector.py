"""Asyncio connector: windowed aggregation over async streams.

The streaming-Python analogue of the reference's push-based engine
connectors (Samza StreamTask / Kafka Processor callbacks, SURVEY.md §2.4):
an async task consumes ``(key, value, ts)`` items from an ``asyncio.Queue``
or async iterator and emits window results to a callback as watermarks fire.
"""

from __future__ import annotations

import asyncio
from typing import AsyncIterator, Awaitable, Callable, Optional, Tuple

from .base import KeyedScottyWindowOperator


async def run_keyed_async(
        source: AsyncIterator[Tuple],
        operator: KeyedScottyWindowOperator,
        emit: Callable[[Tuple], Optional[Awaitable]],
) -> None:
    """Consume (key, value, ts) from an async iterator; call ``emit`` for
    every (key, AggregateWindow) result. ``emit`` may be sync or async."""
    async for key, value, ts in source:
        for item in operator.process_element(key, value, int(ts)):
            r = emit(item)
            if asyncio.iscoroutine(r) or isinstance(r, Awaitable):
                await r


async def queue_source(queue: "asyncio.Queue", sentinel=None):
    """Adapt an asyncio.Queue into an async iterator (terminates on
    ``sentinel``)."""
    while True:
        item = await queue.get()
        if item is sentinel:
            return
        yield item
