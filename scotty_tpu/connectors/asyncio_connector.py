"""Asyncio connector: windowed aggregation over async streams.

The streaming-Python analogue of the reference's push-based engine
connectors (Samza StreamTask / Kafka Processor callbacks, SURVEY.md §2.4):
an async task consumes ``(key, value, ts)`` items from an ``asyncio.Queue``
or async iterator and emits window results to a callback as watermarks fire.

Telemetry: pass an :class:`scotty_tpu.obs.Observability` to record
connector-side ingest metrics — ``ingest_tuples``/``windows_emitted`` in
:func:`run_keyed_async`, the source ``queue_depth`` gauge in
:func:`queue_source`. The registry is thread-safe, so a producer thread
filling the queue and the consumer task share one registry safely.
"""

from __future__ import annotations

import asyncio
from typing import AsyncIterator, Awaitable, Callable, Optional, Tuple

from .. import obs as _obs
from .base import KeyedScottyWindowOperator


async def run_keyed_async(
        source: AsyncIterator[Tuple],
        operator: KeyedScottyWindowOperator,
        emit: Callable[[Tuple], Optional[Awaitable]],
        obs=None,
        serve_port: Optional[int] = None,
        health=None,
        shaper=None,
        control=None,
) -> None:
    """Consume (key, value, ts) from an async iterator; call ``emit`` for
    every (key, AggregateWindow) result. ``emit`` may be sync or async.
    ``obs`` defaults to the operator's attached Observability (metrics are
    then recorded by the operator itself — no double counting).

    ``serve_port`` (opt-in, ISSUE 4) serves ``/metrics``·``/vars``·
    ``/healthz`` over the effective Observability for the duration of the
    loop; ``0`` binds an ephemeral port, read back from
    ``operator.obs_server.port`` while running. ``health`` is the
    :class:`scotty_tpu.obs.HealthPolicy` behind ``/healthz``
    (``HealthPolicy(max_watermark_lag_ms=...)`` arms the lag check).

    ``shaper`` (a :class:`scotty_tpu.shaper.ShaperConfig`, ISSUE 5)
    attaches the coalescing/sorting front-end to the operator for this
    run; held records drain through ``emit`` when the source ends.

    ``control`` (ISSUE 6) is the register/cancel control path shared
    with the iterable run loops: ``(after_records, command)`` rows, each
    ``command`` called with the operator once that many records were
    consumed."""
    from .iterable import _apply_control, _control_cursor

    if shaper is not None:
        operator.attach_shaper(shaper)
    own_obs = obs if obs is not None and obs is not operator.obs else None
    eff_obs = obs if obs is not None else operator.obs
    server = None
    if serve_port is not None and eff_obs is not None:
        server = eff_obs.serve(port=serve_port, health=health)
        operator.obs_server = server
    ctl, nxt = _control_cursor(control)
    n_seen = 0
    try:
        async for key, value, ts in source:
            nxt = _apply_control(operator, ctl, nxt, n_seen)
            n_seen += 1
            items = operator.process_element(key, value, int(ts))
            if own_obs is not None:
                own_obs.counter(_obs.INGEST_TUPLES).inc()
                if items:
                    own_obs.counter(_obs.WINDOWS_EMITTED).inc(len(items))
            for item in items:
                r = emit(item)
                if asyncio.iscoroutine(r) or isinstance(r, Awaitable):
                    await r
        nxt = _apply_control(operator, ctl, nxt, float("inf"))
        for item in operator.drain_shaper():
            r = emit(item)
            if asyncio.iscoroutine(r) or isinstance(r, Awaitable):
                await r
    finally:
        if server is not None:
            server.close()
            operator.obs_server = None


async def queue_source(queue: "asyncio.Queue", sentinel=None, obs=None,
                       depth_sample_every: int = 16,
                       stall_timeout_s: Optional[float] = None,
                       on_stall=None, max_stalls: Optional[int] = None):
    """Adapt an asyncio.Queue into an async iterator (terminates on
    ``sentinel``). With ``obs``, the queue depth gauge is sampled AFTER
    each blocking ``get`` (sampling before it reported the depth seen
    before a possibly-long wait — a perpetually stale value on an idle
    consumer) and throttled to every ``depth_sample_every``-th item.

    ``stall_timeout_s`` arms the preemptive no-progress watchdog: every
    ``get`` that exceeds the timeout counts a ``resilience_stall_events``
    and calls ``on_stall(seconds_waited)``; after ``max_stalls``
    consecutive timeouts (None = keep waiting forever) the source raises
    ``SourceStalled`` so a supervisor can restart the producer."""
    from ..resilience.connectors import SourceStalled

    n = 0
    while True:
        if stall_timeout_s is None:
            item = await queue.get()
        else:
            stalls = 0
            while True:
                try:
                    item = await asyncio.wait_for(queue.get(),
                                                  stall_timeout_s)
                    break
                except asyncio.TimeoutError:
                    stalls += 1
                    if obs is not None:
                        obs.counter(_obs.RESILIENCE_STALL_EVENTS).inc()
                        obs.flight_event("stall", "queue_source",
                                         stalls * stall_timeout_s)
                    if on_stall is not None:
                        on_stall(stalls * stall_timeout_s)
                    if max_stalls is not None and stalls >= max_stalls:
                        raise SourceStalled(
                            f"queue source made no progress for "
                            f"{stalls * stall_timeout_s:.3f}s") from None
        if obs is not None and n % max(1, depth_sample_every) == 0:
            obs.gauge(_obs.QUEUE_DEPTH).set(queue.qsize())
        n += 1
        if item is sentinel:
            return
        yield item
