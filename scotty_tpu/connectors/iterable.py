"""Iterable connector — the simplest host: any Python iterable of
``(key, value, ts)`` (keyed) or ``(value, ts)`` (global) tuples.

Plays the role the reference's per-engine demo sources play for manual
validation (SURVEY.md §2.6 DemoSource); also the building block the asyncio /
torchdata adapters reduce to.

Telemetry: when the operator carries an attached
:class:`scotty_tpu.obs.Observability` it records ingest metrics itself; the
optional ``obs`` parameter here covers the bare-operator case (tuples
accepted + windows emitted at the connector boundary) without double
counting.

Idle ticks (ISSUE 7 satellite): a source may yield the :data:`IDLE_TICK`
sentinel between real records — the loop then evaluates the attached
shaper's ``max_delay_ms`` deadline (:meth:`poll_shaper`) and pumps the
ingest ring, so a chunked or quiet source still flushes held records on
time. (``None`` remains a poison record, as it always was.) A source
that simply *blocks* in ``__next__`` still cannot be polled — yield
ticks if bounded delay matters on silence; the kafka adapter's polling
mode and the asyncio loop's ``idle_poll_s`` generate ticks themselves.

Ingest ring (ISSUE 7 tentpole): ``ingest_ring=`` (a
:class:`scotty_tpu.ingest.RingConfig`) stages records in a bounded
preallocated ring and replays them into the operator a BLOCK at a time
(:meth:`process_block` — with an attached shaper that is one vectorized
``offer_block`` per block instead of a Python call per record).
Ring-full engages the configured policy: ``block`` pauses the source
(backpressure), ``shed`` drops with exact ``ingest_ring_shed`` counts
(``shed_callback`` sees every dropped record, so an oracle can replay
the survivors), ``fail`` raises. Results surface in the same order the
unstaged loop yields them, and bit-match it.
"""

from __future__ import annotations

from typing import Iterable, Iterator, List, Tuple

from .. import obs as _obs
from .base import GlobalScottyWindowOperator, KeyedScottyWindowOperator

#: sentinel a source yields as an IDLE TICK (module docstring): the run
#: loops poll deadlines/the ring instead of treating it as a record
IDLE_TICK = object()


def _control_cursor(control):
    """Normalize a run-loop control schedule (ISSUE 6): an iterable of
    ``(after_records, command)`` rows, ``command`` a callable applied to
    the operator — typically ``op.register_window(...)`` /
    ``op.cancel_window(...)`` closures. Rows fire in order once the
    record count reaches their threshold (and any remainder fires at
    stream end, so a schedule can never be silently dropped)."""
    if control is None:
        return None, None
    it = iter(sorted(control, key=lambda c: c[0]))
    return it, next(it, None)


def _apply_control(operator, it, nxt, n: int):
    while nxt is not None and n >= nxt[0]:
        nxt[1](operator)
        nxt = next(it, None)
    return nxt


def _make_ring(config, operator, keyed: bool, obs, shed_callback,
               results: List):
    """Build the run-loop RingIngestor: blocks replay through the
    operator's vectorized ``process_block``, results land in
    ``results`` for the loop to yield in order. When the operator
    carries a bounded-delay shaper, the ring's open-block stage
    deadline rides the same ``max_delay_ms`` on the same clock, so a
    slow-but-active (never idle) source still flushes on time."""
    from ..ingest import RingIngestor

    if keyed:
        sink = lambda keys, vals, tss: results.extend(   # noqa: E731
            operator.process_block(keys, vals, tss))
    else:
        sink = lambda vals, tss: results.extend(         # noqa: E731
            operator.process_block(vals, tss))
    acc = getattr(getattr(operator, "_shaper", None), "accumulator", None)
    delay_ms = getattr(acc, "max_delay_ms", None)
    return RingIngestor.for_sink(
        config, sink, keyed=keyed,
        obs=obs if obs is not None else operator.obs,
        shed_callback=shed_callback,
        clock=acc.clock if acc is not None else None,
        stage_deadline_s=None if delay_ms is None else delay_ms / 1000.0)


def _ring_polls_deadline(operator, ring) -> bool:
    """Whether the ring path must ALSO evaluate the accumulator deadline
    on every record arrival (the unstaged loop does so implicitly
    through per-record offers): true when a bounded-delay shaper is
    attached — a slow-but-active source never idles, so arrivals are
    the only evaluation points it gets."""
    if ring is None:
        return False
    acc = getattr(getattr(operator, "_shaper", None), "accumulator", None)
    return getattr(acc, "max_delay_ms", None) is not None


def run_keyed(source: Iterable[Tuple], operator: KeyedScottyWindowOperator,
              obs=None, dead_letter=None,
              poison_limit: int | None = None,
              shaper=None, control=None,
              ingest_ring=None, shed_callback=None,
              sink=None) -> Iterator[Tuple]:
    """Drive a keyed operator from an iterable of (key, value, ts); yields
    (key, AggregateWindow) results as watermarks fire.

    Records that fail to destructure or whose ts is not integral are
    POISON (ISSUE 3): counted, handed to ``dead_letter(record, exc)`` and
    skipped instead of killing the loop — engine errors still propagate.
    An :data:`IDLE_TICK` record polls deadlines (module docstring).

    ``shaper`` (a :class:`scotty_tpu.shaper.ShaperConfig`, ISSUE 5)
    attaches the coalescing/sorting front-end to the operator for this
    run: records buffer into sorted blocks instead of trickling one at a
    time, and anything still held drains when the source ends.

    ``control`` (ISSUE 6) is the register/cancel control path: an
    iterable of ``(after_records, command)`` rows — each ``command`` is
    called with the operator once that many records have been consumed
    (e.g. ``lambda op: op.register_window(...)``), interleaving query
    registration/cancellation deterministically with the stream.

    ``ingest_ring`` (a :class:`scotty_tpu.ingest.RingConfig`, ISSUE 7)
    stages records through the bounded backpressure ring (module
    docstring); ``shed_callback(vals, ts, keys)`` sees records a 'shed'
    policy dropped.

    ``sink`` (a :class:`scotty_tpu.delivery.TransactionalSink`, ISSUE 8)
    is the exactly-once output boundary: every yielded result first
    passes ``sink.emit`` — in ``exactly_once`` mode, replayed duplicates
    after a supervised restore are suppressed instead of yielded.
    """
    from ..resilience.connectors import PoisonHandler

    if sink is not None:
        for item in run_keyed(source, operator, obs=obs,
                              dead_letter=dead_letter,
                              poison_limit=poison_limit, shaper=shaper,
                              control=control, ingest_ring=ingest_ring,
                              shed_callback=shed_callback):
            if sink.emit(item):
                yield item
        return
    if shaper is not None:
        operator.attach_shaper(shaper)
    own_obs = obs if obs is not None and obs is not operator.obs else None
    poison = PoisonHandler(dead_letter=dead_letter, limit=poison_limit,
                           obs=obs if obs is not None else operator.obs)
    ring = None
    ring_results: List[Tuple] = []
    if ingest_ring is not None:
        ring = _make_ring(ingest_ring, operator, True,
                          obs if obs is not None else operator.obs,
                          shed_callback, ring_results)
    ring_poll = _ring_polls_deadline(operator, ring)
    ctl, nxt = _control_cursor(control)
    n_seen = 0
    for rec in source:
        if rec is IDLE_TICK:                  # idle tick (module docstring)
            if ring is not None:
                ring.poll()
                for item in _pop_counted(ring_results, own_obs):
                    yield item
            for item in _counted(operator.poll_shaper(), own_obs):
                yield item
            continue
        if nxt is not None and n_seen >= nxt[0] and ring is not None:
            # a control command is due: records staged in the ring must
            # land first, or the command would see an operator that is
            # behind the record count the schedule names
            ring.drain()
            for item in _pop_counted(ring_results, own_obs):
                yield item
        nxt = _apply_control(operator, ctl, nxt, n_seen)
        n_seen += 1
        try:
            key, value, ts = rec
            ts = int(ts)
        except (TypeError, ValueError) as e:
            poison.handle(rec, e)
            continue
        if ring is not None:
            ring.offer_one(value, ts, key)
            if ring_poll:               # per-arrival deadline parity
                items = _pop(ring_results) + operator.poll_shaper()
            else:
                items = _pop(ring_results)
        else:
            items = operator.process_element(key, value, ts)
        if own_obs is not None:
            own_obs.counter(_obs.INGEST_TUPLES).inc()
            if items:
                own_obs.counter(_obs.WINDOWS_EMITTED).inc(len(items))
        for item in items:
            yield item
    if ring is not None:
        ring.drain()
        for item in _pop_counted(ring_results, own_obs):
            yield item
    nxt = _apply_control(operator, ctl, nxt, float("inf"))
    for item in operator.drain_shaper() if hasattr(operator, "drain_shaper") \
            else ():
        yield item


def _pop(buf: List) -> List:
    out = list(buf)
    buf.clear()
    return out


def _counted(items, own_obs):
    """Connector-boundary ``windows_emitted`` parity for windows yielded
    OUTSIDE the per-record counting block (idle-tick shaper flushes,
    ring drains): the same flush triggered by a record arrival counts,
    so one triggered by a tick must too."""
    if own_obs is not None and items:
        own_obs.counter(_obs.WINDOWS_EMITTED).inc(len(items))
    return items


def _pop_counted(buf: List, own_obs) -> List:
    return _counted(_pop(buf), own_obs)


def run_global(source: Iterable[Tuple], operator: GlobalScottyWindowOperator,
               obs=None, dead_letter=None,
               poison_limit: int | None = None,
               shaper=None, control=None,
               ingest_ring=None, shed_callback=None,
               sink=None) -> Iterator:
    """Drive a global operator from an iterable of (value, ts) — same
    poison-record contract as :func:`run_keyed`, same optional
    ``shaper`` front-end, same ``control`` register/cancel path, same
    ``ingest_ring`` bounded staging + :data:`IDLE_TICK` idle ticks
    (``None`` remains a poison record here too), same ``sink``
    transactional output boundary (ISSUE 8)."""
    from ..resilience.connectors import PoisonHandler

    if sink is not None:
        for item in run_global(source, operator, obs=obs,
                               dead_letter=dead_letter,
                               poison_limit=poison_limit, shaper=shaper,
                               control=control, ingest_ring=ingest_ring,
                               shed_callback=shed_callback):
            if sink.emit(item):
                yield item
        return
    if shaper is not None:
        operator.attach_shaper(shaper)
    own_obs = obs if obs is not None and obs is not operator.obs else None
    poison = PoisonHandler(dead_letter=dead_letter, limit=poison_limit,
                           obs=obs if obs is not None else operator.obs)
    ring = None
    ring_results: List = []
    if ingest_ring is not None:
        ring = _make_ring(ingest_ring, operator, False,
                          obs if obs is not None else operator.obs,
                          shed_callback, ring_results)
    ring_poll = _ring_polls_deadline(operator, ring)
    ctl, nxt = _control_cursor(control)
    n_seen = 0
    for rec in source:
        if rec is IDLE_TICK:                  # idle tick
            if ring is not None:
                ring.poll()
                for item in _pop_counted(ring_results, own_obs):
                    yield item
            for item in _counted(operator.poll_shaper(), own_obs):
                yield item
            continue
        if nxt is not None and n_seen >= nxt[0] and ring is not None:
            ring.drain()
            for item in _pop_counted(ring_results, own_obs):
                yield item
        nxt = _apply_control(operator, ctl, nxt, n_seen)
        n_seen += 1
        try:
            value, ts = rec
            ts = int(ts)
        except (TypeError, ValueError) as e:
            poison.handle(rec, e)
            continue
        if ring is not None:
            ring.offer_one(value, ts)
            if ring_poll:               # per-arrival deadline parity
                items = _pop(ring_results) + operator.poll_shaper()
            else:
                items = _pop(ring_results)
        else:
            items = operator.process_element(value, ts)
        if own_obs is not None:
            own_obs.counter(_obs.INGEST_TUPLES).inc()
            if items:
                own_obs.counter(_obs.WINDOWS_EMITTED).inc(len(items))
        for item in items:
            yield item
    if ring is not None:
        ring.drain()
        for item in _pop_counted(ring_results, own_obs):
            yield item
    nxt = _apply_control(operator, ctl, nxt, float("inf"))
    for item in operator.drain_shaper() if hasattr(operator, "drain_shaper") \
            else ():
        yield item


def collect_keyed(source: Iterable[Tuple], operator: KeyedScottyWindowOperator,
                  final_watermark: int | None = None, obs=None,
                  **kwargs) -> List[Tuple]:
    out = list(run_keyed(source, operator, obs=obs, **kwargs))
    if final_watermark is not None:
        out.extend(operator.process_watermark(final_watermark))
    return out


def collect_global(source: Iterable[Tuple], operator: GlobalScottyWindowOperator,
                   final_watermark: int | None = None, obs=None,
                   **kwargs) -> List:
    out = list(run_global(source, operator, obs=obs, **kwargs))
    if final_watermark is not None:
        out.extend(operator.process_watermark(final_watermark))
    return out
