"""Iterable connector — the simplest host: any Python iterable of
``(key, value, ts)`` (keyed) or ``(value, ts)`` (global) tuples.

Plays the role the reference's per-engine demo sources play for manual
validation (SURVEY.md §2.6 DemoSource); also the building block the asyncio /
torchdata adapters reduce to.

Telemetry: when the operator carries an attached
:class:`scotty_tpu.obs.Observability` it records ingest metrics itself; the
optional ``obs`` parameter here covers the bare-operator case (tuples
accepted + windows emitted at the connector boundary) without double
counting.
"""

from __future__ import annotations

from typing import Iterable, Iterator, List, Tuple

from .. import obs as _obs
from .base import GlobalScottyWindowOperator, KeyedScottyWindowOperator


def _control_cursor(control):
    """Normalize a run-loop control schedule (ISSUE 6): an iterable of
    ``(after_records, command)`` rows, ``command`` a callable applied to
    the operator — typically ``op.register_window(...)`` /
    ``op.cancel_window(...)`` closures. Rows fire in order once the
    record count reaches their threshold (and any remainder fires at
    stream end, so a schedule can never be silently dropped)."""
    if control is None:
        return None, None
    it = iter(sorted(control, key=lambda c: c[0]))
    return it, next(it, None)


def _apply_control(operator, it, nxt, n: int):
    while nxt is not None and n >= nxt[0]:
        nxt[1](operator)
        nxt = next(it, None)
    return nxt


def run_keyed(source: Iterable[Tuple], operator: KeyedScottyWindowOperator,
              obs=None, dead_letter=None,
              poison_limit: int | None = None,
              shaper=None, control=None) -> Iterator[Tuple]:
    """Drive a keyed operator from an iterable of (key, value, ts); yields
    (key, AggregateWindow) results as watermarks fire.

    Records that fail to destructure or whose ts is not integral are
    POISON (ISSUE 3): counted, handed to ``dead_letter(record, exc)`` and
    skipped instead of killing the loop — engine errors still propagate.

    ``shaper`` (a :class:`scotty_tpu.shaper.ShaperConfig`, ISSUE 5)
    attaches the coalescing/sorting front-end to the operator for this
    run: records buffer into sorted blocks instead of trickling one at a
    time, and anything still held drains when the source ends.

    ``control`` (ISSUE 6) is the register/cancel control path: an
    iterable of ``(after_records, command)`` rows — each ``command`` is
    called with the operator once that many records have been consumed
    (e.g. ``lambda op: op.register_window(...)``), interleaving query
    registration/cancellation deterministically with the stream.
    """
    from ..resilience.connectors import PoisonHandler

    if shaper is not None:
        operator.attach_shaper(shaper)
    own_obs = obs if obs is not None and obs is not operator.obs else None
    poison = PoisonHandler(dead_letter=dead_letter, limit=poison_limit,
                           obs=obs if obs is not None else operator.obs)
    ctl, nxt = _control_cursor(control)
    n_seen = 0
    for rec in source:
        nxt = _apply_control(operator, ctl, nxt, n_seen)
        n_seen += 1
        try:
            key, value, ts = rec
            ts = int(ts)
        except (TypeError, ValueError) as e:
            poison.handle(rec, e)
            continue
        items = operator.process_element(key, value, ts)
        if own_obs is not None:
            own_obs.counter(_obs.INGEST_TUPLES).inc()
            if items:
                own_obs.counter(_obs.WINDOWS_EMITTED).inc(len(items))
        for item in items:
            yield item
    nxt = _apply_control(operator, ctl, nxt, float("inf"))
    for item in operator.drain_shaper() if hasattr(operator, "drain_shaper") \
            else ():
        yield item


def run_global(source: Iterable[Tuple], operator: GlobalScottyWindowOperator,
               obs=None, dead_letter=None,
               poison_limit: int | None = None,
               shaper=None, control=None) -> Iterator:
    """Drive a global operator from an iterable of (value, ts) — same
    poison-record contract as :func:`run_keyed`, same optional
    ``shaper`` front-end, same ``control`` register/cancel path."""
    from ..resilience.connectors import PoisonHandler

    if shaper is not None:
        operator.attach_shaper(shaper)
    own_obs = obs if obs is not None and obs is not operator.obs else None
    poison = PoisonHandler(dead_letter=dead_letter, limit=poison_limit,
                           obs=obs if obs is not None else operator.obs)
    ctl, nxt = _control_cursor(control)
    n_seen = 0
    for rec in source:
        nxt = _apply_control(operator, ctl, nxt, n_seen)
        n_seen += 1
        try:
            value, ts = rec
            ts = int(ts)
        except (TypeError, ValueError) as e:
            poison.handle(rec, e)
            continue
        items = operator.process_element(value, ts)
        if own_obs is not None:
            own_obs.counter(_obs.INGEST_TUPLES).inc()
            if items:
                own_obs.counter(_obs.WINDOWS_EMITTED).inc(len(items))
        for item in items:
            yield item
    nxt = _apply_control(operator, ctl, nxt, float("inf"))
    for item in operator.drain_shaper() if hasattr(operator, "drain_shaper") \
            else ():
        yield item


def collect_keyed(source: Iterable[Tuple], operator: KeyedScottyWindowOperator,
                  final_watermark: int | None = None, obs=None) -> List[Tuple]:
    out = list(run_keyed(source, operator, obs=obs))
    if final_watermark is not None:
        out.extend(operator.process_watermark(final_watermark))
    return out


def collect_global(source: Iterable[Tuple], operator: GlobalScottyWindowOperator,
                   final_watermark: int | None = None, obs=None) -> List:
    out = list(run_global(source, operator, obs=obs))
    if final_watermark is not None:
        out.extend(operator.process_watermark(final_watermark))
    return out
