"""Iterable connector — the simplest host: any Python iterable of
``(key, value, ts)`` (keyed) or ``(value, ts)`` (global) tuples.

Plays the role the reference's per-engine demo sources play for manual
validation (SURVEY.md §2.6 DemoSource); also the building block the asyncio /
torchdata adapters reduce to.
"""

from __future__ import annotations

from typing import Iterable, Iterator, List, Tuple

from .base import GlobalScottyWindowOperator, KeyedScottyWindowOperator


def run_keyed(source: Iterable[Tuple], operator: KeyedScottyWindowOperator
              ) -> Iterator[Tuple]:
    """Drive a keyed operator from an iterable of (key, value, ts); yields
    (key, AggregateWindow) results as watermarks fire."""
    for key, value, ts in source:
        for item in operator.process_element(key, value, int(ts)):
            yield item


def run_global(source: Iterable[Tuple], operator: GlobalScottyWindowOperator
               ) -> Iterator:
    """Drive a global operator from an iterable of (value, ts)."""
    for value, ts in source:
        for item in operator.process_element(value, int(ts)):
            yield item


def collect_keyed(source: Iterable[Tuple], operator: KeyedScottyWindowOperator,
                  final_watermark: int | None = None) -> List[Tuple]:
    out = list(run_keyed(source, operator))
    if final_watermark is not None:
        out.extend(operator.process_watermark(final_watermark))
    return out


def collect_global(source: Iterable[Tuple], operator: GlobalScottyWindowOperator,
                   final_watermark: int | None = None) -> List:
    out = list(run_global(source, operator))
    if final_watermark is not None:
        out.extend(operator.process_watermark(final_watermark))
    return out
