"""Apache Beam connector (import-gated).

Mirrors the reference beam-connector: a DoFn over ``KV<K, V>`` elements that
keeps a keyed window operator and emits stringified results on an event-time
tick (beam-connector/.../KeyedScottyWindowOperator.java:24-94, 1000 ms tick).
Requires ``apache-beam`` at runtime.
"""

from __future__ import annotations

from typing import List, Optional

from .base import KeyedScottyWindowOperator, PeriodicWatermarks

try:
    import apache_beam as beam

    HAS_BEAM = True
    _DoFnBase = beam.DoFn
except ImportError:                      # pragma: no cover
    HAS_BEAM = False
    _DoFnBase = object


class ScottyWindowDoFn(_DoFnBase):
    """Beam DoFn: input (key, (value, ts)) → output str(window result)
    (the reference Beam connector emits toString of windows,
    beam-connector/.../KeyedScottyWindowOperator.java:79-92)."""

    def __init__(self, windows: Optional[List] = None,
                 aggregations: Optional[List] = None,
                 allowed_lateness: int = 1,
                 watermark_period_ms: int = 1000):
        if HAS_BEAM:
            super().__init__()
        self._windows = windows or []
        self._aggregations = aggregations or []
        self._lateness = allowed_lateness
        self._period = watermark_period_ms
        self._op = None

    def setup(self):
        self._op = KeyedScottyWindowOperator(
            windows=self._windows, aggregations=self._aggregations,
            allowed_lateness=self._lateness,
            watermark_policy=PeriodicWatermarks(self._period))

    def process(self, element, timestamp=None):
        if self._op is None:
            self.setup()
        key, payload = element
        if isinstance(payload, (tuple, list)) and len(payload) == 2:
            value, ts = payload
        else:
            value, ts = payload, int(timestamp.micros // 1000)
        for k, window in self._op.process_element(key, value, int(ts)):
            yield f"{k}: {window!r}"
