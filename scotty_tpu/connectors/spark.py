"""Spark connector (import-gated).

Mirrors the reference spark-connector — a ``FlatMapFunction`` over a
structured stream keeping a keyed operator with a 100 ms event-time tick
(spark-connector/.../KeyedScottyWindowOperator.java:17-85, tick :24,59-72) —
rebuilt for Spark's current API surface:

* :func:`scotty_map_in_pandas` — a pandas-batch mapper for
  ``DataFrame.mapInPandas``: per-partition keyed operator fed whole Arrow
  batches (columns ``key``, ``value``, ``ts``), emitting window-result rows
  (``key``, ``window_start``, ``window_end``, ``agg_0..agg_{n-1}``). This is
  the structured-streaming path and works on micro-batch boundaries exactly
  like the reference's flatMap-with-tick.
* :func:`result_schema` — the matching ``pyspark.sql.types.StructType``
  (needs pyspark).
* :func:`attach` — one-call wiring: ``attach(df, windows, aggs)`` returns
  the transformed DataFrame (needs pyspark).
* :func:`scotty_flat_map` — plain-iterator variant for RDD
  ``mapPartitions`` / DStream ``flatMap`` parity with the reference.

Only :func:`result_schema` / :func:`attach` import pyspark; the mappers are
plain callables so the connector logic is testable (and usable on any
Arrow/pandas micro-batch source) without a Spark installation.
"""

from __future__ import annotations

from typing import Iterable, Iterator, List, Optional, Tuple

from .base import KeyedScottyWindowOperator, PeriodicWatermarks


def _make_operator(windows, aggregations, allowed_lateness,
                   watermark_period_ms):
    return KeyedScottyWindowOperator(
        windows=windows or [], aggregations=aggregations or [],
        allowed_lateness=allowed_lateness,
        watermark_policy=PeriodicWatermarks(watermark_period_ms))


def scotty_flat_map(windows: Optional[List] = None,
                    aggregations: Optional[List] = None,
                    allowed_lateness: int = 1,
                    watermark_period_ms: int = 100):
    """Returns a partition-mapper: Iterable[(key, value, ts)] →
    Iterator[(key, start, end, values)] — apply with
    ``rdd.mapPartitions(scotty_flat_map(...))`` or feed micro-batches
    directly (the reference's FlatMapFunction shape,
    spark-connector/.../KeyedScottyWindowOperator.java:38-57)."""
    def mapper(partition: Iterable[Tuple]) -> Iterator[Tuple]:
        op = _make_operator(windows, aggregations, allowed_lateness,
                            watermark_period_ms)
        for key, value, ts in partition:
            for k, w in op.process_element(key, value, int(ts)):
                yield (k, w.get_start(), w.get_end(),
                       tuple(w.get_agg_values()))
    return mapper


def scotty_map_in_pandas(windows: Optional[List] = None,
                         aggregations: Optional[List] = None,
                         allowed_lateness: int = 1,
                         watermark_period_ms: int = 100,
                         key_col: str = "key", value_col: str = "value",
                         ts_col: str = "ts"):
    """Pandas-batch mapper for ``DataFrame.mapInPandas``.

    Input batches need columns (``key``, ``value``, ``ts``); output rows are
    (``key``, ``window_start``, ``window_end``, ``agg_0``…``agg_{n-1}``),
    one per non-empty emitted window — schema from :func:`result_schema`.
    The operator lives for the partition (one per task), so watermarks tick
    across batches of the same partition, matching the reference's
    per-instance operator + event-time tick."""
    n_aggs = len(aggregations or [])

    def mapper(batches: Iterator) -> Iterator:
        import pandas as pd

        op = _make_operator(windows, aggregations, allowed_lateness,
                            watermark_period_ms)

        def to_frame(results) -> Optional[pd.DataFrame]:
            if not results:
                return None
            rows = []
            for k, w in results:
                vals = w.get_agg_values()
                rows.append((k, w.get_start(), w.get_end(),
                             *[float(vals[i]) for i in range(n_aggs)]))
            cols = ([key_col, "window_start", "window_end"]
                    + [f"agg_{i}" for i in range(n_aggs)])
            return pd.DataFrame(rows, columns=cols)

        for batch in batches:
            out = []
            for key, value, ts in zip(batch[key_col].to_numpy(),
                                      batch[value_col].to_numpy(),
                                      batch[ts_col].to_numpy()):
                out.extend(op.process_element(key, value, int(ts)))
            frame = to_frame(out)
            if frame is not None:
                yield frame

    return mapper


def result_schema(aggregations: List, key_type=None):
    """``StructType`` matching :func:`scotty_map_in_pandas` output.
    Requires pyspark."""
    try:
        from pyspark.sql import types as T
    except ImportError as e:                 # pragma: no cover
        raise ImportError(
            "result_schema/attach need pyspark; use scotty_map_in_pandas "
            "directly for non-Spark pandas micro-batch sources") from e
    fields = [
        T.StructField("key", key_type or T.StringType(), False),
        T.StructField("window_start", T.LongType(), False),
        T.StructField("window_end", T.LongType(), False),
    ]
    for i in range(len(aggregations)):
        fields.append(T.StructField(f"agg_{i}", T.DoubleType(), True))
    return T.StructType(fields)


def attach(df, windows: List, aggregations: List,
           allowed_lateness: int = 1, watermark_period_ms: int = 100,
           key_type=None):
    """Wire a Scotty keyed window operator onto a Spark DataFrame with
    columns (key, value, ts): returns ``df.mapInPandas(...)`` with the
    right schema. Requires pyspark."""
    schema = result_schema(aggregations, key_type=key_type)
    return df.mapInPandas(
        scotty_map_in_pandas(windows, aggregations, allowed_lateness,
                             watermark_period_ms), schema)
