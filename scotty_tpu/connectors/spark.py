"""Spark connector (import-gated).

Mirrors the reference spark-connector: a flatMap function over a structured
stream keeping a keyed operator with a 100 ms event-time tick
(spark-connector/.../KeyedScottyWindowOperator.java:17-85, tick :24,59-72).
Requires ``pyspark`` at runtime; ``scotty_flat_map`` itself is a plain
callable usable with ``DataFrame.mapInPandas`` / RDD ``mapPartitions``.
"""

from __future__ import annotations

from typing import Iterable, Iterator, List, Optional, Tuple

from .base import KeyedScottyWindowOperator, PeriodicWatermarks


def scotty_flat_map(windows: Optional[List] = None,
                    aggregations: Optional[List] = None,
                    allowed_lateness: int = 1,
                    watermark_period_ms: int = 100):
    """Returns a partition-mapper: Iterable[(key, value, ts)] →
    Iterator[(key, start, end, values)] — apply with
    ``rdd.mapPartitions(scotty_flat_map(...))`` or feed micro-batches
    directly."""
    def mapper(partition: Iterable[Tuple]) -> Iterator[Tuple]:
        op = KeyedScottyWindowOperator(
            windows=windows or [], aggregations=aggregations or [],
            allowed_lateness=allowed_lateness,
            watermark_policy=PeriodicWatermarks(watermark_period_ms))
        for key, value, ts in partition:
            for k, w in op.process_element(key, value, int(ts)):
                yield (k, w.get_start(), w.get_end(), tuple(w.get_agg_values()))
    return mapper
