"""Connector base layer: keyed/global operators + watermark policies.

Re-design of the reference's six L3 connector modules (SURVEY.md §2.4) —
each of which is one ~85-115 LoC class adapting a host engine callback to
``SlicingWindowOperator.processElement/processWatermark`` while keeping a
``HashMap<Key, SlicingWindowOperator>`` (e.g.
flink-connector/.../KeyedScottyWindowOperator.java:21,56-66). Differences
between the reference connectors are exactly (a) the host callback API and
(b) the watermark source; this module factors (b) into pluggable
``WatermarkPolicy`` objects and provides the shared keyed/global cores, so
each host adapter (``iterable`` / ``asyncio`` / ``torchdata`` / ``beam`` /
``kafka`` / ``spark``) is as thin as the reference's.

Backends: ``host`` = one reference-semantics operator per key (arbitrary key
and value types, full window support — the reference model); ``device`` =
`scotty_tpu.parallel.KeyedTpuWindowOperator` (keys hashed onto shard lanes of
one batched TPU program).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Hashable, Iterable, List, Optional, Tuple

import numpy as np

from .. import obs as _obs
from ..obs import flight as _flight
from ..obs import latency as _lat
from ..core.aggregates import AggregateFunction
from ..core.operator import AggregateWindow
from ..core.windows import Window


class WatermarkPolicy:
    """Decides when (and at what ts) to advance the watermark.

    ``observe(ts) -> Optional[int]``: called per tuple with its event ts;
    returns a watermark ts when one should fire, else None.
    """

    def observe(self, ts: int) -> Optional[int]:
        raise NotImplementedError

    def current_watermark(self) -> Optional[int]:
        """The last watermark this policy advanced to (None before the
        first) — connector telemetry uses it to flag tuples that arrive
        already older than ``watermark - allowed_lateness`` (the operator
        will not repair them)."""
        return None


class AscendingWatermarks(WatermarkPolicy):
    """Flink-style: the watermark follows the max event ts (optionally minus
    a bounded delay) and fires whenever it advances
    (flink-connector KeyedScottyWindowOperator.java:72-86 — real engine
    watermark, fallback to element ts)."""

    def __init__(self, delay: int = 0):
        self.delay = delay
        self.current = -1

    def observe(self, ts: int) -> Optional[int]:
        wm = ts - self.delay
        if wm > self.current:
            self.current = wm
            return wm
        return None

    def current_watermark(self) -> Optional[int]:
        return self.current if self.current >= 0 else None


class PeriodicWatermarks(WatermarkPolicy):
    """Event-time tick: fire when the stream has advanced ``period`` ms past
    the last watermark — the storm/spark/beam/samza/kafka connector pattern
    (storm-connector KeyedScottyWindowOperator.java:40,74-87 period 1000 ms;
    spark/samza/kafka 100 ms; beam 1000 ms)."""

    def __init__(self, period: int = 1000):
        self.period = period
        self.last = -1
        self._fired = False

    def observe(self, ts: int) -> Optional[int]:
        if self.last == -1:
            self.last = ts
            return None
        if ts > self.last + self.period:
            self.last = ts
            self._fired = True
            return ts
        return None

    def current_watermark(self) -> Optional[int]:
        # before the first FIRED watermark, `last` is just the first
        # element's ts — not a watermark; the contract says None until one
        # actually advanced
        return self.last if self._fired else None


class KeyedScottyWindowOperator:
    """Keyed windowing core shared by every host adapter.

    Host backend mirrors the reference exactly: lazily create one
    reference-semantics operator per key; on watermark, advance EVERY key's
    operator and emit its non-empty windows
    (flink-connector KeyedScottyWindowOperator.java:41-49,56-66,72-86).
    """

    def __init__(self, windows: Optional[List[Window]] = None,
                 aggregations: Optional[List[AggregateFunction]] = None,
                 allowed_lateness: int = 1,
                 watermark_policy: Optional[WatermarkPolicy] = None,
                 backend: str = "host",
                 n_key_shards: int = 64,
                 engine_config=None,
                 obs=None,
                 shaper=None,
                 shaper_clock=None):
        self.windows: List[Window] = list(windows or [])
        self.aggregations: List[AggregateFunction] = list(aggregations or [])
        # reference default allowedLateness = 1 ms
        # (flink KeyedScottyWindowOperator.java:26)
        self.allowed_lateness = allowed_lateness
        self.policy = watermark_policy or AscendingWatermarks()
        self.backend = backend
        self.n_key_shards = n_key_shards
        self.engine_config = engine_config
        self.obs = obs                      # scotty_tpu.obs.Observability
        #: the live ObsServer while a run loop serves this operator
        #: (asyncio run_keyed_async(..., serve_port=...)); None otherwise
        self.obs_server = None
        self._host_ops: Dict[Hashable, Any] = {}
        self._key_lanes: Dict[Hashable, int] = {}
        self._lane_keys: List[Hashable] = []
        self._device_op = None
        # stream shaper (ISSUE 5): coalesce + reorder-slack-sort records
        # before the per-key operators see them, replacing the raw
        # per-record trickle for out-of-order host streams
        self._shaper = None
        self._shaper_results: List[Tuple[Hashable, AggregateWindow]] = []
        self._in_replay = False
        if shaper is not None:
            self.attach_shaper(shaper, clock=shaper_clock)

    def attach_shaper(self, config, clock=None) -> None:
        """Attach a :class:`scotty_tpu.shaper.ShaperConfig`-driven
        front-end: ``process_element`` then buffers records through the
        coalescing/sorting accumulator and replays flushed blocks in
        sorted order (watermark policy observes during replay, so the
        per-key operators see a shaped stream). ``process_watermark``
        and the run loops drain held records first."""
        from ..shaper import ShaperConfig, StreamShaper

        if not isinstance(config, ShaperConfig):
            raise TypeError("attach_shaper expects a ShaperConfig, got "
                            f"{type(config).__name__}")
        B = config.batch_size or getattr(self.engine_config, "batch_size",
                                         None) or 1024
        import dataclasses

        self._shaper = StreamShaper(
            config=dataclasses.replace(config, batch_size=B),
            sink=self._replay_block, keyed=True, clock=clock,
            obs=self.obs, value_dtype=None)

    def _replay_block(self, keys, vals, tss) -> None:
        # replay must NOT re-enter drain_shaper: a policy-fired watermark
        # mid-replay would force-flush the reorder-slack band, undoing
        # the shaping (and re-emitting already-fired windows as late
        # updates the unshaped sorted run never produces)
        self._in_replay = True
        try:
            for k, v, t in zip(keys, vals, tss.tolist()):
                # compute BEFORE looking up the list: a fired watermark
                # pops and REBINDS _shaper_results mid-call, and
                # extending the pre-pop binding would strand results on
                # an orphaned list
                r = self._process_element_now(k, v, int(t))
                self._shaper_results.extend(r)
        finally:
            self._in_replay = False

    def drain_shaper(self) -> List[Tuple[Hashable, AggregateWindow]]:
        """Flush everything the shaper holds (stream end / external
        watermark); returns results emitted during the replay — plus any
        undelivered results a restore() brought back. No-op while a
        replay is already in flight."""
        if self._in_replay:
            return []
        if self._shaper is not None:
            self._shaper.flush()
        out, self._shaper_results = self._shaper_results, []
        return out

    def poll_shaper(self) -> List[Tuple[Hashable, AggregateWindow]]:
        """Idle-tick deadline poll (ISSUE 7 satellite): evaluate an
        attached shaper's ``max_delay_ms`` deadline with no new record —
        the run loops call it on idle ticks so a quiet source still
        flushes held records on time. Returns whatever a deadline flush
        replayed (empty when nothing was due)."""
        if self._shaper is not None and not self._in_replay:
            self._shaper.poll()
        out, self._shaper_results = self._shaper_results, []
        return out

    def process_block(self, keys, vals, tss
                      ) -> List[Tuple[Hashable, AggregateWindow]]:
        """Vectorized block ingestion — the ingest-ring replay path
        (ISSUE 7): with an attached shaper the whole block lands through
        the accumulator's ``offer_block`` (array-slice copies, no
        per-record Python work); bare operators replay per record.
        Result order is exactly what per-record ``process_element``
        calls over the same records would produce."""
        if self._shaper is not None:
            self._shaper.offer_block(vals, np.asarray(tss, np.int64),
                                     keys=keys)
            out, self._shaper_results = self._shaper_results, []
            return out
        out: List[Tuple[Hashable, AggregateWindow]] = []
        for k, v, t in zip(keys, vals,
                           np.asarray(tss, np.int64).tolist()):
            out.extend(self._process_element_now(k, v, int(t)))
        return out

    # -- serving control path (ISSUE 6) ------------------------------------
    def register_window(self, window: Window, tenant: str = "default") -> int:
        """Register a window mid-stream on EVERY key — live per-key
        operators immediately, keys first seen later at their creation —
        and return a stable logical handle for :meth:`cancel_window`.
        Host backend only (the keyed device batch bakes its spec into the
        [K, ...] kernels; serve dynamic sets from
        ``scotty_tpu.serving.QueryService`` there)."""
        if self.backend != "host":
            raise NotImplementedError(
                "keyed register/cancel runs on the host backend; for "
                "device-rate dynamic query sets use "
                "scotty_tpu.serving.QueryService")
        from ..core.windows import ContextFreeWindow, ForwardContextAware, \
            ForwardContextFree

        # validate EAGERLY (the same check each per-key simulator would
        # make): with zero live keys the per-key loop below validates
        # nothing, and an unsupported window must fail the registration —
        # not the first process_element of a later-created key mid-stream
        if not isinstance(window, ContextFreeWindow) or isinstance(
                window, (ForwardContextAware, ForwardContextFree)):
            raise NotImplementedError(
                "serving register/cancel covers context-free grid windows; "
                "session/context windows carry per-registration state")
        if not hasattr(self, "_serving_regs"):
            self._serving_regs = {}
            self._serving_next = 0
        h = self._serving_next
        self._serving_next += 1
        per_key = {key: op.register_window(window, tenant=tenant)
                   for key, op in self._host_ops.items()}
        self._serving_regs[h] = {"window": window, "tenant": tenant,
                                 "per_key": per_key}
        if self.obs is not None:
            self.obs.counter(_obs.SERVING_REGISTERED).inc()
            self.obs.flight_event(_flight.QUERY_REGISTER, f"{tenant}:{window}",
                                  float(h))
        return h

    def cancel_window(self, handle: int, tenant: str = "default") -> None:
        reg = getattr(self, "_serving_regs", {}).pop(handle, None)
        if reg is None:
            raise ValueError(
                f"unknown or already-cancelled window handle {handle}")
        for key, bh in reg["per_key"].items():
            self._host_ops[key].cancel_window(bh, tenant=tenant)
        if self.obs is not None:
            self.obs.counter(_obs.SERVING_CANCELLED).inc()
            self.obs.flight_event(_flight.QUERY_CANCEL,
                                  f"{reg['tenant']}:{reg['window']}",
                                  float(handle))

    # -- builder API (README.md:31-42 chaining) ----------------------------
    def add_window(self, window: Window) -> "KeyedScottyWindowOperator":
        self.windows.append(window)
        return self

    def add_aggregation(self, fn: AggregateFunction) -> "KeyedScottyWindowOperator":
        self.aggregations.append(fn)
        return self

    def with_allowed_lateness(self, lateness: int) -> "KeyedScottyWindowOperator":
        self.allowed_lateness = lateness
        return self

    # -- processing --------------------------------------------------------
    def _op_for_key(self, key: Hashable):
        op = self._host_ops.get(key)
        if op is None:
            from ..simulator import SlicingWindowOperator

            op = SlicingWindowOperator()
            for w in self.windows:
                op.add_window_assigner(w)
            for a in self.aggregations:
                op.add_aggregation(a)
            op.set_max_lateness(self.allowed_lateness)
            # live serving registrations apply to late-arriving keys too
            for reg in getattr(self, "_serving_regs", {}).values():
                reg["per_key"][key] = op.register_window(
                    reg["window"], tenant=reg["tenant"])
            self._host_ops[key] = op
        return op

    def _device(self):
        if self._device_op is None:
            from ..parallel import KeyedTpuWindowOperator

            self._device_op = KeyedTpuWindowOperator(
                n_keys=self.n_key_shards,
                config=self.engine_config)
            for w in self.windows:
                self._device_op.add_window_assigner(w)
            for a in self.aggregations:
                self._device_op.add_aggregation(a)
            self._device_op.set_max_lateness(self.allowed_lateness)
        return self._device_op

    def _lane_for_key(self, key: Hashable) -> int:
        """Exact key→lane assignment. Hashing keys onto lanes would MERGE
        colliding keys' windows (the reference keeps one operator per
        distinct key — KeyedScottyWindowOperator.java:56-61); lanes are
        assigned first-come instead, and running out is an explicit error."""
        lane = self._key_lanes.get(key)
        if lane is None:
            if len(self._key_lanes) >= self.n_key_shards:
                raise RuntimeError(
                    f"more than n_key_shards={self.n_key_shards} distinct "
                    "keys on the device backend; raise n_key_shards")
            lane = len(self._key_lanes)
            self._key_lanes[key] = lane
            self._lane_keys.append(key)
        return lane

    def process_element(self, key: Hashable, value: Any, ts: int
                        ) -> List[Tuple[Hashable, AggregateWindow]]:
        """Feed one tuple; returns window results if this tuple's ts advanced
        the watermark (the connector emit path). With an attached shaper
        the record buffers first and results surface when a block
        flushes (sorted replay)."""
        if self._shaper is not None:
            self._shaper.offer(value, int(ts), key=key)
            out, self._shaper_results = self._shaper_results, []
            return out
        return self._process_element_now(key, value, ts)

    def _process_element_now(self, key: Hashable, value: Any, ts: int
                             ) -> List[Tuple[Hashable, AggregateWindow]]:
        if self.obs is not None:
            if self.obs.latency is not None:
                # record-arrival pre-stamp (ISSUE 14): the connector
                # boundary is where a record's emission chain begins
                self.obs.latency.pre(_lat.STAGE_ARRIVAL)
            self.obs.counter(_obs.INGEST_TUPLES).inc()
            wm_cur = self.policy.current_watermark()
            if wm_cur is not None and ts < wm_cur:
                # below the stream's watermark: late by the same contract
                # name the device operator counts at ITS ingest edge —
                # the workload monitor's late_share reads this (ISSUE 16)
                self.obs.counter(_obs.LATE_TUPLES).inc()
            if wm_cur is not None \
                    and ts + self.allowed_lateness < wm_cur:
                # older than watermark - lateness: the operator will not
                # repair it — surfaced here so silent loss is visible
                self.obs.counter(_obs.DROPPED_TUPLES).inc()
        if self.backend == "device":
            self._device().process_element(self._lane_for_key(key), value, ts)
        else:
            self._op_for_key(key).process_element(value, ts)
        wm = self.policy.observe(ts)
        if wm is not None:
            return self.process_watermark(wm)
        return []

    # -- resilience (ISSUE 3): connector-level snapshot/restore ------------
    def save(self, path: str) -> None:
        """Snapshot the keyed state (host backend: every per-key operator
        + the watermark policy — plain-Python pickles through the
        StateFactory seam, like utils.checkpoint.save_host_operator).
        The Supervisor's connector mode checkpoints through this; the
        device backend snapshots via utils.checkpoint.save_keyed_operator
        instead."""
        import os
        import pickle

        if self.backend == "device":
            raise NotImplementedError(
                "device-backend connectors checkpoint through "
                "utils.checkpoint.save_keyed_operator")
        # records held in an attached shaper count as consumed by the
        # supervisor's source offset: replay them into the per-key
        # operators first, and persist any results that replay emitted
        # so a restore can still deliver them
        if self._shaper is not None:
            drained = self.drain_shaper()   # pops + REBINDS the list
            self._shaper_results.extend(drained)
        os.makedirs(path, exist_ok=True)
        # through fsio, like every other committed byte: the manifest
        # records the INTENT digest, so a silent short write of the
        # pickle can never be blessed at finalize (and the crash-point
        # fuzzer enumerates this write's fault variants)
        from ..utils import fsio

        fsio.write_bytes(
            os.path.join(path, "keyed_connector.pkl"),
            pickle.dumps({"host_ops": self._host_ops,
                          "policy": self.policy,
                          "allowed_lateness": self.allowed_lateness,
                          "shaper_results": list(self._shaper_results)}))

    def restore(self, path: str) -> None:
        """Restore a :meth:`save` snapshot into a freshly-configured
        connector operator (same windows/aggregations)."""
        import os
        import pickle

        with open(os.path.join(path, "keyed_connector.pkl"), "rb") as f:
            snap = pickle.load(f)
        if snap["allowed_lateness"] != self.allowed_lateness:
            raise ValueError(
                "snapshot was taken with allowed_lateness="
                f"{snap['allowed_lateness']}, this operator has "
                f"{self.allowed_lateness} — configure them identically")
        self._host_ops = snap["host_ops"]
        self.policy = snap["policy"]
        # results the checkpoint's shaper drain emitted but the run loop
        # never collected — surfaced by the next process_element /
        # process_watermark so a restored run still delivers them
        self._shaper_results = list(snap.get("shaper_results", []))

    def process_watermark(self, wm: int) -> List[Tuple[Hashable, AggregateWindow]]:
        # held shaper records are about to fall behind this watermark:
        # drain them first (their replay may itself fire policy
        # watermarks — those results lead this one's and were already
        # counted by their own firings)
        pre: List[Tuple[Hashable, AggregateWindow]] = self.drain_shaper()
        out: List[Tuple[Hashable, AggregateWindow]] = []
        if self.backend == "device":
            if self._device_op is not None:
                for lane, w in self._device().process_watermark(wm):
                    out.append((self._lane_keys[lane]
                                if lane < len(self._lane_keys) else lane, w))
        else:
            for key, op in self._host_ops.items():
                for w in op.process_watermark(wm):
                    if w.has_value():      # emit contract: non-empty only
                        out.append((key, w))
        if self.obs is not None:
            self.obs.counter(_obs.WATERMARKS).inc()
            self.obs.flight_event(_flight.WATERMARK, "watermark",
                                  float(wm))
            if out:
                self.obs.counter(_obs.WINDOWS_EMITTED).inc(len(out))
        return pre + out


class GlobalScottyWindowOperator:
    """Non-keyed variant: a single operator instance for the whole stream
    (flink-connector/.../GlobalScottyWindowOperator.java:16-85)."""

    def __init__(self, windows: Optional[List[Window]] = None,
                 aggregations: Optional[List[AggregateFunction]] = None,
                 allowed_lateness: int = 1,
                 watermark_policy: Optional[WatermarkPolicy] = None,
                 backend: str = "host",
                 n_shards: int = 8,
                 engine_config=None,
                 obs=None,
                 shaper=None,
                 shaper_clock=None):
        self.windows = list(windows or [])
        self.aggregations = list(aggregations or [])
        self.allowed_lateness = allowed_lateness
        self.policy = watermark_policy or AscendingWatermarks()
        self.backend = backend
        self.n_shards = n_shards
        self.engine_config = engine_config
        self.obs = obs
        self._op = None
        self._shaper = None
        self._shaper_results: List[AggregateWindow] = []
        self._in_replay = False
        if shaper is not None:
            self.attach_shaper(shaper, clock=shaper_clock)

    def attach_shaper(self, config, clock=None) -> None:
        """Global-stream analogue of
        :meth:`KeyedScottyWindowOperator.attach_shaper`."""
        from ..shaper import ShaperConfig, StreamShaper

        if not isinstance(config, ShaperConfig):
            raise TypeError("attach_shaper expects a ShaperConfig, got "
                            f"{type(config).__name__}")
        B = config.batch_size or getattr(self.engine_config, "batch_size",
                                         None) or 1024
        import dataclasses

        self._shaper = StreamShaper(
            config=dataclasses.replace(config, batch_size=B),
            sink=self._replay_block, keyed=False, clock=clock,
            obs=self.obs, value_dtype=None)

    def _replay_block(self, vals, tss) -> None:
        # no drain re-entry, compute-then-extend — see the keyed
        # operator's _replay_block for both invariants
        self._in_replay = True
        try:
            for v, t in zip(vals, tss.tolist()):
                r = self._process_element_now(v, int(t))
                self._shaper_results.extend(r)
        finally:
            self._in_replay = False

    def drain_shaper(self) -> List[AggregateWindow]:
        if self._in_replay:
            return []
        if self._shaper is not None:
            self._shaper.flush()
        out, self._shaper_results = self._shaper_results, []
        return out

    def poll_shaper(self) -> List[AggregateWindow]:
        """Idle-tick deadline poll — see
        :meth:`KeyedScottyWindowOperator.poll_shaper`."""
        if self._shaper is not None and not self._in_replay:
            self._shaper.poll()
        out, self._shaper_results = self._shaper_results, []
        return out

    def process_block(self, vals, tss) -> List[AggregateWindow]:
        """Vectorized block ingestion (ingest-ring replay path) — see
        :meth:`KeyedScottyWindowOperator.process_block`."""
        if self._shaper is not None:
            self._shaper.offer_block(vals, np.asarray(tss, np.int64))
            out, self._shaper_results = self._shaper_results, []
            return out
        out: List[AggregateWindow] = []
        for v, t in zip(vals, np.asarray(tss, np.int64).tolist()):
            out.extend(self._process_element_now(v, int(t)))
        return out

    def add_window(self, window: Window) -> "GlobalScottyWindowOperator":
        self.windows.append(window)
        return self

    # -- serving control path (ISSUE 6) ------------------------------------
    def register_window(self, window: Window, tenant: str = "default") -> int:
        """Register a window mid-stream; returns the backend's handle for
        :meth:`cancel_window`. Delegates to the underlying operator's
        serving path (host simulator / TpuWindowOperator); the sharded
        global device backend has no per-window cancel and raises."""
        op = self._operator()
        if not hasattr(op, "register_window"):
            raise NotImplementedError(
                f"{type(op).__name__} has no serving control path; use "
                "backend='host' or scotty_tpu.serving.QueryService")
        h = op.register_window(window, tenant=tenant)
        if not hasattr(self, "_serving_tenants"):
            self._serving_tenants: dict = {}
        self._serving_tenants[h] = tenant
        if self.obs is not None:
            self.obs.counter(_obs.SERVING_REGISTERED).inc()
            self.obs.flight_event(_flight.QUERY_REGISTER, f"{tenant}:{window}",
                                  float(h))
        return h

    def cancel_window(self, handle: int) -> None:
        op = self._operator()
        if not hasattr(op, "cancel_window"):
            raise NotImplementedError(
                f"{type(op).__name__} has no serving control path")
        # flight attribution uses the REGISTRATION's tenant, matching the
        # keyed wrapper — a cancel belongs to whoever registered the query
        tenant = getattr(self, "_serving_tenants", {}).pop(handle, "default")
        op.cancel_window(handle, tenant=tenant)
        if self.obs is not None:
            self.obs.counter(_obs.SERVING_CANCELLED).inc()
            self.obs.flight_event(_flight.QUERY_CANCEL, tenant, float(handle))

    def add_aggregation(self, fn: AggregateFunction) -> "GlobalScottyWindowOperator":
        self.aggregations.append(fn)
        return self

    def _operator(self):
        if self._op is None:
            if self.backend == "device":
                from ..parallel import GlobalTpuWindowOperator

                self._op = GlobalTpuWindowOperator(
                    n_shards=self.n_shards, config=self.engine_config)
            else:
                from ..simulator import SlicingWindowOperator

                self._op = SlicingWindowOperator()
            for w in self.windows:
                self._op.add_window_assigner(w)
            for a in self.aggregations:
                self._op.add_aggregation(a)
            self._op.set_max_lateness(self.allowed_lateness)
        return self._op

    def process_element(self, value: Any, ts: int) -> List[AggregateWindow]:
        if self._shaper is not None:
            self._shaper.offer(value, int(ts))
            out, self._shaper_results = self._shaper_results, []
            return out
        return self._process_element_now(value, ts)

    def _process_element_now(self, value: Any, ts: int
                             ) -> List[AggregateWindow]:
        if self.obs is not None:
            self.obs.counter(_obs.INGEST_TUPLES).inc()
            wm_cur = self.policy.current_watermark()
            if wm_cur is not None and ts < wm_cur:
                self.obs.counter(_obs.LATE_TUPLES).inc()
            if wm_cur is not None \
                    and ts + self.allowed_lateness < wm_cur:
                self.obs.counter(_obs.DROPPED_TUPLES).inc()
        self._operator().process_element(value, ts)
        wm = self.policy.observe(ts)
        if wm is not None:
            return self.process_watermark(wm)
        return []

    def process_watermark(self, wm: int) -> List[AggregateWindow]:
        # drained-replay results were already counted by their own nested
        # watermark firings — only this watermark's emissions count here
        pre = self.drain_shaper()
        out = [w for w in self._operator().process_watermark(wm)
               if w.has_value()]
        if self.obs is not None:
            self.obs.counter(_obs.WATERMARKS).inc()
            self.obs.flight_event(_flight.WATERMARK, "watermark",
                                  float(wm))
            if out:
                self.obs.counter(_obs.WINDOWS_EMITTED).inc(len(out))
        return pre + out
