"""Flink connector (import-gated).

Mirrors the reference flink-connector — the flagship adapter: a
``KeyedProcessFunction`` holding one window operator per key, processing
watermarks from the Flink timer service with an element-ts fallback, plus a
non-keyed ``ProcessFunction`` variant
(flink-connector/.../KeyedScottyWindowOperator.java:17-103,
GlobalScottyWindowOperator.java:16-85; builder chaining README.md:31-42).

Requires ``apache-flink`` (pyflink) at runtime; without it the classes
still construct and the same logic is drivable directly through
``process_record(key, value, ts, current_watermark=...)`` — which is also
exactly how the tests exercise the watermark-fallback behavior.
"""

from __future__ import annotations

from typing import Any, List, Optional, Tuple

from .base import KeyedScottyWindowOperator as _Core
from .base import WatermarkPolicy

try:
    from pyflink.datastream.functions import (
        KeyedProcessFunction as _KeyedBase,
        ProcessFunction as _GlobalBase,
    )

    HAS_PYFLINK = True
except ImportError:                      # pragma: no cover
    HAS_PYFLINK = False
    _KeyedBase = object
    _GlobalBase = object


class _EngineWatermarks(WatermarkPolicy):
    """The flink connector's watermark strategy: use the engine's
    currentWatermark when it advances, falling back to the element ts when
    the engine reports none (KeyedScottyWindowOperator.java:72-86)."""

    def __init__(self):
        self.current = -1

    def observe_with_engine(self, ts: int,
                            engine_wm: Optional[int]) -> Optional[int]:
        # fall back to the element ts only on NEGATIVE engine watermarks
        # (KeyedScottyWindowOperator.java: currentWatermark()<0 ? ts : wm);
        # a valid watermark of exactly 0 must be honored or ahead-of-
        # watermark elements fire windows early (ADVICE r2)
        wm = engine_wm if engine_wm is not None and engine_wm >= 0 else ts
        if wm > self.current:
            self.current = wm
            return wm
        return None

    def observe(self, ts: int) -> Optional[int]:
        return self.observe_with_engine(ts, None)


class KeyedScottyWindowOperator(_KeyedBase):
    """pyflink ``KeyedProcessFunction``: ``(value, ts)`` elements under a
    ``key_by``, emitting ``(key, start, end, values)`` tuples downstream.

    Usage with pyflink::

        op = (KeyedScottyWindowOperator()
                .add_window(TumblingWindow(WindowMeasure.Time, 1000))
                .add_aggregation(SumAggregation())
                .allowed_lateness(100))
        stream.key_by(lambda e: e[0]).process(op)
    """

    def __init__(self, windows: Optional[List] = None,
                 aggregations: Optional[List] = None,
                 allowed_lateness: int = 1):
        if HAS_PYFLINK:
            super().__init__()
        self._windows = list(windows or [])
        self._aggregations = list(aggregations or [])
        self._lateness = allowed_lateness
        self._core: Optional[_Core] = None
        self._policy = _EngineWatermarks()

    # builder chaining (README.md:31-42)
    def add_window(self, window) -> "KeyedScottyWindowOperator":
        self._windows.append(window)
        return self

    def add_aggregation(self, fn) -> "KeyedScottyWindowOperator":
        self._aggregations.append(fn)
        return self

    def allowed_lateness(self, lateness: int) -> "KeyedScottyWindowOperator":
        self._lateness = lateness
        return self

    def _ensure_core(self) -> _Core:
        if self._core is None:
            self._core = _Core(
                windows=self._windows, aggregations=self._aggregations,
                allowed_lateness=self._lateness,
                watermark_policy=self._policy)
        return self._core

    def process_record(self, key: Any, value: Any, ts: int,
                       current_watermark: Optional[int] = None
                       ) -> List[Tuple]:
        """Engine-independent core: feed one keyed record with the engine's
        current watermark (or None); returns emitted
        ``(key, start, end, values)`` rows."""
        core = self._ensure_core()
        if core.backend == "device":
            core._device().process_element(core._lane_for_key(key), value, ts)
        else:
            core._op_for_key(key).process_element(value, ts)
        wm = self._policy.observe_with_engine(ts, current_watermark)
        out = []
        if wm is not None:
            for k, w in core.process_watermark(wm):
                out.append((k, w.get_start(), w.get_end(),
                            tuple(w.get_agg_values())))
        return out

    # pyflink callback
    def process_element(self, value, ctx):  # pragma: no cover - needs flink
        key = ctx.get_current_key()
        ts = ctx.timestamp()
        if ts is None:
            v, ts = value
        else:
            v = value
        engine_wm = ctx.timer_service().current_watermark()
        for row in self.process_record(key, v, int(ts), int(engine_wm)):
            yield row


class GlobalScottyWindowOperator(_GlobalBase):
    """Non-keyed pyflink ``ProcessFunction``: one operator for the whole
    stream (flink-connector/.../GlobalScottyWindowOperator.java:16-85)."""

    def __init__(self, windows: Optional[List] = None,
                 aggregations: Optional[List] = None,
                 allowed_lateness: int = 1):
        if HAS_PYFLINK:
            super().__init__()
        self._keyed = KeyedScottyWindowOperator(
            windows=windows, aggregations=aggregations,
            allowed_lateness=allowed_lateness)

    def add_window(self, window) -> "GlobalScottyWindowOperator":
        self._keyed.add_window(window)
        return self

    def add_aggregation(self, fn) -> "GlobalScottyWindowOperator":
        self._keyed.add_aggregation(fn)
        return self

    def process_record(self, value: Any, ts: int,
                       current_watermark: Optional[int] = None) -> List[Tuple]:
        return [(s, e, vals) for _, s, e, vals in
                self._keyed.process_record(0, value, ts, current_watermark)]

    def process_element(self, value, ctx):  # pragma: no cover - needs flink
        ts = ctx.timestamp()
        if ts is None:
            value, ts = value
        engine_wm = ctx.timer_service().current_watermark()
        for row in self.process_record(value, int(ts), int(engine_wm)):
            yield row
