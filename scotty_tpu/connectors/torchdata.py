"""Torch DataLoader / IterableDataset connector.

The PyTorch-ecosystem host adapter (the role the Spark/Beam connectors play
in the reference, SURVEY.md §2.4): wraps a windowing operator around any
``torch.utils.data.IterableDataset`` (or plain DataLoader) yielding
``(key, value, ts)`` and streams out window results.
"""

from __future__ import annotations

from typing import Iterator, Tuple

from .base import KeyedScottyWindowOperator
from .iterable import run_keyed

try:
    import torch
    from torch.utils.data import IterableDataset

    HAS_TORCH = True
except ImportError:                      # pragma: no cover
    HAS_TORCH = False
    IterableDataset = object


if HAS_TORCH:

    class WindowedResultDataset(IterableDataset):
        """IterableDataset of (key, AggregateWindow) results: compose window
        aggregation into a torch input pipeline."""

        def __init__(self, source, operator: KeyedScottyWindowOperator,
                     final_watermark: int | None = None):
            super().__init__()
            self.source = source
            self.operator = operator
            self.final_watermark = final_watermark

        def __iter__(self) -> Iterator[Tuple]:
            def tuples():
                for item in self.source:
                    if isinstance(item, (tuple, list)) and len(item) == 3:
                        k, v, t = item
                    else:                    # tensor row [k, v, t]
                        k, v, t = item[0], item[1], item[2]
                    if torch.is_tensor(k):
                        k = k.item()
                    if torch.is_tensor(v):
                        v = v.item()
                    if torch.is_tensor(t):
                        t = int(t.item())
                    yield k, v, int(t)

            yield from run_keyed(tuples(), self.operator)
            if self.final_watermark is not None:
                yield from self.operator.process_watermark(self.final_watermark)
