"""Host connectors (reference L3 parity, SURVEY.md §2.4): thin adapters from
host stream sources to the windowing operators. Six adapters mirror the
reference's six engine connectors: iterable / asyncio / torchdata are live;
kafka / beam / spark are import-gated on their host libraries."""

from .base import (
    AscendingWatermarks,
    GlobalScottyWindowOperator,
    KeyedScottyWindowOperator,
    PeriodicWatermarks,
    WatermarkPolicy,
)
from .iterable import collect_global, collect_keyed, run_global, run_keyed

__all__ = [
    "AscendingWatermarks", "GlobalScottyWindowOperator",
    "KeyedScottyWindowOperator", "PeriodicWatermarks", "WatermarkPolicy",
    "collect_global", "collect_keyed", "run_global", "run_keyed",
]
