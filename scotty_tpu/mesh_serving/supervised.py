"""Supervised exactly-once driving of a MeshQueryService.

The loop every reshard story runs through — the crash-point fuzzer
(tests/test_mesh_serving_crash.py), the churn bench's delivery audit
and the multichip demo all drive THIS function, so the recovery path
the fuzzer certifies is the path production uses:

* per interval: apply the scheduled churn (register / cancel-one
  commands resolved against the AUTHORITATIVE table, so a replayed
  restart resolves them identically), run one fused step, and hand each
  active slot's psum-folded global rows to the
  :class:`~scotty_tpu.delivery.sink.TransactionalSink` — every emission
  ``(epoch, seq)``-tagged, replay duplicates suppressed exactly;
* at scheduled boundaries: commit an atomic checkpoint (mesh state in
  canonical logical order + query table + sink ledger, one manifest,
  one rename) and/or reshard to the scheduled shard count;
* on any failure: ``Supervisor.handle_failure`` (backoff, postmortem,
  give-up budget), then rebuild the service AT THE SHARD COUNT
  SCHEDULED FOR THE RESUME INTERVAL — a crash just after an 8→4
  reshard restores at 4 shards from the canonical bundle, the
  restore-at-M path exercised by every armed fault.

Determinism contract: the stream is a pure function of
``(seed, interval, logical key)``, churn commands are resolved against
restored table state (lowest matching slot), and emissions are ordered
by slot — so a recovered run's delivered output bit-matches an
uninterrupted one, which is exactly what the crash-point sweep asserts
at every instrumented site.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence

from ..delivery.sink import TransactionalSink
from ..resilience.supervisor import Supervisor, SupervisorGaveUp


def shards_scheduled(reshard_at: Dict[int, int], initial: int,
                     interval: int) -> int:
    """The shard count in force at ``interval`` under the schedule:
    the last reshard at or before it (the restart loop rebuilds at
    this count — restore-at-M)."""
    cur = initial
    for i in sorted(reshard_at):
        if i <= interval:
            cur = reshard_at[i]
    return cur


def apply_churn(service, commands: Sequence) -> None:
    """Apply one interval's churn against the authoritative table.

    Commands: ``("register", window, tenant)`` /
    ``("cancel_one", tenant)`` — cancel-one resolves to the LOWEST
    active slot registered to the tenant, so a restart that restored
    the table replays the same resolution. Registrations shed by
    admission are quietly counted by the service; structural refusals
    (ServingUnsupported) propagate to the supervised edge; a cancel
    with no matching slot is a no-op (its register was shed)."""
    for cmd in commands:
        if cmd[0] == "register":
            _, window, tenant = cmd
            service.register(window, tenant=tenant)
        elif cmd[0] == "cancel_one":
            _, tenant = cmd
            for slot, h in sorted(service.active_handles().items()):
                if h.tenant == tenant:
                    service.cancel(h)
                    break
        else:
            raise ValueError(f"unknown churn command {cmd[0]!r}")


def run_supervised_mesh(make_service: Callable[[int], object],
                        n_intervals: int,
                        supervisor: Supervisor,
                        sink: Optional[TransactionalSink] = None,
                        churn: Optional[Dict[int, Sequence]] = None,
                        reshard_at: Optional[Dict[int, int]] = None,
                        initial_shards: Optional[int] = None,
                        checkpoint_every: int = 2,
                        obs=None) -> List:
    """Drive ``make_service(n_shards)`` for ``n_intervals`` under
    supervision with transactional delivery (module docstring). Returns
    every item actually delivered downstream across all restarts — the
    consumer's exact view. Items are
    ``(interval, slot, gen, global_rows)`` per active slot per
    interval; the sink tags each ``(epoch, seq)`` and the loop audits
    that no tag is ever delivered twice.

    ``obs`` (ISSUE 18 satellite) threads the sensor plane through the
    mesh loop the way the single-device kafka/asyncio loops already do:
    each interval ends in ``obs.flight_sync(watermark=...)``, which
    samples the attached :class:`~scotty_tpu.obs.WorkloadMonitor` first
    — so the ``workload_*`` fingerprint gauges, the drift counter the
    ``/healthz`` drift check reads, and the flight ring all stay live
    for a served mesh. Passing ``obs`` never changes delivered output.
    """
    import jax

    churn = churn or {}
    reshard_at = dict(reshard_at or {})
    if initial_shards is None:
        initial_shards = len(jax.devices())
    sink = sink or TransactionalSink()
    if supervisor.sink is None:
        supervisor.sink = sink
    delivered: List = []
    tags: set = set()

    def deliver(item, epoch, seq):
        if (epoch, seq) in tags:
            raise AssertionError(
                f"duplicate delivery tag (epoch={epoch}, seq={seq}): "
                "the exactly-once contract broke")
        tags.add((epoch, seq))
        delivered.append(item)

    prev_deliver = sink.deliver
    sink.deliver = deliver
    try:
        while True:
            try:
                # construction and restore are INSIDE the supervised
                # edge: a fault at a seed-register flight site or a torn
                # bundle read recovers like any mid-stream crash
                ckpt = supervisor.latest_checkpoint()
                if ckpt is not None:
                    d, _off = ckpt
                else:
                    d = None
                # the resume interval decides the rebuild shard count
                # BEFORE the service exists: read the committed bundle's
                # meta — the restore-at-M half of the reshard contract
                resume = 0
                if d is not None:
                    import json
                    import os

                    with open(os.path.join(d, "meta.json")) as f:
                        resume = int(json.load(f).get("interval", 0))
                svc = make_service(
                    shards_scheduled(reshard_at, initial_shards, resume))
                if d is not None:
                    svc.restore(d, verify=False)   # walk just verified
                    sink.restore(d)
                else:
                    sink.restore(None)
                i = svc.interval
                while i < n_intervals:
                    if i in reshard_at \
                            and svc.n_shards != reshard_at[i]:
                        svc.reshard(reshard_at[i], supervisor, pos=i)
                    if i in churn:
                        apply_churn(svc, churn[i])
                    out = svc.run(1)[0]
                    rows = svc.global_rows_by_slot(out)
                    # per-tenant attribution + per-query freshness
                    # (ISSUE 19): fold the rows ALREADY fetched above
                    # into the ledger — zero extra syncs, and a replayed
                    # restart re-accounts exactly what it re-computes,
                    # so conservation against the engine counters holds
                    # across crash/restore
                    if obs is not None \
                            and getattr(obs, "attribution",
                                        None) is not None:
                        svc.account_emissions(rows)
                    gens = svc.table.gens
                    items = [
                        (i, slot, int(gens[slot]),
                         tuple(map(tuple, rows.get(slot, ()))))
                        for slot in sorted(svc.active_handles())]
                    for item in items:
                        sink.emit(item)
                    i += 1
                    if obs is not None:
                        # the mesh loop's drain point: workload monitor
                        # sampled FIRST, then the flight ring — the
                        # same contract as the connector run loops
                        obs.flight_sync(
                            watermark=float(i * getattr(
                                svc, "wm_period_ms", 1)))
                    if i % checkpoint_every == 0 or i == n_intervals:
                        svc.check_overflow()
                        supervisor.commit_checkpoint(i, svc.save)
                return delivered
            except SupervisorGaveUp:
                raise
            except AssertionError:
                # the duplicate-tag audit's verdict, NOT a transient
                # crash: recovering would let the sink's suppression
                # horizon absorb the duplicate on replay and report the
                # very violation the audit exists to catch as green
                raise
            except Exception as e:        # noqa: BLE001 — supervised edge
                supervisor.handle_failure(e)   # raises at budget
    finally:
        sink.deliver = prev_deliver
