"""MeshQueryService — the multi-tenant serving control plane over the
mesh (ISSUE 13 tentpole).

One service from millions of keys to millions of queries: the PR 6
serving semantics (slot table, admission, geometry-bucketed warm
executables, checkpointable query set) driving the PR 10 mesh execution
(keys sharded over the device mesh, psum global folds, canonical
shard-count-portable checkpoints), plus the two things neither half had:

* **the mesh control path** — register/cancel is one replicated row
  write through the shared jitted writer
  (:meth:`~.pipeline.MeshServingPipeline.write_query_slot`); a churn
  burst between steps coalesces into ONE whole-table upload. Admission
  is shard-aware: every tenant hashes to an affinity **home shard**
  (stable under the routing table's key permutation — rebalances move
  keys, not tenants) and ``QueryAdmission.per_shard_quota`` caps the
  active queries any one home shard carries, on top of the global and
  per-tenant caps. All of it with the PR 3 fail|shed discipline and
  generation-checked handles.
* **elastic reshard** — :meth:`reshard` grows or shrinks the shard
  count mid-stream, Megaphone-style, as a checkpoint-boundary
  operation: commit one atomic verified bundle through the Supervisor
  (mesh state in canonical logical-key order + routing sidecar + the
  query table, sealed by one manifest, landed by one rename), rebuild
  the fused step over the new mesh, restore from the just-committed
  bundle. The generated stream is a pure function of
  ``(seed, interval, logical key)`` and the table re-uploads verbatim,
  so emissions across an 8→4→8 walk bit-match an un-resharded run —
  with exactly-once delivery intact (the sink ledger commits inside the
  same bundle) and query churn + hot-key rebalance running
  concurrently.

Retrace accounting is reconciled against the ACTUAL jit trace counter
(a shared cell every step closure increments): steady-state churn must
add zero (``retraces_since_warm``), while the one compile a reshard's
genuinely-new mesh forces is itemized apart as
``mesh_reshard_retraces`` — returning to a previously-seen shard count
re-enters the warm bucket and traces nothing.
"""

from __future__ import annotations

import json
import os
import time
import zlib
from typing import List, Optional, Sequence

import numpy as np

from .. import obs as _obs
from ..engine.config import EngineConfig
from ..engine.pipeline import SlotGeometry
from ..obs import flight as _flight
from ..serving.admission import QueryAdmission, QueryRejected
from ..serving.cache import pad_pow2
from ..serving.service import (check_trigger_budget, emit_tenant_gauges,
                               lanes_for)
from ..serving.table import QueryHandle, QueryTable, window_row
from .pipeline import MeshServingPipeline

MESH_TABLE_SCHEMA = "scotty_tpu.mesh_query_table/1"


def tenant_home_shard(tenant: str, n_shards: int) -> int:
    """A tenant's affinity home shard: a stable content hash of the
    tenant name over the CURRENT shard count. Deterministic across
    processes (crc32, not Python's salted hash) and recomputed after a
    reshard — affinity follows the mesh, the mesh never follows a
    tenant."""
    return zlib.crc32(tenant.encode()) % max(1, int(n_shards))


class MeshQueryService:
    """Register/cancel windows against the sharded mesh pipeline, with
    elastic reshard at checkpoint boundaries (module docstring).

    Construction mirrors :class:`~scotty_tpu.serving.QueryService`:
    ``slice_grid`` and ``max_window_size`` are state-shaping and
    immutable; slot count and trigger lanes rebucket on demand (pre-pad
    ``min_slots``/``min_trigger_lanes`` to the expected peak so
    steady-state churn never rebuckets). ``n_keys`` must be a multiple
    of every shard count the service will ever run at.
    """

    def __init__(self, aggregations: Sequence, *,
                 slice_grid: int,
                 max_window_size: int,
                 n_keys: int,
                 n_shards: Optional[int] = None,
                 throughput: int = 64_000_000,
                 wm_period_ms: int = 1000,
                 max_lateness: int = 1000,
                 seed: int = 0,
                 config: Optional[EngineConfig] = None,
                 admission: Optional[QueryAdmission] = None,
                 windows: Sequence = (),
                 min_slots: int = 8,
                 min_trigger_lanes: int = 4,
                 tenant_gauge_top_k: int = 32,
                 obs=None,
                 trace_cell: Optional[list] = None,
                 **pipeline_kwargs):
        import jax

        self.config = config or EngineConfig()
        self.admission = admission or QueryAdmission()
        self.obs = obs
        self.aggregations = list(aggregations)
        self.slice_grid = int(slice_grid)
        self.max_window_size = int(max_window_size)
        self.n_keys = int(n_keys)
        self.throughput = int(throughput)
        self.wm_period_ms = int(wm_period_ms)
        self.max_lateness = int(max_lateness)
        self.seed = int(seed)
        self.min_slots = int(min_slots)
        self.min_trigger_lanes = int(min_trigger_lanes)
        self.tenant_gauge_top_k = int(tenant_gauge_top_k)
        self._pipeline_kwargs = dict(pipeline_kwargs)
        self._counters: dict = {}
        self._gauged_tenants: set = set()
        #: the shared jit-trace cell every step closure of every pipeline
        #: this service ever builds increments — reshard-rebuilt
        #: pipelines keep counting into the SAME cell, so reconciliation
        #: survives the mesh changing shape under it. The cell's identity
        #: also keys the step cache, isolating services; pass an external
        #: cell to SHARE warm executables across short-lived services
        #: (the crash-point fuzzer's per-site environments do)
        self._trace_cell = trace_cell if trace_cell is not None else [0]
        #: traces already in the cell when this service was born (a
        #: shared cell carries other services' history)
        self._trace_base = self._trace_cell[0]
        self._counted_retraces = 0
        self._reshard_credits = 0
        self._warm_traces = None
        self._warm_credits = 0
        self.reshard_timeline: List[dict] = []

        if n_shards is None:
            n_shards = len(jax.devices())

        rows = [window_row(w, self.slice_grid, self.max_window_size)
                for w in windows]
        lanes = max([self.min_trigger_lanes]
                    + [self._lanes_for(k, g) for (k, g, _) in rows])
        q0 = pad_pow2(max(len(rows), 1), self.min_slots)
        geometry = SlotGeometry(
            n_slots=q0,
            triggers_per_slot=pad_pow2(lanes, self.min_trigger_lanes),
            slice_grid=self.slice_grid, max_size=self.max_window_size)
        self._check_trigger_budget(geometry)
        self.table = QueryTable(geometry.n_slots)
        self.pipeline = self._build_pipeline(int(n_shards), geometry)
        #: traces the initial build will add: none when construction hit
        #: an already-warm step cache (shared trace cell) — a literal 1
        #: there would silently absorb the first REAL recompile
        self._initial_trace_credit = \
            0 if self.pipeline._step_was_cached else 1
        self.pipeline.set_query_rows(self.table.rows)
        #: slots whose host rows changed but whose device rows haven't:
        #: control operations write the host mirror eagerly and the
        #: device LAZILY at the next step (a few slots -> per-row jitted
        #: writes; a churn burst -> one whole-table upload)
        self._dirty: set = set()
        for w, r in zip(windows, rows):
            h = self._admit_row(w, *r, tenant="default")
            if h is None:       # pragma: no cover — seed set under shed
                raise QueryRejected(
                    "seed window set exceeds admission limits", "capacity",
                    "default")

    def _build_pipeline(self, n_shards: int,
                        geometry: Optional[SlotGeometry] = None
                        ) -> MeshServingPipeline:
        return MeshServingPipeline(
            self.aggregations,
            query_slots=geometry or self.geometry,
            n_keys=self.n_keys, n_shards=n_shards, config=self.config,
            throughput=self.throughput, wm_period_ms=self.wm_period_ms,
            max_lateness=self.max_lateness, seed=self.seed,
            trace_cell=self._trace_cell, **self._pipeline_kwargs)

    # -- geometry (the SHARED calculus — serving.service owns it) ----------
    def _lanes_for(self, kind: int, grid: int) -> int:
        return lanes_for(kind, grid, self.wm_period_ms)

    def _check_trigger_budget(self, geometry: SlotGeometry) -> None:
        check_trigger_budget(geometry, self.config.max_triggers)

    @property
    def geometry(self) -> SlotGeometry:
        return self.pipeline._query_slots

    @property
    def n_shards(self) -> int:
        return self.pipeline.n_shards

    @property
    def interval(self) -> int:
        return int(self.pipeline._interval)

    # -- telemetry ---------------------------------------------------------
    def _count(self, name: str, delta: int = 1) -> None:
        self._counters[name] = self._counters.get(name, 0) + delta
        if self.obs is not None:
            self.obs.counter(name).inc(delta)

    def _gauges(self) -> None:
        if self.obs is None:
            return
        self.obs.gauge(_obs.SERVING_ACTIVE_QUERIES).set(self.table.n_active)
        self._gauged_tenants = emit_tenant_gauges(
            self.obs, self.table.tenant_rollup(), self._gauged_tenants,
            self.tenant_gauge_top_k)

    def _flight(self, kind: str, name: str, value: float = 0.0) -> None:
        if self.obs is not None:
            self.obs.flight_event(kind, name, value)

    def _attr(self, tenant: str, family: str, delta: int = 1) -> None:
        """Feed the per-tenant attribution ledger (ISSUE 19) when one is
        attached — same delta as the engine-level counter at every call
        site, so the conservation identity holds by construction."""
        if self.obs is not None:
            attribution = getattr(self.obs, "attribution", None)
            if attribution is not None:
                attribution.count(tenant, family, delta)

    def _reconcile_retraces(self) -> None:
        """Fold ACTUAL jit traces into the counters: the shared trace
        cell minus the initial build and minus the reshard-attributed
        compiles (already itemized as ``mesh_reshard_retraces``) is the
        steady-state ``serving_retraces`` count."""
        extra = (self._trace_cell[0] - self._trace_base
                 - self._initial_trace_credit
                 - self._reshard_credits - self._counted_retraces)
        if extra > 0:
            self._count(_obs.SERVING_RETRACES, extra)
            self._counted_retraces += extra

    def mark_warm(self) -> None:
        """Freeze the warmup trace baseline: :attr:`retraces_since_warm`
        counts jit traces AFTER this point, reshard-attributed compiles
        excluded (they are itemized, not hidden — see
        ``mesh_reshard_retraces``)."""
        self._warm_traces = self._trace_cell[0]
        self._warm_credits = self._reshard_credits

    @property
    def retraces_since_warm(self) -> int:
        if self._warm_traces is None:
            raise ValueError("mark_warm() was never called")
        return (self._trace_cell[0] - self._warm_traces) \
            - (self._reshard_credits - self._warm_credits)

    @property
    def reshard_retraces(self) -> int:
        return self._reshard_credits

    def stats(self) -> dict:
        self._reconcile_retraces()
        out = dict(self._counters)
        out["active_queries"] = self.table.n_active
        out["n_slots"] = self.geometry.n_slots
        out["triggers_per_slot"] = self.geometry.triggers_per_slot
        out["n_shards"] = self.n_shards
        out["trace_count"] = int(self._trace_cell[0] - self._trace_base)
        out["reshard_retraces"] = int(self._reshard_credits)
        out["tenants"] = self.table.tenant_rollup()
        return out

    # -- the control plane (routed through the mesh control path) ----------
    def tenant_shard(self, tenant: str) -> int:
        """The tenant's affinity home shard under the current mesh."""
        return tenant_home_shard(tenant, self.n_shards)

    def _shard_active(self, tenant: str) -> int:
        """Active queries whose tenants share ``tenant``'s home shard."""
        home = self.tenant_shard(tenant)
        return sum(
            1 for i, t in enumerate(self.table.tenants)
            if self.table.active[i] and t is not None
            and self.tenant_shard(t) == home)

    def register(self, window, tenant: str = "default"
                 ) -> Optional[QueryHandle]:
        """Admit + activate one window query across every shard; returns
        its handle, or ``None`` when admission sheds it. Structural
        impossibility raises
        :class:`~scotty_tpu.serving.table.ServingUnsupported` regardless
        of policy."""
        kind, grid, size = window_row(window, self.slice_grid,
                                      self.max_window_size)
        return self._admit_row(window, kind, grid, size, tenant)

    def _admit_row(self, window, kind: int, grid: int, size: int,
                   tenant: str) -> Optional[QueryHandle]:
        reason = self.admission.check(
            self.table.n_active, self.table.tenant_active(tenant), tenant,
            shard_active=self._shard_active(tenant))
        if reason is not None:
            self._count(_obs.SERVING_REJECTED)
            self._attr(tenant, "rejected")
            self._flight(_flight.QUERY_REJECT, f"{tenant}:{window}",
                         float(self.tenant_shard(tenant)))
            if self.admission.reject_callback is not None:
                self.admission.reject_callback(window, tenant, reason)
            if self.admission.on_reject == "fail":
                raise QueryRejected(
                    self.admission.reject_message(reason, tenant),
                    reason, tenant)
            return None

        geom = self.geometry
        lanes = self._lanes_for(kind, grid)
        want_lanes = geom.triggers_per_slot
        want_slots = geom.n_slots
        if lanes > want_lanes:
            want_lanes = pad_pow2(lanes, self.min_trigger_lanes)
        if self.table.n_free == 0:
            want_slots = pad_pow2(self.table.n_slots + 1, self.min_slots)
        if want_lanes != geom.triggers_per_slot \
                or want_slots != geom.n_slots:
            # a register that forces a COLD bucket is the retrace this
            # tenant caused — itemized on the ledger at the forcing site
            miss_before = self._counters.get(_obs.SERVING_CACHE_MISSES, 0)
            self._rebucket(want_slots, want_lanes)
            if self._counters.get(_obs.SERVING_CACHE_MISSES,
                                  0) > miss_before:
                self._attr(tenant, "retraces")
        else:
            self._count(_obs.SERVING_CACHE_HITS)

        handle = self.table.allocate(kind, grid, size, tenant)
        self._dirty.add(handle.slot)
        self._count(_obs.SERVING_REGISTERED)
        self._attr(tenant, "registered")
        self._flight(_flight.MESH_QUERY_REGISTER, f"{tenant}:{window}",
                     float(self.tenant_shard(tenant)))
        self._gauges()
        return handle

    def cancel(self, handle: QueryHandle) -> None:
        """Deactivate a query: one replicated device mask write; the
        slot recycles LIFO with its generation bumped (stale handles —
        including pre-reshard copies — are rejected)."""
        slot = self.table.release(handle)
        self._dirty.add(slot)
        self._count(_obs.SERVING_CANCELLED)
        self._attr(handle.tenant, "cancelled")
        self._flight(_flight.MESH_QUERY_CANCEL,
                     f"{handle.tenant}:slot{slot}",
                     float(self.tenant_shard(handle.tenant)))
        self._gauges()

    def active_handles(self) -> dict:
        """``{slot: QueryHandle}`` for every active slot, reconstructed
        from the authoritative table — the supervised drivers' restart
        path (a restore replays the exact active set, but the caller's
        in-memory handles died with the crashed process)."""
        out = {}
        for s in np.flatnonzero(self.table.active):
            s = int(s)
            out[s] = QueryHandle(
                slot=s, gen=int(self.table.gens[s]),
                kind=int(self.table.kinds[s]),
                grid=int(self.table.grids[s]),
                size=int(self.table.sizes[s]),
                tenant=self.table.tenants[s])
        return out

    def _rebucket(self, n_slots: int, lanes: int) -> None:
        geom = SlotGeometry(n_slots=n_slots, triggers_per_slot=lanes,
                            slice_grid=self.slice_grid,
                            max_size=self.max_window_size)
        self._check_trigger_budget(geom)
        if geom.n_slots > self.table.n_slots:
            self.table.grow(geom.n_slots)
        self.pipeline.set_slot_geometry(geom)
        if self.pipeline._step_was_cached:
            self._count(_obs.SERVING_CACHE_HITS)
        else:
            self._count(_obs.SERVING_CACHE_MISSES)
            # the fresh closure traces on its next call; serving_retraces
            # counts ACTUAL traces via _reconcile_retraces, not misses
        self.pipeline.set_query_rows(self.table.rows)
        self._dirty.clear()               # the upload carried every row
        self._flight(_flight.QUERY_REBUCKET,
                     f"{geom.n_slots}x{geom.triggers_per_slot}")

    def compact(self) -> bool:
        """Walk the slot grid back down to the active set's needs
        (padded) — usually onto a warm bucket. Same contract as the
        single-device service: retired generations survive, stale
        handles stay dead."""
        geom = self.geometry
        occupied = np.flatnonzero(self.table.active)
        top = int(occupied.max()) + 1 if occupied.size else 0
        want_slots = pad_pow2(max(top, 1), self.min_slots)
        active_lanes = [self._lanes_for(int(self.table.kinds[s]),
                                        int(self.table.grids[s]))
                        for s in occupied]
        want_lanes = pad_pow2(max(active_lanes, default=1),
                              self.min_trigger_lanes)
        if want_slots >= geom.n_slots and want_lanes >= \
                geom.triggers_per_slot:
            return False
        want_slots = min(want_slots, geom.n_slots)
        want_lanes = min(want_lanes, geom.triggers_per_slot)
        self.table.shrink(want_slots)
        self._rebucket(want_slots, want_lanes)
        return True

    def _sync_table(self) -> None:
        """Flush pending control-plane writes to every shard's replica:
        up to a handful of slots as single jitted row writes, a churn
        burst as ONE whole-table upload."""
        if not self._dirty:
            return
        if len(self._dirty) <= 4:
            for slot in sorted(self._dirty):
                self.pipeline.write_query_slot(
                    slot, int(self.table.kinds[slot]),
                    int(self.table.grids[slot]),
                    int(self.table.sizes[slot]),
                    bool(self.table.active[slot]))
        else:
            self.pipeline.set_query_rows(self.table.rows)
        self._dirty.clear()

    # -- the data plane ----------------------------------------------------
    def run(self, n_intervals: int, collect: bool = True):
        self._sync_table()
        out = self.pipeline.run(n_intervals, collect=collect)
        self._reconcile_retraces()
        return out

    def sync(self) -> int:
        return self.pipeline.sync()

    def check_overflow(self) -> None:
        self.pipeline.check_overflow()

    def set_observability(self, obs) -> None:
        self.obs = obs
        self.pipeline.set_observability(obs)
        self._gauges()

    # -- result attribution -------------------------------------------------
    def _check_rows(self, n_rows: int) -> int:
        K = self.geometry.triggers_per_slot
        if n_rows != self.geometry.n_slots * K:
            raise ValueError(
                f"interval output has {n_rows} trigger rows but the "
                f"CURRENT geometry is {self.geometry.n_slots} x {K}: the "
                "service rebucketed since this output was produced — "
                "attribute results before registering queries that change "
                "the bucket")
        return K

    def global_rows_by_slot(self, interval_out) -> dict:
        """One interval's PSUM-FOLDED all-keys emissions attributed to
        slots: ``{slot: [(start, end, count, [values...]), ...]}`` —
        the in-executable global fold's host face; one tiny ``[T]``
        fetch."""
        ws, we, gcnt, lowered = self.pipeline.lowered_global(interval_out)
        K = self._check_rows(ws.shape[0])
        out: dict = {}
        for i in range(ws.shape[0]):
            if gcnt[i] > 0:
                out.setdefault(i // K, []).append(
                    (int(ws[i]), int(we[i]), int(gcnt[i]),
                     [lw[i] for lw in lowered]))
        return out

    def account_emissions(self, rows_by_slot: dict,
                          watermark: Optional[float] = None) -> None:
        """Fold one interval's slot-attributed global emissions into the
        attached per-tenant attribution plane (ISSUE 19): windows and
        late repairs per owning tenant, plus per-query freshness. A
        no-op without ``obs.attribution``; host-side only (the rows
        were already fetched by :meth:`global_rows_by_slot`, the
        watermark is the host interval counter — zero device syncs)."""
        attribution = getattr(self.obs, "attribution", None) \
            if self.obs is not None else None
        if attribution is None:
            return
        if watermark is None:
            watermark = float(self.interval * self.wm_period_ms)
        slot_tenant = {int(s): self.table.tenants[int(s)]
                       for s in np.flatnonzero(self.table.active)}
        attribution.account_rows(rows_by_slot, slot_tenant,
                                 float(watermark),
                                 float(self.wm_period_ms))

    def key_rows_by_slot(self, interval_out, key_idx: int) -> dict:
        """One LOGICAL key's emissions attributed to slots (a device
        row-gather before the fetch — sampling keys never pulls the full
        ``[K, T]`` block)."""
        ws, we, cnt_k, lowered = self.pipeline.per_key_columns(
            interval_out, key_idx)
        K = self._check_rows(ws.shape[0])
        out: dict = {}
        for i in range(ws.shape[0]):
            if cnt_k[i] > 0:
                out.setdefault(i // K, []).append(
                    (int(ws[i]), int(we[i]), int(cnt_k[i]),
                     [lw[i] for lw in lowered]))
        return out

    # -- checkpoint / restore ------------------------------------------------
    def save(self, path: str) -> None:
        """Snapshot mesh state (canonical logical-key order + routing
        sidecar) PLUS the query table INTO THE SAME BUNDLE, so the
        Supervisor's manifest seals them together and a restore replays
        the exact active query set at any shard count — atomically or
        not at all."""
        self._sync_table()
        self.pipeline.save(path)
        geom = self.geometry
        doc = {
            "schema": MESH_TABLE_SCHEMA,
            "table": self.table.state_dict(),
            "geometry": {
                "n_slots": geom.n_slots,
                "triggers_per_slot": geom.triggers_per_slot,
                "slice_grid": geom.slice_grid,
                "max_size": geom.max_size,
            },
            "saved_n_shards": self.n_shards,
        }
        from ..utils import fsio

        tmp = os.path.join(path, f"query_table.json.tmp.{os.getpid()}")
        fsio.write_bytes(tmp, json.dumps(doc, indent=1).encode())
        fsio.replace(tmp, os.path.join(path, "query_table.json"))

    def restore(self, path: str, verify: bool = True) -> None:
        """Restore mesh state + query table into this service at its
        CURRENT shard count (the N→M portability of the canonical
        snapshot is what makes restore the second half of a reshard).
        The table re-uploads before the first post-restore interval."""
        with open(os.path.join(path, "query_table.json")) as f:
            doc = json.load(f)
        if doc.get("schema") != MESH_TABLE_SCHEMA:
            raise ValueError(
                f"{path}: not a mesh-serving checkpoint "
                f"(schema={doc.get('schema')!r})")
        gd = doc["geometry"]
        if int(gd["slice_grid"]) != self.slice_grid \
                or int(gd["max_size"]) != self.max_window_size:
            raise ValueError(
                "mesh-serving checkpoint was taken under a different "
                "slice grid / retention bound — construct the service "
                "with the same slice_grid and max_window_size as saved")
        geom = SlotGeometry(n_slots=int(gd["n_slots"]),
                            triggers_per_slot=int(gd["triggers_per_slot"]),
                            slice_grid=self.slice_grid,
                            max_size=self.max_window_size)
        self.table = QueryTable.from_state_dict(doc["table"])
        if geom != self.geometry:
            self._rebucket(geom.n_slots, geom.triggers_per_slot)
        self.pipeline.set_query_rows(self.table.rows)
        self._dirty.clear()
        self.pipeline.restore(path, verify=verify)
        self._gauges()

    # -- elasticity: reshard at checkpoint boundaries ------------------------
    def reshard(self, n_shards: int, supervisor, pos: int) -> dict:
        """Grow/shrink the shard count mid-stream (module docstring):
        one atomic verified commit of the CURRENT state + query table,
        then rebuild the fused step over the new mesh and restore from
        the just-committed bundle. A crash anywhere inside restores that
        bundle — whose canonical order lands correctly at EITHER shard
        count — with the sink ledger (committed in the same bundle)
        keeping delivery exactly-once across the replay."""
        old = self.n_shards
        if int(n_shards) == old:
            return {"resharded": False, "from": old, "to": old}
        if self.n_keys % int(n_shards):
            raise ValueError(
                f"cannot reshard to {n_shards}: n_keys {self.n_keys} "
                "must stay a positive multiple of the shard count")
        t0 = time.perf_counter()
        self._sync_table()
        self.pipeline.sync()
        self.pipeline.check_overflow()
        supervisor.commit_checkpoint(pos, self.save)
        self.pipeline = self._build_pipeline(int(n_shards))
        if not self.pipeline._step_was_cached:
            # the one compile a genuinely-new mesh forces — itemized
            # apart from steady-state serving_retraces (it will land in
            # the trace cell when the first post-reshard step runs)
            self._reshard_credits += 1
            self._count(_obs.MESH_RESHARD_RETRACES)
            # no single tenant forced a reshard compile: apportion it
            # across the active set (largest remainder, exact) so the
            # ledger's retrace total still conserves against the
            # engine's itemized count
            attribution = getattr(self.obs, "attribution", None) \
                if self.obs is not None else None
            if attribution is not None:
                attribution.apportion_count(
                    "retraces", 1, self.table.tenant_rollup())
        # restore from THE bundle the commit above just landed — not the
        # lineage walk's "newest that verifies": a fallback there would
        # silently rewind the stream (and re-emit intervals) instead of
        # surfacing the torn commit. verify=True re-checks the digests
        # on read; failure raises CheckpointIntegrityError, which a
        # supervised caller's restart path handles with the lineage
        # fallback AND the matching churn replay.
        ckpt = os.path.join(supervisor.dir, f"ckpt-{pos}")
        self.restore(ckpt, verify=True)
        wall_ms = (time.perf_counter() - t0) * 1e3
        self._count(_obs.MESH_RESHARDS)
        self._flight(_flight.MESH_RESHARD, f"{old}->{int(n_shards)}",
                     float(n_shards))
        row = {"resharded": True, "from": old, "to": int(n_shards),
               "at_interval": self.interval,
               "wall_ms": round(wall_ms, 2)}
        self.reshard_timeline.append(row)
        return row

    # -- hot-key rebalance (concurrent with churn) ---------------------------
    def rebalance_keys(self, swaps, supervisor, pos: int) -> dict:
        """Apply a hot-key swap plan at a checkpoint boundary: commit
        the atomic bundle FIRST (a crash mid-move restores the pre-move
        layout), then permute the carried rows. The query table is
        replicated, not row-sharded, so rebalance and query churn
        compose freely."""
        self._sync_table()
        self.pipeline.sync()
        supervisor.commit_checkpoint(pos, self.save)
        swaps = list(swaps)
        if swaps:
            self.pipeline.rebalance(swaps)
            self._count(_obs.MESH_REBALANCES)
            self._count(_obs.MESH_KEYS_MOVED, 2 * len(swaps))
            self._flight(_flight.MESH_REBALANCE, f"{len(swaps)}swaps",
                         2 * len(swaps))
        return {"moved": 2 * len(swaps)}
