"""Multi-tenant mesh serving + elastic reshard (ISSUE 13).

One service from millions of keys to millions of queries: the dynamic
query-serving layer (scotty_tpu.serving, PR 6) fused into the
mesh-sharded keyed step (scotty_tpu.mesh, PR 10), with the shard count
itself elastic at checkpoint boundaries.

* :class:`MeshServingPipeline` — the fused ``shard_map`` step whose
  window set is a replicated :class:`~scotty_tpu.engine.pipeline.
  QuerySlots` table in the donated carry; per-key AND psum-folded
  global answers per query, zero steady-state retraces.
* :class:`MeshQueryService` — the control plane: shard-aware admission
  with tenant home-shard affinity, generation-checked handles, the
  query table checkpointed atomically alongside mesh state, and
  :meth:`~MeshQueryService.reshard` — grow/shrink the shard count
  mid-stream through one atomic verified bundle.
* :func:`run_supervised_mesh` — the supervised exactly-once driver the
  crash-point fuzzer certifies and the demo/bench reuse.
"""

from .pipeline import MeshServingPipeline
from .service import MeshQueryService, tenant_home_shard
from .supervised import apply_churn, run_supervised_mesh, shards_scheduled

__all__ = [
    "MeshServingPipeline",
    "MeshQueryService",
    "tenant_home_shard",
    "run_supervised_mesh",
    "apply_churn",
    "shards_scheduled",
]
