"""MeshServingPipeline: the dynamic-query serving step under shard_map.

The fusion ISSUE 13 names: PR 6's serving machinery (a ``[Q]``
window-parameter table + active mask carried in the jitted step's
donated state, trigger rows enumerated from table DATA so register/
cancel never retraces) composed with PR 10's mesh execution (keys
sharded over the mesh axis, donated carries, in-executable psum global
folds, routing-table row attribution, shard-count-portable canonical
checkpoints). One step answers every active query twice per interval:

* **per key** — the per-shard vmapped range query over that shard's
  ``K // n_shards`` rows, exactly the MeshKeyedPipeline contract but
  with the trigger rows read from the carried
  :class:`~scotty_tpu.engine.pipeline.QuerySlots`;
* **global** — all-keys window totals folded with ``psum``/``pmin``/
  ``pmax`` INSIDE the executable (the ``parallel/global_op.py`` seam,
  ``mesh/engine.py`` ``query_global``'s in-step twin).

Carry layout: ``{"buf": SliceBufferState[K, ...], "keys": i32[K]}``
sharded over the key axis, plus the :class:`QuerySlots` table
REPLICATED across shards (``PartitionSpec()``) — every shard reads the
same query set, so a register/cancel is ONE replicated row write
through the shared jitted writer, and the whole carry (buf, keys, AND
table) is donated: steady state moves zero extra bytes for the table.

The engine state is query-set independent (the keyed generator fills
every slice row regardless), so a query registered mid-stream
immediately answers windows over slices ingested before it existed —
shared slicing at mesh scale, the property the always-active
superset-replay oracle (tests/test_mesh_serving.py) rests on.

Elasticity contract: :meth:`save` writes the canonical LOGICAL-key-order
snapshot (``utils/checkpoint.py save_mesh_state``), so a bundle saved
under N shards restores under M (the reshard path
:class:`~scotty_tpu.mesh_serving.service.MeshQueryService` drives at
checkpoint boundaries); the generated stream is a pure function of
``(seed, interval, logical key)``, so 8-shard, 4-shard, post-reshard and
post-rebalance runs all BIT-MATCH.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np

from ..core.aggregates import AggregateFunction
from ..engine.config import EngineConfig
from ..engine.pipeline import (
    FusedPipelineDriver,
    QuerySlots,
    SlotGeometry,
    build_slot_trigger_grid,
)
from ..mesh.engine import _mesh_token, _shard_map, make_row_permuter
from ..mesh.routing import RoutingTable

#: jitted (step, gc) per (geometry, aggs, shapes, mesh, trace-cell id)
#: — a service's reshard walk (8→4→8) re-enters warm buckets without
#: retracing; the cell id isolates services so one service's trace
#: accounting can never observe another's executions. BOUNDED, unlike
#: the mesh kernel caches it parallels: the per-service keying means a
#: long-lived process churning services would otherwise accumulate
#: compiled shard_map executables forever (eviction only drops the
#: warm-re-entry shortcut — live pipelines hold their own step refs)
_SERVING_STEP_CACHE: dict = {}
_SERVING_STEP_CACHE_CAP = 64


def _cache_put(key, value) -> None:
    _SERVING_STEP_CACHE[key] = value
    while len(_SERVING_STEP_CACHE) > _SERVING_STEP_CACHE_CAP:
        _SERVING_STEP_CACHE.pop(next(iter(_SERVING_STEP_CACHE)))


class MeshServingPipeline(FusedPipelineDriver):
    """Fused mesh pipeline whose window set is the carried query table
    (module docstring). Constructed by
    :class:`~scotty_tpu.mesh_serving.service.MeshQueryService`; direct
    construction is the differential tests' oracle path.
    """

    def __init__(self, aggregations: Sequence[AggregateFunction], *,
                 query_slots: SlotGeometry, n_keys: int,
                 n_shards: Optional[int] = None,
                 config: Optional[EngineConfig] = None,
                 throughput: int = 64_000_000, wm_period_ms: int = 1000,
                 max_lateness: int = 1000, seed: int = 0,
                 gc_every: int = 8, max_chunk_elems: int = 1 << 24,
                 value_scale: float = 10_000.0, mesh=None,
                 axis: str = "keys", trace_cell: Optional[list] = None):
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ..engine import core as ec
        from ..engine.pipeline import draw_uniform16

        if mesh is not None:
            n_shards = mesh.devices.size
        elif n_shards is None:
            n_shards = len(jax.devices())
        if mesh is None:
            from ..parallel import make_mesh

            mesh = make_mesh(axis, n_devices=n_shards)
        self.mesh, self.axis = mesh, axis
        self.n_shards = int(n_shards)
        self.config = config or EngineConfig()
        self.aggregations = list(aggregations)
        self.n_keys = K = int(n_keys)
        self.routing = RoutingTable(K, self.n_shards)
        self.wm_period_ms = P_ms = int(wm_period_ms)
        self.max_lateness = int(max_lateness)
        self.gc_every = gc_every
        self.seed = seed
        self.value_scale = float(value_scale)
        #: shared mutable jit-trace counter (cell[0]): the serving layer
        #: reads it ACROSS reshard-rebuilt pipelines, so it is a cell the
        #: step closures capture, not a per-pipeline attribute
        self._trace_cell = trace_cell if trace_cell is not None else [0]

        g = int(query_slots.slice_grid)
        if P_ms % g:
            raise ValueError(
                f"SlotGeometry.slice_grid {g} must divide wm_period_ms "
                f"{P_ms}")
        self._query_slots = query_slots
        self._qs_host = None
        # GC retention is the ADMISSION bound, not any live window's
        # size: slices must survive long enough for any query registered
        # later (the shared-slicing property)
        self.max_fixed = int(query_slots.max_size)

        aggs = tuple(a.device_spec() for a in self.aggregations)
        if any(a is None for a in aggs):
            raise NotImplementedError(
                "mesh serving pipeline: device-realizable aggregations "
                "only")
        per_key = throughput // K
        R = per_key * g // 1000
        if R < 1:
            raise ValueError(
                f"throughput {throughput} too low: <1 tuple/slice/key at "
                f"{K} keys on a {g} ms grid")
        S = P_ms // g
        self.grid, self.R, self.S = g, R, S
        self.tuples_per_interval = K * S * R

        spec = ec.EngineSpec(periods=(g,), bands=(), count_periods=(),
                             aggs=aggs)
        self.spec = spec
        C, A = self.config.capacity, self.config.annex_capacity
        self._query1 = ec.build_query(spec, C, A)
        self._gc1 = ec.build_gc(spec, C, A)

        # chunking bounds the [Kl, S, Rc, width] lift temporary per shard
        max_width = max(1 if a.is_sparse else a.width for a in aggs)
        n_chunks = 1
        while (K * S * (R // n_chunks) * max_width) > max_chunk_elems \
                and n_chunks < R:
            n_chunks += 1
        while R % n_chunks:
            n_chunks += 1
        self._n_chunks, self._rc = n_chunks, R // n_chunks

        sharding = NamedSharding(mesh, P(axis))
        self._sharding = sharding
        self._qs_sharding = NamedSharding(mesh, P())
        self._permute_fn = None
        self._write_slot_fn = None
        self._root = None
        self.state = None
        self._qstate = None
        self._interval = 0

        self._build_step()

        def init_buf():
            one = ec.init_state(spec, C, A)
            buf = jax.tree.map(
                lambda x: jnp.broadcast_to(x, (K,) + x.shape), one)
            kids = jnp.asarray(self.routing.key_at, jnp.int32)
            return jax.device_put({"buf": buf, "keys": kids}, sharding)

        self._init_buf = init_buf
        # draw_uniform16 is closed over by _build_step via gen_chunk;
        # keep a handle for the host replay face
        self._draw = draw_uniform16

    # -- the fused step (cached per geometry bucket + mesh) -----------------
    def _build_step(self) -> None:
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        from ..engine.pipeline import draw_uniform16

        geometry = self._query_slots
        aggs = self.spec.aggs
        K, S, R = self.n_keys, self.S, self.R
        g, P_ms = self.grid, self.wm_period_ms
        C = self.config.capacity
        n_chunks, Rc = self._n_chunks, self._rc
        value_scale = self.value_scale
        query1 = self._query1
        gc1 = self._gc1
        first_lw = max(0, P_ms - self.max_lateness)
        cell = self._trace_cell

        cache_key = (
            (geometry.n_slots, geometry.triggers_per_slot,
             geometry.slice_grid, geometry.max_size),
            tuple(ag.token for ag in aggs), K,
            C, self.config.annex_capacity, R, S, g, P_ms,
            self.max_lateness, value_scale, n_chunks, Rc,
            _mesh_token(self.mesh, self.axis), id(cell))
        hit = _SERVING_STEP_CACHE.get(cache_key)
        make_triggers, self.T = build_slot_trigger_grid(geometry, P_ms)
        self._make_triggers = make_triggers
        #: whether this bucket's executable was already warm — the
        #: reshard retrace accounting reads it: a fresh closure traces
        #: exactly once on its first call, a cached one never does
        self._step_was_cached = hit is not None
        if hit is not None:
            self._step, self._gc_fn = hit
            return

        red = {"sum": jnp.sum, "min": jnp.min, "max": jnp.max}
        coll = {"sum": jax.lax.psum, "min": jax.lax.pmin,
                "max": jax.lax.pmax}
        shard_map = _shard_map()
        a_name = self.axis
        mesh = self.mesh

        def gen_chunk(kg, kids):
            """[Kl, S, Rc] values for one chunk, threefry keyed by the
            LOGICAL key id — identical under any shard count, routing,
            rebalance or reshard (the invariance every differential and
            the reshard contract rest on; same keying discipline as
            MeshKeyedPipeline)."""
            keys_k = jax.vmap(lambda kid: jax.random.fold_in(
                kg, kid.astype(jnp.uint32)))(kids)
            return jax.vmap(
                lambda k: draw_uniform16(k, (S, Rc), value_scale))(keys_k)

        def shard_body(state, qs, key, interval_idx):
            # host-side trace counter: this body runs once per jit
            # TRACE (the serving layer's zero-retrace contract reads
            # it); no traced ops — the emitted HLO is unchanged
            cell[0] += 1
            buf, kids = state["buf"], state["keys"]
            Kl = kids.shape[0]
            base = interval_idx * P_ms

            def body(parts_c, c):
                vals = gen_chunk(jax.random.fold_in(key, c), kids)
                flat = vals.reshape(-1)
                new_parts = []
                for aspec, acc in zip(aggs, parts_c):
                    if aspec.is_sparse:
                        col, v = aspec.lift_sparse(flat)
                        row_id = jnp.arange(Kl * S * Rc,
                                            dtype=jnp.int32) // Rc
                        fi = row_id * aspec.width + col.astype(jnp.int32)
                        tgt = jnp.full((Kl * S * aspec.width,),
                                       aspec.identity, jnp.float32)
                        if aspec.kind == "sum":
                            tgt = tgt.at[fi].add(v)
                        elif aspec.kind == "min":
                            tgt = tgt.at[fi].min(v)
                        else:
                            tgt = tgt.at[fi].max(v)
                        upd = tgt.reshape(Kl, S, aspec.width)
                    else:
                        lifted = aspec.lift_dense(flat) \
                            .reshape(Kl, S, Rc, -1)
                        upd = red[aspec.kind](lifted, axis=2)
                    if aspec.kind == "sum":
                        new_parts.append(acc + upd)
                    elif aspec.kind == "min":
                        new_parts.append(jnp.minimum(acc, upd))
                    else:
                        new_parts.append(jnp.maximum(acc, upd))
                return tuple(new_parts), None

            init = tuple(jnp.full((Kl, S, ag.width), ag.identity,
                                  jnp.float32) for ag in aggs)
            parts, _ = jax.lax.scan(body, init, jnp.arange(n_chunks))

            row_starts = base + g * jnp.arange(S, dtype=jnp.int64)
            n = buf.n_slices                                  # [Kl] i32

            def app1(b, rows, nn):
                idx = (nn,) + (jnp.int32(0),) * (b.ndim - 1)
                return jax.lax.dynamic_update_slice(
                    b, rows.astype(b.dtype), idx)

            app = jax.vmap(app1)
            rs_k = jnp.broadcast_to(row_starts, (Kl, S))
            buf = buf._replace(
                starts=app(buf.starts, rs_k, n),
                ends=app(buf.ends, rs_k + g, n),
                t_first=app(buf.t_first, rs_k, n),
                t_last=app(buf.t_last, rs_k + (g - 1), n),
                c_start=app(buf.c_start, buf.current_count[:, None]
                            + R * jnp.arange(S, dtype=jnp.int64)[None, :],
                            n),
                counts=app(buf.counts, jnp.full((Kl, S), R, jnp.int64),
                           n),
                partials=tuple(app(p, pr, n)
                               for p, pr in zip(buf.partials, parts)),
                n_slices=n + S,
                max_event_time=jnp.maximum(
                    buf.max_event_time, rs_k[:, -1] + (g - 1)),
                current_count=buf.current_count + S * R,
                overflow=buf.overflow | (n + S > C),
            )
            last_wm = jnp.where(interval_idx > 0, base, jnp.int64(first_lw))
            # trigger rows are TABLE DATA: registering or cancelling a
            # query changes qs, never this program — the zero-retrace
            # property, now replicated across every shard
            ws, we, tmask = make_triggers(qs, last_wm, base + P_ms)
            cnt, results = jax.vmap(
                query1, in_axes=(0, None, None, None, None))(
                buf, ws, we, tmask, jnp.zeros_like(tmask))
            # the cross-shard fold: all-keys window totals per query
            # trigger row INSIDE the executable (psum over ICI on a real
            # mesh) — the global_op.py seam serving the dynamic set
            gcnt = jax.lax.psum(jnp.sum(cnt, axis=0), a_name)
            gparts = tuple(
                coll[ag.kind](red[ag.kind](r, axis=0), a_name)
                for ag, r in zip(aggs, results))
            return ({"buf": buf, "keys": kids}, qs,
                    (ws, we, cnt, results, gcnt, gparts))

        Pa = P(a_name)
        state_spec = {"buf": Pa, "keys": Pa}
        qs_spec = QuerySlots(P(), P(), P(), P())
        hit = (
            jax.jit(shard_map(
                shard_body, mesh=mesh,
                in_specs=(state_spec, qs_spec, P(), P()),
                out_specs=(state_spec, qs_spec,
                           (P(), P(), Pa, Pa, P(), P()))),
                donate_argnums=(0, 1)),
            jax.jit(shard_map(
                lambda st, b: {"buf": jax.vmap(
                    gc1, in_axes=(0, None))(st["buf"], b),
                    "keys": st["keys"]},
                mesh=mesh, in_specs=(state_spec, P()),
                out_specs=state_spec),
                donate_argnums=0),
        )
        _cache_put(cache_key, hit)
        self._step, self._gc_fn = hit

    @property
    def _trace_count(self) -> int:
        return self._trace_cell[0]

    # -- driver hooks -------------------------------------------------------
    def _init_pipeline_state(self) -> None:
        self.state = self._init_buf()
        self._qstate = self._upload_qs(self._qs_host)

    def _upload_qs(self, rows: Optional[dict]):
        import jax
        import jax.numpy as jnp

        Q = self._query_slots.n_slots
        if rows is None:
            kinds = np.zeros((Q,), np.int32)
            grids = np.ones((Q,), np.int64)
            sizes = np.ones((Q,), np.int64)
            active = np.zeros((Q,), bool)
        else:
            kinds = np.asarray(rows["kinds"], np.int32)
            grids = np.asarray(rows["grids"], np.int64)
            sizes = np.asarray(rows["sizes"], np.int64)
            active = np.asarray(rows["active"], bool)
            if kinds.shape != (Q,):
                raise ValueError(
                    f"query-table rows have {kinds.shape[0]} slots, "
                    f"geometry expects {Q}")
        # REPLICATED across the mesh: every shard reads the same table
        dev = jax.device_put(
            (jnp.asarray(kinds), jnp.asarray(grids), jnp.asarray(sizes),
             jnp.asarray(active)), self._qs_sharding)
        return QuerySlots(*dev)

    def _step_interval(self, key, i: int):
        import jax

        iv = jax.device_put(np.int64(i))
        self.state, self._qstate, res = self._step(
            self.state, self._qstate, key, iv)
        return res

    def _gc(self, bound) -> None:
        self.state = self._gc_fn(self.state, bound)

    def _sync_anchor(self):
        return self.state["buf"].n_slices[0]

    def check_overflow(self) -> None:
        import jax

        if bool(np.any(jax.device_get(self.state["buf"].overflow))):
            raise RuntimeError(
                "slice buffer overflow on some key shard: raise capacity "
                "or gc more often")

    # -- the control path (one shared jitted row writer) --------------------
    def set_query_rows(self, rows: Optional[dict]) -> None:
        """Bind the HOST mirror of the query table (held by reference —
        the serving layer's QueryTable rows). ``reset()`` and checkpoint
        restores re-upload from this mirror, so a restore replays the
        exact active query set at the new shard count."""
        self._qs_host = rows
        if getattr(self, "_pipeline_ready", False):
            self._qstate = self._upload_qs(rows)

    def write_query_slot(self, slot: int, kind: int, grid: int, size: int,
                         active: bool) -> None:
        """One replicated row write — the register/cancel hot path
        routed through the mesh control path. Slot and parameters are
        traced arguments, so every write (any slot, any window, any
        tenant) reuses ONE compiled executable; the table is donated and
        updated in place on every shard's replica."""
        import jax

        if self._qstate is None:
            self.reset()
        if self._write_slot_fn is None:
            qs_sh = jax.tree.map(lambda _: self._qs_sharding, self._qstate)

            def w(qs, i, kind, grid, size, act):
                return QuerySlots(
                    kinds=qs.kinds.at[i].set(kind),
                    grids=qs.grids.at[i].set(grid),
                    sizes=qs.sizes.at[i].set(size),
                    active=qs.active.at[i].set(act))

            self._write_slot_fn = jax.jit(w, donate_argnums=0,
                                          out_shardings=qs_sh)
        self._qstate = self._write_slot_fn(
            self._qstate, np.int32(slot), np.int32(kind), np.int64(grid),
            np.int64(size), np.bool_(active))

    def set_slot_geometry(self, geometry: SlotGeometry) -> None:
        """Rebuild the step at a new slot-grid bucket (a counted retrace
        unless the bucket is already warm in the module cache). The
        carried slice state is untouched — its shapes are independent of
        the query set — so a rebucket continues the stream exactly."""
        if int(geometry.slice_grid) != self.grid:
            raise ValueError(
                f"slot-geometry slice grid {geometry.slice_grid} != the "
                f"pipeline's aligned grid {self.grid}: the slice grid is "
                "state-shaping and cannot change at a rebucket")
        if int(geometry.max_size) != self.max_fixed:
            raise ValueError(
                "SlotGeometry.max_size is the GC retention bound and "
                "cannot change at a rebucket")
        self._query_slots = geometry
        self._build_step()

    def compiled_step(self):
        """(step, gc, make_triggers, T, geometry) — what the serving
        compile cache stores per bucket."""
        return (self._step, self._gc_fn, self._make_triggers, self.T,
                self._query_slots)

    def adopt_compiled_step(self, entry) -> None:
        """Re-enter a previously compiled bucket (cache hit): swap the
        jitted step back in without building a fresh closure — reuses
        the warm executable, traces nothing."""
        step, gc_fn, make_triggers, T, geometry = entry
        if int(geometry.slice_grid) != self.grid:
            raise ValueError("cached bucket was built for a different "
                             "slice grid")
        self._step = step
        self._gc_fn = gc_fn
        self._make_triggers = make_triggers
        self.T = T
        self._query_slots = geometry

    # -- rebalance (checkpoint boundaries only) -----------------------------
    def rebalance(self, swaps: Sequence[Tuple[int, int]]) -> None:
        """Permute the carried rows to a swapped routing table (the
        MeshKeyedPipeline contract: one jitted gather, logical-key-id
        generation makes subsequent emissions bit-identical). Call at
        checkpoint boundaries only — concurrent with query churn is fine
        (the table is replicated, not row-permuted)."""
        if not swaps:
            return
        if self.state is None:
            raise RuntimeError("pipeline not started")
        new_table = self.routing.swapped(list(swaps))
        perm = new_table.permutation_from(self.routing)
        if self._permute_fn is None:
            self._permute_fn = make_row_permuter(self.state,
                                                 self._sharding)
        self.state = self._permute_fn(self.state, perm)
        self.routing = new_table

    # -- checkpoint (canonical logical order; shard-count-portable) --------
    def save(self, path: str) -> None:
        from ..utils.checkpoint import save_mesh_state

        if self.state is None or self._root is None:
            raise ValueError("pipeline not started; nothing to checkpoint")
        save_mesh_state(self.state["buf"], self.routing, path, {
            "pipeline": type(self).__name__,
            "interval": int(self._interval), "seed": int(self.seed),
            "root": np.asarray(self._root).tolist(),
        })

    def restore(self, path: str, verify: bool = True) -> None:
        import jax
        import jax.numpy as jnp

        from ..utils.checkpoint import load_mesh_state

        self.reset()
        tree, meta = load_mesh_state(path, self.state["buf"], self.routing,
                                     verify=verify)
        if int(self.seed) != meta["seed"]:
            raise ValueError("seed mismatch: the restored stream would "
                             "differ")
        self.state = jax.device_put(
            {"buf": tree, "keys": jnp.asarray(self.routing.key_at,
                                              jnp.int32)},
            self._sharding)
        self._interval = meta["interval"]
        self._root = jnp.asarray(np.asarray(meta["root"], np.uint32))

    # -- host replay + result attribution ----------------------------------
    def materialize_interval(self, i: int, key_idx: int):
        """Regenerate LOGICAL key ``key_idx``'s interval-i stream on host
        (testing): (vals f32, ts i64) — bit-identical to the device
        generator under any shard count, routing, or reshard."""
        import jax
        import jax.numpy as jnp

        if self._root is None:
            self._root = jax.random.PRNGKey(self.seed)
        key = self._interval_key(i)
        vals_all, ts_all = [], []
        row_starts = i * self.wm_period_ms \
            + self.grid * np.arange(self.S, dtype=np.int64)
        for c in range(self._n_chunks):
            kk = jax.random.fold_in(
                jax.random.fold_in(key, jnp.int64(c)),
                jnp.uint32(key_idx))
            vals = np.asarray(jax.device_get(self._draw(
                kk, (self.S, self._rc), self.value_scale)))
            vals_all.append(vals.reshape(-1))
            ts_all.append(np.broadcast_to(
                row_starts[:, None], (self.S, self._rc)).reshape(-1))
        return np.concatenate(vals_all), np.concatenate(ts_all)

    def per_key_columns(self, interval_out, key_idx: int):
        """One LOGICAL key's trigger columns ``(ws, we, cnt, [per-agg
        lowered [T]])`` — a device row-gather BEFORE the fetch, so
        sampling a few keys of a 64 K-key cell never pulls the full
        ``[K, T]`` result block to host."""
        import jax

        ws_d, we_d, cnt_d, results_d = interval_out[:4]
        r = int(self.routing.row_of[key_idx])
        # per-shard latency fold at the psum drain (ISSUE 14): the
        # sampled-key fetch attributes its duration to the owning shard
        # on the tracer's injectable clock (host-side; HLO pin intact)
        lat = self.obs.latency if self.obs is not None else None
        t0 = lat.clock.now() if lat is not None else 0.0
        ws, we, cnt_k, res_k = jax.device_get(
            (ws_d, we_d, cnt_d[r], [res[r] for res in results_d]))
        if lat is not None:
            lat.shard_fold(r // self.routing.rows_per_shard,
                           (lat.clock.now() - t0) * 1e3)
        lowered = [np.asarray(agg.device_spec().lower(rk, cnt_k))
                   for agg, rk in zip(self.aggregations, res_k)]
        return ws, we, cnt_k, lowered

    def lowered_results_for_key(self, interval_out, key_idx: int) -> list:
        """Non-empty window rows for one LOGICAL key (row attribution
        through the routing table)."""
        ws, we, cnt_k, lowered = self.per_key_columns(interval_out,
                                                     key_idx)
        rows = []
        for i in range(ws.shape[0]):
            if cnt_k[i] > 0:
                rows.append((int(ws[i]), int(we[i]), int(cnt_k[i]),
                             [lw[i] for lw in lowered]))
        return rows

    def lowered_global(self, interval_out):
        """The interval's cross-shard global fold columns ``(ws, we,
        gcnt, [per-agg lowered [T]])`` — the psum seam's host face, one
        tiny ``[T]`` fetch per interval."""
        import jax

        ws, we = jax.device_get(interval_out[:2])
        gcnt, gparts = jax.device_get(interval_out[4:6])
        lowered = [np.asarray(agg.device_spec().lower(gp, gcnt))
                   for agg, gp in zip(self.aggregations, gparts)]
        return ws, we, gcnt, lowered

    def shard_occupancy(self) -> np.ndarray:
        """Per-shard mean live-slice occupancy (drain-point read)."""
        import jax

        n = np.asarray(jax.device_get(self.state["buf"].n_slices)).reshape(
            self.n_shards, self.routing.rows_per_shard)
        return n.astype(np.float64).mean(axis=1) / float(
            self.config.capacity)
