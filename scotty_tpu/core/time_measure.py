"""Time interval value type (core/.../TimeMeasure.java:24-109)."""

from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True, order=True)
class TimeMeasure:
    """An immutable millisecond interval with unit factories."""

    millis: int

    @staticmethod
    def of(millis: int) -> "TimeMeasure":
        return TimeMeasure(millis)

    @staticmethod
    def milliseconds(n: int) -> "TimeMeasure":
        return TimeMeasure(n)

    @staticmethod
    def seconds(n: int) -> "TimeMeasure":
        return TimeMeasure(n * 1000)

    @staticmethod
    def minutes(n: int) -> "TimeMeasure":
        return TimeMeasure(n * 60 * 1000)

    @staticmethod
    def hours(n: int) -> "TimeMeasure":
        return TimeMeasure(n * 60 * 60 * 1000)

    @staticmethod
    def days(n: int) -> "TimeMeasure":
        return TimeMeasure(n * 24 * 60 * 60 * 1000)

    def to_milliseconds(self) -> int:
        return self.millis

    def __int__(self) -> int:
        return self.millis
