"""L1 core: window taxonomy, aggregation algebra, operator contracts.

Parity layer for the reference's ``core/`` module (SURVEY.md §2.1)."""

from .windows import (
    Window,
    WindowMeasure,
    TIME,
    COUNT,
    ContextFreeWindow,
    ForwardContextAware,
    ForwardContextFree,
    TumblingWindow,
    SlidingWindow,
    CappedSessionWindow,
    GenericSessionWindow,
    SessionWindow,
    FixedBandWindow,
    WindowContext,
    ActiveWindow,
    TupleContext,
    AddModification,
    DeleteModification,
    ShiftModification,
)
from .aggregates import (
    AggregateFunction,
    CommutativeAggregateFunction,
    ReduceAggregateFunction,
    InvertibleReduceAggregateFunction,
    DeviceAggregateSpec,
    SumAggregation,
    CountAggregation,
    CountMinSketchAggregation,
    MinAggregation,
    MaxAggregation,
    MeanAggregation,
    QuantileAggregation,
    DDSketchQuantileAggregation,
    HyperLogLogAggregation,
    BUILTIN_AGGREGATIONS,
)
from .operator import AggregateWindow, WindowCollector, WindowOperator
from .time_measure import TimeMeasure

__all__ = [
    "Window", "WindowMeasure", "TIME", "COUNT",
    "ContextFreeWindow", "ForwardContextAware", "ForwardContextFree",
    "TumblingWindow", "SlidingWindow", "CappedSessionWindow", "GenericSessionWindow", "SessionWindow", "FixedBandWindow",
    "WindowContext", "ActiveWindow", "TupleContext",
    "AddModification", "DeleteModification", "ShiftModification",
    "AggregateFunction", "CommutativeAggregateFunction", "ReduceAggregateFunction",
    "InvertibleReduceAggregateFunction", "DeviceAggregateSpec",
    "SumAggregation", "CountAggregation", "CountMinSketchAggregation",
    "MinAggregation", "MaxAggregation",
    "MeanAggregation", "QuantileAggregation", "DDSketchQuantileAggregation",
    "HyperLogLogAggregation", "BUILTIN_AGGREGATIONS",
    "AggregateWindow", "WindowCollector", "WindowOperator",
    "TimeMeasure",
]
