"""Window taxonomy: the window-type algebra of the framework.

Re-design of the reference's ``core/windowType`` package
(core/.../windowType/Window.java:7-9, ContextFreeWindow.java:6-13,
TumblingWindow.java:6-53, SlidingWindow.java:6-72, SessionWindow.java:6-128,
FixedBandWindow.java:5-73, WindowMeasure.java:3-5) as plain Python dataclasses
with two faces:

* a *scalar* face (``assign_next_window_start`` / ``trigger_windows``) used by
  the host-side reference-semantics operator (`scotty_tpu.simulator`), and
* a *vectorized* face (``edges_in_range`` / ``trigger_arrays``) used by the TPU
  engine to enumerate slice edges and triggered windows in closed form with
  NumPy/JAX array ops instead of per-tuple Python loops.

Semantics notes (pinned by the reference test-suite):

* Tumbling ``assign_next_window_start(t) = t + size - t % size`` — i.e. the
  next grid point *strictly after* ``t`` when t is on the grid
  (TumblingWindow.java:29-31).
* Sliding triggers walk *backwards* from the last slide-aligned start at the
  current watermark (SlidingWindow.java:50-57); tumbling triggers walk
  forwards (TumblingWindow.java:34-39). Result order matters and is part of
  the public contract.
* Sessions are context-aware: per-operator mutable session list, inverted
  ``has_active_windows`` naming preserved as ``_is_empty`` internally
  (WindowContext.java:15-17).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field

import numpy as np

LONG_MAX = (1 << 63) - 1
LONG_MIN = -(1 << 63)


class WindowMeasure(enum.Enum):
    """Every window is either event-time measured or arrival-count measured
    (core/.../windowType/WindowMeasure.java:3-5)."""

    Time = "Time"
    Count = "Count"


# Aliases matching common spelling in configs / DSL.
TIME = WindowMeasure.Time
COUNT = WindowMeasure.Count


def java_mod(a: int, b: int) -> int:
    """Java's ``%`` truncates toward zero; Python's floors. The reference's
    edge arithmetic (TumblingWindow.java:30, SlidingWindow.java:42,48) relies
    on Java semantics for negative operands."""
    r = a % b
    if r != 0 and (a < 0) != (b < 0):
        r -= b
    return r


class Window:
    """Base marker (core/.../windowType/Window.java:7-9)."""

    measure: WindowMeasure

    @property
    def window_measure(self) -> WindowMeasure:
        return self.measure

    def get_window_measure(self) -> WindowMeasure:
        return self.measure


class TupleContext:
    """Iterator contract over a window's stored tuples
    (core/.../windowType/TupleContext.java:3-9 — declared but unused by the
    reference slicing code; kept for API parity). Implementations expose
    ``iter_tuples() -> iterator of (ts, record)``."""

    def iter_tuples(self):
        raise NotImplementedError


class ContextFreeWindow(Window):
    """Windows whose edges are computable from a timestamp alone
    (core/.../windowType/ContextFreeWindow.java:6-13)."""

    def assign_next_window_start(self, position: int) -> int:
        raise NotImplementedError

    def trigger_windows(self, collector, last_watermark: int, current_watermark: int) -> None:
        raise NotImplementedError

    def clear_delay(self) -> int:
        raise NotImplementedError

    # --- vectorized face (TPU engine) -------------------------------------
    def edges_in_range(self, lo: int, hi: int) -> np.ndarray:
        """All slice edges e with ``lo < e <= hi`` this window induces.
        Closed-form equivalent of iterating ``assign_next_window_start``."""
        raise NotImplementedError

    def trigger_arrays(self, last_watermark: int, current_watermark: int):
        """(starts, ends) int64 arrays of triggered windows, in the exact
        order the scalar ``trigger_windows`` would emit them."""
        raise NotImplementedError


@dataclass(frozen=True)
class TumblingWindow(ContextFreeWindow):
    """Fixed-size non-overlapping windows (core/.../TumblingWindow.java:6-53)."""

    measure: WindowMeasure
    size: int

    def assign_next_window_start(self, position: int) -> int:
        # TumblingWindow.java:29-31
        return position + self.size - java_mod(position, self.size)

    def trigger_windows(self, collector, last_watermark: int, current_watermark: int) -> None:
        # TumblingWindow.java:34-39: emit every complete [w, w+size) with
        # w >= lastStart and w+size <= currentWatermark, ascending.
        last_start = last_watermark - java_mod(last_watermark + self.size, self.size)
        start = last_start
        while start + self.size <= current_watermark:
            collector.trigger(start, start + self.size, self.measure)
            start += self.size

    def clear_delay(self) -> int:
        return self.size

    def edges_in_range(self, lo: int, hi: int) -> np.ndarray:
        # grid points k*size with lo < k*size <= hi
        first = (lo // self.size + 1) * self.size
        if first > hi:
            return np.empty(0, dtype=np.int64)
        return np.arange(first, hi + 1, self.size, dtype=np.int64)

    def trigger_arrays(self, last_watermark: int, current_watermark: int):
        last_start = last_watermark - java_mod(last_watermark + self.size, self.size)
        n = max(0, (current_watermark - last_start) // self.size)
        starts = last_start + self.size * np.arange(n, dtype=np.int64)
        return starts, starts + self.size

    def __str__(self) -> str:
        return f"TumblingWindow{{measure={self.measure.value}, size={self.size}}}"


@dataclass(frozen=True)
class SlidingWindow(ContextFreeWindow):
    """Overlapping windows of ``size`` sliding by ``slide``
    (core/.../SlidingWindow.java:6-72)."""

    measure: WindowMeasure
    size: int
    slide: int

    def assign_next_window_start(self, position: int) -> int:
        # SlidingWindow.java:41-43 — next slide-grid point strictly after.
        return position + self.slide - java_mod(position, self.slide)

    @staticmethod
    def window_start_with_offset(timestamp: int, window_size: int) -> int:
        # SlidingWindow.java:46-48
        return timestamp - java_mod(timestamp + window_size, window_size)

    def trigger_windows(self, collector, last_watermark: int, current_watermark: int) -> None:
        # SlidingWindow.java:50-57 — walk backwards from the last aligned
        # start; guard 0 <= start and start+size <= currentWatermark+1.
        start = self.window_start_with_offset(current_watermark, self.slide)
        while start + self.size > last_watermark:
            if start >= 0 and start + self.size <= current_watermark + 1:
                collector.trigger(start, start + self.size, self.measure)
            start -= self.slide

    def clear_delay(self) -> int:
        return self.size

    def edges_in_range(self, lo: int, hi: int) -> np.ndarray:
        first = (lo // self.slide + 1) * self.slide
        if first > hi:
            return np.empty(0, dtype=np.int64)
        return np.arange(first, hi + 1, self.slide, dtype=np.int64)

    def trigger_arrays(self, last_watermark: int, current_watermark: int):
        last_start = self.window_start_with_offset(current_watermark, self.slide)
        # descending starts s = last_start - k*slide with s + size > last_wm
        # (strict) → k < (last_start - last_wm + size)/slide → ceil-div count.
        d = last_start - (last_watermark - self.size)
        n_total = max(0, -(-d // self.slide))
        starts = last_start - self.slide * np.arange(n_total, dtype=np.int64)
        keep = (starts >= 0) & (starts + self.size <= current_watermark + 1)
        starts = starts[keep]
        return starts, starts + self.size

    def __str__(self) -> str:
        return (
            f"SlidingWindow{{measure={self.measure.value}, size={self.size},"
            f" slide={self.slide}}}"
        )


@dataclass(frozen=True)
class FixedBandWindow(ContextFreeWindow):
    """One-shot band ``[start, start+size)``; afterwards all tuples share one
    big slice (core/.../FixedBandWindow.java:5-73)."""

    measure: WindowMeasure
    start: int
    size: int

    def assign_next_window_start(self, position: int) -> int:
        # FixedBandWindow.java:36-48
        if position == LONG_MAX or position < self.start:
            return self.start
        if self.start <= position < self.start + self.size:
            return self.start + self.size
        return LONG_MAX

    def trigger_windows(self, collector, last_watermark: int, current_watermark: int) -> None:
        # FixedBandWindow.java:51-57
        end = self.start + self.size
        if last_watermark <= end <= current_watermark:
            collector.trigger(self.start, end, self.measure)

    def clear_delay(self) -> int:
        return self.size

    def edges_in_range(self, lo: int, hi: int) -> np.ndarray:
        pts = [e for e in (self.start, self.start + self.size) if lo < e <= hi]
        return np.asarray(pts, dtype=np.int64)

    def trigger_arrays(self, last_watermark: int, current_watermark: int):
        end = self.start + self.size
        if last_watermark <= end <= current_watermark:
            return (
                np.asarray([self.start], dtype=np.int64),
                np.asarray([end], dtype=np.int64),
            )
        return np.empty(0, dtype=np.int64), np.empty(0, dtype=np.int64)

    def __str__(self) -> str:
        return (
            f"FixedBandWindow{{measure={self.measure.value}, start={self.start},"
            f" size={self.size}}}"
        )


# ---------------------------------------------------------------------------
# Context-aware windows (sessions and user-defined forward-context windows)
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class AddModification:
    """A new window edge appeared at ``post``
    (core/.../windowContext/AddModification.java:3-9)."""

    post: int


@dataclass(frozen=True)
class DeleteModification:
    """The window edge at ``pre`` disappeared
    (core/.../windowContext/DeleteModification.java:3-9)."""

    pre: int


@dataclass(frozen=True)
class ShiftModification:
    """The window edge at ``pre`` moved to ``post``
    (core/.../windowContext/ShiftModification.java:3-11)."""

    pre: int
    post: int


class ActiveWindow:
    """A live context window ``[start, end]``
    (WindowContext.java:77-106 inner class)."""

    __slots__ = ("start", "end")

    def __init__(self, start: int, end: int):
        self.start = start
        self.end = end

    def get_start(self) -> int:
        return self.start

    def get_end(self) -> int:
        return self.end

    def __repr__(self) -> str:
        return f"ActiveWindow({self.start}, {self.end})"


class WindowContext:
    """Per-operator mutable state for context-aware windows
    (core/.../windowContext/WindowContext.java:9-107).

    Edit hooks record `WindowModifications` into a caller-supplied set; these
    records drive slice repair in the slice manager. The reference's
    ``hasActiveWindows()`` returns *true when the list is empty*
    (WindowContext.java:15-17) — session logic depends on that inversion, so
    we keep the behavior under the honest name ``has_no_active_windows``.
    """

    def __init__(self):
        self.active_windows: list[ActiveWindow] = []
        self._modified_window_edges: set | None = None

    # -- reference-parity helpers ------------------------------------------
    def has_no_active_windows(self) -> bool:
        return len(self.active_windows) == 0

    def get_active_windows(self) -> list[ActiveWindow]:
        return self.active_windows

    def get_window(self, i: int) -> ActiveWindow:
        return self.active_windows[i]

    def number_of_active_windows(self) -> int:
        return len(self.active_windows)

    def add_new_window(self, i: int, start: int, end: int) -> ActiveWindow:
        # WindowContext.java:19-26: records Add for BOTH edges.
        w = ActiveWindow(start, end)
        self.active_windows.insert(i, w)
        self._modified_window_edges.add(AddModification(start))
        self._modified_window_edges.add(AddModification(end))
        return w

    def merge_with_pre(self, index: int) -> ActiveWindow:
        # WindowContext.java:38-45
        assert index >= 1
        window = self.active_windows[index]
        pre = self.active_windows[index - 1]
        self.shift_end(pre, window.end)
        self.remove_window(index)
        return pre

    def remove_window(self, index: int) -> None:
        # WindowContext.java:47-51: records Delete for BOTH edges.
        w = self.active_windows[index]
        self._modified_window_edges.add(DeleteModification(w.start))
        self._modified_window_edges.add(DeleteModification(w.end))
        del self.active_windows[index]

    def shift_start(self, window: ActiveWindow, position: int) -> None:
        # WindowContext.java:54-57
        self._modified_window_edges.add(ShiftModification(window.start, position))
        window.start = position

    def shift_end(self, window: ActiveWindow, position: int) -> None:
        # WindowContext.java:59-62 — deliberately does NOT record a shift.
        window.end = position

    # -- abstract ----------------------------------------------------------
    def update_context(self, tuple_, position: int):
        raise NotImplementedError

    def update_context_with_modifications(self, tuple_, position: int, modifications: set):
        # WindowContext.java:68-71
        self._modified_window_edges = modifications
        return self.update_context(tuple_, position)

    def assign_next_window_start(self, position: int) -> int:
        raise NotImplementedError

    def trigger_windows(self, collector, last_watermark: int, current_watermark: int) -> None:
        raise NotImplementedError


class ForwardContextAware(Window):
    """Window that needs per-stream forward context (e.g. sessions)
    (core/.../ForwardContextAware.java:6-9)."""

    def create_context(self) -> WindowContext:
        raise NotImplementedError

    def device_context_spec(self):
        """Device face of the context calculus — a
        :class:`scotty_tpu.engine.context.DeviceContextSpec`, or None when
        the window is host-only (the hybrid operator then routes it to the
        simulator). The same dual-face pattern as
        ``AggregateFunction.device_spec``: coherence between
        ``create_context()`` and the device spec is the implementor's
        contract, pinned by differential tests."""
        return None


class ForwardContextFree(Window):
    """Context windows whose edges do not depend on tuple values
    (core/.../ForwardContextFree.java:5-8)."""

    def create_context(self) -> WindowContext:
        raise NotImplementedError

    def device_context_spec(self):
        return None


@dataclass(frozen=True)
class SessionWindow(ForwardContextAware):
    """Gap-based session windows (core/.../SessionWindow.java:6-128)."""

    measure: WindowMeasure
    gap: int

    def create_context(self) -> "SessionWindow.SessionContext":
        return SessionWindow.SessionContext(self.gap, self.measure)

    class SessionContext(WindowContext):
        """SessionWindow.java:37-118 inner class, reimplemented faithfully."""

        def __init__(self, gap: int, measure: WindowMeasure):
            super().__init__()
            self.gap = gap
            self.measure = measure

        def update_context(self, tuple_, position: int):
            # SessionWindow.java:40-84
            gap = self.gap
            if self.has_no_active_windows():
                self.add_new_window(0, position, position)
                return self.get_window(0)
            session_index = self.get_session(position)

            if session_index == -1:
                self.add_new_window(0, position, position)
                return None

            s = self.get_window(session_index)
            if s.start - gap > position:
                # add new session before
                return self.add_new_window(session_index, position, position)
            elif s.start > position and s.start - gap < position:
                # expand start
                self.shift_start(s, position)
                if session_index > 0:
                    pre = self.get_window(session_index - 1)
                    if pre.end + gap >= s.start:
                        return self.merge_with_pre(session_index)
                return s
            elif s.end < position and s.end + gap >= position:
                self.shift_end(s, position)
                if session_index < self.number_of_active_windows() - 1:
                    nxt = self.get_window(session_index + 1)
                    if s.end + gap >= nxt.start:
                        return self.merge_with_pre(session_index + 1)
                return s
            elif s.end + gap < position:
                # add new session after
                return self.add_new_window(session_index + 1, position, position)
            return None

        def get_session(self, position: int) -> int:
            # SessionWindow.java:86-98 — linear scan over ordered sessions.
            i = 0
            while i < self.number_of_active_windows():
                s = self.get_window(i)
                if s.start - self.gap <= position and s.end + self.gap >= position:
                    return i
                elif s.start - self.gap > position:
                    return i - 1
                i += 1
            return i - 1

        def assign_next_window_start(self, position: int) -> int:
            # SessionWindow.java:102-104
            return position + self.gap

        def trigger_windows(self, collector, last_watermark: int, current_watermark: int) -> None:
            # SessionWindow.java:107-116
            if self.has_no_active_windows():
                return
            session = self.get_window(0)
            while session.end + self.gap < current_watermark:
                collector.trigger(session.start, session.end + self.gap, self.measure)
                self.remove_window(0)
                if self.has_no_active_windows():
                    return
                session = self.get_window(0)

    def device_context_spec(self):
        from ..engine.context import SessionDecider

        return SessionDecider(self.gap)

    def __str__(self) -> str:
        return f"SessionWindow{{measure={self.measure.value}, gap={self.gap}}}"


@dataclass(frozen=True)
class CappedSessionWindow(ForwardContextAware):
    """Gap session that refuses to grow beyond ``max_span``: an extension
    that would stretch a session's ``[first, last]`` extent past
    ``max_span`` opens a fresh session instead, and merges whose combined
    extent would exceed the cap are declined (so capped sessions, unlike
    plain ones, may sit closer than ``gap`` to a neighbor).

    The shipped example of a USER-DEFINED forward-context-aware window
    with both faces: this host context runs through the reference
    calculus + slice repair on the simulator; the device face
    (`engine/context.py::CappedSessionDecider`) expresses the same
    decisions over bounded active-window arrays. No reference
    counterpart — it exists to prove the context API is open
    (ForwardContextAware.java:6-9, WindowContext.java:9-107).
    """

    measure: WindowMeasure
    gap: int
    max_span: int

    def create_context(self) -> "CappedSessionWindow.CappedContext":
        return CappedSessionWindow.CappedContext(self.gap, self.max_span,
                                                 self.measure)

    def device_context_spec(self):
        from ..engine.context import CappedSessionDecider

        return CappedSessionDecider(self.gap, self.max_span)

    class CappedContext(WindowContext):
        """SessionContext's calculus with span-cap checks; inserts at the
        sorted position (a declined extension may target a spot past an
        adjacent capped session)."""

        def __init__(self, gap: int, max_span: int, measure: WindowMeasure):
            super().__init__()
            self.gap = gap
            self.max_span = max_span
            self.measure = measure

        def _add_sorted(self, position: int):
            k = 0
            while (k < self.number_of_active_windows()
                   and self.get_window(k).start <= position):
                k += 1
            return self.add_new_window(k, position, position)

        def update_context(self, tuple_, position: int):
            # Priority calculus (capped sessions may sit CLOSER than gap
            # to a neighbor, so the plain session rule "act on the first
            # window in reach" degenerates — a capped-out session keeps
            # winning the reach walk and every later tuple re-inserts a
            # point window. Instead: (1) fold into a CONTAINING window;
            # (2) else take the first FITTING extension; (3) else a
            # cap-declined reach inserts a fresh point window at the
            # sorted position; exact-gap reach (position == start - gap,
            # the strict/non-strict asymmetry inherited from
            # SessionWindow.java:86-98) orphans, as in plain sessions.
            gap, cap = self.gap, self.max_span
            n = self.number_of_active_windows()
            if n == 0:
                self.add_new_window(0, position, position)
                return self.get_window(0)
            exact_gap = declined = False
            fit_i = -1
            for k in range(n):
                s = self.get_window(k)
                if s.start - gap > position:
                    break           # sorted by start: nothing later reaches
                if s.start <= position <= s.end:
                    return s                        # (1) inside
                if s.start - gap <= position <= s.end + gap:
                    if position == s.start - gap:
                        exact_gap = True
                    elif fit_i < 0 and (
                            (s.start > position
                             and s.end - position <= cap)
                            or (s.end < position
                                and position - s.start <= cap)):
                        fit_i = k
                    else:
                        declined = True
            if fit_i >= 0:                          # (2) fitting extension
                i, s = fit_i, self.get_window(fit_i)
                if s.start > position:
                    self.shift_start(s, position)
                    if i > 0:
                        pre = self.get_window(i - 1)
                        if pre.end + gap >= s.start \
                                and s.end - pre.start <= cap:
                            return self.merge_with_pre(i)
                    return s
                self.shift_end(s, position)
                if i < n - 1:
                    nxt = self.get_window(i + 1)
                    if s.end + gap >= nxt.start \
                            and nxt.end - s.start <= cap:
                        return self.merge_with_pre(i + 1)
                return s
            if declined:                            # (3) cap-declined
                return self._add_sorted(position)
            if exact_gap:                           # exact-gap fall-through
                return None
            return self._add_sorted(position)       # out of all reach

        def assign_next_window_start(self, position: int) -> int:
            # the slicer cuts a flexible slice edge when a tuple reaches
            # this boundary (StreamSlicer.java:118-130): for capped
            # sessions that is the usual gap expiry OR the newest
            # session's span cap — announcing the cap keeps slice edges
            # aligned with declined-extension boundaries, so window
            # values stay exact on the host path too
            nxt = position + self.gap
            if not self.has_no_active_windows():
                s = self.get_window(self.number_of_active_windows() - 1)
                if s.start <= position <= s.end + self.gap:
                    nxt = min(nxt, s.start + self.max_span + 1)
            return nxt

        def trigger_windows(self, collector, last_watermark: int,
                            current_watermark: int) -> None:
            i = 0
            while i < self.number_of_active_windows():
                s = self.get_window(i)
                if s.end + self.gap < current_watermark:
                    collector.trigger(s.start, s.end + self.gap,
                                      self.measure)
                    self.remove_window(i)
                else:
                    i += 1

    def __str__(self) -> str:
        return (f"CappedSessionWindow{{measure={self.measure.value}, "
                f"gap={self.gap}, maxSpan={self.max_span}}}")


@dataclass(frozen=True)
class GenericSessionWindow(ForwardContextAware):
    """Plain gap sessions expressed through the GENERIC context contract
    (ISSUE 11): semantically identical to :class:`SessionWindow`, but
    deliberately NOT a ``SessionWindow`` subclass, so the device engine
    routes it through the generic ``DeviceContextSpec`` machinery
    (engine/context.py) instead of the tuned session arrays — the
    coherence window for the generic path's differential suites, and the
    shipped example of an ``order_free`` speculation certification
    (:class:`scotty_tpu.engine.context.SpeculationCert`). The host face
    reuses the reference session calculus verbatim."""

    measure: WindowMeasure
    gap: int

    def create_context(self) -> "SessionWindow.SessionContext":
        return SessionWindow.SessionContext(self.gap, self.measure)

    def device_context_spec(self):
        from ..engine.context import SessionDecider

        return SessionDecider(self.gap)

    def __str__(self) -> str:
        return (f"GenericSessionWindow{{measure={self.measure.value}, "
                f"gap={self.gap}}}")
