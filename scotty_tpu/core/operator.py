"""Operator and result contracts.

Parity with the reference L1 API:
* ``WindowOperator`` — core/.../WindowOperator.java:9-37
* ``AggregateWindow`` — core/.../AggregateWindow.java:8-21
* ``WindowCollector`` — core/.../WindowCollector.java:5-8

The TPU framework adds a batched entry point ``process_elements`` (arrays of
values + timestamps) because per-tuple Python calls cannot feed an
accelerator; ``process_element`` remains for API parity and tests.
"""

from __future__ import annotations

from typing import Any, List, Sequence

from .windows import Window, WindowMeasure
from .aggregates import AggregateFunction


class AggregateWindow:
    """An emitted window result (AggregateWindow.java:8-21 +
    AggregateWindowState.java result semantics): measure, [start, end) bounds
    and one aggregate value per registered aggregation that produced one."""

    __slots__ = ("measure", "start", "end", "agg_values", "_has_value")

    def __init__(self, measure: WindowMeasure, start: int, end: int,
                 agg_values: Sequence[Any], has_value: bool):
        self.measure = measure
        self.start = start
        self.end = end
        self.agg_values = list(agg_values)
        self._has_value = has_value

    def get_measure(self) -> WindowMeasure:
        return self.measure

    def get_start(self) -> int:
        return self.start

    def get_end(self) -> int:
        return self.end

    def get_agg_values(self) -> List[Any]:
        return self.agg_values

    def has_value(self) -> bool:
        return self._has_value

    def __repr__(self) -> str:
        return (f"WindowResult({self.measure.value},{self.start}-{self.end},"
                f"{self.agg_values})")

    def __eq__(self, other) -> bool:
        return (isinstance(other, AggregateWindow)
                and self.measure == other.measure
                and self.start == other.start
                and self.end == other.end
                and self.agg_values == other.agg_values)

    def __hash__(self):
        return hash((self.measure, self.start, self.end))


class WindowCollector:
    """Trigger sink passed into window types (WindowCollector.java:5-8)."""

    def trigger(self, start: int, end: int, measure: WindowMeasure) -> None:
        raise NotImplementedError


class WindowOperator:
    """The operator contract every backend implements
    (WindowOperator.java:9-37). Backends: the host reference-semantics
    operator (`scotty_tpu.simulator.SlicingWindowOperator`) and the TPU
    engine (`scotty_tpu.engine.TpuWindowOperator`)."""

    def process_element(self, element: Any, ts: int) -> None:
        raise NotImplementedError

    def process_elements(self, elements, timestamps) -> None:
        """Batched ingest (TPU-native extension). Default: per-tuple loop."""
        for element, ts in zip(elements, timestamps):
            self.process_element(element, int(ts))

    def process_watermark(self, watermark_ts: int) -> List[AggregateWindow]:
        raise NotImplementedError

    def add_window_assigner(self, window: Window) -> None:
        raise NotImplementedError

    def add_aggregation(self, window_function: AggregateFunction) -> None:
        raise NotImplementedError

    # alias parity: SlicingWindowOperator.addWindowFunction
    def add_window_function(self, window_function: AggregateFunction) -> None:
        self.add_aggregation(window_function)

    def set_max_lateness(self, max_lateness: int) -> None:
        raise NotImplementedError
