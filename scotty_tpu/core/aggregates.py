"""Aggregation algebra: lift / combine / lower (+ invert / clone).

Re-design of the reference's ``core/windowFunction`` package
(core/.../windowFunction/AggregateFunction.java:6-58,
InvertibleAggregateFunction.java:3-16, ReduceAggregateFunction.java:4-16,
CloneablePartialStateFunction.java:3-12) plus the example aggregations the
reference ships in its demo/benchmark modules (Sum/Min/Max/Count/Mean/Quantile,
demo/flink-demo/.../windowFunctions/*.java).

Every aggregate has two faces:

* the *scalar* face (``lift``/``combine``/``lower``) used by the host-side
  reference-semantics operator — works on arbitrary Python values, supports
  holistic aggregates with unbounded partials (exact quantiles);
* the *device* face (:class:`DeviceAggregateSpec`) used by the TPU engine —
  fixed-width array partials combined with one of the XLA-friendly segment
  primitives (``sum`` / ``min`` / ``max``), which is what lets thousands of
  slices fold in one fused kernel. Holistic aggregates map to fixed-width
  mergeable sketches (DDSketch histogram for quantiles, HyperLogLog registers
  for distinct counts) because unbounded tree partials are not
  XLA-representable — see SURVEY.md §7.
"""

from __future__ import annotations

import bisect
import math
from dataclasses import dataclass
from typing import Any, Callable, Sequence

import numpy as np


# ---------------------------------------------------------------------------
# Device face
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class DeviceAggregateSpec:
    """How the TPU engine realizes one aggregation over slice partials.

    ``kind`` is the segment-combine primitive ('sum' | 'min' | 'max').
    ``width`` is the fixed partial width per slice.

    Two lift modes:

    * dense: ``lift_dense(values) -> [B, width]`` array (sum/min/max/mean);
    * sparse: ``lift_sparse(values) -> (col[B], val[B])`` — each tuple touches
      exactly one of the ``width`` columns (sketches: one histogram bucket /
      one HLL register per tuple), so ingest stays O(B) instead of O(B*width).
      Multi-cell sketches (count-min: one cell per hash row) declare
      ``cells_per_tuple = d`` and return ``(col[d, B], val[d, B])`` — the
      engine's scatter-combine sites index as ``part.at[pos, col]`` where
      ``pos`` is the per-lane slice row, and advanced-index broadcasting
      fans the [B] rows across the d cells with no extra lanes generated.
      Paths that densify per-lane one-hots (sessions, context chains, the
      count record ring, the factored-MXU histogram) stay single-cell and
      reject d > 1 at registration.

    ``lower(partials[N, width], counts[N]) -> [N]`` produces final values.
    ``identity`` is the combine-neutral element used for empty slices.
    """

    kind: str
    width: int
    identity: float
    lower: Callable[[np.ndarray, np.ndarray], np.ndarray]
    lift_dense: Callable[[Any], Any] | None = None
    lift_sparse: Callable[[Any], tuple] | None = None
    dtype: Any = np.float32
    #: sparse cells each tuple touches (count-min: one per hash row). The
    #: scatter-combine ingest paths broadcast over it; one-hot paths
    #: require 1.
    cells_per_tuple: int = 1
    #: Hashable semantic identity (aggregation type + parameters) — the
    #: callables above are closures, so kernel caches key on this instead.
    token: tuple = ()
    #: Optional jnp twin of ``lower`` for DEVICE-side finalization: emitting
    #: lowered values (one float per window) instead of raw partials cuts
    #: the result payload by ``width``× — decisive for wide sketches on
    #: bandwidth-limited device→host links (docs/DESIGN.md).
    lower_device: Callable[[Any, Any], Any] | None = None

    @property
    def is_sparse(self) -> bool:
        return self.lift_sparse is not None


# ---------------------------------------------------------------------------
# Scalar face
# ---------------------------------------------------------------------------


class AggregateFunction:
    """lift/combine/lower algebra (AggregateFunction.java:6-58).

    ``combine`` must be associative; that associativity is the license for the
    engine to fold slice partials in any grouping (tree reductions, prefix
    scans) instead of the reference's left-to-right loop.
    """

    #: True → supports ``invert`` (InvertibleAggregateFunction.java:3-16),
    #: enabling O(1) removal instead of slice recompute on out-of-order repair.
    invertible: bool = False

    def lift(self, value):
        raise NotImplementedError

    def combine(self, a, b):
        raise NotImplementedError

    def lower(self, partial):
        raise NotImplementedError

    def lift_and_combine(self, partial, value):
        # AggregateFunction.java:44-47 default
        return self.combine(partial, self.lift(value))

    def invert(self, current, to_remove):
        raise NotImplementedError(f"{type(self).__name__} is not invertible")

    def lift_and_invert(self, partial, value):
        # InvertibleAggregateFunction.java default
        return self.invert(partial, self.lift(value))

    def clone_partial(self, partial):
        """CloneablePartialStateFunction.java:3-12 — copy hook so merging a
        shared slice partial into a window result can't alias mutable state.
        Immutable partials return themselves."""
        return partial

    def device_spec(self) -> DeviceAggregateSpec | None:
        """Fixed-width device realization, or None if host-only."""
        return None

    #: True → ``combine`` is commutative as well as associative
    #: (CommutativeAggregateFunction.java:3 marker — declared but never
    #: consulted by the reference slicing code; kept for API parity, and
    #: genuinely meaningful here: the global operator's round-robin
    #: sharding reorders tuples, which is only sound for commutative
    #: combines).
    commutative: bool = False


class CommutativeAggregateFunction(AggregateFunction):
    """Marker base matching CommutativeAggregateFunction.java:3."""

    commutative = True


class ReduceAggregateFunction(AggregateFunction):
    """In == Partial == Final; lift/lower are identity
    (ReduceAggregateFunction.java:4-16). Lambda-friendly:

    >>> op.add_aggregation(ReduceAggregateFunction(lambda a, b: a + b))
    """

    def __init__(self, fn: Callable[[Any, Any], Any], invert_fn: Callable | None = None):
        self.fn = fn
        self.invert_fn = invert_fn
        self.invertible = invert_fn is not None

    def lift(self, value):
        return value

    def combine(self, a, b):
        return self.fn(a, b)

    def lower(self, partial):
        return partial

    def invert(self, current, to_remove):
        if self.invert_fn is None:
            raise NotImplementedError("no invert_fn provided")
        return self.invert_fn(current, to_remove)


class InvertibleReduceAggregateFunction(ReduceAggregateFunction):
    """Marker parity with InvertibleReduceAggregateFunction.java:3-6."""

    def __init__(self, fn, invert_fn):
        super().__init__(fn, invert_fn)


# ---------------------------------------------------------------------------
# Built-in aggregations (reference demo windowFunctions/ + benchmark SumAggregation)
# ---------------------------------------------------------------------------


class SumAggregation(AggregateFunction):
    """Invertible sum (benchmark/.../aggregations/SumAggregation.java:8-19)."""

    invertible = True

    def lift(self, value):
        return value

    def combine(self, a, b):
        return a + b

    def lower(self, partial):
        return partial

    def invert(self, current, to_remove):
        return current - to_remove

    def device_spec(self) -> DeviceAggregateSpec:
        return DeviceAggregateSpec(
            kind="sum",
            width=1,
            identity=0.0,
            lift_dense=lambda v: v.reshape(-1, 1),
            lower=lambda p, c: p[:, 0],
            token=("sum",),
        )


class CountAggregation(AggregateFunction):
    """Tuple count (demo windowFunctions Count)."""

    invertible = True

    def lift(self, value):
        return 1

    def combine(self, a, b):
        return a + b

    def lower(self, partial):
        return partial

    def invert(self, current, to_remove):
        return current - to_remove

    def device_spec(self) -> DeviceAggregateSpec:
        import jax.numpy as jnp

        return DeviceAggregateSpec(
            kind="sum",
            width=1,
            identity=0.0,
            lift_dense=lambda v: jnp.ones((v.shape[0], 1), dtype=jnp.float32),
            lower=lambda p, c: p[:, 0],
            token=("count",),
        )


class MinAggregation(AggregateFunction):
    """Minimum (demo windowFunctions Min)."""

    def lift(self, value):
        return value

    def combine(self, a, b):
        return a if a <= b else b

    def lower(self, partial):
        return partial

    def device_spec(self) -> DeviceAggregateSpec:
        return DeviceAggregateSpec(
            kind="min",
            width=1,
            identity=float("inf"),
            lift_dense=lambda v: v.reshape(-1, 1),
            lower=lambda p, c: p[:, 0],
            token=("min",),
        )


class MaxAggregation(AggregateFunction):
    """Maximum (demo windowFunctions Max)."""

    def lift(self, value):
        return value

    def combine(self, a, b):
        return a if a >= b else b

    def lower(self, partial):
        return partial

    def device_spec(self) -> DeviceAggregateSpec:
        return DeviceAggregateSpec(
            kind="max",
            width=1,
            identity=-float("inf"),
            lift_dense=lambda v: v.reshape(-1, 1),
            lower=lambda p, c: p[:, 0],
            token=("max",),
        )


class MeanAggregation(AggregateFunction):
    """Arithmetic mean with (sum, count) partial (demo windowFunctions Mean)."""

    invertible = True

    def lift(self, value):
        return (value, 1)

    def combine(self, a, b):
        return (a[0] + b[0], a[1] + b[1])

    def lower(self, partial):
        s, c = partial
        return s / c if c else None

    def invert(self, current, to_remove):
        return (current[0] - to_remove[0], current[1] - to_remove[1])

    def device_spec(self) -> DeviceAggregateSpec:
        import jax.numpy as jnp

        return DeviceAggregateSpec(
            kind="sum",
            width=2,
            identity=0.0,
            lift_dense=lambda v: jnp.stack([v, jnp.ones_like(v)], axis=-1),
            lower=lambda p, c: p[:, 0] / np.maximum(p[:, 1], 1.0),
            token=("mean",),
        )


class QuantileAggregation(AggregateFunction):
    """Exact quantile — holistic aggregate with an unbounded sorted-list
    partial, mirroring the reference's QuantileTreeMap demo aggregate
    (demo/flink-demo/.../windowFunctions/QuantileTreeMap.java:6-90,
    QuantileWindowFunction.java:98-135). Host-only: the device realization is
    :class:`DDSketchQuantileAggregation`.

    The partial is mutable (a list), so ``clone_partial`` copies it — same
    contract as CloneablePartialStateFunction.
    """

    def __init__(self, quantile: float):
        assert 0.0 <= quantile <= 1.0
        self.quantile = quantile

    def lift(self, value):
        return [value]

    def combine(self, a, b):
        # merge two sorted lists; 'a' may be a shared slice partial → do not
        # mutate either input (AggregateValueState.java:55-69 merge contract).
        merged = list(a)
        for v in b:
            bisect.insort(merged, v)
        return merged

    def lift_and_combine(self, partial, value):
        bisect.insort(partial, value)
        return partial

    def lower(self, partial):
        if not partial:
            return None
        idx = min(len(partial) - 1, int(self.quantile * len(partial)))
        return partial[idx]

    def clone_partial(self, partial):
        return list(partial)


class DDSketchQuantileAggregation(AggregateFunction):
    """Fixed-width mergeable quantile sketch (DDSketch-style log-bucketed
    histogram). The device substitute for the reference's unbounded
    QuantileTreeMap (SURVEY.md §7: sketching is the capability-preserving
    substitute for holistic aggregates under XLA's static shapes).

    Partial = [n_buckets] bucket counts (+ bucket 0 reserved for zero /
    non-positive values); combine = elementwise add → additive, so window
    merges ride the same prefix-sum path as sums. Relative error is bounded
    by ``alpha``.
    """

    def __init__(self, quantile: float, alpha: float = 0.02, n_buckets: int = 512,
                 min_value: float = 1e-3):
        # Defaults cover (1e-3, ~7e5) at 2 % relative error: the dynamic
        # range is gamma^(n_buckets-2) ≈ e^{(n-2)·2α}, so the previous
        # α=0.01/min=1e-9 defaults topped out at ~3e-5 and silently clamped
        # every realistic value into the last bucket.
        self.quantile = quantile
        self.alpha = alpha
        self.n_buckets = n_buckets
        self.gamma = (1 + alpha) / (1 - alpha)
        self.log_gamma = math.log(self.gamma)
        self.min_value = min_value

    # -- scalar face (also the oracle for the device sketch) ---------------
    def _bucket(self, value) -> int:
        if value <= self.min_value:
            return 0
        b = int(math.ceil(math.log(value / self.min_value) / self.log_gamma)) + 1
        return min(b, self.n_buckets - 1)

    def lift(self, value):
        counts = [0] * self.n_buckets
        counts[self._bucket(value)] = 1
        return counts

    def lift_and_combine(self, partial, value):
        partial = list(partial)
        partial[self._bucket(value)] += 1
        return partial

    def combine(self, a, b):
        return [x + y for x, y in zip(a, b)]

    def lower(self, partial):
        total = sum(partial)
        if total == 0:
            return None
        rank = self.quantile * (total - 1)
        acc = 0
        for b, cnt in enumerate(partial):
            acc += cnt
            if acc > rank:
                if b == 0:
                    return 0.0
                # bucket b covers (min*gamma^(b-2), min*gamma^(b-1)]; midpoint
                upper = self.min_value * self.gamma ** (b - 1)
                return 2.0 * upper / (1.0 + self.gamma)
        return None

    def clone_partial(self, partial):
        return list(partial)

    def device_spec(self) -> DeviceAggregateSpec:
        import jax.numpy as jnp

        log_gamma = self.log_gamma
        min_value = self.min_value
        n_buckets = self.n_buckets
        q = self.quantile
        gamma = self.gamma

        def lift_sparse(v):
            pos = v > min_value
            b = jnp.ceil(jnp.log(jnp.maximum(v, min_value) / min_value) / log_gamma) + 1
            col = jnp.where(pos, jnp.minimum(b, n_buckets - 1), 0).astype(jnp.int32)
            return col, jnp.ones_like(v, dtype=jnp.float32)

        def lower(partials: np.ndarray, counts: np.ndarray) -> np.ndarray:
            # partials: [N, n_buckets] bucket counts
            total = partials.sum(axis=-1)
            rank = q * np.maximum(total - 1, 0)
            cum = np.cumsum(partials, axis=-1)
            b = np.argmax(cum > rank[..., None], axis=-1)
            upper = min_value * gamma ** (b - 1)
            vals = np.where(b == 0, 0.0, 2.0 * upper / (1.0 + gamma))
            return np.where(total > 0, vals, np.nan)

        def lower_device(partials, counts):
            total = jnp.sum(partials, axis=-1)
            rank = q * jnp.maximum(total - 1, 0)
            cum = jnp.cumsum(partials, axis=-1)
            b = jnp.argmax(cum > rank[..., None], axis=-1)
            upper = min_value * jnp.power(jnp.float32(gamma),
                                          (b - 1).astype(jnp.float32))
            vals = jnp.where(b == 0, 0.0, 2.0 * upper / (1.0 + gamma))
            return jnp.where(total > 0, vals, jnp.nan)

        return DeviceAggregateSpec(
            kind="sum",
            width=self.n_buckets,
            identity=0.0,
            lift_sparse=lift_sparse,
            lower=lower,
            lower_device=lower_device,
            token=("ddsketch", self.quantile, self.alpha, self.n_buckets,
                   self.min_value),
        )


class HyperLogLogAggregation(AggregateFunction):
    """HyperLogLog distinct count with 2**p registers; combine = register-wise
    max → rides the engine's segment-max path. Fixed-width substitute for a
    distinct-count holistic aggregate (BASELINE.json config 5)."""

    def __init__(self, p: int = 8):
        assert 4 <= p <= 14
        self.p = p
        self.m = 1 << p
        if self.m >= 128:
            self.alpha = 0.7213 / (1.0 + 1.079 / self.m)
        elif self.m == 64:
            self.alpha = 0.709
        elif self.m == 32:
            self.alpha = 0.697
        else:
            self.alpha = 0.673

    @staticmethod
    def _hash64(x: np.ndarray) -> np.ndarray:
        """splitmix64 finalizer — deterministic 64-bit avalanche hash.
        uint64 wraparound is the algorithm; silence numpy's overflow
        warning for it."""
        with np.errstate(over="ignore"):
            z = np.asarray(x, dtype=np.uint64)
            z = (z + np.uint64(0x9E3779B97F4A7C15)) & np.uint64(0xFFFFFFFFFFFFFFFF)
            z = ((z ^ (z >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)) & np.uint64(0xFFFFFFFFFFFFFFFF)
            z = ((z ^ (z >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)) & np.uint64(0xFFFFFFFFFFFFFFFF)
            return z ^ (z >> np.uint64(31))

    def _register_and_rho(self, value):
        # mask in Python-int space BEFORE the uint64 cast: hash() can be
        # negative and np.int64 & 0xFFFF... overflows (the mask doesn't fit
        # a signed 64-bit)
        h = int(self._hash64(np.uint64(hash(value) & 0xFFFFFFFFFFFFFFFF)))
        reg = h & (self.m - 1)
        rest = h >> self.p
        # rho = leading position of first 1 bit in the remaining 64-p bits
        rho = (64 - self.p) - rest.bit_length() + 1
        return reg, rho

    def lift(self, value):
        regs = [0] * self.m
        reg, rho = self._register_and_rho(value)
        regs[reg] = rho
        return regs

    def lift_and_combine(self, partial, value):
        partial = list(partial)
        reg, rho = self._register_and_rho(value)
        partial[reg] = max(partial[reg], rho)
        return partial

    def combine(self, a, b):
        return [max(x, y) for x, y in zip(a, b)]

    def clone_partial(self, partial):
        return list(partial)

    def _estimate(self, regs: np.ndarray) -> np.ndarray:
        regs = np.asarray(regs, dtype=np.float64)
        raw = self.alpha * self.m * self.m / np.sum(2.0 ** (-regs), axis=-1)
        zeros = np.sum(regs == 0, axis=-1)
        # small-range correction (linear counting)
        with np.errstate(divide="ignore"):
            lc = self.m * np.log(np.where(zeros > 0, self.m / np.maximum(zeros, 1), 1.0))
        return np.where((raw <= 2.5 * self.m) & (zeros > 0), lc, raw)

    def lower(self, partial):
        return float(self._estimate(np.asarray(partial)))

    def device_spec(self) -> DeviceAggregateSpec:
        import jax.numpy as jnp

        p, m = self.p, self.m

        def lift_sparse(v):
            # hash the value bits on device (splitmix-style in 2x32-bit lanes)
            x = v.astype(jnp.float32).view(jnp.int32).astype(jnp.uint32)
            x = (x ^ (x >> 16)) * jnp.uint32(0x7FEB352D)
            x = (x ^ (x >> 15)) * jnp.uint32(0x846CA68B)
            x = x ^ (x >> 16)
            y = (x ^ jnp.uint32(0x9E3779B9)) * jnp.uint32(0x85EBCA6B)
            y = y ^ (y >> 13)
            reg = (x & jnp.uint32(m - 1)).astype(jnp.int32)
            rest = y >> jnp.uint32(p)
            # rho: position of first set bit from MSB side of (32-p) bits
            nbits = 32 - p
            hi = jnp.where(rest == 0, jnp.int32(0),
                           jnp.floor(jnp.log2(rest.astype(jnp.float32) + 0.5)).astype(jnp.int32) + 1)
            rho = (nbits - hi + 1).astype(jnp.float32)
            return reg, rho

        est = self._estimate

        def lower(partials: np.ndarray, counts: np.ndarray) -> np.ndarray:
            return est(np.maximum(partials, 0.0)).astype(np.float64)

        alpha = self.alpha

        def lower_device(partials, counts):
            regs = jnp.maximum(partials, 0.0)
            raw = alpha * m * m / jnp.sum(jnp.exp2(-regs), axis=-1)
            zeros = jnp.sum(regs == 0, axis=-1)
            lc = m * jnp.log(jnp.where(zeros > 0,
                                       m / jnp.maximum(zeros, 1), 1.0))
            return jnp.where((raw <= 2.5 * m) & (zeros > 0), lc, raw)

        return DeviceAggregateSpec(
            kind="max",
            width=self.m,
            identity=0.0,
            lift_sparse=lift_sparse,
            lower=lower,
            lower_device=lower_device,
            token=("hll", self.p),
        )


#: count-min hash-row salts: splitmix32 of the row index, fixed so the
#: host oracle, the device kernel and every checkpointed partial agree
#: forever (changing them is a state-format break)
def _cms_salt(r: int) -> int:
    z = (r + 0x9E3779B9) & 0xFFFFFFFF
    z = ((z ^ (z >> 16)) * 0x85EBCA6B) & 0xFFFFFFFF
    z = ((z ^ (z >> 13)) * 0xC2B2AE35) & 0xFFFFFFFF
    return z ^ (z >> 16)


def _cms_mix_host(bits: np.ndarray, salt: int) -> np.ndarray:
    """Host mirror of the device 32-bit mix (same constants as the HLL
    device hash) — uint32 wraparound is the algorithm."""
    with np.errstate(over="ignore"):
        x = np.asarray(bits, dtype=np.uint32) ^ np.uint32(salt)
        x = (x ^ (x >> np.uint32(16))) * np.uint32(0x7FEB352D)
        x = (x ^ (x >> np.uint32(15))) * np.uint32(0x846CA68B)
        return x ^ (x >> np.uint32(16))


class CountMinSketchAggregation(CommutativeAggregateFunction):
    """Count-min sketch (Cormode & Muthukrishnan 2005): ``depth`` hash
    rows of ``width`` counters; each tuple increments one counter per row,
    and the estimated frequency of ``target`` is the MINIMUM of its
    ``depth`` counters — an overestimate by at most the colliding mass,
    ``err <= 2N/width`` per row with probability ``1 - (1/2)^depth``.

    The device substitute for exact per-value frequency (heavy-hitter)
    queries at millions of keys (ROADMAP item 5): the partial is a fixed
    ``[depth·width]`` count vector, combine is elementwise ``sum`` — so
    window merges ride the same prefix-sum range-query path as plain sums,
    and the sketch works through every slice-sharing pipeline including
    the keyed/mesh paths. Hashing is over the value's float32 bit pattern
    with per-row salts; the scalar face below IS the oracle the device
    kernel is differentially tested against (bit-identical bucketing).
    """

    def __init__(self, target: float, depth: int = 4, width: int = 256):
        if depth < 1 or width < 2 or (width & (width - 1)):
            raise ValueError("count-min needs depth >= 1 and a "
                             "power-of-two width >= 2")
        self.target = float(target)
        self.depth = int(depth)
        self.width = int(width)
        self._salts = [_cms_salt(r) for r in range(self.depth)]

    # -- shared bucketing (host side; the device lift mirrors it) ----------
    def _cols(self, values) -> np.ndarray:
        """[depth, B] absolute columns (row-offset included) of each
        value's counters."""
        bits = np.float32(values).reshape(-1).view(np.uint32)
        return np.stack([
            r * self.width
            + (_cms_mix_host(bits, self._salts[r])
               & np.uint32(self.width - 1)).astype(np.int64)
            for r in range(self.depth)])

    def _target_cols(self):
        return self._cols([self.target])[:, 0]

    # -- scalar face (the exact-bucketing oracle) --------------------------
    def lift(self, value):
        counts = [0] * (self.depth * self.width)
        for c in self._cols([value])[:, 0]:
            counts[int(c)] += 1
        return counts

    def lift_and_combine(self, partial, value):
        partial = list(partial)
        for c in self._cols([value])[:, 0]:
            partial[int(c)] += 1
        return partial

    def combine(self, a, b):
        return [x + y for x, y in zip(a, b)]

    def clone_partial(self, partial):
        return list(partial)

    def lower(self, partial):
        return float(min(partial[int(c)] for c in self._target_cols()))

    def device_spec(self) -> DeviceAggregateSpec:
        import jax.numpy as jnp

        depth, width = self.depth, self.width
        salts = np.asarray(self._salts, dtype=np.uint32)
        tcols = np.asarray(self._target_cols(), dtype=np.int64)

        def lift_sparse(v):
            # device twin of _cols: mix the f32 bit pattern per hash row
            x0 = v.astype(jnp.float32).view(jnp.int32).astype(jnp.uint32)
            x = x0[None, :] ^ jnp.asarray(salts)[:, None]       # [d, B]
            x = (x ^ (x >> 16)) * jnp.uint32(0x7FEB352D)
            x = (x ^ (x >> 15)) * jnp.uint32(0x846CA68B)
            x = x ^ (x >> 16)
            col = (jnp.arange(depth, dtype=jnp.int32)[:, None] * width
                   + (x & jnp.uint32(width - 1)).astype(jnp.int32))
            return col, jnp.ones((depth,) + v.shape, dtype=jnp.float32)

        def lower(partials: np.ndarray, counts: np.ndarray) -> np.ndarray:
            return np.min(np.asarray(partials)[..., tcols], axis=-1)

        def lower_device(partials, counts):
            return jnp.min(partials[..., jnp.asarray(tcols)], axis=-1)

        return DeviceAggregateSpec(
            kind="sum",
            width=self.depth * self.width,
            identity=0.0,
            lift_sparse=lift_sparse,
            lower=lower,
            lower_device=lower_device,
            cells_per_tuple=self.depth,
            token=("cms", self.target, self.depth, self.width),
        )


BUILTIN_AGGREGATIONS = {
    "sum": SumAggregation,
    "count": CountAggregation,
    "min": MinAggregation,
    "max": MaxAggregation,
    "mean": MeanAggregation,
    "quantile": QuantileAggregation,
    "ddsketch": DDSketchQuantileAggregation,
    "hll": HyperLogLogAggregation,
    "cms": CountMinSketchAggregation,
}
