"""Bounded ingest ring: preallocated host staging blocks with credits.

The host→device boundary used to be a per-record Python loop feeding
synchronous transfers, backed by buffers that could grow without bound
(an unbounded producer queue, the accumulator's chunk lists) — nothing
pushed back on a source that outran the engine, and nothing proved that
records were never silently lost. :class:`IngestRing` replaces that edge
with a **fixed-depth ring of preallocated numpy staging blocks**:

* **Preallocated**: ``depth`` blocks of ``block_size`` rows (values +
  int64 timestamps, plus an object-array key column when ``keyed``) are
  allocated once at construction. Producing is an array-slice copy into
  the open block — no per-record boxing, no list growth.
* **Credit-based**: a block is a credit. The producer fills the open
  block (:meth:`offer_block` / :meth:`offer_one`); a full block commits
  and becomes visible to the consumer (:meth:`take` → :meth:`free`).
  When every credit is committed-or-checked-out the ring is FULL — a
  first-class backpressure signal (:meth:`has_space` / the truncated
  ``offer_block`` return), never an implicit allocation. What to do
  about it (block the source, shed, fail) is the
  :class:`~scotty_tpu.ingest.feeder.RingIngestor`'s policy, mirroring
  the PR 3 ``overflow_policy`` discipline.
* **Exactly accounted**: ``offered`` / ``delivered`` / ``shed`` /
  ``occupancy`` are plain integers maintained on every transition, so
  the soak harness's tuple-conservation audit can demand
  ``offered == delivered + shed + occupancy`` to the tuple at any
  quiescent point (the obs fold exposes them under the
  ``ingest_ring_*`` contract names).

Single-threaded by design: the synchronous run loops interleave producer
and consumer in one thread (the asyncio path's cross-thread boundary is
the bounded ``asyncio.Queue`` in front of the ring). Slots recycle FIFO.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import numpy as np


class RingFull(RuntimeError):
    """Raised only under ``policy='fail'``: the ring was full and the
    caller asked for an error instead of backpressure or shedding."""


@dataclass(frozen=True)
class RingConfig:
    """Static ingest-ring configuration (the ``ingest_ring=`` face on the
    connector run loops and the line-rate device feed).

    * ``depth`` — staging blocks in the ring (the credit count). Bounded
      memory: ``depth * block_size`` records, allocated once.
    * ``block_size`` — rows per staging block (``None`` = the operator's
      ``config.batch_size``, or 1024 for host connectors).
    * ``policy`` — what ring-full does to the producer: ``"block"``
      (default) pumps the consumer until a credit frees — the source is
      effectively paused, which is end-to-end backpressure in a
      synchronous loop; ``"shed"`` drops the records that did not fit,
      with exact ``ingest_ring_shed`` counts and a ``shed_callback`` so
      an oracle can replay the survivors (the PR 3 SHED discipline at
      the host edge); ``"fail"`` raises :class:`RingFull`.
    * ``stall_timeout_s`` — consumer watchdog: a single blocked-credit
      wait (or consumer delivery) exceeding this on the injectable clock
      counts a ``resilience_stall_events`` and flight-records a stall,
      exactly like the PR 3 source watchdogs — a consumer that stops
      draining is as much an incident as a source that stops producing.
    * ``pump_at`` — committed blocks that trigger an automatic consumer
      pump in the run-loop wiring (1 = deliver as soon as a block fills;
      0 = NO automatic pumping — the consumer runs only on idle ticks,
      drains and ring-full backpressure, which is how the differential
      tests force deterministic full/shed scenarios).
    * ``prefetch`` — device-feeder staging depth: how many transferred
      blocks may wait in the prefetch stage before the oldest's ingest
      is dispatched (1 = classic double buffering).
    """

    depth: int = 8
    block_size: Optional[int] = None
    policy: str = "block"
    stall_timeout_s: Optional[float] = None
    pump_at: int = 1
    prefetch: int = 1

    def __post_init__(self):
        if self.policy not in ("block", "shed", "fail"):
            raise ValueError(
                f"unknown ring policy {self.policy!r}: expected 'block', "
                "'shed' or 'fail'")
        if self.depth < 2:
            raise ValueError("ring depth must be >= 2 (one block filling, "
                             "one draining)")
        if not (0 <= self.pump_at <= self.depth):
            raise ValueError(
                f"pump_at={self.pump_at} must be within [0, depth]")
        if self.prefetch < 1:
            raise ValueError("prefetch must be >= 1")


class RingBlock:
    """A checked-out committed block: read-only views into the slot's
    preallocated storage, valid until :meth:`IngestRing.free`."""

    __slots__ = ("seq", "vals", "ts", "keys", "n", "ts_min", "ts_max")

    def __init__(self, seq, vals, ts, keys, n, ts_min, ts_max):
        self.seq = seq
        self.vals = vals
        self.ts = ts
        self.keys = keys
        self.n = n
        self.ts_min = ts_min
        self.ts_max = ts_max


class IngestRing:
    """The bounded staging ring (module docstring). Producer face:
    :meth:`offer_block` / :meth:`offer_one` / :meth:`flush_open`;
    consumer face: :meth:`take` / :meth:`free`."""

    def __init__(self, depth: int, block_size: int, keyed: bool = False,
                 value_dtype=np.float32):
        if depth < 2:
            raise ValueError("ring depth must be >= 2")
        if block_size < 1:
            raise ValueError("block_size must be >= 1")
        self.depth = int(depth)
        self.block_size = int(block_size)
        self.keyed = keyed
        self.value_dtype = value_dtype
        B = self.block_size
        if value_dtype is None:
            self._vals = [np.empty(B, object) for _ in range(depth)]
        else:
            self._vals = [np.empty(B, value_dtype) for _ in range(depth)]
        self._ts = [np.empty(B, np.int64) for _ in range(depth)]
        self._keys = [np.empty(B, object) for _ in range(depth)] \
            if keyed else None
        self._ns = [0] * depth            # valid rows per committed slot
        self._fill = 0                    # rows in the open slot
        self._seq_w = 0                   # blocks ever committed
        self._seq_r = 0                   # blocks ever taken
        self._seq_f = 0                   # blocks ever freed
        # exact lifetime accounting (the conservation identity's terms)
        self.offered = 0                  # records accepted into the ring
        self.delivered = 0                # records freed by the consumer
        self.blocks = 0                   # blocks committed
        self.full_events = 0              # producer found the ring full
        self.highwater = 0                # occupancy high-water (records)

    # -- state -------------------------------------------------------------
    @property
    def occupancy(self) -> int:
        """Records currently staged (committed + checked-out + open)."""
        return self.offered - self.delivered

    @property
    def committed_blocks(self) -> int:
        """Blocks committed and not yet taken."""
        return self._seq_w - self._seq_r

    @property
    def checked_out_blocks(self) -> int:
        return self._seq_r - self._seq_f

    def has_space(self) -> bool:
        """Whether at least one record can be accepted right now —
        ``False`` IS the backpressure signal."""
        return self._seq_w - self._seq_f < self.depth

    # -- producer ----------------------------------------------------------
    def coerce_block(self, vals, ts, keys=None):
        """Convert one offered chunk to the ring's array types —
        :meth:`offer_block` and the retrying
        :meth:`~scotty_tpu.ingest.feeder.RingIngestor.offer_block` both
        route through the shaper's shared
        :func:`~scotty_tpu.shaper.host.coerce_records` (the one guard
        for the object-payload boxing hazard; idempotent, so retry
        slices re-coerce for free)."""
        from ..shaper.host import coerce_records

        return coerce_records(vals, ts, keys, self.value_dtype,
                              self.keyed, "ring")

    def offer_block(self, vals, ts, keys=None) -> int:
        """Copy records into the ring via array-slice writes; returns how
        many were ACCEPTED (< the offered count means the ring filled —
        the caller's policy decides what happens to the remainder)."""
        v, t, k = self.coerce_block(vals, ts, keys)
        pos, n = 0, t.size
        while pos < n:
            if not self.has_space():
                self.full_events += 1
                break
            i = self._seq_w % self.depth
            take = min(n - pos, self.block_size - self._fill)
            f = self._fill
            self._vals[i][f:f + take] = v[pos:pos + take]
            self._ts[i][f:f + take] = t[pos:pos + take]
            if self.keyed:
                self._keys[i][f:f + take] = k[pos:pos + take]
            self._fill += take
            pos += take
            self.offered += take
            if self._fill == self.block_size:
                self._commit(i)
        self.highwater = max(self.highwater, self.occupancy)
        return pos

    def offer_one(self, val, ts, key=None) -> bool:
        """Scalar fast path (per-record run loops): one slot assignment,
        no array boxing. Returns False when the ring is full."""
        if not self.has_space():
            self.full_events += 1
            return False
        i = self._seq_w % self.depth
        f = self._fill
        self._vals[i][f] = val
        self._ts[i][f] = int(ts)
        if self.keyed:
            self._keys[i][f] = key
        self._fill += 1
        self.offered += 1
        if self._fill == self.block_size:
            self._commit(i)
        self.highwater = max(self.highwater, self.occupancy)
        return True

    def flush_open(self) -> bool:
        """Commit the partially-filled open block (drain/deadline path);
        returns whether a block was committed."""
        if self._fill == 0:
            return False
        self._commit(self._seq_w % self.depth)
        return True

    def _commit(self, i: int) -> None:
        n = self._fill
        self._ns[i] = n
        self._fill = 0
        self._seq_w += 1
        self.blocks += 1

    # -- consumer ----------------------------------------------------------
    def take(self) -> Optional[RingBlock]:
        """Check out the oldest committed block (None when none are
        committed). The block's views stay valid until :meth:`free`."""
        if self._seq_r >= self._seq_w:
            return None
        seq = self._seq_r
        i = seq % self.depth
        n = self._ns[i]
        self._seq_r += 1
        ts = self._ts[i]
        ts_min = int(ts[:n].min()) if n else 0
        ts_max = int(ts[:n].max()) if n else 0
        return RingBlock(seq, self._vals[i], ts,
                         self._keys[i] if self.keyed else None,
                         n, ts_min, ts_max)

    def free(self, block: RingBlock) -> None:
        """Return a checked-out block's credit (FIFO: blocks free in take
        order — the prefetch stage consumes them in order anyway)."""
        if block.seq != self._seq_f:
            raise ValueError(
                f"ring blocks free FIFO: expected seq {self._seq_f}, got "
                f"{block.seq}")
        self._seq_f += 1
        self.delivered += block.n

    def snapshot(self) -> dict:
        """Exact accounting snapshot (tests + the soak audit read it)."""
        return {"offered": self.offered, "delivered": self.delivered,
                "occupancy": self.occupancy, "blocks": self.blocks,
                "full_events": self.full_events,
                "highwater": self.highwater, "depth": self.depth,
                "block_size": self.block_size}
